"""Cell-table stencil engine: dense neighborhood queries without gathers.

This is the TPU-first replacement for the bucketed-grid + candidate-gather
pipeline in ops/aoi.py.  Measured on a real v5e, the old pipeline's
per-candidate irregular gathers (`pos[cand]`, `atk[cand]`, ... over
[N, 9K] index arrays) run at ~1% of HBM bandwidth and dominated the whole
world tick (~250 ms of a 264 ms tick at 131k entities).  Sorting, by
contrast, is nearly free (argsort of 131k int32 keys: 0.11 ms), and dense
shifted-window arithmetic rides the VPU at full throughput.

So the engine inverts the layout ONCE per query instead of gathering per
candidate:

1. `build_cell_table` sorts entities by cell id (one cheap argsort), packs
   caller-chosen per-entity features into a dense `[n_cells*K + 1, F+1]`
   payload table with ONE permutation-gather and ONE scatter (unique slot
   indices, deterministic), and remembers each row's slot (`slot_of`).
   Entities beyond a cell's K slots land in the dump slot and are counted
   in `dropped` — size K from `auto_bucket` to keep that ~zero.
2. `stencil_fold` walks the 3x3 neighborhood as NINE DENSE SHIFTS of the
   [H, W, K, F] grid view (one pad + nine fused slices — no index math,
   no gathers).  The caller folds candidate blocks against the resident
   victim block with plain vectorized arithmetic: [H, W, K, 9K] pairwise
   masked reductions, fully fused by XLA onto the VPU.
3. `pull` maps per-slot results back to per-row results with a single
   row-gather through `slot_of` (dropped/inactive rows read the appended
   identity element).

Everything is static-shaped, jit/vmap/shard_map-friendly, and
deterministic (stable sort + unique-index scatter + fixed fold order).

Reference parity note: this implements the spatial layer behind the
"AOI" broadcast of NFCSceneAOIModule (the reference's own AOI is
group-granular, NFCSceneAOIModule.cpp:531-593; the 2D-grid scan is
BASELINE config 3, and the AoE damage resolve of NFCSkillModule::OnUseSkill
is BASELINE config 4).
"""

from __future__ import annotations

import math
import os
from typing import Callable, NamedTuple, Tuple, TypeVar

import jax
import jax.numpy as jnp

from .aoi import cell_of

A = TypeVar("A")

# NF_BINNING picks the slot-assignment engine behind build_cell_table /
# build_cell_table_pair (and the Verlet rebuild arm).  "sort" is the
# original stable-argsort path; "count" is the sort-free counting path
# (_cell_counts / _counting_ranks / _counting_slots) — bit-identical
# tables, O(K*(N + n_cells)) streaming work instead of an O(N log N)
# comparison network.  Trace-time like NF_RADIX: flip it, then retrace.
ENV_BINNING = "NF_BINNING"
BINNING_MODES = ("sort", "count")


def binning_mode() -> str:
    """The validated NF_BINNING mode; unset/empty means "sort".

    Unknown values raise instead of falling through — a typo'd mode
    silently running the default would invalidate any A/B it labeled.
    This is the ONLY place the env var is read (pinned by
    tests/test_binning.py's lint guard)."""
    # nf-lint: disable=trace-safety -- sanctioned A/B knob: read once at
    # trace time and baked into the compiled tick; tests pin this as the
    # only NF_BINNING read and flipping it requires a fresh jit cache
    raw = os.environ.get(ENV_BINNING, "").strip()
    if not raw:
        return "sort"
    if raw not in BINNING_MODES:
        raise ValueError(
            f"{ENV_BINNING}={raw!r}: expected one of {BINNING_MODES}"
        )
    return raw

# 3x3 stencil in (dy, dx) order — must match ops.aoi._STENCIL so candidate
# iteration order (and therefore argmax tie-breaking) is identical across
# both engines.
STENCIL = [(dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1)]


class CellSlots(NamedTuple):
    """A slot assignment WITHOUT the payload materialization.

    The fused Pallas engine (ops/stencil_pallas.py, NF_PALLAS=2) gathers
    features straight from the SoA banks via these slots, so the padded
    `[n_cells*K + 1, F+1]` payload table — the biggest per-frame HBM
    materialization of the split path — is never written.  Same slot
    semantics as CellTable (dump slot == n_cells*K for unplaced rows,
    `dropped` counts active overflow), minus the scatter.
    """

    slot_of: jnp.ndarray
    dropped: jnp.ndarray
    width: int
    cell_size: float
    bucket: int
    height: int = -1


class CellTable(NamedTuple):
    """Sorted cell-dense payload table.

    payload: [n_cells*K + 1, F+1] — caller features + occupancy column
             (last col, 1.0 = slot holds a live entity).  The final row is
             the dump slot for inactive/overflowed entities; `grid_view`
             excludes it.
    slot_of: [N] int32 — flat payload slot per input row; dump slot
             (== n_cells*K) for rows not placed.
    dropped: scalar int32 — active entities that overflowed their cell.
    width, cell_size, bucket: static grid geometry.
    """

    payload: jnp.ndarray
    slot_of: jnp.ndarray
    dropped: jnp.ndarray
    width: int
    cell_size: float
    bucket: int
    # rectangular grids (spatial slab sharding): rows of the grid; -1
    # means square (height == width).  Trailing default keeps the many
    # existing 6-field positional constructions valid.
    height: int = -1

    def grid_view(self) -> jnp.ndarray:
        """[H, W, K, F+1] dense view (dump slot excluded)."""
        h = self.height if self.height > 0 else self.width
        w = self.width
        k = self.bucket
        return self.payload[:-1].reshape(h, w, k, self.payload.shape[-1])


def auto_bucket(
    capacity: int, width: int, lo: int = 8, hi: int = 256, align: int = 4
) -> int:
    """Pick K so uniform occupancy ~Poisson(capacity/cells) stays under
    the overflow budget: mean + 2.5*sqrt(mean) + 2, rounded up to a
    multiple of `align` within [lo, hi].  Fold cost scales with K^2, so
    the margin is the thinnest that keeps expected drops well below 0.1%
    of entities (capacity already overstates live density by up to 2x,
    which is extra headroom; the bound is pinned by tests/test_stencil.py).
    Sparse candidate tables (the combat attacker side) pass align=2 —
    at occupancy ~0.2/cell the rounding from 6 to 8 alone would cost
    +33% fold work.

    Entities beyond a cell's K slots are dropped from that query (counted
    in CellTable.dropped) — they neither see nor are seen by neighbors
    that tick.  Callers passing an explicit small bucket accept drops
    under crowding."""
    lam = capacity / float(max(width * width, 1))
    k = int(math.ceil(lam + 2.5 * math.sqrt(max(lam, 1.0)) + 2.0))
    k = max(lo, min(hi, k))
    return -(-k // align) * align


def _radix_argsort(
    key: jnp.ndarray, n_bits: int, bits_per_pass: int = 1
) -> jnp.ndarray:
    """Stable LSD radix argsort for small non-negative int keys.

    XLA's TPU `sort` is a comparison network with poor large-N
    efficiency; per docs/ROOFLINE.md it is the prime suspect for the
    1M-tick gap.  This replaces it with ceil(n_bits / bits_per_pass)
    stable partition passes — streaming cumsums plus two unique-index
    scatters per pass over [N] i32 — instead of O(log^2 N) comparison
    stages.  Bit-identical to `jnp.argsort(key)` (both stable).

    bits_per_pass trades cumsum work for scatter count: the two
    permutation scatters are the irregular (bandwidth-hostile) part of
    a pass, so 2-3 bits per pass cuts them 2-3x while the added
    per-digit cumsum planes ([N, 2^b] one-hot) stay cheap streaming
    work.  Opt-in via NF_RADIX=<bits_per_pass> until chip time ranks
    the variants against XLA's sort (virtual-CPU timing cannot)."""
    n = key.shape[0]
    order = jnp.arange(n, dtype=jnp.int32)
    b = max(1, int(bits_per_pass))
    n_digits = 1 << b
    n_passes = -(-n_bits // b)
    mask = n_digits - 1

    if b == 1:
        def one_pass(i, kv):
            k, o = kv
            bit = (k >> (i * 1)) & 1
            zeros = jnp.cumsum(1 - bit)  # inclusive; stable in each half
            ones = jnp.cumsum(bit)
            pos = jnp.where(bit == 0, zeros - 1, zeros[-1] + ones - 1)
            return (
                jnp.zeros_like(k).at[pos].set(k),
                jnp.zeros_like(o).at[pos].set(o),
            )
    else:
        def one_pass(i, kv):
            k, o = kv
            digit = (k >> (i * b)) & mask
            onehot = (
                digit[:, None] == jnp.arange(n_digits, dtype=k.dtype)[None, :]
            ).astype(jnp.int32)
            incl = jnp.cumsum(onehot, axis=0)  # [N, D] running count per digit
            totals = incl[-1]
            base = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(totals)[:-1]]
            )
            rank = jnp.take_along_axis(incl, digit[:, None], axis=1)[:, 0]
            pos = base[digit] + rank - 1
            return (
                jnp.zeros_like(k).at[pos].set(k),
                jnp.zeros_like(o).at[pos].set(o),
            )

    _, order = jax.lax.fori_loop(0, n_passes, one_pass, (key, order))
    return order


def _bits_for(n_cells: int) -> int:
    """Bits needed for keys in [0, n_cells] (the inactive key IS
    n_cells, so it must be representable)."""
    return max(1, int(n_cells).bit_length())


def _cell_keys(pos, active, cell_size: float, width: int,
               cell=None, n_cells: int | None = None):
    """Shared key pass for BOTH binning engines: per-row sort/bin key
    (cell id, or n_cells for inactive rows).  Returns (n_cells, key).

    cell/n_cells: precomputed per-row cell ids over a caller-defined
    (possibly rectangular) grid — the spatial slab shards pass local
    slab-relative ids; default derives square-grid ids from pos."""
    n = pos.shape[0]
    if n >= 1 << 24:
        # row ids (and other int-valued columns) ride in f32 payload
        # columns, exact only below 2^24 — refuse silent corruption
        raise ValueError(f"cell table capacity {n} >= 2^24 breaks f32 row ids")
    if cell is None:
        n_cells = width * width
        cell = cell_of(pos, cell_size, width)
    elif n_cells is None:
        raise ValueError("precomputed cell ids need n_cells")
    key = jnp.where(active, cell, n_cells)
    return n_cells, key


def _sorted_segments(pos, active, cell_size: float, width: int,
                     cell=None, n_cells: int | None = None):
    """Shared build prefix of the SORT engine: the ONE stable argsort by
    cell id plus per-element segment ranks.  Returns (n_cells, order,
    skey, seg_start, rank) — everything both table builders derive slots
    from."""
    n = pos.shape[0]
    n_cells, key = _cell_keys(
        pos, active, cell_size, width, cell=cell, n_cells=n_cells
    )
    # nf-lint: disable=trace-safety -- sanctioned A/B knob: trace-time
    # read baked into the compilation; flipping needs a fresh jit cache
    radix = os.environ.get("NF_RADIX", "")
    if radix.isdigit() and int(radix) > 0:
        # NF_RADIX=<bits per pass>: 1 = binary partition passes,
        # 2/3 = 4-way/8-way digits (fewer irregular scatters)
        order = _radix_argsort(key, _bits_for(n_cells), int(radix))
    else:
        order = jnp.argsort(key)  # stable: preserves row order within a cell
    skey = key[order]
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), skey[1:] != skey[:-1]]
    )
    # index of each sorted element's segment head, via running max
    start_idx = jax.lax.cummax(jnp.where(seg_start, idx, 0))
    rank = idx - start_idx
    return n_cells, order, skey, seg_start, rank


# --- the COUNT engine (NF_BINNING=count): histogram + bounded-rank
# selection + scatter.  No sort or argsort anywhere (pinned by the AST
# guard in tests/test_binning.py) — the super-linear comparison network
# is gone from the build.


def _cell_counts(key: jnp.ndarray, n_cells: int) -> jnp.ndarray:
    """Histogram pass: [n_cells + 1] i32 occupancy per cell (last bin
    counts inactive rows, key == n_cells) via ONE segment_sum — a single
    streaming scatter-add over [N].  In the fixed-stride dense layout the
    exclusive-cumsum offsets this histogram implies are simply
    `cell * bucket`, so no scan materializes on the hot path; the
    histogram itself feeds occupancy telemetry and the per-pass profile
    (scripts/profile_passes.py times it in isolation)."""
    return jax.ops.segment_sum(
        jnp.ones_like(key), key, num_segments=n_cells + 1
    )


def _counting_ranks(key: jnp.ndarray, n_cells: int, kmax: int) -> jnp.ndarray:
    """Deterministic within-cell rank in stable row-id order, WITHOUT a
    sort: `kmax` rounds of scatter-min selection.  Round r finds each
    cell's smallest not-yet-ranked row id (one `.at[key].min` scatter +
    one gather), assigns it rank r, and retires it.  Rows never selected
    (rank >= kmax, or inactive key == n_cells) keep rank == kmax.

    This matches the stable-argsort rank EXACTLY wherever it matters:
    both engines place the `kmax` smallest row ids of each cell (stable
    sort ranks ascending row ids ascending) and dump the rest, so tables
    — including overflow drops — are bit-identical.  Cost is
    O(kmax * (N + n_cells)) streaming work with static shapes; at the 1M
    benchmark geometry that is ~16 passes over ~4 MB for the victim
    table versus the ~400-stage comparison network XLA's sort runs over
    8 MB of (key, row) pairs."""
    n = key.shape[0]
    sentinel = jnp.int32(n)  # > any live row id; also the "retired" mark
    remaining = jnp.where(key < n_cells, jnp.arange(n, dtype=jnp.int32),
                          sentinel)
    rank = jnp.full((n,), kmax, jnp.int32)

    def one_round(r, state):
        remaining, rank = state
        win = (
            jnp.full((n_cells + 1,), sentinel, jnp.int32)
            .at[key]
            .min(remaining)
        )
        # the `< sentinel` guard keeps retired rows of an EXHAUSTED cell
        # (win == sentinel) from matching sentinel == sentinel
        is_win = (remaining < sentinel) & (remaining == win[key])
        rank = jnp.where(is_win, r, rank)
        remaining = jnp.where(is_win, sentinel, remaining)
        return remaining, rank

    _, rank = jax.lax.fori_loop(0, kmax, one_round, (remaining, rank))
    return rank


def _counting_slots(key: jnp.ndarray, n_cells: int, bucket: int) -> jnp.ndarray:
    """Per-row flat payload slot from the counting ranks: placed rows get
    `cell * bucket + rank` (the histogram's trivially-dense exclusive
    offsets), everything else the dump slot.  Drop-in replacement for the
    sort path's un-sorted `_finish_table` slot assignment."""
    rank = _counting_ranks(key, n_cells, bucket)
    dump = n_cells * bucket
    return jnp.where(rank < bucket, key * bucket + rank, dump).astype(jnp.int32)


def _build_pair_counting(
    features, active, sub_mask, sub_features,
    key, n_cells: int, cell_size: float, width: int,
    bucket: int, sub_bucket: int, height: int = -1,
) -> Tuple[CellTable, CellTable]:
    """COUNT-engine pair build from a precomputed key: full and subset
    tables each run their own bounded-rank selection + payload scatter.
    The subset re-ranks over `sub_key` so a sub member's rank is its
    ordinal among SUB members of its cell — same contract as the sort
    path's segmented cumsum (a row overflowing the full table can still
    hold a valid subset slot)."""
    slot_of = _counting_slots(key, n_cells, bucket)
    full = table_from_slots(
        features, active, slot_of, n_cells, cell_size, width, bucket, height
    )
    sub_key = jnp.where(sub_mask, key, n_cells)
    sub_slots = _counting_slots(sub_key, n_cells, sub_bucket)
    sub = table_from_slots(
        sub_features, sub_mask, sub_slots, n_cells, cell_size, width,
        sub_bucket, height,
    )
    return full, sub


def _slots_from_ranks(
    n: int, n_cells: int, order, skey, rank, bucket: int
) -> jnp.ndarray:
    """SORT-engine slot assignment from sorted segment ranks: un-sort
    `skey * bucket + rank` back to row order (one scatter).  Shared by
    _finish_table, the Verlet rebuild (ops/verlet.py) and the slots-only
    builders below so the placement math cannot drift between the
    payload and fused engines."""
    dump = n_cells * bucket
    placed = (rank < bucket) & (skey < n_cells)
    flat_sorted = jnp.where(placed, skey * bucket + rank, dump)
    return jnp.full((n,), dump, jnp.int32).at[order].set(flat_sorted)


def slots_from_assignment(
    active, slot_of, n_cells: int,
    cell_size: float, width: int, bucket: int, height: int = -1,
) -> CellSlots:
    """CellSlots from a precomputed per-row slot array: force inactive
    rows to the dump slot and count active overflow — exactly the
    bookkeeping half of table_from_slots, minus the payload scatter."""
    dump = n_cells * bucket
    slot_of = jnp.where(active, slot_of, dump)
    dropped = jnp.sum(active & (slot_of == dump), dtype=jnp.int32)
    return CellSlots(slot_of, dropped, width, cell_size, bucket, height)


def build_cell_slots_pair(
    pos: jnp.ndarray,
    active: jnp.ndarray,
    sub_mask: jnp.ndarray,
    cell_size: float,
    width: int,
    bucket: int,
    sub_bucket: int,
    cell: jnp.ndarray | None = None,
    height: int = -1,
) -> Tuple[CellSlots, CellSlots]:
    """build_cell_table_pair minus the payloads: the same NF_BINNING
    dispatch, key pass, ranks and dump-slot rules, returning only the
    two slot assignments (full population + subset).  Placement is
    bit-identical to the table pair — including which rows drop — so
    the fused engine inherits the split engine's overflow semantics."""
    n_rows = height if height > 0 else width
    n = pos.shape[0]
    mode = binning_mode()
    if mode == "count":
        n_cells, key = _cell_keys(
            pos, active, cell_size, width, cell=cell,
            n_cells=(n_rows * width if cell is not None else None),
        )
        full = slots_from_assignment(
            active, _counting_slots(key, n_cells, bucket), n_cells,
            cell_size, width, bucket, height,
        )
        sub_key = jnp.where(sub_mask, key, n_cells)
        sub = slots_from_assignment(
            sub_mask, _counting_slots(sub_key, n_cells, sub_bucket), n_cells,
            cell_size, width, sub_bucket, height,
        )
        return full, sub
    if mode != "sort":
        raise ValueError(f"unhandled binning mode {mode!r}")  # pragma: no cover
    n_cells, order, skey, seg_start, rank = _sorted_segments(
        pos, active, cell_size, width, cell=cell,
        n_cells=(n_rows * width if cell is not None else None),
    )
    full = slots_from_assignment(
        active, _slots_from_ranks(n, n_cells, order, skey, rank, bucket),
        n_cells, cell_size, width, bucket, height,
    )
    # subset ranks via the same segmented exclusive cumsum as the pair
    # builder (see build_cell_table_pair for the derivation)
    sub_sorted = sub_mask[order]
    ex = jnp.cumsum(sub_sorted.astype(jnp.int32)) - sub_sorted.astype(jnp.int32)
    head_ex = jax.lax.cummax(jnp.where(seg_start, ex, -1))
    sub_rank = jnp.where(sub_sorted, ex - head_ex, n_cells * sub_bucket + 1)
    sub = slots_from_assignment(
        sub_mask,
        _slots_from_ranks(n, n_cells, order, skey, sub_rank, sub_bucket),
        n_cells, cell_size, width, sub_bucket, height,
    )
    return full, sub


def table_from_slots(
    features, active, slot_of, n_cells: int,
    cell_size: float, width: int, bucket: int, height: int = -1,
) -> CellTable:
    """Materialize a CellTable from a PRECOMPUTED slot assignment: ONE
    deterministic payload scatter (unique slot indices for placed rows),
    dump-slot zeroing, drop count.  This is the sort-free half of the
    build — the Verlet cache (ops/verlet.py) replays it every reuse tick
    against the cached `slot_of` while skipping the argsort entirely.
    Rows not `active` are forced to the dump slot regardless of their
    cached assignment (a cache is only reused while the active set is
    unchanged, but a zero-initialized cache must stay harmless)."""
    n = features.shape[0]
    dump = n_cells * bucket
    slot_of = jnp.where(active, slot_of, dump)
    occ = jnp.ones((n, 1), features.dtype)
    feats = jnp.concatenate([features, occ], axis=-1)
    payload = (
        jnp.zeros((dump + 1, feats.shape[-1]), features.dtype)
        .at[slot_of]
        .set(feats)
    )
    # dump slot may have been written by any loser; force it empty
    payload = payload.at[dump].set(0.0)
    dropped = jnp.sum(active & (slot_of == dump), dtype=jnp.int32)
    return CellTable(payload, slot_of, dropped, width, cell_size, bucket, height)


def _finish_table(
    features, active, n_cells: int, order, skey, rank,
    cell_size: float, width: int, bucket: int, height: int = -1,
) -> CellTable:
    """Shared build suffix: slots from ranks, then the payload scatter.
    Un-sorting the slot assignment costs one scatter instead of a
    sorted-gather + scatter (each N-sized irregular op costs ~1 ms per
    131k rows on a v5e; this is the hot per-tick build)."""
    n = features.shape[0]
    slot_of = _slots_from_ranks(n, n_cells, order, skey, rank, bucket)
    return table_from_slots(
        features, active, slot_of, n_cells, cell_size, width, bucket, height
    )


def build_cell_table(
    pos: jnp.ndarray,
    active: jnp.ndarray,
    features: jnp.ndarray,
    cell_size: float,
    width: int,
    bucket: int,
) -> CellTable:
    """Bin `active` entities into the uniform grid, carrying `features`.

    pos: [N, >=2] positions; active: [N] bool; features: [N, F] float32.
    Slot assignment dispatches on NF_BINNING (bit-identical either way):
    sort = one argsort + permutation-gather + scatter; count = bounded
    scatter-min ranks, no sort.  All slot indices are unique so the
    payload scatter is deterministic.
    """
    mode = binning_mode()
    if mode == "count":
        n_cells, key = _cell_keys(pos, active, cell_size, width)
        slot_of = _counting_slots(key, n_cells, bucket)
        return table_from_slots(
            features, active, slot_of, n_cells, cell_size, width, bucket
        )
    if mode == "sort":
        n_cells, order, skey, _seg_start, rank = _sorted_segments(
            pos, active, cell_size, width
        )
        return _finish_table(
            features, active, n_cells, order, skey, rank, cell_size, width,
            bucket,
        )
    raise ValueError(f"unhandled binning mode {mode!r}")  # pragma: no cover


def build_cell_table_pair(
    pos: jnp.ndarray,
    active: jnp.ndarray,
    features: jnp.ndarray,
    sub_mask: jnp.ndarray,
    sub_features: jnp.ndarray,
    cell_size: float,
    width: int,
    bucket: int,
    sub_bucket: int,
    cell: jnp.ndarray | None = None,
    height: int = -1,
) -> Tuple[CellTable, CellTable]:
    """Build the full table AND a subset table from ONE key pass.

    Dispatches on NF_BINNING: the sort engine derives both tables from a
    single stable argsort; the count engine runs bounded scatter-min
    selection per table (no sort at all).  Both produce bit-identical
    tables — including which rows overflow to the dump slot.

    `sub_mask` must be a subset of `active` (combat: attackers among all
    alive entities).  Placement is bit-identical to two independent
    `build_cell_table` calls — within a cell both tables hold rows in
    ascending order, and the subset ranks are the subset's own ordinal
    positions — but the second sort and its key gather are replaced by a
    segmented cumsum over the shared sorted order.

    cell/height: precomputed cell ids over a rectangular [height, width]
    grid (spatial slab shards); default square grid derived from pos."""
    n_rows = height if height > 0 else width
    mode = binning_mode()
    if mode == "count":
        n_cells, key = _cell_keys(
            pos, active, cell_size, width, cell=cell,
            n_cells=(n_rows * width if cell is not None else None),
        )
        return _build_pair_counting(
            features, active, sub_mask, sub_features, key, n_cells,
            cell_size, width, bucket, sub_bucket, height,
        )
    if mode != "sort":
        raise ValueError(f"unhandled binning mode {mode!r}")  # pragma: no cover
    n_cells, order, skey, seg_start, rank = _sorted_segments(
        pos, active, cell_size, width, cell=cell,
        n_cells=(n_rows * width if cell is not None else None),
    )
    full = _finish_table(
        features, active, n_cells, order, skey, rank, cell_size, width,
        bucket, height,
    )
    # subset ranks via segmented exclusive cumsum: ex is non-decreasing,
    # so "ex at my segment's head" is a cummax over heads — no gather.
    # Non-members get an out-of-range rank so _finish_table sends them
    # to the dump slot.
    sub_sorted = sub_mask[order]
    ex = jnp.cumsum(sub_sorted.astype(jnp.int32)) - sub_sorted.astype(jnp.int32)
    head_ex = jax.lax.cummax(jnp.where(seg_start, ex, -1))
    sub_rank = jnp.where(sub_sorted, ex - head_ex, n_cells * sub_bucket + 1)
    sub = _finish_table(
        sub_features, sub_mask, n_cells, order, skey, sub_rank,
        cell_size, width, sub_bucket, height,
    )
    return full, sub


def stencil_fold(
    table: CellTable,
    fold: Callable[[A, jnp.ndarray], A],
    init: A,
) -> A:
    """Fold `fold(acc, cand)` over the nine shifted candidate blocks.

    cand: [H, W, K, F+1] — the neighbor cell's payload aligned onto every
    cell (edge neighbors read zero payload => occupancy 0).  Iteration
    order is STENCIL order; keep reductions order-insensitive or rely on
    that fixed order for tie-breaking.
    """
    v = table.grid_view()
    h, w, k, f = v.shape
    vp = jnp.pad(v, ((1, 1), (1, 1), (0, 0), (0, 0)))
    acc = init
    for dy, dx in STENCIL:
        cand = jax.lax.slice(
            vp, (dy + 1, dx + 1, 0, 0), (dy + 1 + h, dx + 1 + w, k, f)
        )
        acc = fold(acc, cand)
    return acc


def pull_slots(
    slot_of: jnp.ndarray, values: jnp.ndarray,
    fill: float | Tuple[float, ...] = 0.0,
) -> jnp.ndarray:
    """Map per-slot results [H, W, K] or [H, W, K, V] back to rows [N] /
    [N, V] with one gather through a raw slot array; unplaced rows (dump
    slot) read `fill`.  The slot-only half of `pull` — the fused engine
    (CellSlots) has no table to pass."""
    squeeze = values.ndim == 3
    if squeeze:
        values = values[..., None]
    nv = values.shape[-1]
    flat = values.reshape(-1, nv)
    fill_row = jnp.broadcast_to(
        jnp.asarray(fill, values.dtype).reshape(-1), (nv,)
    )
    flat = jnp.concatenate([flat, fill_row[None, :]], axis=0)
    out = flat[slot_of]
    return out[..., 0] if squeeze else out


def pull(
    table: CellTable, values: jnp.ndarray, fill: float | Tuple[float, ...] = 0.0
) -> jnp.ndarray:
    """`pull_slots` through a CellTable's slot assignment."""
    return pull_slots(table.slot_of, values, fill)
