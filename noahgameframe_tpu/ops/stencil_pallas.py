"""Pallas TPU kernel for the combat stencil fold (split-table form).

The XLA path (game/combat.py's fold over ops/stencil.stencil_fold) walks
the 3x3 neighborhood as nine shifted slices of the padded attacker
table — nine HBM passes over the candidate planes plus whatever
intermediates XLA materializes for the [Kv, Ka] pairwise masks.  This
kernel makes the whole fold ONE pass: the grid iterates over cell rows;
each program holds the victim row's planes plus the three neighboring
attacker rows in VMEM (the same padded attacker planes bound three times
with block index maps y, y+1, y+2 — overlapping, read-only), and the
nine shifted pairwise reductions run on-core against resident data.

Layout: planes ride as [rows, F, K, W(+2)] so the wide W axis lands on
vector lanes and K on sublanes.  Victims are resident (no padding, one
mid-row ref); attackers are the scanned side (padded, three refs).
Outputs are [H, 3, Kv, W] (incoming, best-atk, best-row planes).

Semantics are identical to CombatModule's XLA fold (same stencil order,
same tie-breaks) — pinned by tests/test_stencil_pallas.py, which runs
this kernel in interpret mode on CPU against the XLA path.  On real TPU
hardware the kernel compiles natively; enable with NF_PALLAS=1 (opt-in
until chip-time confirms a win over the already-fused XLA fold).

Victim feature planes (CombatModule's vic_feats; occupancy dropped):
    0: x   1: y   2: camp   3: scene   4: group
Attacker feature planes (att_feats):
    0: x   1: y   2: eff_atk   3: camp   4: scene   5: group   6: row
(no self-exclusion compare: self always shares its own camp, so the
no-friendly-fire mask rules self out — keep in sync with CombatModule)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# "no attacker" sentinel (2^24) — must match game.combat.NO_ROW; finite
# on purpose, an inf loop carry hangs the XLA CPU algebraic simplifier
_NO_ROW = 16777216.0

V_X, V_Y, V_CAMP, V_SCENE, V_GROUP = range(5)
N_VFEATS = 5
A_X, A_Y, A_ATK, A_CAMP, A_SCENE, A_GROUP, A_ROW = range(7)
N_AFEATS = 7


def _kernel(vic_ref, top_ref, mid_ref, bot_ref, out_ref, *, w: int, r2: float):
    kv = vic_ref.shape[2]
    ka = top_ref.shape[2]
    vx = vic_ref[0, V_X]
    vy = vic_ref[0, V_Y]
    vcamp = vic_ref[0, V_CAMP]
    vscene = vic_ref[0, V_SCENE]
    vgroup = vic_ref[0, V_GROUP]

    inc = jnp.zeros((kv, w), jnp.int32)
    besta = jnp.full((kv, w), -1.0, jnp.float32)
    bestr = jnp.full((kv, w), _NO_ROW, jnp.float32)

    # stencil order (dy, dx) ascending — identical to ops.stencil.STENCIL
    for ref in (top_ref, mid_ref, bot_ref):
        for dx in (0, 1, 2):
            cx = ref[0, A_X, :, dx : dx + w]
            cy = ref[0, A_Y, :, dx : dx + w]
            ca = ref[0, A_ATK, :, dx : dx + w]
            cc = ref[0, A_CAMP, :, dx : dx + w]
            csc = ref[0, A_SCENE, :, dx : dx + w]
            cg = ref[0, A_GROUP, :, dx : dx + w]
            cr = ref[0, A_ROW, :, dx : dx + w]
            ddx = vx[:, None, :] - cx[None, :, :]
            ddy = vy[:, None, :] - cy[None, :, :]
            cab = ca[None, :, :]
            ok = (
                (ddx * ddx + ddy * ddy <= r2)
                & (cab != 0.0)
                & (cc[None, :, :] != vcamp[:, None, :])
                & (csc[None, :, :] == vscene[:, None, :])
                & (cg[None, :, :] == vgroup[:, None, :])
            )
            inc = inc + jnp.sum(
                jnp.where(ok, cab, 0.0), axis=1
            ).astype(jnp.int32)
            sa = jnp.where(ok, cab, -1.0)
            sa = jnp.broadcast_to(sa, (kv, ka, w))
            m = jnp.max(sa, axis=1)
            first = jnp.min(
                jnp.where(sa >= m[:, None, :],
                          jnp.broadcast_to(cr[None, :, :], (kv, ka, w)),
                          _NO_ROW),
                axis=1,
            )
            # global min-row tie-break, identical to combat_fold_closure:
            # neutralize empty shifts (m == -1), then lexicographic
            # (max attack, min row) merge with `bestr` consumed once
            first = jnp.where(m >= 0.0, first, _NO_ROW)
            top = jnp.maximum(besta, m)
            bestr = jnp.minimum(
                jnp.where(m >= top, first, _NO_ROW),
                jnp.where(besta >= top, bestr, _NO_ROW),
            )
            besta = top

    # bitcast keeps the exact int32 damage total through the f32 plane
    # (a value cast would round above 2^24)
    out_ref[0, 0] = jax.lax.bitcast_convert_type(inc, jnp.float32)
    out_ref[0, 1] = besta
    out_ref[0, 2] = bestr


def combat_fold_pallas(vic_table, att_table, radius: float, interpret: bool = False):
    """Fused 3x3 stencil fold: victims resident, attackers scanned.

    vic_table / att_table: ops.stencil.CellTable over the SAME grid
    geometry (vic carries 5 feature cols, att 7 — see module docstring).
    Returns (inc [H, W, Kv] int32, bestr [H, W, Kv] int32), matching the
    XLA fold's outputs before `pull`.

    NF_PALLAS_ALIGN=<n> pads the lane (W) axis up to a multiple of n
    (128 = TPU lane width) with zero-occupancy ghost cells — masked out
    by the fold exactly like edge padding.  Insurance for grids whose W
    (395 at the 1M benchmark) Mosaic may reject or tile poorly; costs
    W_pad/W extra lanes, so it is opt-in until chip time ranks the two."""
    import os

    width = vic_table.width
    assert att_table.width == width and att_table.cell_size == vic_table.cell_size
    # nf-lint: disable=trace-safety -- sanctioned A/B knob: trace-time
    # read baked into the compilation; flipping needs a fresh jit cache
    align = int(os.environ.get("NF_PALLAS_ALIGN", "0") or 0)
    w_pad = ((-width) % align) if align > 1 else 0
    vic = _planes(vic_table.payload, width, vic_table.bucket, N_VFEATS,
                  pad=False, w_pad=w_pad)
    att = _planes(att_table.payload, width, att_table.bucket, N_AFEATS,
                  pad=True, w_pad=w_pad)
    h = width
    w = width + w_pad
    kv = vic.shape[2]
    ka = att.shape[2]
    vic_spec = pl.BlockSpec((1, N_VFEATS, kv, w), lambda y: (y, 0, 0, 0))
    att_spec = lambda off: pl.BlockSpec(  # noqa: E731
        (1, N_AFEATS, ka, w + 2), lambda y, o=off: (y + o, 0, 0, 0)
    )
    out = pl.pallas_call(
        functools.partial(_kernel, w=w, r2=float(radius) * float(radius)),
        grid=(h,),
        in_specs=[vic_spec, att_spec(0), att_spec(1), att_spec(2)],
        out_specs=pl.BlockSpec((1, 3, kv, w), lambda y: (y, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((h, 3, kv, w), jnp.float32),
        interpret=interpret,
    )(vic, att, att, att)
    inc = jax.lax.bitcast_convert_type(
        out[:, 0].transpose(0, 2, 1), jnp.int32
    )  # [H, W(+pad), Kv]
    bestr_f = out[:, 2].transpose(0, 2, 1)
    # _NO_ROW (no attacker) -> -1; row ids are exact in f32 (< 2^24)
    bestr = jnp.where(bestr_f >= _NO_ROW, -1.0, bestr_f).astype(jnp.int32)
    if w_pad:
        inc = inc[:, :width]
        bestr = bestr[:, :width]
    if kv > vic_table.bucket:
        inc = inc[..., : vic_table.bucket]
        bestr = bestr[..., : vic_table.bucket]
    return inc, bestr


def _planes(payload: jnp.ndarray, width: int, bucket: int, n_feats: int,
            pad: bool, w_pad: int = 0) -> jnp.ndarray:
    """CellTable payload [(H*W*K)+1, F+1] -> feature planes.

    pad=True (attacker side) adds the one-cell zero border the shifted
    reads need: [H+2, F, K, W+2]; border slots are all-zero => eff_atk 0
    => masked, exactly like the XLA fold's zero padding.  pad=False
    (victim side, resident) gives [H, F, K, W].  K pads up to a multiple
    of 8 so the sublane axis stays tile-aligned on real TPUs (pad slots
    are all-zero; for victims the caller slices outputs back to K —
    zero-slot victims never map back through `pull`).  w_pad appends
    zero-occupancy ghost cell columns for lane alignment (see
    combat_fold_pallas)."""
    h = w = width
    k = bucket
    v = payload[:-1, :n_feats].reshape(h, w, k, n_feats)
    planes = v.transpose(0, 3, 2, 1)  # [H, F, K, W]
    k_pad = (-k) % 8
    if pad:
        return jnp.pad(planes, ((1, 1), (0, 0), (0, k_pad), (1, 1 + w_pad)))
    if k_pad or w_pad:
        return jnp.pad(planes, ((0, 0), (0, 0), (0, k_pad), (0, w_pad)))
    return planes
