"""Pallas TPU kernel for the combat stencil fold.

The XLA path (ops/stencil.py stencil_fold) walks the 3x3 neighborhood as
nine shifted slices of the padded cell table — nine reads of the table
from HBM, fused per shift.  This kernel makes the whole fold ONE pass:
the grid iterates over cell rows, Pallas streams each row's three
neighbor rows into VMEM (the same padded table is bound three times with
block index maps y, y+1, y+2 — overlapping, read-only), and the nine
shifted pairwise reductions run on-core against resident data.

Layout: the table rides as [H+2, F, K, W+2] so the wide W axis lands on
vector lanes and K on sublanes; per-program blocks are [1, F, K, W+2].
Outputs are [H, 3, K, W] (incoming, best-atk, best-row planes).

Semantics are identical to CombatModule's XLA fold (same stencil order,
same tie-breaks) — pinned by tests/test_stencil_pallas.py, which runs
this kernel in interpret mode on CPU against the XLA path.  On real TPU
hardware the kernel compiles natively; enable with NF_PALLAS=1 (opt-in
until chip-time confirms a win over the already-fused XLA fold).

Feature plane order (CombatModule's feats stack; the table's
occupancy column is dropped — empty slots carry eff_atk 0 and mask out):
    0: x   1: y   2: eff_atk   3: camp   4: scene   5: group   6: row
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

F_X, F_Y, F_ATK, F_CAMP, F_SCENE, F_GROUP, F_ROW = range(7)
N_FEATS = 7


def _kernel(top_ref, mid_ref, bot_ref, out_ref, *, w: int, r2: float):
    k = mid_ref.shape[2]
    vx = mid_ref[0, F_X, :, 1 : w + 1]
    vy = mid_ref[0, F_Y, :, 1 : w + 1]
    vcamp = mid_ref[0, F_CAMP, :, 1 : w + 1]
    vscene = mid_ref[0, F_SCENE, :, 1 : w + 1]
    vgroup = mid_ref[0, F_GROUP, :, 1 : w + 1]
    vrow = mid_ref[0, F_ROW, :, 1 : w + 1]

    inc = jnp.zeros((k, w), jnp.int32)
    besta = jnp.full((k, w), -1.0, jnp.float32)
    bestr = jnp.full((k, w), -1.0, jnp.float32)

    # stencil order (dy, dx) ascending — identical to ops.stencil.STENCIL
    for ref in (top_ref, mid_ref, bot_ref):
        for dx in (0, 1, 2):
            cx = ref[0, F_X, :, dx : dx + w]
            cy = ref[0, F_Y, :, dx : dx + w]
            ca = ref[0, F_ATK, :, dx : dx + w]
            cc = ref[0, F_CAMP, :, dx : dx + w]
            cs = ref[0, F_SCENE, :, dx : dx + w]
            cg = ref[0, F_GROUP, :, dx : dx + w]
            cr = ref[0, F_ROW, :, dx : dx + w]
            ddx = vx[:, None, :] - cx[None, :, :]
            ddy = vy[:, None, :] - cy[None, :, :]
            cab = ca[None, :, :]
            ok = (
                (ddx * ddx + ddy * ddy <= r2)
                & (cab != 0.0)
                & (cc[None, :, :] != vcamp[:, None, :])
                & (cs[None, :, :] == vscene[:, None, :])
                & (cg[None, :, :] == vgroup[:, None, :])
                & (cr[None, :, :] != vrow[:, None, :])
            )
            inc = inc + jnp.sum(
                jnp.where(ok, cab, 0.0), axis=1
            ).astype(jnp.int32)
            sa = jnp.where(ok, cab, -1.0)
            sa = jnp.broadcast_to(sa, (k, k, w))
            m = jnp.max(sa, axis=1)
            first = jnp.min(
                jnp.where(sa >= m[:, None, :],
                          jnp.broadcast_to(cr[None, :, :], (k, k, w)),
                          jnp.inf),
                axis=1,
            )
            better = m > besta
            besta = jnp.where(better, m, besta)
            bestr = jnp.where(better, first, bestr)

    # bitcast keeps the exact int32 damage total through the f32 plane
    # (a value cast would round above 2^24)
    out_ref[0, 0] = jax.lax.bitcast_convert_type(inc, jnp.float32)
    out_ref[0, 1] = besta
    out_ref[0, 2] = bestr


def combat_fold_pallas(
    table_planes: jnp.ndarray,
    radius: float,
    width: int,
    interpret: bool = False,
    bucket: int = 0,
):
    """table_planes: [H+2, F, Kpad, W+2] padded feature planes (f32,
    from planes_from_table).  Returns (inc [H,W,K] int32, bestr
    [H,W,K] int32) sliced back to `bucket` slots (0 = keep Kpad)."""
    hp, f, k, wp = table_planes.shape
    h = hp - 2
    w = wp - 2
    assert f == N_FEATS and w == width
    row_spec = lambda off: pl.BlockSpec(  # noqa: E731
        (1, f, k, wp), lambda y, o=off: (y + o, 0, 0, 0)
    )
    out = pl.pallas_call(
        functools.partial(_kernel, w=w, r2=float(radius) * float(radius)),
        grid=(h,),
        in_specs=[row_spec(0), row_spec(1), row_spec(2)],
        out_specs=pl.BlockSpec((1, 3, k, w), lambda y: (y, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((h, 3, k, w), jnp.float32),
        interpret=interpret,
    )(table_planes, table_planes, table_planes)
    inc = jax.lax.bitcast_convert_type(
        out[:, 0].transpose(0, 2, 1), jnp.int32
    )  # [H, W, Kpad]
    bestr = out[:, 2].transpose(0, 2, 1).astype(jnp.int32)
    if bucket and bucket < k:
        inc = inc[..., :bucket]
        bestr = bestr[..., :bucket]
    return inc, bestr


def planes_from_table(payload: jnp.ndarray, width: int, bucket: int) -> jnp.ndarray:
    """CellTable payload [(H*W*K)+1, F+1] -> padded planes [H+2, F, K, W+2].

    The occupancy column is dropped (the kernel masks empty slots via
    eff_atk == 0); border cells pad with zeros so edge neighbors mask
    out exactly like the XLA fold's zero padding.  K also pads up to a
    multiple of 8 so the sublane axis stays tile-aligned on real TPUs
    (pad slots are all-zero => eff_atk 0 => masked; the caller slices
    the outputs back to the table's K)."""
    h = w = width
    k = bucket
    v = payload[:-1, :N_FEATS].reshape(h, w, k, N_FEATS)
    planes = v.transpose(0, 3, 2, 1)  # [H, F, K, W]
    k_pad = (-k) % 8
    return jnp.pad(planes, ((1, 1), (0, 0), (0, k_pad), (1, 1)))
