"""Pallas TPU kernel for the combat stencil fold (split-table form).

The XLA path (game/combat.py's fold over ops/stencil.stencil_fold) walks
the 3x3 neighborhood as nine shifted slices of the padded attacker
table — nine HBM passes over the candidate planes plus whatever
intermediates XLA materializes for the [Kv, Ka] pairwise masks.  This
kernel makes the whole fold ONE pass: the grid iterates over cell rows;
each program holds the victim row's planes plus the three neighboring
attacker rows in VMEM (the same padded attacker planes bound three times
with block index maps y, y+1, y+2 — overlapping, read-only), and the
nine shifted pairwise reductions run on-core against resident data.

Layout: planes ride as [rows, F, K, W(+2)] so the wide W axis lands on
vector lanes and K on sublanes.  Victims are resident (no padding, one
mid-row ref); attackers are the scanned side (padded, three refs).
Outputs are [H, 3, Kv, W] (incoming, best-atk, best-row planes).

Semantics are identical to CombatModule's XLA fold (same stencil order,
same tie-breaks) — pinned by tests/test_stencil_pallas.py, which runs
this kernel in interpret mode on CPU against the XLA path.  On real TPU
hardware the kernel compiles natively; enable with NF_PALLAS=1 (opt-in
until chip-time confirms a win over the already-fused XLA fold).

Victim feature planes (CombatModule's vic_feats; occupancy dropped):
    0: x   1: y   2: camp   3: scene   4: group
Attacker feature planes (att_feats):
    0: x   1: y   2: eff_atk   3: camp   4: scene   5: group   6: row
(no self-exclusion compare: self always shares its own camp, so the
no-friendly-fire mask rules self out — keep in sync with CombatModule)
"""

from __future__ import annotations

import functools
import logging
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# "no attacker" sentinel (2^24) — must match game.combat.NO_ROW; finite
# on purpose, an inf loop carry hangs the XLA CPU algebraic simplifier
_NO_ROW = 16777216.0

V_X, V_Y, V_CAMP, V_SCENE, V_GROUP = range(5)
N_VFEATS = 5
A_X, A_Y, A_ATK, A_CAMP, A_SCENE, A_GROUP, A_ROW = range(7)
N_AFEATS = 7

# SoA feature-bank columns of the FUSED engine (NF_PALLAS=2): one
# [N, 6] bank serves both sides of the fold — victims read the first
# five, attackers additionally read eff_atk, and the attacker "row"
# column of the split layout disappears (the gather index IS the row).
B_X, B_Y, B_CAMP, B_SCENE, B_GROUP, B_ATK = range(6)
N_BFEATS = 6

# nf-lint pallas-parity-pinned registry (lint/rules_pallas.py): every
# jit-reachable `pl.pallas_call` site in this module must be named here,
# keyed by its enclosing function, with the interpret-mode parity test
# that pins it bit-identical to the XLA reference fold.  Paths are
# repo-relative; the rule checks the file exists and actually exercises
# the named function in interpret mode.
PALLAS_PARITY_TESTS = {
    "combat_fold_pallas": "tests/test_stencil_pallas.py",
    "fused_neighborhood": "tests/test_stencil_pallas.py",
}


def _kernel(vic_ref, top_ref, mid_ref, bot_ref, out_ref, *, w: int, r2: float):
    kv = vic_ref.shape[2]
    ka = top_ref.shape[2]
    vx = vic_ref[0, V_X]
    vy = vic_ref[0, V_Y]
    vcamp = vic_ref[0, V_CAMP]
    vscene = vic_ref[0, V_SCENE]
    vgroup = vic_ref[0, V_GROUP]

    inc = jnp.zeros((kv, w), jnp.int32)
    besta = jnp.full((kv, w), -1.0, jnp.float32)
    bestr = jnp.full((kv, w), _NO_ROW, jnp.float32)

    # stencil order (dy, dx) ascending — identical to ops.stencil.STENCIL
    for ref in (top_ref, mid_ref, bot_ref):
        for dx in (0, 1, 2):
            cx = ref[0, A_X, :, dx : dx + w]
            cy = ref[0, A_Y, :, dx : dx + w]
            ca = ref[0, A_ATK, :, dx : dx + w]
            cc = ref[0, A_CAMP, :, dx : dx + w]
            csc = ref[0, A_SCENE, :, dx : dx + w]
            cg = ref[0, A_GROUP, :, dx : dx + w]
            cr = ref[0, A_ROW, :, dx : dx + w]
            ddx = vx[:, None, :] - cx[None, :, :]
            ddy = vy[:, None, :] - cy[None, :, :]
            cab = ca[None, :, :]
            ok = (
                (ddx * ddx + ddy * ddy <= r2)
                & (cab != 0.0)
                & (cc[None, :, :] != vcamp[:, None, :])
                & (csc[None, :, :] == vscene[:, None, :])
                & (cg[None, :, :] == vgroup[:, None, :])
            )
            inc = inc + jnp.sum(
                jnp.where(ok, cab, 0.0), axis=1
            ).astype(jnp.int32)
            sa = jnp.where(ok, cab, -1.0)
            sa = jnp.broadcast_to(sa, (kv, ka, w))
            m = jnp.max(sa, axis=1)
            first = jnp.min(
                jnp.where(sa >= m[:, None, :],
                          jnp.broadcast_to(cr[None, :, :], (kv, ka, w)),
                          _NO_ROW),
                axis=1,
            )
            # global min-row tie-break, identical to combat_fold_closure:
            # neutralize empty shifts (m == -1), then lexicographic
            # (max attack, min row) merge with `bestr` consumed once
            first = jnp.where(m >= 0.0, first, _NO_ROW)
            top = jnp.maximum(besta, m)
            bestr = jnp.minimum(
                jnp.where(m >= top, first, _NO_ROW),
                jnp.where(besta >= top, bestr, _NO_ROW),
            )
            besta = top

    # bitcast keeps the exact int32 damage total through the f32 plane
    # (a value cast would round above 2^24)
    out_ref[0, 0] = jax.lax.bitcast_convert_type(inc, jnp.float32)
    out_ref[0, 1] = besta
    out_ref[0, 2] = bestr


def combat_fold_pallas(vic_table, att_table, radius: float, interpret: bool = False):
    """Fused 3x3 stencil fold: victims resident, attackers scanned.

    vic_table / att_table: ops.stencil.CellTable over the SAME grid
    geometry (vic carries 5 feature cols, att 7 — see module docstring).
    Returns (inc [H, W, Kv] int32, bestr [H, W, Kv] int32), matching the
    XLA fold's outputs before `pull`.

    NF_PALLAS_ALIGN=<n> pads the lane (W) axis up to a multiple of n
    (128 = TPU lane width) with zero-occupancy ghost cells — masked out
    by the fold exactly like edge padding.  Insurance for grids whose W
    (395 at the 1M benchmark) Mosaic may reject or tile poorly; costs
    W_pad/W extra lanes, so it is opt-in until chip time ranks the two."""
    import os

    width = vic_table.width
    assert att_table.width == width and att_table.cell_size == vic_table.cell_size
    # nf-lint: disable=trace-safety -- sanctioned A/B knob: trace-time
    # read baked into the compilation; flipping needs a fresh jit cache
    align = int(os.environ.get("NF_PALLAS_ALIGN", "0") or 0)
    w_pad = ((-width) % align) if align > 1 else 0
    vic = _planes(vic_table.payload, width, vic_table.bucket, N_VFEATS,
                  pad=False, w_pad=w_pad)
    att = _planes(att_table.payload, width, att_table.bucket, N_AFEATS,
                  pad=True, w_pad=w_pad)
    h = width
    w = width + w_pad
    kv = vic.shape[2]
    ka = att.shape[2]
    vic_spec = pl.BlockSpec((1, N_VFEATS, kv, w), lambda y: (y, 0, 0, 0))
    att_spec = lambda off: pl.BlockSpec(  # noqa: E731
        (1, N_AFEATS, ka, w + 2), lambda y, o=off: (y + o, 0, 0, 0)
    )
    out = pl.pallas_call(
        functools.partial(_kernel, w=w, r2=float(radius) * float(radius)),
        grid=(h,),
        in_specs=[vic_spec, att_spec(0), att_spec(1), att_spec(2)],
        out_specs=pl.BlockSpec((1, 3, kv, w), lambda y: (y, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((h, 3, kv, w), jnp.float32),
        interpret=interpret,
    )(vic, att, att, att)
    inc = jax.lax.bitcast_convert_type(
        out[:, 0].transpose(0, 2, 1), jnp.int32
    )  # [H, W(+pad), Kv]
    bestr_f = out[:, 2].transpose(0, 2, 1)
    # _NO_ROW (no attacker) -> -1; row ids are exact in f32 (< 2^24)
    bestr = jnp.where(bestr_f >= _NO_ROW, -1.0, bestr_f).astype(jnp.int32)
    if w_pad:
        inc = inc[:, :width]
        bestr = bestr[:, :width]
    if kv > vic_table.bucket:
        inc = inc[..., : vic_table.bucket]
        bestr = bestr[..., : vic_table.bucket]
    return inc, bestr


# ---------------------------------------------------------------------------
# Fused neighborhood engine (NF_PALLAS=2)
#
# The split engine above still eats two `[n_cells*K+1, F+1]` payload
# scatters per frame (table_from_slots for victims AND attackers — the
# two biggest per-frame HBM materializations on the roofline).  The
# fused engine keeps only the slot ASSIGNMENT (ops.stencil.CellSlots —
# the counting-sort `slot_of` ranks) and inverts the data flow: the SoA
# feature bank rides into VMEM once per program, and each grid program
# GATHERS its victim row and the three neighboring attacker rows from
# the bank via per-cell row-id planes, then runs the nine shifted
# pairwise reductions on-core.  The AOI/interest occupancy count
# (ops/aoi.neighbor_counts semantics, ops/interest.scope_mask scoping)
# folds in the same VMEM residency — the padded payload tables are
# never written at all on this path.
# ---------------------------------------------------------------------------

_log = logging.getLogger(__name__)

# Per-core VMEM on current TPUs is ~16 MB; leave headroom for Mosaic's
# own scratch.  NF_PALLAS_VMEM_MB overrides (tests force it tiny to
# exercise the fallback arm without building a 1M-entity world).
FUSED_VMEM_MB_DEFAULT = 12.0
ENV_VMEM_MB = "NF_PALLAS_VMEM_MB"

_FUSED_FALLBACKS = {"total": 0}
_FUSED_LOGGED: set = set()


def fused_fallback_total() -> int:
    """Trace-time NF_PALLAS=2 -> split-path downgrades this process —
    scraped by telemetry as `nf_pallas_fallback_total`.  Counts per
    retrace (the engine choice is trace-time), not per tick."""
    return _FUSED_FALLBACKS["total"]


def note_fused_fallback(reason: str, need: int, budget: int) -> None:
    """Record a fused-path downgrade: bump the metric always, log once
    per distinct reason (a 1M-world retraces often; one line is signal,
    a thousand are noise)."""
    _FUSED_FALLBACKS["total"] += 1
    if reason not in _FUSED_LOGGED:
        _FUSED_LOGGED.add(reason)
        _log.warning(
            "NF_PALLAS=2 fused engine falling back to split tables: %s "
            "(tile footprint %d bytes > VMEM budget %d bytes)",
            reason, need, budget,
        )


def fused_vmem_bytes(
    n: int, width: int, vic_bucket: int, att_bucket: int, w_pad: int = 0
) -> int:
    """Host-side estimate of one fused program's VMEM residency: the
    whole feature bank + six bound idx tiles + the gathered per-band
    feature planes + the output tile, all f32/i32 (4 B), with the same
    sublane (K->8) and lane (bank->128) padding the wrapper applies.
    Deliberately counts the bank once and temporaries generously — the
    check gates a fallback, so overestimating is the safe direction."""
    w = width + w_pad + 2
    lanes = (n + 1) + ((-(n + 1)) % 128)
    kv = vic_bucket + ((-vic_bucket) % 8)
    ka = att_bucket + ((-att_bucket) % 8)
    bank = N_BFEATS * lanes * 4
    idx_tiles = 3 * (kv + ka) * w * 4
    # per band: 6 gathered victim-candidate planes (x/y/scene/group/
    # occ/row) and 7 attacker planes (those + eff_atk/camp, minus occ)
    gathered = 3 * (6 * kv + 7 * ka) * w * 4
    out = 4 * kv * (w - 2) * 4
    return bank + idx_tiles + gathered + out


def fused_fits_vmem(
    n: int, width: int, vic_bucket: int, att_bucket: int, w_pad: int = 0
) -> Tuple[bool, int, int]:
    """(fits, need_bytes, budget_bytes) for the fused engine at this
    static geometry.  Called at trace time from the engine dispatch in
    game/combat.py; oversize worlds downgrade to the split path instead
    of letting Mosaic (or the interpreter) blow VMEM."""
    import os

    # nf-lint: disable=trace-safety -- sanctioned sizing knob: read at
    # trace time to pick the engine baked into this compilation; tests
    # shrink it to force the fallback arm deterministically
    budget_mb = float(os.environ.get(ENV_VMEM_MB, "") or FUSED_VMEM_MB_DEFAULT)
    budget = int(budget_mb * 1024 * 1024)
    need = fused_vmem_bytes(n, width, vic_bucket, att_bucket, w_pad)
    return need <= budget, need, budget


def _idx_planes(
    slot_of: jnp.ndarray, n: int, width: int, bucket: int,
    height: int, w_pad: int,
) -> jnp.ndarray:
    """CellSlots.slot_of [N] -> bordered row-id planes [H+2, K8, W+2+pad]
    (i32).  Slot s holds the row scattered there by the slot assignment,
    or the sentinel `n` when empty — the bank carries an all-zero row at
    index n, so sentinel gathers read zero features exactly like the
    split path's zero payload slots.  Borders and K/W alignment pads are
    sentinel too (the split path pads payload with zeros; same mask
    outcome).  Placed slots are unique by construction; only the dump
    slot sees duplicate scatters, and it is re-pinned to the sentinel
    afterwards so the planes stay deterministic."""
    dump = height * width * bucket
    rows = jnp.arange(slot_of.shape[0], dtype=jnp.int32)
    idx = (
        jnp.full((dump + 1,), n, jnp.int32)
        .at[slot_of].set(rows)
        .at[dump].set(n)
    )
    planes = idx[:dump].reshape(height, width, bucket).transpose(0, 2, 1)
    k_pad = (-bucket) % 8
    return jnp.pad(
        planes, ((1, 1), (0, k_pad), (1, 1 + w_pad)), constant_values=n
    )


def _fused_kernel(
    bank_ref, vt_ref, vm_ref, vb_ref, at_ref, am_ref, ab_ref, out_ref,
    *, w: int, r2: float, n: int,
):
    """One grid program = one cell row: gather the resident victims and
    the three neighboring bands from the bank, fold combat AND the AOI
    occupancy count in one residency.

    Combat math is line-for-line the split `_kernel` above (same stencil
    order, same lexicographic tie-break with `bestr` consumed once) with
    the payload reads replaced by bank gathers; the attacker row id is
    the gather index itself.  Sentinel gathers (empty slots, borders)
    read the all-zero bank row => eff_atk 0 => masked, identical to the
    split path's zero padding.  Empty-shift neutralization (m == -1)
    also absorbs the one place sentinels differ — their row id is n, not
    0, but `first` is discarded whenever no real attacker set m."""
    from .interest import scope_mask

    kv = vt_ref.shape[1]
    ka = at_ref.shape[1]
    bank = bank_ref[:]
    vi = vm_ref[0][:, 1 : 1 + w]  # [kv, w] resident victim row ids
    vx = bank[B_X][vi]
    vy = bank[B_Y][vi]
    vcamp = bank[B_CAMP][vi]
    vscene = bank[B_SCENE][vi]
    vgroup = bank[B_GROUP][vi]
    vrow = vi.astype(jnp.float32)

    inc = jnp.zeros((kv, w), jnp.int32)
    besta = jnp.full((kv, w), -1.0, jnp.float32)
    bestr = jnp.full((kv, w), _NO_ROW, jnp.float32)
    nbr = jnp.zeros((kv, w), jnp.int32)

    # stencil order (dy, dx) ascending — identical to ops.stencil.STENCIL
    for a_ref, v_ref in (
        (at_ref, vt_ref), (am_ref, vm_ref), (ab_ref, vb_ref)
    ):
        ai = a_ref[0]  # [ka, w+2] attacker row ids for this band
        ax = bank[B_X][ai]
        ay = bank[B_Y][ai]
        aa = bank[B_ATK][ai]
        ac = bank[B_CAMP][ai]
        asc = bank[B_SCENE][ai]
        ag = bank[B_GROUP][ai]
        ar = ai.astype(jnp.float32)
        bi = v_ref[0]  # [kv, w+2] AOI candidates: the full population
        bx = bank[B_X][bi]
        by = bank[B_Y][bi]
        bsc = bank[B_SCENE][bi]
        bg = bank[B_GROUP][bi]
        bocc = bi < n
        br = bi.astype(jnp.float32)
        for dx in (0, 1, 2):
            cx = ax[:, dx : dx + w]
            cy = ay[:, dx : dx + w]
            ca = aa[:, dx : dx + w]
            cc = ac[:, dx : dx + w]
            csc = asc[:, dx : dx + w]
            cg = ag[:, dx : dx + w]
            cr = ar[:, dx : dx + w]
            ddx = vx[:, None, :] - cx[None, :, :]
            ddy = vy[:, None, :] - cy[None, :, :]
            cab = ca[None, :, :]
            ok = (
                (ddx * ddx + ddy * ddy <= r2)
                & (cab != 0.0)
                & (cc[None, :, :] != vcamp[:, None, :])
                & (csc[None, :, :] == vscene[:, None, :])
                & (cg[None, :, :] == vgroup[:, None, :])
            )
            inc = inc + jnp.sum(
                jnp.where(ok, cab, 0.0), axis=1
            ).astype(jnp.int32)
            sa = jnp.where(ok, cab, -1.0)
            sa = jnp.broadcast_to(sa, (kv, ka, w))
            m = jnp.max(sa, axis=1)
            first = jnp.min(
                jnp.where(sa >= m[:, None, :],
                          jnp.broadcast_to(cr[None, :, :], (kv, ka, w)),
                          _NO_ROW),
                axis=1,
            )
            first = jnp.where(m >= 0.0, first, _NO_ROW)
            top = jnp.maximum(besta, m)
            bestr = jnp.minimum(
                jnp.where(m >= top, first, _NO_ROW),
                jnp.where(besta >= top, bestr, _NO_ROW),
            )
            besta = top

            # AOI/interest occupancy in the same residency: occupied,
            # within radius, interest-scoped, not self (row compare —
            # combat needs no self-exclusion, camp does it; here self is
            # always in scope of itself and must be ruled out)
            nx = bx[:, dx : dx + w]
            ny = by[:, dx : dx + w]
            nsc = bsc[:, dx : dx + w]
            ng = bg[:, dx : dx + w]
            nocc = bocc[:, dx : dx + w]
            nrw = br[:, dx : dx + w]
            ndx = vx[:, None, :] - nx[None, :, :]
            ndy = vy[:, None, :] - ny[None, :, :]
            near = (
                (ndx * ndx + ndy * ndy <= r2)
                & nocc[None, :, :]
                & scope_mask(
                    nsc[None, :, :], ng[None, :, :],
                    vscene[:, None, :], vgroup[:, None, :],
                )
                & (nrw[None, :, :] != vrow[:, None, :])
            )
            nbr = nbr + jnp.sum(near, axis=1).astype(jnp.int32)

    out_ref[0, 0] = jax.lax.bitcast_convert_type(inc, jnp.float32)
    out_ref[0, 1] = besta
    out_ref[0, 2] = bestr
    out_ref[0, 3] = jax.lax.bitcast_convert_type(nbr, jnp.float32)


def fused_neighborhood(
    bank: jnp.ndarray,
    vic_slots,
    att_slots,
    radius: float,
    interpret: bool = False,
):
    """Fused table-free neighborhood fold (NF_PALLAS=2).

    bank: [N, 6] f32 SoA feature bank, columns B_X..B_ATK (victims read
    the first five, attackers all six; the attacker row id is implicit —
    it IS the bank row).  vic_slots / att_slots: ops.stencil.CellSlots
    over the same grid geometry (typically the full population and the
    attacking subset of the same frame).

    Returns (inc [H, W, Kv] i32, bestr [H, W, Kv] i32, nbr [H, W, Kv]
    i32): incoming damage and best-attacker row bit-identical to
    combat_fold_pallas / the XLA fold on equal slot assignments, plus
    the AOI/interest occupancy count per victim (scope per
    ops.interest.scope_mask, self excluded) — the split path would need
    a whole second stencil pass (ops.aoi.neighbor_counts) for that.

    NF_PALLAS_ALIGN pads the lane axis exactly like combat_fold_pallas
    (sentinel ghost cells instead of zero payload)."""
    import os

    width = vic_slots.width
    height = vic_slots.height if vic_slots.height > 0 else width
    assert att_slots.width == width
    assert att_slots.cell_size == vic_slots.cell_size
    n = bank.shape[0]
    # nf-lint: disable=trace-safety -- sanctioned A/B knob: trace-time
    # read baked into the compilation; flipping needs a fresh jit cache
    align = int(os.environ.get("NF_PALLAS_ALIGN", "0") or 0)
    w_pad = ((-width) % align) if align > 1 else 0
    w = width + w_pad
    lane_pad = (-(n + 1)) % 128
    # sentinel zero row at index n, then lane-align; pad rows are never
    # gathered (all plane ids are <= n)
    bank_t = jnp.pad(
        bank.astype(jnp.float32), ((0, 1 + lane_pad), (0, 0))
    ).T  # [6, NP]
    vic = _idx_planes(
        vic_slots.slot_of, n, width, vic_slots.bucket, height, w_pad
    )
    att = _idx_planes(
        att_slots.slot_of, n, width, att_slots.bucket, height, w_pad
    )
    kv = vic.shape[1]
    ka = att.shape[1]
    bank_spec = pl.BlockSpec(bank_t.shape, lambda y: (0, 0))
    band = lambda kk, off: pl.BlockSpec(  # noqa: E731
        (1, kk, w + 2), lambda y, o=off: (y + o, 0, 0)
    )
    out = pl.pallas_call(
        functools.partial(
            _fused_kernel, w=w, r2=float(radius) * float(radius), n=n
        ),
        grid=(height,),
        in_specs=[
            bank_spec,
            band(kv, 0), band(kv, 1), band(kv, 2),
            band(ka, 0), band(ka, 1), band(ka, 2),
        ],
        out_specs=pl.BlockSpec((1, 4, kv, w), lambda y: (y, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((height, 4, kv, w), jnp.float32),
        interpret=interpret,
    )(bank_t, vic, vic, vic, att, att, att)
    inc = jax.lax.bitcast_convert_type(
        out[:, 0].transpose(0, 2, 1), jnp.int32
    )  # [H, W(+pad), Kv]
    bestr_f = out[:, 2].transpose(0, 2, 1)
    bestr = jnp.where(bestr_f >= _NO_ROW, -1.0, bestr_f).astype(jnp.int32)
    nbr = jax.lax.bitcast_convert_type(
        out[:, 3].transpose(0, 2, 1), jnp.int32
    )
    if w_pad:
        inc = inc[:, :width]
        bestr = bestr[:, :width]
        nbr = nbr[:, :width]
    if kv > vic_slots.bucket:
        inc = inc[..., : vic_slots.bucket]
        bestr = bestr[..., : vic_slots.bucket]
        nbr = nbr[..., : vic_slots.bucket]
    return inc, bestr, nbr


def _planes(payload: jnp.ndarray, width: int, bucket: int, n_feats: int,
            pad: bool, w_pad: int = 0) -> jnp.ndarray:
    """CellTable payload [(H*W*K)+1, F+1] -> feature planes.

    pad=True (attacker side) adds the one-cell zero border the shifted
    reads need: [H+2, F, K, W+2]; border slots are all-zero => eff_atk 0
    => masked, exactly like the XLA fold's zero padding.  pad=False
    (victim side, resident) gives [H, F, K, W].  K pads up to a multiple
    of 8 so the sublane axis stays tile-aligned on real TPUs (pad slots
    are all-zero; for victims the caller slices outputs back to K —
    zero-slot victims never map back through `pull`).  w_pad appends
    zero-occupancy ghost cell columns for lane alignment (see
    combat_fold_pallas)."""
    h = w = width
    k = bucket
    v = payload[:-1, :n_feats].reshape(h, w, k, n_feats)
    planes = v.transpose(0, 3, 2, 1)  # [H, F, K, W]
    k_pad = (-k) % 8
    if pad:
        return jnp.pad(planes, ((1, 1), (0, 0), (0, k_pad), (1, 1 + w_pad)))
    if k_pad or w_pad:
        return jnp.pad(planes, ((0, 0), (0, 0), (0, k_pad), (0, w_pad)))
    return planes
