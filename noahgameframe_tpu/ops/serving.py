"""Batched serving edge: vmap-over-sessions interest deltas, on device.

The legacy serve path (net/roles/game.py `_send_interest_pos`) walks a
Python loop over sessions — per session a numpy sort, two searchsorted
passes and half a dozen fancy gathers against a per-session
`_interest_seen` dict.  At 2000 sessions that loop alone is ~190 ms of
exclusive frame time (bench_runs/r05_served_100k_2000s_cpu.json).  This
module computes the SAME per-session delta stream for ALL sessions in
one static-shaped dispatch:

1. `bump_qver` — a device-carried version counter per entity row that
   increments exactly when the u16-quantized position changes.  Together
   with the host-bumped allocation generation (core/store.py
   `_ClassHost.row_gen`, +1 per row free) it replaces the legacy
   per-session `(rows, guid_head, guid_data, qpos)` seen tuples with two
   i32 vectors: a session has seen the CURRENT identity+position of row
   r iff its stored `(gen, qver)` for r equals the live `(gen[r],
   qver[r])`.  Guid equality ⟺ gen equality because guids are
   never reused (pure-counter allocator) and gen bumps on every free;
   qpos equality ⟺ qver equality because the serve kernel runs on
   every flush in which any position changed, so the version counter
   observes every quantum transition the legacy path would have stored.
2. `interest_delta` — per-session set algebra over the candidate slots
   from ops/interest (`_scan_observers` 3x3 reads): sort the visible
   rows (ascending, sentinel-padded — the legacy wire order), match
   them against the session's sorted seen-table by vmapped
   searchsorted, and emit `send` (enter or changed) and `gone`
   (previously seen, no longer visible or recycled) masks plus the next
   seen-table.  One dispatch for every session; the host's only job is
   slicing the fetched dense buffers into per-session packets
   (net/serving.py).
3. `slot_compact` — stable compaction of candidate slots in SLOT order
   (not sorted) for the interest-scoped BatchPropertySync lane, whose
   legacy wire order is candidate order.

Everything here is shape-static and jit-compiled by the caller (the
game role caches per-(class, padded-session-count) jits, same policy as
`_interest_step`).  No int64 on device: guids stay host-side (the wire
payload gathers guid_head/guid_data from the host mirrors by fetched
row id); the kernel deals only in i32 rows, generations and versions.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

# sentinel for "empty slot" in sorted row vectors: sorts after every
# real row id and never equals one (capacities are << 2^31)
SENTINEL = jnp.iinfo(jnp.int32).max


class SeenTable(NamedTuple):
    """Per-session device seen-state for one class: which entity rows the
    session's client currently mirrors, and at which (allocation
    generation, position version) it last received them.  `rows` is
    sorted ascending per session with SENTINEL padding — the invariant
    both searchsorted passes in `interest_delta` rely on."""

    rows: jnp.ndarray  # [S, M] i32, sorted asc, SENTINEL = empty
    gen: jnp.ndarray  # [S, M] i32 allocation generation at last send
    qver: jnp.ndarray  # [S, M] i32 position version at last send


class ServeDelta(NamedTuple):
    """One frame's serve output for all sessions of one class."""

    vis: jnp.ndarray  # [S, M] i32 visible rows, sorted asc, SENTINEL pad
    send: jnp.ndarray  # [S, M] bool — enter-view or changed since seen
    gone: jnp.ndarray  # [S, M] bool over the OLD seen slots
    gone_rows: jnp.ndarray  # [S, M] i32 old seen rows (garbage where ~gone)
    seen: SeenTable  # next frame's seen-state


def init_seen(sessions: int, slots: int) -> SeenTable:
    """All-empty seen state ([S, M]); also the per-slot reset value."""
    return SeenTable(
        rows=jnp.full((sessions, slots), SENTINEL, jnp.int32),
        gen=jnp.zeros((sessions, slots), jnp.int32),
        qver=jnp.zeros((sessions, slots), jnp.int32),
    )


def bump_qver(
    q: jnp.ndarray,  # [C, 3] i32 quantized positions (ops.interest.quantize)
    prev_q: jnp.ndarray,  # [C, 3] i32 last kernel run's q
    qver: jnp.ndarray,  # [C] i32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(qver', prev_q'): bump a row's version when its quantum moved.
    Runs inside the serve kernel, so the counter advances exactly once
    per observed transition — two sessions comparing stored versions
    against it agree with the legacy per-session qpos equality test."""
    changed = jnp.any(q != prev_q, axis=-1)
    return qver + changed.astype(jnp.int32), q


def interest_delta(
    cand_rows: jnp.ndarray,  # [S, M] i32 candidate rows (ops.interest)
    cand_ok: jnp.ndarray,  # [S, M] bool — occupied, in-radius, in-zone
    gen: jnp.ndarray,  # [C] i32 live allocation generations (host upload)
    qver: jnp.ndarray,  # [C] i32 live position versions (bump_qver output)
    seen: SeenTable,
) -> ServeDelta:
    """The per-session delta set algebra, vmapped over the session axis.

    send[s,j] ⇔ vis[s,j] is visible and the session has NOT seen it at
    the current (gen, qver); gone[s,j] ⇔ seen row j is no longer in the
    visible set under the SAME generation (left radius, died, or row
    recycled to a new guid — the legacy guid-mismatch despawn)."""
    n_rows = gen.shape[0]
    # sorted visible set; stencil cells are disjoint so a row appears in
    # at most one candidate slot — no dedup pass needed
    vis = jnp.sort(jnp.where(cand_ok, cand_rows, SENTINEL), axis=1)
    vis_ok = vis < SENTINEL
    vr = jnp.clip(vis, 0, n_rows - 1)
    vis_gen = jnp.where(vis_ok, gen[vr], 0)
    vis_qver = jnp.where(vis_ok, qver[vr], 0)

    find = jax.vmap(lambda hay, needles: jnp.searchsorted(hay, needles))
    m = seen.rows.shape[1]
    idx = jnp.clip(find(seen.rows, vis), 0, m - 1)
    take = jnp.take_along_axis
    same = (
        vis_ok
        & (take(seen.rows, idx, 1) == vis)
        & (take(seen.gen, idx, 1) == vis_gen)
        & (take(seen.qver, idx, 1) == vis_qver)
    )
    send = vis_ok & ~same

    seen_ok = seen.rows < SENTINEL
    sr = jnp.clip(seen.rows, 0, n_rows - 1)
    j = jnp.clip(find(vis, seen.rows), 0, m - 1)
    still = (
        seen_ok
        & (take(vis, j, 1) == seen.rows)
        & (gen[sr] == seen.gen)  # same row AND same allocation = same guid
    )
    gone = seen_ok & ~still

    return ServeDelta(
        vis=vis,
        send=send,
        gone=gone,
        gone_rows=seen.rows,
        seen=SeenTable(rows=vis, gen=vis_gen, qver=vis_qver),
    )


def slot_compact(
    cand_rows: jnp.ndarray,  # [S, M] i32
    cand_ok: jnp.ndarray,  # [S, M] bool
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(rows [S, M], count [S]): ok slots compacted to the front of each
    session's lane in ORIGINAL slot order (stable) — the legacy
    BatchPropertySync wire order is candidate order, not sorted."""
    perm = jnp.argsort(~cand_ok, axis=1, stable=True)
    rows = jnp.take_along_axis(cand_rows, perm, axis=1)
    return rows, jnp.sum(cand_ok, axis=1, dtype=jnp.int32)
