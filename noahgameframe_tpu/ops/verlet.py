"""Verlet-cached cell tables: displacement-gated rebuild of the binning.

The per-tick cell-table build (ops/stencil.py) re-sorts every entity every
tick, and the profile names that one stable argsort as the dominant
irregular-memory cost of the device tick.  Molecular-dynamics engines
solved this shape decades ago: Verlet neighbor lists (Verlet, Phys. Rev.
159, 1967) bin with an INFLATED radius `r + skin` and rebuild only when
accumulated displacement threatens recall — GPU MD codes (HOOMD-blue,
Anderson et al. 2008) amortize the O(N log N) structure build across many
cheap reuse steps the same way.

Applied to the cell-table engine:

- The grid is laid out with `cell_size >= r + skin` (the caller inflates
  its geometry once, at module init).  A build anchors every entity at its
  CURRENT position; the cache keeps that anchor plus the sorted order /
  sorted keys / slot assignment the argsort produced.
- While every entity has moved less than `skin / 2` from its anchor
  (`2 * max_displacement < skin`), any pair within true radius `r` of each
  other TODAY was within `r + skin` of each other at anchor time, so the
  anchor binning still covers the 3x3 stencil query — the sort can be
  skipped and only the cheap payload scatter replayed with fresh features.
- Queries always mask by true distance on CURRENT positions, so results
  are bit-identical to an always-rebuild baseline on the same (inflated)
  geometry: the same candidate pairs pass the mask either way, damage
  sums are order-insensitive exact int-in-f32, and the combat tie-break
  is placement-invariant (global min row, game/combat.py).  The one
  caveat is bucket overflow: anchor and current binnings can drop
  DIFFERENT rows when a cell exceeds its K slots, so bit-parity claims
  assume zero drops (auto_bucket's contract).

The rebuild decision is a single on-device scalar, so the whole build
wraps in one `lax.cond`: the expensive branch re-sorts and re-anchors,
the cheap branch bumps the age.  Under shard_map the predicate is
`lax.pmax`-combined so every shard votes coherently (a one-sided rebuild
would desynchronize the carried caches).  Under jit+GSPMD the global
`jnp.max` reduction achieves the same automatically.

Any change to the ACTIVE set (spawn, destroy, shard migration) forces a
rebuild: a departed row's stale slot would keep it visible, an arrival
would be invisible.  The trigger therefore compares the full active mask
against the anchor mask, which also guarantees every subset table built
through the cached order (attackers, moved-entity interest lists) only
ever draws from anchored rows.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .stencil import (
    CellTable,
    _cell_keys,
    _counting_slots,
    _slots_from_ranks,
    _sorted_segments,
    binning_mode,
    table_from_slots,
)

ENV_SKIN = "NF_VERLET_SKIN"


class VerletCache(NamedTuple):
    """Carried tick state for one grid (pure arrays: rides WorldState /
    shard_map carries, donates, checkpoints and tree_maps like any leaf).

    anchor_pos:    [N, 2] f32 — positions at the last rebuild.
    anchor_active: [N] bool   — active mask at the last rebuild.
    order:         [N] i32    — the stable sort by anchor cell id
                                (NF_BINNING=sort engine; the count engine
                                has no sorted order and stores arange —
                                carried but unused).
    skey:          [N] i32    — engine-dependent: the SORTED cell keys
                                under the sort engine, the PER-ROW anchor
                                cell keys under the count engine (both
                                use n_cells for inactive).  Either way it
                                is exactly what sub_table() needs to
                                re-rank a fresh subset on a reuse tick,
                                and it is meaningless across engines — a
                                cache built under one NF_BINNING value
                                must be dropped before running the other
                                (SpatialWorld.load() enforces this for
                                snapshots).
    slot_of:       [N] i32    — full-table slot per row for the bucket the
                                cache was built with (geometry-baked: any
                                bucket/width change must drop the cache).
    rebuilds/reuses: i32 scalars — lifetime counters (telemetry).
    age:           i32 scalar — ticks since the last rebuild (staleness).
    """

    anchor_pos: jnp.ndarray
    anchor_active: jnp.ndarray
    order: jnp.ndarray
    skey: jnp.ndarray
    slot_of: jnp.ndarray
    rebuilds: jnp.ndarray
    reuses: jnp.ndarray
    age: jnp.ndarray


def skin_from_env(default: float = 0.0) -> float:
    """The NF_VERLET_SKIN tuning knob; <= 0 (or unset/garbage) means off —
    exactly today's rebuild-every-tick behavior, zero structural change."""
    raw = os.environ.get(ENV_SKIN, "").strip()
    if not raw:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        return float(default)


def init_cache(n: int) -> VerletCache:
    """A never-built cache: the all-False anchor mask disagrees with any
    live world, so the first refresh() always takes the rebuild branch
    (and table_from_slots stays harmless even if queried raw)."""
    # each leaf gets its OWN buffer — run_device donates the whole state
    # pytree, and XLA rejects the same buffer donated twice
    return VerletCache(
        anchor_pos=jnp.zeros((n, 2), jnp.float32),
        anchor_active=jnp.zeros((n,), bool),
        order=jnp.zeros((n,), jnp.int32),
        skey=jnp.zeros((n,), jnp.int32),
        slot_of=jnp.zeros((n,), jnp.int32),
        rebuilds=jnp.int32(0),
        reuses=jnp.int32(0),
        age=jnp.int32(0),
    )


def need_rebuild(
    cache: VerletCache,
    pos: jnp.ndarray,
    active: jnp.ndarray,
    skin: float,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Scalar bool: must the binning be rebuilt this tick?

    Triggers on ARRIVALS — rows active now that the anchor never binned
    (spawn, respawn, migration-in): a stale table would hide them.  Rows
    that merely LEFT (death, migration-out) do not trigger — the payload
    replay (table_from_slots) forces every now-inactive row to the dump
    slot, which is exactly what a fresh build of the shrunken set would
    produce; this also keeps every sub_mask a subset of the anchor, since
    callers only pass sub_mask & active.  Also triggers when
    `2 * max_displacement >= skin` over rows live in BOTH the anchor and
    the present (the boundary itself rebuilds: reuse is only proven for
    strictly-less-than).  Displacement uses the first two position
    components, matching the grid's 2D cells.

    axis_name: shard_map axis to pmax the vote over (sharded worlds must
    rebuild together or their carried caches desynchronize); jit+GSPMD
    callers omit it — the global reductions already see the whole array.
    """
    d = pos[:, :2] - cache.anchor_pos
    both = active & cache.anchor_active
    d2 = jnp.where(both, jnp.sum(d * d, axis=-1), 0.0)
    s = jnp.float32(float(skin))
    trig = jnp.any(active & ~cache.anchor_active) | (
        4.0 * jnp.max(d2, initial=0.0) >= s * s
    )
    if axis_name is not None:
        trig = jax.lax.pmax(trig.astype(jnp.int32), axis_name) > 0
    return trig


def refresh(
    cache: VerletCache,
    pos: jnp.ndarray,
    active: jnp.ndarray,
    cell_size: float,
    width: int,
    bucket: int,
    skin: float,
    *,
    cell: Optional[jnp.ndarray] = None,
    n_cells: Optional[int] = None,
    height: int = -1,
    axis_name: Optional[str] = None,
) -> Tuple[VerletCache, jnp.ndarray]:
    """The lax.cond-gated build step: returns (valid cache, rebuilt i32).

    Rebuild branch = the full _sorted_segments argsort + slot assignment
    (everything build_cell_table derives before the payload scatter),
    re-anchored at today's positions.  Reuse branch = the cached arrays
    untouched, age bumped.  Either way the returned cache is valid for
    full_table()/sub_table() THIS tick, which replay only the sort-free
    payload scatters against fresh features.

    cell/n_cells/height: precomputed (rectangular) cell ids, same contract
    as build_cell_table_pair — the spatial slab shards pass local ids.
    Note `cell` must be derived from the SAME positions passed here; the
    rebuild branch anchors both together.
    """
    if n_cells is None:
        if cell is not None:
            raise ValueError("precomputed cell ids need n_cells")
        n_cells = width * width
    trig = need_rebuild(cache, pos, active, skin, axis_name=axis_name)
    n = pos.shape[0]
    mode = binning_mode()  # trace-time, like the NF_RADIX read below it

    def rebuild(_):
        if mode == "count":
            # sort-free anchor: bounded scatter-min slots; `skey` caches
            # the PER-ROW anchor keys (what sub_table re-ranks against),
            # `order` degenerates to identity (see VerletCache docstring)
            _nc, key = _cell_keys(
                pos, active, cell_size, width, cell=cell, n_cells=n_cells
            )
            order = jnp.arange(n, dtype=jnp.int32)
            skey = key
            slot_of = _counting_slots(key, n_cells, bucket)
        else:
            _nc, order, skey, _seg_start, rank = _sorted_segments(
                pos, active, cell_size, width, cell=cell, n_cells=n_cells
            )
            slot_of = _slots_from_ranks(n, n_cells, order, skey, rank, bucket)
        return VerletCache(
            anchor_pos=pos[:, :2].astype(jnp.float32),
            anchor_active=active,
            order=order.astype(jnp.int32),
            skey=skey.astype(jnp.int32),
            slot_of=slot_of,
            rebuilds=cache.rebuilds + 1,
            reuses=cache.reuses,
            age=jnp.int32(0),
        )

    def reuse(_):
        return cache._replace(reuses=cache.reuses + 1, age=cache.age + 1)

    new_cache = jax.lax.cond(trig, rebuild, reuse, None)
    return new_cache, trig.astype(jnp.int32)


def full_table(
    cache: VerletCache,
    features: jnp.ndarray,
    active: jnp.ndarray,
    n_cells: int,
    cell_size: float,
    width: int,
    bucket: int,
    height: int = -1,
) -> CellTable:
    """The full-population table through the cached slot assignment: one
    payload scatter, no sort.  Bit-identical to build_cell_table when the
    cache is fresh (refresh() guarantees it is)."""
    return table_from_slots(
        features, active, cache.slot_of, n_cells, cell_size, width, bucket,
        height,
    )


def sub_slots(
    cache: VerletCache,
    sub_mask: jnp.ndarray,
    n_cells: int,
    sub_bucket: int,
) -> jnp.ndarray:
    """The raw subset slot assignment through the cached order — the
    sort-free core of sub_table, shared with the fused Pallas engine
    (which gathers from the SoA banks instead of scattering a payload).
    Returns [N] i32 flat slots (dump == n_cells*sub_bucket for
    non-members); callers wanting drop counts wrap it in
    stencil.slots_from_assignment."""
    if binning_mode() == "count":
        sub_key = jnp.where(sub_mask, cache.skey, n_cells)
        return _counting_slots(sub_key, n_cells, sub_bucket)
    order, skey = cache.order, cache.skey
    n = order.shape[0]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), skey[1:] != skey[:-1]]
    )
    sub_sorted = sub_mask[order]
    ex = jnp.cumsum(sub_sorted.astype(jnp.int32)) - sub_sorted.astype(jnp.int32)
    head_ex = jax.lax.cummax(jnp.where(seg_start, ex, -1))
    sub_rank = jnp.where(sub_sorted, ex - head_ex, n_cells * sub_bucket + 1)
    return _slots_from_ranks(n, n_cells, order, skey, sub_rank, sub_bucket)


def sub_table(
    cache: VerletCache,
    sub_mask: jnp.ndarray,
    sub_features: jnp.ndarray,
    n_cells: int,
    cell_size: float,
    width: int,
    sub_bucket: int,
    height: int = -1,
) -> CellTable:
    """A subset table (this tick's attackers / moved entities) through the
    cached order: the subset CHANGES every tick, so its per-cell ranks are
    recomputed — but via the same segmented exclusive cumsum
    build_cell_table_pair uses, a streaming pass over the cached sorted
    order instead of a second argsort.  Under NF_BINNING=count the cached
    `skey` holds per-row anchor keys instead, and the subset re-runs the
    bounded scatter-min selection over them.  Bit-identical to the pair
    builder's sub table for any sub_mask subset of the anchor active set."""
    return table_from_slots(
        sub_features, sub_mask, sub_slots(cache, sub_mask, n_cells, sub_bucket),
        n_cells, cell_size, width, sub_bucket, height,
    )
