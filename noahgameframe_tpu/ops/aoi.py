"""Spatial AOI ops: uniform-grid neighbor queries with static shapes.

The reference's "AOI" is group-granular broadcast; its spatial layer
(2D-grid neighbor scan, BASELINE config 3) is rebuilt here TPU-first: a
bucketed uniform grid with *static* shapes — `[n_cells, K]` entity slots —
built by one sort + rank + scatter, queried by dense 3x3-stencil gathers.
No dynamic shapes, no host loops: everything jits, vmaps and shard_maps.

Design notes for TPU:
- argsort + searchsorted-rank is XLA-native and O(N log N); the grid build
  is one scatter with `mode="drop"` for bucket overflow (overflowing
  entities simply miss the grid this tick — bounded error, never OOB).
- queries gather fixed 9*K candidates per entity and mask by distance and
  partition key, so the whole pipeline fuses into a handful of kernels.
- K (bucket capacity) trades recall vs FLOPs; pick K ≥ expected max
  entities/cell.  `grid_overflow` reports dropped counts for monitoring.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

# 3x3 neighborhood stencil (dy, dx)
_STENCIL = [(-1, -1), (-1, 0), (-1, 1), (0, -1), (0, 0), (0, 1), (1, -1), (1, 0), (1, 1)]


class Grid(NamedTuple):
    """Bucketed uniform grid: slots[c, k] = entity row or -1."""

    slots: jnp.ndarray  # int32 [n_cells + 1, K]; last cell = overflow/dead
    counts: jnp.ndarray  # int32 [n_cells + 1] true occupancy (may exceed K)
    width: int  # cells per row (static)
    cell_size: float  # world units per cell (static)


def cell_of(pos: jnp.ndarray, cell_size: float, width: int) -> jnp.ndarray:
    """[N, 2+] positions -> [N] row-major cell ids, clipped to the grid."""
    cx = jnp.clip(jnp.floor(pos[:, 0] / cell_size).astype(jnp.int32), 0, width - 1)
    cy = jnp.clip(jnp.floor(pos[:, 1] / cell_size).astype(jnp.int32), 0, width - 1)
    return cy * width + cx


def build_grid(
    pos: jnp.ndarray,
    active: jnp.ndarray,
    cell_size: float,
    width: int,
    bucket: int,
) -> Grid:
    """Build the grid over `active` entities.  [N,2+] pos, [N] bool."""
    n = pos.shape[0]
    n_cells = width * width
    cell = cell_of(pos, cell_size, width)
    key = jnp.where(active, cell, n_cells)  # inactive -> overflow cell
    order = jnp.argsort(key)
    sorted_key = key[order]
    # rank of each sorted element within its cell run
    start = jnp.searchsorted(sorted_key, sorted_key, side="left")
    rank = jnp.arange(n, dtype=jnp.int32) - start.astype(jnp.int32)
    flat_slot = sorted_key * bucket + jnp.minimum(rank, bucket - 1)
    # overflow (rank >= bucket) and dead entities scatter out of bounds -> dropped
    oob = (n_cells + 1) * bucket
    flat_slot = jnp.where((rank < bucket) & (sorted_key < n_cells), flat_slot, oob)
    slots = (
        jnp.full(((n_cells + 1) * bucket,), -1, jnp.int32)
        .at[flat_slot]
        .set(order.astype(jnp.int32), mode="drop")
        .reshape(n_cells + 1, bucket)
    )
    counts = jnp.zeros((n_cells + 1,), jnp.int32).at[key].add(1, mode="drop")
    return Grid(slots=slots, counts=counts, width=width, cell_size=cell_size)


def grid_overflow(grid: Grid) -> jnp.ndarray:
    """Total entities dropped by bucket overflow this build (monitoring)."""
    bucket = grid.slots.shape[1]
    return jnp.sum(jnp.maximum(grid.counts[:-1] - bucket, 0))


def neighbor_candidates(query_cell: jnp.ndarray, grid: Grid) -> jnp.ndarray:
    """[Q] query cell ids -> [Q, 9*K] candidate entity rows (-1 padded),
    gathered from the 3x3 stencil around each query cell."""
    w = grid.width
    n_cells = w * w
    cx = query_cell % w
    cy = query_cell // w
    cand = []
    for dy, dx in _STENCIL:
        nx, ny = cx + dx, cy + dy
        valid = (nx >= 0) & (nx < w) & (ny >= 0) & (ny < w)
        ncell = jnp.where(valid, ny * w + nx, n_cells)  # overflow cell is all -1
        cand.append(grid.slots[ncell])  # [Q, K]
    return jnp.concatenate(cand, axis=-1)


def neighbor_mask(
    pos: jnp.ndarray,
    query_pos: jnp.ndarray,
    cand: jnp.ndarray,
    radius: float,
    partition: Optional[jnp.ndarray] = None,
    query_partition: Optional[jnp.ndarray] = None,
    exclude_self: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """[Q, 9K] bool: candidate within `radius` of the query point, same
    partition (scene*groups+group cell key), not self."""
    safe = jnp.maximum(cand, 0)
    d = query_pos[:, None, :2] - pos[safe][:, :, :2]
    within = jnp.sum(d * d, axis=-1) <= radius * radius
    m = within & (cand >= 0)
    if partition is not None and query_partition is not None:
        m &= partition[safe] == query_partition[:, None]
    if exclude_self is not None:
        m &= cand != exclude_self[:, None]
    return m


def neighbor_counts(
    pos: jnp.ndarray,
    active: jnp.ndarray,
    radius: float,
    cell_size: float,
    width: int,
    bucket: int,
    partition: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """[N] number of active neighbors within radius of each entity — the
    500k-entity AOI scan of BASELINE config 3.

    Implemented on the gather-free cell-table engine (ops/stencil.py):
    one sort + one scatter + nine dense shifted-window reductions, instead
    of per-candidate irregular gathers.  Inactive entities count 0, as do
    active entities beyond a cell's `bucket` slots (they drop out of the
    query entirely — size the bucket for peak density, cf. auto_bucket)."""
    from .stencil import build_cell_table, pull, stencil_fold

    n = pos.shape[0]
    f32 = jnp.float32
    part = (
        partition.astype(jnp.int32)
        if partition is not None
        else jnp.zeros((n,), jnp.int32)
    )
    # split the partition key into two f32-exact halves (each < 2^24) so
    # any int32 key compares exactly (int64 keys would silently truncate
    # under JAX's default x64-disabled config — keep the domain honest)
    part_hi = (part >> 12).astype(f32)
    part_lo = (part & 0xFFF).astype(f32)
    feats = jnp.stack(
        [pos[:, 0], pos[:, 1], part_hi, part_lo, jnp.arange(n, dtype=f32)],
        axis=-1,
    )
    table = build_cell_table(pos, active, feats, cell_size, width, bucket)
    v = table.grid_view()
    vx, vy, vph, vpl, vr = (
        v[..., 0], v[..., 1], v[..., 2], v[..., 3], v[..., 4]
    )
    r2 = radius * radius

    def fold(cnt, cand):
        cx = cand[:, :, None, :, 0]
        cy = cand[:, :, None, :, 1]
        cph = cand[:, :, None, :, 2]
        cpl = cand[:, :, None, :, 3]
        cr = cand[:, :, None, :, 4]
        occ = cand[:, :, None, :, 5]
        dx = vx[..., None] - cx
        dy = vy[..., None] - cy
        ok = (
            (dx * dx + dy * dy <= r2)
            & (occ > 0)
            & (cph == vph[..., None])
            & (cpl == vpl[..., None])
            & (cr != vr[..., None])
        )
        return cnt + jnp.sum(ok, axis=-1, dtype=jnp.int32)

    counts = stencil_fold(table, fold, jnp.zeros(v.shape[:3], jnp.int32))
    return pull(table, counts, fill=0)


def gather_reduce(
    values: jnp.ndarray, cand: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Sum `values[cand]` over masked candidates: the scatter-free damage
    accumulation primitive (victims PULL from an attacker grid instead of
    attackers scattering — no collisions, fully parallel)."""
    safe = jnp.maximum(cand, 0)
    return jnp.sum(jnp.where(mask, values[safe], 0), axis=-1)
