"""Per-observer interest queries + quantized delta filtering, on device.

The group-granular broadcast of the reference (NFCSceneAOIModule: every
player in the (scene, group) sees every change there,
NFCSceneAOIModule.cpp:531-593) collapses at TPU-scale worlds — one busy
group means full-world fan-out per client (round-3: 24.5 MB/frame of
position sync at 100k entities / 500 sessions).  This module computes
*per-session* visible sets the TPU-first way:

1. `quantize_delta` — u16-quantize positions over the scene extent and
   mask entities whose quantized cell didn't change since last sync
   (sub-quantum jitter never hits the wire).  One fused elementwise op.
2. `visible_candidates` — bin the moved entities into the stencil
   engine's cell table (ops/stencil.build_cell_table, one argsort) and,
   for every observer position, read the 3x3 neighborhood's K slots and
   distance-mask them: [S, 9K] candidate rows in ONE dispatch, no host
   loops.

Both are static-shaped and jit-compiled by the caller (the game role
caches per-shape jits).  The host then slices each session's visible
rows and packs one compact message per session (net/roles/game.py).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .stencil import STENCIL, build_cell_table

QMAX = 65535  # u16 quantization range


class InterestResult(NamedTuple):
    rows: jnp.ndarray  # [S, 9K] int32 entity row ids (garbage where ~ok)
    ok: jnp.ndarray  # [S, 9K] bool — occupied slot AND within radius


def quantize_delta(
    pos: jnp.ndarray,  # [C, >=2] float32 world positions
    alive: jnp.ndarray,  # [C] bool
    last_q: jnp.ndarray,  # [C, 3] int32 last-synced quantized position
    extent: float,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(q [C,3] i32, moved [C] bool, new_last [C,3] i32).

    `moved` = alive AND quantized position differs from the last synced
    one; new_last advances ONLY for moved rows, so an entity drifting
    less than one quantum accumulates drift until it crosses it (no
    stuck-forever error)."""
    scale = QMAX / extent
    p3 = pos[:, :3] if pos.shape[1] >= 3 else jnp.pad(
        pos, ((0, 0), (0, 3 - pos.shape[1]))
    )
    q = jnp.clip(jnp.round(p3 * scale), 0, QMAX).astype(jnp.int32)
    moved = jnp.any(q != last_q, axis=-1) & alive
    new_last = jnp.where(moved[:, None], q, last_q)
    return q, moved, new_last


def visible_candidates(
    pos: jnp.ndarray,  # [C, >=2] float32 entity positions
    moved: jnp.ndarray,  # [C] bool — which entities changed this frame
    scene: jnp.ndarray,  # [C] float32 scene id
    group: jnp.ndarray,  # [C] float32 group id (0 = scene-wide)
    obs_pos: jnp.ndarray,  # [S, >=2] float32 observer positions
    obs_scene: jnp.ndarray,  # [S] float32
    obs_group: jnp.ndarray,  # [S] float32
    radius: float,
    cell_size: float,
    width: int,
    bucket: int,
) -> InterestResult:
    """For each observer, the moved entities within `radius` AND visible
    under the reference's broadcast scoping (NFCSceneAOIModule): same
    scene, and either the same group or the entity carries GroupID 0
    (scene-wide).  Scenes share one coordinate space, so proximity alone
    would leak entities across scene/clone-group boundaries.

    cell_size must be >= radius so the 3x3 stencil covers the disc.
    Entities beyond a cell's `bucket` slots are dropped for the frame
    (they re-qualify next time they move; size via ops.stencil.auto_bucket
    to keep that ~zero)."""
    n = pos.shape[0]
    feats = jnp.concatenate(
        [
            jnp.arange(n, dtype=jnp.float32)[:, None],  # row id
            pos[:, :2].astype(jnp.float32),
            scene.astype(jnp.float32)[:, None],
            group.astype(jnp.float32)[:, None],
        ],
        axis=1,
    )
    table = build_cell_table(pos, moved, feats, cell_size, width, bucket)
    grid = table.grid_view()  # [H, W, K, F+1]
    h, w, k, f = grid.shape
    inv = 1.0 / cell_size
    ox = jnp.floor(obs_pos[:, 0] * inv).astype(jnp.int32)
    oy = jnp.floor(obs_pos[:, 1] * inv).astype(jnp.int32)
    cand_list = []
    ok_list = []
    for dy, dx in STENCIL:
        yy, xx = oy + dy, ox + dx
        in_grid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        cells = grid[jnp.clip(yy, 0, h - 1), jnp.clip(xx, 0, w - 1)]
        # cells: [S, K, F+1]; occupancy rides the last column
        occ = (cells[..., -1] > 0) & in_grid[:, None]
        dxv = cells[..., 1] - obs_pos[:, None, 0]
        dyv = cells[..., 2] - obs_pos[:, None, 1]
        within = (dxv * dxv + dyv * dyv) <= radius * radius
        same_scene = cells[..., 3] == obs_scene[:, None]
        grp_ok = (cells[..., 4] == 0) | (cells[..., 4] == obs_group[:, None])
        cand_list.append(cells[..., 0].astype(jnp.int32))
        ok_list.append(occ & within & same_scene & grp_ok)
    return InterestResult(
        rows=jnp.concatenate(cand_list, axis=1),
        ok=jnp.concatenate(ok_list, axis=1),
    )
