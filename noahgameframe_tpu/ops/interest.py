"""Per-observer interest queries + quantized delta filtering, on device.

The group-granular broadcast of the reference (NFCSceneAOIModule: every
player in the (scene, group) sees every change there,
NFCSceneAOIModule.cpp:531-593) collapses at TPU-scale worlds — one busy
group means full-world fan-out per client (round-3: 24.5 MB/frame of
position sync at 100k entities / 500 sessions).  This module computes
*per-session* visible sets the TPU-first way:

1. `quantize` — u16-quantize positions over the scene extent and mask
   out-of-extent rows.  One fused elementwise op.  Per-session change
   suppression (send only what THIS observer hasn't seen at this
   quantum) happens on the host against each session's seen-state
   (net/roles/game.py `_send_interest_pos`) — a global delta gate can't
   express enter-view resends.
2. `visible_candidates` — bin the alive entities into the stencil
   engine's cell table (ops/stencil.build_cell_table, one argsort) and,
   for every observer position, read the 3x3 neighborhood's K slots and
   distance-mask them: [S, 9K] candidate rows in ONE dispatch, no host
   loops.

Both are static-shaped and jit-compiled by the caller (the game role
caches per-shape jits).  The host then slices each session's visible
rows and packs one compact message per session (net/roles/game.py).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .stencil import STENCIL, CellTable, build_cell_table
from .verlet import VerletCache, refresh, sub_table

QMAX = 65535  # u16 quantization range


class InterestResult(NamedTuple):
    rows: jnp.ndarray  # [S, 9K] int32 entity row ids (garbage where ~ok)
    ok: jnp.ndarray  # [S, 9K] bool — occupied slot AND within radius


def quantize(
    pos: jnp.ndarray,  # [C, >=2] float32 world positions
    alive: jnp.ndarray,  # [C] bool
    extent: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(q [C,3] i32, in_extent [C] bool).

    World-coordinate contract: the stream covers [0, extent] per axis.
    Rows outside it are NOT clamped onto the boundary (a client would
    render them pinned at the edge) — they are excluded via the returned
    mask and simply don't ride the wire until they re-enter the extent.
    """
    p3 = pos[:, :3] if pos.shape[1] >= 3 else jnp.pad(
        pos, ((0, 0), (0, 3 - pos.shape[1]))
    )
    # X/Y only: visibility distance is 2D, and Z is client-supplied
    # (jump/flight jitter) — gating on it would let an entity go
    # invisible by sending z=-0.5 while staying fully active
    in_extent = (
        jnp.all((p3[:, :2] >= 0.0) & (p3[:, :2] <= extent), axis=-1) & alive
    )
    q = jnp.clip(jnp.round(p3 * (QMAX / extent)), 0, QMAX).astype(jnp.int32)
    return q, in_extent


def visible_candidates(
    pos: jnp.ndarray,  # [C, >=2] float32 entity positions
    moved: jnp.ndarray,  # [C] bool — which entities changed this frame
    scene: jnp.ndarray,  # [C] float32 scene id
    group: jnp.ndarray,  # [C] float32 group id (0 = scene-wide)
    obs_pos: jnp.ndarray,  # [S, >=2] float32 observer positions
    obs_scene: jnp.ndarray,  # [S] float32
    obs_group: jnp.ndarray,  # [S] float32
    radius: float,
    cell_size: float,
    width: int,
    bucket: int,
) -> InterestResult:
    """For each observer, the moved entities within `radius` AND visible
    under the reference's broadcast scoping (NFCSceneAOIModule): same
    scene, and either the same group or the entity carries GroupID 0
    (scene-wide).  Scenes share one coordinate space, so proximity alone
    would leak entities across scene/clone-group boundaries.

    cell_size must be >= radius so the 3x3 stencil covers the disc.
    Entities beyond a cell's `bucket` slots are dropped for the frame
    (they re-qualify next time they move; size via ops.stencil.auto_bucket
    to keep that ~zero)."""
    feats = _interest_feats(pos, scene, group)
    table = build_cell_table(pos, moved, feats, cell_size, width, bucket)
    return _scan_observers(
        table, obs_pos, obs_scene, obs_group, radius, cell_size
    )


def scope_mask(cand_scene, cand_group, obs_scene, obs_group) -> jnp.ndarray:
    """The reference's broadcast visibility scope (NFCSceneAOIModule):
    same scene, and either the same group or the candidate carries
    GroupID 0 (scene-wide wildcard).  All args are broadcastable f32
    planes.  Shared by the per-observer scan below AND the fused Pallas
    neighborhood kernel's AOI occupancy fold (ops/stencil_pallas.py) so
    scope semantics cannot drift between the serving and combat paths."""
    return (cand_scene == obs_scene) & (
        (cand_group == 0) | (cand_group == obs_group)
    )


def _interest_feats(pos, scene, group) -> jnp.ndarray:
    """The candidate feature layout both builders share: row id, x, y,
    scene, group (occupancy appended by the table builder)."""
    n = pos.shape[0]
    return jnp.concatenate(
        [
            jnp.arange(n, dtype=jnp.float32)[:, None],  # row id
            pos[:, :2].astype(jnp.float32),
            scene.astype(jnp.float32)[:, None],
            group.astype(jnp.float32)[:, None],
        ],
        axis=1,
    )


def _scan_observers(
    table: CellTable,
    obs_pos: jnp.ndarray,
    obs_scene: jnp.ndarray,
    obs_group: jnp.ndarray,
    radius: float,
    cell_size: float,
) -> InterestResult:
    """The per-observer 3x3 read shared by the fresh and Verlet-cached
    builders: observers index by their CURRENT cell, candidates mask by
    TRUE radius on the current positions carried in the payload — which
    is what keeps cached (anchor-binned) tables bit-identical, provided
    cell_size >= radius + skin/2 covers the staleness."""
    grid = table.grid_view()  # [H, W, K, F+1]
    h, w, k, f = grid.shape
    inv = 1.0 / cell_size
    ox = jnp.floor(obs_pos[:, 0] * inv).astype(jnp.int32)
    oy = jnp.floor(obs_pos[:, 1] * inv).astype(jnp.int32)
    cand_list = []
    ok_list = []
    for dy, dx in STENCIL:
        yy, xx = oy + dy, ox + dx
        in_grid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        cells = grid[jnp.clip(yy, 0, h - 1), jnp.clip(xx, 0, w - 1)]
        # cells: [S, K, F+1]; occupancy rides the last column
        occ = (cells[..., -1] > 0) & in_grid[:, None]
        dxv = cells[..., 1] - obs_pos[:, None, 0]
        dyv = cells[..., 2] - obs_pos[:, None, 1]
        within = (dxv * dxv + dyv * dyv) <= radius * radius
        scoped = scope_mask(
            cells[..., 3], cells[..., 4],
            obs_scene[:, None], obs_group[:, None],
        )
        cand_list.append(cells[..., 0].astype(jnp.int32))
        ok_list.append(occ & within & scoped)
    return InterestResult(
        rows=jnp.concatenate(cand_list, axis=1),
        ok=jnp.concatenate(ok_list, axis=1),
    )


def visible_candidates_cached(
    cache: VerletCache,
    pos: jnp.ndarray,
    moved: jnp.ndarray,  # [C] bool — this frame's candidate subset
    alive: jnp.ndarray,  # [C] bool — the cache anchors over ALL alive rows
    scene: jnp.ndarray,
    group: jnp.ndarray,
    obs_pos: jnp.ndarray,
    obs_scene: jnp.ndarray,
    obs_group: jnp.ndarray,
    radius: float,
    cell_size: float,
    width: int,
    bucket: int,
    skin: float,
) -> Tuple[InterestResult, VerletCache, jnp.ndarray]:
    """`visible_candidates` with a Verlet-cached binning (ops/verlet.py):
    the cache anchors the FULL alive population, and each frame's `moved`
    subset rides a sub-table through the cached sorted order (a streaming
    cumsum instead of an argsort — the moved set changes every frame, so
    its table always refreshes; only the sort is amortized).

    cell_size must be >= radius + skin (caller inflates its geometry);
    the distance mask uses the true radius on current positions, so
    results are bit-identical to the fresh builder on the same inflated
    grid (modulo bucket-overflow drops — size generously).

    Returns (result, new_cache, rebuilt i32) — thread the cache back in
    next frame."""
    # anchor over the STABLE alive set — anchoring on `moved` would flip
    # the active mask (and force a rebuild) every frame.  moved & alive
    # is then a subset of the anchor by construction, which is all
    # sub_table needs.
    cache, rebuilt = refresh(
        cache, pos, alive, cell_size, width, bucket, skin
    )
    feats = _interest_feats(pos, scene, group)
    table = sub_table(
        cache, moved & alive, feats, width * width, cell_size, width, bucket
    )
    result = _scan_observers(
        table, obs_pos, obs_scene, obs_group, radius, cell_size
    )
    return result, cache, rebuilt
