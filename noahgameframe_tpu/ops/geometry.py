"""Batched geometry helpers (vectors, rays, spheres, planes, AABBs).

Reference parity: NFComm/NFCore ships NFVector2/3, NFMath and the
NFLine/NFPlane/NFRay/NFSphere/NFBox headers (SURVEY §2.1 — unused by any
reference module, but part of the core surface).  Rebuilt TPU-first:
every helper is a pure jnp function over [..., 2|3] coordinate arrays,
so one call tests N rays against N spheres on device — usable inside
jit'd module phases (line-of-sight gates, projectile sweeps) instead of
one-object-at-a-time host math.

Conventions: rays are (origin, direction) with unnormalized directions
allowed; "t" parameters are in units of the direction vector; misses
return t = inf so downstream `jnp.where(hit, ...)` stays branch-free.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12
INF = jnp.inf


# ------------------------------------------------------------------ vectors
def dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(a * b, axis=-1)


def length(v: jnp.ndarray) -> jnp.ndarray:
    return jnp.sqrt(jnp.maximum(dot(v, v), 0.0))


def normalize(v: jnp.ndarray) -> jnp.ndarray:
    """Zero vectors normalize to zero (no NaNs under jit)."""
    n = length(v)
    return jnp.where(n[..., None] > _EPS, v / jnp.maximum(n, _EPS)[..., None], 0.0)


def distance(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return length(a - b)


def lerp(a: jnp.ndarray, b: jnp.ndarray, t) -> jnp.ndarray:
    t = jnp.asarray(t)
    return a + (b - a) * t[..., None]


def cross(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.cross(a, b)


# -------------------------------------------------------------------- rays
def ray_point(origin: jnp.ndarray, direction: jnp.ndarray, t) -> jnp.ndarray:
    return origin + direction * jnp.asarray(t)[..., None]


def ray_sphere(
    origin: jnp.ndarray,
    direction: jnp.ndarray,
    center: jnp.ndarray,
    radius,
) -> jnp.ndarray:
    """First intersection t >= 0 of ray(s) with sphere(s); inf on miss.
    Rays starting inside hit at the exit point."""
    radius = jnp.asarray(radius)
    oc = origin - center
    a = dot(direction, direction)
    b = 2.0 * dot(oc, direction)
    c = dot(oc, oc) - radius * radius
    disc = b * b - 4.0 * a * c
    sq = jnp.sqrt(jnp.maximum(disc, 0.0))
    a2 = jnp.maximum(2.0 * a, _EPS)
    t0 = (-b - sq) / a2
    t1 = (-b + sq) / a2
    t = jnp.where(t0 >= 0.0, t0, t1)
    # a degenerate (zero-direction) ray hits only if it STARTS inside
    ok = jnp.where(a > _EPS, (disc >= 0.0) & (t >= 0.0), c <= 0.0)
    return jnp.where(ok, jnp.where(a > _EPS, t, 0.0), INF)


def ray_plane(
    origin: jnp.ndarray,
    direction: jnp.ndarray,
    normal: jnp.ndarray,
    plane_d,
) -> jnp.ndarray:
    """t of ray against plane dot(n, x) + d = 0; inf when parallel or
    behind the origin."""
    plane_d = jnp.asarray(plane_d)
    denom = dot(direction, normal)
    t = -(dot(origin, normal) + plane_d) / jnp.where(
        jnp.abs(denom) > _EPS, denom, _EPS
    )
    return jnp.where((jnp.abs(denom) > _EPS) & (t >= 0.0), t, INF)


def ray_aabb(
    origin: jnp.ndarray,
    direction: jnp.ndarray,
    box_min: jnp.ndarray,
    box_max: jnp.ndarray,
) -> jnp.ndarray:
    """Slab test: entry t (0 when starting inside); inf on miss."""
    inv = 1.0 / jnp.where(jnp.abs(direction) > _EPS, direction, _EPS)
    t1 = (box_min - origin) * inv
    t2 = (box_max - origin) * inv
    t_near = jnp.max(jnp.minimum(t1, t2), axis=-1)
    t_far = jnp.min(jnp.maximum(t1, t2), axis=-1)
    hit = (t_far >= jnp.maximum(t_near, 0.0))
    return jnp.where(hit, jnp.maximum(t_near, 0.0), INF)


# ----------------------------------------------------------------- queries
def point_in_aabb(p: jnp.ndarray, box_min: jnp.ndarray, box_max: jnp.ndarray) -> jnp.ndarray:
    return jnp.all((p >= box_min) & (p <= box_max), axis=-1)


def sphere_overlap(ca: jnp.ndarray, ra, cb: jnp.ndarray, rb) -> jnp.ndarray:
    ra, rb = jnp.asarray(ra), jnp.asarray(rb)
    d2 = dot(ca - cb, ca - cb)
    r = ra + rb
    return d2 <= r * r


def segment_point_distance(a: jnp.ndarray, b: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Distance from point(s) p to segment(s) ab."""
    ab = b - a
    t = dot(p - a, ab) / jnp.maximum(dot(ab, ab), _EPS)
    t = jnp.clip(t, 0.0, 1.0)
    return length(p - (a + ab * t[..., None]))
