"""Device mesh construction.

The reference scales by multi-process sharding: players consistent-hash
onto game servers, worlds partition into (scene, group) cells, and
cross-shard traffic relays through the World server (SURVEY §5
"long-context").  The TPU equivalent is a jax.sharding.Mesh: the entity
axis of every class bank shards across devices ("shard" axis), and
cross-shard effects ride XLA collectives over ICI instead of TCP relays.

Multi-host: build the mesh over all addressable+remote devices via
jax.distributed (the driver's dryrun uses a virtual CPU mesh; real pods
use the same code path).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

SHARD_AXIS = "shard"
# the many-worlds room engine (parallel/rooms.py) batches INDEPENDENT
# rooms on a leading [R] axis and shards that axis instead of the
# entity axis — one mesh, two orthogonal scale shapes
ROOMS_AXIS = "rooms"


def make_mesh(n_devices: Optional[int] = None, axis: str = SHARD_AXIS) -> Mesh:
    """1-D mesh over the first n devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)} "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count=N for CPU)"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def row_sharding(mesh: Mesh, axis: str = SHARD_AXIS) -> NamedSharding:
    """Shard the leading (entity/capacity) axis; replicate the rest."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
