"""Elastic mesh: grow/drain the device set under a LIVE serving world.

The reference framework survives hardware churn by supervision — roles
crash, get evicted from routed lists, and sessions re-home.  Our engine's
equivalent churn is the *mesh*: diurnal load and device maintenance mean
the device set must change under a running, serving world (ROADMAP
item 4).  This module is that runtime, built from the PR 15 toolkit:

- **grow**: re-place immediately onto the wider mesh (block partition of
  the leading capacity axis is content-preserving — a row's shard is a
  pure function of its global index, so nothing is lost by re-slicing),
  then retarget :class:`~.rowmigrate.RowMigrationModule` so the normal
  budgeted migrate phase *rebalances* rows toward their new spatial
  owners over the following ticks.  Done when ``settle_polls``
  consecutive ticks report zero overflow — migrated stays nonzero in a
  moving world (steady-state churn); overflow is the re-place backlog.
- **drain**: evict ONE device via a budgeted row exodus — the migrate
  phase's owner function is remapped (``set_exodus``) so rows standing
  on the draining shard route to a survivor within ``mig_budget`` while
  every other row holds position (normal spatial rebalance pauses: any
  through-traffic hopping across the draining bank would keep it
  occupied forever under motion churn).  When the draining device's row
  range is empty (or ``exodus_tick_bound`` ticks elapse — re-placement
  is content-preserving either way, the bound only caps how long we
  wait for the polite pre-copy), the mesh shrinks around it and
  ``clear_exodus`` resumes normal routing.

Every reshard rides :meth:`ShardedKernel.reshard`: a CostBook
generation bump announced BEFORE traces drop (so ``unexplained_since()
== []`` still gates recompiles), Verlet/binning aux caches dropped
exactly like row arrival, and a fresh ``world_shardings`` re-place.

The serve edge stays coherent through :meth:`poll`'s return value: the
set of row indices whose (identity, liveness) actually changed across
the op — GameRole force-``reset_view``\\ s exactly the sessions whose
seen-state intersects those rows, nobody else.

:class:`Autoscaler` closes the loop from signals the stack already
exports (StageClock stage walls, ``nf_hbm_*``, persist lag, failover
lag) with consecutive-breach hysteresis and a post-op cooldown, so
grow/drain can be policy-driven, not just drill-driven.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np
from jax.sharding import Mesh

from .mesh import SHARD_AXIS
from .rowmigrate import canonical_digest


class ElasticMesh:
    """Grow/drain driver over one :class:`~.shard.ShardedKernel`.

    ``migration`` (a bound :class:`~.rowmigrate.RowMigrationModule`) is
    optional: without it grow/drain are pure re-places (still
    generation-announced, still zero dropped rows); with it, drains
    pre-copy via the exodus protocol and grows rebalance spatially.

    ``ident_cols`` (``{class_name: i32 column}``, the
    :func:`~.rowmigrate.canonical_digest` contract) powers precise
    moved-row detection and :meth:`digest`; without it, a completed op
    conservatively reports EVERY row of the migrating class as moved.
    """

    def __init__(self, sharded, migration=None, registry=None,
                 ident_cols: Optional[Dict[str, int]] = None,
                 exodus_tick_bound: int = 256, settle_polls: int = 2,
                 autoscaler: Optional["Autoscaler"] = None):
        self.sharded = sharded
        self.migration = migration
        self.ident_cols = dict(ident_cols) if ident_cols else None
        self.exodus_tick_bound = int(exodus_tick_bound)
        self.settle_polls = max(1, int(settle_polls))
        self.autoscaler = autoscaler
        self._op: Optional[Dict[str, object]] = None
        self.ops_done: List[Dict[str, object]] = []
        self.dropped_rows = 0
        self.rows_moved_total = 0
        self.last_exodus_ticks = 0
        self._pop_baseline = self._pop()
        self._pop_last = self._pop_baseline
        self._c_total = self._c_moved = self._c_dropped = None
        self._g_devices = self._g_inflight = self._h_exodus = None
        if registry is not None:
            self._c_total = registry.counter(
                "nf_reshard_total", "mesh reshards completed", ("kind",))
            self._c_moved = registry.counter(
                "nf_reshard_rows_moved_total",
                "rows whose content changed index across a reshard")
            self._c_dropped = registry.counter(
                "nf_reshard_dropped_rows_total",
                "rows lost across a reshard (must stay 0)")
            self._g_devices = registry.gauge(
                "nf_reshard_devices", "devices in the serving mesh")
            self._g_inflight = registry.gauge(
                "nf_reshard_inflight", "1 while a grow/drain is in flight")
            self._h_exodus = registry.histogram(
                "nf_reshard_exodus_ticks",
                "ticks from drain arm to empty device row-range")
            self._g_devices.set(float(self.n_devices))
            self._g_inflight.set(0.0)

    # ----------------------------------------------------------- introspect
    @property
    def kernel(self):
        return self.sharded.kernel

    @property
    def n_devices(self) -> int:
        return int(self.sharded.mesh.devices.size)

    @property
    def inflight(self) -> Optional[str]:
        return None if self._op is None else str(self._op["kind"])

    def _mig_class(self) -> Optional[str]:
        if self.migration is None:
            return None
        return self.migration.placement.class_name

    def _pop(self) -> int:
        """Live rows of the migrating class (the population the exodus
        must conserve; serve-side Player churn is deliberately outside)."""
        cname = self._mig_class()
        if cname is None:
            if not self.ident_cols:
                return 0
            return sum(
                int(np.asarray(self.kernel.state.classes[c].alive).sum())
                for c in self.ident_cols
            )
        return int(np.asarray(
            self.kernel.state.classes[cname].alive).sum())

    def _snapshot(self) -> Optional[Dict[str, np.ndarray]]:
        """(ident, alive) per row of the migrating class — the moved-row
        baseline.  Identity-based so content churn (regen ticking HP)
        never reads as movement."""
        cname = self._mig_class()
        if cname is None or self.ident_cols is None \
                or cname not in self.ident_cols:
            return None
        cs = self.kernel.state.classes[cname]
        return {
            "ident": np.asarray(cs.i32)[:, self.ident_cols[cname]].copy(),
            "alive": np.asarray(cs.alive).copy(),
        }

    def _moved_since(self, snap) -> Dict[str, np.ndarray]:
        """Row indices whose (identity, liveness) changed since ``snap``
        — exactly the rows whose serve-side seen-state went stale."""
        cname = self._mig_class()
        if cname is None:
            return {}
        cs = self.kernel.state.classes[cname]
        alive = np.asarray(cs.alive)
        if snap is None:
            return {cname: np.arange(alive.shape[0], dtype=np.int64)}
        ident = np.asarray(cs.i32)[:, self.ident_cols[cname]]
        changed = (alive != snap["alive"]) | (
            (alive | snap["alive"]) & (ident != snap["ident"]))
        return {cname: np.flatnonzero(changed)}

    def digest(self) -> Optional[int]:
        """Placement-invariant world digest over the configured identity
        columns (the parity oracle the StableUnderReshard invariant pins
        against a control world)."""
        if not self.ident_cols:
            return None
        return canonical_digest(
            self.kernel.state, sorted(self.ident_cols), self.ident_cols)

    # ------------------------------------------------------------------ ops
    def begin_grow(self, n_devices: int) -> None:
        """Expand the mesh to ``n_devices`` at the next :meth:`poll`."""
        self._require_idle()
        n_new = int(n_devices)
        if n_new <= self.n_devices:
            raise ValueError(
                f"grow_mesh({n_new}) on a {self.n_devices}-device mesh")
        import jax

        cur = list(self.sharded.mesh.devices.ravel())
        extra = [d for d in jax.devices() if d not in cur]
        if len(cur) + len(extra) < n_new:
            raise RuntimeError(
                f"need {n_new} devices, have {len(cur) + len(extra)}")
        devs = cur + extra[: n_new - len(cur)]
        mesh = Mesh(np.asarray(devs), (SHARD_AXIS,))
        self._op = {
            "kind": "grow", "stage": "reshard", "mesh": mesh,
            "snap": self._snapshot(), "start_tick": self._tick_count(),
            "settled": 0, "last_seen_tick": -1,
        }
        self._pop_baseline = self._pop()
        if self._g_inflight is not None:
            self._g_inflight.set(1.0)

    def begin_drain(self, device_index: int) -> None:
        """Arm the exodus that evicts mesh position ``device_index``."""
        self._require_idle()
        n = self.n_devices
        d = int(device_index)
        if n <= 1:
            raise ValueError("cannot drain the last device")
        if not 0 <= d < n:
            raise ValueError(f"device_index {d} out of range for {n}")
        self._op = {
            "kind": "drain", "stage": "exodus", "device": d,
            "snap": self._snapshot(), "start_tick": self._tick_count(),
        }
        self._pop_baseline = self._pop()
        if self.migration is not None:
            # spatial owner o re-homes to the adjacent survivor when o
            # is the draining shard; every other owner keeps its rows
            remap = np.arange(n, dtype=np.int32)
            remap[d] = d - 1 if d > 0 else d + 1
            self.migration.set_exodus(remap)
        if self._g_inflight is not None:
            self._g_inflight.set(1.0)

    def _require_idle(self) -> None:
        if self._op is not None:
            raise RuntimeError(
                f"reshard already in flight: {self._op['kind']}")

    def _tick_count(self) -> int:
        return int(getattr(self.kernel, "tick_count", 0))

    # ----------------------------------------------------------------- poll
    def poll(self) -> Dict[str, np.ndarray]:
        """Advance the in-flight op one step; call once per served tick
        (GameRole does, under the ``reshard`` stage).  Returns the moved
        row indices per class when an op COMPLETES this poll — empty
        otherwise — so the caller can reset exactly the affected views."""
        self._sample_drops()
        op = self._op
        if op is None:
            return {}
        if op["kind"] == "drain":
            return self._poll_drain(op)
        return self._poll_grow(op)

    def _sample_drops(self) -> None:
        if self.migration is None:
            return
        stats = self.kernel.state.aux.get(self.migration.aux_key)
        if stats is None:
            return
        d = int(np.asarray(stats)[:, 2].sum())
        if d:
            self.dropped_rows += d
            if self._c_dropped is not None:
                self._c_dropped.inc(d)

    def _poll_drain(self, op) -> Dict[str, np.ndarray]:
        d = int(op["device"])
        ticks = self._tick_count() - int(op["start_tick"])
        cname = self._mig_class()
        drained = True
        if cname is not None:
            alive = np.asarray(self.kernel.state.classes[cname].alive)
            cap = alive.shape[0]
            n = self.n_devices
            lo, hi = d * cap // n, (d + 1) * cap // n
            drained = not alive[lo:hi].any()
        if not drained and ticks <= self.exodus_tick_bound:
            return {}
        # shrink around the evicted device.  Content survives either way
        # (block re-place); a not-yet-drained range just means the
        # eviction copies at shrink time instead of ahead of it — the
        # StableUnderReshard invariant surfaces the blown bound.
        if self.migration is not None:
            self.migration.clear_exodus()
            new_n = self.n_devices - 1
            self.migration.retarget(
                placement=dataclasses.replace(
                    self.migration.placement, n_shards=new_n),
                mesh=Mesh(np.delete(self.sharded.mesh.devices, d),
                          (SHARD_AXIS,)),
            )
            mesh = self.migration.mesh
        else:
            mesh = Mesh(np.delete(self.sharded.mesh.devices, d),
                        (SHARD_AXIS,))
        self.sharded.reshard(mesh, cause=f"drain:{d}")
        self.last_exodus_ticks = ticks
        if self._h_exodus is not None:
            self._h_exodus.observe(float(ticks))
        return self._complete(op, {"device": d, "exodus_ticks": ticks,
                                   "drained_in_budget": drained})

    def _poll_grow(self, op) -> Dict[str, np.ndarray]:
        if op["stage"] == "reshard":
            mesh = op["mesh"]
            if self.migration is not None:
                self.migration.retarget(
                    placement=dataclasses.replace(
                        self.migration.placement,
                        n_shards=int(mesh.devices.size)),
                    mesh=mesh,
                )
            self.sharded.reshard(mesh, cause=f"grow:{mesh.devices.size}")
            if self.migration is None:
                return self._complete(op, {"rebalance_ticks": 0})
            op["stage"] = "rebalance"
            return {}
        # rebalance: done once the migrate phase reports zero overflow
        # settle_polls ticks in a row — migrated stays nonzero under
        # normal motion churn; overflow is the stranded re-place backlog.
        # Counted only when the kernel actually ticked since last poll.
        tick = self._tick_count()
        if tick == op["last_seen_tick"]:
            return {}
        op["last_seen_tick"] = tick
        ticks = tick - int(op["start_tick"])
        stats = np.asarray(self.kernel.state.aux[self.migration.aux_key])
        if int(stats[:, 1].sum()) == 0:
            op["settled"] = int(op["settled"]) + 1
        else:
            op["settled"] = 0
        if int(op["settled"]) < self.settle_polls \
                and ticks <= self.exodus_tick_bound:
            return {}
        return self._complete(op, {"rebalance_ticks": ticks})

    def _complete(self, op, extra: Dict[str, object]) -> Dict[str, np.ndarray]:
        moved = self._moved_since(op["snap"])
        n_moved = sum(int(v.size) for v in moved.values())
        self.rows_moved_total += n_moved
        self._pop_last = self._pop()
        done = {
            "kind": op["kind"], "devices": self.n_devices,
            "rows_moved": n_moved,
            "pop_before": int(self._pop_baseline),
            "pop_after": int(self._pop_last),
            **extra,
        }
        self.ops_done.append(done)
        self._op = None
        if self._c_total is not None:
            self._c_total.inc(kind=str(op["kind"]))
            self._c_moved.inc(n_moved)
            self._g_devices.set(float(self.n_devices))
            self._g_inflight.set(0.0)
        return moved

    # ------------------------------------------------------------ autoscale
    def maybe_autoscale(self, signals: Dict[str, float]) -> Optional[str]:
        """Feed one signal sample to the attached :class:`Autoscaler`;
        fire the decided op (grow doubles up to the policy max, drain
        evicts the highest mesh position).  Returns the decision."""
        if self.autoscaler is None or self._op is not None:
            return None
        decision = self.autoscaler.observe(signals, self.n_devices)
        if decision == "grow":
            self.begin_grow(min(self.n_devices * 2,
                                self.autoscaler.policy.max_devices))
        elif decision == "drain":
            self.begin_drain(self.n_devices - 1)
        return decision

    # --------------------------------------------------------------- status
    def status(self) -> Dict[str, object]:
        """Defensively-readable snapshot for invariants and ``/json``."""
        op = self._op
        return {
            "devices": self.n_devices,
            "inflight": self.inflight,
            "stage": None if op is None else op.get("stage"),
            "exodus_ticks": (
                self._tick_count() - int(op["start_tick"])
                if op is not None and op["kind"] == "drain"
                else self.last_exodus_ticks),
            "exodus_tick_bound": self.exodus_tick_bound,
            "dropped_rows": int(self.dropped_rows),
            "rows_moved_total": int(self.rows_moved_total),
            "pop": int(self._pop_last),
            "pop_baseline": int(self._pop_baseline),
            "resharded_total": len(self.ops_done),
            "generation": int(self.kernel.costbook.generation),
        }


# ---------------------------------------------------------------- autoscaler


@dataclasses.dataclass
class AutoscalePolicy:
    """Thresholds over already-exported signals.  A signal missing from
    a sample simply doesn't vote — the loop degrades to whatever is
    actually being measured."""

    grow_tick_p95_ms: float = 50.0    # StageClock "tick" stage p95
    grow_hbm_frac: float = 0.85       # nf_hbm live/limit
    grow_persist_lag_s: float = 2.0   # write-behind flush lag
    grow_failover_lag_s: float = 2.0  # oldest pending re-home
    shrink_tick_p95_ms: float = 4.0   # everything calm below this
    min_devices: int = 1
    max_devices: int = 8
    consecutive: int = 3              # breaches in a row before acting
    cooldown_polls: int = 200         # quiet period after any decision


class Autoscaler:
    """Hysteresis loop: ``observe`` one signal sample per poll, get back
    ``"grow"``/``"drain"``/``None``.  A decision requires
    ``policy.consecutive`` breaching samples in a row AND an expired
    cooldown, so one hot frame (or one idle lull) never flaps the mesh.
    """

    GROW_KEYS = (
        ("tick_p95_ms", "grow_tick_p95_ms"),
        ("hbm_frac", "grow_hbm_frac"),
        ("persist_lag_s", "grow_persist_lag_s"),
        ("failover_lag_s", "grow_failover_lag_s"),
    )

    def __init__(self, policy: Optional[AutoscalePolicy] = None):
        self.policy = policy or AutoscalePolicy()
        self._hot_streak = 0
        self._cold_streak = 0
        self._cooldown = 0
        self.decisions: List[str] = []

    def observe(self, signals: Dict[str, float],
                devices: int) -> Optional[str]:
        p = self.policy
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        hot = any(
            signals.get(sig) is not None
            and float(signals[sig]) > getattr(p, thr)
            for sig, thr in self.GROW_KEYS
        )
        tick = signals.get("tick_p95_ms")
        cold = (not hot and tick is not None
                and float(tick) < p.shrink_tick_p95_ms)
        self._hot_streak = self._hot_streak + 1 if hot else 0
        self._cold_streak = self._cold_streak + 1 if cold else 0
        if hot and self._hot_streak >= p.consecutive \
                and devices < p.max_devices:
            self._hot_streak = self._cold_streak = 0
            self._cooldown = p.cooldown_polls
            self.decisions.append("grow")
            return "grow"
        if cold and self._cold_streak >= p.consecutive \
                and devices > p.min_devices:
            self._hot_streak = self._cold_streak = 0
            self._cooldown = p.cooldown_polls
            self.decisions.append("drain")
            return "drain"
        return None


# ------------------------------------------------------------ parity oracle


class DigestControl:
    """Lockstep single-shard control twin for digest-pinned parity.

    Wraps a control world (same seed, same config, static mesh, no
    faults) and advances it to a requested tick count on demand; the
    digest it returns is what the elastic world must equal at the same
    tick — :func:`~.rowmigrate.canonical_digest` is placement-invariant,
    so ANY mesh history with intact rows matches."""

    def __init__(self, world, ident_cols: Dict[str, int]):
        self.world = world
        self.ident_cols = dict(ident_cols)

    @property
    def tick_count(self) -> int:
        return int(self.world.kernel.tick_count)

    def advance_to(self, tick_count: int) -> int:
        k = self.world.kernel
        target = int(tick_count)
        while k.tick_count < target:
            self.world.tick()
        if k.tick_count != target:
            raise RuntimeError(
                f"control overshot: at {k.tick_count}, wanted {target}")
        return canonical_digest(
            k.state, sorted(self.ident_cols), self.ident_cols)
