"""Distributed execution: device meshes, sharded world tick, collectives.

The reference's scale-out stack (consistent-hash player routing, scene/
group partitioning, World-server cross-shard relay — SURVEY §2.4, §5) maps
here to jax.sharding over ICI/DCN.
"""

from .elastic import Autoscaler, AutoscalePolicy, DigestControl, ElasticMesh
from .mesh import SHARD_AXIS, make_mesh, replicated, row_sharding
from .multihost import (
    DistRendezvous,
    global_mesh,
    init_distributed,
    rendezvous_via_master,
    serve_dist,
)
from .rowmigrate import (
    RowMigrationModule,
    SpatialPlacement,
    canonical_digest,
    mesh_migrate_class,
    migrate_rows,
)
from .shard import ShardedKernel, shard_rows_by_cell, world_shardings
from .spatial import SpatialGeom, SpatialState, SpatialWorld

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "DigestControl",
    "DistRendezvous",
    "ElasticMesh",
    "RowMigrationModule",
    "SpatialPlacement",
    "canonical_digest",
    "global_mesh",
    "init_distributed",
    "mesh_migrate_class",
    "migrate_rows",
    "rendezvous_via_master",
    "serve_dist",
    "SHARD_AXIS",
    "ShardedKernel",
    "SpatialGeom",
    "SpatialState",
    "SpatialWorld",
    "make_mesh",
    "replicated",
    "row_sharding",
    "shard_rows_by_cell",
    "world_shardings",
]
