"""Distributed execution: device meshes, sharded world tick, collectives.

The reference's scale-out stack (consistent-hash player routing, scene/
group partitioning, World-server cross-shard relay — SURVEY §2.4, §5) maps
here to jax.sharding over ICI/DCN.
"""

from .elastic import Autoscaler, AutoscalePolicy, DigestControl, ElasticMesh
from .mesh import ROOMS_AXIS, SHARD_AXIS, make_mesh, replicated, row_sharding
from .rooms import (
    ROOM_EXCLUDED,
    ROOM_PACK_SPEC,
    RoomBatch,
    RoomBinPacker,
    RoomDirectory,
    RoomSlotsFull,
    pack_room_blob,
    room_digest,
    unpack_room_blob,
    world_room_leaf_items,
)
from .multihost import (
    DistRendezvous,
    global_mesh,
    init_distributed,
    rendezvous_via_master,
    serve_dist,
)
from .rowmigrate import (
    RowMigrationModule,
    SpatialPlacement,
    canonical_digest,
    mesh_migrate_class,
    migrate_rows,
)
from .shard import (
    ShardedKernel,
    room_shardings,
    shard_rows_by_cell,
    world_shardings,
)
from .spatial import SpatialGeom, SpatialState, SpatialWorld

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "DigestControl",
    "DistRendezvous",
    "ElasticMesh",
    "RowMigrationModule",
    "SpatialPlacement",
    "canonical_digest",
    "global_mesh",
    "init_distributed",
    "mesh_migrate_class",
    "migrate_rows",
    "pack_room_blob",
    "rendezvous_via_master",
    "room_digest",
    "room_shardings",
    "serve_dist",
    "unpack_room_blob",
    "world_room_leaf_items",
    "ROOM_EXCLUDED",
    "ROOM_PACK_SPEC",
    "ROOMS_AXIS",
    "RoomBatch",
    "RoomBinPacker",
    "RoomDirectory",
    "RoomSlotsFull",
    "SHARD_AXIS",
    "ShardedKernel",
    "SpatialGeom",
    "SpatialState",
    "SpatialWorld",
    "make_mesh",
    "replicated",
    "row_sharding",
    "shard_rows_by_cell",
    "world_shardings",
]
