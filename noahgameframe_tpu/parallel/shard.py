"""Sharded world: distribute the entity store over a device mesh.

Strategy (SURVEY §7 step 5): every per-entity array in WorldState shards
its leading capacity axis across the 1-D mesh; scalars (tick, rng)
replicate.  The tick compiles once with `jax.jit` + sharding annotations
and XLA inserts the collectives — the grid-AOI sort/gather pipeline
becomes a global sort with all-to-alls over ICI, replacing the reference's
World-server relay hop for cross-shard visibility
(NFCWorldNet_ServerModule.cpp:600-830).

Entities don't migrate between shards explicitly: a row's shard is fixed
by its index, and *visibility* crosses shards through the collectives, so
"cross-shard migration" is free (the reference must re-home the object and
replay its state; here the row never moves, only the data flows).  For
locality-tuned placement, `shard_rows_by_cell` allocates rows so that a
(scene, group) cell lands on one shard.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.store import WorldState
from ..kernel.kernel import Kernel
from .mesh import ROOMS_AXIS, SHARD_AXIS, make_mesh


def world_shardings(state: WorldState, mesh: Mesh, axis: str = SHARD_AXIS):
    """Pytree of NamedShardings matching WorldState: leading-axis sharding
    for per-entity arrays, replication for scalars/keys."""
    row = NamedSharding(mesh, PartitionSpec(axis))
    rep = NamedSharding(mesh, PartitionSpec())
    n_dev = mesh.devices.size

    def pick(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] % n_dev == 0 and leaf.shape[0] > 0:
            return row
        return rep

    classes = jax.tree.map(pick, state.classes)
    # aux carries module tick state (Verlet caches): per-entity leading
    # axes shard like class banks, counters/anchors-of-scalars replicate
    aux = jax.tree.map(pick, state.aux)
    return state.replace(classes=classes, tick=rep, rng=rep, aux=aux)


def room_shardings(state, mesh: Mesh, axis: str = ROOMS_AXIS):
    """Pytree of NamedShardings for a ROOM-BATCHED WorldState: every
    leaf carries a leading ``[R]`` room axis (tick and rng included —
    rooms tick independently), so the whole tree shards room-major.
    Contrast :func:`world_shardings`, which shards the entity axis and
    replicates scalars; here there are no scalars left to replicate."""
    row = NamedSharding(mesh, PartitionSpec(axis))
    n_dev = mesh.devices.size

    def pick(leaf):
        if not (hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] > 0 and leaf.shape[0] % n_dev == 0):
            raise ValueError(
                f"room-batched leaf shape {getattr(leaf, 'shape', None)} "
                f"has no [R] axis divisible by {n_dev} devices — "
                "RoomBatch pads capacity to pow2; is this state batched?"
            )
        return row

    return jax.tree.map(pick, state)


class ShardedKernel:
    """Wraps a built Kernel to run its tick sharded over a mesh.

    Usage:
        k.build(...); sk = ShardedKernel(k, n_devices=8)
        sk.place()          # move state onto the mesh
        sk.tick()           # sharded single step (host observation intact)
        sk.run_device(n)    # fused n-step loop, zero host syncs
    """

    def __init__(self, kernel: Kernel, n_devices: Optional[int] = None, mesh: Optional[Mesh] = None):
        self.kernel = kernel
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self.replicated_classes = self._scan_classes(self.mesh)
        self._jit_step = None
        self._jit_step1 = None
        self._jit_run = None
        self._jit_train = None
        self._train_k = 0
        self._shardings = None
        self._shardings_key = None
        self._seen_trace_gen = getattr(kernel, "_trace_gen", 0)

    def _scan_classes(self, mesh: Mesh):
        """Capacity/divisibility policy for one mesh width.

        Tiny control-plane classes (IObject/Scene/config singletons)
        REPLICATE when their capacity doesn't divide the mesh — a
        16-device dryrun must not fail on an 8-row class, and a few
        redundant rows cost nothing.  Anything bigger still errors:
        silently replicating a real entity bank (8x memory, zero
        speedup) would be a perf trap.  Re-run on every reshard — a
        width legal at construction may be illegal after a grow."""
        n_dev = mesh.devices.size
        replicate_limit = max(64, 2 * n_dev)
        replicated = []
        for cname in self.kernel.store.class_order:
            cap = self.kernel.store.capacity(cname)
            if cap % n_dev != 0:
                if cap <= replicate_limit:
                    replicated.append(cname)
                    continue
                raise ValueError(
                    f"class {cname!r} capacity {cap} not divisible by "
                    f"{n_dev} devices — pad StoreConfig.capacities"
                )
        if replicated:
            import warnings

            warnings.warn(
                f"ShardedKernel: classes {replicated} have "
                f"capacities not divisible by {n_dev} devices and will be "
                f"REPLICATED on every device",
                stacklevel=2,
            )
        return replicated

    def _sync_generation(self) -> None:
        """Drop the sharded traces when the wrapped kernel invalidated.

        Kernel.invalidate() (overflow auto-resize, set_phases, digest
        enable) clears only the kernel's OWN jits; without this check the
        sharded wrapper would keep dispatching its stale trace — e.g.
        CombatModule's bucket doubling would never take effect under
        ShardedKernel and overflow drops would repeat forever."""
        gen = getattr(self.kernel, "_trace_gen", 0)
        if gen != self._seen_trace_gen:
            self._jit_step = None
            self._jit_step1 = None
            self._jit_run = None
            self._jit_train = None
            self._shardings = None
            self._seen_trace_gen = gen

    # -- placement -----------------------------------------------------------

    def shardings(self):
        """The sharding pytree for the CURRENT state structure on the
        CURRENT mesh — the single derivation the place/compile paths
        share (previously four per-call ``world_shardings`` walks).

        Cached keyed on (mesh, aux keyset): late-registered aux changes
        the state pytree structure, so priming between calls re-derives;
        ``reshard``/``_sync_generation`` invalidate explicitly."""
        key = (self.mesh, tuple(sorted(self.kernel.state.aux.keys())))
        if self._shardings is None or self._shardings_key != key:
            self._shardings = world_shardings(self.kernel.state, self.mesh)
            self._shardings_key = key
        return self._shardings

    def place(self) -> None:
        # prime registered aux first: the sharding pytree must match the
        # state pytree structurally, and priming later would leave new
        # leaves off-mesh
        self.kernel._ensure_aux()
        self.kernel.state = jax.device_put(self.kernel.state, self.shardings())

    def reshard(self, new_mesh: Optional[Mesh] = None,
                cause: str = "reshard") -> Mesh:
        """Re-place the LIVE world onto ``new_mesh`` (or onto the current
        mesh when None — the cross-engine snapshot-load path).

        Zero dropped rows by construction: the leading capacity axis is
        block-partitioned, so a row's shard is a pure function of its
        global index, and the global index never changes here — growing
        2→8 or shrinking 8→2 re-slices the same axis.  (Evicting a
        SPECIFIC device first drains row contents toward survivors via
        the exodus protocol in parallel/elastic.py, then calls this.)

        Every call announces a CostBook generation bump BEFORE dropping
        traces, so the recompiles the new topology forces are sanctioned
        — ``unexplained_since()`` stays clean — and drops Verlet/binning
        aux caches exactly like row arrival does (kernel.invalidate)."""
        old_n = self.mesh.devices.size
        mesh = self.mesh if new_mesh is None else new_mesh
        self.replicated_classes = self._scan_classes(mesh)
        k = self.kernel
        k.costbook.generation_bump(
            f"{cause}:{old_n}->{mesh.devices.size}")
        k.invalidate()
        self.mesh = mesh
        self._jit_step = None
        self._jit_step1 = None
        self._jit_run = None
        self._jit_train = None
        self._shardings = None
        self._seen_trace_gen = getattr(k, "_trace_gen", 0)
        k._ensure_aux()
        k.state = jax.device_put(k.state, self.shardings())
        return mesh

    # -- compiled sharded step ----------------------------------------------

    def _compile(self):
        if self._jit_step is None:
            shardings = self.shardings()
            self._jit_step = self.kernel.costbook.wrap(
                "kernel.sharded_step", self.kernel._trace_step,
                donate_argnums=0, stage="tick",
                jit_kwargs={"in_shardings": (shardings,),
                            "out_shardings": (shardings, None)},
            )
        return self._jit_step

    def tick(self):
        """One sharded step with full host observation (events, deaths,
        diffs) — same semantics as Kernel.tick."""
        import numpy as np

        from ..kernel.kernel import DeviceEvent, TickOutputs

        k = self.kernel
        self._sync_generation()
        k._ensure_aux()
        step = self._compile()
        k.state, raw = step(k.state)
        k.tick_count += 1
        out = TickOutputs(
            fired=raw["fired"],
            diff=raw["diff"],
            diff_count=raw["diff_count"],
            rec_diff=raw["rec_diff"],
            rec_diff_count=raw["rec_diff_count"],
            died=raw["died"],
            died_count=raw["died_count"],
            events=[
                DeviceEvent(eid, cname, mask, dict(params))
                for (eid, cname, pnames), (mask, params) in zip(
                    k._event_meta, raw["events"]
                )
            ],
        )
        summary = np.asarray(raw["summary"])
        # decode the counter bank exactly like Kernel.tick_finish — a
        # sharded frame's observers (journal digest marks, train tails)
        # read the same surface as a single-device frame's
        if k._counter_names:
            out.counters = {
                kk: int(v) for kk, v in k.decode_counters(summary).items()
            }
            k.last_counters = dict(out.counters)
            for kk, v in out.counters.items():
                if kk in ("state_digest", "tick"):
                    continue
                k.counter_totals[kk] = k.counter_totals.get(kk, 0) + v
        k._post_tick(out, summary)
        return out

    def _compile_headless(self):
        """One sharded step returning ONLY the state (host outputs
        dead-code-eliminated) — the benchmark-loop body."""
        if getattr(self, "_jit_step1", None) is None:
            shardings = self.shardings()

            def step1(st):
                st2, _out = self.kernel._trace_step(st)
                return st2

            self._jit_step1 = self.kernel.costbook.wrap(
                "kernel.sharded_step1", step1,
                donate_argnums=0, stage="tick",
                jit_kwargs={"in_shardings": (shardings,),
                            "out_shardings": shardings},
            )
        return self._jit_step1

    def run_device(self, n: int, fused: bool = True) -> None:
        """n sharded headless ticks with zero host syncs.

        fused=True (default, the documented semantics): ONE fori_loop
        program — no per-tick dispatch, but a ~3.5x bigger XLA compile
        (176 s vs 50 s at 512k x 8 virtual devices; round-3's 319 s
        sharded compile was exactly this).  fused=False host-dispatches
        a single compiled headless step per tick: state stays
        device-resident (no readbacks), and compile cost is one step's —
        what bench.py's ladder uses so compile doesn't dominate."""
        key = int(n)
        self._sync_generation()
        self.kernel._ensure_aux()
        if not fused:
            step = self._compile_headless()
            for _ in range(key):
                self.kernel.state = step(self.kernel.state)
            self.kernel.tick_count += key
            return
        if self._jit_run is None:
            # traced trip count: one compile serves every n (matches
            # Kernel.run_device; a per-n recompile at 512k x 8 devices
            # is ~minutes of XLA wall)
            shardings = self.shardings()

            def body(_, st):
                st2, _out = self.kernel._trace_step(st)
                return st2

            self._jit_run = self.kernel.costbook.wrap(
                "kernel.sharded_run",
                lambda st, k: jax.lax.fori_loop(0, k, body, st),
                donate_argnums=0, stage="tick",
                jit_kwargs={"in_shardings": (shardings, None),
                            "out_shardings": shardings},
            )
        self.kernel.state = self._jit_run(self.kernel.state, jnp.int32(key))
        self.kernel.tick_count += key

    # -- K-tick trains --------------------------------------------------------

    def configure_train(self, k: int) -> None:
        """Pin the sharded train length (see Kernel.configure_train).
        The wrapped kernel's K is kept in sync so its lane fan-out
        (train_finish) slices the right depth."""
        self.kernel.configure_train(k)
        if int(k) != self._train_k:
            self._train_k = int(k)
            self._jit_train = None

    def _compile_train(self):
        if self._jit_train is None:
            if self._train_k < 1:
                raise RuntimeError("configure_train(k) before train()")
            shardings = self.shardings()
            self._jit_train = self.kernel.costbook.wrap(
                "kernel.sharded_train", self.kernel._trace_train,
                donate_argnums=0, stage="tick",
                jit_kwargs={"in_shardings": (shardings,),
                            "out_shardings": (shardings, None)},
            )
        return self._jit_train

    def train(self, n: int):
        """n sharded frames in ⌊n/K⌋ train dispatches + a per-tick
        ragged tail, with full host observation per frame — shardings
        carried through the scan, lanes fanned out by the wrapped
        kernel's train_finish (tick-exact death attribution included)."""
        n = int(n)
        k = self.kernel
        kk = self._train_k
        if kk < 1:
            raise RuntimeError("configure_train(k) before train()")
        self._sync_generation()
        k._ensure_aux()
        jt = self._compile_train()
        outs = []
        for _ in range(n // kk):
            k.state, raw = jt(k.state)
            k.tick_count += kk
            k.train_dispatches += 1
            k.train_ticks += kk
            outs.extend(k.train_finish(raw))
        for _ in range(n % kk):
            outs.append(self.tick())
        return outs


def shard_rows_by_cell(n: int, n_devices: int, cell: np.ndarray) -> np.ndarray:
    """Allocation helper: order n new rows so entities of one (scene,group)
    cell land contiguously, i.e. on as few shards as possible.  Returns a
    permutation of arange(n) — pass positions/cells through it before
    create_many so row index ≈ locality."""
    order = np.argsort(cell, kind="stable")
    return order
