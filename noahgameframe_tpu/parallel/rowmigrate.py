"""Full-row cross-shard migration: the slab protocol generalized to ClassState.

parallel/spatial.py pioneered budgeted ppermute migration for its private
six-column mini-world (free-slot capacity vote → pack → ppermute →
scatter-insert).  This module lifts that protocol to the real entity
store: a migrating entity moves its ENTIRE ``ClassState`` row — every
property bank, every record page, the TimerState triple, and the alive
bit — as one pytree-structured pack/scatter compiled into the sharded
tick.  The pack list is derived generically from the store pytree by
``persist.rowblob.class_row_leaf_items`` (the same leaf walk
``shard.py:world_shardings`` does for placement), so a newly added bank
can never be silently left behind; the ``migrate-covers-store`` nf-lint
rule pins that statically and the walk asserts it at trace time.

Verlet/binning caches are NOT migrated: they live in ``WorldState.aux``
(never in ClassState), are excluded from ``state_digest``, and are
dropped-and-rebuilt on arrival — the cache-rebuild contract documented in
docs/ARCHITECTURE.md.

Reference contrast: NFCWorldNet_ServerModule.cpp:600-830 re-homes an
entity between game servers by serialize → destroy → recreate through the
World relay; here the same "whole entity moves" semantics is two
fixed-size collectives inside the jitted tick.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.store import ClassState, WorldState, with_class
from ..kernel.module import Module
from ..persist.rowblob import class_row_leaf_items, rebuild_class_state, row_nbytes
from .mesh import SHARD_AXIS, make_mesh

# jax.shard_map landed as a top-level API (with check_vma) after 0.4.x;
# older releases spell it jax.experimental.shard_map with check_rep.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SM_KW = {"check_vma": False}
else:  # pragma: no cover - exercised on jax<0.6 only
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_KW = {"check_rep": False}


def _pack_rows(sel, rank, budget, *arrays):
    """Gather up to `budget` selected rows into fixed [budget] buffers.
    sel: [n] bool, rank: [n] exclusive rank among selected.  Returns
    (valid [budget] bool, packed arrays).  Generic over trailing dims —
    property banks [n, k], record pages [n, R, k] and [n, R, k, 3] all
    pack with the same leading-axis scatter."""
    idx = jnp.where(sel & (rank < budget), rank, budget)
    valid = jnp.zeros((budget + 1,), bool).at[idx].set(sel)[:budget]
    out = []
    for a in arrays:
        buf_shape = (budget + 1,) + a.shape[1:]
        out.append(jnp.zeros(buf_shape, a.dtype).at[idx].set(a)[:budget])
    return valid, out


def migrate_rows(leaves, alive, owner_fn, axis, n_shards, budget):
    """One budgeted ppermute migration round over arbitrary row leaves.

    Runs INSIDE shard_map: ``leaves`` are the shard-local banks (leading
    axis = local bank rows), ``alive`` the local occupancy mask.
    ``owner_fn(leaves, alive) -> [rows] i32`` returns each row's owning
    shard index; it is re-evaluated after each direction so freshly
    arrived rows are never double-hopped.  Protocol (verbatim from the
    slab engine, now generic over the leaf list):

    1. each shard advertises its free-slot count BEFORE clearing its own
       outbound rows (the advertised number only understates reality);
       the sender clamps to min(budget, advertised) so a row that would
       find no destination slot stays home and retries,
    2. selected rows pack into fixed [budget] buffers, one ppermute per
       leaf per direction,
    3. arrivals scatter into free-slot ranks; a drop here is a protocol
       bug (counted, should never fire), not expected overflow.

    Returns (leaves, alive, (migrated, overflow, dropped)) — the three
    stats as i32 scalars for this shard.
    """
    n = n_shards
    me = jax.lax.axis_index(axis)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    cap_rows = alive.shape[0]
    migrated = jnp.int32(0)
    overflow = jnp.int32(0)
    dropped = jnp.int32(0)
    leaves = list(leaves)
    owner = owner_fn(leaves, alive)
    for d, perm in ((1, fwd), (-1, bwd)):
        # direction of travel, not exact neighbor: a row stranded 2+
        # shards from home hops one shard toward its owner per tick
        m = alive & ((owner > me) if d == 1 else (owner < me))
        free_cnt = jnp.sum(~alive, dtype=jnp.int32)
        remote_free = jax.lax.ppermute(free_cnt, axis, bwd if d == 1 else fwd)
        cap_d = jnp.minimum(jnp.int32(budget), remote_free)
        csum = jnp.cumsum(m.astype(jnp.int32))
        sel = m & (csum <= cap_d)
        migrated = migrated + jnp.sum(sel, dtype=jnp.int32)
        overflow = overflow + jnp.sum(m, dtype=jnp.int32) - jnp.sum(
            sel, dtype=jnp.int32
        )
        valid, packed = _pack_rows(sel, csum - 1, budget, *leaves)
        rvalid = jax.lax.ppermute(valid, axis, perm)
        rpacked = [jax.lax.ppermute(b, axis, perm) for b in packed]
        # wrap-around sends are impossible (owner is clipped into range),
        # but mask the circular receive anyway for edge shards
        sender_ok = (me - d >= 0) & (me - d < n)
        rvalid = rvalid & sender_ok
        alive = alive & ~sel
        # insert into free slots: dest[j] = row index of the j-th free slot
        free = ~alive
        frank = jnp.cumsum(free.astype(jnp.int32)) - free.astype(jnp.int32)
        slots = jnp.where(free & (frank < budget), frank, budget)
        dest = (
            jnp.full((budget + 1,), cap_rows, jnp.int32)
            .at[slots]
            .set(jnp.arange(cap_rows, dtype=jnp.int32))[:budget]
        )
        dest_j = jnp.where(rvalid, dest, cap_rows)
        dropped = dropped + jnp.sum(
            rvalid & (dest_j >= cap_rows), dtype=jnp.int32
        )
        leaves = [
            cur.at[dest_j].set(rb, mode="drop")
            for cur, rb in zip(leaves, rpacked)
        ]
        alive = alive.at[dest_j].set(True, mode="drop")
        owner = owner_fn(leaves, alive)
    return leaves, alive, (migrated, overflow, dropped)


def mesh_migrate_class(
    cs: ClassState,
    mesh: Mesh,
    owner_fn: Callable,
    budget: int,
    axis: str = SHARD_AXIS,
    extra_leaves: Optional[Sequence[jnp.ndarray]] = None,
):
    """Migrate full ClassState rows toward their owning shard.

    ``owner_fn({path: local_leaf}) -> [rows] i32`` maps the shard-local
    leaf dict (paths as in ``persist.rowblob.ROW_LEAF_SPEC``, plus
    ``alive``) to owning shard indices.  The alive bit is the protocol's
    own occupancy bookkeeping; every other leaf rides the generic
    pack/scatter.  Returns (new ClassState, [n_shards, 3] i32 stats:
    migrated / budget-overflow / dropped per shard).

    ``extra_leaves`` are additional per-row arrays (leading axis = class
    capacity, row-sharded like the banks) that migrate WITH the row but
    live outside ClassState — e.g. the tick's in-flight fired mask, which
    the schedule computed before this phase and later phases still read.
    They ride the same pack/ppermute/scatter and are returned as a third
    element, permuted consistently with the class state.
    """
    n = mesh.devices.size
    items = class_row_leaf_items(cs)
    paths = [p for p, _ in items]
    arrs = [a for _, a in items]
    ai = paths.index("alive")
    extras = list(extra_leaves) if extra_leaves else []
    n_row = len(arrs)
    row = P(axis)

    def body(*local):
        local = list(local)
        row_local, extras_local = local[:n_row], local[n_row:]
        alive = row_local[ai]
        others = row_local[:ai] + row_local[ai + 1:] + extras_local

        def owner_of(ls, alv):
            # extras sit past the named paths; owner_fn never sees them
            full: Dict[str, jnp.ndarray] = {}
            j = 0
            for p in paths:
                if p == "alive":
                    full[p] = alv
                else:
                    full[p] = ls[j]
                    j += 1
            return owner_fn(full)

        new_others, new_alive, (mig, ovf, drp) = migrate_rows(
            others, alive, owner_of, axis, n, budget
        )
        merged = []
        j = 0
        for p in paths:
            if p == "alive":
                merged.append(new_alive)
            else:
                merged.append(new_others[j])
                j += 1
        stats = jnp.stack([mig, ovf, drp])[None, :]  # [1, 3] per shard
        return tuple(merged) + tuple(new_others[n_row - 1:]) + (stats,)

    smapped = _shard_map(
        body,
        mesh=mesh,
        in_specs=(row,) * (n_row + len(extras)),
        out_specs=(row,) * (n_row + len(extras) + 1),
        **_SM_KW,
    )
    out = smapped(*(arrs + extras))
    new_leaves = list(out[:n_row])
    new_extras = list(out[n_row:-1])
    stats = out[-1]
    new_cs = rebuild_class_state(cs, new_leaves)
    if extra_leaves is None:
        return new_cs, stats
    return new_cs, stats, new_extras


# -- GameWorld-facing placement config ------------------------------------


@dataclasses.dataclass(frozen=True)
class SpatialPlacement:
    """Config-selected spatial placement: grid geometry + migration budget
    as a kernel phase.  Attach via ``RowMigrationModule`` (GameWorld does
    this when ``WorldConfig.placement`` is set)."""

    class_name: str          # entity class whose rows migrate
    pos_prop: str            # vector property giving world position
    extent: float            # world is [0, extent)^2
    cell_size: float
    width: int               # cells per axis
    n_shards: int            # horizontal slabs
    mig_budget: int          # migrant rows per direction per shard per tick

    @property
    def slab_h(self) -> int:
        # ceil division: when width % n_shards != 0 (an elastic drain to
        # an odd survivor count) the LAST shard owns a narrower slab but
        # owner_of_pos stays in [0, n_shards) for every cell
        return -(-self.width // self.n_shards)

    def owner_of_pos(self, pos_xy: jnp.ndarray) -> jnp.ndarray:
        """[rows, 2+] positions -> [rows] i32 owning shard index."""
        cy = jnp.clip(
            (pos_xy[:, 1] / self.cell_size).astype(jnp.int32), 0,
            self.width - 1,
        )
        return cy // self.slab_h


class RowMigrationModule(Module):
    """Kernel module registering the ``migrate`` phase: full-row
    cross-shard migration for one class, keyed on its position property.

    Stats ride ``state.aux["rowmigrate.<class>.stats"]`` ([n_shards, 3]
    i32: migrated / budget-overflow / dropped) so the headless sharded
    loop keeps them device-resident, and ``ctx.count`` mirrors the
    migrated total into the tick summary for the observed path.
    """

    name = "rowmigrate"

    def __init__(self, placement: SpatialPlacement,
                 mesh: Optional[Mesh] = None, order: int = 20):
        super().__init__()
        self.placement = placement
        self.mesh = mesh if mesh is not None else make_mesh(placement.n_shards)
        self.aux_key = f"rowmigrate.{placement.class_name}.stats"
        # exodus overlay (parallel/elastic.py drain protocol): when set,
        # owners are remapped through a host table so rows vacate a
        # draining shard; both are trace-time constants, so arming or
        # clearing REQUIRES kernel.invalidate() (set_exodus does it)
        self._exodus_map: Optional[jnp.ndarray] = None
        self.add_phase("migrate", self._migrate, order=order)

    def bind(self, kernel) -> None:
        """Register carried aux BEFORE the first trace (stats must exist
        in the state pytree so sharded in/out shardings stay stable)."""
        self.kernel = kernel
        n = self.placement.n_shards
        kernel.register_aux(
            self.aux_key, lambda: jnp.zeros((n, 3), jnp.int32)
        )

    def retarget(self, placement: Optional[SpatialPlacement] = None,
                 mesh: Optional[Mesh] = None) -> None:
        """Re-aim the migrate phase at a new placement and/or mesh — the
        elastic reshard path.  The stats aux re-registers at the new
        shard count; the caller must invalidate + re-place (ElasticMesh
        does both via ShardedKernel.reshard, which drops the old aux and
        primes the new shape before the next trace)."""
        if placement is not None:
            if placement.class_name != self.placement.class_name:
                raise ValueError("retarget cannot change the migrating "
                                 "class (aux key is class-keyed)")
            self.placement = placement
        if mesh is not None:
            self.mesh = mesh
        if self.kernel is not None:
            self.bind(self.kernel)

    def set_exodus(self, index_map) -> None:
        """Arm the drain overlay: spatial owner ``o`` is remapped to
        ``index_map[o]`` so every row owned by a draining shard re-homes
        to a surviving one.  Bumps the kernel trace generation — the
        remap is a traced constant."""
        self._exodus_map = jnp.asarray(index_map, jnp.int32)
        if self.kernel is not None:
            self.kernel.invalidate()

    def clear_exodus(self) -> None:
        if self._exodus_map is None:
            return
        self._exodus_map = None
        if self.kernel is not None:
            self.kernel.invalidate()

    def after_init(self) -> None:
        if self.kernel is not None and self.aux_key not in getattr(
                self.kernel, "_aux_init", {}):
            self.bind(self.kernel)

    def row_bytes(self) -> int:
        """Per-row wire bytes of the migrating class (bench accounting)."""
        if self.kernel is None or self.kernel.state is None:
            return 0
        cs = self.kernel.state.classes[self.placement.class_name]
        return row_nbytes(cs)

    def _migrate(self, state: WorldState, ctx) -> WorldState:
        pl = self.placement
        exodus = self._exodus_map
        cs = state.classes[pl.class_name]
        slot = ctx.store.spec(pl.class_name).slot(pl.pos_prop)

        def owner_fn(leaves: Dict[str, jnp.ndarray]) -> jnp.ndarray:
            pos = leaves["vec"][:, slot.col, :]
            owner = pl.owner_of_pos(pos)
            if exodus is not None:
                # runs inside mesh_migrate_class's shard_map, so the
                # local shard index is addressable.  While the drain is
                # armed, ALL migration freezes except evacuation: rows
                # standing on the draining shard route to their remapped
                # owner (never the draining shard itself — the remap has
                # no fixed point there), everyone else re-homes to where
                # they already stand.  Routing by spatial owner instead
                # would keep a trickle of through-traffic hopping ACROSS
                # the draining bank (ring transit is one shard per
                # tick), and under continuous motion churn the bank then
                # never empties — the drain blows its tick bound.
                # Spatial rebalance pauses for the few evacuation ticks
                # and resumes when clear_exodus() re-arms normal routing.
                mapped = jnp.take(exodus, owner)
                me = jax.lax.axis_index(SHARD_AXIS)
                draining_here = jnp.take(exodus, me) != me
                owner = jnp.where(draining_here, mapped, me)
            return owner

        # the tick's fired mask was computed pre-migration; it must move
        # WITH the row or a migrant's timer fire lands on its vacated
        # (dead) slot and every later handler silently skips it
        fired = ctx._fired.get(pl.class_name)
        extras = [fired] if fired is not None and fired.shape[1] else None

        # the module's mesh is generation-safe by contract: every elastic
        # reshard retarget()s it and invalidates before the re-trace
        out = mesh_migrate_class(
            cs, self.mesh, owner_fn, pl.mig_budget,  # nf-lint: disable=mesh-not-captured -- retarget()+invalidate() re-aim it pre-retrace
            extra_leaves=extras,
        )
        if extras is None:
            cs2, stats = out
        else:
            cs2, stats, (new_fired,) = out
            # vacated source slots keep stale mask bytes; dead rows never
            # fire, so pin the invariant here rather than trust consumers
            ctx.remap_fired(pl.class_name, new_fired & cs2.alive[:, None])
        ctx.count("migrated", jnp.sum(stats[:, 0]))
        ctx.count("mig_overflow", jnp.sum(stats[:, 1]))
        state = with_class(state, pl.class_name, cs2)
        return state.replace(aux={**state.aux, self.aux_key: stats})


# -- placement-invariant digest (parity oracle) ----------------------------


def canonical_digest(state: WorldState, class_order: Sequence[str],
                     ident_cols: Dict[str, int]) -> int:
    """Host-side uint32 digest that is invariant to row PLACEMENT.

    ``kernel.state_digest`` is position-weighted, so the same logical
    world hashed on an 8-shard mesh (rows scattered by migration) and on
    a single-shard control (rows never move) produces different values.
    This twin canonicalizes first: per class, live rows are ordered by a
    stable identity column (``ident_cols[cname]``: i32 column index; the
    class's rows must carry unique ids there), dead rows are dropped
    entirely (a vacated slot keeps stale bank bytes by design), and the
    same fold math as state_digest runs over the canonical view.  Two
    runs agree iff every live row's full ClassState content agrees.
    """
    mult = np.uint64(1000003)
    mask = np.uint64(0xFFFFFFFF)

    def fold(acc: np.uint64, arr: np.ndarray) -> np.uint64:
        a = np.ascontiguousarray(arr)
        if a.dtype == np.bool_:
            u = a.astype(np.uint32)
        elif a.dtype.itemsize == 4:
            u = a.view(np.uint32)
        else:
            u = a.astype(np.uint32)
        u = u.ravel().astype(np.uint64)
        w = np.arange(u.size, dtype=np.uint64) * 2 + 1
        s = np.uint64(int((u * w).sum(dtype=np.uint64)) & 0xFFFFFFFF)
        return (acc * mult + s) & mask

    acc = np.uint64(0x9E3779B9)
    acc = fold(acc, np.asarray(state.tick))
    for cname in class_order:
        cs = state.classes[cname]
        alive = np.asarray(cs.alive)
        ident = np.asarray(cs.i32)[:, ident_cols[cname]]
        live = np.flatnonzero(alive)
        order = live[np.argsort(ident[live], kind="stable")]
        acc = fold(acc, np.uint32(live.size))
        for _path, arr in class_row_leaf_items(cs):
            a = np.asarray(arr)
            if _path == "alive":
                continue  # canonical view is all-live by construction
            acc = fold(acc, a[order])
    return int(acc)
