"""Many-worlds room engine: thousands of independent rooms on one mesh.

The reference's genre scales by INSTANCES, not by one giant world: the
scene/group AOI layer (NFCSceneAOIModule) partitions players into
~100-entity rooms and proxies route each session to the game server
hosting its scene — "millions of users" means tens of thousands of small
rooms.  The single-world engines here (ShardedKernel, ElasticMesh) shard
one world's ENTITY axis; this module adds the orthogonal scale shape:

    batched = stack(room_0, room_1, ..., room_{R-1})      # [R, ...]
    step_R  = jax.vmap(kernel._trace_step)                # one trace
    sharding = NamedSharding(mesh, PartitionSpec("rooms"))

Every WorldState leaf gains a leading room axis (tick and rng included —
rooms tick independently), the fused tick vmaps over it unchanged, and
the room axis block-partitions across the mesh so each device owns a
contiguous range of room SLOTS.  Rooms never interact on device by
construction (vmap semantics ARE the isolation proof), so per-room
results are bit-identical to R independent single-room kernels — the
parity spine tests/test_rooms.py pins.

Host side mirrors the serving layer's slot discipline:

* ``RoomBinPacker`` — slots group into per-device blocks; create picks
  the least-loaded block's lowest free slot (or first-fit).
* create/destroy are SLOT RECYCLING with lazy wipe (SessionTable's
  ``_stale`` discipline): destroy only frees the host slot; admit's
  full-leaf scatter overwrites every byte, so no device wipe runs and —
  critically — no shape changes, so room churn never retraces.  Growing
  the slot bank doubles capacity under a sanctioned
  ``costbook.generation_bump`` exactly like the combat bucket resize.
* re-home moves a room between slots/devices as BYTES: the packed leaves
  travel in a ``persist/rowblob.frame_blob`` CRC frame carrying the
  room's positional digest, so a torn or stale re-home is rejected
  before it ever reaches the destination slot.

``ROOM_PACK_SPEC`` below is the reviewed enumeration of what "a room"
is; the ``room-axis-covered`` nf-lint rule cross-checks it against the
WorldState dataclass statically, and :func:`world_room_leaf_items`
enforces it at runtime (the rowblob/migrate-covers-store pattern one
level up the pytree).  ``WorldState.aux`` is excluded on purpose: Verlet
and binning caches are dropped on admit and rebuilt by the next tick,
and the true-radius masking of ops/verlet.py keeps results bit-identical
to a warm-cache control (same contract checkpoint resume relies on).
"""

from __future__ import annotations

import os
import struct as _struct
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..core.datatypes import next_pow2
from ..core.store import WorldState
from ..kernel.kernel import Kernel
from ..persist.rowblob import (
    RowBlobError,
    class_row_leaf_items,
    frame_blob,
    rebuild_class_state,
    unframe_blob,
)
from .mesh import ROOMS_AXIS  # noqa: F401  (re-exported: the axis name)

__all__ = [
    "ROOMS_AXIS",
    "ROOM_EXCLUDED",
    "ROOM_PACK_SPEC",
    "RoomBatch",
    "RoomBinPacker",
    "RoomDirectory",
    "RoomSlotsFull",
    "pack_room_blob",
    "room_digest",
    "unpack_room_blob",
    "world_room_leaf_items",
]

#: default slot-bank capacity when RoomDirectory isn't told one
ENV_ROOM_SLOTS = "NF_ROOM_SLOTS"

# Every WorldState leaf path must match one of these patterns (or appear
# in ROOM_EXCLUDED with a reason).  The room-axis-covered lint rule
# cross-checks this tuple against the store dataclasses; keep it a plain
# literal.
ROOM_PACK_SPEC = (
    "tick",
    "rng",
    "classes.*.i32",
    "classes.*.f32",
    "classes.*.vec",
    "classes.*.alive",
    "classes.*.timers.next_fire",
    "classes.*.timers.interval",
    "classes.*.timers.remain",
    "classes.*.timers.active",
    "classes.*.records.*.i32",
    "classes.*.records.*.f32",
    "classes.*.records.*.vec",
    "classes.*.records.*.used",
)

# Leaves waived from the room pack, with a reason each.  aux holds
# module caches (Verlet tables) that are dropped on admit and rebuilt by
# the next tick — results stay bit-identical under true-radius masking,
# and the caches bake trace-time geometry that must not travel.
ROOM_EXCLUDED = (
    "aux.*",
)


class RoomSlotsFull(RuntimeError):
    """Every room slot is occupied — grow() the batch (a sanctioned
    generation bump) or shed rooms before creating more."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        super().__init__(
            f"all {capacity} room slots occupied — grow the RoomBatch "
            "(sanctioned retrace) or destroy rooms first"
        )


# -- the room leaf walk (pack/lint contract) --------------------------------


def world_room_leaf_items(
    state: WorldState, class_order: Optional[Sequence[str]] = None,
) -> List[Tuple[str, Any]]:
    """Ordered ``(path, array)`` pairs for every PACKED leaf of one
    room's WorldState (no leading room axis) — tick, rng, then every
    ClassState row leaf per class.  aux is skipped (ROOM_EXCLUDED) but
    its keys are still checked against the exclusion patterns, so an
    aux entry can never silently dodge the reviewed contract."""
    import fnmatch

    def covered(path: str, pats) -> bool:
        return any(fnmatch.fnmatch(path, p) for p in pats)

    items: List[Tuple[str, Any]] = [("tick", state.tick), ("rng", state.rng)]
    names = list(class_order) if class_order is not None \
        else sorted(state.classes)
    for cname in names:
        for path, arr in class_row_leaf_items(state.classes[cname]):
            items.append((f"classes.{cname}.{path}", arr))
    for path, _arr in items:
        if not covered(path, ROOM_PACK_SPEC):
            raise RowBlobError(
                f"WorldState leaf {path!r} not covered by ROOM_PACK_SPEC "
                "— re-homing would silently leave this bank behind")
    for key in getattr(state, "aux", {}) or {}:
        if not covered(f"aux.{key}", ROOM_EXCLUDED):
            raise RowBlobError(
                f"aux entry {key!r} matches neither ROOM_PACK_SPEC nor "
                "ROOM_EXCLUDED — waive it explicitly or pack it")
    return items


# -- placement-invariant per-room digest ------------------------------------


def room_digest(
    state: WorldState,
    class_order: Sequence[str],
    ident_cols: Optional[Dict[str, int]] = None,
) -> int:
    """Host-side uint32 digest of ONE room, bit-compatible with the
    device ``kernel.state_digest`` fold (same seed, weights, rolling
    multiply, aux exclusion).  Row layout inside a room never changes
    when the room moves slots — admit copies leaves verbatim — so the
    positional fold is already SLOT-invariant, and equality against a
    single-room control world is exact.  Pass ``ident_cols`` to delegate
    to ``rowmigrate.canonical_digest`` instead when rows themselves may
    have been permuted (a room extracted from a mesh-migrating world)."""
    if ident_cols is not None:
        from .rowmigrate import canonical_digest

        return canonical_digest(state, class_order, ident_cols)
    mult = np.uint64(1000003)
    mask = np.uint64(0xFFFFFFFF)

    def fold(acc: np.uint64, arr) -> np.uint64:
        a = np.ascontiguousarray(np.asarray(arr))
        if a.dtype == np.bool_:
            u = a.astype(np.uint32)
        elif a.dtype.itemsize == 4:
            u = a.view(np.uint32)
        else:
            u = a.astype(np.uint32)
        u = u.ravel().astype(np.uint64)
        w = np.arange(u.size, dtype=np.uint64) * 2 + 1
        s = np.uint64(int((u * w).sum(dtype=np.uint64)) & 0xFFFFFFFF)
        return (acc * mult + s) & mask

    acc = np.uint64(0x9E3779B9)
    acc = fold(acc, state.tick)
    acc = fold(acc, state.rng)
    for cname in class_order:
        cs = state.classes[cname]
        for arr in (cs.i32, cs.f32, cs.vec, cs.alive,
                    cs.timers.next_fire, cs.timers.interval,
                    cs.timers.remain, cs.timers.active):
            acc = fold(acc, arr)
        for rname in sorted(cs.records):
            rec = cs.records[rname]
            for arr in (rec.i32, rec.f32, rec.vec, rec.used):
                acc = fold(acc, arr)
    return int(acc)


# -- room blob (re-home / cross-engine snapshot framing) --------------------

_ROOM_MAGIC = b"NFRM"
_ROOM_VERSION = 1
_ROOM_HEADER = _struct.Struct("<4sBHI")  # magic, version, n_leaves, digest
_LEAF_HEADER = _struct.Struct("<HHB")  # path_len, dtype_len, ndim


def pack_room_blob(state: WorldState, class_order: Sequence[str]) -> bytes:
    """Serialize one room's packed leaves (ROOM_PACK_SPEC order) into a
    CRC-framed blob carrying the room's positional digest.  The frame is
    ``persist/rowblob.frame_blob`` — the same envelope session snapshots
    cross hosts in — so torn re-homes are detected identically."""
    items = world_room_leaf_items(state, class_order)
    digest = room_digest(state, class_order)
    parts = [_ROOM_HEADER.pack(_ROOM_MAGIC, _ROOM_VERSION, len(items), digest)]
    for path, arr in items:
        # NOT ascontiguousarray: it promotes the 0-d tick to [1], and
        # tobytes() already emits a C-order copy for any layout
        a = np.asarray(arr)
        p = path.encode()
        d = a.dtype.str.encode()
        parts.append(_LEAF_HEADER.pack(len(p), len(d), a.ndim))
        parts.append(p)
        parts.append(d)
        parts.append(_struct.pack(f"<{a.ndim}I", *a.shape))
        parts.append(a.tobytes())
    return frame_blob(b"".join(parts))


def unpack_room_blob(blob: bytes, template: WorldState,
                     class_order: Sequence[str]) -> WorldState:
    """Validate + decode a room blob against ``template``'s structure.

    Fail-closed on every mismatch: frame CRC, magic/version, leaf order,
    dtype, shape — and finally the embedded digest is recomputed over
    the rebuilt room, so a blob corrupted in a way the CRC survived (or
    packed by a structurally different build) can never be admitted.
    Returns a room WorldState with ``aux={}`` (admit supplies fresh
    caches)."""
    payload = unframe_blob(blob, allow_legacy=False)
    if len(payload) < _ROOM_HEADER.size:
        raise RowBlobError("room blob truncated before header")
    magic, version, n_leaves, digest = _ROOM_HEADER.unpack_from(payload)
    if magic != _ROOM_MAGIC:
        raise RowBlobError("missing room blob magic")
    if version != _ROOM_VERSION:
        raise RowBlobError(f"unknown room blob version {version}")
    expect = world_room_leaf_items(template, class_order)
    if n_leaves != len(expect):
        raise RowBlobError(
            f"room blob carries {n_leaves} leaves, template has "
            f"{len(expect)} — cross-build re-home rejected")
    off = _ROOM_HEADER.size
    leaves: List[np.ndarray] = []
    for path, tarr in expect:
        plen, dlen, ndim = _LEAF_HEADER.unpack_from(payload, off)
        off += _LEAF_HEADER.size
        got_path = payload[off:off + plen].decode()
        off += plen
        dtype = np.dtype(payload[off:off + dlen].decode())
        off += dlen
        shape = _struct.unpack_from(f"<{ndim}I", payload, off)
        off += 4 * ndim
        t = np.asarray(tarr)
        if got_path != path or dtype != t.dtype or shape != t.shape:
            raise RowBlobError(
                f"room blob leaf {got_path!r} ({dtype}{list(shape)}) does "
                f"not match template {path!r} ({t.dtype}{list(t.shape)})")
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        leaves.append(np.frombuffer(
            payload[off:off + nbytes], dtype=dtype).reshape(shape))
        off += nbytes
    if off != len(payload):
        raise RowBlobError("room blob has trailing bytes")
    it = iter(leaves)
    tick, rng = next(it), next(it)
    names = list(class_order) if class_order is not None \
        else sorted(template.classes)
    classes = {}
    for cname in names:
        cs = template.classes[cname]
        n = len(class_row_leaf_items(cs))
        classes[cname] = rebuild_class_state(
            cs, [jnp.asarray(next(it)) for _ in range(n)])
    out = template.replace(
        classes={**template.classes, **classes},
        tick=jnp.asarray(tick), rng=jnp.asarray(rng), aux={},
    )
    got = room_digest(out, class_order)
    if got != digest:
        raise RowBlobError(
            f"room blob digest mismatch: header {digest:#x}, rebuilt "
            f"{got:#x} — refusing to admit a corrupted room")
    return out


# -- host-side slot allocation ----------------------------------------------


class RoomBinPacker:
    """Assigns rooms to device slots by load.

    Slots group into ``n_blocks`` contiguous blocks — one per mesh
    device under the room-major NamedSharding, so "pick a block" IS
    "pick a device".  Policy ``least-loaded`` (default) admits into the
    block with the smallest total load that still has a free slot;
    ``first-fit`` takes the globally lowest free slot (deterministic
    packing for parity tests)."""

    def __init__(self, capacity: int, n_blocks: int = 1,
                 policy: str = "least-loaded"):
        capacity, n_blocks = int(capacity), max(1, int(n_blocks))
        if capacity % n_blocks:
            raise ValueError(
                f"{capacity} slots do not divide into {n_blocks} blocks")
        if policy not in ("least-loaded", "first-fit"):
            raise ValueError(f"unknown packer policy {policy!r}")
        self.capacity = capacity
        self.n_blocks = n_blocks
        self.policy = policy
        self.load = np.zeros(capacity, np.float64)
        self.used = np.zeros(capacity, bool)

    @property
    def block_size(self) -> int:
        return self.capacity // self.n_blocks

    def block_of(self, slot: int) -> int:
        return int(slot) // self.block_size

    @property
    def free_count(self) -> int:
        return int(self.capacity - self.used.sum())

    def block_loads(self) -> np.ndarray:
        return self.load.reshape(self.n_blocks, self.block_size).sum(axis=1)

    def alloc(self, load: float = 1.0) -> int:
        free = ~self.used
        if not free.any():
            raise RoomSlotsFull(self.capacity)
        if self.policy == "first-fit":
            slot = int(np.flatnonzero(free)[0])
        else:
            has_free = free.reshape(self.n_blocks, self.block_size).any(axis=1)
            loads = np.where(has_free, self.block_loads(), np.inf)
            b = int(np.argmin(loads))
            slot = b * self.block_size + int(
                np.flatnonzero(free[b * self.block_size:(b + 1) * self.block_size])[0])
        self.used[slot] = True
        self.load[slot] = float(load)
        return slot

    def free(self, slot: int) -> None:
        # lazy wipe: the slot's device bytes stay as-is (dead rooms are
        # never read; admit overwrites every leaf) — only host book-keeping
        self.used[int(slot)] = False
        self.load[int(slot)] = 0.0

    def set_load(self, slot: int, load: float) -> None:
        self.load[int(slot)] = float(load)

    def grow(self, new_capacity: int, n_blocks: Optional[int] = None) -> None:
        new_capacity = int(new_capacity)
        if new_capacity < self.capacity:
            raise ValueError("packer cannot shrink")
        n_blocks = self.n_blocks if n_blocks is None else int(n_blocks)
        if new_capacity % n_blocks:
            raise ValueError(
                f"{new_capacity} slots do not divide into {n_blocks} blocks")
        pad = new_capacity - self.capacity
        self.load = np.concatenate([self.load, np.zeros(pad)])
        self.used = np.concatenate([self.used, np.zeros(pad, bool)])
        self.capacity = new_capacity
        self.n_blocks = n_blocks


# -- the batched device engine ----------------------------------------------


class RoomBatch:
    """R independent rooms ticking as ONE vmapped program.

    Wraps a built template :class:`Kernel` (any recipe world's kernel);
    its ``_trace_step`` is vmapped over a leading ``[R]`` axis and the
    template's own state/jit entries go unused.  All jit entries ride
    the template's CostBook (``rooms.step`` / ``rooms.run`` /
    ``rooms.admit`` / ``rooms.extract``), slot indices are TRACED
    scalars, and capacity is pow2 — so create/destroy/re-home churn is
    recompile-free and the soak gate ``unexplained_since`` holds."""

    def __init__(self, template: Kernel, capacity: int,
                 mesh: Optional[Mesh] = None, *, seed: int = 0):
        if template.state is None:
            raise RuntimeError("template kernel must be built before "
                               "RoomBatch wraps it")
        self.kernel = template
        template.room_batch = self
        self.capacity = next_pow2(max(1, int(capacity)))
        self.mesh = mesh
        if mesh is not None and self.capacity % mesh.devices.size:
            raise ValueError(
                f"{self.capacity} room slots not divisible by "
                f"{mesh.devices.size} devices")
        self.costbook = template.costbook
        self.tick_count = 0
        self.last_counters: Dict[str, np.ndarray] = {}
        self._seed = int(seed)
        self._jit_step = None
        self._jit_run = None
        self._jit_train = None
        self._train_k = 0
        # train accounting (mirrors Kernel.train_*): dispatches land on
        # the batch, not the template — the template's own entries are
        # unused under a RoomBatch
        self.train_dispatches = 0
        self.train_ticks = 0
        self.train_fetch_bytes = 0
        self._jit_admit = None
        self._jit_extract = None
        self._seen_trace_gen = getattr(template, "_trace_gen", 0)
        template._ensure_aux()
        self._blank = self._blank_room()
        self.state = self._broadcast(self._blank, self.capacity)
        if mesh is not None:
            self.place()

    # ------------------------------------------------------------ state
    def _blank_room(self) -> WorldState:
        """A pristine single-room state: zeroed store + freshly primed
        aux caches — exactly what a just-built recipe world starts from,
        so an admitted room's first tick sees what a fresh single world's
        first tick would."""
        st = self.kernel.store.init_state(self._seed)
        aux = {k: fn() for k, fn in self.kernel._aux_init.items()}
        return st.replace(aux=aux)

    @staticmethod
    def _broadcast(room: WorldState, n: int):
        return jax.tree.map(
            lambda l: jnp.broadcast_to(
                jnp.asarray(l)[None], (n,) + jnp.asarray(l).shape), room)

    def shardings(self):
        from .shard import room_shardings

        return room_shardings(self.state, self.mesh)

    def place(self) -> None:
        self.state = jax.device_put(self.state, self.shardings())

    def _sync_generation(self) -> None:
        """Drop the vmapped traces when the template invalidated, and
        re-blank every aux cache: invalidate() means aux layouts changed
        (bucket resize, grid width), and the next vmapped trace rebuilds
        caches from zeros exactly like a fresh single world would."""
        gen = getattr(self.kernel, "_trace_gen", 0)
        if gen == self._seen_trace_gen:
            return
        self._seen_trace_gen = gen
        self._jit_step = self._jit_run = self._jit_train = None
        self._jit_admit = self._jit_extract = None
        self._blank = self._blank_room()
        for cname in self.kernel.store.class_order:
            want = np.asarray(self._blank.classes[cname].alive).shape[0]
            got = np.asarray(self.state.classes[cname].alive).shape[1]
            if want != got:
                raise RuntimeError(
                    f"store capacity of {cname!r} changed {got}->{want} "
                    "under a live RoomBatch — size recipe capacities so "
                    "auto-resize never fires in batched worlds")
        aux = {k: v for k, v in self.state.aux.items()
               if k not in self.kernel._aux_init}
        aux.update({k: self._broadcast_leafs(v)
                    for k, v in self._blank.aux.items()})
        self.state = self.state.replace(aux=aux)
        if self.mesh is not None:
            self.place()

    def _broadcast_leafs(self, tree):
        n = self.capacity
        return jax.tree.map(
            lambda l: jnp.broadcast_to(
                jnp.asarray(l)[None], (n,) + jnp.asarray(l).shape), tree)

    # ------------------------------------------------------------ ticks
    def _compile_step(self):
        if self._jit_step is not None:
            return self._jit_step
        k = self.kernel

        def vstep(st):
            st2, out = jax.vmap(k._trace_step)(st)
            # only the [R, L] summary survives to the host; everything
            # else (fired masks, diffs, events) is DCE'd like run_device
            return st2, out["summary"]

        jkw = {}
        if self.mesh is not None:
            sh = self.shardings()
            from jax.sharding import NamedSharding, PartitionSpec

            jkw = {"in_shardings": (sh,),
                   "out_shardings": (sh, NamedSharding(
                       self.mesh, PartitionSpec(ROOMS_AXIS)))}
        self._jit_step = self.costbook.wrap(
            "rooms.step", vstep, donate_argnums=0, stage="tick",
            jit_kwargs=jkw)
        return self._jit_step

    def tick(self) -> Dict[str, np.ndarray]:
        """One frame for EVERY room; returns the per-room counter bank
        (name -> [R] int column) decoded off the one summary fetch —
        per-room observability at the same zero-extra-syncs cost as the
        single-world counter bank."""
        self._sync_generation()
        step = self._compile_step()
        self.state, summary = step(self.state)
        self.tick_count += 1
        self.last_counters = self.kernel.decode_counters(np.asarray(summary))
        return self.last_counters

    def run(self, n: int) -> Dict[str, np.ndarray]:
        """n frames for every room, zero host syncs inside (fori_loop
        over the vmapped step, traced trip count — one compile serves
        every n).  The final frame's summary rides the carry out, so
        ``last_counters`` reflects the post-run world instead of going
        stale at the pre-run tick (the r12 bug: a drill sampling
        counters after run() read frame N-n's numbers as frame N's)."""
        self._sync_generation()
        if int(n) <= 0:
            return self.last_counters
        if self._jit_run is None:
            k = self.kernel

            def body(_, carry):
                st, _prev = carry
                st2, out = jax.vmap(k._trace_step)(st)
                return st2, out["summary"]

            def runner(st, t):
                st1, out = jax.vmap(k._trace_step)(st)
                return jax.lax.fori_loop(0, t - 1, body, (st1, out["summary"]))

            jkw = {}
            if self.mesh is not None:
                sh = self.shardings()
                from jax.sharding import NamedSharding, PartitionSpec

                jkw = {"in_shardings": (sh, None),
                       "out_shardings": (sh, NamedSharding(
                           self.mesh, PartitionSpec(ROOMS_AXIS)))}
            self._jit_run = self.costbook.wrap(
                "rooms.run", runner,
                donate_argnums=0, stage="tick", jit_kwargs=jkw)
        self.state, summary = self._jit_run(self.state, jnp.int32(int(n)))
        self.tick_count += int(n)
        self.last_counters = self.kernel.decode_counters(np.asarray(summary))
        return self.last_counters

    # ---------------------------------------------------------- trains
    def configure_train(self, k: int) -> None:
        """Pin the train length (see Kernel.configure_train); the
        template's K is synced so its scan trace matches."""
        self.kernel.configure_train(k)
        if int(k) != self._train_k:
            self._train_k = int(k)
            self._jit_train = None

    def _compile_train(self):
        if self._jit_train is not None:
            return self._jit_train
        if self._train_k < 1:
            raise RuntimeError("configure_train(k) before train()")
        k = self.kernel
        kk = self._train_k

        def vtrain(st):
            # vmap INSIDE the scan: each scanned step advances all R
            # rooms, so the stacked summary comes out [K, R, L] with
            # the room axis sharding preserved on axis 1.  Only the
            # summary lane survives to the host — the rooms engine's
            # whole per-tick observed surface IS the counter bank
            # (rooms.step makes the same reduction), so fired/diff/
            # event lanes are DCE'd, not lost.
            def body(s, _):
                s2, out = jax.vmap(k._trace_step)(s)
                return s2, out["summary"]

            return jax.lax.scan(body, st, None, length=kk)

        jkw = {}
        if self.mesh is not None:
            sh = self.shardings()
            from jax.sharding import NamedSharding, PartitionSpec

            jkw = {"in_shardings": (sh,),
                   "out_shardings": (sh, NamedSharding(
                       self.mesh, PartitionSpec(None, ROOMS_AXIS)))}
        self._jit_train = self.costbook.wrap(
            "rooms.train", vtrain, donate_argnums=0, stage="tick",
            jit_kwargs=jkw)
        return self._jit_train

    def train(self, n: int) -> np.ndarray:
        """n frames for every room in ⌊n/K⌋ megadispatches plus a
        per-tick ragged tail; per-tick per-room counters survive as
        stacked ``[K, R, L]`` summary lanes fetched ONCE per train.

        Returns the concatenated ``[n, R, L]`` summary (one row per
        logical tick, in order — decode with ``kernel.decode_counters``
        for per-tick ``[R]`` counter columns, including the in-lane
        "tick" stamp and, when enabled, "state_digest").
        ``last_counters`` lands on the final frame."""
        self._sync_generation()
        n = int(n)
        kk = self._train_k
        if kk < 1:
            raise RuntimeError("configure_train(k) before train()")
        jt = self._compile_train()
        lanes: List[np.ndarray] = []
        for _ in range(n // kk):
            self.state, stacked = jt(self.state)
            self.tick_count += kk
            self.train_dispatches += 1
            self.train_ticks += kk
            arr = np.asarray(stacked)  # ONE [K, R, L] fetch per train
            self.train_fetch_bytes += arr.nbytes
            lanes.append(arr)
        for _ in range(n % kk):
            step = self._compile_step()
            self.state, summary = step(self.state)
            self.tick_count += 1
            lanes.append(np.asarray(summary)[None])
        out = (np.concatenate(lanes, axis=0) if lanes
               else np.zeros((0, self.capacity, 0), np.int32))
        if len(out):
            self.last_counters = self.kernel.decode_counters(out[-1])
        return out

    # ---------------------------------------------------- slot plumbing
    def _room_payload(self, room: WorldState) -> WorldState:
        """A full room pytree structurally matching one batched lane:
        the room's packed leaves + FRESH aux caches (blank for
        registered entries, zeros for trace-added ones like migration
        stats) — the admit scatter is then one tree_map."""
        aux = {}
        for key, cur in self.state.aux.items():
            if key in self._blank.aux:
                aux[key] = self._blank.aux[key]
            else:
                aux[key] = jax.tree.map(
                    lambda l: jnp.zeros(l.shape[1:], l.dtype), cur)
        for cname in self.kernel.store.class_order:
            want = np.asarray(self._blank.classes[cname].alive).shape[0]
            got = np.asarray(room.classes[cname].alive).shape[0]
            if want != got:
                raise ValueError(
                    f"admitted room's {cname!r} capacity {got} != batch "
                    f"template {want} — recipes must share StoreConfig")
        return room.replace(aux=aux)

    def admit(self, slot: int, room: WorldState) -> int:
        """Scatter one room's state into ``slot``.  Full-leaf overwrite:
        whatever the slot held before (a destroyed room's remains — lazy
        wipe) is unreadable afterwards.  The slot index is a traced
        scalar, so admitting to any slot reuses one compiled scatter."""
        self._sync_generation()
        if self._jit_admit is None:
            self._jit_admit = self.costbook.wrap(
                "rooms.admit",
                lambda b, r, s: jax.tree.map(
                    lambda bb, ll: bb.at[s].set(ll), b, r),
                donate_argnums=0, stage="tick")
        payload = self._room_payload(room)
        self.state = self._jit_admit(self.state, payload, jnp.int32(int(slot)))
        return int(slot)

    def extract(self, slot: int) -> WorldState:
        """Gather one room's full state (aux included) off the batch;
        traced slot index — one compiled gather serves every slot."""
        self._sync_generation()
        if self._jit_extract is None:
            self._jit_extract = self.costbook.wrap(
                "rooms.extract",
                lambda b, s: jax.tree.map(lambda bb: bb[s], b),
                stage="tick")
        return self._jit_extract(self.state, jnp.int32(int(slot)))

    def digest(self, slot: int,
               ident_cols: Optional[Dict[str, int]] = None) -> int:
        return room_digest(self.extract(slot),
                           self.kernel.store.class_order, ident_cols)

    def pack_blob(self, slot: int) -> bytes:
        return pack_room_blob(self.extract(slot),
                              self.kernel.store.class_order)

    def admit_blob(self, slot: int, blob: bytes) -> int:
        """Admit a framed room blob — the re-home landing path, and the
        cross-engine door: a single-world snapshot packed by
        ``pack_room_blob(world.kernel.state, ...)`` loads into a slot."""
        room = unpack_room_blob(blob, self._blank,
                                self.kernel.store.class_order)
        return self.admit(slot, room)

    def rehome(self, src: int, dst: int) -> int:
        """Move a room between slots (and thus devices) as a framed,
        digest-carrying blob; the source slot is NOT wiped (lazy) — the
        caller frees it in its packer."""
        if int(src) == int(dst):
            raise ValueError(f"re-home src == dst slot {src}")
        blob = self.pack_blob(src)
        return self.admit_blob(dst, blob)

    # ------------------------------------------------------------- grow
    def grow(self, new_capacity: int) -> int:
        """Double (at least) the slot bank — the ONE sanctioned retrace
        of room churn, announced via ``generation_bump`` exactly like
        the combat bucket resize, so the soak gate stays clean."""
        new_cap = next_pow2(max(int(new_capacity), self.capacity + 1))
        if self.mesh is not None and new_cap % self.mesh.devices.size:
            raise ValueError(
                f"{new_cap} slots not divisible by mesh width")
        self.costbook.generation_bump(
            f"rooms_grow:{self.capacity}->{new_cap}")
        pad = new_cap - self.capacity
        blank_pad = self._broadcast(self._blank, pad)

        def widen(cur, pad_leaf):
            return jnp.concatenate([cur, pad_leaf], axis=0)

        aux = {}
        for key, cur in self.state.aux.items():
            if key in self._blank.aux:
                aux[key] = jax.tree.map(widen, cur, blank_pad.aux[key])
            else:
                aux[key] = jax.tree.map(
                    lambda l: jnp.concatenate(
                        [l, jnp.zeros((pad,) + l.shape[1:], l.dtype)],
                        axis=0), cur)
        self.state = self.state.replace(
            classes=jax.tree.map(widen, dict(self.state.classes),
                                 dict(blank_pad.classes)),
            tick=widen(self.state.tick, blank_pad.tick),
            rng=widen(self.state.rng, blank_pad.rng),
            aux=aux,
        )
        self.capacity = new_cap
        self._jit_step = self._jit_run = None
        self._jit_admit = self._jit_extract = None
        if self.mesh is not None:
            self.place()
        return new_cap


# -- host directory: room ids, packing, controls, metrics -------------------


class RoomDirectory:
    """The host face of the many-worlds engine: room ids -> slots.

    ``recipe(seed)`` builds one fresh single-room world (a GameWorld or
    a bare built Kernel); room 0's build becomes the vmap TEMPLATE.
    create/destroy/re-home recycle slots through the bin-packer;
    ``attach_control`` keeps a room's recipe world alive and ticks it in
    LOCKSTEP with the batch — the parity oracle drill's RoomIsolation
    invariant compares per-room digests against."""

    def __init__(self, recipe: Callable[[int], Any],
                 capacity: Optional[int] = None,
                 mesh: Optional[Mesh] = None, *,
                 template_seed: int = 0,
                 policy: str = "least-loaded",
                 registry: Optional[Any] = None):
        if capacity is None:
            capacity = int(os.environ.get(ENV_ROOM_SLOTS, "16"))
        self._recipe = recipe
        template = self._kernel_of(recipe(template_seed))
        self.batch = RoomBatch(template, capacity, mesh=mesh,
                               seed=template_seed)
        n_blocks = mesh.devices.size if mesh is not None else 1
        self.packer = RoomBinPacker(self.batch.capacity, n_blocks,
                                    policy=policy)
        self.rooms: Dict[int, int] = {}  # room_id -> slot
        self.seeds: Dict[int, int] = {}  # room_id -> recipe seed
        self.controls: Dict[int, Any] = {}  # room_id -> lockstep world
        self._next_room_id = 1
        self.created = 0
        self.destroyed = 0
        self.rehomed = 0
        self._metrics = None
        if registry is not None:
            self._metrics = {
                "active": registry.gauge(
                    "nf_rooms_active", "rooms currently admitted"),
                "slots_free": registry.gauge(
                    "nf_rooms_slots_free", "free room slots"),
                "created": registry.counter(
                    "nf_rooms_created_total", "rooms created"),
                "destroyed": registry.counter(
                    "nf_rooms_destroyed_total", "rooms destroyed"),
                "rehomed": registry.counter(
                    "nf_rooms_rehomed_total", "room re-homes"),
            }
            self._publish()

    @staticmethod
    def _kernel_of(world: Any) -> Kernel:
        return world if isinstance(world, Kernel) else world.kernel

    @staticmethod
    def _load_of(state: WorldState) -> float:
        return float(sum(int(np.asarray(cs.alive).sum())
                         for cs in state.classes.values()))

    def _publish(self) -> None:
        if self._metrics is None:
            return
        self._metrics["active"].set(len(self.rooms))
        self._metrics["slots_free"].set(self.packer.free_count)

    # ----------------------------------------------------------- churn
    def create_room(self, seed: Optional[int] = None,
                    room_id: Optional[int] = None,
                    control: bool = False) -> int:
        """Build a fresh room from the recipe and admit it into the
        least-loaded free slot.  With ``control=True`` the recipe world
        stays alive host-side and ``tick``/``run`` advance it in
        lockstep — the independent oracle for isolation/parity gates."""
        if room_id is None:
            room_id = self._next_room_id
            self._next_room_id += 1
        room_id = int(room_id)
        if room_id in self.rooms:
            raise ValueError(f"room {room_id} already exists")
        seed = int(seed) if seed is not None else room_id
        world = self._recipe(seed)
        k = self._kernel_of(world)
        k._ensure_aux()
        slot = self.packer.alloc(load=self._load_of(k.state))
        self.batch.admit(slot, k.state)
        self.rooms[room_id] = slot
        self.seeds[room_id] = seed
        if control:
            self.controls[room_id] = world
        self.created += 1
        if self._metrics is not None:
            self._metrics["created"].inc()
        self._publish()
        return room_id

    def destroy_room(self, room_id: int) -> int:
        """Free the room's slot (lazy wipe — admit's full overwrite is
        the only writer a recycled slot ever needs)."""
        slot = self.rooms.pop(int(room_id))
        self.seeds.pop(int(room_id), None)
        self.controls.pop(int(room_id), None)
        self.packer.free(slot)
        self.destroyed += 1
        if self._metrics is not None:
            self._metrics["destroyed"].inc()
        self._publish()
        return slot

    def rehome_room(self, room_id: int) -> Tuple[int, int]:
        """Move a room to the (now) least-loaded block's free slot via
        the framed blob path; returns (old_slot, new_slot)."""
        room_id = int(room_id)
        src = self.rooms[room_id]
        load = float(self.packer.load[src])
        dst = self.packer.alloc(load=load)
        try:
            self.batch.rehome(src, dst)
        except Exception:
            self.packer.free(dst)
            raise
        self.packer.free(src)
        self.rooms[room_id] = dst
        self.rehomed += 1
        if self._metrics is not None:
            self._metrics["rehomed"].inc()
        self._publish()
        return src, dst

    def grow(self, new_capacity: Optional[int] = None) -> int:
        cap = self.batch.grow(new_capacity or self.batch.capacity * 2)
        self.packer.grow(cap)
        self._publish()
        return cap

    # ----------------------------------------------------------- ticks
    def tick(self) -> Dict[str, np.ndarray]:
        """One frame for every room + every lockstep control."""
        counters = self.batch.tick()
        for world in self.controls.values():
            self._kernel_of(world).run_device(1, reconcile=False)
        return counters

    def run(self, n: int) -> None:
        self.batch.run(n)
        for world in self.controls.values():
            self._kernel_of(world).run_device(int(n), reconcile=False)

    # ---------------------------------------------------------- oracle
    def slot_of(self, room_id: int) -> int:
        return self.rooms[int(room_id)]

    def digest(self, room_id: int) -> int:
        return self.batch.digest(self.rooms[int(room_id)])

    def control_digest(self, room_id: int) -> int:
        world = self.controls[int(room_id)]
        k = self._kernel_of(world)
        return room_digest(k.state, k.store.class_order)

    def status(self) -> Dict[str, Any]:
        """Heartbeat/`/json` blob: totals + per-room occupancy."""
        return {
            "capacity": self.batch.capacity,
            "active": len(self.rooms),
            "slots_free": self.packer.free_count,
            "created": self.created,
            "destroyed": self.destroyed,
            "rehomed": self.rehomed,
            "tick": self.batch.tick_count,
            "policy": self.packer.policy,
            "blocks": self.packer.n_blocks,
            "occupancy": {
                str(rid): {"slot": slot,
                           "block": self.packer.block_of(slot),
                           "load": float(self.packer.load[slot])}
                for rid, slot in sorted(self.rooms.items())
            },
        }
