"""Multi-host bootstrap: the Master role as the jax.distributed rendezvous.

The reference scales across hosts by every server process dialing the
Master for registration and discovery (SURVEY §3.5).  The TPU build's
data plane scales the same way conceptually, but the transport is the
JAX distributed runtime: each host process calls
``jax.distributed.initialize(coordinator, num_processes, process_id)``,
after which ``jax.devices()`` spans the whole pod and the standard
``make_mesh()``/``ShardedKernel`` path shards the world over ICI/DCN
with XLA collectives — no NCCL/MPI, no hand-rolled relay hop.

What this module adds:

- :func:`init_distributed` — env-aware wrapper over
  ``jax.distributed.initialize`` (honours the standard
  ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``
  variables, no-ops cleanly for single-process runs).
- :func:`global_mesh` — a mesh over every device in the initialized
  process group (locals + remotes).
- Master-backed rendezvous: :meth:`MasterRole hosts /dist <register_dist>`
  so worker hosts can discover (coordinator, num_processes, process_id)
  from the same place they already register their server roles —
  :func:`rendezvous_via_master` polls it until the expected host count
  has arrived, mirroring the reference's "start all, watch the master
  go green" bring-up.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request
from typing import Dict, Optional, Tuple

from .mesh import SHARD_AXIS


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the JAX distributed runtime (idempotent).

    Arguments default to the standard env vars; with one process (or no
    configuration at all) this is a no-op and single-host behavior is
    unchanged.  Returns True when a multi-process group was joined."""
    import jax

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    if num_processes <= 1 or coordinator is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(axis: str = SHARD_AXIS):
    """1-D mesh over EVERY device of the process group (after
    init_distributed, that includes remote hosts' chips)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis,))


class DistRendezvous:
    """Host-side assignment table the Master serves at /dist.

    First registrant's announced endpoint becomes the coordinator;
    process ids are dense in arrival order.  `expected` is the pod's
    host-process count (from deployment config, like MaxOnline rows in
    Server.xml)."""

    def __init__(self, expected: int) -> None:
        self.expected = int(expected)
        self._procs: Dict[str, int] = {}
        self._coordinator: Optional[str] = None

    def register(self, host_key: str, coord_endpoint: str) -> dict:
        if host_key not in self._procs:
            if len(self._procs) >= self.expected:
                return {"error": "pod full", "expected": self.expected}
            self._procs[host_key] = len(self._procs)
            if self._coordinator is None:
                self._coordinator = coord_endpoint
        return self.view(host_key)

    def view(self, host_key: Optional[str] = None) -> dict:
        out = {
            "coordinator": self._coordinator,
            "num_processes": self.expected,
            "registered": len(self._procs),
            "ready": len(self._procs) >= self.expected,
        }
        if host_key is not None and host_key in self._procs:
            out["process_id"] = self._procs[host_key]
        return out


def serve_dist(master_role, expected: int) -> DistRendezvous:
    """Attach the /dist rendezvous endpoint to a MasterRole's HTTP
    server: GET /dist?host=<key>&coord=<ip:port> registers and returns
    the assignment; GET /dist reports status."""
    rz = DistRendezvous(expected)

    def handler(_path: str, params: Dict[str, str]) -> dict:
        host = params.get("host")
        coord = params.get("coord", "")
        if host:
            return rz.register(host, coord)
        return rz.view()

    master_role.http.route("/dist", handler)
    return rz


def rendezvous_via_master(
    master_http: str,
    host_key: str,
    coord_endpoint: str,
    timeout_s: float = 60.0,
    poll_s: float = 0.5,
) -> Tuple[str, int, int]:
    """Register with the master's /dist endpoint and wait until every
    expected host has arrived.  Returns (coordinator, num_processes,
    process_id) ready to hand to init_distributed."""
    base = f"http://{master_http}/dist?host={host_key}&coord={coord_endpoint}"
    deadline = time.monotonic() + timeout_s
    assignment = None
    while time.monotonic() < deadline:
        with urllib.request.urlopen(base, timeout=5) as r:
            assignment = json.loads(r.read())
        if "error" in assignment:
            raise RuntimeError(f"dist rendezvous refused: {assignment}")
        if assignment.get("ready"):
            return (
                assignment["coordinator"],
                int(assignment["num_processes"]),
                int(assignment["process_id"]),
            )
        time.sleep(poll_s)
    raise TimeoutError(
        f"dist rendezvous incomplete after {timeout_s}s: {assignment}"
    )
