"""Spatially-sharded combat core: slab partition, halo exchange, migration.

The default sharded world (`parallel/shard.py`) shards the ENTITY axis
and lets XLA partition the cell-table argsort — correct, but the
partitioned sort is a global all-to-all every tick and was the round-3
sharded-compile/latency hotspot.  This module is the TPU-first
alternative the round-4 verdict asked to explore: partition SPACE, not
rows.

Design (scaling-book recipe: pick a mesh, keep collectives O(boundary)):

- The [width x width] cell grid is cut into `n_shards` horizontal slabs
  of `slab_h` cell rows; shard i owns slab i and the entities inside it.
- Each tick, every shard builds its OWN cell table (argsort over
  capacity/n_shards rows — the sort shrinks with the mesh instead of
  becoming a distributed sort).
- The 3x3 stencil fold needs attacker candidates from the one cell row
  beyond each slab edge: shards exchange their edge attacker PLANES
  ([1, W, K_att, F] — dense, fixed-size) with both neighbors via
  `lax.ppermute`, then fold locally over [slab_h + 2] rows.  Bytes on
  the wire per tick are O(W * K_att), independent of entity count.
- Entities whose cell crossed a slab boundary MIGRATE: up to
  `mig_budget` rows per direction per tick are packed, `ppermute`d to
  the neighbor shard, and scattered into free bank slots — real
  cross-shard migration (BASELINE config 5), with overflow counters
  when the budget or the destination bank is full.  A row that could
  not migrate stays home and simply misses combat that tick (counted,
  like a cell-bucket overflow) and retries next tick.

Damage semantics are bit-identical to the single-device engine: the
fold body is game.combat.combat_fold_closure (shared, not copied), the
attacker `row` payload column carries the GLOBAL entity gid, damage
sums are exact int32 in f32 (< 2^24), and tie-breaks reduce over gid —
so within migration/bucket budgets, spatial and single-device worlds
produce identical HP trajectories (tests/test_spatial.py pins this).

Reference contrast: NFCWorldNet_ServerModule.cpp:600-830 re-homes
players between game servers through the World relay (serialize,
destroy, recreate); here migration is two fixed-size collectives inside
the jitted tick and visibility across the boundary is a dense halo, not
a relay hop.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..game.combat import combat_fold_closure
from ..ops.stencil import binning_mode, build_cell_table_pair, pull
from ..ops.verlet import VerletCache, full_table, refresh, sub_table
from .mesh import SHARD_AXIS, make_mesh

# jax.shard_map landed as a top-level API (with check_vma) after 0.4.x;
# older releases spell it jax.experimental.shard_map with check_rep.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SM_KW = {"check_vma": False}
else:  # pragma: no cover - exercised on jax<0.6 only
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_KW = {"check_rep": False}


class SpatialGeom(NamedTuple):
    """Static geometry of the spatially-sharded world."""

    extent: float          # world is [0, extent)^2
    cell_size: float
    width: int             # cells per axis; grid [width, width]
    n_shards: int          # horizontal slabs; width % n_shards == 0
    bucket: int            # victim slots per cell
    att_bucket: int        # attacker slots per cell
    radius: float          # AoE radius (<= cell_size)
    mig_budget: int        # migrant rows per direction per shard per tick
    speed: float = 0.5     # random-walk step per tick (< cell_size)
    attack_period: int = 30  # a gid attacks every `attack_period` ticks
    # the rest of the benchmark phase chain (0 disables either):
    regen_per_tick: int = 0   # hp regained per tick while alive
    hp_max: int = 0           # regen/respawn ceiling (0 = no ceiling)
    respawn_ticks: int = 0    # dead rows revive at hp_max after this many
    # Verlet skin (ops/verlet.py): > 0 gates the per-slab sort+build on
    # accumulated displacement.  Requires cell_size >= radius + skin.
    # Any tick that migrates a row (or strands one mid-hop) changes the
    # in-slab mask and forces a rebuild, so the win concentrates in ticks
    # where no entity crosses a slab boundary.
    skin: float = 0.0

    @property
    def slab_h(self) -> int:
        return self.width // self.n_shards


class SpatialState(NamedTuple):
    """Per-entity banks, leading axis = n_shards * bank_size, sharded
    row-wise so shard i holds rows [i*bank : (i+1)*bank]."""

    pos: jnp.ndarray     # [cap, 2] f32
    hp: jnp.ndarray      # [cap] i32
    atk: jnp.ndarray     # [cap] i32
    camp: jnp.ndarray    # [cap] i32
    gid: jnp.ndarray     # [cap] i32 — stable global id, rides migration
    died: jnp.ndarray    # [cap] i32 — tick of death, -1 while alive
    active: jnp.ndarray  # [cap] bool
    # Verlet cache leaves (geom.skin > 0; carried zeros otherwise).
    # Flattened VerletCache so the whole state stays one NamedTuple of
    # row-sharded banks (cstat: [n_shards, 3] = rebuilds/reuses/age,
    # one [1, 3] row per shard).
    vc_pos: jnp.ndarray      # [cap, 2] f32 — anchor positions
    vc_active: jnp.ndarray   # [cap] bool  — anchor in-slab mask
    vc_order: jnp.ndarray    # [cap] i32
    vc_skey: jnp.ndarray     # [cap] i32
    vc_slot: jnp.ndarray     # [cap] i32
    cstat: jnp.ndarray       # [n_shards, 3] i32


def _walk(pos, gid, tick, geom: SpatialGeom):
    """Deterministic per-gid random walk — a pure function of (gid,
    tick), so every shard placement computes the identical trajectory
    (the parity tests rely on this).  The murmur3-style finalizer
    matters: a LINEAR hash of (gid, tick) rotates each heading by a
    constant ~0.9 deg/tick, producing near-straight paths that stick to
    the clipped world walls and pile entire populations into corner
    cells within ~100 ticks."""
    h = (gid.astype(jnp.uint32) * jnp.uint32(2654435761)
         + jnp.uint32(tick) * jnp.uint32(40503))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    ang = (h >> 8).astype(jnp.float32) * (2.0 * np.pi / float(1 << 24))
    step = jnp.stack([jnp.cos(ang), jnp.sin(ang)], -1) * geom.speed
    eps = 1e-3
    return jnp.clip(pos + step, eps, geom.extent - eps)


def _pack_rows(sel, rank, budget, *arrays):
    """Gather up to `budget` selected rows into fixed [budget] buffers.
    sel: [n] bool, rank: [n] exclusive rank among selected.  Returns
    (valid [budget] bool, packed arrays)."""
    n = sel.shape[0]
    idx = jnp.where(sel & (rank < budget), rank, budget)
    valid = jnp.zeros((budget + 1,), bool).at[idx].set(sel)[:budget]
    out = []
    for a in arrays:
        buf_shape = (budget + 1,) + a.shape[1:]
        out.append(jnp.zeros(buf_shape, a.dtype).at[idx].set(a)[:budget])
    return valid, out


def _life_phases(geom: SpatialGeom, hp, died, incoming, tick):
    """Damage -> death mark -> regen -> respawn, shared verbatim by the
    spatial tick and the single-device parity oracle (pure elementwise,
    placement-invariant)."""
    hp_after = jnp.maximum(hp - incoming, 0)
    died = jnp.where((hp > 0) & (hp_after == 0), tick, died)
    if geom.regen_per_tick > 0:
        regen = jnp.where(hp_after > 0, hp_after + geom.regen_per_tick,
                          hp_after)
        if geom.hp_max > 0:
            regen = jnp.minimum(regen, geom.hp_max)
        hp_after = regen
    if geom.respawn_ticks > 0:
        revive = (
            (hp_after == 0) & (died >= 0)
            & (tick - died >= geom.respawn_ticks)
        )
        hp_after = jnp.where(revive, geom.hp_max, hp_after)
        died = jnp.where(revive, -1, died)
    return hp_after, died


def _spatial_body(geom: SpatialGeom, axis, pos, hp, atk, camp, gid, died,
                  active, vc_pos, vc_active, vc_order, vc_skey, vc_slot,
                  cstat, tick):
    """One tick on one shard (runs under shard_map; arrays are the
    shard-local banks)."""
    n = geom.n_shards
    hs = geom.slab_h
    w = geom.width
    me = jax.lax.axis_index(axis)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]

    # -- movement (identical math on any placement) ----------------------
    pos = _walk(pos, gid, tick, geom)

    cx = jnp.clip((pos[:, 0] / geom.cell_size).astype(jnp.int32), 0, w - 1)
    cy = jnp.clip((pos[:, 1] / geom.cell_size).astype(jnp.int32), 0, w - 1)
    owner = cy // hs

    # -- migration: one budgeted ppermute per direction ------------------
    migrated = jnp.int32(0)
    mig_overflow = jnp.int32(0)
    mig_dropped = jnp.int32(0)
    banks = (pos, hp, atk, camp, gid, died)
    for d, perm in ((1, fwd), (-1, bwd)):
        # direction of travel, not exact neighbor: a row stranded 2+
        # slabs from home (sustained budget overflow, or a teleport)
        # hops one slab toward its owner per tick until it arrives —
        # otherwise it would be excluded from combat forever
        m = active & ((owner > me) if d == 1 else (owner < me))
        # destination capacity vote: each shard advertises its free-slot
        # count BEFORE clearing its own outbound rows (so the advertised
        # number only understates reality), and the sender clamps its
        # send to it — a row that would find no slot stays home and
        # retries instead of leaving the source bank and being destroyed
        # in flight.  Receiving the successor's count means permuting
        # values BACKWARD (each shard sends its count to its predecessor).
        free_cnt = jnp.sum(~active, dtype=jnp.int32)
        remote_free = jax.lax.ppermute(
            free_cnt, axis, bwd if d == 1 else fwd
        )
        cap_d = jnp.minimum(jnp.int32(geom.mig_budget), remote_free)
        csum = jnp.cumsum(m.astype(jnp.int32))
        sel = m & (csum <= cap_d)
        migrated = migrated + jnp.sum(sel, dtype=jnp.int32)
        mig_overflow = mig_overflow + jnp.sum(m, dtype=jnp.int32) - jnp.sum(
            sel, dtype=jnp.int32
        )
        valid, packed = _pack_rows(sel, csum - 1, geom.mig_budget, *banks)
        rvalid = jax.lax.ppermute(valid, axis, perm)
        rpacked = [jax.lax.ppermute(b, axis, perm) for b in packed]
        # wrap-around sends are impossible (owner is clipped into range),
        # but mask the circular receive anyway for edge shards
        sender_ok = (me - d >= 0) & (me - d < n)
        rvalid = rvalid & sender_ok
        active = active & ~sel
        # insert into free slots: dest[j] = row index of the j-th free slot
        free = ~active
        frank = jnp.cumsum(free.astype(jnp.int32)) - free.astype(jnp.int32)
        slots = jnp.where(free & (frank < geom.mig_budget), frank,
                          geom.mig_budget)
        dest = (
            jnp.full((geom.mig_budget + 1,), pos.shape[0], jnp.int32)
            .at[slots]
            .set(jnp.arange(pos.shape[0], dtype=jnp.int32))[: geom.mig_budget]
        )
        dest_j = jnp.where(rvalid, dest, pos.shape[0])
        # should-never-fire assertion counter: the sender clamped to our
        # advertised free count, so every arriving row has a slot; any
        # nonzero here is a protocol bug, not expected overflow
        mig_dropped = mig_dropped + jnp.sum(
            rvalid & (dest_j >= pos.shape[0]), dtype=jnp.int32
        )
        new_banks = []
        for cur, rb in zip(banks, rpacked):
            new_banks.append(cur.at[dest_j].set(rb, mode="drop"))
        pos, hp, atk, camp, gid, died = new_banks
        active = active.at[dest_j].set(True, mode="drop")
        banks = (pos, hp, atk, camp, gid, died)
        # re-derive cells for rows that just arrived
        cx = jnp.clip((pos[:, 0] / geom.cell_size).astype(jnp.int32), 0, w - 1)
        cy = jnp.clip((pos[:, 1] / geom.cell_size).astype(jnp.int32), 0, w - 1)
        owner = cy // hs

    # -- local cell tables over the slab ---------------------------------
    in_slab = active & (owner == me)
    misplaced = jnp.sum(active & (owner != me), dtype=jnp.int32)
    cell_local = (cy - me * hs) * w + cx
    f32 = jnp.float32
    camp_f = camp.astype(f32)
    zeros_f = jnp.zeros_like(camp_f)
    vic_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], camp_f, zeros_f, zeros_f], -1
    )
    attacking = (
        in_slab
        & (hp > 0)
        & ((gid + tick) % geom.attack_period == 0)
    )
    eff_atk = jnp.where(attacking, atk, 0).astype(f32)
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], eff_atk, camp_f, zeros_f, zeros_f,
         gid.astype(f32)],
        -1,
    )
    if geom.skin > 0.0:
        # displacement-gated build (ops/verlet.py): the anchor mask is the
        # in-slab set, so any migration/straggler flip forces a rebuild —
        # and the vote is pmax'd over the mesh so every shard's carried
        # cache takes the same branch.  cell_local is derived from the
        # same positions passed to refresh, as its contract requires.
        cache = VerletCache(
            anchor_pos=vc_pos, anchor_active=vc_active, order=vc_order,
            skey=vc_skey, slot_of=vc_slot,
            rebuilds=cstat[0, 0], reuses=cstat[0, 1], age=cstat[0, 2],
        )
        cache, _rebuilt = refresh(
            cache, pos, in_slab, geom.cell_size, w, geom.bucket, geom.skin,
            cell=cell_local, n_cells=hs * w, height=hs, axis_name=axis,
        )
        vic_t = full_table(
            cache, vic_feats, in_slab, hs * w, geom.cell_size, w,
            geom.bucket, height=hs,
        )
        att_t = sub_table(
            cache, attacking, att_feats, hs * w, geom.cell_size, w,
            geom.att_bucket, height=hs,
        )
        vc_pos, vc_active = cache.anchor_pos, cache.anchor_active
        vc_order, vc_skey, vc_slot = cache.order, cache.skey, cache.slot_of
        cstat = jnp.stack([cache.rebuilds, cache.reuses, cache.age])[None, :]
    else:
        vic_t, att_t = build_cell_table_pair(
            pos, in_slab, vic_feats, attacking, att_feats,
            geom.cell_size, w, geom.bucket, geom.att_bucket,
            cell=cell_local, height=hs,
        )

    # -- halo exchange: one dense attacker plane per edge ----------------
    ag = att_t.grid_view()  # [hs, w, K_att, F+1]
    halo_top = jax.lax.ppermute(ag[hs - 1:hs], axis, fwd)   # prev's bottom
    halo_bot = jax.lax.ppermute(ag[0:1], axis, bwd)          # next's top
    halo_top = jnp.where(me > 0, halo_top, jnp.zeros_like(halo_top))
    halo_bot = jnp.where(me < n - 1, halo_bot, jnp.zeros_like(halo_bot))
    ag_h = jnp.concatenate([halo_top, ag, halo_bot], axis=0)  # [hs+2, ...]

    # -- fold: same body as the single-chip engine, halo-aware walk ------
    fold, init = combat_fold_closure(vic_t.grid_view(), geom.radius)
    agp = jnp.pad(ag_h, ((0, 0), (1, 1), (0, 0), (0, 0)))
    acc = init
    for dy in (0, 1, 2):  # (dy, dx) ascending == ops.stencil.STENCIL order
        for dx in (0, 1, 2):
            cand = jax.lax.slice(
                agp, (dy, dx, 0, 0),
                (dy + hs, dx + w, agp.shape[2], agp.shape[3]),
            )
            acc = fold(acc, cand)
    inc, _besta, _bestr = acc

    # -- damage -----------------------------------------------------------
    pulled = pull(vic_t, inc, fill=0)
    incoming = jnp.where(in_slab & (hp > 0), pulled, 0)
    hp, died = _life_phases(geom, hp, died, incoming, tick)

    # columns: migrated, mig_overflow (budget), mig_dropped (no free
    # slot), misplaced (awaiting retry), vic/att cell-bucket drops
    stats = jnp.stack(
        [migrated, mig_overflow, mig_dropped, misplaced,
         vic_t.dropped, att_t.dropped]
    )[None, :]  # [1, 6] per shard -> [n_shards, 6] outside
    return (pos, hp, atk, camp, gid, died, active,
            vc_pos, vc_active, vc_order, vc_skey, vc_slot, cstat, stats)


class SpatialWorld:
    """Host wrapper: placement, compiled step, counters.

    Usage:
        geom = SpatialGeom(...)
        world = SpatialWorld(geom)            # makes its own mesh
        world.place(pos, hp, atk, camp)       # numpy rows, any order
        world.step()                          # one jitted sharded tick
        world.gather()                        # {gid -> (pos, hp)} to host
    """

    def __init__(self, geom: SpatialGeom, mesh: Optional[Mesh] = None,
                 bank_size: Optional[int] = None):
        if geom.width % geom.n_shards:
            raise ValueError("width must divide into n_shards slabs")
        if geom.skin > 0.0 and geom.cell_size < geom.radius + geom.skin:
            raise ValueError(
                f"Verlet skin {geom.skin} needs cell_size >= radius + skin "
                f"({geom.radius + geom.skin}), got {geom.cell_size}"
            )
        self.geom = geom
        self.mesh = mesh if mesh is not None else make_mesh(geom.n_shards)
        self.axis = SHARD_AXIS
        self.bank_size = bank_size
        self.state: Optional[SpatialState] = None
        self.tick_count = 0
        self.stats_last = np.zeros((geom.n_shards, 6), np.int32)
        self.overflow_budget = 1e-4  # alert threshold, as CombatModule
        self.overflow_alerts = 0
        # crowding response, ported from CombatModule._on_overflow: when
        # cell-bucket drops breach the budget, double both buckets
        # (bounded) and retrace — silent drops stop instead of repeating
        # every tick (r05_sharded_4m saw grid_overflow_max=374/tick).
        self.auto_resize = True
        self.max_bucket_boost = 8
        self._bucket_boost = 1
        self._step = None
        # standalone cost ledger (the slab runs kernel-less); benches and
        # tests read world.costbook directly
        from ..telemetry.costbook import CostBook

        self.costbook = CostBook()

    # -- placement --------------------------------------------------------
    def place(self, pos: np.ndarray, hp: np.ndarray, atk: np.ndarray,
              camp: np.ndarray) -> None:
        """Distribute entities into per-shard banks by their slab.

        Vectorized: one stable argsort by owning shard, per-shard base
        offsets, and a single fancy-index write per bank — the previous
        per-entity Python loop was O(n) interpreter work at placement
        (minutes at 1M rows)."""
        g = self.geom
        n = pos.shape[0]
        cy = np.clip((pos[:, 1] / g.cell_size).astype(np.int32), 0,
                     g.width - 1)
        owner = cy // g.slab_h
        counts = np.bincount(owner, minlength=g.n_shards)
        bank = self.bank_size or int(1 << int(np.ceil(np.log2(
            max(counts.max() * 2, 64)))))
        over = np.flatnonzero(counts > bank)
        if over.size:
            raise ValueError(f"bank {int(over[0])} overflow at placement")
        cap = bank * g.n_shards
        st = SpatialState(
            pos=np.zeros((cap, 2), np.float32),
            hp=np.zeros((cap,), np.int32),
            atk=np.zeros((cap,), np.int32),
            camp=np.zeros((cap,), np.int32),
            gid=np.full((cap,), -1, np.int32),
            died=np.full((cap,), -1, np.int32),
            active=np.zeros((cap,), bool),
            vc_pos=np.zeros((cap, 2), np.float32),
            vc_active=np.zeros((cap,), bool),
            vc_order=np.zeros((cap,), np.int32),
            vc_skey=np.zeros((cap,), np.int32),
            vc_slot=np.zeros((cap,), np.int32),
            cstat=np.zeros((g.n_shards, 3), np.int32),
        )
        if n:
            order = np.argsort(owner, kind="stable")
            so = owner[order]
            starts = np.zeros(g.n_shards, np.int64)
            starts[1:] = np.cumsum(counts)[:-1]
            r = so.astype(np.int64) * bank + (np.arange(n) - starts[so])
            st.pos[r] = pos[order, :2]
            st.hp[r] = hp[order]
            st.atk[r] = atk[order]
            st.camp[r] = camp[order]
            st.gid[r] = order
            st.active[r] = True
        self.bank_size = bank
        sh = NamedSharding(self.mesh, P(self.axis))
        self.state = SpatialState(
            *[jax.device_put(a, sh) for a in st]
        )

    # -- compiled step ----------------------------------------------------
    def _build_step(self):
        g = self.geom
        body = partial(_spatial_body, g, self.axis)
        row = P(self.axis)
        rep = P()
        smapped = _shard_map(
            body,
            mesh=self.mesh,
            in_specs=(row,) * 13 + (rep,),
            out_specs=(row,) * 14,
            **_SM_KW,
        )
        return self.costbook.wrap("spatial.step", smapped, stage="tick")

    def step(self, n: int = 1) -> None:
        if self._step is None:
            self._step = self._build_step()
        st = self.state
        for _ in range(n):
            t = jnp.int32(self.tick_count)
            *banks, stats = self._step(*st, t)
            st = SpatialState(*banks)
            self.tick_count += 1
        self.state = st
        self.stats_last = np.asarray(stats)
        # runtime alerting, same contract as CombatModule's overflow
        # budget (the counters alone are bench-only visibility):
        # - mig_dropped rows left their source bank and found no free
        #   slot at the destination — permanently LOST, always alert
        #   (should never fire now that senders clamp to advertised
        #   destination capacity)
        # - rows that missed migration (budget or capacity clamp) are a
        #   SUBSET of `misplaced` — every unmigrated row is still active
        #   with owner != me when misplaced is counted — so `missed`
        #   counts misplaced + bucket drops and each affected row once
        #   (adding mig_overflow on top would double-count)
        lost_forever = int(self.stats_last[:, 2].sum())
        missed = int(self.stats_last[:, 3].sum()) + int(
            self.stats_last[:, 4:].sum()
        )
        if lost_forever or missed:
            pop = max(1, int(np.asarray(
                jax.jit(lambda a: a.sum())(self.state.active)
            )))
            if lost_forever or missed / pop > self.overflow_budget:
                self.overflow_alerts += 1
                import logging

                logging.getLogger("nf.spatial").warning(
                    "spatial overflow: %d rows lost (bank full), %d "
                    "missed combat/migration this tick (%.4f%% of %d, "
                    "budget %.4f%%) - stats %s",
                    lost_forever, missed, 100 * missed / pop, pop,
                    100 * self.overflow_budget,
                    self.stats_last.sum(axis=0).tolist(),
                )
            # cell-bucket drops specifically (columns 4:6) respond to a
            # bucket resize; migration misses do not
            drops = int(self.stats_last[:, 4:].sum())
            if (
                self.auto_resize
                and drops / pop > self.overflow_budget
                and self._bucket_boost < self.max_bucket_boost
            ):
                self._resize_buckets(drops, pop)

    def _resize_buckets(self, drops: int, pop: int) -> None:
        """Double both cell buckets and retrace — the SpatialGeom twin of
        CombatModule._on_overflow.  The carried Verlet cache bakes the
        old bucket into its slot assignment, so its leaves are zeroed
        (all-False anchor => next tick rebuilds); the lifetime counters
        in cstat survive."""
        self._bucket_boost *= 2
        g = self.geom
        self.geom = g._replace(bucket=g.bucket * 2, att_bucket=g.att_bucket * 2)
        self._step = None
        # sanctioned retrace: the doubled buckets bake into the next trace
        self.costbook.generation_bump("bucket_resize")
        st = self.state
        self.state = st._replace(
            vc_pos=jnp.zeros_like(st.vc_pos),
            vc_active=jnp.zeros_like(st.vc_active),
            vc_order=jnp.zeros_like(st.vc_order),
            vc_skey=jnp.zeros_like(st.vc_skey),
            vc_slot=jnp.zeros_like(st.vc_slot),
        )
        import logging

        logging.getLogger("nf.spatial").warning(
            "cell-bucket overflow: %d drops over %d rows breached budget "
            "%.4f%%; buckets doubled to %d/%d (boost x%d of max x%d), "
            "step retraced",
            drops, pop, 100 * self.overflow_budget,
            self.geom.bucket, self.geom.att_bucket,
            self._bucket_boost, self.max_bucket_boost,
        )

    # -- Verlet cache visibility ------------------------------------------
    @property
    def rebuilds_total(self) -> int:
        """Max over shards (the pmax vote makes every shard rebuild
        together, so any shard's counter is the grid's)."""
        if self.state is None:
            return 0
        return int(np.asarray(self.state.cstat)[:, 0].max())

    @property
    def reuses_total(self) -> int:
        if self.state is None:
            return 0
        return int(np.asarray(self.state.cstat)[:, 1].max())

    # -- host observation -------------------------------------------------
    def gather(self):
        """{gid: (x, y, hp)} for live rows — host-side verification."""
        st = jax.tree.map(np.asarray, self.state)
        out = {}
        for r in np.flatnonzero(st.active):
            out[int(st.gid[r])] = (
                float(st.pos[r, 0]), float(st.pos[r, 1]), int(st.hp[r])
            )
        return out

    # -- checkpoint / resume ----------------------------------------------
    def save(self, path: str) -> None:
        """Snapshot banks + tick counter; resuming continues the exact
        trajectory (the walk/duty are pure functions of (gid, tick))."""
        st = jax.tree.map(np.asarray, self.state)
        np.savez_compressed(
            path, tick=self.tick_count, bank=self.bank_size,
            binning=binning_mode(), **st._asdict(),
        )

    def load(self, path: str) -> None:
        with np.load(path) as z:
            self.tick_count = int(z["tick"])
            self.bank_size = int(z["bank"])
            cap = z["pos"].shape[0]
            # snapshots from before the Verlet cache carry zero caches:
            # the all-False anchor mask forces a rebuild on the first
            # tick, so resume trajectories are unchanged
            fresh = {
                "vc_pos": np.zeros((cap, 2), np.float32),
                "vc_active": np.zeros((cap,), bool),
                "vc_order": np.zeros((cap,), np.int32),
                "vc_skey": np.zeros((cap,), np.int32),
                "vc_slot": np.zeros((cap,), np.int32),
                "cstat": np.zeros((self.geom.n_shards, 3), np.int32),
            }
            # vc_order/vc_skey are NF_BINNING-engine-specific (sorted
            # keys vs per-row anchor keys — VerletCache docstring); a
            # snapshot resumed under the other engine must drop the
            # cache or reuse-tick sub tables silently corrupt.  Old
            # snapshots carry no marker and were written by the sort
            # engine.
            stored = str(z["binning"]) if "binning" in z.files else "sort"
            drop_cache = stored != binning_mode()

            def pick(f):
                if f in z.files and not (drop_cache and f.startswith("vc_")):
                    return z[f]
                return fresh[f]

            sh = NamedSharding(self.mesh, P(self.axis))
            self.state = SpatialState(
                *[jax.device_put(pick(f), sh)
                  for f in SpatialState._fields]
            )


def reference_step(geom: SpatialGeom, pos, hp, atk, camp, gid, died, active,
                   tick):
    """Single-device twin of the spatial tick (same movement, same
    attacker duty, the square-grid combat_fold_xla, the same
    _life_phases chain) — the parity oracle for tests and the
    global-sort side of the A/B."""
    from ..game.combat import combat_fold_xla

    pos = _walk(pos, gid, tick, geom)
    f32 = jnp.float32
    camp_f = camp.astype(f32)
    zeros_f = jnp.zeros_like(camp_f)
    vic_feats = jnp.stack([pos[:, 0], pos[:, 1], camp_f, zeros_f, zeros_f], -1)
    attacking = active & (hp > 0) & ((gid + tick) % geom.attack_period == 0)
    eff_atk = jnp.where(attacking, atk, 0).astype(f32)
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], eff_atk, camp_f, zeros_f, zeros_f,
         gid.astype(f32)],
        -1,
    )
    vic_t, att_t = build_cell_table_pair(
        pos, active, vic_feats, attacking, att_feats,
        geom.cell_size, geom.width, geom.bucket, geom.att_bucket,
    )
    inc, _bestr = combat_fold_xla(vic_t, att_t, geom.radius)
    pulled = pull(vic_t, inc, fill=0)
    incoming = jnp.where(active & (hp > 0), pulled, 0)
    hp, died = _life_phases(geom, hp, died, incoming, tick)
    return pos, hp, died
