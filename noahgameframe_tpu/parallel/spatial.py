"""Spatially-sharded combat core: slab partition, halo exchange, migration.

The default sharded world (`parallel/shard.py`) shards the ENTITY axis
and lets XLA partition the cell-table argsort — correct, but the
partitioned sort is a global all-to-all every tick and was the round-3
sharded-compile/latency hotspot.  This module is the TPU-first
alternative the round-4 verdict asked to explore: partition SPACE, not
rows.

Design (scaling-book recipe: pick a mesh, keep collectives O(boundary)):

- The [width x width] cell grid is cut into `n_shards` horizontal slabs
  of `slab_h` cell rows; shard i owns slab i and the entities inside it.
- Each tick, every shard builds its OWN cell table (argsort over
  capacity/n_shards rows — the sort shrinks with the mesh instead of
  becoming a distributed sort).
- The 3x3 stencil fold needs attacker candidates from the one cell row
  beyond each slab edge: shards exchange their edge attacker PLANES
  ([1, W, K_att, F] — dense, fixed-size) with both neighbors via
  `lax.ppermute`, then fold locally over [slab_h + 2] rows.  Bytes on
  the wire per tick are O(W * K_att), independent of entity count.
- Entities whose cell crossed a slab boundary MIGRATE: up to
  `mig_budget` rows per direction per tick are packed, `ppermute`d to
  the neighbor shard, and scattered into free bank slots — real
  cross-shard migration (BASELINE config 5), with overflow counters
  when the budget or the destination bank is full.  A row that could
  not migrate stays home and simply misses combat that tick (counted,
  like a cell-bucket overflow) and retries next tick.

Damage semantics are bit-identical to the single-device engine: the
fold body is game.combat.combat_fold_closure (shared, not copied), the
attacker `row` payload column carries the GLOBAL entity gid, damage
sums are exact int32 in f32 (< 2^24), and tie-breaks reduce over gid —
so within migration/bucket budgets, spatial and single-device worlds
produce identical HP trajectories (tests/test_spatial.py pins this).

Reference contrast: NFCWorldNet_ServerModule.cpp:600-830 re-homes
players between game servers through the World relay (serialize,
destroy, recreate); here migration is two fixed-size collectives inside
the jitted tick and visibility across the boundary is a dense halo, not
a relay hop.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..game.combat import combat_fold_closure
from ..ops.stencil import build_cell_table_pair, pull
from .mesh import SHARD_AXIS, make_mesh


class SpatialGeom(NamedTuple):
    """Static geometry of the spatially-sharded world."""

    extent: float          # world is [0, extent)^2
    cell_size: float
    width: int             # cells per axis; grid [width, width]
    n_shards: int          # horizontal slabs; width % n_shards == 0
    bucket: int            # victim slots per cell
    att_bucket: int        # attacker slots per cell
    radius: float          # AoE radius (<= cell_size)
    mig_budget: int        # migrant rows per direction per shard per tick
    speed: float = 0.5     # random-walk step per tick (< cell_size)
    attack_period: int = 30  # a gid attacks every `attack_period` ticks
    # the rest of the benchmark phase chain (0 disables either):
    regen_per_tick: int = 0   # hp regained per tick while alive
    hp_max: int = 0           # regen/respawn ceiling (0 = no ceiling)
    respawn_ticks: int = 0    # dead rows revive at hp_max after this many

    @property
    def slab_h(self) -> int:
        return self.width // self.n_shards


class SpatialState(NamedTuple):
    """Per-entity banks, leading axis = n_shards * bank_size, sharded
    row-wise so shard i holds rows [i*bank : (i+1)*bank]."""

    pos: jnp.ndarray     # [cap, 2] f32
    hp: jnp.ndarray      # [cap] i32
    atk: jnp.ndarray     # [cap] i32
    camp: jnp.ndarray    # [cap] i32
    gid: jnp.ndarray     # [cap] i32 — stable global id, rides migration
    died: jnp.ndarray    # [cap] i32 — tick of death, -1 while alive
    active: jnp.ndarray  # [cap] bool


def _walk(pos, gid, tick, geom: SpatialGeom):
    """Deterministic per-gid random walk — a pure function of (gid,
    tick), so every shard placement computes the identical trajectory
    (the parity tests rely on this).  The murmur3-style finalizer
    matters: a LINEAR hash of (gid, tick) rotates each heading by a
    constant ~0.9 deg/tick, producing near-straight paths that stick to
    the clipped world walls and pile entire populations into corner
    cells within ~100 ticks."""
    h = (gid.astype(jnp.uint32) * jnp.uint32(2654435761)
         + jnp.uint32(tick) * jnp.uint32(40503))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    ang = (h >> 8).astype(jnp.float32) * (2.0 * np.pi / float(1 << 24))
    step = jnp.stack([jnp.cos(ang), jnp.sin(ang)], -1) * geom.speed
    eps = 1e-3
    return jnp.clip(pos + step, eps, geom.extent - eps)


def _pack_rows(sel, rank, budget, *arrays):
    """Gather up to `budget` selected rows into fixed [budget] buffers.
    sel: [n] bool, rank: [n] exclusive rank among selected.  Returns
    (valid [budget] bool, packed arrays)."""
    n = sel.shape[0]
    idx = jnp.where(sel & (rank < budget), rank, budget)
    valid = jnp.zeros((budget + 1,), bool).at[idx].set(sel)[:budget]
    out = []
    for a in arrays:
        buf_shape = (budget + 1,) + a.shape[1:]
        out.append(jnp.zeros(buf_shape, a.dtype).at[idx].set(a)[:budget])
    return valid, out


def _life_phases(geom: SpatialGeom, hp, died, incoming, tick):
    """Damage -> death mark -> regen -> respawn, shared verbatim by the
    spatial tick and the single-device parity oracle (pure elementwise,
    placement-invariant)."""
    hp_after = jnp.maximum(hp - incoming, 0)
    died = jnp.where((hp > 0) & (hp_after == 0), tick, died)
    if geom.regen_per_tick > 0:
        regen = jnp.where(hp_after > 0, hp_after + geom.regen_per_tick,
                          hp_after)
        if geom.hp_max > 0:
            regen = jnp.minimum(regen, geom.hp_max)
        hp_after = regen
    if geom.respawn_ticks > 0:
        revive = (
            (hp_after == 0) & (died >= 0)
            & (tick - died >= geom.respawn_ticks)
        )
        hp_after = jnp.where(revive, geom.hp_max, hp_after)
        died = jnp.where(revive, -1, died)
    return hp_after, died


def _spatial_body(geom: SpatialGeom, axis, pos, hp, atk, camp, gid, died,
                  active, tick):
    """One tick on one shard (runs under shard_map; arrays are the
    shard-local banks)."""
    n = geom.n_shards
    hs = geom.slab_h
    w = geom.width
    me = jax.lax.axis_index(axis)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]

    # -- movement (identical math on any placement) ----------------------
    pos = _walk(pos, gid, tick, geom)

    cx = jnp.clip((pos[:, 0] / geom.cell_size).astype(jnp.int32), 0, w - 1)
    cy = jnp.clip((pos[:, 1] / geom.cell_size).astype(jnp.int32), 0, w - 1)
    owner = cy // hs

    # -- migration: one budgeted ppermute per direction ------------------
    migrated = jnp.int32(0)
    mig_overflow = jnp.int32(0)
    mig_dropped = jnp.int32(0)
    banks = (pos, hp, atk, camp, gid, died)
    for d, perm in ((1, fwd), (-1, bwd)):
        # direction of travel, not exact neighbor: a row stranded 2+
        # slabs from home (sustained budget overflow, or a teleport)
        # hops one slab toward its owner per tick until it arrives —
        # otherwise it would be excluded from combat forever
        m = active & ((owner > me) if d == 1 else (owner < me))
        csum = jnp.cumsum(m.astype(jnp.int32))
        sel = m & (csum <= geom.mig_budget)
        migrated = migrated + jnp.sum(sel, dtype=jnp.int32)
        mig_overflow = mig_overflow + jnp.sum(m, dtype=jnp.int32) - jnp.sum(
            sel, dtype=jnp.int32
        )
        valid, packed = _pack_rows(sel, csum - 1, geom.mig_budget, *banks)
        rvalid = jax.lax.ppermute(valid, axis, perm)
        rpacked = [jax.lax.ppermute(b, axis, perm) for b in packed]
        # wrap-around sends are impossible (owner is clipped into range),
        # but mask the circular receive anyway for edge shards
        sender_ok = (me - d >= 0) & (me - d < n)
        rvalid = rvalid & sender_ok
        active = active & ~sel
        # insert into free slots: dest[j] = row index of the j-th free slot
        free = ~active
        frank = jnp.cumsum(free.astype(jnp.int32)) - free.astype(jnp.int32)
        slots = jnp.where(free & (frank < geom.mig_budget), frank,
                          geom.mig_budget)
        dest = (
            jnp.full((geom.mig_budget + 1,), pos.shape[0], jnp.int32)
            .at[slots]
            .set(jnp.arange(pos.shape[0], dtype=jnp.int32))[: geom.mig_budget]
        )
        dest_j = jnp.where(rvalid, dest, pos.shape[0])  # OOB => dropped
        mig_dropped = mig_dropped + jnp.sum(
            rvalid & (dest_j >= pos.shape[0]), dtype=jnp.int32
        )
        new_banks = []
        for cur, rb in zip(banks, rpacked):
            new_banks.append(cur.at[dest_j].set(rb, mode="drop"))
        pos, hp, atk, camp, gid, died = new_banks
        active = active.at[dest_j].set(True, mode="drop")
        banks = (pos, hp, atk, camp, gid, died)
        # re-derive cells for rows that just arrived
        cx = jnp.clip((pos[:, 0] / geom.cell_size).astype(jnp.int32), 0, w - 1)
        cy = jnp.clip((pos[:, 1] / geom.cell_size).astype(jnp.int32), 0, w - 1)
        owner = cy // hs

    # -- local cell tables over the slab ---------------------------------
    in_slab = active & (owner == me)
    misplaced = jnp.sum(active & (owner != me), dtype=jnp.int32)
    cell_local = (cy - me * hs) * w + cx
    f32 = jnp.float32
    camp_f = camp.astype(f32)
    zeros_f = jnp.zeros_like(camp_f)
    vic_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], camp_f, zeros_f, zeros_f], -1
    )
    attacking = (
        in_slab
        & (hp > 0)
        & ((gid + tick) % geom.attack_period == 0)
    )
    eff_atk = jnp.where(attacking, atk, 0).astype(f32)
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], eff_atk, camp_f, zeros_f, zeros_f,
         gid.astype(f32)],
        -1,
    )
    vic_t, att_t = build_cell_table_pair(
        pos, in_slab, vic_feats, attacking, att_feats,
        geom.cell_size, w, geom.bucket, geom.att_bucket,
        cell=cell_local, height=hs,
    )

    # -- halo exchange: one dense attacker plane per edge ----------------
    ag = att_t.grid_view()  # [hs, w, K_att, F+1]
    halo_top = jax.lax.ppermute(ag[hs - 1:hs], axis, fwd)   # prev's bottom
    halo_bot = jax.lax.ppermute(ag[0:1], axis, bwd)          # next's top
    halo_top = jnp.where(me > 0, halo_top, jnp.zeros_like(halo_top))
    halo_bot = jnp.where(me < n - 1, halo_bot, jnp.zeros_like(halo_bot))
    ag_h = jnp.concatenate([halo_top, ag, halo_bot], axis=0)  # [hs+2, ...]

    # -- fold: same body as the single-chip engine, halo-aware walk ------
    fold, init = combat_fold_closure(vic_t.grid_view(), geom.radius)
    agp = jnp.pad(ag_h, ((0, 0), (1, 1), (0, 0), (0, 0)))
    acc = init
    for dy in (0, 1, 2):  # (dy, dx) ascending == ops.stencil.STENCIL order
        for dx in (0, 1, 2):
            cand = jax.lax.slice(
                agp, (dy, dx, 0, 0),
                (dy + hs, dx + w, agp.shape[2], agp.shape[3]),
            )
            acc = fold(acc, cand)
    inc, _besta, _bestr = acc

    # -- damage -----------------------------------------------------------
    pulled = pull(vic_t, inc, fill=0)
    incoming = jnp.where(in_slab & (hp > 0), pulled, 0)
    hp, died = _life_phases(geom, hp, died, incoming, tick)

    # columns: migrated, mig_overflow (budget), mig_dropped (no free
    # slot), misplaced (awaiting retry), vic/att cell-bucket drops
    stats = jnp.stack(
        [migrated, mig_overflow, mig_dropped, misplaced,
         vic_t.dropped, att_t.dropped]
    )[None, :]  # [1, 6] per shard -> [n_shards, 6] outside
    return pos, hp, atk, camp, gid, died, active, stats


class SpatialWorld:
    """Host wrapper: placement, compiled step, counters.

    Usage:
        geom = SpatialGeom(...)
        world = SpatialWorld(geom)            # makes its own mesh
        world.place(pos, hp, atk, camp)       # numpy rows, any order
        world.step()                          # one jitted sharded tick
        world.gather()                        # {gid -> (pos, hp)} to host
    """

    def __init__(self, geom: SpatialGeom, mesh: Optional[Mesh] = None,
                 bank_size: Optional[int] = None):
        if geom.width % geom.n_shards:
            raise ValueError("width must divide into n_shards slabs")
        self.geom = geom
        self.mesh = mesh if mesh is not None else make_mesh(geom.n_shards)
        self.axis = SHARD_AXIS
        self.bank_size = bank_size
        self.state: Optional[SpatialState] = None
        self.tick_count = 0
        self.stats_last = np.zeros((geom.n_shards, 6), np.int32)
        self.overflow_budget = 1e-4  # alert threshold, as CombatModule
        self.overflow_alerts = 0
        self._step = None

    # -- placement --------------------------------------------------------
    def place(self, pos: np.ndarray, hp: np.ndarray, atk: np.ndarray,
              camp: np.ndarray) -> None:
        """Distribute entities into per-shard banks by their slab."""
        g = self.geom
        n = pos.shape[0]
        cy = np.clip((pos[:, 1] / g.cell_size).astype(np.int32), 0,
                     g.width - 1)
        owner = cy // g.slab_h
        counts = np.bincount(owner, minlength=g.n_shards)
        bank = self.bank_size or int(1 << int(np.ceil(np.log2(
            max(counts.max() * 2, 64)))))
        cap = bank * g.n_shards
        st = SpatialState(
            pos=np.zeros((cap, 2), np.float32),
            hp=np.zeros((cap,), np.int32),
            atk=np.zeros((cap,), np.int32),
            camp=np.zeros((cap,), np.int32),
            gid=np.full((cap,), -1, np.int32),
            died=np.full((cap,), -1, np.int32),
            active=np.zeros((cap,), bool),
        )
        fill = np.zeros(g.n_shards, np.int32)
        for i in range(n):
            s = owner[i]
            if fill[s] >= bank:
                raise ValueError(f"bank {s} overflow at placement")
            r = s * bank + fill[s]
            fill[s] += 1
            st.pos[r] = pos[i]
            st.hp[r] = hp[i]
            st.atk[r] = atk[i]
            st.camp[r] = camp[i]
            st.gid[r] = i
            st.active[r] = True
        self.bank_size = bank
        sh = NamedSharding(self.mesh, P(self.axis))
        self.state = SpatialState(
            *[jax.device_put(a, sh) for a in st]
        )

    # -- compiled step ----------------------------------------------------
    def _build_step(self):
        g = self.geom
        body = partial(_spatial_body, g, self.axis)
        row = P(self.axis)
        rep = P()
        smapped = jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(row, row, row, row, row, row, row, rep),
            out_specs=(row, row, row, row, row, row, row, row),
            check_vma=False,
        )
        return jax.jit(smapped)

    def step(self, n: int = 1) -> None:
        if self._step is None:
            self._step = self._build_step()
        st = self.state
        for _ in range(n):
            t = jnp.int32(self.tick_count)
            *banks, stats = self._step(
                st.pos, st.hp, st.atk, st.camp, st.gid, st.died,
                st.active, t
            )
            st = SpatialState(*banks)
            self.tick_count += 1
        self.state = st
        self.stats_last = np.asarray(stats)
        # runtime alerting, same contract as CombatModule's overflow
        # budget (the counters alone are bench-only visibility):
        # - mig_dropped rows left their source bank and found no free
        #   slot at the destination — permanently LOST, always alert
        # - budget-overflow/misplaced rows retry next tick and bucket
        #   drops miss one tick of combat — alert above the budget
        lost_forever = int(self.stats_last[:, 2].sum())
        missed = int(self.stats_last[:, 1].sum()) + int(
            self.stats_last[:, 4:].sum()
        )
        if lost_forever or missed:
            pop = max(1, int(np.asarray(
                jax.jit(lambda a: a.sum())(self.state.active)
            )))
            if lost_forever or missed / pop > self.overflow_budget:
                self.overflow_alerts += 1
                import logging

                logging.getLogger("nf.spatial").warning(
                    "spatial overflow: %d rows lost (bank full), %d "
                    "missed combat/migration this tick (%.4f%% of %d, "
                    "budget %.4f%%) - stats %s",
                    lost_forever, missed, 100 * missed / pop, pop,
                    100 * self.overflow_budget,
                    self.stats_last.sum(axis=0).tolist(),
                )

    # -- host observation -------------------------------------------------
    def gather(self):
        """{gid: (x, y, hp)} for live rows — host-side verification."""
        st = jax.tree.map(np.asarray, self.state)
        out = {}
        for r in np.flatnonzero(st.active):
            out[int(st.gid[r])] = (
                float(st.pos[r, 0]), float(st.pos[r, 1]), int(st.hp[r])
            )
        return out

    # -- checkpoint / resume ----------------------------------------------
    def save(self, path: str) -> None:
        """Snapshot banks + tick counter; resuming continues the exact
        trajectory (the walk/duty are pure functions of (gid, tick))."""
        st = jax.tree.map(np.asarray, self.state)
        np.savez_compressed(
            path, tick=self.tick_count, bank=self.bank_size,
            **st._asdict(),
        )

    def load(self, path: str) -> None:
        with np.load(path) as z:
            self.tick_count = int(z["tick"])
            self.bank_size = int(z["bank"])
            sh = NamedSharding(self.mesh, P(self.axis))
            self.state = SpatialState(
                *[jax.device_put(z[f], sh) for f in SpatialState._fields]
            )


def reference_step(geom: SpatialGeom, pos, hp, atk, camp, gid, died, active,
                   tick):
    """Single-device twin of the spatial tick (same movement, same
    attacker duty, the square-grid combat_fold_xla, the same
    _life_phases chain) — the parity oracle for tests and the
    global-sort side of the A/B."""
    from ..game.combat import combat_fold_xla

    pos = _walk(pos, gid, tick, geom)
    f32 = jnp.float32
    camp_f = camp.astype(f32)
    zeros_f = jnp.zeros_like(camp_f)
    vic_feats = jnp.stack([pos[:, 0], pos[:, 1], camp_f, zeros_f, zeros_f], -1)
    attacking = active & (hp > 0) & ((gid + tick) % geom.attack_period == 0)
    eff_atk = jnp.where(attacking, atk, 0).astype(f32)
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], eff_atk, camp_f, zeros_f, zeros_f,
         gid.astype(f32)],
        -1,
    )
    vic_t, att_t = build_cell_table_pair(
        pos, active, vic_feats, attacking, att_feats,
        geom.cell_size, geom.width, geom.bucket, geom.att_bucket,
    )
    inc, _bestr = combat_fold_xla(vic_t, att_t, geom.radius)
    pulled = pull(vic_t, inc, fill=0)
    incoming = jnp.where(active & (hp > 0), pulled, 0)
    hp, died = _life_phases(geom, hp, died, incoming, tick)
    return pos, hp, died
