"""Spatially-sharded combat preset over the unified mesh engine.

Historically this module owned a bespoke six-column mini-world
(pos/hp/atk/camp/gid in its own NamedTuple banks) that bypassed the
Kernel's property banks, records and timers entirely.  It is now a THIN
PRESET over the one mesh engine: entities live in a real ``ClassState``
("spatial" class: five int properties + a vector2 position), the tick is
``Kernel._trace_step`` compiled by ``ShardedKernel``, and cross-shard
migration is the generic full-row protocol in ``parallel/rowmigrate.py``
(free-slot capacity vote → pack → ppermute → scatter-insert, lifted from
the slab engine and generalized to every store leaf).

Phase chain (one jit-compiled sharded tick):

- ``spatial.walk`` (order 10): deterministic per-gid random walk, pure
  elementwise — identical math on any placement.
- ``rowmigrate.migrate`` (order 20): budgeted ppermute migration of FULL
  ClassState rows toward the shard owning their cell row.  Up to
  `mig_budget` rows per direction per tick; overflow rows stay home,
  miss combat that tick (counted) and retry.
- ``spatial.combat`` (order 30): per-slab cell tables, dense halo planes
  to both neighbors, the shared combat fold, damage/regen/respawn.

Damage semantics are bit-identical to the single-device engine: the
fold body is game.combat.combat_fold_closure (shared, not copied), the
attacker `row` payload column carries the GLOBAL entity gid, damage
sums are exact int32 in f32 (< 2^24), and tie-breaks reduce over gid —
so within migration/bucket budgets, spatial and single-device worlds
produce identical HP trajectories (tests/test_spatial.py pins this).

Verlet/binning caches ride ``WorldState.aux`` (never ClassState): they
are rebuilt, not migrated, and stay excluded from ``state_digest`` — the
cache-rebuild contract documented in docs/ARCHITECTURE.md.

Reference contrast: NFCWorldNet_ServerModule.cpp:600-830 re-homes
players between game servers through the World relay (serialize,
destroy, recreate); here migration is fixed-size collectives inside
the jitted tick and visibility across the boundary is a dense halo, not
a relay hop.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.schema import ClassDef, ClassRegistry, prop
from ..core.store import StoreConfig, with_class
from ..game.combat import combat_fold_closure
from ..kernel.kernel import Kernel
from ..kernel.module import Module
from ..ops.stencil import binning_mode, build_cell_table_pair, pull
from ..ops.verlet import VerletCache, full_table, refresh, sub_table
from .mesh import SHARD_AXIS, make_mesh
from .rowmigrate import (
    _SM_KW,
    _pack_rows,  # noqa: F401  (re-export: the slab protocol's packer moved)
    _shard_map,
    RowMigrationModule,
    SpatialPlacement,
)
from .shard import ShardedKernel

# i32 property columns of the "spatial" class, in definition order
_HP, _ATK, _CAMP, _GID, _DIED = range(5)
_POS = 0  # vec column


class SpatialGeom(NamedTuple):
    """Static geometry of the spatially-sharded world."""

    extent: float          # world is [0, extent)^2
    cell_size: float
    width: int             # cells per axis; grid [width, width]
    n_shards: int          # horizontal slabs; width % n_shards == 0
    bucket: int            # victim slots per cell
    att_bucket: int        # attacker slots per cell
    radius: float          # AoE radius (<= cell_size)
    mig_budget: int        # migrant rows per direction per shard per tick
    speed: float = 0.5     # random-walk step per tick (< cell_size)
    attack_period: int = 30  # a gid attacks every `attack_period` ticks
    # the rest of the benchmark phase chain (0 disables either):
    regen_per_tick: int = 0   # hp regained per tick while alive
    hp_max: int = 0           # regen/respawn ceiling (0 = no ceiling)
    respawn_ticks: int = 0    # dead rows revive at hp_max after this many
    # Verlet skin (ops/verlet.py): > 0 gates the per-slab sort+build on
    # accumulated displacement.  Requires cell_size >= radius + skin.
    # Any tick that migrates a row (or strands one mid-hop) changes the
    # in-slab mask and forces a rebuild, so the win concentrates in ticks
    # where no entity crosses a slab boundary.
    skin: float = 0.0

    @property
    def slab_h(self) -> int:
        return self.width // self.n_shards

    def placement(self, class_name: str = "spatial",
                  pos_prop: str = "pos") -> SpatialPlacement:
        """The rowmigrate config this geometry implies."""
        return SpatialPlacement(
            class_name=class_name, pos_prop=pos_prop, extent=self.extent,
            cell_size=self.cell_size, width=self.width,
            n_shards=self.n_shards, mig_budget=self.mig_budget,
        )


class SpatialState(NamedTuple):
    """Host-facing VIEW of the unified engine's state, kept for API and
    snapshot compatibility: column slices of the "spatial" ClassState
    banks plus the aux-carried Verlet cache.  Leading axis =
    n_shards * bank_size, sharded row-wise so shard i holds rows
    [i*bank : (i+1)*bank]."""

    pos: jnp.ndarray     # [cap, 2] f32
    hp: jnp.ndarray      # [cap] i32
    atk: jnp.ndarray     # [cap] i32
    camp: jnp.ndarray    # [cap] i32
    gid: jnp.ndarray     # [cap] i32 — stable global id, rides migration
    died: jnp.ndarray    # [cap] i32 — tick of death, -1 while alive
    active: jnp.ndarray  # [cap] bool
    # Verlet cache leaves (geom.skin > 0; carried zeros otherwise).
    # cstat: [n_shards, 3] = rebuilds/reuses/age, one [1, 3] row per shard.
    vc_pos: jnp.ndarray      # [cap, 2] f32 — anchor positions
    vc_active: jnp.ndarray   # [cap] bool  — anchor in-slab mask
    vc_order: jnp.ndarray    # [cap] i32
    vc_skey: jnp.ndarray     # [cap] i32
    vc_slot: jnp.ndarray     # [cap] i32
    cstat: jnp.ndarray       # [n_shards, 3] i32


def _walk(pos, gid, tick, geom: SpatialGeom):
    """Deterministic per-gid random walk — a pure function of (gid,
    tick), so every shard placement computes the identical trajectory
    (the parity tests rely on this).  The murmur3-style finalizer
    matters: a LINEAR hash of (gid, tick) rotates each heading by a
    constant ~0.9 deg/tick, producing near-straight paths that stick to
    the clipped world walls and pile entire populations into corner
    cells within ~100 ticks."""
    h = (gid.astype(jnp.uint32) * jnp.uint32(2654435761)
         + jnp.uint32(tick) * jnp.uint32(40503))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    ang = (h >> 8).astype(jnp.float32) * (2.0 * np.pi / float(1 << 24))
    step = jnp.stack([jnp.cos(ang), jnp.sin(ang)], -1) * geom.speed
    eps = 1e-3
    return jnp.clip(pos + step, eps, geom.extent - eps)


def _life_phases(geom: SpatialGeom, hp, died, incoming, tick):
    """Damage -> death mark -> regen -> respawn, shared verbatim by the
    spatial tick and the single-device parity oracle (pure elementwise,
    placement-invariant)."""
    hp_after = jnp.maximum(hp - incoming, 0)
    died = jnp.where((hp > 0) & (hp_after == 0), tick, died)
    if geom.regen_per_tick > 0:
        regen = jnp.where(hp_after > 0, hp_after + geom.regen_per_tick,
                          hp_after)
        if geom.hp_max > 0:
            regen = jnp.minimum(regen, geom.hp_max)
        hp_after = regen
    if geom.respawn_ticks > 0:
        revive = (
            (hp_after == 0) & (died >= 0)
            & (tick - died >= geom.respawn_ticks)
        )
        hp_after = jnp.where(revive, geom.hp_max, hp_after)
        died = jnp.where(revive, -1, died)
    return hp_after, died


def _combat_body(geom: SpatialGeom, axis, pos, hp, atk, camp, gid, died,
                 active, vc_pos, vc_active, vc_order, vc_skey, vc_slot,
                 cstat, tick):
    """Combat on one shard (runs under shard_map; arrays are the
    shard-local banks).  Movement and migration already happened in
    earlier phases; cells are re-derived from the post-migration
    positions exactly as the old fused body did."""
    n = geom.n_shards
    hs = geom.slab_h
    w = geom.width
    me = jax.lax.axis_index(axis)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]

    cx = jnp.clip((pos[:, 0] / geom.cell_size).astype(jnp.int32), 0, w - 1)
    cy = jnp.clip((pos[:, 1] / geom.cell_size).astype(jnp.int32), 0, w - 1)
    owner = cy // hs

    # -- local cell tables over the slab ---------------------------------
    in_slab = active & (owner == me)
    misplaced = jnp.sum(active & (owner != me), dtype=jnp.int32)
    cell_local = (cy - me * hs) * w + cx
    f32 = jnp.float32
    camp_f = camp.astype(f32)
    zeros_f = jnp.zeros_like(camp_f)
    vic_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], camp_f, zeros_f, zeros_f], -1
    )
    attacking = (
        in_slab
        & (hp > 0)
        & ((gid + tick) % geom.attack_period == 0)
    )
    eff_atk = jnp.where(attacking, atk, 0).astype(f32)
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], eff_atk, camp_f, zeros_f, zeros_f,
         gid.astype(f32)],
        -1,
    )
    if geom.skin > 0.0:
        # displacement-gated build (ops/verlet.py): the anchor mask is the
        # in-slab set, so any migration/straggler flip forces a rebuild —
        # and the vote is pmax'd over the mesh so every shard's carried
        # cache takes the same branch.  cell_local is derived from the
        # same positions passed to refresh, as its contract requires.
        cache = VerletCache(
            anchor_pos=vc_pos, anchor_active=vc_active, order=vc_order,
            skey=vc_skey, slot_of=vc_slot,
            rebuilds=cstat[0, 0], reuses=cstat[0, 1], age=cstat[0, 2],
        )
        cache, _rebuilt = refresh(
            cache, pos, in_slab, geom.cell_size, w, geom.bucket, geom.skin,
            cell=cell_local, n_cells=hs * w, height=hs, axis_name=axis,
        )
        vic_t = full_table(
            cache, vic_feats, in_slab, hs * w, geom.cell_size, w,
            geom.bucket, height=hs,
        )
        att_t = sub_table(
            cache, attacking, att_feats, hs * w, geom.cell_size, w,
            geom.att_bucket, height=hs,
        )
        vc_pos, vc_active = cache.anchor_pos, cache.anchor_active
        vc_order, vc_skey, vc_slot = cache.order, cache.skey, cache.slot_of
        cstat = jnp.stack([cache.rebuilds, cache.reuses, cache.age])[None, :]
    else:
        vic_t, att_t = build_cell_table_pair(
            pos, in_slab, vic_feats, attacking, att_feats,
            geom.cell_size, w, geom.bucket, geom.att_bucket,
            cell=cell_local, height=hs,
        )

    # -- halo exchange: one dense attacker plane per edge ----------------
    ag = att_t.grid_view()  # [hs, w, K_att, F+1]
    halo_top = jax.lax.ppermute(ag[hs - 1:hs], axis, fwd)   # prev's bottom
    halo_bot = jax.lax.ppermute(ag[0:1], axis, bwd)          # next's top
    halo_top = jnp.where(me > 0, halo_top, jnp.zeros_like(halo_top))
    halo_bot = jnp.where(me < n - 1, halo_bot, jnp.zeros_like(halo_bot))
    ag_h = jnp.concatenate([halo_top, ag, halo_bot], axis=0)  # [hs+2, ...]

    # -- fold: same body as the single-chip engine, halo-aware walk ------
    fold, init = combat_fold_closure(vic_t.grid_view(), geom.radius)
    agp = jnp.pad(ag_h, ((0, 0), (1, 1), (0, 0), (0, 0)))
    acc = init
    for dy in (0, 1, 2):  # (dy, dx) ascending == ops.stencil.STENCIL order
        for dx in (0, 1, 2):
            cand = jax.lax.slice(
                agp, (dy, dx, 0, 0),
                (dy + hs, dx + w, agp.shape[2], agp.shape[3]),
            )
            acc = fold(acc, cand)
    inc, _besta, _bestr = acc

    # -- damage -----------------------------------------------------------
    pulled = pull(vic_t, inc, fill=0)
    incoming = jnp.where(in_slab & (hp > 0), pulled, 0)
    hp, died = _life_phases(geom, hp, died, incoming, tick)

    # columns: misplaced (awaiting migration retry), vic/att cell-bucket
    # drops — the migration counters ride rowmigrate's own stats aux
    stats = jnp.stack(
        [misplaced, vic_t.dropped, att_t.dropped]
    )[None, :]  # [1, 3] per shard -> [n_shards, 3] outside
    return (hp, died, vc_pos, vc_active, vc_order, vc_skey, vc_slot,
            cstat, stats)


VC_AUX = "spatial.vc"
COMBAT_STATS_AUX = "spatial.stats"


class _SpatialModule(Module):
    """Walk + combat phases of the spatial preset (migration is the
    generic RowMigrationModule between them)."""

    name = "spatial"

    def __init__(self, world: "SpatialWorld"):
        super().__init__()
        self.world = world
        self.add_phase("walk", self._walk_phase, order=10)
        self.add_phase("combat", self._combat_phase, order=30)

    def _walk_phase(self, state, ctx):
        g = self.world.geom
        cs = state.classes["spatial"]
        new = _walk(cs.vec[:, _POS, :2], cs.i32[:, _GID], ctx.tick, g)
        vec = cs.vec.at[:, _POS, 0].set(new[:, 0]).at[:, _POS, 1].set(
            new[:, 1])
        return with_class(state, "spatial", cs.replace(vec=vec))

    def _combat_phase(self, state, ctx):
        w = self.world
        g = w.geom  # read at trace time: invalidate() picks up resizes
        cs = state.classes["spatial"]
        vc = state.aux[VC_AUX]
        row, rep = P(w.axis), P()
        smapped = _shard_map(
            partial(_combat_body, g, w.axis),
            mesh=w.mesh,
            in_specs=(row,) * 13 + (rep,),
            out_specs=(row,) * 9,
            **_SM_KW,
        )
        (hp, died, vc_pos, vc_active, vc_order, vc_skey, vc_slot, cstat,
         stats) = smapped(
            cs.vec[:, _POS, :2], cs.i32[:, _HP], cs.i32[:, _ATK],
            cs.i32[:, _CAMP], cs.i32[:, _GID], cs.i32[:, _DIED],
            cs.alive, vc["pos"], vc["active"], vc["order"], vc["skey"],
            vc["slot"], vc["cstat"], ctx.tick,
        )
        i32 = cs.i32.at[:, _HP].set(hp).at[:, _DIED].set(died)
        state = with_class(state, "spatial", cs.replace(i32=i32))
        ctx.count("misplaced", jnp.sum(stats[:, 0]))
        ctx.count("grid_drops", jnp.sum(stats[:, 1:]))
        return state.replace(aux={
            **state.aux,
            VC_AUX: {"pos": vc_pos, "active": vc_active, "order": vc_order,
                     "skey": vc_skey, "slot": vc_slot, "cstat": cstat},
            COMBAT_STATS_AUX: stats,
        })


class SpatialWorld:
    """Thin spatial preset over the unified Kernel/ShardedKernel engine.

    Usage:
        geom = SpatialGeom(...)
        world = SpatialWorld(geom)            # makes its own mesh
        world.place(pos, hp, atk, camp)       # numpy rows, any order
        world.step()                          # one jitted sharded tick
        world.gather()                        # {gid -> (pos, hp)} to host
    """

    def __init__(self, geom: SpatialGeom, mesh: Optional[Mesh] = None,
                 bank_size: Optional[int] = None):
        if geom.width % geom.n_shards:
            raise ValueError("width must divide into n_shards slabs")
        if geom.skin > 0.0 and geom.cell_size < geom.radius + geom.skin:
            raise ValueError(
                f"Verlet skin {geom.skin} needs cell_size >= radius + skin "
                f"({geom.radius + geom.skin}), got {geom.cell_size}"
            )
        self.geom = geom
        self.mesh = mesh if mesh is not None else make_mesh(geom.n_shards)
        self.axis = SHARD_AXIS
        self.bank_size = bank_size
        self.stats_last = np.zeros((geom.n_shards, 6), np.int32)
        self.overflow_budget = 1e-4  # alert threshold, as CombatModule
        self.overflow_alerts = 0
        # crowding response, ported from CombatModule._on_overflow: when
        # cell-bucket drops breach the budget, double both buckets
        # (bounded) and retrace — silent drops stop instead of repeating
        # every tick (r05_sharded_4m saw grid_overflow_max=374/tick).
        self.auto_resize = True
        self.max_bucket_boost = 8
        self._bucket_boost = 1
        self._kernel: Optional[Kernel] = None
        self._sharded: Optional[ShardedKernel] = None
        self._mig: Optional[RowMigrationModule] = None
        self._tick0 = 0
        # one cost ledger across rebuilds; the kernel adopts it at build
        # so benches and tests keep reading world.costbook
        from ..telemetry.costbook import CostBook

        self.costbook = CostBook()

    # -- engine assembly ---------------------------------------------------
    def _build_kernel(self, cap: int) -> None:
        g = self.geom
        reg = ClassRegistry()
        reg.define(ClassDef(name="spatial", properties=[
            prop("hp", "int"), prop("atk", "int"), prop("camp", "int"),
            prop("gid", "int"), prop("died", "int"),
            prop("pos", "vector2"),
        ]))
        k = Kernel(
            reg,
            store_config=StoreConfig(default_capacity=cap,
                                     capacities={"spatial": cap}),
            seed=0,
        )
        k.costbook = self.costbook
        self._mig = RowMigrationModule(
            g.placement(), mesh=self.mesh, order=20)
        k.build([_SpatialModule(self), self._mig])
        self._mig.bind(k)
        n_sh, bank = g.n_shards, cap // g.n_shards
        k.register_aux(VC_AUX, lambda: {
            "pos": jnp.zeros((cap, 2), jnp.float32),
            "active": jnp.zeros((cap,), bool),
            "order": jnp.zeros((cap,), jnp.int32),
            "skey": jnp.zeros((cap,), jnp.int32),
            "slot": jnp.zeros((cap,), jnp.int32),
            "cstat": jnp.zeros((n_sh, 3), jnp.int32),
        })
        k.register_aux(
            COMBAT_STATS_AUX, lambda: jnp.zeros((n_sh, 3), jnp.int32))
        self._kernel = k
        self._sharded = ShardedKernel(k, mesh=self.mesh)

    @property
    def kernel(self) -> Optional[Kernel]:
        """The unified engine underneath (None before place()/load())."""
        return self._kernel

    @property
    def tick_count(self) -> int:
        return self._kernel.tick_count if self._kernel else self._tick0

    @tick_count.setter
    def tick_count(self, v: int) -> None:
        v = int(v)
        if self._kernel is None:
            self._tick0 = v
            return
        self._kernel.tick_count = v
        self._kernel.state = self._kernel.state.replace(
            tick=jnp.asarray(v, jnp.int32))

    # -- state view (API/snapshot compatibility) ---------------------------
    @property
    def state(self) -> Optional[SpatialState]:
        if self._kernel is None:
            return None
        self._kernel._ensure_aux()
        cs = self._kernel.state.classes["spatial"]
        vc = self._kernel.state.aux[VC_AUX]
        return SpatialState(
            pos=cs.vec[:, _POS, :2], hp=cs.i32[:, _HP],
            atk=cs.i32[:, _ATK], camp=cs.i32[:, _CAMP],
            gid=cs.i32[:, _GID], died=cs.i32[:, _DIED], active=cs.alive,
            vc_pos=vc["pos"], vc_active=vc["active"], vc_order=vc["order"],
            vc_skey=vc["skey"], vc_slot=vc["slot"], cstat=vc["cstat"],
        )

    @state.setter
    def state(self, st: Optional[SpatialState]) -> None:
        if st is None:
            return
        k = self._kernel
        if k is None:
            raise RuntimeError("place() or load() before assigning state")
        k._ensure_aux()
        cs = k.state.classes["spatial"]
        i32 = jnp.stack(
            [jnp.asarray(st.hp), jnp.asarray(st.atk), jnp.asarray(st.camp),
             jnp.asarray(st.gid), jnp.asarray(st.died)], axis=1,
        ).astype(jnp.int32)
        pos = jnp.asarray(st.pos)
        vec = cs.vec.at[:, _POS, 0].set(pos[:, 0]).at[:, _POS, 1].set(
            pos[:, 1])
        cs = cs.replace(i32=i32, vec=vec, alive=jnp.asarray(st.active))
        new_state = with_class(k.state, "spatial", cs)
        k.state = new_state.replace(aux={
            **new_state.aux,
            VC_AUX: {
                "pos": jnp.asarray(st.vc_pos),
                "active": jnp.asarray(st.vc_active),
                "order": jnp.asarray(st.vc_order),
                "skey": jnp.asarray(st.vc_skey),
                "slot": jnp.asarray(st.vc_slot),
                "cstat": jnp.asarray(st.cstat),
            },
        })
        self._sharded.place()

    # -- placement --------------------------------------------------------
    def place(self, pos: np.ndarray, hp: np.ndarray, atk: np.ndarray,
              camp: np.ndarray) -> None:
        """Distribute entities into per-shard bank rows by their slab.

        Vectorized: one stable argsort by owning shard, per-shard base
        offsets, and a single fancy-index write per bank.  Rows seed the
        ClassState banks DIRECTLY (device-only population): per-guid
        host allocation would be O(n) interpreter work at placement, and
        these rows never need host identity — the host alloc_mask stays
        all-False, so migration-vacated slots never reconcile as deaths.
        """
        g = self.geom
        n = pos.shape[0]
        cy = np.clip((pos[:, 1] / g.cell_size).astype(np.int32), 0,
                     g.width - 1)
        owner = cy // g.slab_h
        counts = np.bincount(owner, minlength=g.n_shards)
        bank = self.bank_size or int(1 << int(np.ceil(np.log2(
            max(counts.max() * 2, 64)))))
        over = np.flatnonzero(counts > bank)
        if over.size:
            raise ValueError(f"bank {int(over[0])} overflow at placement")
        cap = bank * g.n_shards
        self.bank_size = bank
        self._build_kernel(cap)
        i32 = np.zeros((cap, 5), np.int32)
        i32[:, _GID] = -1
        i32[:, _DIED] = -1
        vec = np.zeros((cap, 1, 3), np.float32)
        alive = np.zeros((cap,), bool)
        if n:
            order = np.argsort(owner, kind="stable")
            so = owner[order]
            starts = np.zeros(g.n_shards, np.int64)
            starts[1:] = np.cumsum(counts)[:-1]
            r = so.astype(np.int64) * bank + (np.arange(n) - starts[so])
            vec[r, 0, 0] = pos[order, 0]
            vec[r, 0, 1] = pos[order, 1]
            i32[r, _HP] = hp[order]
            i32[r, _ATK] = atk[order]
            i32[r, _CAMP] = camp[order]
            i32[r, _GID] = order
            alive[r] = True
        k = self._kernel
        cs = k.state.classes["spatial"].replace(
            i32=jnp.asarray(i32), vec=jnp.asarray(vec),
            alive=jnp.asarray(alive),
        )
        k.state = with_class(k.state, "spatial", cs)
        self._sharded.place()

    # -- compiled step ----------------------------------------------------
    def step(self, n: int = 1) -> None:
        sk = self._sharded
        for _ in range(n):
            sk.run_device(1, fused=False)
        aux = self._kernel.state.aux
        mig = np.asarray(aux[self._mig.aux_key])
        cmb = np.asarray(aux[COMBAT_STATS_AUX])
        self.stats_last = np.concatenate([mig, cmb], axis=1)
        # runtime alerting, same contract as CombatModule's overflow
        # budget (the counters alone are bench-only visibility):
        # - mig_dropped rows left their source bank and found no free
        #   slot at the destination — permanently LOST, always alert
        #   (should never fire now that senders clamp to advertised
        #   destination capacity)
        # - rows that missed migration (budget or capacity clamp) are a
        #   SUBSET of `misplaced` — every unmigrated row is still active
        #   with owner != me when misplaced is counted — so `missed`
        #   counts misplaced + bucket drops and each affected row once
        #   (adding mig_overflow on top would double-count)
        lost_forever = int(self.stats_last[:, 2].sum())
        missed = int(self.stats_last[:, 3].sum()) + int(
            self.stats_last[:, 4:].sum()
        )
        if lost_forever or missed:
            alive = self._kernel.state.classes["spatial"].alive
            pop = max(1, int(np.asarray(alive).sum()))
            if lost_forever or missed / pop > self.overflow_budget:
                self.overflow_alerts += 1
                import logging

                logging.getLogger("nf.spatial").warning(
                    "spatial overflow: %d rows lost (bank full), %d "
                    "missed combat/migration this tick (%.4f%% of %d, "
                    "budget %.4f%%) - stats %s",
                    lost_forever, missed, 100 * missed / pop, pop,
                    100 * self.overflow_budget,
                    self.stats_last.sum(axis=0).tolist(),
                )
            # cell-bucket drops specifically (columns 4:6) respond to a
            # bucket resize; migration misses do not
            drops = int(self.stats_last[:, 4:].sum())
            if (
                self.auto_resize
                and drops / pop > self.overflow_budget
                and self._bucket_boost < self.max_bucket_boost
            ):
                self._resize_buckets(drops, pop)

    def _resize_buckets(self, drops: int, pop: int) -> None:
        """Double both cell buckets and retrace — the SpatialGeom twin of
        CombatModule._on_overflow.  Kernel.invalidate() drops the traces
        AND the registered aux (the carried Verlet cache bakes the old
        bucket into its slot assignment); the lifetime counters in cstat
        survive by being written back into the re-primed cache."""
        self._bucket_boost *= 2
        g = self.geom
        self.geom = g._replace(bucket=g.bucket * 2, att_bucket=g.att_bucket * 2)
        k = self._kernel
        old_cstat = k.state.aux[VC_AUX]["cstat"]
        # sanctioned retrace: the doubled buckets bake into the next trace
        k.invalidate()
        k._ensure_aux()
        vc = dict(k.state.aux[VC_AUX])
        vc["cstat"] = old_cstat
        k.state = k.state.replace(aux={**k.state.aux, VC_AUX: vc})
        import logging

        logging.getLogger("nf.spatial").warning(
            "cell-bucket overflow: %d drops over %d rows breached budget "
            "%.4f%%; buckets doubled to %d/%d (boost x%d of max x%d), "
            "step retraced",
            drops, pop, 100 * self.overflow_budget,
            self.geom.bucket, self.geom.att_bucket,
            self._bucket_boost, self.max_bucket_boost,
        )

    # -- Verlet cache visibility ------------------------------------------
    @property
    def rebuilds_total(self) -> int:
        """Max over shards (the pmax vote makes every shard rebuild
        together, so any shard's counter is the grid's)."""
        if self._kernel is None:
            return 0
        return int(np.asarray(self.state.cstat)[:, 0].max())

    @property
    def reuses_total(self) -> int:
        if self._kernel is None:
            return 0
        return int(np.asarray(self.state.cstat)[:, 1].max())

    # -- host observation -------------------------------------------------
    def gather(self):
        """{gid: (x, y, hp)} for live rows — host-side verification."""
        st = jax.tree.map(np.asarray, self.state)
        out = {}
        for r in np.flatnonzero(st.active):
            out[int(st.gid[r])] = (
                float(st.pos[r, 0]), float(st.pos[r, 1]), int(st.hp[r])
            )
        return out

    # -- checkpoint / resume ----------------------------------------------
    def save(self, path: str) -> None:
        """Snapshot banks + tick counter; resuming continues the exact
        trajectory (the walk/duty are pure functions of (gid, tick)).
        The npz keys are the historical slab-engine layout, so old
        snapshots load into the unified engine and vice versa; `layout`
        marks full-row snapshots (absent = pre-unification slab file)."""
        st = jax.tree.map(np.asarray, self.state)
        np.savez_compressed(
            path, tick=self.tick_count, bank=self.bank_size,
            binning=binning_mode(), layout="classrow", **st._asdict(),
        )

    def load(self, path: str) -> None:
        with np.load(path) as z:
            tick = int(z["tick"])
            self.bank_size = int(z["bank"])
            cap = z["pos"].shape[0]
            # snapshots from before the Verlet cache carry zero caches:
            # the all-False anchor mask forces a rebuild on the first
            # tick, so resume trajectories are unchanged
            fresh = {
                "vc_pos": np.zeros((cap, 2), np.float32),
                "vc_active": np.zeros((cap,), bool),
                "vc_order": np.zeros((cap,), np.int32),
                "vc_skey": np.zeros((cap,), np.int32),
                "vc_slot": np.zeros((cap,), np.int32),
                "cstat": np.zeros((self.geom.n_shards, 3), np.int32),
            }
            # vc_order/vc_skey are NF_BINNING-engine-specific (sorted
            # keys vs per-row anchor keys — VerletCache docstring), and a
            # pre-unification slab snapshot (no `layout` key) recorded
            # binning but not the full-row layout this engine carries: in
            # either mismatch the cache is dropped (all-False anchors =>
            # first tick rebuilds; trajectories are unchanged) and only
            # the row banks load.  Geometry is re-derived from this
            # world's SpatialGeom + the stored bank size.
            stored = str(z["binning"]) if "binning" in z.files else "sort"
            layout = str(z["layout"]) if "layout" in z.files else "slab"
            drop_cache = stored != binning_mode() or layout != "classrow"

            def pick(f):
                if f in z.files and not (drop_cache and f.startswith("vc_")):
                    return z[f]
                return fresh[f]

            self._build_kernel(cap)
            self.state = SpatialState(
                *[pick(f) for f in SpatialState._fields]
            )
            self.tick_count = tick


def reference_step(geom: SpatialGeom, pos, hp, atk, camp, gid, died, active,
                   tick):
    """Single-device twin of the spatial tick (same movement, same
    attacker duty, the square-grid combat_fold_xla, the same
    _life_phases chain) — the parity oracle for tests and the
    global-sort side of the A/B."""
    from ..game.combat import combat_fold_xla

    pos = _walk(pos, gid, tick, geom)
    f32 = jnp.float32
    camp_f = camp.astype(f32)
    zeros_f = jnp.zeros_like(camp_f)
    vic_feats = jnp.stack([pos[:, 0], pos[:, 1], camp_f, zeros_f, zeros_f], -1)
    attacking = active & (hp > 0) & ((gid + tick) % geom.attack_period == 0)
    eff_atk = jnp.where(attacking, atk, 0).astype(f32)
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], eff_atk, camp_f, zeros_f, zeros_f,
         gid.astype(f32)],
        -1,
    )
    vic_t, att_t = build_cell_table_pair(
        pos, active, vic_feats, attacking, att_feats,
        geom.cell_size, geom.width, geom.bucket, geom.att_bucket,
    )
    inc, _bestr = combat_fold_xla(vic_t, att_t, geom.radius)
    pulled = pull(vic_t, inc, fill=0)
    incoming = jnp.where(active & (hp > 0), pulled, 0)
    hp, died = _life_phases(geom, hp, died, incoming, tick)
    return pos, hp, died
