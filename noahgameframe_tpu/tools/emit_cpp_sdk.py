"""C++ client-SDK emitter: wire messages + proto2 codec + framing.

The reference ships native client SDKs (NFClient/Unity3D C#, Cocos C++)
that speak the 6-byte-frame + protobuf MsgBase protocol.  Here the
client binding is GENERATED from the same declarative message set the
server speaks (net/wire.py + net/wire_families.py FIELDS tables), so
client and server can never drift: one header, zero dependencies, C++11.

Emitted surface per message:  a struct with typed fields + `has_<f>`
presence flags, `Encode(std::string&)` writing proto2 wire format in tag
order (matching protoc byte-for-byte, like the Python codec), and
`Decode(ptr, len)` tolerating unknown fields.  Plus frame helpers for
the u16 msg-id / u32 total-size big-endian header (`NFINet.h:63-68`).

tests/test_cpp_sdk.py compiles the emitted header with g++ and
round-trips real bytes against the Python codec.
"""

from __future__ import annotations

import io
from typing import List

from ..net import wire, wire_families
from ..net.wire import Message

_SCALAR_CPP = {
    "int32": "int32_t",
    "int64": "int64_t",
    "uint64": "uint64_t",
    "bool": "bool",
    "enum": "int32_t",
    "float": "float",
    "double": "double",
    "bytes": "std::string",
    "string": "std::string",
}

_RUNTIME = r"""// GENERATED client SDK - do not edit by hand.
// Regenerate with: python -m noahgameframe_tpu.tools.emit_cpp_sdk > nfmsg.hpp
#pragma once
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace nfmsg {

// ----------------------------------------------------------- wire codec
inline void put_varint(std::string& out, uint64_t v) {
    while (v >= 0x80) { out.push_back(char((v & 0x7F) | 0x80)); v >>= 7; }
    out.push_back(char(v));
}
inline void put_tag(std::string& out, uint32_t tag, uint32_t wt) {
    put_varint(out, (uint64_t(tag) << 3) | wt);
}
inline void put_i64v(std::string& out, int64_t v) { put_varint(out, uint64_t(v)); }
inline void put_f32(std::string& out, float v) {
    char b[4]; std::memcpy(b, &v, 4); out.append(b, 4);
}
inline void put_f64(std::string& out, double v) {
    char b[8]; std::memcpy(b, &v, 8); out.append(b, 8);
}
inline void put_bytes(std::string& out, const std::string& v) {
    put_varint(out, v.size()); out.append(v);
}

struct Reader {
    const uint8_t* p; const uint8_t* end; bool ok = true;
    Reader(const void* d, size_t n)
        : p(static_cast<const uint8_t*>(d)), end(p + n) {}
    bool done() const { return p >= end; }
    uint64_t varint() {
        uint64_t v = 0; int shift = 0;
        while (p < end && shift <= 63) {
            uint8_t b = *p++;
            v |= uint64_t(b & 0x7F) << shift;
            if (!(b & 0x80)) return v;
            shift += 7;
        }
        ok = false; return 0;
    }
    float f32() {
        if (end - p < 4) { ok = false; return 0; }
        float v; std::memcpy(&v, p, 4); p += 4; return v;
    }
    double f64() {
        if (end - p < 8) { ok = false; return 0; }
        double v; std::memcpy(&v, p, 8); p += 8; return v;
    }
    std::string bytes() {
        uint64_t n = varint();
        if (!ok || uint64_t(end - p) < n) { ok = false; return {}; }
        std::string s(reinterpret_cast<const char*>(p), size_t(n)); p += n;
        return s;
    }
    void skip(uint32_t wt) {
        switch (wt) {
            case 0: varint(); break;
            case 1: p += 8; break;
            case 2: { uint64_t n = varint();
                      if (uint64_t(end - p) < n) ok = false; else p += n; break; }
            case 5: p += 4; break;
            default: ok = false;
        }
        if (p > end) ok = false;
    }
};

// ------------------------------------------------------ 6-byte framing
// u16 msg-id + u32 total-size, big-endian (total includes the header).
inline void frame(std::string& out, uint16_t msg_id, const std::string& body) {
    uint32_t total = uint32_t(body.size() + 6);
    out.push_back(char(msg_id >> 8)); out.push_back(char(msg_id & 0xFF));
    out.push_back(char(total >> 24)); out.push_back(char(total >> 16));
    out.push_back(char(total >> 8)); out.push_back(char(total));
    out.append(body);
}
// Max frame size mirrors the server codec (net/framing.py): a header
// announcing more is a protocol error, not a reason to buffer gigabytes.
const uint32_t kMaxFrameSize = 64u * 1024u * 1024u;

enum UnframeResult { UNFRAME_NEED_MORE = 0, UNFRAME_OK = 1, UNFRAME_ERROR = -1 };

inline UnframeResult unframe(const std::string& buf, size_t& off,
                             uint16_t& msg_id, std::string& body) {
    if (buf.size() - off < 6) return UNFRAME_NEED_MORE;
    const uint8_t* d = reinterpret_cast<const uint8_t*>(buf.data()) + off;
    msg_id = uint16_t(d[0]) << 8 | d[1];
    uint32_t total = uint32_t(d[2]) << 24 | uint32_t(d[3]) << 16 |
                     uint32_t(d[4]) << 8 | d[5];
    if (total < 6 || total > kMaxFrameSize) return UNFRAME_ERROR;
    if (buf.size() - off < total) return UNFRAME_NEED_MORE;
    body.assign(buf, off + 6, total - 6);
    off += total;
    return UNFRAME_OK;
}
"""


def _collect() -> List[type]:
    """All wire message classes, dependency-ordered (definition order —
    both modules define embedded messages before use)."""
    seen = {}
    for mod in (wire, wire_families):
        for c in vars(mod).values():
            if isinstance(c, type) and issubclass(c, Message) and c is not Message:
                seen.setdefault(c.__name__, c)
    return list(seen.values())


def _is_msg(t) -> bool:
    return isinstance(t, type) and issubclass(t, Message)


def _cpp_type(t) -> str:
    if _is_msg(t):
        return t.__name__
    return _SCALAR_CPP[t]


def _enc_scalar(field: str, t: str, out: io.StringIO, indent: str) -> None:
    w = out.write
    if t in ("int32", "int64", "uint64", "bool", "enum"):
        w(f"{indent}put_i64v(nf__out, int64_t({field}));\n")
    elif t == "float":
        w(f"{indent}put_f32(nf__out, {field});\n")
    elif t == "double":
        w(f"{indent}put_f64(nf__out, {field});\n")
    else:
        w(f"{indent}put_bytes(nf__out, {field});\n")


_WT = {"int32": 0, "int64": 0, "uint64": 0, "bool": 0, "enum": 0,
       "float": 5, "double": 1, "bytes": 2, "string": 2}

_DEC_SCALAR = {
    "int32": "int32_t(nf__r.varint())",
    "enum": "int32_t(nf__r.varint())",
    "int64": "int64_t(nf__r.varint())",
    "uint64": "nf__r.varint()",
    "bool": "(nf__r.varint() != 0)",
    "float": "nf__r.f32()",
    "double": "nf__r.f64()",
    "bytes": "nf__r.bytes()",
    "string": "nf__r.bytes()",
}


def emit_header() -> str:
    out = io.StringIO()
    w = out.write
    w(_RUNTIME)
    w("\n// ------------------------------------------------ messages\n")
    for cls in _collect():
        name = cls.__name__
        w(f"\nstruct {name} {{\n")
        for tag, fname, ftype, _ in cls.FIELDS:
            if isinstance(ftype, tuple):
                w(f"    std::vector<{_cpp_type(ftype[1])}> {fname};\n")
            else:
                w(f"    {_cpp_type(ftype)} {fname}{{}};\n")
                w(f"    bool has_{fname} = false;\n")
        # ---- encode
        w("    void Encode(std::string& nf__out) const {\n")
        for tag, fname, ftype, _ in cls.FIELDS:
            if isinstance(ftype, tuple):
                inner = ftype[1]
                w(f"        for (const auto& nf__it : {fname}) {{\n")
                if _is_msg(inner):
                    w(f"            put_tag(nf__out, {tag}, 2);\n")
                    w("            std::string nf__sub; nf__it.Encode(nf__sub);\n")
                    w("            put_bytes(nf__out, nf__sub);\n")
                else:
                    w(f"            put_tag(nf__out, {tag}, {_WT[inner]});\n")
                    _enc_scalar("nf__it", inner, out, "            ")
                w("        }\n")
            elif _is_msg(ftype):
                w(f"        if (has_{fname}) {{\n")
                w(f"            put_tag(nf__out, {tag}, 2);\n")
                w(f"            std::string nf__sub; {fname}.Encode(nf__sub);\n")
                w("            put_bytes(nf__out, nf__sub);\n")
                w("        }\n")
            else:
                w(f"        if (has_{fname}) {{\n")
                w(f"            put_tag(nf__out, {tag}, {_WT[ftype]});\n")
                _enc_scalar(fname, ftype, out, "            ")
                w("        }\n")
        w("    }\n")
        w("    std::string Encode() const {\n")
        w("        std::string nf__s; Encode(nf__s); return nf__s;\n    }\n")
        # ---- clear (Decode resets to defaults first, like protobuf Parse)
        w("    void Clear() {\n")
        for _tag, fname, ftype, _ in cls.FIELDS:
            if isinstance(ftype, tuple):
                w(f"        {fname}.clear();\n")
            else:
                w(f"        {fname} = {_cpp_type(ftype)}{{}};\n")
                w(f"        has_{fname} = false;\n")
        w("    }\n")
        # ---- decode
        w("    bool Decode(const void* nf__data, size_t nf__len) {\n")
        w("        Clear();\n")
        w("        Reader nf__r(nf__data, nf__len);\n")
        w("        while (!nf__r.done()) {\n")
        w("            uint64_t nf__key = nf__r.varint();\n")
        w("            if (!nf__r.ok) return false;\n")
        w("            switch (uint32_t(nf__key >> 3)) {\n")
        for tag, fname, ftype, _ in cls.FIELDS:
            rep = isinstance(ftype, tuple)
            inner = ftype[1] if rep else ftype
            expected_wt = 2 if _is_msg(inner) else _WT[inner]
            w(f"            case {tag}: {{\n")
            # a known tag with the wrong wire type is treated like an
            # unknown field (skip by actual type, stream stays aligned)
            w(f"                if (uint32_t(nf__key & 7) != {expected_wt}) {{\n")
            w("                    nf__r.skip(uint32_t(nf__key & 7));\n")
            w("                    if (!nf__r.ok) return false;\n")
            w("                    break;\n                }\n")
            if _is_msg(inner):
                w("                std::string nf__sub = nf__r.bytes();\n")
                w("                if (!nf__r.ok) return false;\n")
                if rep:
                    w(f"                {_cpp_type(inner)} nf__tmp{{}};\n")
                    w("                if (!nf__tmp.Decode(nf__sub.data(), nf__sub.size())) return false;\n")
                else:
                    w(f"                if (!{fname}.Decode(nf__sub.data(), nf__sub.size())) return false;\n")
            else:
                expr = _DEC_SCALAR[inner]
                if rep:
                    w(f"                {_cpp_type(inner)} nf__tmp = {expr};\n")
                else:
                    w(f"                {fname} = {expr};\n")
                w("                if (!nf__r.ok) return false;\n")
            if rep:
                w(f"                {fname}.push_back(nf__tmp);\n")
            else:
                w(f"                has_{fname} = true;\n")
            w("                break;\n            }\n")
        w("            default:\n")
        w("                nf__r.skip(uint32_t(nf__key & 7));\n")
        w("                if (!nf__r.ok) return false;\n")
        w("            }\n        }\n        return nf__r.ok;\n    }\n")
        w("};\n")
    w("\n}  // namespace nfmsg\n")
    return out.getvalue()


if __name__ == "__main__":
    print(emit_header())
