"""Codegen tooling: the NFFileProcess-equivalent config pipeline."""

from .codegen import (  # noqa: F401
    CodegenPipeline,
    emit_instance_xml,
    emit_logic_class_xml,
    emit_name_constants,
    emit_name_constants_cs,
    emit_name_constants_java,
    load_class_csv,
    load_class_xlsx,
)
from .xlsx import read_xlsx_sheets  # noqa: F401
