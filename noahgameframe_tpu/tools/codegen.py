"""The config codegen pipeline — NFFileProcess re-imagined.

Reference: `NFTools/NFFileProcess` turns Excel workbooks into Struct XML
+ Ini XML + `NFProtocolDefine.{hpp,java,cs}` + `NFrame.sql`
(`FileProcess.h:38-72` lists every emitter), and `GenerateConfigXML.sh`
runs it and copies configs into `_Out/NFDataCfg`.

This pipeline accepts CSV or XLSX class sheets and emits:
- ``Struct/LogicClass.xml`` + ``Struct/Class/<name>.xml`` in the exact
  reference format (`core.schema.load_logic_class_xml` round-trips it);
- ``Ini/<class>.xml`` instance files (`ElementStore.load_instance_xml``
  round-trips those);
- ``proto_define.py`` — the NFProtocolDefine equivalent: one namespace
  class per entity class with property/record name constants, so game
  code writes ``NF.Player.HP`` instead of bare strings;
- ``NFrame.sql`` via ``persist.sql.emit_ddl``.

Sheet layout (CSV sections / XLSX sheets):
- ``class`` row: ``name``,``parent``
- ``property`` table: Name,Type,Public,Private,Save,Cache,Ref,Upload,Desc
- ``record:<RecName>`` table header carries rows/flags; body lists
  Tag,Type columns
- ``components`` table: Name,Language
"""

from __future__ import annotations

import csv
import io
import keyword
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple
from xml.dom import minidom

from ..core.datatypes import DataType
from ..core.schema import (
    ClassDef,
    ClassRegistry,
    ComponentDef,
    PropertyDef,
    RecordColDef,
    RecordDef,
)

_TYPE_NAME = {
    DataType.INT: "int",
    DataType.FLOAT: "float",
    DataType.STRING: "string",
    DataType.OBJECT: "object",
    DataType.VECTOR2: "vector2",
    DataType.VECTOR3: "vector3",
}
_NAME_TYPE = {v: k for k, v in _TYPE_NAME.items()}

_FLAGS = ("Public", "Private", "Save", "Cache", "Ref", "Upload")


def _truthy(v) -> bool:
    return str(v or "").strip().lower() in ("1", "true", "yes")


# =====================================================================
# Input: CSV / XLSX class sheets -> ClassDef
# =====================================================================


def _parse_sections(rows: List[List[str]]) -> Dict[str, List[List[str]]]:
    """Split a sheet into [section]-headed tables."""
    sections: Dict[str, List[List[str]]] = {}
    current: Optional[str] = None
    for row in rows:
        cells = ["" if c is None else str(c).strip() for c in row]
        if not any(cells):
            continue
        head = cells[0]
        if head.startswith("[") and head.endswith("]"):
            sec = head[1:-1].strip()
            if sec.lower().startswith("record:"):
                # keep the record's name case, lowercase only the tag
                current = "record:" + sec.split(":", 1)[1].strip()
            else:
                current = sec.lower()
            sections.setdefault(current, [])
            # section header rows may carry key=value pairs after the tag
            extras = [c for c in cells[1:] if c]
            if extras:
                sections[current].append(["__kv__", *extras])
            continue
        if current is not None:
            sections[current].append(cells)
    return sections


def _table(rows: List[List[str]]) -> List[Dict[str, str]]:
    """First non-kv row is the header; the rest map header->cell."""
    body = [r for r in rows if r and r[0] != "__kv__"]
    if not body:
        return []
    header = [h.strip() for h in body[0]]
    out = []
    for r in body[1:]:
        out.append({header[i]: (r[i] if i < len(r) else "")
                    for i in range(len(header)) if header[i]})
    return out


def _kv(rows: List[List[str]]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for r in rows:
        if r and r[0] == "__kv__":
            for cell in r[1:]:
                if "=" in cell:
                    k, _, v = cell.partition("=")
                    out[k.strip().lower()] = v.strip()
    return out


def _class_from_sections(
    sections: Dict[str, List[List[str]]], default_name: str
) -> ClassDef:
    meta = _kv(sections.get("class", []))
    for row in _table(sections.get("class", [])):
        meta.setdefault("name", row.get("name", ""))
        meta.setdefault("parent", row.get("parent", ""))
    name = meta.get("name") or default_name
    parent = meta.get("parent") or None

    props = []
    for row in _table(sections.get("property", [])):
        pname = row.get("Name", "").strip()
        if not pname:
            continue
        props.append(PropertyDef(
            name=pname,
            type=_NAME_TYPE[(row.get("Type") or "int").strip().lower()],
            public=_truthy(row.get("Public")),
            private=_truthy(row.get("Private")),
            save=_truthy(row.get("Save")),
            cache=_truthy(row.get("Cache")),
            ref=_truthy(row.get("Ref")),
            upload=_truthy(row.get("Upload")),
            desc=row.get("Desc", ""),
        ))

    records = []
    for key, rows in sections.items():
        if not key.startswith("record:"):
            continue
        rname = key.split(":", 1)[1].strip()
        meta_r = _kv(rows)
        cols = tuple(
            RecordColDef(tag=row["Tag"].strip(),
                         type=_NAME_TYPE[(row.get("Type") or "int").strip().lower()])
            for row in _table(rows)
            if row.get("Tag", "").strip()
        )
        records.append(RecordDef(
            name=rname,
            max_rows=int(meta_r.get("rows", "1")),
            cols=cols,
            public=_truthy(meta_r.get("public")),
            private=_truthy(meta_r.get("private")),
            save=_truthy(meta_r.get("save")),
            cache=_truthy(meta_r.get("cache")),
            upload=_truthy(meta_r.get("upload")),
        ))

    comps = [
        ComponentDef(name=row.get("Name", ""),
                     language=row.get("Language", "python"))
        for row in _table(sections.get("components", []))
        if row.get("Name", "").strip()
    ]
    return ClassDef(name=name, parent=parent, properties=props,
                    records=records, components=comps,
                    instance_path=meta.get("instancepath", ""))


def load_class_csv(path: Path) -> ClassDef:
    """One CSV file -> ClassDef (sections per module docstring)."""
    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    return _class_from_sections(_parse_sections(rows), Path(path).stem)


def load_class_xlsx(path: Path) -> List[ClassDef]:
    """One workbook -> ClassDefs (one sheet per class; each sheet uses
    the same [section] layout in column A)."""
    from .xlsx import read_xlsx_sheets

    out = []
    for sheet_name, rows in read_xlsx_sheets(path).items():
        str_rows = [["" if c is None else str(c) for c in r] for r in rows]
        out.append(_class_from_sections(_parse_sections(str_rows), sheet_name))
    return out


# =====================================================================
# Output: reference-format Struct XML
# =====================================================================


def _pretty(elem: ET.Element) -> str:
    raw = ET.tostring(elem, encoding="unicode")
    return minidom.parseString(raw).toprettyxml(indent="    ")


def _flags_attrs(d) -> Dict[str, str]:
    return {f: ("1" if d.flag(f.lower()) else "0") for f in _FLAGS
            if hasattr(d, f.lower())}


def emit_class_xml(cdef: ClassDef) -> str:
    root = ET.Element("XML")
    props = ET.SubElement(root, "Propertys")
    for p in cdef.properties:
        ET.SubElement(props, "Property", {
            "Id": p.name,
            "Type": _TYPE_NAME[p.type],
            **_flags_attrs(p),
            **({"Desc": p.desc} if p.desc else {}),
        })
    recs = ET.SubElement(root, "Records")
    for r in cdef.records:
        rec_el = ET.SubElement(recs, "Record", {
            "Id": r.name,
            "Row": str(r.max_rows),
            "Col": str(len(r.cols)),
            **{f: ("1" if r.flag(f.lower()) else "0")
               for f in ("Public", "Private", "Save", "Cache", "Upload")},
        })
        for c in r.cols:
            ET.SubElement(rec_el, "Col",
                          {"Tag": c.tag, "Type": _TYPE_NAME[c.type]})
    comps = ET.SubElement(root, "Components")
    for c in cdef.components:
        ET.SubElement(comps, "Component", {
            "Name": c.name, "Language": c.language,
            "Enable": "1" if c.enable else "0",
        })
    return _pretty(root)


def emit_logic_class_xml(
    registry: ClassRegistry, out_root: Path,
    root_class: str = "IObject",
) -> List[Path]:
    """Write Struct/LogicClass.xml + Struct/Class/<name>.xml mirroring the
    reference layout; returns written paths."""
    out_root = Path(out_root)
    struct = out_root / "Struct"
    class_dir = struct / "Class"
    class_dir.mkdir(parents=True, exist_ok=True)
    written: List[Path] = []

    children: Dict[Optional[str], List[str]] = {}
    for name in registry.names():
        children.setdefault(registry.get_def(name).parent, []).append(name)

    def class_el(parent_el: ET.Element, name: str) -> None:
        cdef = registry.get_def(name)
        el = ET.SubElement(parent_el, "Class", {
            "Id": name,
            "Path": f"Struct/Class/{name}.xml",
            **({"InstancePath": cdef.instance_path}
               if cdef.instance_path else {}),
        })
        p = class_dir / f"{name}.xml"
        p.write_text(emit_class_xml(cdef))
        written.append(p)
        for child in children.get(name, []):
            class_el(el, child)

    root = ET.Element("XML")
    for top in children.get(None, []):
        class_el(root, top)
    emitted = {p.stem for p in written}
    missing = [n for n in registry.names() if n not in emitted]
    if missing:
        raise ValueError(
            f"classes {missing} unreachable from a root class — missing "
            "parent definition or a parent cycle"
        )
    logic = struct / "LogicClass.xml"
    logic.write_text(_pretty(root))
    written.append(logic)
    return written


def emit_instance_xml(
    elements: Sequence[Dict[str, str]], out_path: Path
) -> Path:
    """Rows of {Id, prop: value} -> reference Ini XML."""
    root = ET.Element("XML")
    for row in elements:
        ET.SubElement(root, "Object",
                      {k: str(v) for k, v in row.items() if v is not None})
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    out_path.write_text(_pretty(root))
    return out_path


# =====================================================================
# Output: name-constant module (NFProtocolDefine equivalent)
# =====================================================================


_PY_KEYWORDS = frozenset(keyword.kwlist)


def _py_ident(name: str) -> str:
    return _sanitize_ident(name, _PY_KEYWORDS)


def emit_name_constants(registry: ClassRegistry) -> str:
    """Python module text: one class per entity class, string constants
    per property/record (+ record column indices), mirroring
    `NFProtocolDefine.hpp`'s `NFrame::Player::HP()` bindings."""
    out = io.StringIO()
    out.write('"""GENERATED name constants — do not edit by hand.\n\n')
    out.write("Regenerate with scripts/codegen.py (the NFProtocolDefine\n")
    out.write("equivalent of the reference codegen).\n"
              '"""\n\n')
    for name in registry.names():
        flat = registry._flatten(name)
        out.write(f"\nclass {_py_ident(name)}:\n")
        out.write(f'    ThisName = "{name}"\n')
        for p in flat.properties:
            out.write(f'    {_py_ident(p.name)} = "{p.name}"\n')
        for r in flat.records:
            rid = _py_ident(r.name)
            out.write(f"\n    class R_{rid}:\n")
            out.write(f'        ThisName = "{r.name}"\n')
            out.write(f"        MaxRows = {r.max_rows}\n")
            for i, c in enumerate(r.cols):
                out.write(f"        Col_{_py_ident(c.tag)} = {i}\n")
    return out.getvalue()


_CS_KEYWORDS = {
    "abstract", "as", "base", "bool", "break", "byte", "case", "catch",
    "char", "checked", "class", "const", "continue", "decimal", "default",
    "delegate", "do", "double", "else", "enum", "event", "explicit",
    "extern", "false", "finally", "fixed", "float", "for", "foreach",
    "goto", "if", "implicit", "in", "int", "interface", "internal", "is",
    "lock", "long", "namespace", "new", "null", "object", "operator",
    "out", "override", "params", "private", "protected", "public",
    "readonly", "ref", "return", "sbyte", "sealed", "short", "sizeof",
    "stackalloc", "static", "string", "struct", "switch", "this", "throw",
    "true", "try", "typeof", "uint", "ulong", "unchecked", "unsafe",
    "ushort", "using", "virtual", "void", "volatile", "while",
}


def _sanitize_ident(name: str, keywords, used: Optional[set] = None) -> str:
    """Language-safe identifier; with `used`, also unique within that scope
    (distinct schema names like 'a-b' vs 'a_b' both sanitize to 'a_b' —
    emitting both would fail compilation)."""
    ident = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in name)
    if not ident or ident[0].isdigit() or ident in keywords:
        ident = "_" + ident
    if used is not None:
        base, n = ident, 2
        while ident in used:
            ident = f"{base}_{n}"
            n += 1
        used.add(ident)
    return ident


def _cs_ident(name: str, used: Optional[set] = None) -> str:
    return _sanitize_ident(name, _CS_KEYWORDS, used)


def emit_name_constants_cs(registry: ClassRegistry) -> str:
    """C# source text for the Unity client SDK: per-class name constants
    and record column indices in an `NFrame` namespace, matching the
    reference codegen's .cs emitter
    (NFTools/NFFileProcess FileProcess.h:38-72 emits NFProtocolDefine.cs
    alongside the .hpp/.java bindings)."""
    out = io.StringIO()
    out.write("// GENERATED name constants - do not edit by hand.\n")
    out.write("// Regenerate with scripts/codegen.py.\n\n")
    out.write("namespace NFrame\n{\n")
    top_used: set = set()
    for name in registry.names():
        flat = registry._flatten(name)
        cls = _cs_ident(name, top_used)
        # a member named like its enclosing type is a C# error (CS0542)
        used = {cls, "ThisName"}
        out.write(f"    public static class {cls}\n    {{\n")
        out.write(f'        public const string ThisName = "{name}";\n')
        for p in flat.properties:
            out.write(
                f'        public const string {_cs_ident(p.name, used)} = "{p.name}";\n'
            )
        for r in flat.records:
            rid = _cs_ident(f"R_{r.name}", used)
            rec_used = {"ThisName", "MaxRows"}
            out.write(f"\n        public static class {rid}\n        {{\n")
            out.write(f'            public const string ThisName = "{r.name}";\n')
            out.write(f"            public const int MaxRows = {r.max_rows};\n")
            for i, c in enumerate(r.cols):
                out.write(
                    f"            public const int "
                    f"{_cs_ident(f'Col_{c.tag}', rec_used)} = {i};\n"
                )
            out.write("        }\n")
        out.write("    }\n\n")
    out.write("}\n")
    return out.getvalue()


_JAVA_KEYWORDS = {
    "abstract", "assert", "boolean", "break", "byte", "case", "catch",
    "char", "class", "const", "continue", "default", "do", "double",
    "else", "enum", "extends", "final", "finally", "float", "for",
    "goto", "if", "implements", "import", "instanceof", "int",
    "interface", "long", "native", "new", "package", "private",
    "protected", "public", "return", "short", "static", "strictfp",
    "super", "switch", "synchronized", "this", "throw", "throws",
    "transient", "try", "void", "volatile", "while", "true", "false",
    "null", "_",  # `_` is a keyword as of Java 9
}


def _java_ident(name: str, used: Optional[set] = None) -> str:
    return _sanitize_ident(name, _JAVA_KEYWORDS, used)


def emit_name_constants_java(registry: ClassRegistry) -> str:
    """Java source for client bindings: per-class name constants + record
    column indices, the `NFProtocolDefine.java` output of the reference
    codegen (its _Out/NFDataCfg/proto/NFProtocolDefine.java artifact).

    Unlike the reference — which emits many top-level `public class`es in
    one file, which javac rejects — everything nests inside one
    `public final class NFProtocolDefine`, so the file actually compiles.
    """
    out = io.StringIO()
    out.write("// GENERATED name constants - do not edit by hand.\n")
    out.write("// Regenerate with scripts/codegen.py.\n\n")
    out.write("package nframe;\n\n")
    out.write("public final class NFProtocolDefine {\n")
    out.write("    private NFProtocolDefine() {}\n\n")
    top_used: set = {"NFProtocolDefine"}
    for name in registry.names():
        flat = registry._flatten(name)
        cls = _java_ident(name, top_used)
        used = {cls, "ThisName"}
        out.write(f"    public static final class {cls} {{\n")
        out.write(f"        private {cls}() {{}}\n")
        out.write(f'        public static final String ThisName = "{name}";\n')
        for p in flat.properties:
            out.write(
                f"        public static final String "
                f'{_java_ident(p.name, used)} = "{p.name}"; // {p.type.name}\n'
            )
        for r in flat.records:
            rid = _java_ident(f"R_{r.name}", used)
            rec_used = {rid, "ThisName", "MaxRows"}
            out.write(f"\n        public static final class {rid} {{\n")
            out.write(f"            private {rid}() {{}}\n")
            out.write(
                f'            public static final String ThisName = "{r.name}";\n'
            )
            out.write(
                f"            public static final int MaxRows = {r.max_rows};\n"
            )
            for i, c in enumerate(r.cols):
                out.write(
                    f"            public static final int "
                    f"{_java_ident(f'Col_{c.tag}', rec_used)} = {i};\n"
                )
            out.write("        }\n")
        out.write("    }\n\n")
    out.write("}\n")
    return out.getvalue()


# =====================================================================
# The pipeline (GenerateConfigXML.sh equivalent)
# =====================================================================


class CodegenPipeline:
    """in_dir (CSV/XLSX class sheets + <Class>.ini.csv element rows)
    -> out_dir (Struct XML, Ini XML, proto_define.py, NFrame.sql)."""

    def __init__(self, in_dir: Path, out_dir: Path) -> None:
        self.in_dir = Path(in_dir)
        self.out_dir = Path(out_dir)

    def run(self) -> Dict[str, List[str]]:
        registry = ClassRegistry()
        ini_files: List[Tuple[str, Path]] = []
        for p in sorted(self.in_dir.iterdir()):
            if p.suffixes[-2:] == [".ini", ".csv"]:
                ini_files.append((p.name[: -len(".ini.csv")], p))
            elif p.suffix == ".csv":
                registry.define(load_class_csv(p))
            elif p.suffix == ".xlsx":
                for cdef in load_class_xlsx(p):
                    registry.define(cdef)
        report: Dict[str, List[str]] = {"classes": registry.names()}

        # instance files first so InstancePath attributes are known before
        # the one-and-only Struct emit
        ini_out: List[str] = []
        for cname, path in ini_files:
            with open(path, newline="") as f:
                rows = list(csv.DictReader(f))
            out = emit_instance_xml(
                rows, self.out_dir / "Ini" / f"{cname}.xml"
            )
            ini_out.append(str(out))
            if cname in registry:
                cdef = registry.get_def(cname)
                if not cdef.instance_path:
                    cdef.instance_path = f"Ini/{cname}.xml"
        report["ini"] = ini_out

        written = emit_logic_class_xml(registry, self.out_dir)
        report["struct"] = [str(p) for p in written]

        consts = self.out_dir / "proto_define.py"
        consts.write_text(emit_name_constants(registry))
        cs = self.out_dir / "NFProtocolDefine.cs"
        cs.write_text(emit_name_constants_cs(registry))
        java = self.out_dir / "NFProtocolDefine.java"
        java.write_text(emit_name_constants_java(registry))
        report["constants"] = [str(consts), str(cs), str(java)]

        from ..persist.sql import emit_ddl

        sql = self.out_dir / "NFrame.sql"
        sql.write_text(emit_ddl(registry, registry.names()))
        report["sql"] = [str(sql)]
        return report
