"""C# client-SDK emitter: wire messages + proto2 codec + framing.

The reference's Unity3D client is C# (NFClient/Unity3D) speaking the
6-byte-frame + protobuf MsgBase protocol via protoc-generated classes.
Here the C# binding is GENERATED from the same declarative message set
the server speaks (net/wire.py + net/wire_families.py FIELDS tables), so
client and server can never drift: one file, zero dependencies, C# 7 /
.NET Standard — drop `NFMsg.cs` into a Unity project next to the
generated `NFProtocolDefine.cs` name constants (tools/codegen.py).

Emitted surface per message: a class with typed fields + `Has<F>`
presence flags, `Encode()` writing proto2 wire format in tag order
(matching protoc byte-for-byte, like the Python and C++ codecs), and
`Decode(byte[], offset, length)` tolerating unknown fields and wrong
wire types (skip, stay aligned).  Plus frame helpers for the u16 msg-id
/ u32 total-size big-endian header (NFINet.h:63-68).

The emitter mirrors tools/emit_cpp_sdk.py structurally; the structural
test (tests/test_cs_sdk.py) cross-checks every message, field, tag and
wire type in the emitted text against the FIELDS tables (no C# compiler
ships in this image, so byte-level verification rides on the C++ twin,
which IS compiled and byte-verified against the Python codec).
"""

from __future__ import annotations

import io
from typing import List

from .emit_cpp_sdk import _WT, _collect, _is_msg

_SCALAR_CS = {
    "int32": "int",
    "int64": "long",
    "uint64": "ulong",
    "bool": "bool",
    "enum": "int",
    "float": "float",
    "double": "double",
    "bytes": "byte[]",
    "string": "byte[]",  # NF strings are raw bytes on the wire; callers
    # use Nf.Utf8()/Nf.Str() to convert
}

_DEFAULT_CS = {
    "int32": "0",
    "int64": "0",
    "uint64": "0",
    "bool": "false",
    "enum": "0",
    "float": "0f",
    "double": "0d",
    "bytes": "Nf.Empty",
    "string": "Nf.Empty",
}

_RUNTIME = r"""// GENERATED client SDK - do not edit by hand.
// Regenerate with: python -m noahgameframe_tpu.tools.emit_cs_sdk > NFMsg.cs
using System;
using System.Collections.Generic;
using System.IO;
using System.Text;

namespace NFMsg
{
    // ------------------------------------------------------- wire codec
    public static class Nf
    {
        public static readonly byte[] Empty = new byte[0];
        public static byte[] Utf8(string s) { return Encoding.UTF8.GetBytes(s); }
        public static string Str(byte[] b) { return Encoding.UTF8.GetString(b); }

        public static void PutVarint(MemoryStream o, ulong v)
        {
            while (v >= 0x80) { o.WriteByte((byte)((v & 0x7F) | 0x80)); v >>= 7; }
            o.WriteByte((byte)v);
        }
        public static void PutTag(MemoryStream o, uint tag, uint wt)
        {
            PutVarint(o, ((ulong)tag << 3) | wt);
        }
        public static void PutI64(MemoryStream o, long v) { PutVarint(o, (ulong)v); }
        public static void PutF32(MemoryStream o, float v)
        {
            var b = BitConverter.GetBytes(v);
            if (!BitConverter.IsLittleEndian) Array.Reverse(b);
            o.Write(b, 0, 4);
        }
        public static void PutF64(MemoryStream o, double v)
        {
            var b = BitConverter.GetBytes(v);
            if (!BitConverter.IsLittleEndian) Array.Reverse(b);
            o.Write(b, 0, 8);
        }
        public static void PutBytes(MemoryStream o, byte[] v)
        {
            PutVarint(o, (ulong)v.Length); o.Write(v, 0, v.Length);
        }

        // ---------------------------------------------------- 6-byte framing
        // u16 msg-id + u32 total-size, big-endian (total includes header).
        public const uint MaxFrameSize = 64u * 1024u * 1024u;

        public static byte[] Frame(ushort msgId, byte[] body)
        {
            uint total = (uint)(body.Length + 6);
            var f = new byte[total];
            f[0] = (byte)(msgId >> 8); f[1] = (byte)msgId;
            f[2] = (byte)(total >> 24); f[3] = (byte)(total >> 16);
            f[4] = (byte)(total >> 8); f[5] = (byte)total;
            Buffer.BlockCopy(body, 0, f, 6, body.Length);
            return f;
        }

        /// Returns 1 (frame ready: msgId/body set, off advanced),
        /// 0 (need more data), -1 (protocol error).
        public static int Unframe(byte[] buf, int len, ref int off,
                                  out ushort msgId, out byte[] body)
        {
            msgId = 0; body = Empty;
            if (len - off < 6) return 0;
            msgId = (ushort)((buf[off] << 8) | buf[off + 1]);
            uint total = ((uint)buf[off + 2] << 24) | ((uint)buf[off + 3] << 16)
                       | ((uint)buf[off + 4] << 8) | buf[off + 5];
            if (total < 6 || total > MaxFrameSize) return -1;
            if (len - off < total) return 0;
            body = new byte[total - 6];
            Buffer.BlockCopy(buf, off + 6, body, 0, (int)(total - 6));
            off += (int)total;
            return 1;
        }
    }

    public class NfReader
    {
        public byte[] D; public int P; public int End; public bool Ok = true;
        public NfReader(byte[] d, int off, int len) { D = d; P = off; End = off + len; }
        public bool Done() { return P >= End; }
        public ulong Varint()
        {
            ulong v = 0; int shift = 0;
            while (P < End && shift <= 63)
            {
                byte b = D[P++];
                v |= (ulong)(b & 0x7F) << shift;
                if ((b & 0x80) == 0) return v;
                shift += 7;
            }
            Ok = false; return 0;
        }
        public float F32()
        {
            if (End - P < 4) { Ok = false; return 0; }
            var b = new byte[4]; Buffer.BlockCopy(D, P, b, 0, 4); P += 4;
            if (!BitConverter.IsLittleEndian) Array.Reverse(b);
            return BitConverter.ToSingle(b, 0);
        }
        public double F64()
        {
            if (End - P < 8) { Ok = false; return 0; }
            var b = new byte[8]; Buffer.BlockCopy(D, P, b, 0, 8); P += 8;
            if (!BitConverter.IsLittleEndian) Array.Reverse(b);
            return BitConverter.ToDouble(b, 0);
        }
        public byte[] Bytes()
        {
            ulong n = Varint();
            if (!Ok || (ulong)(End - P) < n) { Ok = false; return Nf.Empty; }
            var s = new byte[n]; Buffer.BlockCopy(D, P, s, 0, (int)n); P += (int)n;
            return s;
        }
        public void Skip(uint wt)
        {
            switch (wt)
            {
                case 0: Varint(); break;
                case 1: P += 8; break;
                case 2: { ulong n = Varint();
                          if ((ulong)(End - P) < n) Ok = false; else P += (int)n; break; }
                case 5: P += 4; break;
                default: Ok = false; break;
            }
            if (P > End) Ok = false;
        }
    }
"""


def _cs_type(t) -> str:
    if _is_msg(t):
        return t.__name__
    return _SCALAR_CS[t]


def _cs_default(t) -> str:
    if _is_msg(t):
        return f"new {t.__name__}()"
    return _DEFAULT_CS[t]


def _enc_scalar(expr: str, t: str, w, indent: str) -> None:
    if t in ("int32", "int64", "enum"):
        w(f"{indent}Nf.PutI64(nf__o, (long){expr});\n")
    elif t == "uint64":
        w(f"{indent}Nf.PutVarint(nf__o, {expr});\n")
    elif t == "bool":
        w(f"{indent}Nf.PutVarint(nf__o, {expr} ? 1ul : 0ul);\n")
    elif t == "float":
        w(f"{indent}Nf.PutF32(nf__o, {expr});\n")
    elif t == "double":
        w(f"{indent}Nf.PutF64(nf__o, {expr});\n")
    else:
        w(f"{indent}Nf.PutBytes(nf__o, {expr});\n")


_DEC_SCALAR = {
    "int32": "(int)nf__r.Varint()",
    "enum": "(int)nf__r.Varint()",
    "int64": "(long)nf__r.Varint()",
    "uint64": "nf__r.Varint()",
    "bool": "nf__r.Varint() != 0",
    "float": "nf__r.F32()",
    "double": "nf__r.F64()",
    "bytes": "nf__r.Bytes()",
    "string": "nf__r.Bytes()",
}


def _pascal(name: str) -> str:
    return "".join(p[:1].upper() + p[1:] for p in name.split("_"))


def emit_cs() -> str:
    out = io.StringIO()
    w = out.write
    w(_RUNTIME)
    for cls in _collect():
        name = cls.__name__
        w(f"\n    public class {name}\n    {{\n")
        for tag, fname, ftype, _ in cls.FIELDS:
            if isinstance(ftype, tuple):
                w(f"        public List<{_cs_type(ftype[1])}> {fname} = "
                  f"new List<{_cs_type(ftype[1])}>();\n")
            else:
                w(f"        public {_cs_type(ftype)} {fname} = {_cs_default(ftype)};\n")
                w(f"        public bool Has{_pascal(fname)} = false;\n")
        # ---- encode
        w("        public void Encode(MemoryStream nf__o)\n        {\n")
        for tag, fname, ftype, _ in cls.FIELDS:
            if isinstance(ftype, tuple):
                inner = ftype[1]
                w(f"            foreach (var nf__it in {fname})\n            {{\n")
                if _is_msg(inner):
                    w(f"                Nf.PutTag(nf__o, {tag}, 2);\n")
                    w("                var nf__sub = new MemoryStream(); nf__it.Encode(nf__sub);\n")
                    w("                Nf.PutBytes(nf__o, nf__sub.ToArray());\n")
                else:
                    w(f"                Nf.PutTag(nf__o, {tag}, {_WT[inner]});\n")
                    _enc_scalar("nf__it", inner, w, "                ")
                w("            }\n")
            elif _is_msg(ftype):
                w(f"            if (Has{_pascal(fname)})\n            {{\n")
                w(f"                Nf.PutTag(nf__o, {tag}, 2);\n")
                w(f"                var nf__sub = new MemoryStream(); {fname}.Encode(nf__sub);\n")
                w("                Nf.PutBytes(nf__o, nf__sub.ToArray());\n")
                w("            }\n")
            else:
                w(f"            if (Has{_pascal(fname)})\n            {{\n")
                w(f"                Nf.PutTag(nf__o, {tag}, {_WT[ftype]});\n")
                _enc_scalar(fname, ftype, w, "                ")
                w("            }\n")
        w("        }\n")
        w("        public byte[] Encode()\n        {\n")
        w("            var nf__o = new MemoryStream(); Encode(nf__o); return nf__o.ToArray();\n")
        w("        }\n")
        # ---- clear
        w("        public void Clear()\n        {\n")
        for _tag, fname, ftype, _ in cls.FIELDS:
            if isinstance(ftype, tuple):
                w(f"            {fname}.Clear();\n")
            else:
                w(f"            {fname} = {_cs_default(ftype)};\n")
                w(f"            Has{_pascal(fname)} = false;\n")
        w("        }\n")
        # ---- decode
        w("        public bool Decode(byte[] nf__data, int nf__off, int nf__len)\n        {\n")
        w("            Clear();\n")
        w("            var nf__r = new NfReader(nf__data, nf__off, nf__len);\n")
        w("            while (!nf__r.Done())\n            {\n")
        w("                ulong nf__key = nf__r.Varint();\n")
        w("                if (!nf__r.Ok) return false;\n")
        w("                switch ((uint)(nf__key >> 3))\n                {\n")
        for tag, fname, ftype, _ in cls.FIELDS:
            rep = isinstance(ftype, tuple)
            inner = ftype[1] if rep else ftype
            expected_wt = 2 if _is_msg(inner) else _WT[inner]
            w(f"                    case {tag}:\n")
            w("                    {\n")
            # wrong wire type for a known tag: skip like an unknown field
            w(f"                        if ((uint)(nf__key & 7) != {expected_wt})\n")
            w("                        {\n")
            w("                            nf__r.Skip((uint)(nf__key & 7));\n")
            w("                            if (!nf__r.Ok) return false;\n")
            w("                            break;\n")
            w("                        }\n")
            if _is_msg(inner):
                w("                        var nf__sub = nf__r.Bytes();\n")
                w("                        if (!nf__r.Ok) return false;\n")
                w(f"                        var nf__m = new {inner.__name__}();\n")
                w("                        if (!nf__m.Decode(nf__sub, 0, nf__sub.Length)) return false;\n")
                if rep:
                    w(f"                        {fname}.Add(nf__m);\n")
                else:
                    w(f"                        {fname} = nf__m; Has{_pascal(fname)} = true;\n")
            else:
                if rep:
                    w(f"                        {fname}.Add({_DEC_SCALAR[inner]});\n")
                    w("                        if (!nf__r.Ok) return false;\n")
                else:
                    w(f"                        {fname} = {_DEC_SCALAR[inner]};\n")
                    w("                        if (!nf__r.Ok) return false;\n")
                    w(f"                        Has{_pascal(fname)} = true;\n")
            w("                        break;\n")
            w("                    }\n")
        w("                    default:\n")
        w("                        nf__r.Skip((uint)(nf__key & 7));\n")
        w("                        if (!nf__r.Ok) return false;\n")
        w("                        break;\n")
        w("                }\n")
        w("            }\n")
        w("            return nf__r.Ok;\n")
        w("        }\n")
        w("    }\n")
    w("}\n")
    return out.getvalue()


def emit_messages() -> List[str]:
    """Names of every emitted message class (for tests/tools)."""
    return [c.__name__ for c in _collect()]


if __name__ == "__main__":
    print(emit_cs())
