"""Minimal read-only .xlsx sheet reader (stdlib only).

The reference's NFFileProcess vendors MiniExcelReader to pull schema
sheets out of Excel workbooks (`NFTools/NFFileProcess/`).  An .xlsx is a
zip of XML parts; this reads sharedStrings + each worksheet into rows of
python values without external dependencies (openpyxl is not in the
image).  Supports inline/shared strings and numbers — the subset schema
workbooks use.
"""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET
import zipfile
from pathlib import Path
from typing import Dict, List, Optional, Union

_NS = {"m": "http://schemas.openxmlformats.org/spreadsheetml/2006/main"}
_REL_NS = {
    "r": "http://schemas.openxmlformats.org/package/2006/relationships"
}

Cell = Union[str, int, float, None]


def _col_index(ref: str) -> int:
    """'C7' -> 2 (zero-based column)."""
    m = re.match(r"([A-Z]+)", ref or "A")
    n = 0
    for ch in m.group(1):
        n = n * 26 + (ord(ch) - ord("A") + 1)
    return n - 1


def _cell_value(c: ET.Element, shared: List[str]) -> Cell:
    t = c.get("t", "n")
    v = c.find("m:v", _NS)
    if t == "inlineStr":
        is_el = c.find("m:is", _NS)
        return "".join(
            t_el.text or "" for t_el in is_el.iter(
                "{%s}t" % _NS["m"]
            )
        ) if is_el is not None else None
    if v is None or v.text is None:
        return None
    if t == "s":
        return shared[int(v.text)]
    if t == "str":
        return v.text
    if t == "b":
        return int(v.text)
    # numeric: keep ints integral
    txt = v.text
    try:
        f = float(txt)
        return int(f) if f.is_integer() else f
    except ValueError:
        return txt


def read_xlsx_sheets(path: Path) -> Dict[str, List[List[Cell]]]:
    """Workbook -> {sheet_name: rows}; rows are padded to ragged width."""
    path = Path(path)
    with zipfile.ZipFile(path) as z:
        shared: List[str] = []
        if "xl/sharedStrings.xml" in z.namelist():
            root = ET.fromstring(z.read("xl/sharedStrings.xml"))
            for si in root.findall("m:si", _NS):
                shared.append(
                    "".join(t.text or "" for t in si.iter("{%s}t" % _NS["m"]))
                )
        wb = ET.fromstring(z.read("xl/workbook.xml"))
        rels = ET.fromstring(z.read("xl/_rels/workbook.xml.rels"))
        rel_target = {
            r.get("Id"): r.get("Target") for r in rels.findall("r:Relationship", _REL_NS)
        }
        out: Dict[str, List[List[Cell]]] = {}
        rid_attr = ("{http://schemas.openxmlformats.org/officeDocument/2006/"
                    "relationships}id")
        for sheet in wb.findall("m:sheets/m:sheet", _NS):
            name = sheet.get("name", "Sheet")
            target = rel_target.get(sheet.get(rid_attr), "")
            if not target:
                continue
            member = "xl/" + target.lstrip("/").removeprefix("xl/")
            ws = ET.fromstring(z.read(member))
            rows: List[List[Cell]] = []
            for row in ws.findall("m:sheetData/m:row", _NS):
                cells: List[Cell] = []
                for c in row.findall("m:c", _NS):
                    idx = _col_index(c.get("r", ""))
                    while len(cells) < idx:
                        cells.append(None)
                    cells.append(_cell_value(c, shared))
                rows.append(cells)
            out[name] = rows
    return out


def write_xlsx(path: Path, sheets: Dict[str, List[List[Cell]]]) -> None:
    """Tiny writer (inline strings only) — lets tests build workbooks and
    deployments hand-edit schema sheets without Excel."""
    from xml.sax.saxutils import escape

    def col_ref(i: int) -> str:
        s = ""
        i += 1
        while i:
            i, r = divmod(i - 1, 26)
            s = chr(ord("A") + r) + s
        return s

    sheet_xmls = []
    for rows in sheets.values():
        body = []
        for r_i, row in enumerate(rows, start=1):
            cells = []
            for c_i, val in enumerate(row):
                if val is None:
                    continue
                ref = f"{col_ref(c_i)}{r_i}"
                if isinstance(val, (int, float)) and not isinstance(val, bool):
                    cells.append(f'<c r="{ref}"><v>{val}</v></c>')
                else:
                    cells.append(
                        f'<c r="{ref}" t="inlineStr"><is><t>'
                        f"{escape(str(val))}</t></is></c>"
                    )
            body.append(f'<row r="{r_i}">' + "".join(cells) + "</row>")
        sheet_xmls.append(
            '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
            f'<worksheet xmlns="{_NS["m"]}"><sheetData>'
            + "".join(body)
            + "</sheetData></worksheet>"
        )

    names = [escape(n, {'"': "&quot;"}) for n in sheets]
    wb = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        f'<workbook xmlns="{_NS["m"]}" xmlns:r='
        '"http://schemas.openxmlformats.org/officeDocument/2006/relationships"'
        "><sheets>"
        + "".join(
            f'<sheet name="{n}" sheetId="{i + 1}" r:id="rId{i + 1}"/>'
            for i, n in enumerate(names)
        )
        + "</sheets></workbook>"
    )
    rels = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        '<Relationships xmlns='
        '"http://schemas.openxmlformats.org/package/2006/relationships">'
        + "".join(
            f'<Relationship Id="rId{i + 1}" Type="http://schemas.'
            "openxmlformats.org/officeDocument/2006/relationships/worksheet"
            f'" Target="worksheets/sheet{i + 1}.xml"/>'
            for i in range(len(names))
        )
        + "</Relationships>"
    )
    root_rels = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        '<Relationships xmlns='
        '"http://schemas.openxmlformats.org/package/2006/relationships">'
        '<Relationship Id="rId1" Type="http://schemas.openxmlformats.org/'
        'officeDocument/2006/relationships/officeDocument" '
        'Target="xl/workbook.xml"/></Relationships>'
    )
    types = (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        '<Types xmlns='
        '"http://schemas.openxmlformats.org/package/2006/content-types">'
        '<Default Extension="rels" ContentType="application/vnd.'
        'openxmlformats-package.relationships+xml"/>'
        '<Default Extension="xml" ContentType="application/xml"/>'
        '<Override PartName="/xl/workbook.xml" ContentType="application/vnd.'
        'openxmlformats-officedocument.spreadsheetml.sheet.main+xml"/>'
        + "".join(
            f'<Override PartName="/xl/worksheets/sheet{i + 1}.xml" '
            'ContentType="application/vnd.openxmlformats-officedocument.'
            'spreadsheetml.worksheet+xml"/>'
            for i in range(len(names))
        )
        + "</Types>"
    )
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("[Content_Types].xml", types)
        z.writestr("_rels/.rels", root_rels)
        z.writestr("xl/workbook.xml", wb)
        z.writestr("xl/_rels/workbook.xml.rels", rels)
        for i, xml in enumerate(sheet_xmls):
            z.writestr(f"xl/worksheets/sheet{i + 1}.xml", xml)
