"""Golden byte vectors for client-SDK validation without a compiler.

No C# toolchain ships in this image, so the generated Unity binding
(`NFMsg.cs`, tools/emit_cs_sdk.py) can't be compile-tested the way the
C++ SDK is (tests/test_cpp_sdk.py).  Instead this module freezes the
wire contract as data: one deterministic instance of EVERY declared
message, encoded by the Python codec (itself protoc-byte-verified,
tests/test_wire_protoc.py), written as `name \\t hex` lines — plus a
generated C# harness that replays the file against NFMsg.cs
(decode -> re-encode -> byte-compare) the moment a Unity project or
dotnet SDK is available.

Reference analog: the Unity3D client's protobuf-net bindings are only
validated by running the game (NFClient/Unity3D); here the contract is
checkable offline on both sides.
"""

from __future__ import annotations

import io
from typing import List, Tuple

from ..net.wire import Message
from .emit_cpp_sdk import _collect


class _Gen:
    """Deterministic field filler (same spirit as tests/test_cpp_sdk.py):
    every scalar family exercised, negatives included (they encode as
    10-byte varints — the classic cross-language divergence point)."""

    def __init__(self) -> None:
        self.n = 0

    def value(self, ftype):
        self.n += 1
        i = self.n
        if isinstance(ftype, tuple):  # repeated
            return [self.value(ftype[1]) for _ in range(2)]
        if isinstance(ftype, type) and issubclass(ftype, Message):
            return self.message(ftype)
        return {
            "int32": [5, -3, 0, 1 << 28][i % 4],
            "int64": [9, -1, 1 << 40][i % 3],
            "uint64": [0, 7, (1 << 62) + 3][i % 3],
            "bool": bool(i % 2),
            "enum": [0, 2, -1][i % 3],
            "float": [0.5, -2.25, 100.125][i % 3],
            "double": [1.5, -3.25e10][i % 2],
            "bytes": b"b%d" % i,
            "string": "s%d" % i,
        }[ftype]

    def message(self, cls):
        return cls(**{f[1]: self.value(f[2]) for f in cls.FIELDS})


def golden_cases() -> List[Tuple[str, bytes]]:
    """(message name, encoded bytes) for every declared wire message,
    deterministic across runs (one shared counter, definition order)."""
    gen = _Gen()
    return [(cls.__name__, gen.message(cls).encode()) for cls in _collect()]


def emit_vectors() -> str:
    """The `NFMsgGolden.tsv` text: `name<TAB>hex` per message."""
    out = io.StringIO()
    out.write("# GENERATED golden wire vectors - do not edit by hand.\n")
    out.write("# Regenerate with scripts/emit_client_vectors.py.\n")
    for name, raw in golden_cases():
        out.write(f"{name}\t{raw.hex()}\n")
    return out.getvalue()


def emit_cs_harness() -> str:
    """`NFMsgGoldenTest.cs`: standalone console program (C# 7, no deps
    beyond the generated NFMsg.cs) that replays the vector file.

    For each line it decodes the golden bytes into the named message,
    re-encodes, and byte-compares — any field-order, tag, wire-type or
    varint divergence in the C# binding fails loudly.  Exit 0 = all pass.
    """
    names = [name for name, _ in golden_cases()]
    out = io.StringIO()
    out.write("// GENERATED golden-vector replay harness - do not edit.\n")
    out.write("// Usage: NFMsgGoldenTest <path-to-NFMsgGolden.tsv>\n")
    out.write("// Compile next to the generated NFMsg.cs.\n\n")
    out.write("using System;\nusing System.IO;\n\n")
    out.write("public static class NFMsgGoldenTest\n{\n")
    out.write(
        "    static byte[] Roundtrip(string name, byte[] raw)\n    {\n"
        "        switch (name)\n        {\n"
    )
    for name in names:
        out.write(
            f'            case "{name}": {{ var m = new NFMsg.{name}(); '
            "if (!m.Decode(raw, 0, raw.Length)) return null; "
            "return m.Encode(); }\n"
        )
    out.write(
        "            default: return null;\n"
        "        }\n    }\n\n"
    )
    out.write(
        "    public static int Main(string[] args)\n    {\n"
        "        int bad = 0, n = 0;\n"
        "        foreach (var line in File.ReadAllLines(args[0]))\n"
        "        {\n"
        "            if (line.Length == 0 || line[0] == '#') continue;\n"
        "            var parts = line.Split('\\t');\n"
        "            var raw = new byte[parts[1].Length / 2];\n"
        "            for (int i = 0; i < raw.Length; i++)\n"
        "                raw[i] = Convert.ToByte(parts[1].Substring(2 * i, 2), 16);\n"
        "            var back = Roundtrip(parts[0], raw);\n"
        "            n++;\n"
        "            bool ok = back != null && back.Length == raw.Length;\n"
        "            if (ok) for (int i = 0; i < raw.Length; i++)\n"
        "                if (back[i] != raw[i]) { ok = false; break; }\n"
        "            if (!ok) { bad++; Console.WriteLine(\"FAIL \" + parts[0]); }\n"
        "        }\n"
        "        Console.WriteLine(n + \" vectors, \" + bad + \" failures\");\n"
        "        return bad == 0 && n > 0 ? 0 : 1;\n"
        "    }\n}\n"
    )
    return out.getvalue()
