"""MySQL client/server wire protocol + the reference's table API over it.

Reference: NFMysqlPlugin speaks real MySQL through mysql-connector
(NFComm/NFMysqlPlugin/NFCMysqlDriver.cpp); its module surface is the
key-value-over-tables API (`NFCMysqlModule.h:32-40`).  No MySQL client
library or server ships in this image, so this module implements the
actual MySQL client/server protocol from scratch:

- packet framing (3-byte LE length + sequence id),
- handshake v10 + HandshakeResponse41 with `mysql_native_password`
  challenge/response auth (SHA1(pw) XOR SHA1(salt . SHA1(SHA1(pw)))),
- COM_QUERY text-protocol resultsets (column definitions, EOF framing,
  length-encoded row values), COM_PING, COM_QUIT,
- OK/ERR/EOF packet parsing.

`MysqlModule` mirrors SqlModule's Updata/Query/... surface over a live
wire connection, and `MiniMysql` is the in-process wire *server* twin
(the MiniRedis pattern, persist/resp.py) — it performs the real
handshake, verifies the client's scramble against the password, and
executes the query on sqlite after a light MySQL→sqlite dialect shim.
Tests therefore exercise genuine protocol bytes end to end without an
external mysqld.
"""

from __future__ import annotations

import hashlib
import re
import socket
import socketserver
import sqlite3
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

# capability flags (the subset this dialect uses)
CLIENT_LONG_PASSWORD = 0x1
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 1 << 19

_CAPS = (
    CLIENT_LONG_PASSWORD
    | CLIENT_CONNECT_WITH_DB
    | CLIENT_PROTOCOL_41
    | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION
    | CLIENT_PLUGIN_AUTH
)

COM_QUIT, COM_QUERY, COM_PING = 0x01, 0x03, 0x0E

AUTH_PLUGIN = b"mysql_native_password"

# MySQL text-protocol column type codes (just the ones emitted here)
TYPE_VAR_STRING = 0xFD


class MysqlError(Exception):
    """Wire-level or server-reported (ERR packet) failure."""

    def __init__(self, msg: str, code: int = 2000):
        super().__init__(msg)
        self.code = code


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def scramble_native(password: str, salt: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(salt + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _lenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def _lenc_str(b: bytes) -> bytes:
    return _lenc_int(len(b)) + b


def _read_lenc_int(data: bytes, off: int) -> Tuple[Optional[int], int]:
    first = data[off]
    off += 1
    if first < 0xFB:
        return first, off
    if first == 0xFB:  # NULL in row data
        return None, off
    if first == 0xFC:
        return struct.unpack_from("<H", data, off)[0], off + 2
    if first == 0xFD:
        return int.from_bytes(data[off : off + 3], "little"), off + 3
    return struct.unpack_from("<Q", data, off)[0], off + 8


def _read_lenc_str(data: bytes, off: int) -> Tuple[Optional[bytes], int]:
    n, off = _read_lenc_int(data, off)
    if n is None:
        return None, off
    return data[off : off + n], off + n


class _PacketIO:
    """Framed packet reader/writer over a socket (3-byte len + seq)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0
        self._buf = b""

    def reset_seq(self) -> None:
        self.seq = 0

    def read(self) -> bytes:
        hdr = self._exactly(4)
        n = int.from_bytes(hdr[:3], "little")
        self.seq = (hdr[3] + 1) & 0xFF
        return self._exactly(n)

    def write(self, payload: bytes) -> None:
        # >16MB payloads never occur in this API surface
        self.sock.sendall(
            len(payload).to_bytes(3, "little") + bytes([self.seq]) + payload
        )
        self.seq = (self.seq + 1) & 0xFF

    def _exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise MysqlError("connection closed mid-packet")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out


def _parse_err(payload: bytes) -> MysqlError:
    code = struct.unpack_from("<H", payload, 1)[0]
    off = 3
    if payload[off : off + 1] == b"#":  # sql-state marker
        off += 6
    return MysqlError(payload[off:].decode("utf-8", "replace"), code)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class MysqlClient:
    """A connected, authenticated MySQL session (text protocol)."""

    def __init__(
        self,
        host: str,
        port: int,
        user: str = "",
        password: str = "",
        database: str = "",
        timeout: float = 5.0,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.io = _PacketIO(self.sock)
        self.server_version = ""
        try:
            self._handshake(user, password, database)
        except BaseException:
            self.sock.close()  # reconnect loops must not leak fds
            raise

    # -- connection phase ---------------------------------------------------

    def _handshake(self, user: str, password: str, database: str) -> None:
        pkt = self.io.read()
        if pkt[0] == 0xFF:
            raise _parse_err(pkt)
        if pkt[0] != 10:
            raise MysqlError(f"unsupported protocol version {pkt[0]}")
        off = 1
        end = pkt.index(b"\x00", off)
        self.server_version = pkt[off:end].decode()
        off = end + 1 + 4  # thread id
        salt = pkt[off : off + 8]
        off += 8 + 1  # filler
        caps = struct.unpack_from("<H", pkt, off)[0]
        off += 2
        if len(pkt) > off:
            off += 1 + 2  # charset, status
            caps |= struct.unpack_from("<H", pkt, off)[0] << 16
            off += 2
            off += 1 + 10  # auth data len, reserved
            if caps & CLIENT_SECURE_CONNECTION:
                # 12 scramble bytes + NUL terminator
                salt = salt + pkt[off : off + 12]
        if not caps & CLIENT_PROTOCOL_41:
            raise MysqlError("server lacks CLIENT_PROTOCOL_41")

        auth = scramble_native(password, salt)
        resp = struct.pack("<IIB23x", _CAPS, 1 << 24, 33)  # utf8_general_ci
        resp += user.encode() + b"\x00"
        resp += bytes([len(auth)]) + auth
        resp += database.encode() + b"\x00"
        resp += AUTH_PLUGIN + b"\x00"
        self.io.write(resp)
        ok = self.io.read()
        if ok[0] == 0xFE:
            # AuthSwitchRequest: plugin name NUL, then fresh auth data.
            # MySQL 8 sends this when the account's default plugin differs
            # from what we offered (e.g. caching_sha2_password accounts
            # that still allow native auth) — re-scramble with the new
            # salt when the server asks for mysql_native_password, fail
            # with the plugin's NAME otherwise (not an opaque byte).
            nul = ok.find(b"\x00", 1)
            if nul < 0:
                raise MysqlError(
                    "malformed AuthSwitchRequest (no plugin terminator; "
                    "pre-4.1 old-password switch is not supported)"
                )
            end = nul
            plugin = ok[1:end]
            if plugin != AUTH_PLUGIN:
                raise MysqlError(
                    "server requests unsupported auth plugin "
                    f"{plugin.decode(errors='replace')!r}"
                    f" (only {AUTH_PLUGIN.decode()} is implemented)"
                )
            new_salt = ok[end + 1:].rstrip(b"\x00")
            self.io.write(scramble_native(password, new_salt))
            ok = self.io.read()
        if ok[0] == 0xFF:
            raise _parse_err(ok)
        if ok[0] != 0x00:
            raise MysqlError(f"unexpected auth reply 0x{ok[0]:02x}")

    # -- command phase ------------------------------------------------------

    def ping(self) -> bool:
        try:
            self.io.reset_seq()
            self.io.write(bytes([COM_PING]))
            return self.io.read()[0] == 0x00
        except (OSError, MysqlError):
            return False

    def close(self) -> None:
        try:
            self.io.reset_seq()
            self.io.write(bytes([COM_QUIT]))
        except OSError:
            pass
        finally:
            self.sock.close()

    def query(self, sql: str) -> Tuple[List[str], List[List[Optional[str]]]]:
        """COM_QUERY.  Returns (column names, rows) — empty for OK-only
        statements.  Raises MysqlError on an ERR packet."""
        self.io.reset_seq()
        self.io.write(bytes([COM_QUERY]) + sql.encode())
        first = self.io.read()
        if first[0] == 0xFF:
            raise _parse_err(first)
        if first[0] == 0x00:  # OK: no resultset
            return [], []
        ncols, _ = _read_lenc_int(first, 0)
        names: List[str] = []
        for _ in range(ncols):
            names.append(self._parse_coldef(self.io.read()))
        self._expect_eof(self.io.read())
        rows: List[List[Optional[str]]] = []
        while True:
            pkt = self.io.read()
            if pkt[0] == 0xFE and len(pkt) < 9:  # EOF
                break
            if pkt[0] == 0xFF:
                raise _parse_err(pkt)
            row: List[Optional[str]] = []
            off = 0
            for _ in range(ncols):
                raw, off = _read_lenc_str(pkt, off)
                row.append(None if raw is None else raw.decode("utf-8"))
            rows.append(row)
        return names, rows

    @staticmethod
    def _parse_coldef(pkt: bytes) -> str:
        off = 0
        for _ in range(4):  # catalog, schema, table, org_table
            _, off = _read_lenc_str(pkt, off)
        name, off = _read_lenc_str(pkt, off)
        return name.decode("utf-8")

    @staticmethod
    def _expect_eof(pkt: bytes) -> None:
        if not (pkt[0] == 0xFE and len(pkt) < 9):
            raise MysqlError("expected EOF between columns and rows")


# ---------------------------------------------------------------------------
# the reference table API over the wire (SqlModule twin)
# ---------------------------------------------------------------------------

_ID = "id"


def _bq(name: str) -> str:
    """Backtick-quote an identifier; reject anything exotic."""
    if not name.replace("_", "").isalnum():
        raise ValueError(f"bad identifier {name!r}")
    return f"`{name}`"


def _lit(v: Union[str, bytes, int, float, None]) -> str:
    """SQL literal with MySQL escaping."""
    if v is None:
        return "NULL"
    if isinstance(v, bytes):
        return "X'" + v.hex() + "'"
    if isinstance(v, (int, float)):
        return repr(v)
    s = str(v)
    s = s.replace("\\", "\\\\").replace("'", "\\'")
    s = s.replace("\x00", "\\0").replace("\n", "\\n").replace("\r", "\\r")
    return f"'{s}'"


class MysqlModule:
    """Updata/Query/Select/Delete/Exists/Keys over a live MySQL wire
    connection — the same surface as persist.sql.SqlModule, so
    SqlDriver can put either engine behind one registration call.

    Values come back as text (MySQL text protocol), matching the
    reference module's all-strings valueVec contract
    (NFCMysqlModule.h:32-40)."""

    def __init__(
        self,
        host: str,
        port: int,
        user: str = "",
        password: str = "",
        database: str = "",
        timeout: float = 5.0,
    ):
        self._cli = MysqlClient(host, port, user, password, database, timeout)
        self._known_cols: Dict[str, set] = {}

    def _ensure(self, table: str, fields: Sequence[str]) -> None:
        t = _bq(table)
        cols = self._known_cols.get(table)
        if cols is None:
            self._cli.query(
                f"CREATE TABLE IF NOT EXISTS {t} "
                f"(`{_ID}` VARCHAR(128) PRIMARY KEY)"
            )
            _, rows = self._cli.query(f"SHOW COLUMNS FROM {t}")
            cols = {r[0] for r in rows}
            self._known_cols[table] = cols
        for f in fields:
            if f not in cols:
                self._cli.query(f"ALTER TABLE {t} ADD COLUMN {_bq(f)} TEXT")
                cols.add(f)

    # NOTE: like SqlModule, methods RAISE on wire/server failure
    # (MysqlError/OSError) — SqlDriverManager._call owns the
    # catch-ping-markdead policy; swallowing here would blind its
    # dead-driver failover.

    def updata(self, table, key, fields, values) -> bool:
        if len(fields) != len(values):
            return False
        self._ensure(table, fields)
        collist = ", ".join([f"`{_ID}`"] + [_bq(f) for f in fields])
        vallist = ", ".join([_lit(key)] + [_lit(v) for v in values])
        upd = ", ".join(
            f"{_bq(f)}=VALUES({_bq(f)})" for f in fields
        ) or f"`{_ID}`=`{_ID}`"
        self._cli.query(
            f"INSERT INTO {_bq(table)} ({collist}) VALUES ({vallist}) "
            f"ON DUPLICATE KEY UPDATE {upd}"
        )
        return True

    def query(self, table, key, fields):
        self._ensure(table, fields)
        collist = ", ".join(_bq(f) for f in fields) or f"`{_ID}`"
        _, rows = self._cli.query(
            f"SELECT {collist} FROM {_bq(table)} "
            f"WHERE `{_ID}` = {_lit(key)}"
        )
        if not rows:
            return None
        return list(rows[0])

    def select(self, table, key):
        self._ensure(table, ())
        names, rows = self._cli.query(
            f"SELECT * FROM {_bq(table)} WHERE `{_ID}` = {_lit(key)}"
        )
        if not rows:
            return None
        return {n: v for n, v in zip(names, rows[0]) if n != _ID}

    def delete(self, table, key) -> bool:
        self._ensure(table, ())
        self._cli.query(
            f"DELETE FROM {_bq(table)} WHERE `{_ID}` = {_lit(key)}"
        )
        return True

    def exists(self, table, key) -> bool:
        self._ensure(table, ())
        _, rows = self._cli.query(
            f"SELECT 1 FROM {_bq(table)} WHERE `{_ID}` = {_lit(key)}"
        )
        return bool(rows)

    def keys(self, table, like: str = "%"):
        self._ensure(table, ())
        _, rows = self._cli.query(
            f"SELECT `{_ID}` FROM {_bq(table)} "
            f"WHERE `{_ID}` LIKE {_lit(like)} ORDER BY `{_ID}`"
        )
        return [r[0] for r in rows]

    def ping(self) -> bool:
        return self._cli.ping()

    def close(self) -> None:
        self._cli.close()


# ---------------------------------------------------------------------------
# MiniMysql: in-process wire server (test double / dev backend)
# ---------------------------------------------------------------------------

_SHOW_COLS = re.compile(r"^SHOW COLUMNS FROM (`?\w+`?)$", re.I)

_BACKSLASH_UNESCAPE = {
    "\\": "\\", "'": "'", '"': '"', "0": "\x00",
    "n": "\n", "r": "\r", "t": "\t", "Z": "\x1a", "b": "\b",
}


def _translate_literals(sql: str) -> str:
    """Rewrite MySQL single-quoted literals (backslash escapes) as sqlite
    literals (doubled-quote escapes), leaving everything outside strings
    untouched.  Identifier backticks become double quotes."""
    out: List[str] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch == "`":
            out.append('"')
            i += 1
        elif ch == "'":
            i += 1
            val: List[str] = []
            while i < n:
                c = sql[i]
                if c == "\\" and i + 1 < n:
                    val.append(_BACKSLASH_UNESCAPE.get(sql[i + 1], sql[i + 1]))
                    i += 2
                elif c == "'":
                    i += 1
                    break
                else:
                    val.append(c)
                    i += 1
            out.append("'" + "".join(val).replace("'", "''") + "'")
        else:
            out.append(ch)
            i += 1
    return "".join(out)


_VALUES_REF = re.compile(r"VALUES\((`?\w+`?)\)")
_UPSERT_CLAUSE = " ON DUPLICATE KEY UPDATE "


def _find_outside_literals(sql: str, needle: str) -> int:
    """Index of `needle` outside single-quoted literals, or -1 — a data
    value containing the upsert-clause text must not split the statement."""
    i, n = 0, len(sql)
    up = sql.upper()
    while i < n:
        c = sql[i]
        if c == "'":
            i += 1
            while i < n:
                if sql[i] == "\\" and i + 1 < n:
                    i += 2
                elif sql[i] == "'":
                    i += 1
                    break
                else:
                    i += 1
        elif up.startswith(needle, i):
            return i
        else:
            i += 1
    return -1


def _mysql_to_sqlite(sql: str) -> str:
    """The dialect shim for the statements MysqlModule emits."""
    m = _SHOW_COLS.match(sql.strip())
    if m:
        return f'PRAGMA table_info({m.group(1).replace("`", chr(34))})'
    # MySQL upsert -> sqlite upsert; VALUES(col) -> excluded.col.  A
    # partial-field update must keep the other columns (REPLACE would
    # null them — real MySQL preserves them).
    idx = _find_outside_literals(sql, _UPSERT_CLAUSE)
    if idx != -1:
        head = sql[:idx]
        tail = _VALUES_REF.sub(r"excluded.\1",
                               sql[idx + len(_UPSERT_CLAUSE):])
        sql = head + " ON CONFLICT(`id`) DO UPDATE SET " + tail
    return _translate_literals(sql)


class _MiniHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:  # noqa: D401
        srv: "MiniMysql" = self.server.mini  # type: ignore[attr-defined]
        with srv.conns_lock:
            srv.conns.add(self.request)
        try:
            self._serve(srv)
        finally:
            with srv.conns_lock:
                srv.conns.discard(self.request)

    def _serve(self, srv: "MiniMysql") -> None:
        io = _PacketIO(self.request)
        salt = b"0123456789abcdefghij"  # fixed 20-byte salt (deterministic)
        greeting = bytes([10]) + b"5.7.0-mini\x00"
        greeting += struct.pack("<I", 1)  # thread id
        greeting += salt[:8] + b"\x00"
        greeting += struct.pack("<H", _CAPS & 0xFFFF)
        greeting += bytes([33]) + struct.pack("<H", 2)  # charset, status
        greeting += struct.pack("<H", (_CAPS >> 16) & 0xFFFF)
        greeting += bytes([21]) + b"\x00" * 10
        greeting += salt[8:] + b"\x00"
        greeting += AUTH_PLUGIN + b"\x00"
        io.write(greeting)

        resp = io.read()
        off = 4 + 4 + 1 + 23  # caps, max packet, charset, zeros
        end = resp.index(b"\x00", off)
        user = resp[off:end].decode()
        off = end + 1
        alen = resp[off]
        off += 1
        auth = resp[off : off + alen]
        if srv.auth_switch:
            # exercise the MySQL-8 AuthSwitchRequest path: demand a
            # re-scramble against a fresh salt before accepting
            salt = b"jihgfedcba9876543210"
            io.write(b"\xfe" + AUTH_PLUGIN + b"\x00" + salt + b"\x00")
            auth = io.read()
        expected = scramble_native(srv.password, salt)
        if user != srv.user or auth != expected:
            io.write(
                b"\xff" + struct.pack("<H", 1045) + b"#28000"
                + b"Access denied"
            )
            return
        io.write(b"\x00\x00\x00\x02\x00\x00\x00")  # OK

        while True:
            io.reset_seq()
            try:
                cmd = io.read()
            except MysqlError:
                return
            if cmd[0] == COM_QUIT:
                return
            if cmd[0] == COM_PING:
                io.write(b"\x00\x00\x00\x02\x00\x00\x00")
                continue
            if cmd[0] != COM_QUERY:
                io.write(
                    b"\xff" + struct.pack("<H", 1047) + b"#08S01"
                    + b"unknown command"
                )
                continue
            self._run_query(io, srv, cmd[1:].decode("utf-8"))

    @staticmethod
    def _run_query(io: _PacketIO, srv: "MiniMysql", sql: str) -> None:
        try:
            # one shared database per server (data survives reconnects,
            # like a real mysqld), serialized by the server lock
            with srv.db_lock:
                cur = srv.db.execute(_mysql_to_sqlite(sql))
                rows = cur.fetchall()
                desc = cur.description
                srv.db.commit()
        except sqlite3.Error as e:
            io.write(
                b"\xff" + struct.pack("<H", 1064) + b"#42000"
                + str(e).encode()
            )
            return
        if desc is None:  # OK-only statement
            io.write(b"\x00\x00\x00\x02\x00\x00\x00")
            return
        if _SHOW_COLS.match(sql.strip()):
            # PRAGMA table_info rows -> SHOW COLUMNS shape (name first)
            rows = [(r[1],) for r in rows]
            names = ["Field"]
        else:
            names = [d[0] for d in desc]
        io.write(_lenc_int(len(names)))
        for n in names:
            nb = n.encode()
            io.write(
                _lenc_str(b"def") + _lenc_str(b"") * 3
                + _lenc_str(nb) + _lenc_str(nb)
                + bytes([0x0C]) + struct.pack("<HIBHB", 33, 255,
                                              TYPE_VAR_STRING, 0, 0)
                + b"\x00\x00"
            )
        eof = b"\xfe\x00\x00\x02\x00"
        io.write(eof)
        for row in rows:
            out = b""
            for v in row:
                if v is None:
                    out += b"\xfb"
                else:
                    if isinstance(v, bytes):
                        b = v
                    else:
                        b = str(v).encode("utf-8")
                    out += _lenc_str(b)
            io.write(out)
        io.write(eof)


class MiniMysql:
    """In-process MySQL wire server on a real TCP port (sqlite engine).

    The MiniRedis analog for SQL: real sockets, real packets, real
    native-password auth — so MysqlModule's bytes are validated without
    an external mysqld, and dev clusters can run a SQL endpoint with
    zero dependencies."""

    def __init__(self, user: str = "root", password: str = "",
                 host: str = "127.0.0.1", port: int = 0,
                 auth_switch: bool = False):
        self.user, self.password = user, password
        self.auth_switch = auth_switch
        self.db = sqlite3.connect(":memory:", check_same_thread=False)
        self.db_lock = threading.Lock()
        self.conns: set = set()
        self.conns_lock = threading.Lock()
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _MiniHandler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.mini = self  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        """Stop accepting AND sever live sessions — a dead server must
        look dead to connected clients (keepalive tests rely on it)."""
        self._srv.shutdown()
        self._srv.server_close()
        with self.conns_lock:
            for s in list(self.conns):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        with self.db_lock:
            self.db.close()
