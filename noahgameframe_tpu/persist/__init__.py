"""Persistence: KV backends, RESP client, blob codec, agents, checkpoints."""

from .agent import PlayerDataAgent, RoleListStore  # noqa: F401
from .checkpoint import load_world, save_world  # noqa: F401
from .codec import ObjectDataPack, apply_snapshot, snapshot_object  # noqa: F401
from .kv import FileKV, KVStore, MemoryKV  # noqa: F401
from .mysql import MiniMysql, MysqlClient, MysqlError, MysqlModule  # noqa: F401
from .resp import MiniRedisServer, RespKV  # noqa: F401
from .social import SocialDataAgent  # noqa: F401
from .sql import SqlModule, emit_ddl  # noqa: F401
from .writebehind import (  # noqa: F401
    KVBackend,
    SqlBackend,
    StagingWAL,
    StoreBackend,
    WALError,
    WriteBehindPipeline,
)
