"""Schema-driven object ↔ wire serialization (persistence + sync share it).

Reference: NFCCommonRedisModule converts a live object's property/record
managers to `ObjectPropertyList`/`ObjectRecordList` protos and back
(`NFCCommonRedisModule.h:45-49`); only properties/records flagged
Save/Cache participate (flag plumbing `NFCKernelModule.cpp:158-184`).
The network sync path serializes the *same* structures with a different
flag predicate (Public/Private, `NFCGameServerNet_ServerModule.cpp:
271-400`), so both paths here go through one serializer parameterized by
a predicate — the save blob is literally a replayable sync burst.

GUID-valued cells (OBJECT properties and record columns) are written as
wire Idents, never as packed row handles: row handles are allocation-
dependent and dangle across restarts.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from ..core.datatypes import Bank, DataType, Guid
from ..core.store import EntityStore, WorldState
from ..net.wire import (
    Ident,
    Message,
    ObjectPropertyList,
    ObjectRecordBase,
    ObjectRecordList,
    PropertyFloat,
    PropertyInt,
    PropertyObject,
    PropertyString,
    PropertyVector3,
    RecordAddRowStruct,
    RecordFloat,
    RecordInt,
    RecordObject,
    RecordString,
    RecordVector3,
    Vector3,
)

# predicate over a PropertyDef / RecordDef deciding inclusion
DefPredicate = Callable[[object], bool]


def flag_predicate(flags: Tuple[str, ...]) -> DefPredicate:
    return lambda d: any(d.flag(f) for f in flags)


def _guid_to_ident(store: EntityStore, handle: int) -> Ident:
    g = store.guid_of_handle(int(handle))
    return Ident(svrid=g.head if g else 0, index=g.data if g else 0)


def serialize_properties(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    pred: DefPredicate,
) -> ObjectPropertyList:
    """One entity's predicate-selected properties as a wire list, read
    straight out of the SoA row slices."""
    cname, row = store.row_of(guid)
    spec = store.spec(cname)
    cs = state.classes[cname]
    out = ObjectPropertyList(player_id=Ident(svrid=guid.head, index=guid.data))
    banks = {
        Bank.I32: np.asarray(cs.i32[row]),
        Bank.F32: np.asarray(cs.f32[row]),
        Bank.VEC: np.asarray(cs.vec[row]),
    }
    for bank, rowvals in banks.items():
        for slot in spec.bank_props(bank):
            p = slot.prop
            if not pred(p):
                continue
            raw = rowvals[slot.col]
            name = p.name.encode()
            if p.type == DataType.INT:
                out.property_int_list.append(
                    PropertyInt(property_name=name, data=int(raw)))
            elif p.type == DataType.FLOAT:
                out.property_float_list.append(
                    PropertyFloat(property_name=name, data=float(raw)))
            elif p.type == DataType.STRING:
                out.property_string_list.append(PropertyString(
                    property_name=name,
                    data=store.strings.lookup(int(raw)).encode()))
            elif p.type == DataType.OBJECT:
                out.property_object_list.append(PropertyObject(
                    property_name=name, data=_guid_to_ident(store, raw)))
            else:  # VECTOR2 / VECTOR3 (vec bank)
                out.property_vector3_list.append(PropertyVector3(
                    property_name=name,
                    data=Vector3(x=float(raw[0]), y=float(raw[1]),
                                 z=float(raw[2]))))
    return out


def serialize_records(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    pred: DefPredicate,
) -> ObjectRecordList:
    """One entity's predicate-selected records, all column types."""
    cname, row = store.row_of(guid)
    spec = store.spec(cname)
    cs = state.classes[cname]
    out = ObjectRecordList(player_id=Ident(svrid=guid.head, index=guid.data))
    for rname, rs in spec.records.items():
        if not pred(rs.rec):
            continue
        rstate = cs.records[rname]
        used = np.asarray(rstate.used[row])
        if not used.any():
            continue
        r_i32 = np.asarray(rstate.i32[row]) if rs.n_i32 else None
        r_f32 = np.asarray(rstate.f32[row]) if rs.n_f32 else None
        r_vec = np.asarray(rstate.vec[row]) if rs.n_vec else None
        base = ObjectRecordBase(record_name=rname.encode())
        for r_i in np.flatnonzero(used):
            rowmsg = RecordAddRowStruct(row=int(r_i))
            for c_i, tag in enumerate(rs.col_order):
                cslot = rs.cols[tag]
                t = cslot.col_def.type
                if cslot.bank == Bank.I32:
                    raw = int(r_i32[int(r_i), cslot.col])
                    if t == DataType.STRING:
                        rowmsg.record_string_list.append(RecordString(
                            row=int(r_i), col=c_i,
                            data=store.strings.lookup(raw).encode()))
                    elif t == DataType.OBJECT:
                        rowmsg.record_object_list.append(RecordObject(
                            row=int(r_i), col=c_i,
                            data=_guid_to_ident(store, raw)))
                    else:
                        rowmsg.record_int_list.append(RecordInt(
                            row=int(r_i), col=c_i, data=raw))
                elif cslot.bank == Bank.F32:
                    rowmsg.record_float_list.append(RecordFloat(
                        row=int(r_i), col=c_i,
                        data=float(r_f32[int(r_i), cslot.col])))
                else:
                    v = r_vec[int(r_i), cslot.col]
                    rowmsg.record_vector3_list.append(RecordVector3(
                        row=int(r_i), col=c_i,
                        data=Vector3(x=float(v[0]), y=float(v[1]),
                                     z=float(v[2]))))
            base.row_struct.append(rowmsg)
        out.record_list.append(base)
    return out


class ObjectDataPack(Message):
    """The persisted unit: class name + flagged properties + records."""

    FIELDS = [
        (1, "class_name", "bytes", b""),
        (2, "property_list", ObjectPropertyList, None),
        (3, "record_list", ObjectRecordList, None),
        (4, "guid", Ident, None),
    ]


def snapshot_object(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    flags: Tuple[str, ...] = ("save",),
) -> bytes:
    """Serialize the flag-masked slice of one entity (save-on-destroy)."""
    cname, _ = store.row_of(guid)
    pred = flag_predicate(flags)
    return ObjectDataPack(
        class_name=cname.encode(),
        property_list=serialize_properties(store, state, guid, pred),
        record_list=serialize_records(store, state, guid, pred),
        guid=Ident(svrid=guid.head, index=guid.data),
    ).encode()


def _ident_to_guid(store: EntityStore, ident: Optional[Ident]) -> Optional[Guid]:
    if ident is None:
        return Guid()
    g = Guid(ident.svrid, ident.index)
    if g.is_null() or g in store.guid_map:
        return g
    return None  # referenced entity no longer exists


def apply_snapshot(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    blob: bytes,
) -> WorldState:
    """Write a saved blob back onto a live entity (load-on-create,
    the COE_CREATE_LOADDATA attach)."""
    pack = ObjectDataPack.decode(blob)
    cname, _ = store.row_of(guid)
    spec = store.spec(cname)
    pl = pack.property_list or ObjectPropertyList()
    for p in pl.property_int_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            state = store.set_property(state, guid, name, int(p.data))
    for p in pl.property_float_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            state = store.set_property(state, guid, name, float(p.data))
    for p in pl.property_string_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            state = store.set_property(state, guid, name, p.data.decode())
    for p in pl.property_object_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            target = _ident_to_guid(store, p.data)
            if target is not None:
                state = store.set_property(state, guid, name, target)
    for p in pl.property_vector3_list:
        name = p.property_name.decode()
        if not spec.has_property(name):
            continue
        v = p.data or Vector3()
        t = spec.slot(name).prop.type
        val = (v.x, v.y) if t == DataType.VECTOR2 else (v.x, v.y, v.z)
        state = store.set_property(state, guid, name, val)

    rl = pack.record_list or ObjectRecordList()
    for rec in rl.record_list:
        rname = rec.record_name.decode()
        if rname not in spec.records:
            continue
        rs = spec.records[rname]

        def tag_of(col: int) -> Optional[str]:
            return rs.col_order[col] if col < len(rs.col_order) else None

        for rowmsg in rec.row_struct:
            values: Dict[str, object] = {}
            for c in rowmsg.record_int_list:
                tag = tag_of(c.col)
                if tag is not None:
                    values[tag] = int(c.data)
            for c in rowmsg.record_float_list:
                tag = tag_of(c.col)
                if tag is not None:
                    values[tag] = float(c.data)
            for c in rowmsg.record_string_list:
                tag = tag_of(c.col)
                if tag is not None:
                    values[tag] = c.data.decode()
            for c in rowmsg.record_object_list:
                tag = tag_of(c.col)
                if tag is not None:
                    target = _ident_to_guid(store, c.data)
                    if target is not None:
                        values[tag] = target
            for c in rowmsg.record_vector3_list:
                tag = tag_of(c.col)
                if tag is None:
                    continue
                v = c.data or Vector3()
                t = rs.cols[tag].col_def.type
                values[tag] = ((v.x, v.y) if t == DataType.VECTOR2
                               else (v.x, v.y, v.z))
            if rowmsg.row < rs.max_rows:
                state = store.record_restore_row(
                    state, guid, rname, int(rowmsg.row), values
                )
    return state
