"""Schema-driven object ↔ wire serialization (persistence + sync share it).

Reference: NFCCommonRedisModule converts a live object's property/record
managers to `ObjectPropertyList`/`ObjectRecordList` protos and back
(`NFCCommonRedisModule.h:45-49`); only properties/records flagged
Save/Cache participate (flag plumbing `NFCKernelModule.cpp:158-184`).
The network sync path serializes the *same* structures with a different
flag predicate (Public/Private, `NFCGameServerNet_ServerModule.cpp:
271-400`), so both paths here go through one serializer parameterized by
a predicate — the save blob is literally a replayable sync burst.

GUID-valued cells (OBJECT properties and record columns) are written as
wire Idents, never as packed row handles: row handles are allocation-
dependent and dangle across restarts.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from ..core.datatypes import Bank, DataType, Guid
from ..core.store import EntityStore, WorldState
from ..net.wire import (
    Ident,
    Message,
    ObjectPropertyList,
    ObjectRecordBase,
    ObjectRecordList,
    PropertyFloat,
    PropertyInt,
    PropertyObject,
    PropertyString,
    PropertyVector3,
    RecordAddRowStruct,
    RecordFloat,
    RecordInt,
    RecordObject,
    RecordString,
    RecordVector3,
    Vector3,
)

# predicate over a PropertyDef / RecordDef deciding inclusion
DefPredicate = Callable[[object], bool]


def flag_predicate(flags: Tuple[str, ...]) -> DefPredicate:
    return lambda d: any(d.flag(f) for f in flags)


def _guid_to_ident(store: EntityStore, handle: int) -> Ident:
    g = store.guid_of_handle(int(handle))
    return Ident(svrid=g.head if g else 0, index=g.data if g else 0)


def record_row_cells(store, rs, i32_rows, f32_rows, vec_rows, r_i, tags=None):
    """Per-kind wire cell lists for one record row — the ONE record→wire
    cell mapping (snapshots and per-change sync must emit identical
    encodings).  `i32_rows`/`f32_rows`/`vec_rows` are one entity's record
    arrays [R, ncols]; `col` on the wire is the position in col_order;
    `tags` restricts to a column subset (None = all)."""
    ints, floats, strings, objects, vecs = [], [], [], [], []
    for c_i, tag in enumerate(rs.col_order):
        if tags is not None and tag not in tags:
            continue
        cslot = rs.cols[tag]
        t = cslot.col_def.type
        if cslot.bank == Bank.I32:
            raw = int(i32_rows[r_i, cslot.col])
            if t == DataType.STRING:
                strings.append(RecordString(
                    row=r_i, col=c_i,
                    data=store.strings.lookup(raw).encode()))
            elif t == DataType.OBJECT:
                objects.append(RecordObject(
                    row=r_i, col=c_i, data=_guid_to_ident(store, raw)))
            else:
                ints.append(RecordInt(row=r_i, col=c_i, data=raw))
        elif cslot.bank == Bank.F32:
            floats.append(RecordFloat(
                row=r_i, col=c_i, data=float(f32_rows[r_i, cslot.col])))
        else:
            v = vec_rows[r_i, cslot.col]
            vecs.append(RecordVector3(
                row=r_i, col=c_i,
                data=Vector3(x=float(v[0]), y=float(v[1]), z=float(v[2]))))
    return ints, floats, strings, objects, vecs


def record_row_struct(store, rs, i32_rows, f32_rows, vec_rows, r_i,
                      tags=None) -> RecordAddRowStruct:
    """One full record row as a wire RecordAddRowStruct."""
    ints, floats, strings, objects, vecs = record_row_cells(
        store, rs, i32_rows, f32_rows, vec_rows, r_i, tags)
    return RecordAddRowStruct(
        row=r_i, record_int_list=ints, record_float_list=floats,
        record_string_list=strings, record_object_list=objects,
        record_vector3_list=vecs)


def serialize_properties(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    pred: DefPredicate,
) -> ObjectPropertyList:
    """One entity's predicate-selected properties as a wire list, read
    straight out of the SoA row slices."""
    cname, row = store.row_of(guid)
    spec = store.spec(cname)
    cs = state.classes[cname]
    out = ObjectPropertyList(player_id=Ident(svrid=guid.head, index=guid.data))
    banks = {
        Bank.I32: np.asarray(cs.i32[row]),
        Bank.F32: np.asarray(cs.f32[row]),
        Bank.VEC: np.asarray(cs.vec[row]),
    }
    for bank, rowvals in banks.items():
        for slot in spec.bank_props(bank):
            p = slot.prop
            if not pred(p):
                continue
            raw = rowvals[slot.col]
            name = p.name.encode()
            if p.type == DataType.INT:
                out.property_int_list.append(
                    PropertyInt(property_name=name, data=int(raw)))
            elif p.type == DataType.FLOAT:
                out.property_float_list.append(
                    PropertyFloat(property_name=name, data=float(raw)))
            elif p.type == DataType.STRING:
                out.property_string_list.append(PropertyString(
                    property_name=name,
                    data=store.strings.lookup(int(raw)).encode()))
            elif p.type == DataType.OBJECT:
                out.property_object_list.append(PropertyObject(
                    property_name=name, data=_guid_to_ident(store, raw)))
            else:  # VECTOR2 / VECTOR3 (vec bank)
                out.property_vector3_list.append(PropertyVector3(
                    property_name=name,
                    data=Vector3(x=float(raw[0]), y=float(raw[1]),
                                 z=float(raw[2]))))
    return out


def serialize_records(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    pred: DefPredicate,
) -> ObjectRecordList:
    """One entity's predicate-selected records, all column types."""
    cname, row = store.row_of(guid)
    spec = store.spec(cname)
    cs = state.classes[cname]
    out = ObjectRecordList(player_id=Ident(svrid=guid.head, index=guid.data))
    for rname, rs in spec.records.items():
        if not pred(rs.rec):
            continue
        rstate = cs.records[rname]
        used = np.asarray(rstate.used[row])
        if not used.any():
            continue
        r_i32 = np.asarray(rstate.i32[row]) if rs.n_i32 else None
        r_f32 = np.asarray(rstate.f32[row]) if rs.n_f32 else None
        r_vec = np.asarray(rstate.vec[row]) if rs.n_vec else None
        base = ObjectRecordBase(record_name=rname.encode())
        for r_i in np.flatnonzero(used):
            base.row_struct.append(
                record_row_struct(store, rs, r_i32, r_f32, r_vec, int(r_i))
            )
        out.record_list.append(base)
    return out


class ObjectDataPack(Message):
    """The persisted unit: class name + flagged properties + records."""

    FIELDS = [
        (1, "class_name", "bytes", b""),
        (2, "property_list", ObjectPropertyList, None),
        (3, "record_list", ObjectRecordList, None),
        (4, "guid", Ident, None),
    ]


def snapshot_object(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    flags: Tuple[str, ...] = ("save",),
) -> bytes:
    """Serialize the flag-masked slice of one entity (save-on-destroy)."""
    cname, _ = store.row_of(guid)
    pred = flag_predicate(flags)
    return ObjectDataPack(
        class_name=cname.encode(),
        property_list=serialize_properties(store, state, guid, pred),
        record_list=serialize_records(store, state, guid, pred),
        guid=Ident(svrid=guid.head, index=guid.data),
    ).encode()


def _ident_to_guid(store: EntityStore, ident: Optional[Ident]) -> Optional[Guid]:
    if ident is None:
        return Guid()
    g = Guid(ident.svrid, ident.index)
    if g.is_null() or g in store.guid_map:
        return g
    return None  # referenced entity doesn't exist (yet)


# one unresolved OBJECT reference: owner guid, site, target guid.  site is
# ("prop", name) or ("rec", record_name, row, tag)
PendingRef = Tuple[Guid, Tuple, Guid]


def resolve_pending(
    store: EntityStore, state: WorldState, pending: List[PendingRef]
) -> Tuple[WorldState, List[PendingRef]]:
    """Re-apply deferred OBJECT references whose targets now exist (call
    after a bulk load so restores aren't load-order dependent).  Returns
    (state', still-unresolved)."""
    left: List[PendingRef] = []
    for owner, site, target in pending:
        if owner not in store.guid_map:
            continue  # owner died before the target appeared
        if target not in store.guid_map:
            left.append((owner, site, target))
            continue
        if site[0] == "prop":
            state = store.set_property(state, owner, site[1], target)
        else:
            _, rname, row, tag = site
            state = store.record_set(state, owner, rname, row, tag, target)
    return state, left


def apply_snapshot(
    store: EntityStore,
    state: WorldState,
    guid: Guid,
    blob: bytes,
    pending: Optional[List[PendingRef]] = None,
) -> WorldState:
    """Write a saved blob back onto a live entity (load-on-create,
    the COE_CREATE_LOADDATA attach).

    OBJECT references to not-yet-loaded entities are appended to `pending`
    (resolve with resolve_pending after the batch) instead of being
    silently dropped; with pending=None they are dropped as before."""
    pack = ObjectDataPack.decode(blob)
    cname, _ = store.row_of(guid)
    spec = store.spec(cname)
    # self-references (WearGUID = owner, MasterID = owner, ...) must
    # remap to the entity's NEW guid: a relog mints a fresh guid, and the
    # old one will never exist again
    old_self = (Guid(pack.guid.svrid, pack.guid.index)
                if pack.guid is not None else None)

    def deref(ident: Optional[Ident]) -> Optional[Guid]:
        if (old_self is not None and ident is not None
                and Guid(ident.svrid, ident.index) == old_self):
            return guid
        return _ident_to_guid(store, ident)

    pl = pack.property_list or ObjectPropertyList()
    for p in pl.property_int_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            state = store.set_property(state, guid, name, int(p.data))
    for p in pl.property_float_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            state = store.set_property(state, guid, name, float(p.data))
    for p in pl.property_string_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            state = store.set_property(state, guid, name, p.data.decode())
    for p in pl.property_object_list:
        name = p.property_name.decode()
        if spec.has_property(name):
            target = deref(p.data)
            if target is not None:
                state = store.set_property(state, guid, name, target)
            elif pending is not None and p.data is not None:
                pending.append(
                    (guid, ("prop", name), Guid(p.data.svrid, p.data.index))
                )
    for p in pl.property_vector3_list:
        name = p.property_name.decode()
        if not spec.has_property(name):
            continue
        v = p.data or Vector3()
        t = spec.slot(name).prop.type
        val = (v.x, v.y) if t == DataType.VECTOR2 else (v.x, v.y, v.z)
        state = store.set_property(state, guid, name, val)

    rl = pack.record_list or ObjectRecordList()
    for rec in rl.record_list:
        rname = rec.record_name.decode()
        if rname not in spec.records:
            continue
        rs = spec.records[rname]

        def tag_of(col: int) -> Optional[str]:
            return rs.col_order[col] if col < len(rs.col_order) else None

        for rowmsg in rec.row_struct:
            values: Dict[str, object] = {}
            for c in rowmsg.record_int_list:
                tag = tag_of(c.col)
                if tag is not None:
                    values[tag] = int(c.data)
            for c in rowmsg.record_float_list:
                tag = tag_of(c.col)
                if tag is not None:
                    values[tag] = float(c.data)
            for c in rowmsg.record_string_list:
                tag = tag_of(c.col)
                if tag is not None:
                    values[tag] = c.data.decode()
            for c in rowmsg.record_object_list:
                tag = tag_of(c.col)
                if tag is not None:
                    target = deref(c.data)
                    if target is not None:
                        values[tag] = target
                    elif (pending is not None and c.data is not None
                          and int(rowmsg.row) < rs.max_rows):
                        pending.append((
                            guid,
                            ("rec", rname, int(rowmsg.row), tag),
                            Guid(c.data.svrid, c.data.index),
                        ))
            for c in rowmsg.record_vector3_list:
                tag = tag_of(c.col)
                if tag is None:
                    continue
                v = c.data or Vector3()
                t = rs.cols[tag].col_def.type
                values[tag] = ((v.x, v.y) if t == DataType.VECTOR2
                               else (v.x, v.y, v.z))
            if rowmsg.row < rs.max_rows:
                state = store.record_restore_row(
                    state, guid, rname, int(rowmsg.row), values
                )
    return state
