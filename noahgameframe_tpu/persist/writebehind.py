"""Write-behind durable persistence: stream the diff spine to the store
without ever blocking the tick.

The reference dedicates a whole async role to exactly this —
NFCAsyMysqlModule pushes player saves onto an actor queue so MySQL
round-trips never stall the main loop.  Here the kernel already computes
exactly what changed per tick (the device diff masks the GameRole drains
for sync), so durability is a *tap* on that spine: the role snapshots
each dirty entity's Save-flagged pack (persist.codec) and hands
``{key: blob}`` to this pipeline; a background flusher owns every store
round-trip.  The compiled tick never waits on a socket.

Robustness model, in order of defense:

1. **Staging WAL** (:class:`StagingWAL`): every enqueued batch is
   appended to a CRC-framed on-disk log *before* it is eligible to
   flush, using the same framing discipline as ``replay/journal.py``
   (fixed ``>HII`` header, explicit length, CRC32 per record, fail
   closed on corruption).  A role killed mid-flush loses nothing that
   reached the WAL: the next pipeline over the same directory recovers
   every batch past the flushed watermark and replays it.  Appends are
   OS-flushed (cheap) per batch; ``fsync`` happens only at
   :meth:`WriteBehindPipeline.barrier`, which the GameRole calls at its
   checkpoint marks — so the newest durable ``(checkpoint, WAL
   suffix)`` pair on disk is always mutually recoverable, mirroring the
   journal's checkpoint protocol.
2. **Bounded queue → coalesce-only degradation**: the in-memory queue
   holds at most ``max_queue_batches`` batches.  When the store is down
   long enough to fill it, adjacent batches are *coalesced* (later
   write per key wins — exactly the semantics the store would observe
   anyway) instead of blocking the producer or growing without bound.
   The WAL keeps the full history regardless; only RAM is bounded.
3. **Retry with capped backoff**: the flusher retries a failing batch
   on a :class:`net.retry.RetryPolicy` schedule (deterministic jitter,
   capped), surfacing ``nf_persist_degraded`` while the store is
   unreachable.  Flush order is strictly batch-sequence order, and
   sequence numbers derive from tick watermarks + a monotonic counter —
   never a wall clock — so recovery flushes are byte-identical to the
   flushes a crash interrupted.
4. **Idempotence**: a batch may be flushed twice (crash between store
   write and WAL mark).  Entries are full-blob upserts keyed by entity
   key, so replaying a batch is a no-op for the store; a per-pipeline
   watermark key (``__wb__:<name>``) records the last applied
   ``seq:tick`` so operators (and tests) can observe exactly-once
   *effects* over at-least-once delivery.

Thread contract: ``enqueue``/``note_tick``/``barrier``/``pump``/
``pending``/``discard`` are pump-thread calls and never touch the
store; the flusher thread owns every backend call.  The nf-lint
``pump-surface`` and ``fsync-barrier`` rules (docs/LINT.md) enforce
both properties structurally.
"""

from __future__ import annotations

import collections
import os
import struct
import threading
import time
import zlib
from pathlib import Path
from typing import Deque, Dict, List, Optional, Tuple

from ..net.retry import RetryPolicy

WAL_MAGIC = b"NFWAL01\n"
WAL_GLOB = "wal-*.nfw"
HEADER = struct.Struct(">HII")  # (rec_type, body_len, crc32) — journal twin
BATCH_HEAD = struct.Struct(">qqI")  # (tick, seq, n_entries)
MARK_BODY = struct.Struct(">qq")  # (seq, tick) flushed through
U32 = struct.Struct(">I")
OP_PUT, OP_DEL = 0, 1

WB_META = 1
WB_BATCH = 2
WB_MARK = 3
_KNOWN_RECS = (WB_META, WB_BATCH, WB_MARK)

# same ceiling as the journal: a length past this is corruption
MAX_RECORD_SIZE = 64 * 1024 * 1024


class WALError(Exception):
    """Raised on malformed WAL bytes that cannot be a crash artifact:
    CRC mismatch on a complete frame, unknown record type, impossible
    length, or a torn tail anywhere but the newest segment.  A torn
    tail of the newest segment IS the expected crash artifact and is
    truncated away instead (bounded by the barrier fsync discipline)."""


def _segment_name(index: int) -> str:
    return f"wal-{index:08d}.nfw"


def _segment_index(path: Path) -> int:
    return int(path.stem.split("-", 1)[1])


class _TornTail(Exception):
    """Internal scan signal: a record header/body runs past the end of
    the segment bytes — the expected crash artifact on the newest
    segment.  The owning reader truncates it; the read-side peer scan
    skips it."""

    def __init__(self, off: int, what: str) -> None:
        super().__init__(what)
        self.off = off
        self.what = what


def _iter_frames(data: bytes, name: str):
    """Yield ``(offset, rec_type, body)`` for every complete CRC-checked
    frame in one segment's bytes.  Raises :class:`_TornTail` when the
    tail is incomplete, and :class:`WALError` on anything that cannot be
    a crash artifact (bad magic, unknown record type, impossible length,
    CRC mismatch on a complete frame)."""
    if data[: len(WAL_MAGIC)] != WAL_MAGIC:
        raise WALError(f"{name}: bad segment magic")
    off = len(WAL_MAGIC)
    while off < len(data):
        if off + HEADER.size > len(data):
            raise _TornTail(off, "torn record header")
        rec_type, length, crc = HEADER.unpack_from(data, off)
        if rec_type not in _KNOWN_RECS:
            raise WALError(f"{name}@{off}: unknown record type {rec_type}")
        if length > MAX_RECORD_SIZE:
            raise WALError(f"{name}@{off}: record length {length} "
                           f"exceeds {MAX_RECORD_SIZE}")
        if off + HEADER.size + length > len(data):
            raise _TornTail(off, "torn record body")
        body = data[off + HEADER.size: off + HEADER.size + length]
        if zlib.crc32(body) != crc:
            # a complete frame with a bad CRC is bit damage, not a
            # crash artifact — fail closed like the journal reader
            raise WALError(f"{name}@{off}: CRC mismatch")
        yield off, rec_type, body
        off += HEADER.size + length


class PeerWALView:
    """Read-only recovery view over a (possibly dead) pipeline's WAL
    directory — what :func:`read_peer_wal` returns."""

    __slots__ = ("pending", "flushed_seq", "flushed_tick", "max_tick",
                 "pending_batches", "torn_tail_skipped")

    def __init__(self, pending: Dict[str, Optional[bytes]],
                 flushed_seq: int, flushed_tick: int, max_tick: int,
                 pending_batches: int, torn_tail_skipped: int) -> None:
        self.pending = pending
        self.flushed_seq = int(flushed_seq)
        self.flushed_tick = int(flushed_tick)
        self.max_tick = int(max_tick)
        self.pending_batches = int(pending_batches)
        self.torn_tail_skipped = int(torn_tail_skipped)


def read_peer_wal(wal_dir) -> PeerWALView:
    """Read-side recovery over a PEER's WAL directory (ISSUE 10).

    The world's failover driver reconstructs a dead game's player blobs
    from the newest durable (checkpoint, WAL suffix) pair without taking
    ownership of the directory.  Unlike :class:`StagingWAL` construction
    this NEVER mutates the directory: a torn tail on the newest segment
    is skipped in memory, not truncated in place — the owner may later
    be revived over the same directory and must find its crash artifact
    exactly where it left it.  Corruption anywhere else raises
    :class:`WALError`, same as the owning reader.

    ``pending`` holds the newest value per key across every batch past
    the flushed watermark, applied in seq order (tombstones stay as
    ``None`` entries so callers can distinguish "deleted after the last
    flush" from "never staged").  An empty/missing directory yields an
    empty view — the store is then the only durable source.
    """
    path = Path(wal_dir)
    by_seq: Dict[int, Batch] = {}
    flushed_seq = 0
    flushed_tick = 0
    torn_skipped = 0
    segments = (sorted(path.glob(WAL_GLOB), key=_segment_index)
                if path.is_dir() else [])
    for i, seg in enumerate(segments):
        newest = i == len(segments) - 1
        try:
            for _off, rec_type, body in _iter_frames(seg.read_bytes(),
                                                     seg.name):
                if rec_type == WB_BATCH:
                    b = decode_batch(body)
                    by_seq[b.seq] = b
                elif rec_type == WB_MARK:
                    seq, tick = MARK_BODY.unpack(body)
                    if seq > flushed_seq:
                        flushed_seq, flushed_tick = seq, tick
        except _TornTail as torn:
            if not newest:
                raise WALError(
                    f"{seg.name}@{torn.off}: {torn.what} in closed segment"
                ) from torn
            torn_skipped += 1
    pending: Dict[str, Optional[bytes]] = {}
    max_tick = flushed_tick
    pending_batches = 0
    for b in sorted(by_seq.values(), key=lambda b: b.seq):
        if b.seq <= flushed_seq:
            continue
        pending.update(b.entries)
        max_tick = max(max_tick, b.tick)
        pending_batches += 1
    return PeerWALView(pending, flushed_seq, flushed_tick, max_tick,
                       pending_batches, torn_skipped)


class Batch:
    """One tick-watermarked, key-coalesced unit of durability.

    ``entries`` maps entity key -> blob (upsert) or None (tombstone);
    later batches win per key, so merging two batches is a dict merge."""

    __slots__ = ("seq", "tick", "entries")

    def __init__(self, seq: int, tick: int,
                 entries: Dict[str, Optional[bytes]]) -> None:
        self.seq = int(seq)
        self.tick = int(tick)
        self.entries = entries

    def merge_older(self, older: "Batch") -> None:
        """Absorb an OLDER batch (this batch's entries win per key)."""
        merged = dict(older.entries)
        merged.update(self.entries)
        self.entries = merged


def encode_batch(batch: Batch) -> bytes:
    out = bytearray(BATCH_HEAD.pack(batch.tick, batch.seq,
                                    len(batch.entries)))
    for key, blob in batch.entries.items():
        kb = key.encode("utf-8")
        out += U32.pack(len(kb)) + kb
        if blob is None:
            out.append(OP_DEL)
        else:
            out.append(OP_PUT)
            out += U32.pack(len(blob)) + blob
    return bytes(out)


def decode_batch(body: bytes) -> Batch:
    if len(body) < BATCH_HEAD.size:
        raise WALError(f"batch record too short ({len(body)} bytes)")
    tick, seq, n = BATCH_HEAD.unpack_from(body)
    off = BATCH_HEAD.size
    entries: Dict[str, Optional[bytes]] = {}
    for _ in range(n):
        if off + U32.size > len(body):
            raise WALError("batch entry truncated (key length)")
        (klen,) = U32.unpack_from(body, off)
        off += U32.size
        if off + klen + 1 > len(body):
            raise WALError("batch entry truncated (key/op)")
        key = body[off: off + klen].decode("utf-8")
        off += klen
        op = body[off]
        off += 1
        if op == OP_DEL:
            entries[key] = None
        elif op == OP_PUT:
            if off + U32.size > len(body):
                raise WALError("batch entry truncated (value length)")
            (vlen,) = U32.unpack_from(body, off)
            off += U32.size
            if off + vlen > len(body):
                raise WALError("batch entry truncated (value)")
            entries[key] = body[off: off + vlen]
            off += vlen
        else:
            raise WALError(f"unknown batch entry op {op}")
    if off != len(body):
        raise WALError(f"batch record has {len(body) - off} trailing bytes")
    return Batch(seq, tick, entries)


class StagingWAL:
    """Segmented, CRC-framed staging log for queued-but-unflushed
    batches.  Single-writer (the pump thread); the flusher never
    touches it — flush completions come back through
    :meth:`WriteBehindPipeline.pump`, which appends the marks.

    Construction recovers the directory: every batch past the newest
    flush mark is returned in ``pending`` (sorted by seq), segment
    numbering resumes, and a torn tail on the newest segment is
    truncated in place (the crash artifact the barrier protocol
    bounds).  Corruption anywhere else raises :class:`WALError`."""

    def __init__(self, path, segment_bytes: int = 1 << 20) -> None:
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = max(4096, int(segment_bytes))
        self.bytes_total = 0
        self.batches_total = 0
        self.torn_tail_dropped = 0
        # closed segments: [(index, path, max_seq)] for pruning
        self._closed: List[Tuple[int, Path, int]] = []
        self._cur_max_seq = -1
        self.pending: List[Batch] = []
        self.flushed_seq = 0
        self.flushed_tick = 0
        self._recover()
        existing = sorted(self.path.glob(WAL_GLOB), key=_segment_index)
        self._seg_index = _segment_index(existing[-1]) if existing else 0
        self._file = None
        self._seg_size = 0
        self._open_segment()

    # ---------------------------------------------------------- recovery
    def _recover(self) -> None:
        by_seq: Dict[int, Batch] = {}
        segments = sorted(self.path.glob(WAL_GLOB), key=_segment_index)
        for i, seg in enumerate(segments):
            newest = i == len(segments) - 1
            max_seq = self._scan_segment(seg, newest, by_seq)
            self._closed.append((_segment_index(seg), seg, max_seq))
        self.pending = sorted(
            (b for b in by_seq.values() if b.seq > self.flushed_seq),
            key=lambda b: b.seq,
        )

    def _scan_segment(self, seg: Path, newest: bool,
                      by_seq: Dict[int, Batch]) -> int:
        max_seq = -1
        try:
            for _off, rec_type, body in _iter_frames(seg.read_bytes(),
                                                     seg.name):
                if rec_type == WB_BATCH:
                    b = decode_batch(body)
                    by_seq[b.seq] = b
                    max_seq = max(max_seq, b.seq)
                elif rec_type == WB_MARK:
                    seq, tick = MARK_BODY.unpack(body)
                    if seq > self.flushed_seq:
                        self.flushed_seq, self.flushed_tick = seq, tick
        except _TornTail as torn:
            self._torn(seg, newest, torn.off, torn.what)
        return max_seq

    def _torn(self, seg: Path, newest: bool, off: int, what: str) -> int:
        if not newest:
            # older segments were fsynced at rotation; a torn record
            # there is corruption, not a crash tail
            raise WALError(f"{seg.name}@{off}: {what} in closed segment")
        with open(seg, "r+b") as f:
            f.truncate(off)
        self.torn_tail_dropped += 1
        return off

    # ---------------------------------------------------------- segments
    def _open_segment(self) -> None:
        if self._file is not None:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._file.close()
            self._closed.append((
                self._seg_index,
                self.path / _segment_name(self._seg_index),
                self._cur_max_seq,
            ))
        self._seg_index += 1
        self._cur_max_seq = -1
        self._file = open(self.path / _segment_name(self._seg_index), "wb")
        self._file.write(WAL_MAGIC)
        self._seg_size = len(WAL_MAGIC)
        self.bytes_total += len(WAL_MAGIC)

    def _append(self, rec_type: int, body: bytes) -> None:
        if self._file is None:
            raise WALError("staging WAL is closed")
        if len(body) > MAX_RECORD_SIZE:
            raise WALError(f"record body {len(body)} exceeds "
                           f"{MAX_RECORD_SIZE}")
        frame = HEADER.pack(rec_type, len(body), zlib.crc32(body)) + body
        self._file.write(frame)
        # OS-flush per record: an in-process role kill (the chaos-smoke
        # kill path) loses nothing; only a machine crash can cost the
        # suffix past the last barrier fsync
        self._file.flush()
        self._seg_size += len(frame)
        self.bytes_total += len(frame)
        if self._seg_size >= self.segment_bytes:
            self._open_segment()

    # ----------------------------------------------------------- records
    def append_batch(self, batch: Batch) -> None:
        self._cur_max_seq = max(self._cur_max_seq, batch.seq)
        self._append(WB_BATCH, encode_batch(batch))
        self.batches_total += 1

    def mark(self, seq: int, tick: int) -> None:
        """Record that everything through batch `seq` (watermark `tick`)
        reached the store."""
        self._append(WB_MARK, MARK_BODY.pack(int(seq), int(tick)))
        if seq > self.flushed_seq:
            self.flushed_seq, self.flushed_tick = int(seq), int(tick)

    def prune(self) -> int:
        """Unlink closed segments whose every batch is below the newest
        durable mark; returns how many were removed."""
        keep, removed = [], 0
        for index, path, max_seq in self._closed:
            if max_seq <= self.flushed_seq and path.exists():
                path.unlink()
                removed += 1
            else:
                keep.append((index, path, max_seq))
        self._closed = keep
        return removed

    def sync(self) -> None:
        if self._file is not None:
            self._file.flush()
            os.fsync(self._file.fileno())

    def close(self) -> None:
        if self._file is not None:
            self.sync()
            self._file.close()
            self._file = None


# --------------------------------------------------------------- backends
class StoreBackend:
    """What the flusher needs from a store: blob upsert/delete + ping."""

    def write(self, key: str, blob: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def ping(self) -> bool:
        return True

    def close(self) -> None:
        pass


class KVBackend(StoreBackend):
    """KVStore adapter (memory/file/RESP): key → blob, natural upsert."""

    def __init__(self, kv) -> None:
        self.kv = kv

    def write(self, key: str, blob: bytes) -> None:
        self.kv.set(key, blob)

    def delete(self, key: str) -> None:
        self.kv.delete(key)

    def ping(self) -> bool:
        fn = getattr(self.kv, "ping", None)
        return bool(fn()) if fn is not None else True


class SqlBackend(StoreBackend):
    """SqlModule/MysqlModule adapter: one all-strings row per key with
    the blob hex-encoded (the reference module's valueVec contract)."""

    def __init__(self, sql, table: str = "Player",
                 column: str = "blob") -> None:
        self.sql = sql
        self.table = table
        self.column = column

    def write(self, key: str, blob: bytes) -> None:
        if not self.sql.updata(self.table, key, [self.column], [blob.hex()]):
            raise IOError(f"sql updata refused key {key!r}")

    def delete(self, key: str) -> None:
        self.sql.delete(self.table, key)

    def ping(self) -> bool:
        fn = getattr(self.sql, "ping", None)
        return bool(fn()) if fn is not None else True


def as_backend(store) -> StoreBackend:
    """KVStore → KVBackend, SqlModule-shaped → SqlBackend, StoreBackend
    (or anything already exposing write/delete) passes through."""
    if isinstance(store, StoreBackend):
        return store
    if hasattr(store, "write") and hasattr(store, "delete"):
        return store  # duck-typed backend (FaultyStore wraps like this)
    if hasattr(store, "set") and hasattr(store, "get"):
        return KVBackend(store)
    if hasattr(store, "updata"):
        return SqlBackend(store)
    raise TypeError(f"no write-behind backend for {type(store).__name__}")


# --------------------------------------------------------------- pipeline
class WriteBehindPipeline:
    """Bounded-queue async persistence: WAL-staged batches drained to a
    store backend on a background thread with capped-backoff retries.

    Pump-thread surface (never touches the store):
      enqueue / enqueue_one / note_tick / barrier / pump / pending /
      discard / lag_ticks / queue_depth / degraded
    Flusher-thread surface: the backend calls, and nothing else.
    """

    def __init__(self, store, wal_dir, *, registry=None,
                 max_queue_batches: int = 64,
                 retry: Optional[RetryPolicy] = None,
                 name: str = "persist",
                 segment_bytes: int = 1 << 20) -> None:
        self.backend = as_backend(store)
        self.name = str(name)
        self.retry = retry if retry is not None else RetryPolicy(
            base=0.05, cap=2.0, seed=zlib.crc32(self.name.encode())
        )
        self.max_queue_batches = max(4, int(max_queue_batches))
        self.wal = StagingWAL(wal_dir, segment_bytes=segment_bytes)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: Deque[Batch] = collections.deque(self.wal.pending)
        self.wal.pending = []
        self._next_seq = max(
            [b.seq for b in self._queue] + [self.wal.flushed_seq]
        ) + 1
        self._now_tick = max(
            [b.tick for b in self._queue] + [self.wal.flushed_tick]
        )
        self._completed: List[Tuple[int, int]] = []
        self._store_failing = False
        self._overflowed = False
        self._stop = False
        # counters the test/smoke assertions read directly
        self.flushes_total = 0
        self.retries_total = 0
        self.entries_total = 0
        self.recovered_batches = len(self._queue)
        # thread hygiene evidence: every thread that ever called the
        # backend (the non-blocking-tick assertion reads this)
        self.store_threads: set = set()
        self._register_metrics(registry)
        self._thread = threading.Thread(
            target=self._run, name=f"writebehind-{self.name}", daemon=True
        )
        self._thread.start()

    # -------------------------------------------------------- telemetry
    def _register_metrics(self, registry) -> None:
        if registry is None:
            self._flush_counter = self._retry_counter = None
            return
        self._flush_counter = registry.counter(
            "nf_persist_flush_total", "write-behind batches flushed"
        )
        self._retry_counter = registry.counter(
            "nf_persist_retry_total", "write-behind flush retries"
        )
        registry.gauge(
            "nf_persist_lag_ticks",
            "ticks since the oldest unflushed write-behind batch",
        ).set_function(self.lag_ticks)
        registry.gauge(
            "nf_persist_queue_depth", "write-behind batches queued in RAM"
        ).set_function(self.queue_depth)
        registry.gauge(
            "nf_persist_degraded",
            "1 while the store is unreachable or the queue overflowed",
        ).set_function(lambda: 1.0 if self.degraded() else 0.0)

    # ------------------------------------------------- pump-thread calls
    def enqueue(self, tick: int, items: Dict[str, Optional[bytes]]) -> int:
        """Stage one tick's coalesced dirty set.  Returns the batch seq
        (0 when `items` is empty).  Never blocks on the store."""
        if not items:
            return 0
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            batch = Batch(seq, tick, dict(items))
            self.wal.append_batch(batch)
            if len(self._queue) >= self.max_queue_batches:
                # coalesce-only degradation: merge the two oldest
                # *idle* batches (index 0 may be in flight) — RAM stays
                # bounded, the WAL keeps full history, later writes win
                if len(self._queue) >= 3:
                    older = self._queue[1]
                    newer = self._queue[2]
                    newer.merge_older(older)
                    del self._queue[1]
                self._overflowed = True
            self._queue.append(batch)
            self._now_tick = max(self._now_tick, int(tick))
            self._cond.notify_all()
            return seq

    def enqueue_one(self, key: str, blob: Optional[bytes]) -> int:
        """Single-entity staging at the current tick watermark (the
        agent's save-on-destroy path)."""
        return self.enqueue(self._now_tick, {key: blob})

    def note_tick(self, tick: int) -> None:
        """Advance the watermark clock (drives the lag gauge)."""
        with self._lock:
            self._now_tick = max(self._now_tick, int(tick))

    def barrier(self, tick: int) -> None:
        """Durability point: fsync the WAL so the (checkpoint at `tick`,
        WAL suffix) pair on disk is mutually recoverable.  Called from
        GameRole.checkpoint_now, next to the journal's checkpoint_mark."""
        with self._lock:
            self._now_tick = max(self._now_tick, int(tick))
            self.wal.sync()

    def pump(self) -> None:
        """Per-frame housekeeping on the pump thread: append flush
        marks for completed batches, prune dead WAL segments, clear the
        overflow latch once the queue drains."""
        with self._lock:
            done, self._completed = self._completed, []
            for seq, tick in done:
                self.wal.mark(seq, tick)
            if done:
                self.wal.prune()
            if self._overflowed and len(self._queue) <= self.max_queue_batches // 2:
                self._overflowed = False

    def pending(self, key: str) -> Tuple[bool, Optional[bytes]]:
        """Read-your-writes: newest queued value for `key`.  Returns
        (found, blob); blob None means a queued tombstone."""
        with self._lock:
            for batch in reversed(self._queue):
                if key in batch.entries:
                    return True, batch.entries[key]
        return False, None

    def discard(self, key: str) -> int:
        """Drop every queued value for `key` (role deletion must not be
        resurrected by an older queued save).  The WAL copy is
        superseded by enqueueing a tombstone instead — use
        ``enqueue_one(key, None)`` for durable deletes."""
        n = 0
        with self._lock:
            for batch in self._queue:
                if key in batch.entries:
                    del batch.entries[key]
                    n += 1
        return n

    # ----------------------------------------------------------- gauges
    def lag_ticks(self) -> int:
        with self._lock:
            if not self._queue:
                return 0
            return max(0, self._now_tick - self._queue[0].tick)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def degraded(self) -> bool:
        return self._store_failing or self._overflowed

    # --------------------------------------------------------- shutdown
    def drain(self, timeout: float = 2.0) -> bool:
        """Best-effort flush of everything queued; True when the queue
        emptied.  On timeout (store down) the batches stay durable in
        the WAL for the next pipeline over this directory."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        while time.monotonic() < deadline:
            self.pump()
            with self._lock:
                if not self._queue:
                    break
            time.sleep(0.01)
        self.pump()
        with self._lock:
            drained = not self._queue
            self.wal.sync()
        return drained

    def close(self) -> None:
        with self._lock:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        with self._lock:
            self.wal.close()

    def kill(self) -> None:
        """Test-only abrupt stop: no drain, no final mark — simulates a
        role killed mid-flush (WAL appends are already OS-flushed)."""
        with self._lock:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        with self._lock:
            if self.wal._file is not None:
                self.wal._file.close()
                self.wal._file = None

    # --------------------------------------------------- flusher thread
    def _run(self) -> None:
        attempt = 0
        while True:
            with self._lock:
                while not self._queue and not self._stop:
                    self._cond.wait(timeout=0.1)
                if self._stop:
                    return
                batch = self._queue[0]  # peek; pop only after success
            try:
                self._flush_batch(batch)
            except Exception:  # noqa: BLE001 — any store error = retry
                attempt += 1
                self.retries_total += 1
                self._store_failing = True
                if self._retry_counter is not None:
                    self._retry_counter.inc()
                delay = self.retry.delay(attempt, key=self.name)
                with self._lock:
                    if self._stop:
                        return
                    self._cond.wait(timeout=delay)
                continue
            attempt = 0
            self._store_failing = False
            self.flushes_total += 1
            self.entries_total += len(batch.entries)
            if self._flush_counter is not None:
                self._flush_counter.inc()
            with self._lock:
                if self._queue and self._queue[0] is batch:
                    self._queue.popleft()
                self._completed.append((batch.seq, batch.tick))

    def _flush_batch(self, batch: Batch) -> None:
        self.store_threads.add(threading.get_ident())
        for key, blob in batch.entries.items():
            if blob is None:
                self.backend.delete(key)
            else:
                self.backend.write(key, blob)
        # idempotence watermark: replays of this batch are observable as
        # a non-advancing seq (entries themselves are natural upserts)
        self.backend.write(
            f"__wb__:{self.name}",
            f"{batch.seq}:{batch.tick}".encode(),
        )
