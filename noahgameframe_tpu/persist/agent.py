"""Data agents: event-driven per-entity persistence + role lists.

Reference: NFDataAgent_NosqlPlugin — player save/load rides the object
lifecycle: on COE_CREATE_LOADDATA the saved protobuf blob is attached to
the fresh object, on destroy/offline the live managers are converted
back and written (`NFCPlayerRedisModule.cpp:226-321`); account role
lists live under their own keys.  Here the same hooks bind to the
kernel's class-event chain, and blobs are the codec.py packs.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..core.datatypes import Guid
from ..kernel.kernel import Kernel, ObjectEvent
from ..net.wire import AckRoleLiteInfoList, RoleLiteInfo
from .codec import apply_snapshot, resolve_pending, snapshot_object
from .kv import KVStore

KeyFn = Callable[[Guid], Optional[str]]


class PlayerDataAgent:
    """Save-on-destroy / load-on-create for one class (default Player).

    The storage key is derived per object by `key_fn`.  The default is
    "account:name" — one slot per character, so two roles on one account
    never share a blob (the reference likewise keys role blobs by role,
    not account).  Only Save-flagged (optionally Cache) columns persist."""

    def __init__(
        self,
        kv: KVStore,
        class_name: str = "Player",
        key_prefix: str = "obj:",
        flags: tuple = ("save",),
        key_fn: Optional[KeyFn] = None,
    ) -> None:
        self.kv = kv
        self.class_name = class_name
        self.key_prefix = key_prefix
        self.flags = flags
        self.kernel: Optional[Kernel] = None
        self._key_fn = key_fn
        # optional write-behind pipeline (persist.writebehind): when
        # set, saves stage through its WAL-backed queue instead of
        # calling the store inline — a destroy during a store outage is
        # durable in the WAL instead of silently lost
        self.pipeline = None
        # OBJECT refs whose targets weren't loaded yet (e.g. a player's
        # GuildID applied before the guild entity exists); re-resolved on
        # every subsequent load and via resolve_refs()
        self._pending: list = []

    def bind(self, kernel: Kernel) -> "PlayerDataAgent":
        self.kernel = kernel
        kernel.register_class_event(self._on_event, self.class_name)
        return self

    def _key_of(self, guid: Guid) -> Optional[str]:
        if self._key_fn is not None:
            k = self._key_fn(guid)
            return None if not k else self.key_prefix + k
        spec = self.kernel.store.spec(self.class_name)
        if spec.has_property("Account") and spec.has_property("Name"):
            account = str(self.kernel.get_property(guid, "Account"))
            name = str(self.kernel.get_property(guid, "Name"))
            if account and name:
                return f"{self.key_prefix}{account}:{name}"
        return None

    # -- lifecycle hooks ------------------------------------------------
    def _on_event(self, guid: Guid, cname: str, ev: ObjectEvent) -> None:
        if ev == ObjectEvent.CREATE_LOADDATA:
            self.load(guid)
        elif ev == ObjectEvent.BEFORE_DESTROY:
            self.save(guid)

    def load(self, guid: Guid) -> bool:
        key = self._key_of(guid)
        if key is None:
            return False
        blob = None
        if self.pipeline is not None:
            # read-your-writes: a save still queued (store down, or the
            # flusher simply hasn't reached it) must win over the
            # store's stale copy; a queued tombstone means "no blob"
            queued, pend = self.pipeline.pending(key)
            if queued:
                blob = pend
                if blob is None:
                    return False
        if blob is None:
            blob = self.kv.get(key)
        if blob is None:
            return False
        k = self.kernel
        k.state = apply_snapshot(k.store, k.state, guid, blob, self._pending)
        self.resolve_refs()
        return True

    def resolve_refs(self) -> int:
        """Re-apply deferred OBJECT references whose targets exist now;
        returns how many remain unresolved (load-order independence)."""
        if not self._pending:
            return 0
        k = self.kernel
        k.state, self._pending = resolve_pending(k.store, k.state, self._pending)
        return len(self._pending)

    def save(self, guid: Guid) -> bool:
        key = self._key_of(guid)
        if key is None:
            return False
        k = self.kernel
        blob = snapshot_object(k.store, k.state, guid, self.flags)
        if self.pipeline is not None:
            self.pipeline.enqueue_one(key, blob)
        else:
            self.kv.set(key, blob)
        return True

    def exists(self, key: str) -> bool:
        """key is the suffix after the prefix, e.g. "account:RoleName"."""
        full = self.key_prefix + key
        if self.pipeline is not None:
            queued, pend = self.pipeline.pending(full)
            if queued:
                return pend is not None
        return self.kv.exists(full)

    def delete(self, key: str) -> bool:
        """Drop a character's blob (role deletion).  With a pipeline the
        delete is a queued tombstone: it supersedes any older queued
        save (no resurrection) and reaches the store durably."""
        full = self.key_prefix + key
        if self.pipeline is not None:
            self.pipeline.discard(full)
            self.pipeline.enqueue_one(full, None)
            return True
        return self.kv.delete(full)


class RoleListStore:
    """Account → role-list persistence (the pre-enter-game role CRUD data;
    reference NFCAccountRedisModule keeps these under account keys)."""

    def __init__(self, kv: KVStore, key_prefix: str = "roles:") -> None:
        self.kv = kv
        self.key_prefix = key_prefix

    def load(self, account: str) -> List[RoleLiteInfo]:
        blob = self.kv.get(self.key_prefix + account)
        if blob is None:
            return []
        return list(AckRoleLiteInfoList.decode(blob).char_data)

    def save(self, account: str, roles: List[RoleLiteInfo]) -> None:
        self.kv.set(
            self.key_prefix + account,
            AckRoleLiteInfoList(char_data=list(roles)).encode(),
        )
