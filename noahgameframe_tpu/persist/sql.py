"""Relational persistence: the reference MySQL module's API over SQLite.

Reference: NFMysqlPlugin exposes a key-value-style API over tables —
`Updata/Query/Select/Delete/Exists/Keys` with (table, key, fieldVec,
valueVec) signatures (`NFCMysqlModule.h:32-40`) plus a driver manager
with reconnect keepalive.  The engine here is stdlib sqlite3 (no server
dependency); the API shape is preserved so a real MySQL driver can slot
behind the same calls.  Rows are (id TEXT PRIMARY KEY, field columns
added on demand) exactly like the reference's generated NFrame.sql
tables.
"""

from __future__ import annotations

import sqlite3
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

_ID = "id"


def _q(name: str) -> str:
    """Quote an identifier; reject anything that cannot be a column."""
    if not name.replace("_", "").isalnum():
        raise ValueError(f"bad identifier {name!r}")
    return f'"{name}"'


class SqlModule:
    """Updata/Query/Select/Delete/Exists/Keys over a SQLite database."""

    def __init__(self, path: Union[str, Path] = ":memory:") -> None:
        self._conn = sqlite3.connect(str(path), check_same_thread=False)
        self._lock = threading.Lock()
        self._known_cols: Dict[str, set] = {}

    # -- schema management (CREATE TABLE on demand) ---------------------
    def _ensure(self, table: str, fields: Sequence[str]) -> None:
        t = _q(table)
        with self._lock:
            cols = self._known_cols.get(table)
            if cols is None:
                self._conn.execute(
                    f"CREATE TABLE IF NOT EXISTS {t} ({_ID} TEXT PRIMARY KEY)"
                )
                cols = {
                    r[1]
                    for r in self._conn.execute(f"PRAGMA table_info({t})")
                }
                self._known_cols[table] = cols
            for f in fields:
                if f not in cols:
                    self._conn.execute(f"ALTER TABLE {t} ADD COLUMN {_q(f)}")
                    cols.add(f)

    # -- reference-shaped API -------------------------------------------
    def updata(self, table: str, key: str, fields: Sequence[str],
               values: Sequence[Union[str, bytes, int, float]]) -> bool:
        """Upsert one row (the reference's spelling)."""
        if len(fields) != len(values):
            return False
        self._ensure(table, fields)
        with self._lock:
            if not fields:  # key-only touch
                self._conn.execute(
                    f"INSERT OR IGNORE INTO {_q(table)} ({_ID}) VALUES (?)",
                    [key],
                )
            else:
                cols = ", ".join(_q(f) for f in fields)
                marks = ", ".join("?" for _ in fields)
                sets = ", ".join(f"{_q(f)}=excluded.{_q(f)}" for f in fields)
                self._conn.execute(
                    f"INSERT INTO {_q(table)} ({_ID}, {cols}) "
                    f"VALUES (?, {marks}) ON CONFLICT({_ID}) DO UPDATE SET {sets}",
                    [key, *values],
                )
            self._conn.commit()
        return True

    def query(self, table: str, key: str,
              fields: Sequence[str]) -> Optional[List]:
        """Read selected fields of one row (reference Query)."""
        self._ensure(table, fields)
        cols = ", ".join(_q(f) for f in fields)
        with self._lock:
            row = self._conn.execute(
                f"SELECT {cols} FROM {_q(table)} WHERE {_ID}=?", [key]
            ).fetchone()
        return list(row) if row is not None else None

    def select(self, table: str, key: str) -> Optional[Dict[str, object]]:
        """Whole row as a field->value dict."""
        self._ensure(table, ())
        with self._lock:
            cur = self._conn.execute(
                f"SELECT * FROM {_q(table)} WHERE {_ID}=?", [key]
            )
            row = cur.fetchone()
            if row is None:
                return None
            names = [d[0] for d in cur.description]
        return dict(zip(names, row))

    def delete(self, table: str, key: str) -> bool:
        self._ensure(table, ())
        with self._lock:
            n = self._conn.execute(
                f"DELETE FROM {_q(table)} WHERE {_ID}=?", [key]
            ).rowcount
            self._conn.commit()
        return n > 0

    def exists(self, table: str, key: str) -> bool:
        self._ensure(table, ())
        with self._lock:
            row = self._conn.execute(
                f"SELECT 1 FROM {_q(table)} WHERE {_ID}=?", [key]
            ).fetchone()
        return row is not None

    def keys(self, table: str, like: str = "%") -> List[str]:
        self._ensure(table, ())
        with self._lock:
            rows = self._conn.execute(
                f"SELECT {_ID} FROM {_q(table)} WHERE {_ID} LIKE ?", [like]
            ).fetchall()
        return sorted(r[0] for r in rows)

    def close(self) -> None:
        self._conn.close()


def emit_ddl(registry, class_names: Sequence[str]) -> str:
    """Generate CREATE TABLE statements for save-flagged properties — the
    NFrame.sql emitter of the reference codegen (`FileProcess.h:38-72`)."""
    out: List[str] = []
    for cname in class_names:
        cdef = registry.get_def(cname)
        cols = [f"  {_q(_ID)} TEXT PRIMARY KEY"]
        for p in cdef.properties:
            if not (p.save or p.cache):
                continue
            sql_t = {
                1: "BIGINT", 2: "DOUBLE", 3: "TEXT",
                4: "TEXT", 5: "TEXT", 6: "TEXT",
            }[int(p.type)]
            cols.append(f"  {_q(p.name)} {sql_t}")
        body = ",\n".join(cols)
        out.append(f"CREATE TABLE IF NOT EXISTS {_q(cname)} (\n{body}\n);")
    return "\n".join(out)
