"""Relational persistence: the reference MySQL module's API + driver FSM.

Reference: NFMysqlPlugin exposes a key-value-style API over tables —
`Updata/Query/Select/Delete/Exists/Keys` with (table, key, fieldVec,
valueVec) signatures (`NFCMysqlModule.h:32-40`) plus a driver manager
with reconnect keepalive.  Two engines sit behind the same surface:
stdlib sqlite3 here (serverless), and the real MySQL wire protocol in
persist/mysql.py — SqlDriver selects by registration (ip/port ⇒ MySQL).
Rows are (id TEXT PRIMARY KEY, field columns added on demand) exactly
like the reference's generated NFrame.sql tables.
"""

from __future__ import annotations

import dataclasses
import sqlite3
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

_ID = "id"


def _q(name: str) -> str:
    """Quote an identifier; reject anything that cannot be a column."""
    if not name.replace("_", "").isalnum():
        raise ValueError(f"bad identifier {name!r}")
    return f'"{name}"'


class SqlModule:
    """Updata/Query/Select/Delete/Exists/Keys over a SQLite database."""

    def __init__(self, path: Union[str, Path] = ":memory:") -> None:
        self._conn = sqlite3.connect(str(path), check_same_thread=False)
        self._lock = threading.Lock()
        self._known_cols: Dict[str, set] = {}

    # -- schema management (CREATE TABLE on demand) ---------------------
    def _ensure(self, table: str, fields: Sequence[str]) -> None:
        t = _q(table)
        with self._lock:
            cols = self._known_cols.get(table)
            if cols is None:
                self._conn.execute(
                    f"CREATE TABLE IF NOT EXISTS {t} ({_ID} TEXT PRIMARY KEY)"
                )
                cols = {
                    r[1]
                    for r in self._conn.execute(f"PRAGMA table_info({t})")
                }
                self._known_cols[table] = cols
            for f in fields:
                if f not in cols:
                    self._conn.execute(f"ALTER TABLE {t} ADD COLUMN {_q(f)}")
                    cols.add(f)

    # -- reference-shaped API -------------------------------------------
    def updata(self, table: str, key: str, fields: Sequence[str],
               values: Sequence[Union[str, bytes, int, float]]) -> bool:
        """Upsert one row (the reference's spelling)."""
        if len(fields) != len(values):
            return False
        self._ensure(table, fields)
        with self._lock:
            if not fields:  # key-only touch
                self._conn.execute(
                    f"INSERT OR IGNORE INTO {_q(table)} ({_ID}) VALUES (?)",
                    [key],
                )
            else:
                cols = ", ".join(_q(f) for f in fields)
                marks = ", ".join("?" for _ in fields)
                sets = ", ".join(f"{_q(f)}=excluded.{_q(f)}" for f in fields)
                self._conn.execute(
                    f"INSERT INTO {_q(table)} ({_ID}, {cols}) "
                    f"VALUES (?, {marks}) ON CONFLICT({_ID}) DO UPDATE SET {sets}",
                    [key, *values],
                )
            self._conn.commit()
        return True

    def query(self, table: str, key: str,
              fields: Sequence[str]) -> Optional[List]:
        """Read selected fields of one row (reference Query)."""
        self._ensure(table, fields)
        cols = ", ".join(_q(f) for f in fields)
        with self._lock:
            row = self._conn.execute(
                f"SELECT {cols} FROM {_q(table)} WHERE {_ID}=?", [key]
            ).fetchone()
        return list(row) if row is not None else None

    def select(self, table: str, key: str) -> Optional[Dict[str, object]]:
        """Whole row as a field->value dict."""
        self._ensure(table, ())
        with self._lock:
            cur = self._conn.execute(
                f"SELECT * FROM {_q(table)} WHERE {_ID}=?", [key]
            )
            row = cur.fetchone()
            if row is None:
                return None
            names = [d[0] for d in cur.description]
        return dict(zip(names, row))

    def delete(self, table: str, key: str) -> bool:
        self._ensure(table, ())
        with self._lock:
            n = self._conn.execute(
                f"DELETE FROM {_q(table)} WHERE {_ID}=?", [key]
            ).rowcount
            self._conn.commit()
        return n > 0

    def exists(self, table: str, key: str) -> bool:
        self._ensure(table, ())
        with self._lock:
            row = self._conn.execute(
                f"SELECT 1 FROM {_q(table)} WHERE {_ID}=?", [key]
            ).fetchone()
        return row is not None

    def keys(self, table: str, like: str = "%") -> List[str]:
        self._ensure(table, ())
        with self._lock:
            rows = self._conn.execute(
                f"SELECT {_ID} FROM {_q(table)} WHERE {_ID} LIKE ?", [like]
            ).fetchall()
        return sorted(r[0] for r in rows)

    def close(self) -> None:
        self._conn.close()

    def ping(self) -> bool:
        """Connection health probe (the driver manager's keepalive)."""
        try:
            with self._lock:
                self._conn.execute("SELECT 1")
            return True
        except sqlite3.Error:
            return False


# ---------------------------------------------------------------------------
# Driver manager: multi-server registration + keepalive/reconnect FSM
# ---------------------------------------------------------------------------

DRV_DISCONNECTED, DRV_CONNECTED = 0, 1


@dataclasses.dataclass
class SqlServerConfig:
    """One database server row (reference AddMysqlServer signature:
    serverID, dns/ip, port, dbName, user, password, reconnect time/count —
    NFCMysqlModule.h:32-40).  The sqlite engine only uses db_name as the
    database path; the endpoint/credential fields ride along so a real
    MySQL driver slots behind the same registration call."""

    server_id: int
    db_name: str = ":memory:"
    ip: str = ""
    port: int = 0
    user: str = ""
    password: str = ""
    reconnect_time: float = 10.0
    reconnect_count: int = -1  # -1 = retry forever


class SqlDriver:
    """One managed connection with a reconnect state machine."""

    def __init__(self, config: SqlServerConfig) -> None:
        self.config = config
        self.state = DRV_DISCONNECTED
        self.module = None  # SqlModule or mysql.MysqlModule
        self.reconnects_left = config.reconnect_count
        self.last_error = ""  # most recent connect failure, for operators
        self._next_attempt = 0.0

    def _drop_module(self) -> None:
        """Close the dead connection before discarding it — reconnect
        cycles must not leak file handles / lock-holding transactions."""
        if self.module is not None:
            try:
                self.module.close()
            except (sqlite3.Error, OSError):
                pass
            self.module = None

    def connect(self, now: float = 0.0) -> bool:
        """Engine selection mirrors the reference AddMysqlServer: an
        ip/port endpoint means a real MySQL wire connection
        (persist.mysql.MysqlModule, handshake + native-password auth);
        otherwise the serverless sqlite engine."""
        self._drop_module()
        from .mysql import MysqlError, MysqlModule

        try:
            if self.config.ip and self.config.port:
                self.module = MysqlModule(
                    self.config.ip,
                    self.config.port,
                    self.config.user,
                    self.config.password,
                    "" if self.config.db_name == ":memory:"
                    else self.config.db_name,
                )
            else:
                self.module = SqlModule(self.config.db_name)
            self.state = DRV_CONNECTED
            return True
        except (sqlite3.Error, MysqlError, OSError) as e:
            self.last_error = str(e)  # e.g. "Access denied" vs refused
            self.state = DRV_DISCONNECTED
            self._next_attempt = now + self.config.reconnect_time
            return False

    def mark_dead(self, now: float) -> None:
        self._drop_module()
        self.state = DRV_DISCONNECTED
        self._next_attempt = now + self.config.reconnect_time

    def keep_alive(self, now: float) -> bool:
        """Ping; on failure enter DISCONNECTED and retry after
        reconnect_time, at most reconnect_count times (reference driver
        keepalive semantics).  Returns current health."""
        if self.state == DRV_CONNECTED:
            if self.module is not None and self.module.ping():
                return True
            self.mark_dead(now)
            return False
        if now >= self._next_attempt and self.reconnects_left != 0:
            if self.reconnects_left > 0:
                self.reconnects_left -= 1
            return self.connect(now)
        return False


class SqlDriverManager:
    """Multiple named servers behind one Updata/Query/... facade.

    Mirrors the reference's driver manager: register servers by id,
    operations route to a healthy driver (an explicit server_id or the
    first connected one), and `execute(now)` runs the 10 s keepalive
    sweep from the main loop."""

    def __init__(self, keepalive_seconds: float = 10.0) -> None:
        self.keepalive_seconds = float(keepalive_seconds)
        self._drivers: Dict[int, SqlDriver] = {}
        self._last_sweep = 0.0
        self._now = 0.0  # latest injected time (advanced by execute())

    def add_server(self, config: SqlServerConfig, now: float = 0.0) -> SqlDriver:
        old = self._drivers.get(config.server_id)
        if old is not None:
            old._drop_module()  # re-registration must not leak the old link
        drv = SqlDriver(config)
        drv.connect(now)
        self._drivers[config.server_id] = drv
        self._now = max(self._now, now)
        return drv

    def driver(self, server_id: Optional[int] = None) -> Optional[SqlDriver]:
        if server_id is not None:
            d = self._drivers.get(server_id)
            return d if d is not None and d.state == DRV_CONNECTED else None
        for d in self._drivers.values():
            if d.state == DRV_CONNECTED:
                return d
        return None

    def execute(self, now: float) -> None:
        self._now = max(self._now, now)
        if now - self._last_sweep < self.keepalive_seconds:
            return
        self._last_sweep = now
        for d in self._drivers.values():
            d.keep_alive(now)

    # -- facade (reference-shaped, returns False/None on any failure) ----
    def _call(self, server_id: Optional[int], op, fail):
        """Route to a healthy driver; failures return the `fail` value
        instead of leaking sqlite3.Error into the caller's main-loop
        tick.  A statement/data error on a healthy connection (bad bind
        value, constraint) does NOT kill the driver — only a failed
        re-ping marks it dead, arming the backoff from the latest
        injected time."""
        from .mysql import MysqlError

        d = self.driver(server_id)
        if d is None or d.module is None:
            return fail
        try:
            return op(d.module)
        except (sqlite3.Error, MysqlError, OSError, ValueError):
            # ValueError: identifier validation (_q/_bq) — a caller bug,
            # not a connection fault; either way the tick must not die.
            # MysqlError/OSError: wire engine faults — ping-check below
            # marks the driver dead so routing fails over immediately.
            if not d.module.ping():
                d.mark_dead(self._now)
            return fail

    def updata(self, table, key, fields, values, server_id=None) -> bool:
        return self._call(
            server_id, lambda m: m.updata(table, key, fields, values), False
        )

    def query(self, table, key, fields, server_id=None):
        return self._call(
            server_id, lambda m: m.query(table, key, fields), None
        )

    def select(self, table, key, server_id=None):
        return self._call(server_id, lambda m: m.select(table, key), None)

    def delete(self, table, key, server_id=None) -> bool:
        return self._call(server_id, lambda m: m.delete(table, key), False)

    def exists(self, table, key, server_id=None) -> bool:
        return self._call(server_id, lambda m: m.exists(table, key), False)

    def keys(self, table, like="%", server_id=None):
        return self._call(server_id, lambda m: m.keys(table, like), [])

    def close(self) -> None:
        """Terminal shutdown: drivers close AND lose their reconnect
        budget, so a stray execute() after close cannot reopen files."""
        for d in self._drivers.values():
            d._drop_module()
            d.state = DRV_DISCONNECTED
            d.reconnects_left = 0


def emit_ddl(registry, class_names: Sequence[str]) -> str:
    """Generate CREATE TABLE statements for save-flagged properties — the
    NFrame.sql emitter of the reference codegen (`FileProcess.h:38-72`)."""
    out: List[str] = []
    for cname in class_names:
        cdef = registry.get_def(cname)
        cols = [f"  {_q(_ID)} TEXT PRIMARY KEY"]
        for p in cdef.properties:
            if not (p.save or p.cache):
                continue
            sql_t = {
                1: "BIGINT", 2: "DOUBLE", 3: "TEXT",
                4: "TEXT", 5: "TEXT", 6: "TEXT",
            }[int(p.type)]
            cols.append(f"  {_q(p.name)} {sql_t}")
        body = ",\n".join(cols)
        out.append(f"CREATE TABLE IF NOT EXISTS {_q(cname)} (\n{body}\n);")
    return "\n".join(out)
