"""RESP (Redis serialization protocol) client + a miniature server.

Reference: NFNoSqlPlugin drives a real Redis through a vendored C++
client (`NFComm/NFNoSqlPlugin/`, wrapping redis-cplusplus-client).  Here
:class:`RespKV` is a from-scratch RESP2 client implementing the same op
set over a blocking socket (persistence is control-plane, not tick-path),
and :class:`MiniRedisServer` is an in-process RESP server implementing
just enough of the command set (GET/SET/DEL/EXISTS/KEYS/HSET/HGET/
HGETALL/HDEL/PING) to stand in for Redis in tests and single-box
deployments — the localhost analogue of the reference's "start redis
first" deployment step.
"""

from __future__ import annotations

import fnmatch
import socket
import socketserver
import threading
from typing import Dict, List, Optional

from .kv import KVStore, MemoryKV

# ---------------------------------------------------------------- protocol


def encode_command(*parts: bytes) -> bytes:
    out = [b"*%d\r\n" % len(parts)]
    for p in parts:
        out.append(b"$%d\r\n%s\r\n" % (len(p), p))
    return b"".join(out)


class _RespReader:
    """Incremental RESP value reader over a readable file object."""

    def __init__(self, rfile) -> None:
        self.rfile = rfile

    def read_value(self):
        line = self.rfile.readline()
        if not line:
            raise ConnectionError("connection closed")
        kind, rest = line[:1], line[1:-2]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RuntimeError(f"redis error: {rest.decode()}")
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self.rfile.read(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self.read_value() for _ in range(n)]
        raise ValueError(f"bad RESP type byte {kind!r}")


# ---------------------------------------------------------------- client


class RespKV(KVStore):
    """KVStore over a live RESP endpoint (Redis or MiniRedisServer)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout: float = 5.0) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._rfile = self._sock.makefile("rb")
        self._reader = _RespReader(self._rfile)
        self._lock = threading.Lock()

    def _cmd(self, *parts):
        enc = [p.encode() if isinstance(p, str) else bytes(p) for p in parts]
        with self._lock:
            self._sock.sendall(encode_command(*enc))
            return self._reader.read_value()

    def ping(self) -> bool:
        return self._cmd("PING") == "PONG"

    def get(self, key: str) -> Optional[bytes]:
        return self._cmd("GET", key)

    def set(self, key: str, value: bytes) -> None:
        self._cmd("SET", key, value)

    def delete(self, key: str) -> bool:
        return int(self._cmd("DEL", key)) > 0

    def exists(self, key: str) -> bool:
        return int(self._cmd("EXISTS", key)) > 0

    def keys(self, pattern: str = "*") -> List[str]:
        return sorted(k.decode() for k in self._cmd("KEYS", pattern))

    def hset(self, key: str, field: str, value: bytes) -> None:
        self._cmd("HSET", key, field, value)

    def hget(self, key: str, field: str) -> Optional[bytes]:
        return self._cmd("HGET", key, field)

    def hgetall(self, key: str) -> Dict[str, bytes]:
        flat = self._cmd("HGETALL", key)
        return {
            flat[i].decode(): flat[i + 1] for i in range(0, len(flat), 2)
        }

    def hdel(self, key: str, field: str) -> bool:
        return int(self._cmd("HDEL", key, field)) > 0

    def close(self) -> None:
        try:
            self._rfile.close()
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------- server


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        reader = _RespReader(self.rfile)
        store: MemoryKV = self.server.store  # type: ignore[attr-defined]
        lock: threading.Lock = self.server.lock  # type: ignore[attr-defined]
        while True:
            try:
                parts = reader.read_value()
            except (ConnectionError, ValueError):
                return
            if not isinstance(parts, list) or not parts:
                return
            cmd = parts[0].decode().upper()
            args = parts[1:]
            with lock:
                self.wfile.write(self._run(store, cmd, args))
            self.wfile.flush()

    def _run(self, store: MemoryKV, cmd: str, args: List[bytes]) -> bytes:
        def s(i: int) -> str:
            return args[i].decode()

        if cmd == "PING":
            return b"+PONG\r\n"
        if cmd == "SET":
            store.set(s(0), args[1])
            return b"+OK\r\n"
        if cmd == "GET":
            v = store.get(s(0))
            return b"$-1\r\n" if v is None else b"$%d\r\n%s\r\n" % (len(v), v)
        if cmd == "DEL":
            n = sum(1 for a in args if store.delete(a.decode()))
            return b":%d\r\n" % n
        if cmd == "EXISTS":
            return b":%d\r\n" % (1 if store.exists(s(0)) else 0)
        if cmd == "KEYS":
            ks = store.keys(s(0))
            return b"*%d\r\n" % len(ks) + b"".join(
                b"$%d\r\n%s\r\n" % (len(k.encode()), k.encode()) for k in ks
            )
        if cmd == "HSET":
            store.hset(s(0), s(1), args[2])
            return b":1\r\n"
        if cmd == "HGET":
            v = store.hget(s(0), s(1))
            return b"$-1\r\n" if v is None else b"$%d\r\n%s\r\n" % (len(v), v)
        if cmd == "HGETALL":
            h = store.hgetall(s(0))
            out = [b"*%d\r\n" % (2 * len(h))]
            for f, v in h.items():
                fb = f.encode()
                out.append(b"$%d\r\n%s\r\n" % (len(fb), fb))
                out.append(b"$%d\r\n%s\r\n" % (len(v), v))
            return b"".join(out)
        if cmd == "HDEL":
            return b":%d\r\n" % (1 if store.hdel(s(0), s(1)) else 0)
        return b"-ERR unknown command '%s'\r\n" % cmd.encode()


class MiniRedisServer:
    """Threaded in-process RESP server over a MemoryKV."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.store = MemoryKV()
        self.lock = threading.Lock()
        self._srv = socketserver.ThreadingTCPServer((host, port), _Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.store = self.store  # type: ignore[attr-defined]
        self._srv.lock = self.lock  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=2)
