"""KV-backed persistence for the social systems (mail, rank, guild).

Reference: `NFServer/NFDataAgent_NosqlPlugin/` — each social system
persists its own Redis keys as it mutates, independent of player blobs
and whole-world checkpoints.  Same seam here: the agent binds a
:class:`~noahgameframe_tpu.persist.kv.KVStore` to the social modules and
write-through-saves on every mutation:

- ``mail:<account>``  — the account's mailbox (JSON);
- ``rank:<list>``     — one named score list (JSON);
- ``guild:<name>``    — durable guild membership by ACCOUNT (JSON).

Guilds need the account indirection: live ``GroupInfo`` rosters hold
entity guids, which die at logout (the membership module removes
destroyed members on purpose).  The durable truth is the account set;
when a member logs back in, :meth:`SocialDataAgent` re-links them — the
guild entity is resurrected on the first returning member (who holds
interim leadership until the saved leader returns) and each member
re-joins as they arrive.  A leave caused by entity destruction
(``destroy_cleanup``) keeps durable membership; a voluntary leave drops
it.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Optional, Set

from ..core.datatypes import Guid
from .kv import KVStore

MAIL_PREFIX = "mail:"
RANK_PREFIX = "rank:"
GUILD_PREFIX = "guild:"


class SocialDataAgent:
    """Write-through KV persistence + login re-link for social state."""

    def __init__(self, kv: KVStore) -> None:
        self.kv = kv
        self.kernel = None
        self.mail = None
        self.rank = None
        self.guilds = None
        # durable guild rosters: name -> {"leader": account,
        # "members": [account, ...], "capacity": int}
        self._guild_records: Dict[str, dict] = {}

    # ------------------------------------------------------------- bind
    def bind(self, kernel, mail=None, rank=None, guilds=None) -> "SocialDataAgent":
        self.kernel = kernel
        if mail is not None:
            self.mail = mail
            self._load_mail()
            mail.on_dirty = self._save_mailbox
        if rank is not None:
            self.rank = rank
            self._load_rank()
            rank.on_dirty = self._save_rank
        if guilds is not None:
            self.guilds = guilds
            self._load_guilds()
            guilds.on_membership_event = self._on_guild_event
            # a dormant guild (all members offline, entity dissolved)
            # still owns its name — strangers must not merge into its
            # durable record by re-creating the name
            guilds.name_taken = lambda n: n in self._guild_records
            from ..kernel.kernel import ObjectEvent

            def on_player(guid: Guid, _cn: str, ev) -> None:
                if ev == ObjectEvent.CREATE_FINISH:
                    self.relink(guid)

            kernel.register_class_event(on_player, "Player")
        return self

    # ------------------------------------------------------------- mail
    def _load_mail(self) -> None:
        from ..game.social import Mail

        meta = self.kv.get(MAIL_PREFIX + "__meta__")
        if meta:
            self.mail._next_id = int(json.loads(meta)["next_id"])
        for key in self.kv.keys(MAIL_PREFIX + "*"):
            account = key[len(MAIL_PREFIX):]
            if account == "__meta__":
                continue
            raw = self.kv.get(key)
            if raw:
                self.mail._boxes[account] = [
                    Mail(**m) for m in json.loads(raw)
                ]

    def _save_mailbox(self, account: str) -> None:
        box = self.mail._boxes.get(account, [])
        key = MAIL_PREFIX + account
        if box:
            self.kv.set(key, json.dumps(
                [dataclasses.asdict(m) for m in box]).encode())
        else:
            self.kv.delete(key)
        self.kv.set(MAIL_PREFIX + "__meta__",
                    json.dumps({"next_id": self.mail._next_id}).encode())

    # ------------------------------------------------------------- rank
    def _load_rank(self) -> None:
        for key in self.kv.keys(RANK_PREFIX + "*"):
            raw = self.kv.get(key)
            if raw:
                self.rank._lists[key[len(RANK_PREFIX):]] = {
                    k: int(v) for k, v in json.loads(raw).items()
                }

    def _save_rank(self, list_name: str) -> None:
        entries = self.rank._lists.get(list_name, {})
        key = RANK_PREFIX + list_name
        if entries:
            self.kv.set(key, json.dumps(entries).encode())
        else:
            self.kv.delete(key)

    # ------------------------------------------------------------ guilds
    def _account_of(self, guid: Guid) -> Optional[str]:
        if self.kernel is None or guid not in self.kernel.store.guid_map:
            return None
        acct = str(self.kernel.get_property(guid, "Account"))
        return acct or None

    def _load_guilds(self) -> None:
        self._guild_records = {}
        for key in self.kv.keys(GUILD_PREFIX + "*"):
            raw = self.kv.get(key)
            if raw:
                self._guild_records[key[len(GUILD_PREFIX):]] = json.loads(raw)

    def _persist_guild(self, name: str) -> None:
        key = GUILD_PREFIX + name
        rec = self._guild_records.get(name)
        if rec and rec["members"]:
            self.kv.set(key, json.dumps(rec).encode())
        else:
            self._guild_records.pop(name, None)
            self.kv.delete(key)

    def _on_guild_event(self, event: str, g, member, cleanup: bool) -> None:
        if not g.name:
            return  # unnamed groups (teams) are transient by design
        rec = self._guild_records.setdefault(
            g.name, {"leader": "", "members": [], "capacity": g.capacity})
        acct = self._account_of(member) if member is not None else None
        if event == "create":
            rec["leader"] = acct or rec["leader"]
            if acct and acct not in rec["members"]:
                rec["members"].append(acct)
        elif event == "join":
            if acct and acct not in rec["members"]:
                rec["members"].append(acct)
        elif event == "leave":
            # logout keeps durable membership; walking out drops it
            if not cleanup and acct in rec["members"]:
                rec["members"].remove(acct)
                if rec["leader"] == acct and rec["members"]:
                    rec["leader"] = rec["members"][0]
        elif event == "disband":
            rec["members"] = []
        # entity dissolve with surviving durable members (last member
        # logged out) keeps the record — relink resurrects the guild
        self._persist_guild(g.name)

    def relink(self, guid: Guid) -> None:
        """Re-attach a logging-in player to their durable guild: first
        returning member resurrects the guild entity (interim leader);
        the saved leader reclaims leadership on return."""
        acct = self._account_of(guid)
        if acct is None or self.guilds is None:
            return
        for name, rec in list(self._guild_records.items()):
            if acct not in rec["members"]:
                continue
            info = self.guilds.find_by_name(name)
            # resurrect/re-join without re-firing durable bookkeeping,
            # and with the dormant-name reservation lifted for ourselves
            cb = self.guilds.on_membership_event
            taken = self.guilds.name_taken
            self.guilds.on_membership_event = None
            self.guilds.name_taken = None
            try:
                if info is None:
                    self.guilds.create_guild(guid, name)
                elif guid not in info.members:
                    self.guilds.join(info.group_id, guid)
            finally:
                self.guilds.on_membership_event = cb
                self.guilds.name_taken = taken
            info = self.guilds.find_by_name(name)
            if info is not None and rec["leader"] == acct:
                info.leader = guid
                self.kernel.set_property(info.group_id, "LeaderID", guid)
            return  # at most one guild per player
