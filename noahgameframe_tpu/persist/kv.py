"""Pluggable key-value backends for the persistence agents.

Reference: NFNoSqlPlugin wraps a Redis client with KV/Hash ops behind
`NFINoSqlModule` (`NFCNoSqlDriver.h:29-120`), and the data agents store
player blobs under string keys.  The same seam here: agents speak
:class:`KVStore`; deployments pick memory (tests), file (single-node
durability) or the RESP client in resp.py (real Redis).
"""

from __future__ import annotations

import fnmatch
import hashlib
import os
import tempfile
from pathlib import Path
from typing import Dict, Iterable, List, Optional


class KVStore:
    """The minimal contract the agents need (subset of NFINoSqlModule)."""

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def set(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        return self.get(key) is not None

    def keys(self, pattern: str = "*") -> List[str]:
        raise NotImplementedError

    # hash ops (HSET/HGET/HGETALL family)
    def hset(self, key: str, field: str, value: bytes) -> None:
        raise NotImplementedError

    def hget(self, key: str, field: str) -> Optional[bytes]:
        raise NotImplementedError

    def hgetall(self, key: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def hdel(self, key: str, field: str) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryKV(KVStore):
    """In-process dict backend (tests, single-process worlds)."""

    def __init__(self) -> None:
        self._kv: Dict[str, bytes] = {}
        self._hashes: Dict[str, Dict[str, bytes]] = {}

    def get(self, key: str) -> Optional[bytes]:
        return self._kv.get(key)

    def set(self, key: str, value: bytes) -> None:
        self._kv[key] = bytes(value)

    def delete(self, key: str) -> bool:
        had = key in self._kv or key in self._hashes
        self._kv.pop(key, None)
        self._hashes.pop(key, None)
        return had

    def keys(self, pattern: str = "*") -> List[str]:
        names = set(self._kv) | set(self._hashes)
        return sorted(k for k in names if fnmatch.fnmatchcase(k, pattern))

    def hset(self, key: str, field: str, value: bytes) -> None:
        self._hashes.setdefault(key, {})[field] = bytes(value)

    def hget(self, key: str, field: str) -> Optional[bytes]:
        return self._hashes.get(key, {}).get(field)

    def hgetall(self, key: str) -> Dict[str, bytes]:
        return dict(self._hashes.get(key, {}))

    def hdel(self, key: str, field: str) -> bool:
        h = self._hashes.get(key)
        if h and field in h:
            del h[field]
            return True
        return False


class FileKV(KVStore):
    """One file per key under a directory; atomic writes via rename.

    Keys are hashed into the filename (keys may contain '/' etc.); the
    original key is stored alongside for `keys()` listing."""

    def __init__(self, root: Path) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str, kind: str = "v") -> Path:
        h = hashlib.sha1(key.encode()).hexdigest()
        return self.root / f"{h}.{kind}"

    def _write_atomic(self, path: Path, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=str(self.root))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, str(path))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, key: str) -> Optional[bytes]:
        p = self._path(key)
        return p.read_bytes() if p.exists() else None

    def set(self, key: str, value: bytes) -> None:
        self._write_atomic(self._path(key, "k"), key.encode())
        self._write_atomic(self._path(key), value)

    def delete(self, key: str) -> bool:
        had = False
        for kind in ("v", "k", "h"):
            p = self._path(key, kind)
            if p.exists():
                p.unlink()
                had = True
        return had

    def keys(self, pattern: str = "*") -> List[str]:
        out = []
        for kp in self.root.glob("*.k"):
            key = kp.read_bytes().decode()
            if fnmatch.fnmatchcase(key, pattern):
                out.append(key)
        return sorted(out)

    # hashes: stored as one file of length-prefixed field/value pairs
    def _read_hash(self, key: str) -> Dict[str, bytes]:
        p = self._path(key, "h")
        if not p.exists():
            return {}
        data = p.read_bytes()
        out: Dict[str, bytes] = {}
        off = 0
        while off < len(data):
            fl = int.from_bytes(data[off : off + 4], "big")
            field = data[off + 4 : off + 4 + fl].decode()
            off += 4 + fl
            vl = int.from_bytes(data[off : off + 4], "big")
            out[field] = data[off + 4 : off + 4 + vl]
            off += 4 + vl
        return out

    def _write_hash(self, key: str, h: Dict[str, bytes]) -> None:
        self._write_atomic(self._path(key, "k"), key.encode())
        buf = bytearray()
        for field, value in h.items():
            fb = field.encode()
            buf += len(fb).to_bytes(4, "big") + fb
            buf += len(value).to_bytes(4, "big") + value
        self._write_atomic(self._path(key, "h"), bytes(buf))

    def hset(self, key: str, field: str, value: bytes) -> None:
        h = self._read_hash(key)
        h[field] = bytes(value)
        self._write_hash(key, h)

    def hget(self, key: str, field: str) -> Optional[bytes]:
        return self._read_hash(key).get(field)

    def hgetall(self, key: str) -> Dict[str, bytes]:
        return self._read_hash(key)

    def hdel(self, key: str, field: str) -> bool:
        h = self._read_hash(key)
        if field not in h:
            return False
        del h[field]
        self._write_hash(key, h)
        return True
