"""Whole-world checkpoint / resume.

The reference persists per-entity only (player blobs to Redis on
destroy); a crashed game server loses live NPC state.  The TPU build can
do strictly better: the world IS one pytree of arrays, so a checkpoint is
a device→host snapshot of every class bank plus the host-side identity
maps (guid allocation, free lists, string intern table).  SURVEY §5
("checkpoint/resume") calls this out as the TPU equivalent.

Format: one directory with `arrays.npz` (all banks, flat key namespace)
+ `meta.json` (guids, free rows, strings, tick).  No framework-specific
container, so checkpoints are debuggable with numpy alone.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.datatypes import Guid
from ..core.store import EntityStore, WorldState
from ..core.strings import StringTable
from ..kernel.kernel import Kernel


def _flatten_state(state: WorldState) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {
        "tick": np.asarray(state.tick),
        "rng": np.asarray(state.rng),
    }
    for cname, cs in state.classes.items():
        p = f"c/{cname}/"
        out[p + "i32"] = np.asarray(cs.i32)
        out[p + "f32"] = np.asarray(cs.f32)
        out[p + "vec"] = np.asarray(cs.vec)
        out[p + "alive"] = np.asarray(cs.alive)
        out[p + "t/next_fire"] = np.asarray(cs.timers.next_fire)
        out[p + "t/interval"] = np.asarray(cs.timers.interval)
        out[p + "t/remain"] = np.asarray(cs.timers.remain)
        out[p + "t/active"] = np.asarray(cs.timers.active)
        for rname, rec in cs.records.items():
            rp = f"{p}r/{rname}/"
            out[rp + "i32"] = np.asarray(rec.i32)
            out[rp + "f32"] = np.asarray(rec.f32)
            out[rp + "vec"] = np.asarray(rec.vec)
            out[rp + "used"] = np.asarray(rec.used)
    return out


def save_world(kernel: Kernel, path: Path, modules=()) -> None:
    """Snapshot the whole world (device state + host identity) to disk,
    atomically: everything is written into a temp sibling directory and
    renamed into place, so a crash mid-save leaves either the previous
    checkpoint or the new one — never a torn arrays.npz/meta.json pair.

    `modules` — iterable of Modules whose `checkpoint_state()` host state
    (teams, guild name index, mailboxes, rank lists, buff defs…) must
    survive the resume; without them a restored player's TeamID would
    point at a Team entity the TeamModule no longer knows."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.parent / f".{path.name}.tmp{os.getpid()}"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    arrays = _flatten_state(kernel.state)
    np.savez_compressed(tmp / "arrays.npz", **arrays)
    store = kernel.store
    mod_states = {}
    for m in modules:
        data = m.checkpoint_state()
        if data is not None:
            mod_states[m.name] = data
    meta = {
        "modules": mod_states,
        "class_order": store.class_order,
        "tick_count": kernel.tick_count,
        # the device tick duplicated host-side: load_world cross-checks
        # it against arrays.npz so a mixed pair is rejected, not resumed
        "array_tick": int(arrays["tick"]),
        "strings": store.strings.snapshot(),
        "guids": {
            f"{g.head}-{g.data}": int(h) for g, h in store.guid_map.items()
        },
        "hosts": {
            cname: {
                "free": [int(r) for r in host.free],
                "row_guid": [
                    (str(g) if g is not None else None) for g in host.row_guid
                ],
                "live_count": host.live_count,
            }
            for cname, host in store._hosts.items()
        },
    }
    (tmp / "meta.json").write_text(json.dumps(meta))
    # swap into place: os.replace can't overwrite a non-empty dir, so an
    # existing checkpoint is renamed aside first (the only non-atomic
    # window leaves a complete .old copy next to the complete new one)
    if path.exists():
        old = path.parent / f".{path.name}.old{os.getpid()}"
        if old.exists():
            shutil.rmtree(old)
        os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(tmp, path)


def peek_checkpoint(path) -> Optional[dict]:
    """Light read-side probe of a checkpoint directory (ISSUE 10): the
    failover driver wants the recovery basis tick of a DEAD peer's
    checkpoint without building a kernel or loading arrays.  Returns
    ``{"tick_count", "array_tick"}`` from meta.json, or None when no
    complete checkpoint exists (missing dir / torn write in flight)."""
    meta_path = Path(path) / "meta.json"
    try:
        meta = json.loads(meta_path.read_text())
    except (OSError, ValueError):
        return None
    return {
        "tick_count": int(meta.get("tick_count", 0)),
        "array_tick": int(meta.get("array_tick", meta.get("tick_count", 0))),
    }


def load_world(kernel: Kernel, path: Path, modules=()) -> None:
    """Restore a checkpoint into a kernel built from the SAME schema and
    capacities (shape mismatch raises).  Pass the same `modules` given to
    save_world; their host state restores after identity maps (so guids
    resolve).  Module state present in the checkpoint but not claimed by
    any passed module is ignored."""
    path = Path(path)
    arrays = np.load(path / "arrays.npz")
    meta = json.loads((path / "meta.json").read_text())
    recorded = meta.get("array_tick")
    if recorded is not None and int(recorded) != int(arrays["tick"]):
        raise ValueError(
            f"torn checkpoint: meta.json array_tick={int(recorded)} "
            f"disagrees with arrays.npz tick={int(arrays['tick'])}"
        )
    store = kernel.store
    if meta["class_order"] != store.class_order:
        raise ValueError(
            f"checkpoint classes {meta['class_order']} != store "
            f"{store.class_order}"
        )
    state = kernel.state
    new_classes = {}
    for cname in store.class_order:
        cs = state.classes[cname]
        p = f"c/{cname}/"

        def arr(key: str, like: jnp.ndarray) -> jnp.ndarray:
            a = arrays[key]
            if a.shape != like.shape:
                raise ValueError(
                    f"checkpoint {key} shape {a.shape} != {like.shape}"
                )
            return jnp.asarray(a)

        timers = cs.timers.replace(
            next_fire=arr(p + "t/next_fire", cs.timers.next_fire),
            interval=arr(p + "t/interval", cs.timers.interval),
            remain=arr(p + "t/remain", cs.timers.remain),
            active=arr(p + "t/active", cs.timers.active),
        )
        records = {}
        for rname, rec in cs.records.items():
            rp = f"{p}r/{rname}/"
            records[rname] = rec.replace(
                i32=arr(rp + "i32", rec.i32),
                f32=arr(rp + "f32", rec.f32),
                vec=arr(rp + "vec", rec.vec),
                used=arr(rp + "used", rec.used),
            )
        new_classes[cname] = cs.replace(
            i32=arr(p + "i32", cs.i32),
            f32=arr(p + "f32", cs.f32),
            vec=arr(p + "vec", cs.vec),
            alive=arr(p + "alive", cs.alive),
            timers=timers,
            records=records,
        )
    kernel.state = state.replace(
        classes=new_classes,
        tick=jnp.asarray(arrays["tick"]),
        rng=jnp.asarray(arrays["rng"]),
    )
    kernel.tick_count = int(meta["tick_count"])
    # host identity: strings must restore in-place (device columns hold
    # interned handles; modules may hold references to the table object)
    restored = StringTable.restore(meta["strings"])
    table = store.strings
    with table._lock:
        table._to_id = dict(restored._to_id)
        table._to_str = list(restored._to_str)
    store.guid_map.clear()
    for key, handle in meta["guids"].items():
        store.guid_map[Guid.parse(key)] = int(handle)
    for cname, hmeta in meta["hosts"].items():
        host = store._hosts[cname]
        host.free = [int(r) for r in hmeta["free"]]
        host.row_guid = [
            Guid.parse(s) if s else None for s in hmeta["row_guid"]
        ]
        host.live_count = int(hmeta["live_count"])
        # alloc_mask / guid columns are derived state — rebuild from
        # row_guid, else reconcile_deaths/_build_player_index and the
        # batch sync path see the pre-load allocation
        host.alloc_mask = np.asarray(
            [g is not None for g in host.row_guid], bool
        )
        host.guid_head = np.asarray(
            [g.head if g is not None else 0 for g in host.row_guid], np.int64
        )
        host.guid_data = np.asarray(
            [g.data if g is not None else 0 for g in host.row_guid], np.int64
        )
    mod_states = meta.get("modules", {})
    for m in modules:
        data = mod_states.get(m.name)
        if data is not None:
            m.restore_state(data)
