"""Row blob: the single definition of "an entity's state" as bytes/leaves.

Two consumers share this module so they can never disagree about what a
full entity row contains:

* **cross-host failover** (net/failover.py, net/roles/game.py) frames the
  session snapshot blob with a CRC so a torn hand-off is detected before
  ``apply_snapshot`` ever sees it, and
* **on-mesh migration** (parallel/rowmigrate.py) derives its pack/scatter
  list from :func:`class_row_leaf_items` — the same generic leaf walk
  ``shard.py:world_shardings`` performs — so a newly added property bank
  or record page can never be silently left behind when a row crosses
  shards.

``ROW_LEAF_SPEC`` below is the human-auditable contract: every
``ClassState`` leaf path must match one of its patterns (or appear in
``MIGRATION_EXCLUDED`` with a reason).  The ``migrate-covers-store``
nf-lint rule cross-checks this tuple against the dataclass fields in
core/store.py statically; :func:`class_row_leaf_items` enforces the same
contract at runtime with a tree_leaves count assertion.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import struct as _struct
import zlib
from typing import Any, Dict, List, Tuple

import numpy as np

from ..core.store import ClassState, RecordState, TimerState

# -- framed session blob (failover hand-off) -------------------------------

MAGIC = b"NFRB"
VERSION = 1
_HEADER = _struct.Struct("<4sBII")  # magic, version, payload_len, crc32
MAX_BLOB = 64 * 1024 * 1024  # fail-closed before allocating on a bad length


class RowBlobError(Exception):
    """Framed row blob failed validation (torn, corrupt, wrong version)."""


def frame_blob(payload: bytes) -> bytes:
    """Wrap a snapshot payload in magic + version + length + CRC32."""
    return _HEADER.pack(MAGIC, VERSION, len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF) + payload


def unframe_blob(blob: bytes, allow_legacy: bool = True) -> bytes:
    """Validate and strip the frame; raise :class:`RowBlobError` fail-closed.

    ``allow_legacy=True`` passes through blobs that don't start with the
    magic unchanged — pre-framing peers (and raw garbage) flow on to the
    snapshot decoder, which rejects them on its own terms.  A blob that
    DOES claim the magic must validate completely: truncation, length
    overrun, CRC mismatch and unknown versions are all errors.
    """
    if not blob.startswith(MAGIC):
        if allow_legacy:
            return blob
        raise RowBlobError("missing row-blob magic")
    if len(blob) < _HEADER.size:
        raise RowBlobError("truncated row-blob header")
    magic, version, length, crc = _HEADER.unpack_from(blob)
    if version != VERSION:
        raise RowBlobError(f"unknown row-blob version {version}")
    if length > MAX_BLOB:
        raise RowBlobError(f"row-blob length {length} exceeds cap")
    payload = blob[_HEADER.size:]
    if len(payload) != length:
        raise RowBlobError(
            f"row-blob torn: header says {length} bytes, got {len(payload)}")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise RowBlobError("row-blob CRC mismatch")
    return payload


# -- generic ClassState row-leaf walk (on-mesh migration) ------------------

# Every ClassState leaf path must match one of these patterns.  The
# migrate-covers-store lint rule checks this tuple against the store
# dataclasses; keep it a plain literal.
ROW_LEAF_SPEC = (
    "i32",
    "f32",
    "vec",
    "alive",
    "timers.next_fire",
    "timers.interval",
    "timers.remain",
    "timers.active",
    "records.*.i32",
    "records.*.f32",
    "records.*.vec",
    "records.*.used",
)

# Leaves waived from migration, with a reason each.  Verlet/binning
# caches live in WorldState.aux (not ClassState) precisely so they are
# dropped-and-rebuilt on arrival instead of migrated, so this is empty.
MIGRATION_EXCLUDED: Tuple[str, ...] = ()


def _covered(path: str) -> bool:
    return any(fnmatch.fnmatch(path, pat)
               for pat in ROW_LEAF_SPEC + MIGRATION_EXCLUDED)


def _walk_fields(obj: Any, prefix: str, out: List[Tuple[str, Any]]) -> None:
    for f in dataclasses.fields(type(obj)):
        val = getattr(obj, f.name)
        path = prefix + f.name
        if isinstance(val, (TimerState, RecordState)):
            _walk_fields(val, path + ".", out)
        elif isinstance(val, dict):
            for key in sorted(val):
                _walk_fields(val[key], f"{path}.{key}.", out)
        else:
            out.append((path, val))


def class_row_leaf_items(cs: ClassState) -> List[Tuple[str, Any]]:
    """Ordered ``(path, array)`` pairs for every per-row leaf of ``cs``.

    Guarantees — each violation raises rather than silently dropping
    entity data during migration:

    * the walk sees exactly as many leaves as ``jax.tree.leaves(cs)``
      (a new bank added to the store cannot be missed),
    * every path is covered by ``ROW_LEAF_SPEC``/``MIGRATION_EXCLUDED``,
    * every leaf's leading axis is the class capacity (row-packable).
    """
    import jax

    items: List[Tuple[str, Any]] = []
    _walk_fields(cs, "", items)
    n_tree = len(jax.tree_util.tree_leaves(cs))
    if len(items) != n_tree:
        raise RowBlobError(
            f"row-leaf walk found {len(items)} leaves but the ClassState "
            f"pytree has {n_tree} — a store bank is invisible to migration")
    cap = cs.capacity
    for path, arr in items:
        if not _covered(path):
            raise RowBlobError(
                f"ClassState leaf {path!r} not covered by ROW_LEAF_SPEC — "
                f"add it to the spec (or MIGRATION_EXCLUDED with a reason)")
        if arr.ndim < 1 or arr.shape[0] != cap:
            raise RowBlobError(
                f"ClassState leaf {path!r} shape {arr.shape} has no "
                f"capacity-leading axis; cannot pack rows")
    return items


def rebuild_class_state(cs: ClassState, leaves: List[Any]) -> ClassState:
    """Inverse of :func:`class_row_leaf_items`: reassemble a ClassState
    from replacement leaves in the same walk order."""
    it = iter(leaves)

    def rebuild(obj: Any) -> Any:
        kw = {}
        for f in dataclasses.fields(type(obj)):
            val = getattr(obj, f.name)
            if isinstance(val, (TimerState, RecordState)):
                kw[f.name] = rebuild(val)
            elif isinstance(val, dict):
                kw[f.name] = {k: rebuild(val[k]) for k in sorted(val)}
            else:
                kw[f.name] = next(it)
        return obj.replace(**kw)

    out = rebuild(cs)
    try:
        next(it)
    except StopIteration:
        return out
    raise RowBlobError("rebuild_class_state: more leaves than store fields")


def row_nbytes(cs: ClassState) -> int:
    """Bytes one migrating row carries across the mesh (all banks,
    records, timers, alive bit) — the analytic collective-bytes unit
    CostBook/bench attribute to the migration phase."""
    total = 0
    for _path, arr in class_row_leaf_items(cs):
        per_row = int(np.prod(arr.shape[1:], dtype=np.int64)) if arr.ndim > 1 else 1
        total += per_row * arr.dtype.itemsize
    return total
