"""Client SDK: the Unity3D/Cocos-equivalent connection + mirror layer."""

from .sdk import GameClient, MirrorObject  # noqa: F401
