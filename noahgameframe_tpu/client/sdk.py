"""Python client SDK mirroring the reference Unity3D/Cocos clients.

Reference: `NFClient/Unity3D` — the C# SDK drives the login → select-world
→ connect-key → select-server → role → enter-game pipeline and keeps a
local mirror of every synced object by decoding the property/record sync
messages (SURVEY §2.10 L12).  This is the same state machine in Python:
pump-driven (call ``execute()`` from your loop), every received payload is
a MsgBase envelope (the proxy transponds envelopes verbatim).

Used by the integration tests as the "player" end of the five-role
cluster, and usable as a bot/load-test client against a real deployment.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from ..telemetry.pipeline import TraceError, decode_trace, encode_trace
from ..net.defines import EventCode, MsgID
from ..net.transport import EV_CONNECTED, EV_DISCONNECTED, EV_MSG, PyNetClient
from ..net.wire import (
    AckConnectWorldResult,
    AckEventResult,
    AckPlayerEntryList,
    AckPlayerLeaveList,
    AckRoleLiteInfoList,
    AckServerList,
    Ident,
    Message,
    MsgBase,
    ObjectPropertyFloat,
    ObjectPropertyInt,
    ObjectPropertyList,
    ObjectPropertyObject,
    ObjectPropertyString,
    ObjectPropertyVector2,
    ObjectPropertyVector3,
    ObjectRecordAddRow,
    ObjectRecordFloat,
    ObjectRecordInt,
    ObjectRecordList,
    ObjectRecordObject,
    ObjectRecordRemove,
    ObjectRecordString,
    ObjectRecordSwap,
    ObjectRecordVector3,
    Position,
    ReqAcceptTask,
    ReqAccountLogin,
    ReqAckCreateGuild,
    ReqAckCreateTeam,
    ReqAckJoinGuild,
    ReqAckJoinTeam,
    ReqAckLeaveGuild,
    ReqAckLeaveTeam,
    ReqAckOprTeamMember,
    ReqAckPlayerChat,
    ReqAckPlayerMove,
    ReqAckUseItem,
    ReqAckUseSkill,
    ReqCompeleteTask,
    ReqConnectWorld,
    ReqCreateRole,
    ReqEnterGameServer,
    ReqRoleList,
    ReqSearchGuild,
    ReqSelectServer,
    ReqWearEquip,
    AckSearchGuild,
    ItemStruct,
    RoleLiteInfo,
    TakeOffEquip,
    ident_key as _key,
    unwrap,
    wrap,
)

_IdentKey = Tuple[int, int]


@dataclasses.dataclass
class MirrorObject:
    """Client-side replica of one synced entity."""

    ident: Ident
    class_id: str = ""
    config_id: str = ""
    scene_id: int = 0
    position: Tuple[float, float, float] = (0.0, 0.0, 0.0)
    properties: Dict[str, object] = dataclasses.field(default_factory=dict)
    records: Dict[str, Dict[Tuple[int, int], object]] = dataclasses.field(
        default_factory=dict
    )


class GameClient:
    """One player's connection state machine + world mirror."""

    def __init__(self, account: str, password: str = "") -> None:
        self.account = account
        self.password = password
        self._conn: Optional[PyNetClient] = None
        self.connected = False
        # handshake state
        self.logged_in = False
        self.worlds: List = []
        self.world_grant: Optional[AckConnectWorldResult] = None
        self.key_verified = False
        self.server_selected = False
        self.roles: List[RoleLiteInfo] = []
        self.player_ident: Optional[Ident] = None  # proxy-assigned client id
        self.player_guid: Optional[Ident] = None  # game-side avatar guid
        self.entered = False
        self.last_enter_code: Optional[int] = None  # refusal visibility
        # the world mirror
        self.objects: Dict[_IdentKey, MirrorObject] = {}
        self.chat_log: List[Tuple[str, str]] = []
        self.moves: List[ReqAckPlayerMove] = []
        self.skills: List[ReqAckUseSkill] = []
        self.item_acks: list = []
        self.team_acks: list = []
        self.guild_acks: list = []
        self.guild_search: list = []
        self.slg_acks: list = []
        self.pvp_matches: list = []   # AckPVPApplyMatch (room assignments)
        self.pvp_ectypes: list = []   # AckCreatePVPEctype (instance grants)
        # frame observatory: received trace sidecars (bounded), acked back
        self.traces: List[dict] = []
        # session failover (ISSUE 10): proxy control notices — REHOMING
        # while a crashed binding re-homes, BUSY with a retry hint when
        # no survivor has capacity, DROPPED when parked frames were lost
        self.switch_notices: list = []
        self._handlers: Dict[int, Callable[[MsgBase], None]] = {}
        self._install()

    # ------------------------------------------------------------- wiring
    def _install(self) -> None:
        h = self._handlers
        h[int(MsgID.ACK_LOGIN)] = self._on_login
        h[int(MsgID.ACK_WORLD_LIST)] = self._on_world_list
        h[int(MsgID.ACK_CONNECT_WORLD)] = self._on_connect_world
        h[int(MsgID.ACK_CONNECT_KEY)] = self._on_connect_key
        h[int(MsgID.ACK_SELECT_SERVER)] = self._on_select_server
        h[int(MsgID.ACK_ROLE_LIST)] = self._on_role_list
        h[int(MsgID.ACK_ENTER_GAME)] = self._on_enter_game
        h[int(MsgID.ACK_OBJECT_ENTRY)] = self._on_object_entry
        h[int(MsgID.ACK_OBJECT_LEAVE)] = self._on_object_leave
        h[int(MsgID.ACK_OBJECT_PROPERTY_ENTRY)] = self._on_property_list
        h[int(MsgID.ACK_OBJECT_RECORD_ENTRY)] = self._on_record_list
        h[int(MsgID.ACK_PROPERTY_INT)] = self._on_property_int
        h[int(MsgID.ACK_PROPERTY_FLOAT)] = self._on_property_float
        h[int(MsgID.ACK_PROPERTY_STRING)] = self._on_property_string
        h[int(MsgID.ACK_PROPERTY_OBJECT)] = self._on_property_object
        h[int(MsgID.ACK_PROPERTY_VECTOR2)] = self._on_property_vector2
        h[int(MsgID.ACK_PROPERTY_VECTOR3)] = self._on_property_vector3
        h[int(MsgID.ACK_ADD_ROW)] = self._on_record_add_row
        h[int(MsgID.ACK_REMOVE_ROW)] = self._on_record_remove
        h[int(MsgID.ACK_SWAP_ROW)] = self._on_record_swap
        h[int(MsgID.ACK_RECORD_INT)] = self._on_record_int
        h[int(MsgID.ACK_RECORD_FLOAT)] = self._on_record_float
        h[int(MsgID.ACK_RECORD_STRING)] = self._on_record_string
        h[int(MsgID.ACK_RECORD_OBJECT)] = self._on_record_object
        h[int(MsgID.ACK_RECORD_VECTOR3)] = self._on_record_vector3
        h[int(MsgID.ACK_BATCH_PROPERTY)] = self._on_batch_property
        h[int(MsgID.ACK_INTEREST_POS)] = self._on_interest_pos
        h[int(MsgID.ACK_MOVE)] = self._on_move
        h[int(MsgID.ACK_CHAT)] = self._on_chat
        h[int(MsgID.ACK_SKILL_OBJECTX)] = self._on_skill
        h[int(MsgID.FRAME_TRACE)] = self._on_frame_trace
        # middleware acks: stored raw-decoded for callers to inspect
        def keep(store: list, cls):
            def on(base: MsgBase) -> None:
                store.append(cls.decode(base.msg_data))
            return on

        h[int(MsgID.ACK_ITEM_OBJECT)] = keep(self.item_acks, ReqAckUseItem)
        h[int(MsgID.ACK_CREATE_TEAM)] = keep(self.team_acks, ReqAckCreateTeam)
        h[int(MsgID.ACK_JOIN_TEAM)] = keep(self.team_acks, ReqAckJoinTeam)
        h[int(MsgID.ACK_LEAVE_TEAM)] = keep(self.team_acks, ReqAckLeaveTeam)
        h[int(MsgID.ACK_OPRMEMBER_TEAM)] = keep(self.team_acks,
                                                ReqAckOprTeamMember)
        h[int(MsgID.ACK_CREATE_GUILD)] = keep(self.guild_acks,
                                              ReqAckCreateGuild)
        h[int(MsgID.ACK_JOIN_GUILD)] = keep(self.guild_acks, ReqAckJoinGuild)
        h[int(MsgID.ACK_LEAVE_GUILD)] = keep(self.guild_acks,
                                             ReqAckLeaveGuild)
        h[int(MsgID.ACK_SEARCH_GUILD)] = keep(self.guild_search,
                                              AckSearchGuild)
        from ..net.wire import AckCreatePVPEctype, AckPVPApplyMatch
        from ..net.wire_families import (
            ReqAckBuyObjectFormShop,
            ReqAckMoveBuildObject,
        )

        h[int(MsgID.ACK_BUY_FORM_SHOP)] = keep(self.slg_acks,
                                               ReqAckBuyObjectFormShop)
        h[int(MsgID.ACK_MOVE_BUILD_OBJECT)] = keep(self.slg_acks,
                                                   ReqAckMoveBuildObject)
        h[int(MsgID.ACK_PVP_APPLY_MATCH)] = keep(self.pvp_matches,
                                                 AckPVPApplyMatch)
        h[int(MsgID.ACK_CREATE_PVP_ECTYPE)] = keep(self.pvp_ectypes,
                                                   AckCreatePVPEctype)
        from ..net.wire import SwitchNotice

        h[int(MsgID.ACK_SWITCH_NOTICE)] = keep(self.switch_notices,
                                               SwitchNotice)

    def connect(self, host: str, port: int) -> None:
        """Dial an endpoint (login first, later the granted proxy)."""
        if self._conn is not None:
            self._conn.close()
        self.connected = False
        self._conn = PyNetClient(host, port)
        self._conn.connect()

    def execute(self) -> None:
        if self._conn is None:
            return
        for ev in self._conn.poll():
            if ev.kind == EV_CONNECTED:
                self.connected = True
            elif ev.kind == EV_DISCONNECTED:
                self.connected = False
            elif ev.kind == EV_MSG:
                base = MsgBase.decode(ev.body)
                fn = self._handlers.get(ev.msg_id)
                if fn is not None:
                    fn(base)

    def _send(self, msg_id: int, msg: Message) -> bool:
        return self._conn is not None and self._conn.send_msg(
            int(msg_id), wrap(msg)
        )

    def _on_frame_trace(self, base: MsgBase) -> None:
        """Frame-observatory sidecar: stamp receipt, keep a bounded local
        log, and echo the header back — the ack rides the normal
        client→proxy→game path so the game measures a true round trip."""
        try:
            ctx = decode_trace(base.msg_data)
        except TraceError:
            return
        ctx.client_recv_ns = _time.perf_counter_ns()
        self.traces.append({
            "tick": ctx.tick,
            "game_id": ctx.game_id,
            "seq": ctx.seq,
            "proxy_relay_ms": (
                (ctx.proxy_out_ns - ctx.proxy_in_ns) / 1e6
                if ctx.proxy_out_ns and ctx.proxy_in_ns else None
            ),
        })
        del self.traces[:-256]
        if self._conn is not None:
            self._conn.send_msg(
                int(MsgID.FRAME_TRACE_ACK),
                MsgBase(msg_data=encode_trace(ctx)).encode(),
            )

    # ------------------------------------------------------------- login flow
    def login(self) -> None:
        self._send(
            MsgID.REQ_LOGIN,
            ReqAccountLogin(
                account=self.account.encode(), password=self.password.encode()
            ),
        )

    def _on_login(self, base: MsgBase) -> None:
        ack = AckEventResult.decode(base.msg_data)
        self.logged_in = int(ack.event_code) == int(EventCode.ACCOUNT_SUCCESS)

    def request_world_list(self) -> None:
        from ..net.wire import ReqServerList
        from ..net.defines import ServerType

        self._send(
            MsgID.REQ_WORLD_LIST, ReqServerList(type=int(ServerType.WORLD))
        )

    def _on_world_list(self, base: MsgBase) -> None:
        self.worlds = list(AckServerList.decode(base.msg_data).info)

    def connect_world(self, world_id: int) -> None:
        self._send(MsgID.REQ_CONNECT_WORLD, ReqConnectWorld(world_id=world_id))

    def _on_connect_world(self, base: MsgBase) -> None:
        self.world_grant = AckConnectWorldResult.decode(base.msg_data)

    # ------------------------------------------------------------- proxy flow
    def connect_proxy(self) -> None:
        """Dial the granted proxy and present the connect key."""
        g = self.world_grant
        if g is None:
            raise RuntimeError("no world grant yet")
        self.connect(g.world_ip.decode(), g.world_port)

    def verify_key(self) -> None:
        g = self.world_grant
        self._send(
            MsgID.REQ_CONNECT_KEY,
            ReqAccountLogin(
                account=self.account.encode(), security_code=g.world_key
            ),
        )

    def _on_connect_key(self, base: MsgBase) -> None:
        ack = AckEventResult.decode(base.msg_data)
        if int(ack.event_code) == int(EventCode.VERIFY_KEY_SUCCESS):
            self.key_verified = True
            self.player_ident = ack.event_object

    def select_server(self, game_id: int) -> None:
        self._send(MsgID.REQ_SELECT_SERVER, ReqSelectServer(world_id=game_id))

    def _on_select_server(self, base: MsgBase) -> None:
        ack = AckEventResult.decode(base.msg_data)
        self.server_selected = int(ack.event_code) == int(
            EventCode.SELECTSERVER_SUCCESS
        )

    # ------------------------------------------------------------- role flow
    def request_role_list(self, game_id: int = 0) -> None:
        self._send(
            MsgID.REQ_ROLE_LIST,
            ReqRoleList(game_id=game_id, account=self.account.encode()),
        )

    def create_role(self, name: str, career: int = 0, game_id: int = 0) -> None:
        self._send(
            MsgID.REQ_CREATE_ROLE,
            ReqCreateRole(
                account=self.account.encode(),
                noob_name=name.encode(),
                career=career,
                game_id=game_id,
            ),
        )

    def _on_role_list(self, base: MsgBase) -> None:
        self.roles = list(AckRoleLiteInfoList.decode(base.msg_data).char_data)

    def enter_game(self, name: str, game_id: int = 0) -> None:
        self._send(
            MsgID.REQ_ENTER_GAME,
            ReqEnterGameServer(
                id=self.player_ident,
                account=self.account.encode(),
                name=name.encode(),
                game_id=game_id,
            ),
        )

    def _on_enter_game(self, base: MsgBase) -> None:
        ack = AckEventResult.decode(base.msg_data)
        self.last_enter_code = int(ack.event_code)
        if int(ack.event_code) == int(EventCode.ENTER_GAME_SUCCESS):
            self.entered = True
            self.player_guid = ack.event_object

    # ------------------------------------------------------------- mirror
    def _obj(self, ident: Optional[Ident]) -> MirrorObject:
        k = _key(ident)
        if k not in self.objects:
            self.objects[k] = MirrorObject(ident=ident or Ident())
        return self.objects[k]

    def _on_object_entry(self, base: MsgBase) -> None:
        for e in AckPlayerEntryList.decode(base.msg_data).object_list:
            o = self._obj(e.object_guid)
            o.class_id = e.class_id.decode("utf-8", "replace")
            o.config_id = e.config_id.decode("utf-8", "replace")
            o.scene_id = e.scene_id
            o.position = (e.x, e.y, e.z)

    def _on_object_leave(self, base: MsgBase) -> None:
        for ident in AckPlayerLeaveList.decode(base.msg_data).object_list:
            self.objects.pop(_key(ident), None)

    def _on_property_list(self, base: MsgBase) -> None:
        pl = ObjectPropertyList.decode(base.msg_data)
        o = self._obj(pl.player_id)
        for p in pl.property_int_list:
            o.properties[p.property_name.decode()] = int(p.data)
        for p in pl.property_float_list:
            o.properties[p.property_name.decode()] = float(p.data)
        for p in pl.property_string_list:
            o.properties[p.property_name.decode()] = p.data.decode("utf-8", "replace")
        for p in pl.property_vector3_list:
            v = p.data
            o.properties[p.property_name.decode()] = (
                (v.x, v.y, v.z) if v is not None else (0.0, 0.0, 0.0)
            )

    def _on_property_int(self, base: MsgBase) -> None:
        pl = ObjectPropertyInt.decode(base.msg_data)
        o = self._obj(pl.player_id)
        for p in pl.property_list:
            o.properties[p.property_name.decode()] = int(p.data)

    def _on_property_float(self, base: MsgBase) -> None:
        pl = ObjectPropertyFloat.decode(base.msg_data)
        o = self._obj(pl.player_id)
        for p in pl.property_list:
            o.properties[p.property_name.decode()] = float(p.data)

    def _on_property_string(self, base: MsgBase) -> None:
        pl = ObjectPropertyString.decode(base.msg_data)
        o = self._obj(pl.player_id)
        for p in pl.property_list:
            o.properties[p.property_name.decode()] = p.data.decode(
                "utf-8", "replace"
            )

    def _on_property_object(self, base: MsgBase) -> None:
        pl = ObjectPropertyObject.decode(base.msg_data)
        o = self._obj(pl.player_id)
        for p in pl.property_list:
            o.properties[p.property_name.decode()] = self._ident_tuple(p.data)

    def _on_property_vector2(self, base: MsgBase) -> None:
        pl = ObjectPropertyVector2.decode(base.msg_data)
        o = self._obj(pl.player_id)
        for p in pl.property_list:
            v = p.data
            o.properties[p.property_name.decode()] = (
                (v.x, v.y) if v is not None else (0.0, 0.0)
            )

    def _on_property_vector3(self, base: MsgBase) -> None:
        pl = ObjectPropertyVector3.decode(base.msg_data)
        o = self._obj(pl.player_id)
        for p in pl.property_list:
            v = p.data
            o.properties[p.property_name.decode()] = (
                (v.x, v.y, v.z) if v is not None else (0.0, 0.0, 0.0)
            )

    @staticmethod
    def _ident_tuple(i: Optional[Ident]) -> Tuple[int, int]:
        return (i.svrid, i.index) if i is not None else (0, 0)

    def _absorb_row_struct(self, cells: Dict, rowmsg) -> None:
        """Fold one RecordAddRowStruct's cells (every column type) into a
        mirror record."""
        for c in rowmsg.record_int_list:
            cells[(c.row, c.col)] = int(c.data)
        for c in rowmsg.record_float_list:
            cells[(c.row, c.col)] = float(c.data)
        for c in rowmsg.record_string_list:
            cells[(c.row, c.col)] = c.data.decode("utf-8", "replace")
        for c in rowmsg.record_object_list:
            cells[(c.row, c.col)] = self._ident_tuple(c.data)
        for c in rowmsg.record_vector2_list:
            v = c.data
            cells[(c.row, c.col)] = (v.x, v.y) if v is not None else (0.0, 0.0)
        for c in rowmsg.record_vector3_list:
            v = c.data
            cells[(c.row, c.col)] = (
                (v.x, v.y, v.z) if v is not None else (0.0, 0.0, 0.0)
            )

    def _on_record_list(self, base: MsgBase) -> None:
        rl = ObjectRecordList.decode(base.msg_data)
        o = self._obj(rl.player_id)
        for rec in rl.record_list:
            cells = o.records.setdefault(rec.record_name.decode(), {})
            for rowmsg in rec.row_struct:
                self._absorb_row_struct(cells, rowmsg)

    # ------------------------------------------------- per-change record sync
    def _rec_cells(self, base_pid: Optional[Ident], record_name: bytes) -> Dict:
        o = self._obj(base_pid)
        return o.records.setdefault(record_name.decode(), {})

    def _on_record_add_row(self, base: MsgBase) -> None:
        msg = ObjectRecordAddRow.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.record_name)
        for rowmsg in msg.row_data:
            self._absorb_row_struct(cells, rowmsg)

    def _on_record_remove(self, base: MsgBase) -> None:
        msg = ObjectRecordRemove.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.record_name)
        gone = set(msg.remove_row)
        for key in [k for k in cells if k[0] in gone]:
            del cells[key]

    def _on_record_swap(self, base: MsgBase) -> None:
        msg = ObjectRecordSwap.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.origin_record_name)
        a, b = msg.row_origin, msg.row_target
        moved = {}
        for (r, c) in list(cells):
            if r == a:
                moved[(b, c)] = cells.pop((r, c))
            elif r == b:
                moved[(a, c)] = cells.pop((r, c))
        cells.update(moved)

    def _on_record_int(self, base: MsgBase) -> None:
        msg = ObjectRecordInt.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.record_name)
        for c in msg.property_list:
            cells[(c.row, c.col)] = int(c.data)

    def _on_record_float(self, base: MsgBase) -> None:
        msg = ObjectRecordFloat.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.record_name)
        for c in msg.property_list:
            cells[(c.row, c.col)] = float(c.data)

    def _on_record_string(self, base: MsgBase) -> None:
        msg = ObjectRecordString.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.record_name)
        for c in msg.property_list:
            cells[(c.row, c.col)] = c.data.decode("utf-8", "replace")

    def _on_record_object(self, base: MsgBase) -> None:
        msg = ObjectRecordObject.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.record_name)
        for c in msg.property_list:
            cells[(c.row, c.col)] = self._ident_tuple(c.data)

    def _on_record_vector3(self, base: MsgBase) -> None:
        msg = ObjectRecordVector3.decode(base.msg_data)
        cells = self._rec_cells(msg.player_id, msg.record_name)
        for c in msg.property_list:
            v = c.data
            cells[(c.row, c.col)] = (
                (v.x, v.y, v.z) if v is not None else (0.0, 0.0, 0.0)
            )

    def _on_batch_property(self, base: MsgBase) -> None:
        """Columnar batch sync (TPU-native extension): unpack the arrays
        and fold each entity's value into the mirror."""
        import numpy as np

        from ..net.wire import BatchPropertySync

        msg = BatchPropertySync.decode(base.msg_data)
        heads = np.frombuffer(msg.svrid, np.int64)
        datas = np.frombuffer(msg.index, np.int64)
        name = msg.property_name.decode()
        t = msg.ptype
        if t == 5 or t == 6:  # VECTOR2 / VECTOR3 ride as float32[n*3]
            vals = np.frombuffer(msg.data, np.float32).reshape(-1, 3)
            vals = [
                (float(v[0]), float(v[1])) if t == 5
                else (float(v[0]), float(v[1]), float(v[2]))
                for v in vals
            ]
        elif t == 2:  # FLOAT
            vals = [float(v) for v in np.frombuffer(msg.data, np.float32)]
        else:  # INT
            vals = [int(v) for v in np.frombuffer(msg.data, np.int32)]
        for h_, d_, v in zip(heads.tolist(), datas.tolist(), vals):
            o = self._obj(Ident(svrid=h_, index=d_))
            o.properties[name] = v
            if name == "Position":
                o.position = v if len(v) == 3 else (*v, 0.0)

    def _on_interest_pos(self, base: MsgBase) -> None:
        """Per-session interest stream: u16-quantized positions of the
        entities near this client's avatar; scale rides the message."""
        import numpy as np

        from ..net.wire import InterestPosSync

        msg = InterestPosSync.decode(base.msg_data)
        heads = np.frombuffer(msg.svrid, np.int64)
        datas = np.frombuffer(msg.index, np.int64)
        qpos = np.frombuffer(msg.qpos, np.uint16).reshape(-1, 3)
        s = float(msg.scale)
        for h_, d_, qp in zip(heads.tolist(), datas.tolist(), qpos.tolist()):
            o = self._obj(Ident(svrid=h_, index=d_))
            pos = (qp[0] * s, qp[1] * s, qp[2] * s)
            o.properties["Position"] = pos
            o.position = pos
        # the stream is a delta: entities that left this client's view
        # arrive in the gone list and are despawned from the mirror
        for h_, d_ in zip(
            np.frombuffer(msg.gone_svrid, np.int64).tolist(),
            np.frombuffer(msg.gone_index, np.int64).tolist(),
        ):
            self.objects.pop(_key(Ident(svrid=h_, index=d_)), None)

    # ------------------------------------------------------------- gameplay
    def move_to(self, x: float, y: float, z: float = 0.0) -> None:
        self._send(
            MsgID.REQ_MOVE,
            ReqAckPlayerMove(
                mover=self.player_guid,
                target_pos=[Position(x=x, y=y, z=z)],
            ),
        )

    def _on_move(self, base: MsgBase) -> None:
        self.moves.append(ReqAckPlayerMove.decode(base.msg_data))

    def use_item(self, config_id: str, target_row: int | None = None) -> None:
        """EGMI_REQ_ITEM_OBJECT — family targets (hero/equip row) ride
        targetid.index with svrid == 1 (the game role's ROW_TARGET_SVRID
        tag: row 0 is a valid record row, so a zeroed ident must keep
        meaning "no target")."""
        self._send(MsgID.REQ_ITEM_OBJECT, ReqAckUseItem(
            item=ItemStruct(item_id=config_id.encode(), item_count=1),
            targetid=(Ident(svrid=1, index=target_row)
                      if target_row is not None else None),
        ))

    def wear_equip(self, row: int) -> None:
        self._send(MsgID.WEAR_EQUIP,
                   ReqWearEquip(equipid=Ident(svrid=0, index=row)))

    def take_off_equip(self, row: int) -> None:
        self._send(MsgID.TAKEOFF_EQUIP,
                   TakeOffEquip(equipid=Ident(svrid=0, index=row)))

    def accept_task(self, task_id: str) -> None:
        self._send(MsgID.REQ_ACCEPT_TASK,
                   ReqAcceptTask(task_id=task_id.encode()))

    def complete_task(self, task_id: str) -> None:
        self._send(MsgID.REQ_COMPLETE_TASK,
                   ReqCompeleteTask(task_id=task_id.encode()))

    def create_team(self) -> None:
        self._send(MsgID.REQ_CREATE_TEAM, ReqAckCreateTeam())

    def join_team(self, team_id: "Ident") -> None:
        self._send(MsgID.REQ_JOIN_TEAM, ReqAckJoinTeam(team_id=team_id))

    def leave_team(self) -> None:
        self._send(MsgID.REQ_LEAVE_TEAM, ReqAckLeaveTeam())

    def opr_team_member(self, team_id: "Ident", member: "Ident",
                        op_type: int) -> None:
        """EGMI_REQ_OPRMEMBER_TEAM: captain member ops (KICK etc.)."""
        self._send(MsgID.REQ_OPRMEMBER_TEAM, ReqAckOprTeamMember(
            team_id=team_id, member_id=member, type=int(op_type),
        ))

    def create_guild(self, name: str) -> None:
        self._send(MsgID.REQ_CREATE_GUILD,
                   ReqAckCreateGuild(guild_name=name.encode()))

    def join_guild(self, name: str) -> None:
        self._send(MsgID.REQ_JOIN_GUILD,
                   ReqAckJoinGuild(guild_name=name.encode()))

    def leave_guild(self) -> None:
        self._send(MsgID.REQ_LEAVE_GUILD, ReqAckLeaveGuild())

    def search_guild(self, name: str = "") -> None:
        self._send(MsgID.REQ_SEARCH_GUILD,
                   ReqSearchGuild(guild_name=name.encode()))

    def chat(self, text: str) -> None:
        self._send(
            MsgID.REQ_CHAT,
            ReqAckPlayerChat(chat_info=text.encode(), chat_type=0),
        )

    def _on_chat(self, base: MsgBase) -> None:
        msg = ReqAckPlayerChat.decode(base.msg_data)
        who = msg.chat_id
        self.chat_log.append(
            (f"{who.svrid}-{who.index}" if who else "?",
             msg.chat_info.decode("utf-8", "replace"))
        )

    def use_skill(self, target: Ident, skill_id: str = "skill_1") -> None:
        from ..net.wire import EffectData

        self._send(
            MsgID.REQ_SKILL_OBJECTX,
            ReqAckUseSkill(
                user=self.player_guid,
                skill_id=skill_id.encode(),
                effect_data=[EffectData(effect_ident=target)],
            ),
        )

    def _on_skill(self, base: MsgBase) -> None:
        self.skills.append(ReqAckUseSkill.decode(base.msg_data))

    # ------------------------------------------------- SLG city building
    # client side of NFCSLGShopModule / NFCSLGBuildingModule's wire
    # surface (EGEC_REQ_BUY_FORM_SHOP .. EGEC_REQ_BUILD_OPERATE)
    def slg_buy(self, shop_id: str, x: float, y: float,
                z: float = 0.0) -> None:
        from ..net.wire_families import ReqAckBuyObjectFormShop

        self._send(MsgID.REQ_BUY_FORM_SHOP, ReqAckBuyObjectFormShop(
            config_id=shop_id.encode(), x=x, y=y, z=z,
        ))

    def slg_move(self, row: int, x: float, y: float, z: float = 0.0) -> None:
        from ..net.wire_families import ReqAckMoveBuildObject

        self._send(MsgID.REQ_MOVE_BUILD_OBJECT, ReqAckMoveBuildObject(
            row=row, x=x, y=y, z=z,
        ))

    def slg_upgrade(self, row: int) -> None:
        from ..net.wire_families import ReqUpBuildLv

        self._send(MsgID.REQ_UP_BUILD_LVL, ReqUpBuildLv(row=row))

    def slg_produce(self, row: int, config_id: str, count: int = 1) -> None:
        from ..net.wire_families import ReqCreateItem

        self._send(MsgID.REQ_CREATE_ITEM, ReqCreateItem(
            row=row, config_id=config_id.encode(), count=count,
        ))

    def slg_operate(self, row: int, functype: int) -> None:
        from ..net.wire_families import ReqBuildOperate

        self._send(MsgID.REQ_BUILD_OPERATE, ReqBuildOperate(
            row=row, functype=int(functype),
        ))

    def slg_collect(self, row: int, resource: str = "Gold") -> None:
        from ..net.wire_families import SLGFuncType

        self.slg_operate(row, int(SLGFuncType[f"COLLECT_{resource.upper()}"]))

    def set_fight_hero(self, hero_row: int, fight_pos: int = 0) -> None:
        """EGEC_REQ_SET_FIGHT_HERO: pick the battle line-up hero by its
        PlayerHero record row (heroes are row-identified)."""
        from ..net.wire import ReqSetFightHero

        self._send(MsgID.REQ_SET_FIGHT_HERO, ReqSetFightHero(
            selfid=self.player_guid,
            heroid=Ident(svrid=0, index=hero_row),
            fight_pos=fight_pos,
        ))

    def switch_server(self, target_game_id: int, scene_id: int = 1,
                      group_id: int = 0) -> None:
        """EGMI_REQSWICHSERVER (OnClientReqSwichServer): ask to be
        re-homed onto another game server; the proxy re-routes after the
        blob lands there."""
        from ..net.wire import ReqSwitchServer

        self._send(MsgID.REQ_SWITCH_SERVER, ReqSwitchServer(
            selfid=self.player_guid, target_serverid=target_game_id,
            scene_id=scene_id, group_id=group_id,
        ))

    # --------------------------------------------------------- GM + PVP
    def gm_command(self, command_id: int, str_value: str = "",
                   int_value: int = 0) -> None:
        """EGMI_REQ_CMD_NORMAL: 0 = set int property, 1 = give item,
        3 = add exp (gated by the avatar's GMLevel server-side)."""
        from ..net.wire import ReqCommand

        self._send(MsgID.REQ_CMD_NORMAL, ReqCommand(
            command_id=int(command_id),
            command_str_value=str_value.encode() or None,
            command_value_int=int_value,
        ))

    def pvp_apply_match(self, mode: int = 0,
                        score: int | None = None) -> None:
        """Queue for PVP matchmaking; the room assignment arrives as
        AckPVPApplyMatch in `pvp_matches` (both fighters get it)."""
        from ..net.wire import ReqPVPApplyMatch

        self._send(MsgID.REQ_PVP_APPLY_MATCH, ReqPVPApplyMatch(
            self_id=self.player_guid, nPVPMode=mode, score=score,
        ))

    def pvp_create_ectype(self, room=None) -> None:
        """Mint the PVP instance for a granted room (defaults to the
        most recent match's room)."""
        from ..net.wire import ReqCreatePVPEctype

        if room is None and self.pvp_matches:
            room = self.pvp_matches[-1].xRoomInfo
        if room is None:
            return
        self._send(MsgID.REQ_CREATE_PVP_ECTYPE, ReqCreatePVPEctype(
            self_id=self.player_guid, xRoomInfo=room,
        ))

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
