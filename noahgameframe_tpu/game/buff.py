"""Buffs: timed stat modifiers, expired and folded entirely on device.

Reference: NFCBuffModule (`NFServer/NFGameLogicPlugin/NFCBuffModule.cpp`)
applies a buff's property deltas per object and reverts them on a timer
callback — O(buffs) host work with per-buff heartbeats.

TPU inversion: active buffs are rows in the `BuffList` record
(ConfigIdx → a frozen [n_buffs, n_stats] config table, ExpireTick).  One
phase per tick computes, for EVERY entity at once:

    active[C, R]  = used & (expire > tick)
    contrib[C, S] = sum_R  buff_table[cfg[C, R]] * active
    RUNTIME_BUFF row of CommPropertyValue <- contrib

and clears expired rows' used flags.  The stat recompute phase (order 60)
then folds the group row into final stats, so the whole buff system —
expiry, stacking, reverts — is two fused gathers with zero host work.
This phase runs at order 55, just before the recompute.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.datatypes import Guid
from ..core.store import WorldState, with_class
from ..kernel.module import Module
from .defines import COMM_PROPERTY_RECORD, PropertyGroup, STAT_NAMES

BUFF_RECORD = "BuffList"


class BuffModule(Module):
    name = "BuffModule"

    def __init__(self, classes: Sequence[str] = ("Player", "NPC"),
                 order: int = 55) -> None:
        super().__init__()
        self.classes = tuple(classes)
        self._defs: Dict[str, int] = {}  # buff id -> config index
        self._durations: List[float] = []
        self._stats: List[List[int]] = []
        self._table: Optional[jnp.ndarray] = None
        self._rec_cols: Dict[str, np.ndarray] = {}
        self.add_phase("buffs", self._buff_phase, order=order)

    # ------------------------------------------------------- definitions
    def define_buff(self, buff_id: str, duration_s: float,
                    stats: Dict[str, int]) -> int:
        """Register (or redefine) a buff kind; returns its config index.
        The table is a traced constant, so any change forces a retrace."""
        idx = self._defs.get(buff_id)
        if idx is None:
            idx = len(self._durations)
            self._defs[buff_id] = idx
            self._durations.append(0.0)
            self._stats.append([0] * len(STAT_NAMES))
        self._durations[idx] = float(duration_s)
        self._stats[idx] = [0] * len(STAT_NAMES)
        for stat, v in stats.items():
            self._stats[idx][STAT_NAMES.index(stat)] = int(v)
        self._rebuild_table()
        if self.kernel is not None:
            self.kernel.invalidate()
        return idx

    def _rebuild_table(self) -> None:
        """Freeze the config table EAGERLY on the host.  Building it
        lazily inside the traced phase would cache a tracer (shard_map
        rejects the leak; plain jit silently re-creates it every trace)."""
        rows = self._stats or [[0] * len(STAT_NAMES)]
        self._table = jnp.asarray(np.asarray(rows, np.int32))

    # ------------------------------------------------- checkpoint/resume
    def checkpoint_state(self) -> dict:
        return {
            "defs": self._defs,
            "durations": self._durations,
            "stats": self._stats,
        }

    def restore_state(self, data: dict) -> None:
        self._defs = {k: int(v) for k, v in data.get("defs", {}).items()}
        self._durations = [float(d) for d in data.get("durations", [])]
        self._stats = [[int(x) for x in row] for row in data.get("stats", [])]
        self._rebuild_table()
        if self.kernel is not None:
            self.kernel.invalidate()

    def after_init(self) -> None:
        self._rebuild_table()
        store = self.kernel.store
        for cname in self.classes:
            if cname not in store.class_index:
                continue
            spec = store.spec(cname)
            if BUFF_RECORD not in spec.records:
                continue
            if COMM_PROPERTY_RECORD not in spec.records:
                continue
            rs = spec.records[COMM_PROPERTY_RECORD]
            self._rec_cols[cname] = np.asarray(
                [rs.cols[n].col for n in STAT_NAMES], np.int32
            )

    # ------------------------------------------------------- host API
    def apply_buff(self, guid: Guid, buff_id: str) -> bool:
        """Add (or refresh) a timed buff on one entity."""
        idx = self._defs.get(buff_id)
        if idx is None:
            return False
        k = self.kernel
        cname, _ = k.store.row_of(guid)
        if BUFF_RECORD not in k.store.spec(cname).records:
            return False
        expire = int(k.state.tick) + max(
            1, int(round(self._durations[idx] / k.schedule.dt))
        )
        rows = k.store.record_find_rows(k.state, guid, BUFF_RECORD,
                                        "ConfigIdx", idx)
        if rows:  # re-apply refreshes the expiry
            k.state = k.store.record_set(k.state, guid, BUFF_RECORD,
                                         rows[0], "ExpireTick", expire)
            return True
        try:
            k.state, _ = k.store.record_add_row(
                k.state, guid, BUFF_RECORD,
                {"ConfigIdx": idx, "ExpireTick": expire},
            )
        except RuntimeError:
            return False
        return True

    def active_buffs(self, guid: Guid) -> List[str]:
        k = self.kernel
        by_idx = {v: b for b, v in self._defs.items()}
        out = []
        cname, row = k.store.row_of(guid)
        if BUFF_RECORD not in k.store.spec(cname).records:
            return out
        rec = k.state.classes[cname].records[BUFF_RECORD]
        rs = k.store.spec(cname).records[BUFF_RECORD]
        used = np.asarray(rec.used[row])
        cfg = np.asarray(rec.i32[row, :, rs.cols["ConfigIdx"].col])
        exp = np.asarray(rec.i32[row, :, rs.cols["ExpireTick"].col])
        tick = int(k.state.tick)
        for r in np.flatnonzero(used & (exp > tick)):
            name = by_idx.get(int(cfg[r]))
            if name:
                out.append(name)
        return out

    # ------------------------------------------------------- device phase
    def _buff_phase(self, state: WorldState, ctx) -> WorldState:
        table = self._table
        if table is None:  # phase traced before after_init (bare kernel)
            return state
        for cname, rec_cols in self._rec_cols.items():
            cs = state.classes[cname]
            buf = cs.records[BUFF_RECORD]
            rs = ctx.store.spec(cname).records[BUFF_RECORD]
            cfg = buf.i32[:, :, rs.cols["ConfigIdx"].col]  # [C, R]
            exp = buf.i32[:, :, rs.cols["ExpireTick"].col]
            active = buf.used & (exp > ctx.tick)
            # gather each row's stat vector, mask, sum over the buff axis
            contrib = jnp.sum(
                table[jnp.clip(cfg, 0, table.shape[0] - 1)]
                * active[:, :, None].astype(jnp.int32),
                axis=1,
                dtype=jnp.int32,
            )  # [C, S]
            stats_rec = cs.records[COMM_PROPERTY_RECORD]
            i32 = stats_rec.i32.at[
                :, int(PropertyGroup.RUNTIME_BUFF), jnp.asarray(rec_cols)
            ].set(contrib)
            records = {
                **cs.records,
                COMM_PROPERTY_RECORD: stats_rec.replace(i32=i32),
                BUFF_RECORD: buf.replace(used=active),  # expiry frees rows
            }
            state = with_class(state, cname, cs.replace(records=records))
        return state
