"""Items: bag CRUD, use/consume dispatch, equipment stat contribution.

Reference modules (all in `NFServer/NFGameLogicPlugin/`):
- NFCPackModule — BagItemList (stackables keyed by ConfigID) and
  BagEquipList (unique rows with their own GUID) CRUD;
- NFCItemModule — `OnUseItem`: looks up the item element's ItemType and
  dispatches to the registered consume-process module for that family
  (`NFCItemModule.cpp:320-370`, ConsumeLegal → ConsumeProcess);
- NFCPotionItemConsumeProcessModule etc. — family-specific effects;
- NFCEquipModule / NFCEquipPropertyModule — wearing an equip folds its
  element-config stats into the NPG_EQUIP group, the stat recompute sums
  groups into final stats.

Item definitions are elements (per-instance config) with `ItemType`,
`ItemSubType`, `AwardValue` and optional stat columns — the same shape
the reference's Item.xlsx rows take.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.datatypes import Guid
from ..kernel.module import Module
from .defines import STAT_NAMES, ItemSubType, ItemType, PropertyGroup

BAG_ITEMS = "BagItemList"
BAG_EQUIP = "BagEquipList"

# consume processor: (player guid, item config id, target) -> success.
# `target` is family-specific — a hero record row for card/hero awards,
# an equip record row for gems, None for self-targeted consumables —
# mirroring the reference's NFIDataList targetID parameter
# (NFCItemModule.cpp ConsumeProcess).
ConsumeFn = Callable[[Guid, str, object], bool]


class PackModule(Module):
    """Bag CRUD over the BagItemList / BagEquipList records
    (NFCPackModule).  Equip rows are unique (non-stacking) and identified
    by their record row — all equip state lives in the record banks, so
    checkpoints and player blobs restore it with no host-side registry.
    The WearGUID column marks a worn equip (it holds the owner's guid)."""

    name = "PackModule"

    def __init__(self) -> None:
        super().__init__()
        # fired as (owner, equip_row) when an equip row is removed so the
        # equip-stat module can drop its contribution
        self.on_equip_deleted: List = []

    # ----------------------------------------------------- stackables
    def _find_item_row(self, guid: Guid, config_id: str) -> Optional[int]:
        rows = self.kernel.store.record_find_rows(
            self.kernel.state, guid, BAG_ITEMS, "ConfigID", config_id
        )
        return rows[0] if rows else None

    def create_item(self, guid: Guid, config_id: str, count: int = 1) -> bool:
        """Add `count` of a stackable (stacks onto an existing row)."""
        k = self.kernel
        row = self._find_item_row(guid, config_id)
        if row is not None:
            cur = int(k.store.record_get(k.state, guid, BAG_ITEMS, row,
                                         "ItemCount"))
            k.state = k.store.record_set(k.state, guid, BAG_ITEMS, row,
                                         "ItemCount", cur + count)
            return True
        try:
            k.state, _ = k.store.record_add_row(
                k.state, guid, BAG_ITEMS,
                {"ConfigID": config_id, "ItemCount": count},
            )
        except RuntimeError:
            return False  # bag full
        return True

    def item_count(self, guid: Guid, config_id: str) -> int:
        k = self.kernel
        row = self._find_item_row(guid, config_id)
        if row is None:
            return 0
        return int(k.store.record_get(k.state, guid, BAG_ITEMS, row,
                                      "ItemCount"))

    def enough_item(self, guid: Guid, config_id: str, count: int = 1) -> bool:
        return self.item_count(guid, config_id) >= count

    def delete_item(self, guid: Guid, config_id: str, count: int = 1) -> bool:
        """Consume `count`; removes the row when it hits zero."""
        k = self.kernel
        row = self._find_item_row(guid, config_id)
        if row is None:
            return False
        cur = int(k.store.record_get(k.state, guid, BAG_ITEMS, row,
                                     "ItemCount"))
        if cur < count:
            return False
        if cur == count:
            k.state = k.store.record_remove_row(k.state, guid, BAG_ITEMS, row)
        else:
            k.state = k.store.record_set(k.state, guid, BAG_ITEMS, row,
                                         "ItemCount", cur - count)
        return True

    # ----------------------------------------------------- equipment
    def create_equip(self, guid: Guid, config_id: str) -> Optional[int]:
        """Add a unique equip; returns its record row (its identity)."""
        k = self.kernel
        try:
            k.state, row = k.store.record_add_row(
                k.state, guid, BAG_EQUIP, {"ConfigID": config_id}
            )
        except RuntimeError:
            return None
        return row

    def equips(self, guid: Guid) -> Dict[int, str]:
        """row -> config id, straight from the record (restore-safe)."""
        k = self.kernel
        cname, erow = k.store.row_of(guid)
        spec = k.store.spec(cname)
        if BAG_EQUIP not in spec.records:
            return {}
        rec = k.state.classes[cname].records[BAG_EQUIP]
        rs = spec.records[BAG_EQUIP]
        used = np.asarray(rec.used[erow])
        cfg_col = np.asarray(rec.i32[erow, :, rs.cols["ConfigID"].col])
        return {
            int(r): k.store.strings.lookup(int(cfg_col[r]))
            for r in np.flatnonzero(used)
        }

    def delete_equip(self, guid: Guid, row: int) -> bool:
        if row not in self.equips(guid):
            return False
        k = self.kernel
        k.state = k.store.record_remove_row(k.state, guid, BAG_EQUIP, row)
        for fn in self.on_equip_deleted:
            fn(guid, row)
        return True


class ItemModule(Module):
    """Use-item pipeline with per-family consume processors — the full
    NFC*ConsumeProcessModule family (NFCItemModule.cpp dispatch +
    NFCPotionItem/NFCItemToken/NFCItemEquip/NFCItemGem/NFCItemCard/
    NFCHeroItem/NFCRebornItem ConsumeProcessModule.cpp).  Several of the
    reference processors are empty skeletons (gem/equip return 1 with no
    effect, reborn is commented out); here every family has a real,
    tested effect — documented per-processor."""

    name = "ItemModule"

    def __init__(self, pack: PackModule) -> None:
        super().__init__()
        self.pack = pack
        self._processors: Dict[int, ConsumeFn] = {}
        self.max_sockets = 6
        # wired by the world assembly; processors degrade gracefully
        self.heroes = None  # game.hero.HeroModule (card/hero awards)
        self.equip = None  # game.items.EquipModule (gem refresh)
        self.level = None  # game.level.LevelModule (EXP items)

    def after_init(self) -> None:
        self.register_processor(ItemType.ITEM, self._consume_potion)
        self.register_processor(ItemType.TOKEN, self._consume_token)
        self.register_processor(ItemType.EQUIP, self._consume_equip)
        self.register_processor(ItemType.GEM, self._consume_gem)
        self.register_processor(ItemType.CARD, self._consume_card)

    def register_processor(self, item_type: int, fn: ConsumeFn) -> None:
        """Attach a family processor (the GetConsumeModule dispatch)."""
        self._processors[int(item_type)] = fn

    def _item_config(self, config_id: str):
        elems = self.kernel.elements
        return elems.element(config_id) if elems.exists(config_id) else None

    def use_item(self, guid: Guid, config_id: str, target=None) -> bool:
        """ConsumeLegal (owned + processor exists) → ConsumeProcess →
        remove one from the bag (`NFCItemModule::OnClientUseItem`).
        `target` routes to the family processor (hero row, equip row)."""
        e = self._item_config(config_id)
        if e is None:
            return False
        if not self.pack.enough_item(guid, config_id):
            return False
        fn = self._processors.get(int(e.values.get("ItemType", -1)))
        if fn is None:
            return False
        if not fn(guid, config_id, target):
            return False
        return self.pack.delete_item(guid, config_id, 1)

    # ------------------------------------------------ family processors
    def _consume_potion(self, guid: Guid, config_id: str, target) -> bool:
        """ITEM family (NFCPotionItemConsumeProcessModule): HP/MP/SP
        waters restore the matching pool — an HP water used at 0 HP
        revives (NFCRebornItemConsumeProcessModule's intent; its body is
        commented out in the reference).  EXP items award player exp;
        with a hero-row target the award goes to the hero instead
        (NFCHeroItemConsumeProcessModule::AwardItemProperty — the
        EIT_HERO_STONE type it registers under does not exist in the
        shipped EItemType enum, so that module is dead code in the
        reference; its targeted-award behavior lives here)."""
        e = self._item_config(config_id)
        sub = int(e.values.get("ItemSubType", -1))
        amount = int(e.values.get("AwardValue", 0))
        k = self.kernel
        if sub == int(ItemSubType.EXP):
            if target is not None:
                # an explicit hero target must never silently become a
                # player grant — refuse (item stays in the bag) when the
                # hero module is not wired
                if self.heroes is None:
                    return False
                return self.heroes.add_hero_exp(guid, int(target), amount) > 0
            if self.level is not None:
                self.level.add_exp(guid, amount)
                return True
            return False
        target_prop = {
            int(ItemSubType.HP): ("HP", "MAXHP"),
            int(ItemSubType.MP): ("MP", "MAXMP"),
            int(ItemSubType.SP): ("SP", "MAXSP"),
        }.get(sub)
        if target_prop is None:
            return False
        prop_name, max_name = target_prop
        cur = int(k.get_property(guid, prop_name))
        cap = int(k.get_property(guid, max_name))
        k.set_property(guid, prop_name, min(cap, cur + amount) if cap else cur + amount)
        return True

    def _consume_token(self, guid: Guid, config_id: str, target) -> bool:
        """TOKEN family: currency grants (Gold/Money)."""
        e = self._item_config(config_id)
        sub = int(e.values.get("ItemSubType", -1))
        amount = int(e.values.get("AwardValue", 0))
        k = self.kernel
        prop_name = "Gold" if sub == int(ItemSubType.CURRENCY) else "Money"
        k.set_property(guid, prop_name,
                       int(k.get_property(guid, prop_name)) + amount)
        return True

    def _consume_equip(self, guid: Guid, config_id: str, target) -> bool:
        """EQUIP family (NFCItemEquipConsumeProcessModule is an empty
        skeleton; the shop's default branch shows the intent): using an
        equip token materializes the equip as a unique BagEquipList row."""
        return self.pack.create_equip(guid, config_id) is not None

    def _consume_gem(self, guid: Guid, config_id: str, target) -> bool:
        """GEM family (NFCItemGemConsumeProcessModule is an empty
        skeleton): socket the gem into a TARGET equip row — its stat
        columns fold into the owner's stats while that equip is worn
        (EquipModule.refresh reads the sockets).  Sockets live in the
        row's InlayInfo column, so a recycled row or a relog can never
        inherit or lose them."""
        if target is None:
            return False
        equips = self.pack.equips(guid)
        if int(target) not in equips:
            return False
        k = self.kernel
        row = int(target)
        gems = self.gems_of(guid, row)
        if len(gems) >= self.max_sockets:
            return False
        gems.append(config_id)
        k.state = k.store.record_set(k.state, guid, BAG_EQUIP, row,
                                     "InlayInfo", ";".join(gems))
        k.state = k.store.record_set(k.state, guid, BAG_EQUIP, row,
                                     "SlotCount", len(gems))
        if self.equip is not None:
            self.equip.refresh(guid)
        return True

    def gems_of(self, guid: Guid, equip_row: int) -> List[str]:
        """Socketed gem ids, straight from the row's InlayInfo column."""
        k = self.kernel
        raw = str(k.store.record_get(k.state, guid, BAG_EQUIP,
                                     int(equip_row), "InlayInfo"))
        return [g for g in raw.split(";") if g]

    def _consume_card(self, guid: Guid, config_id: str, target) -> bool:
        """CARD family (NFCItemCardConsumeProcessModule): the card IS the
        hero config — add it to the collection (AddHero(self, strItemID));
        a duplicate stacks a star (HeroModule.add_hero)."""
        if self.heroes is None:
            return False
        return self.heroes.add_hero(guid, config_id) is not None



class EquipModule(Module):
    """Wearing: NPG_EQUIP stat-group recompute (NFCEquipModule /
    NFCEquipPropertyModule).  Worn state IS the record: WearGUID holds the
    owner's guid for worn rows, so restores need only a refresh() call."""

    name = "EquipModule"

    def __init__(self, pack: PackModule, properties) -> None:
        super().__init__()
        self.pack = pack
        self.properties = properties  # game.stats.PropertyModule
        self.items = None  # ItemModule; supplies gem sockets per equip
        pack.on_equip_deleted.append(lambda owner, _row: self.refresh(owner))

    def wear(self, guid: Guid, row: int) -> bool:
        if row not in self.pack.equips(guid):
            return False
        k = self.kernel
        k.state = k.store.record_set(k.state, guid, BAG_EQUIP, row,
                                     "WearGUID", guid)
        self.refresh(guid)
        return True

    def take_off(self, guid: Guid, row: int) -> bool:
        if row not in self.worn(guid):
            return False
        k = self.kernel
        from ..core.datatypes import NULL_GUID

        k.state = k.store.record_set(k.state, guid, BAG_EQUIP, row,
                                     "WearGUID", NULL_GUID)
        self.refresh(guid)
        return True

    def worn(self, guid: Guid) -> Dict[int, str]:
        """Worn rows (WearGUID == owner), derived from the record."""
        k = self.kernel
        owned = self.pack.equips(guid)
        out = {}
        for row, config_id in owned.items():
            wearer = k.store.record_get(k.state, guid, BAG_EQUIP, row,
                                        "WearGUID")
            if wearer == guid:
                out[row] = config_id
        return out

    def refresh(self, guid: Guid) -> None:
        """Re-sum worn equips' element-config stat columns — plus their
        socketed gems' — into the EQUIP group row (call after restore
        too); the per-tick recompute folds groups into final stats."""
        elems = self.kernel.elements
        totals = {n: 0 for n in STAT_NAMES}

        def fold(config_id: str) -> None:
            if not elems.exists(config_id):
                return
            vals = elems.element(config_id).values
            for n in STAT_NAMES:
                v = vals.get(n)
                if v:
                    totals[n] += int(v)

        for row, config_id in self.worn(guid).items():
            fold(config_id)
            if self.items is not None:
                for gem_id in self.items.gems_of(guid, row):
                    fold(gem_id)
        for n, v in totals.items():
            self.properties.set_group_value(guid, n, PropertyGroup.EQUIP, v)
