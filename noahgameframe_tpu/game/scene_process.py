"""Scene-process module: normal vs clone scene instances.

Reference parity: NFCSceneProcessModule
(NFServer/NFGameServerPlugin/NFCSceneProcessModule.cpp:74-134,
NFISceneProcessModule.h:15-20).  A scene's TYPE comes from its config
element (the reference reads Scene::CanClone from the element whose id
is the scene id; here the Scene class's SceneType property):

- NORMAL: every enterer shares one world group (created on demand).
- CLONE:  each enter request allocates a PRIVATE group — a per-player
  (or per-team) instance of the scene — and the group is released when
  its owner is destroyed (NFCSceneProcessModule::OnObjectClassEvent,
  COE_DESTROY -> ReleaseGroupScene).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..core.datatypes import Guid
from ..kernel.module import Module
from ..kernel.scene import SceneModule

SCENE_TYPE_NORMAL = 0
SCENE_TYPE_CLONE = 1


class SceneProcessModule(Module):
    name = "SceneProcessModule"

    def __init__(self, scene: SceneModule,
                 player_class: str = "Player") -> None:
        super().__init__()
        self._scene = scene
        self.player_class = player_class
        # clone-group ownership: guid -> (scene_id, group_id)
        self._clone_groups: Dict[Guid, tuple] = {}

    # -- lifecycle -----------------------------------------------------------

    def after_init(self) -> None:
        # release a player's clone instance when the player goes away
        self.kernel.register_class_event(self._on_player_event, self.player_class)

    # -- API (NFISceneProcessModule surface) ---------------------------------

    def scene_type(self, scene_id: int) -> int:
        """GetCloneSceneType: the scene element's SceneType.  ElementStore
        defaults missing elements/properties to 0 == NORMAL."""
        return int(self.kernel.elements.get_int(str(scene_id), "SceneType"))

    def enter(self, guid: Guid, scene_id: int, group_id: int = 0) -> int:
        """Route an enter-scene request by scene type; returns the group
        actually entered.  CLONE scenes ignore the requested group and
        mint a private instance (seeded from the scene's seed specs)."""
        scene = self._scene
        if scene_id not in scene.scenes:
            scene.create_scene(scene_id)
        if self.scene_type(scene_id) == SCENE_TYPE_CLONE:
            group = scene.request_group(scene_id, seed_npcs=True)
            old = self._clone_groups.pop(guid, None)
            scene.enter_scene(guid, scene_id, group)
            # release the previous instance only AFTER the owner moved
            # out of it — releasing a group destroys its members
            if old is not None:
                sc, gr = old
                if sc in scene.scenes and gr in scene.scenes[sc].groups:
                    scene.release_group(sc, gr)
            self._clone_groups[guid] = (scene_id, group)
        else:
            group = group_id if group_id > 0 else 1
            if group not in scene.scenes[scene_id].groups:
                scene.request_group(scene_id, seed_npcs=True, group_id=group)
            scene.enter_scene(guid, scene_id, group)
            # the owner walked out of any clone instance it held
            self._release_owned(guid)
        return group

    # -- internals -----------------------------------------------------------

    def _release_owned(self, guid: Guid) -> None:
        owned = self._clone_groups.pop(guid, None)
        if owned is not None:
            sc, gr = owned
            if sc in self._scene.scenes and gr in self._scene.scenes[sc].groups:
                self._scene.release_group(sc, gr)

    def _on_player_event(self, guid: Guid, class_name: str, ev) -> None:
        from ..kernel.kernel import ObjectEvent

        if ev == ObjectEvent.DESTROY:
            self._release_owned(guid)
