"""Level module: exp accrual and level-ups, host API + batched device phase.

Reference: NFCLevelModule::AddExp loops `while remain >= 0: level++` reading
MAXEXP from the property config each iteration (NFCLevelModule.cpp:38-69),
and the Level property-callback chain then refreshes base stats and refills
HP/MP/SP (NFCPropertyModule::OnObjectLevelEvent).

TPU inversion: exp awarded during a tick accumulates in an `EXP` delta
column; the level phase converts *total accumulated exp* to (level, rem)
via one searchsorted over precomputed cumulative thresholds
(PropertyConfigModule.level_from_total_exp) — no loops, any number of
level-ups per tick.  On level change it rewrites the NPG_JOBLEVEL stat row
from the (job, level) table and refills HP/MP/SP, then emits ON_LEVEL_UP
with the old/new levels.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.datatypes import Guid
from ..core.store import WorldState, with_class
from ..kernel.module import Module
from .defines import GameEvent, PropertyGroup
from .property_config import PropertyConfigModule
from .stats import PropertyModule


class LevelModule(Module):
    name = "LevelModule"

    def __init__(
        self,
        config: PropertyConfigModule,
        properties: Optional[PropertyModule] = None,
        class_name: str = "Player",
        order: int = 50,
        emit_events: bool = True,
    ):
        super().__init__()
        self.config = config
        self.properties = properties
        self.class_name = class_name
        self.emit_events = emit_events
        # device phase BEFORE the stat recompute (order 60) so a level-up's
        # new JOBLEVEL row lands in the same tick's final stats
        self.add_phase("level", self._level_phase, order=order)

    # -- device phase --------------------------------------------------------

    def _level_phase(self, state: WorldState, ctx) -> WorldState:
        cname = self.class_name
        store = ctx.store
        if cname not in store.class_index:
            return state
        spec = store.spec(cname)
        cs = state.classes[cname]
        job = store.column(state, cname, "Job") if spec.has_property("Job") else None
        if job is None or self.config.cum_exp is None:
            return state
        level_col = spec.slot("Level").col
        exp_col = spec.slot("EXP").col
        maxexp_col = spec.slot("MAXEXP").col

        old_level = cs.i32[:, level_col]
        exp_in_level = cs.i32[:, exp_col]
        # total accumulated exp = cum threshold of current level + exp within
        j = jnp.clip(job, 0, self.config.n_jobs - 1)
        cum = self.config.cum_exp[j]  # [C, L+1]
        lvl_idx = jnp.clip(old_level, 0, self.config.max_level)
        base = jnp.take_along_axis(cum, lvl_idx[:, None], axis=1)[:, 0]
        total = base + exp_in_level
        new_level, rem = self.config.level_from_total_exp(job, total)
        # a job with no MAXEXP configured at the current level cannot level
        # (host add_exp guards max_exp > 0 the same way; an all-zero table
        # would otherwise searchsorted everyone straight to max_level)
        cur_maxexp = jnp.take_along_axis(
            self.config.max_exp[j], lvl_idx[:, None], axis=1
        )[:, 0]
        can_level = cs.alive & (cur_maxexp > 0)
        new_level = jnp.where(can_level, jnp.maximum(new_level, old_level), old_level)
        rem = jnp.where(can_level, rem, exp_in_level)

        leveled = new_level != old_level
        i32 = cs.i32.at[:, level_col].set(new_level)
        i32 = i32.at[:, exp_col].set(rem)
        new_maxexp = jnp.take_along_axis(
            self.config.max_exp[j], jnp.clip(new_level, 0, self.config.max_level)[:, None], axis=1
        )[:, 0]
        i32 = i32.at[:, maxexp_col].set(new_maxexp)
        cs = cs.replace(i32=i32)

        # refresh NPG_JOBLEVEL stat row for leveled entities + refill
        # HP/MP/SP from the NEW MAXes (reference FullHPMP/FullSP); the stat
        # recompute phase (order 60) folds the row into MAXHP etc, so we
        # compute the new maxima here from the group sums directly.
        from .defines import COMM_PROPERTY_RECORD, STAT_NAMES  # local to avoid cycle

        if COMM_PROPERTY_RECORD in spec.records:
            rs = spec.records[COMM_PROPERTY_RECORD]
            rec = cs.records[COMM_PROPERTY_RECORD]
            base_stats = self.config.base_stats_for(job, new_level)  # [C, S]
            rec_cols = jnp.asarray([rs.cols[n].col for n in STAT_NAMES])
            job_row = rec.i32[:, int(PropertyGroup.JOBLEVEL), :]
            updated = job_row.at[:, rec_cols].set(base_stats)
            new_rec_i32 = rec.i32.at[:, int(PropertyGroup.JOBLEVEL), :].set(
                jnp.where(leveled[:, None], updated, job_row)
            )
            rec = rec.replace(i32=new_rec_i32)
            totals = jnp.sum(new_rec_i32, axis=1, dtype=jnp.int32)  # [C, S_rec]
            i32 = cs.i32
            for cur, mx in (("HP", "MAXHP"), ("MP", "MAXMP"), ("SP", "MAXSP")):
                if not spec.has_property(cur):
                    continue
                mcol = totals[:, rs.cols[mx].col]
                ccol = spec.slot(cur).col
                i32 = i32.at[:, ccol].set(
                    jnp.where(leveled & (mcol > 0), mcol, i32[:, ccol])
                )
            cs = cs.replace(
                i32=i32, records={**cs.records, COMM_PROPERTY_RECORD: rec}
            )

        if self.emit_events:
            ctx.emit(
                int(GameEvent.ON_LEVEL_UP),
                cname,
                leveled & cs.alive,
                old_level=old_level,
                new_level=new_level,
            )
        return with_class(state, cname, cs)

    # -- host API (reference NFILevelModule) --------------------------------

    def add_exp(self, guid: Guid, exp: int) -> int:
        """Host-side immediate AddExp with full level-up semantics; the
        device phase does the same thing batch-wise at the next tick."""
        k = self.kernel
        job = int(k.get_property(guid, "Job"))
        level = int(k.get_property(guid, "Level"))
        cur = int(k.get_property(guid, "EXP")) + int(exp)
        max_exp = self.config.calculate_base_value(job, level, "MAXEXP")
        leveled = False
        while max_exp > 0 and cur >= max_exp and level < self.config.max_level:
            cur -= max_exp
            level += 1
            leveled = True
            max_exp = self.config.calculate_base_value(job, level, "MAXEXP")
        k.set_property(guid, "EXP", cur)
        if leveled:
            k.set_property(guid, "Level", level)
            k.set_property(guid, "MAXEXP", max_exp)
            if self.properties is not None:
                self.properties.refresh_base_property(guid, self.config)
                self.properties.recompute_now(guid)
                self.properties.full_hp_mp(guid)
                self.properties.full_sp(guid)
        return level
