"""Social & meta systems: team, mail, rank, shop, friends, guild, GM, PVP.

Reference modules: NFCGSTeamModule (team CRUD + member sync), mail with
attachments (NFMidWare/NFMailPlugin + DataAgent mail redis module),
NFCRankModule (score lists), NFCSLGShopModule (buy → bag), Friend/Guild
plugins (NFMidWare skeletons backed by DataAgent redis modules),
NFCGmModule (chat-command cheats gated by GMLevel) and NFCGSPVPMatchModule
(queue pairing).  All of these are control-plane (rare ops, host dicts +
entity properties/records) — exactly where the reference keeps them; the
tick path is untouched.

Where a module touches entity state it goes through the kernel so the
usual flag/diff machinery broadcasts the change (e.g. TeamID/GuildID are
Public OBJECT properties).
"""

from __future__ import annotations

import collections
import dataclasses
import time as _time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from ..core.datatypes import Guid, NULL_GUID
from ..kernel.module import Module

# ============================================================ membership


@dataclasses.dataclass
class GroupInfo:
    group_id: Guid
    leader: Guid
    members: List[Guid] = dataclasses.field(default_factory=list)
    capacity: int = 5
    name: str = ""

    @property
    def team_id(self) -> Guid:  # reference-parity spelling
        return self.group_id

    @property
    def guild_id(self) -> Guid:
        return self.group_id


class _MembershipModule(Module):
    """Shared team/guild mechanics: an entity-backed group whose members
    carry its guid in an OBJECT property; no double-join, capacity cap,
    leadership handoff, dissolve-when-empty, and automatic removal when a
    member entity is destroyed (logout/death cleanup)."""

    entity_class = "Team"
    member_prop = "TeamID"

    def __init__(self, capacity: int) -> None:
        super().__init__()
        self.capacity = capacity
        self.groups: Dict[Guid, GroupInfo] = {}
        # persistence hook: (event, group, member, destroy_cleanup) with
        # event in create/join/leave/disband/dissolve — destroy_cleanup
        # marks a leave caused by entity destruction (logout), which must
        # NOT drop durable membership (persist.social.SocialDataAgent)
        self.on_membership_event = None
        self._destroy_cleanup = False

    def _fire(self, event: str, g: GroupInfo, member=None) -> None:
        if self.on_membership_event is not None:
            self.on_membership_event(event, g, member, self._destroy_cleanup)

    def after_init(self) -> None:
        from ..kernel.kernel import ObjectEvent

        def on_event(guid: Guid, _cname: str, ev) -> None:
            # BEFORE_DESTROY: the member's row is still live, so the
            # membership property write and count updates all succeed
            if ev == ObjectEvent.BEFORE_DESTROY and self.group_of(guid):
                self._destroy_cleanup = True
                try:
                    self.leave(guid)
                finally:
                    self._destroy_cleanup = False

        self.kernel.register_class_event(on_event)

    def _set_member_prop(self, member: Guid, group_id: Guid) -> None:
        store = self.kernel.store
        if member not in store.guid_map:
            return  # member entity already destroyed
        cname, _ = store.row_of(member)
        if store.spec(cname).has_property(self.member_prop):
            self.kernel.set_property(member, self.member_prop, group_id)

    def _create_group(self, leader: Guid, name: str = "") -> Optional[Guid]:
        if self.group_of(leader) is not None:
            return None
        values = {"LeaderID": leader, "MemberCount": 1}
        if name:
            values["Name"] = name
        group_id = self.kernel.create_object(self.entity_class, values)
        self.groups[group_id] = GroupInfo(group_id, leader, [leader],
                                          self.capacity, name)
        self._set_member_prop(leader, group_id)
        self._fire("create", self.groups[group_id], leader)
        return group_id

    def group_of(self, member: Guid) -> Optional[GroupInfo]:
        for g in self.groups.values():
            if member in g.members:
                return g
        return None

    def join(self, group_id: Guid, member: Guid) -> bool:
        g = self.groups.get(group_id)
        if g is None or member in g.members or len(g.members) >= g.capacity:
            return False
        if self.group_of(member) is not None:
            return False
        g.members.append(member)
        self._set_member_prop(member, group_id)
        self.kernel.set_property(group_id, "MemberCount", len(g.members))
        self._fire("join", g, member)
        return True

    def leave(self, member: Guid) -> bool:
        g = self.group_of(member)
        if g is None:
            return False
        g.members.remove(member)
        self._set_member_prop(member, NULL_GUID)
        self._fire("leave", g, member)
        if not g.members:
            self._dissolve(g)
            self._fire("dissolve", g)
            return True
        if g.leader == member:
            g.leader = g.members[0]  # leadership passes down
            self.kernel.set_property(g.group_id, "LeaderID", g.leader)
        self.kernel.set_property(g.group_id, "MemberCount", len(g.members))
        return True

    def disband(self, leader: Guid) -> bool:
        g = self.group_of(leader)
        if g is None or g.leader != leader:
            return False
        for m in list(g.members):
            self._set_member_prop(m, NULL_GUID)
        self._dissolve(g)
        self._fire("disband", g)
        return True

    def _dissolve(self, g: GroupInfo) -> None:
        del self.groups[g.group_id]
        self.kernel.destroy_object(g.group_id)

    # ------------------------------------------------- checkpoint/resume
    def checkpoint_state(self) -> dict:
        return {
            "groups": [
                {
                    "group_id": str(g.group_id),
                    "leader": str(g.leader),
                    "members": [str(m) for m in g.members],
                    "capacity": g.capacity,
                    "name": g.name,
                }
                for g in self.groups.values()
            ]
        }

    def restore_state(self, data: dict) -> None:
        self.groups = {}
        for gd in data.get("groups", []):
            gid = Guid.parse(gd["group_id"])
            self.groups[gid] = GroupInfo(
                gid,
                Guid.parse(gd["leader"]),
                [Guid.parse(m) for m in gd["members"]],
                int(gd["capacity"]),
                gd.get("name", ""),
            )


# ===================================================================== team


TeamInfo = GroupInfo  # reference-parity aliases


class TeamModule(_MembershipModule):
    """Team CRUD (NFCGSTeamModule); members carry the Public TeamID
    property so the sync spine broadcasts membership."""

    name = "TeamModule"
    entity_class = "Team"
    member_prop = "TeamID"

    def __init__(self, capacity: int = 5) -> None:
        super().__init__(capacity)

    @property
    def teams(self) -> Dict[Guid, GroupInfo]:
        return self.groups

    def create_team(self, leader: Guid) -> Optional[Guid]:
        return self._create_group(leader)

    def team_of(self, member: Guid) -> Optional[GroupInfo]:
        return self.group_of(member)


# ===================================================================== mail


@dataclasses.dataclass
class Mail:
    mail_id: int
    sender: str
    title: str
    body: str
    gold: int = 0
    items: Dict[str, int] = dataclasses.field(default_factory=dict)
    sent_at: float = 0.0  # logical time: kernel tick at send
    read: bool = False
    drawn: bool = False


class MailModule(Module):
    """Account-keyed mailboxes with gold/item attachments; drawing pays
    through the wallet and the bag (reference mail flow)."""

    name = "MailModule"

    def __init__(self, pack=None, keep: int = 100) -> None:
        super().__init__()
        self.pack = pack  # items.PackModule for attachment delivery
        self.keep = keep
        self._boxes: Dict[str, List[Mail]] = {}
        self._next_id = 1
        self.on_dirty = None  # fn(account) — persistence write-through

    def _mark(self, account: str) -> None:
        if self.on_dirty is not None:
            self.on_dirty(account)

    def send(self, to_account: str, sender: str, title: str, body: str = "",
             gold: int = 0, items: Optional[Dict[str, int]] = None) -> int:
        # stamp with the kernel tick, not the wall clock: mail state must
        # be a pure function of journaled inputs for record/replay
        k = self.kernel
        sent_at = float(k.tick_count) if k is not None else 0.0
        mail = Mail(self._next_id, sender, title, body, gold,
                    dict(items or {}), sent_at)
        self._next_id += 1
        box = self._boxes.setdefault(to_account, [])
        box.append(mail)
        del box[: max(0, len(box) - self.keep)]
        self._mark(to_account)
        return mail.mail_id

    def mailbox(self, account: str) -> List[Mail]:
        return list(self._boxes.get(account, []))

    def _find(self, account: str, mail_id: int) -> Optional[Mail]:
        for m in self._boxes.get(account, []):
            if m.mail_id == mail_id:
                return m
        return None

    def read(self, account: str, mail_id: int) -> Optional[Mail]:
        m = self._find(account, mail_id)
        if m is not None:
            m.read = True
            self._mark(account)
        return m

    def draw(self, account: str, mail_id: int, player: Guid) -> bool:
        """Claim attachments: items to the bag first (a full bag fails the
        whole draw, leaving the mail claimable later), then gold."""
        m = self._find(account, mail_id)
        if m is None or m.drawn:
            return False
        k = self.kernel
        if m.items:
            if self.pack is None:
                return False
            delivered = []
            for config_id, count in m.items.items():
                if not self.pack.create_item(player, config_id, count):
                    for cid, n in delivered:  # roll back partial delivery
                        self.pack.delete_item(player, cid, n)
                    return False
                delivered.append((config_id, count))
        if m.gold:
            k.set_property(player, "Gold",
                           int(k.get_property(player, "Gold")) + m.gold)
        m.drawn = True
        m.read = True
        self._mark(account)
        return True

    def delete(self, account: str, mail_id: int) -> bool:
        box = self._boxes.get(account, [])
        n = len(box)
        self._boxes[account] = [m for m in box if m.mail_id != mail_id]
        if len(self._boxes[account]) != n:
            self._mark(account)
            return True
        return False

    # ------------------------------------------------- checkpoint/resume
    def checkpoint_state(self) -> dict:
        return {
            "next_id": self._next_id,
            "boxes": {
                acct: [dataclasses.asdict(m) for m in box]
                for acct, box in self._boxes.items()
            },
        }

    def restore_state(self, data: dict) -> None:
        self._next_id = int(data.get("next_id", 1))
        self._boxes = {
            acct: [Mail(**m) for m in box]
            for acct, box in data.get("boxes", {}).items()
        }


# ===================================================================== rank


class RankModule(Module):
    """Named score lists with top-N queries (NFCRankModule).  Scores are
    pushed (e.g. on level-up/fight-power change); storage is a plain dict
    — rank reads are rare relative to the tick."""

    name = "RankModule"

    def __init__(self) -> None:
        super().__init__()
        self._lists: Dict[str, Dict[str, int]] = {}  # list -> key -> score
        self.on_dirty = None  # fn(list_name) — persistence write-through

    def _mark(self, list_name: str) -> None:
        if self.on_dirty is not None:
            self.on_dirty(list_name)

    def update(self, list_name: str, key: str, score: int) -> None:
        self._lists.setdefault(list_name, {})[key] = int(score)
        self._mark(list_name)

    def remove(self, list_name: str, key: str) -> None:
        if self._lists.get(list_name, {}).pop(key, None) is not None:
            self._mark(list_name)

    def score(self, list_name: str, key: str) -> Optional[int]:
        return self._lists.get(list_name, {}).get(key)

    def top(self, list_name: str, n: int = 10) -> List[Tuple[str, int]]:
        entries = self._lists.get(list_name, {})
        return sorted(entries.items(), key=lambda kv: (-kv[1], kv[0]))[:n]

    def rank_of(self, list_name: str, key: str) -> Optional[int]:
        """1-based rank, None if absent."""
        entries = self._lists.get(list_name, {})
        if key not in entries:
            return None
        my = entries[key]
        return 1 + sum(1 for k, v in entries.items()
                       if v > my or (v == my and k < key))

    # ------------------------------------------------- checkpoint/resume
    def checkpoint_state(self) -> dict:
        return {"lists": self._lists}

    def restore_state(self, data: dict) -> None:
        self._lists = {
            ln: {k: int(v) for k, v in entries.items()}
            for ln, entries in data.get("lists", {}).items()
        }


# ===================================================================== shop


class ShopModule(Module):
    """Buy an item element for its BuyPrice in Gold → bag
    (NFCSLGShopModule shape: config-driven catalogue)."""

    name = "ShopModule"

    def __init__(self, pack) -> None:
        super().__init__()
        self.pack = pack

    def price_of(self, config_id: str) -> Optional[int]:
        """None = not purchasable (unknown element or no positive
        BuyPrice) — a missing price must never mean "free"."""
        elems = self.kernel.elements
        if not elems.exists(config_id):
            return None
        price = int(elems.element(config_id).values.get("BuyPrice", 0) or 0)
        return price if price > 0 else None

    def buy(self, player: Guid, config_id: str, count: int = 1) -> bool:
        price = self.price_of(config_id)
        if price is None or count <= 0:
            return False
        k = self.kernel
        total = price * count
        gold = int(k.get_property(player, "Gold"))
        if gold < total:
            return False
        if not self.pack.create_item(player, config_id, count):
            return False
        k.set_property(player, "Gold", gold - total)
        return True

    def sell(self, player: Guid, config_id: str, count: int = 1) -> bool:
        elems = self.kernel.elements
        if not elems.exists(config_id):
            return False
        price = int(elems.element(config_id).values.get("SalePrice", 0) or 0)
        if not self.pack.delete_item(player, config_id, count):
            return False
        k = self.kernel
        k.set_property(player, "Gold",
                       int(k.get_property(player, "Gold")) + price * count)
        return True


# ===================================================================== friends


class FriendModule(Module):
    """Mutual friend lists + block lists, account-keyed (NFMidWare
    NFFriendPlugin backed by the DataAgent friend redis module)."""

    name = "FriendModule"

    def __init__(self, max_friends: int = 50) -> None:
        super().__init__()
        self.max_friends = max_friends
        self._friends: Dict[str, List[str]] = {}
        self._blocked: Dict[str, List[str]] = {}

    def add_friend(self, a: str, b: str) -> bool:
        if a == b or b in self._blocked.get(a, []) or a in self._blocked.get(b, []):
            return False
        fa = self._friends.setdefault(a, [])
        fb = self._friends.setdefault(b, [])
        if b in fa or len(fa) >= self.max_friends or len(fb) >= self.max_friends:
            return False
        fa.append(b)
        fb.append(a)
        return True

    def remove_friend(self, a: str, b: str) -> bool:
        fa = self._friends.get(a, [])
        if b not in fa:
            return False
        fa.remove(b)
        fb = self._friends.get(b, [])
        if a in fb:
            fb.remove(a)
        return True

    def friends(self, account: str) -> List[str]:
        return list(self._friends.get(account, []))

    def block(self, a: str, b: str) -> None:
        self.remove_friend(a, b)
        blocked = self._blocked.setdefault(a, [])
        if b not in blocked:
            blocked.append(b)

    def unblock(self, a: str, b: str) -> None:
        if b in self._blocked.get(a, []):
            self._blocked[a].remove(b)

    def blocked(self, account: str) -> List[str]:
        return list(self._blocked.get(account, []))

    # ------------------------------------------------- checkpoint/resume
    def checkpoint_state(self) -> dict:
        return {"friends": self._friends, "blocked": self._blocked}

    def restore_state(self, data: dict) -> None:
        self._friends = {k: list(v) for k, v in data.get("friends", {}).items()}
        self._blocked = {k: list(v) for k, v in data.get("blocked", {}).items()}


# ===================================================================== guild


GuildInfo = GroupInfo


class GuildModule(_MembershipModule):
    """Guild registry over the shared membership base; guilds are Guild
    entities with unique names; members carry GuildID."""

    name = "GuildModule"
    entity_class = "Guild"
    member_prop = "GuildID"

    def __init__(self, capacity: int = 50) -> None:
        super().__init__(capacity)
        self._by_name: Dict[str, Guid] = {}
        # durable name reservation (persist.social.SocialDataAgent): a
        # guild whose members are all OFFLINE has no live entity, but its
        # name must not be claimable by strangers
        self.name_taken = None  # Optional[Callable[[str], bool]]

    @property
    def guilds(self) -> Dict[Guid, GroupInfo]:
        return self.groups

    def create_guild(self, leader: Guid, name: str) -> Optional[Guid]:
        if not name or name in self._by_name:
            return None
        if self.name_taken is not None and self.name_taken(name):
            return None  # dormant durable guild owns the name
        gid = self._create_group(leader, name=name)
        if gid is not None:
            self._by_name[name] = gid
        return gid

    def guild_of(self, member: Guid) -> Optional[GroupInfo]:
        return self.group_of(member)

    def find_by_name(self, name: str) -> Optional[GroupInfo]:
        gid = self._by_name.get(name)
        return self.groups.get(gid) if gid is not None else None

    def _dissolve(self, g: GroupInfo) -> None:
        self._by_name.pop(g.name, None)
        super()._dissolve(g)

    def restore_state(self, data: dict) -> None:
        super().restore_state(data)
        self._by_name = {g.name: gid for gid, g in self.groups.items() if g.name}


# ===================================================================== GM


class GmModule(Module):
    """Chat-command cheats gated by the GMLevel property (NFCGmModule
    parses "/command arg" chat lines)."""

    name = "GmModule"

    def __init__(self, level_module=None, pack=None, min_gm_level: int = 1):
        super().__init__()
        self.level = level_module
        self.pack = pack
        self.min_gm_level = min_gm_level

    def handle_command(self, player: Guid, text: str) -> bool:
        """Returns True if `text` was a GM command this player may run."""
        if not text.startswith("/"):
            return False
        k = self.kernel
        if int(k.get_property(player, "GMLevel")) < self.min_gm_level:
            return False
        parts = text[1:].split()
        if not parts:
            return False
        cmd, args = parts[0].lower(), parts[1:]
        try:
            return self._run(k, player, cmd, args)
        except (ValueError, IndexError):
            return False  # malformed args are not a crash

    def _run(self, k, player: Guid, cmd: str, args: List[str]) -> bool:
        if cmd == "level" and args:
            k.set_property(player, "Level", int(args[0]))
            return True
        if cmd == "gold" and args:
            k.set_property(player, "Gold",
                           int(k.get_property(player, "Gold")) + int(args[0]))
            return True
        if cmd == "exp" and args and self.level is not None:
            self.level.add_exp(player, int(args[0]))
            return True
        if cmd == "item" and args and self.pack is not None:
            count = int(args[1]) if len(args) > 1 else 1
            return self.pack.create_item(player, args[0], count)
        if cmd == "kill" and args:
            target = Guid.parse(args[0])
            if target in k.store.guid_map:
                k.set_property(target, "HP", 0)
                return True
        return False


# ===================================================================== PVP


@dataclasses.dataclass
class MatchTicket:
    player: Guid
    score: int
    queued_at: float
    mode: int = 0  # players only pair within one PVP mode


class PvpMatchModule(Module):
    """Queue pairing by score window (NFCGSPVPMatchModule): join with a
    rating, `execute()`-style matching pairs the closest tickets whose
    scores are within `window` (widening by wait time)."""

    name = "PvpMatchModule"

    def __init__(self, window: int = 100, widen_per_s: int = 50,
                 keep_matches: int = 256) -> None:
        super().__init__()
        self.window = window
        self.widen_per_s = widen_per_s
        self.queue: List[MatchTicket] = []
        # bounded recent-match history (consumers should act on the
        # match_once() return value, not poll this)
        self.matches: Deque[Tuple[Guid, Guid]] = collections.deque(
            maxlen=keep_matches
        )

    def join_queue(self, player: Guid, score: int,
                   now: Optional[float] = None, mode: int = 0) -> bool:
        if any(t.player == player for t in self.queue):
            return False
        self.queue.append(MatchTicket(
            player, int(score),
            _time.monotonic() if now is None else now, int(mode)))
        return True

    def leave_queue(self, player: Guid) -> bool:
        n = len(self.queue)
        self.queue = [t for t in self.queue if t.player != player]
        return len(self.queue) != n

    def match_once(self, now: Optional[float] = None) -> List[Tuple[Guid, Guid]]:
        """Pair greedily by score; each ticket's acceptable window widens
        with wait time.  Returns the new pairs (also kept in .matches)."""
        return [(a.player, b.player)
                for a, b in self.match_once_tickets(now)]

    def match_once_tickets(
        self, now: Optional[float] = None
    ) -> List[Tuple[MatchTicket, MatchTicket]]:
        """match_once, but returning the full tickets — consumers that
        label the match (room mode = the PAIR's queue mode, not the
        triggering request's) need more than the guids."""
        now = _time.monotonic() if now is None else now
        order = sorted(self.queue, key=lambda t: t.score)
        paired: List[Tuple[MatchTicket, MatchTicket]] = []
        used = set()
        for i, a in enumerate(order):
            if id(a) in used:
                continue
            win_a = self.window + self.widen_per_s * int(now - a.queued_at)
            best = None
            for b in order[i + 1:]:
                if id(b) in used or b.mode != a.mode:
                    continue  # only pair within one PVP mode
                gap = b.score - a.score
                win_b = self.window + self.widen_per_s * int(now - b.queued_at)
                if gap <= min(win_a, win_b):
                    best = b
                    break  # sorted: first same-mode candidate is closest
            if best is not None:
                used.add(id(a))
                used.add(id(best))
                paired.append((a, best))
        if paired:
            matched_players = {t.player for pair in paired for t in pair}
            self.queue = [t for t in self.queue
                          if t.player not in matched_players]
            self.matches.extend(
                (a.player, b.player) for a, b in paired
            )
        return paired
