"""Property-trail debug logging: follow every property change of chosen
objects.

Reference: NFCPropertyTrailModule
(NFServer/NFGameServerPlugin/NFCPropertyTrailModule.cpp) — StartTrail
dumps the object's data and hooks its property/record callbacks so each
subsequent change is logged; EndTrail unhooks.  The reference version is
mostly a stub (empty Execute/EndTrail, Trail* bodies log-only); here the
same surface is implemented completely on top of the kernel's
property-event spine.

Design note: property events in this framework arrive *batched per
(class, property)* with changed row indices (the device diff path), so
the trail keeps a per-class set of tracked rows and filters each batch —
one subscription per property regardless of how many objects are
trailed, and zero cost on the compiled tick (diff extraction is already
flag-driven).  A class-event hook drops dead rows from the tracked set
so a recycled row never trails the unrelated object that inherits it.
"""

from __future__ import annotations

from typing import Dict, Set

import numpy as np

from ..core.datatypes import Guid
from ..kernel.kernel import ObjectEvent
from ..kernel.module import Module


class PropertyTrailModule(Module):
    """StartTrail/EndTrail per-object property change logging."""

    name = "PropertyTrailModule"

    def __init__(self, logger=None):
        super().__init__()
        self._logger = logger  # LogModule-like (info/debug) or None -> print
        # class -> set of tracked rows; class -> whether subs installed
        self._rows: Dict[str, Set[int]] = {}
        self._hooked: Set[str] = set()
        # trail's own guid -> (class, row): rows are recycled on destroy
        # (store free-list) and DESTROY fires after the guid is unmapped,
        # so the store can't answer "which row was that" at cleanup time
        self._tracked: Dict[Guid, tuple] = {}

    def after_init(self) -> None:
        self.kernel.register_class_event(self._on_class_event)

    # -- public surface (reference StartTrail/EndTrail) ----------------------

    def start_trail(self, guid: Guid) -> None:
        """Log the object's current data, then follow every change."""
        class_name, row = self.kernel.store.row_of(guid)
        self._log_object_data(guid, class_name)
        self._rows.setdefault(class_name, set()).add(row)
        self._tracked[guid] = (class_name, row)
        if class_name not in self._hooked:
            self._hooked.add(class_name)
            spec = self.kernel.store.spec(class_name)
            for prop_name in spec.prop_order:
                # unflagged (non-public/upload) properties are normally
                # excluded from device diff extraction — a trail must see
                # ALL changes, so opt every column in (recompiles the
                # tick once per newly-trailed class)
                self.kernel.force_diff_property(class_name, prop_name)
                self.kernel.register_property_event(
                    class_name, prop_name, self._on_prop_batch
                )

    def end_trail(self, guid: Guid) -> None:
        """Idempotent; a destroyed guid is already un-trailed."""
        loc = self._tracked.pop(guid, None)
        if loc is not None:
            self._rows.get(loc[0], set()).discard(loc[1])

    def is_trailing(self, guid: Guid) -> bool:
        return guid in self._tracked

    # -- internals -----------------------------------------------------------

    def _on_class_event(self, guid: Guid, class_name: str, ev) -> None:
        if ev == ObjectEvent.DESTROY:
            self.end_trail(guid)

    def _log(self, msg: str) -> None:
        if self._logger is not None:
            self._logger.info(msg)
        else:  # pragma: no cover - fallback path
            print(msg)

    def _log_object_data(self, guid: Guid, class_name: str) -> None:
        """The LogObjectData dump: every property's current value."""
        spec = self.kernel.store.spec(class_name)
        for prop_name in spec.prop_order:
            val = self.kernel.get_property(guid, prop_name)
            self._log(f"[trail] {guid} {class_name}.{prop_name} = {val!r}")

    def _on_prop_batch(
        self, class_name: str, prop_name: str, rows: np.ndarray
    ) -> None:
        tracked = self._rows.get(class_name)
        if not tracked:
            return
        host = self.kernel.store._hosts[class_name]
        for row in np.asarray(rows).tolist():
            if row in tracked:
                guid = host.row_guid[row]
                if guid is None:  # row died between diff and delivery
                    continue
                val = self.kernel.get_property(guid, prop_name)
                self._log(
                    f"[trail] {guid} {class_name}.{prop_name} -> {val!r}"
                )
