"""Gameplay layer: the reference's NFGameServerPlugin/NFGameLogicPlugin
capabilities rebuilt as batched device phases + host control-plane APIs."""

from .combat import ATTACK_TIMER, CombatModule, SkillModule
from .defines import COMM_PROPERTY_RECORD, GameEvent, NpcType, PropertyGroup, STAT_NAMES
from .level import LevelModule
from .movement import MovementModule
from .property_config import PropertyConfigModule
from .regen import REGEN_TIMER, RegenModule
from .schema import standard_registry
from .stats import PropertyModule
from .world import GameWorld, WorldConfig, build_benchmark_world

__all__ = [
    "ATTACK_TIMER",
    "COMM_PROPERTY_RECORD",
    "CombatModule",
    "GameEvent",
    "GameWorld",
    "LevelModule",
    "MovementModule",
    "NpcType",
    "PropertyConfigModule",
    "PropertyGroup",
    "PropertyModule",
    "REGEN_TIMER",
    "RegenModule",
    "STAT_NAMES",
    "SkillModule",
    "WorldConfig",
    "build_benchmark_world",
    "standard_registry",
]
