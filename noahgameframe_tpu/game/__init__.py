"""Gameplay layer: the reference's NFGameServerPlugin/NFGameLogicPlugin
capabilities rebuilt as batched device phases + host control-plane APIs."""

from .buff import BuffModule
from .combat import ATTACK_TIMER, CombatModule, SkillModule
from .defines import (
    COMM_PROPERTY_RECORD,
    EShopType,
    GameEvent,
    ItemSubType,
    ItemType,
    NpcType,
    PropertyGroup,
    SLGBuildingState,
    STAT_NAMES,
    TaskState,
)
from .hero import HeroModule
from .items import EquipModule, ItemModule, PackModule
from .level import LevelModule
from .task import TaskDef, TaskModule
from .trail import PropertyTrailModule
from .movement import MovementModule
from .scene_process import SCENE_TYPE_CLONE, SCENE_TYPE_NORMAL, SceneProcessModule
from .property_config import PropertyConfigModule
from .regen import REGEN_TIMER, RegenModule
from .schema import standard_registry
from .slg import SLGBuildingModule, SLGShopModule
from .social import (
    FriendModule,
    GmModule,
    GuildModule,
    MailModule,
    PvpMatchModule,
    RankModule,
    ShopModule,
    TeamModule,
)
from .stats import PropertyModule
from .world import GameWorld, WorldConfig, build_benchmark_world

__all__ = [
    "ATTACK_TIMER",
    "BuffModule",
    "EquipModule",
    "HeroModule",
    "ItemModule",
    "ItemSubType",
    "ItemType",
    "PackModule",
    "TaskDef",
    "TaskModule",
    "TaskState",
    "FriendModule",
    "GmModule",
    "GuildModule",
    "MailModule",
    "PvpMatchModule",
    "RankModule",
    "ShopModule",
    "TeamModule",
    "COMM_PROPERTY_RECORD",
    "CombatModule",
    "GameEvent",
    "GameWorld",
    "LevelModule",
    "MovementModule",
    "SceneProcessModule",
    "SCENE_TYPE_CLONE",
    "SCENE_TYPE_NORMAL",
    "NpcType",
    "PropertyConfigModule",
    "PropertyGroup",
    "PropertyModule",
    "PropertyTrailModule",
    "REGEN_TIMER",
    "RegenModule",
    "EShopType",
    "SLGBuildingModule",
    "SLGBuildingState",
    "SLGShopModule",
    "STAT_NAMES",
    "SkillModule",
    "WorldConfig",
    "build_benchmark_world",
    "standard_registry",
]
