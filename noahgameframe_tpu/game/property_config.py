"""Per-(job, level) base-stat tables, compiled to a dense device array.

Reference: NFCPropertyConfigModule loads InitProperty elements into a
job -> level -> effect-element map and answers CalculateBaseValue(job,
level, stat) with a per-call element lookup
(NFCPropertyConfigModule.cpp:37-88).  Here the whole table compiles once
into `table[n_jobs, n_levels, n_stats]` int32 on device, so RefreshBase-
Property for a million players is one gather — and level-from-exp is a
searchsorted over precomputed cumulative MAXEXP thresholds instead of the
reference's while-loop (NFCLevelModule.cpp:38-69).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.element import ElementStore
from ..kernel.module import Module
from .defines import STAT_NAMES


class PropertyConfigModule(Module):
    name = "PropertyConfigModule"

    def __init__(self, n_jobs: int = 4, max_level: int = 100):
        super().__init__()
        self.n_jobs = int(n_jobs)
        self.max_level = int(max_level)
        # host-side staging; frozen to device arrays on ready_execute
        self._base = np.zeros((n_jobs, max_level + 1, len(STAT_NAMES)), np.int32)
        self._max_exp = np.zeros((n_jobs, max_level + 1), np.int32)
        self.table: Optional[jnp.ndarray] = None  # [J, L+1, S] int32
        self.max_exp: Optional[jnp.ndarray] = None  # [J, L+1] int32
        self.cum_exp: Optional[jnp.ndarray] = None  # [J, L+1] int64

    # -- table construction --------------------------------------------------

    def set_level_config(
        self, job: int, level: int, stats: Dict[str, int], max_exp: int = 0
    ) -> None:
        for k, v in stats.items():
            self._base[job, level, STAT_NAMES.index(k)] = int(v)
        self._max_exp[job, level] = int(max_exp)
        self.table = None

    def load_elements(self, elements: ElementStore) -> int:
        """Ingest InitProperty elements: each names a (Job, Level) cell and
        an EffectData element holding the stat values (reference
        NFCPropertyConfigModule::Load)."""
        n = 0
        for eid in elements.ids_of_class("InitProperty"):
            e = elements.element(eid)
            job = int(e.values.get("Job", 0))
            level = int(e.values.get("Level", 0))
            if not (0 <= job < self.n_jobs and 0 <= level <= self.max_level):
                continue
            stats: Dict[str, int] = {}
            ref = str(e.values.get("EffectData", "") or "")
            if ref and elements.exists(ref):
                ev = elements.element(ref).values
                stats = {k: int(v) for k, v in ev.items() if k in STAT_NAMES}
            self.set_level_config(
                job, level, stats, max_exp=int(e.values.get("MAXEXP", 0))
            )
            n += 1
        return n

    def fill_linear(
        self,
        job: int,
        base: Dict[str, int],
        per_level: Dict[str, int],
        max_exp_base: int = 100,
        max_exp_per_level: int = 50,
    ) -> None:
        """Procedural table for tests/benchmarks: stat = base + lvl*slope."""
        lv = np.arange(self.max_level + 1)
        for k in STAT_NAMES:
            b, s = int(base.get(k, 0)), int(per_level.get(k, 0))
            self._base[job, :, STAT_NAMES.index(k)] = b + lv * s
        self._max_exp[job] = max_exp_base + lv * max_exp_per_level
        self.table = None

    def freeze(self) -> None:
        """Push the tables to device.  cum_exp[j, L] = total exp needed to
        REACH level L from level 0 — level(total_exp) is one searchsorted.

        The compiled tick closes over these arrays as constants, so
        re-freezing after the world compiled must invalidate the jit cache
        — otherwise phases keep the old table silently."""
        self.table = jnp.asarray(self._base)
        self.max_exp = jnp.asarray(self._max_exp)
        cum = np.zeros((self.n_jobs, self.max_level + 1), np.int64)
        cum[:, 1:] = np.cumsum(self._max_exp[:, :-1].astype(np.int64), axis=1)
        self.cum_exp = jnp.asarray(cum)
        if self.kernel is not None:
            self.kernel.invalidate()

    def ready_execute(self) -> None:
        if self.table is None:
            self.freeze()

    # -- host-side queries (reference-parity API) ---------------------------

    def calculate_base_value(self, job: int, level: int, stat: str) -> int:
        if stat == "MAXEXP":
            return int(self._max_exp[job, level])
        return int(self._base[job, level, STAT_NAMES.index(stat)])

    def legal_level(self, job: int, level: int) -> bool:
        return 0 <= job < self.n_jobs and 0 <= level <= self.max_level

    # -- device-side queries -------------------------------------------------

    def base_stats_for(self, job: jnp.ndarray, level: jnp.ndarray) -> jnp.ndarray:
        """[C] job, [C] level -> [C, S] base stats (one fused gather)."""
        j = jnp.clip(job, 0, self.n_jobs - 1)
        l = jnp.clip(level, 0, self.max_level)
        return self.table[j, l]

    def level_from_total_exp(
        self, job: jnp.ndarray, total_exp: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Total accumulated exp -> (level, exp-within-level).  Replaces the
        reference's per-player level-up while-loop with a vectorised
        searchsorted per job row."""
        j = jnp.clip(job, 0, self.n_jobs - 1)
        te = total_exp

        # searchsorted row-wise: level = number of thresholds <= total_exp, -1
        thresholds = self.cum_exp[j]  # [C, L+1]
        level = jnp.sum(thresholds <= te[:, None], axis=1).astype(jnp.int32) - 1
        level = jnp.clip(level, 0, self.max_level)
        rem = (te - jnp.take_along_axis(thresholds, level[:, None], axis=1)[:, 0]).astype(jnp.int32)
        return level, rem
