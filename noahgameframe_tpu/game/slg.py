"""SLG city-building gameplay: building placement, timed upgrade/boost,
item production, and the SLG shop.

Reference modules (`NFServer/NFGameLogicPlugin/`):
- NFCSLGBuildingModule (`NFCSLGBuildingModule.cpp:57-96` AddBuilding,
  `:98-131` Upgrade, `:241-273` Boost, `:275-306` Produce, `:308-331`
  Move, `:334-381` CheckBuildingStatusEnd) — BuildingList record rows
  with a State machine (EBS_IDLE/UPGRADE/BOOST) driven by schedule
  callbacks;
- NFCSLGShopModule (`NFCSLGShopModule.cpp:52-117` ReqBuyItem) — element-
  config catalogue: level gate, Gold+Diamond cost, then per-EShopType
  effect (item, equip, or building placement).

Design differences from the reference, on purpose:
- Buildings are identified by their record ROW (like BagEquipList
  equips), not a per-row GUID column: the row index is stable for the
  row's lifetime, rides the wire messages (`ReqAckMoveBuildObject.row`),
  and restores from checkpoints with no registry.  The reference's
  BuildingGUID column exists only to find the row again.
- Timers are wall-anchored absolute SECONDS stored in the record
  (StateStartTime / StateEndTime; see _now()), so the record itself is
  the source of truth: resume re-arms pending completions by scanning
  the record (CheckBuildingStatusEnd), no host timer state needs
  checkpointing, and a blob saved in one process resolves correctly in
  a freshly-started one (downtime counts toward completion).
- Upgrade completion has a real effect (Level column +1): the
  reference's OnUpgradeHeartBeat body is commented out ("TO ADD"), we
  complete the obvious intent.
- The shop consumes Diamond for the element's Diamond cost; the
  reference passes nGold to ConsumeDiamond (`NFCSLGShopModule.cpp:76`),
  which reads like a bug, not a contract.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.datatypes import Guid
from ..kernel.module import Module
from .defines import EShopType, ItemType, SLGBuildingState

BUILDING_RECORD = "BuildingList"
PRODUCE_RECORD = "BuildingProduce"


class SLGBuildingModule(Module):
    """BuildingList state machine (NFCSLGBuildingModule)."""

    name = "SLGBuildingModule"

    def __init__(
        self,
        pack=None,
        upgrade_s: float = 20.0,  # reference nNeedTime = 20
        boost_factor: float = 0.5,
        produce_interval_s: float = 50.0,  # reference nTime = 50
        wall_base: float = 0.0,
    ) -> None:
        super().__init__()
        self.pack = pack
        self.upgrade_s = upgrade_s
        self.boost_factor = boost_factor
        self.produce_interval_s = produce_interval_s
        self.collect_amount = 10  # per building level, per collect interval
        self.collect_interval_s = 10.0  # accrual period for RESOURCE yield
        self.wall_base: float = float(wall_base)  # see _now()
        # due-tick heap over (tick, owner, kind, rec_row); the record is
        # the source of truth — entries are validated when they fire
        self._due: List[Tuple[int, Guid, str, int]] = []

    def after_init(self) -> None:
        # the reference re-arms building timers on COE_CREATE_FINISH
        # (NFCSLGBuildingModule::OnClassObjectEvent) — a player logging
        # back in mid-upgrade must not stay stuck in UPGRADE forever.
        # CREATE_FINISH fires after CREATE_LOADDATA, so the data agent has
        # already restored the records by the time we scan them.
        from ..kernel.kernel import ObjectEvent

        def on_player(guid: Guid, _cname: str, ev) -> None:
            if ev == ObjectEvent.CREATE_FINISH:
                self.check_building_status_end(guid)

        self.kernel.register_class_event(on_player, "Player")

    # ------------------------------------------------------------ helpers
    # Time unit: ANCHORED sim seconds — `wall_base` plus sim time
    # (tick x dt).  Absolute seconds persist in the record (the reference
    # stores GetNowTime() the same way, NFCSLGBuildingModule.cpp:121-124).
    # The anchor defaults to 0 (pure logical time), keeping every value
    # a function of journaled inputs for record/replay; a deployment that
    # wants offline progression across restarts (downtime counting toward
    # completion) injects wall_base=time.time() at construction — the one
    # wall read then happens outside the simulation layer and is itself
    # journalable.  Fits int32 like the reference's.
    def _dur_s(self, seconds: float) -> int:
        """Duration in whole seconds (floor 1 — timers must fire)."""
        return max(1, int(round(seconds)))

    def _now(self) -> int:
        return int(self.wall_base
                   + self.kernel.tick_count * self.kernel.schedule.dt)

    def _get(self, guid: Guid, row: int, tag: str):
        k = self.kernel
        return k.store.record_get(k.state, guid, BUILDING_RECORD, row, tag)

    def _set(self, guid: Guid, row: int, tag: str, value) -> None:
        k = self.kernel
        k.state = k.store.record_set(k.state, guid, BUILDING_RECORD, row,
                                     tag, value)

    def buildings(self, guid: Guid) -> Dict[int, str]:
        """row -> building config id, straight from the record."""
        k = self.kernel
        cname, erow = k.store.row_of(guid)
        spec = k.store.spec(cname)
        if BUILDING_RECORD not in spec.records:
            return {}
        rec = k.state.classes[cname].records[BUILDING_RECORD]
        rs = spec.records[BUILDING_RECORD]
        used = np.asarray(rec.used[erow])
        ids = np.asarray(rec.i32[erow, :, rs.cols["BuildingID"].col])
        return {
            int(r): k.store.strings.lookup(int(ids[r]))
            for r in np.flatnonzero(used)
        }

    # -------------------------------------------------------------- verbs
    def add_building(self, guid: Guid, building_id: str, x: float, y: float,
                     z: float) -> Optional[int]:
        """Place a building (AddBuilding, NFCSLGBuildingModule.cpp:57-96);
        returns its record row or None when the record is full."""
        if not building_id:
            return None
        k = self.kernel
        if guid not in k.store.guid_map:
            return None
        try:
            k.state, row = k.store.record_add_row(
                k.state, guid, BUILDING_RECORD,
                {
                    "BuildingID": building_id,
                    "State": int(SLGBuildingState.IDLE),
                    "X": int(x), "Y": int(y), "Z": int(z),
                    "StateStartTime": self._now(),
                    "StateEndTime": 0,
                    "Level": 1,
                    "LastCollect": self._now(),  # accrual starts now
                },
            )
        except RuntimeError:
            return None
        return row

    def upgrade(self, guid: Guid, row: int) -> bool:
        """IDLE -> UPGRADE with a timed completion
        (Upgrade, NFCSLGBuildingModule.cpp:98-131)."""
        blds = self.buildings(guid)
        if row not in blds:
            return False
        if int(self._get(guid, row, "State")) != int(SLGBuildingState.IDLE):
            return False
        # per-building duration from the config element when present
        secs = self.upgrade_s
        elems = self.kernel.elements
        if elems.exists(blds[row]):
            cfg = float(elems.element(blds[row]).values.get("UpgradeTime", 0)
                        or 0)
            if cfg > 0:
                secs = cfg
        now, end = self._now(), self._now() + self._dur_s(secs)
        self._set(guid, row, "State", int(SLGBuildingState.UPGRADE))
        self._set(guid, row, "StateStartTime", now)
        self._set(guid, row, "StateEndTime", end)
        heapq.heappush(self._due, (end, guid, "state", row))
        return True

    def boost(self, guid: Guid, row: int) -> bool:
        """Shorten a running upgrade by boost_factor
        (Boost, NFCSLGBuildingModule.cpp:241-273)."""
        if row not in self.buildings(guid):
            return False
        if int(self._get(guid, row, "State")) != int(SLGBuildingState.UPGRADE):
            return False
        now = self._now()
        end = int(self._get(guid, row, "StateEndTime"))
        boosted = now + max(1, int((end - now) * self.boost_factor))
        self._set(guid, row, "State", int(SLGBuildingState.BOOST))
        self._set(guid, row, "StateEndTime", boosted)
        heapq.heappush(self._due, (boosted, guid, "state", row))
        return True

    def cancel(self, guid: Guid, row: int) -> bool:
        """Back to IDLE, timers void (EFT_CANCEL)."""
        if row not in self.buildings(guid):
            return False
        self._set(guid, row, "State", int(SLGBuildingState.IDLE))
        self._set(guid, row, "StateEndTime", 0)
        return True

    def move(self, guid: Guid, row: int, x: float, y: float, z: float) -> bool:
        """Re-place a building (Move, NFCSLGBuildingModule.cpp:308-331)."""
        if row not in self.buildings(guid):
            return False
        self._set(guid, row, "X", int(x))
        self._set(guid, row, "Y", int(y))
        self._set(guid, row, "Z", int(z))
        return True

    def building_level(self, guid: Guid, row: int) -> int:
        return int(self._get(guid, row, "Level"))

    def building_state(self, guid: Guid, row: int) -> int:
        return int(self._get(guid, row, "State"))

    # ------------------------------------------------------------ produce
    def _produce_dur_s(self, guid: Guid, building_row: int) -> int:
        """Per-building production interval: the Building config element's
        ProduceTime (seconds) when set, else the module default."""
        secs = self.produce_interval_s
        blds = self.buildings(guid)
        elems = self.kernel.elements
        bid = blds.get(building_row)
        if bid is not None and elems.exists(bid):
            cfg = float(elems.element(bid).values.get("ProduceTime", 0) or 0)
            if cfg > 0:
                secs = cfg
        return self._dur_s(secs)

    def can_produce(self, guid: Guid, building_row: int,
                    item_id: str) -> bool:
        """A building only produces items its CONFIG lists (ItemID or the
        ";"-joined ItemList column) — clients pick the ids they send, so
        an unvalidated produce would mint shop items for free."""
        blds = self.buildings(guid)
        bid = blds.get(building_row)
        elems = self.kernel.elements
        if bid is None or not elems.exists(bid):
            return False
        cfg = elems.element(bid).values
        allowed = [str(cfg.get("ItemID", "") or "")]
        allowed += str(cfg.get("ItemList", "") or "").split(";")
        return item_id in [a for a in allowed if a]

    def produce(self, guid: Guid, row: int, item_id: str,
                count: int) -> bool:
        """Queue `count` items from a building; one item lands in the bag
        per produce interval (Produce + OnProduceHeartBeat intent,
        NFCSLGBuildingModule.cpp:275-306).  Refuses items the building's
        config doesn't list."""
        if count <= 0 or not self.can_produce(guid, row, item_id):
            return False
        k = self.kernel
        rows = k.store.record_find_rows(
            k.state, guid, PRODUCE_RECORD, "BuildingRow", row
        )
        match = [
            r for r in rows
            if str(k.store.record_get(k.state, guid, PRODUCE_RECORD, r,
                                      "ItemID")) == item_id
        ]
        if match:
            r = match[0]
            left = int(k.store.record_get(k.state, guid, PRODUCE_RECORD, r,
                                          "LeftCount"))
            k.state = k.store.record_set(k.state, guid, PRODUCE_RECORD, r,
                                         "LeftCount", left + count)
            return True
        nxt = self._now() + self._produce_dur_s(guid, row)
        try:
            k.state, r = k.store.record_add_row(
                k.state, guid, PRODUCE_RECORD,
                {"BuildingRow": row, "ItemID": item_id, "LeftCount": count,
                 "NextTime": nxt},
            )
        except RuntimeError:
            return False
        heapq.heappush(self._due, (nxt, guid, "produce", r))
        return True

    def produce_left(self, guid: Guid, row: int, item_id: str) -> int:
        k = self.kernel
        for r in k.store.record_find_rows(k.state, guid, PRODUCE_RECORD,
                                          "BuildingRow", row):
            if str(k.store.record_get(k.state, guid, PRODUCE_RECORD, r,
                                      "ItemID")) == item_id:
                return int(k.store.record_get(k.state, guid, PRODUCE_RECORD,
                                              r, "LeftCount"))
        return 0

    # ------------------------------------------------------ timer driving
    def execute(self) -> None:
        now = self._now()
        k = self.kernel
        while self._due and self._due[0][0] <= now:
            _, guid, kind, row = heapq.heappop(self._due)
            if guid not in k.store.guid_map:
                continue  # owner gone; record died with it
            if kind == "state":
                self._complete_state(guid, row)
            else:
                self._step_produce(guid, row)

    def _complete_state(self, guid: Guid, row: int) -> None:
        if row not in self.buildings(guid):
            return
        st = int(self._get(guid, row, "State"))
        if st not in (int(SLGBuildingState.UPGRADE),
                      int(SLGBuildingState.BOOST)):
            return  # cancelled or re-armed meanwhile
        end = int(self._get(guid, row, "StateEndTime"))
        if end > self._now():
            return  # boost re-scheduled it; a later heap entry fires
        self._set(guid, row, "Level", self.building_level(guid, row) + 1)
        self._set(guid, row, "State", int(SLGBuildingState.IDLE))
        self._set(guid, row, "StateStartTime", self._now())
        self._set(guid, row, "StateEndTime", 0)

    def _step_produce(self, guid: Guid, prow: int) -> None:
        k = self.kernel
        cname, _ = k.store.row_of(guid)
        rec = k.state.classes[cname].records.get(PRODUCE_RECORD)
        if rec is None:
            return
        erow = k.store.row_of(guid)[1]
        if not bool(np.asarray(rec.used[erow, prow])):
            return
        # duplicate/stale heap entries (relogin re-arm + surviving old
        # entries) must not double-produce: the record's NextTime is the
        # truth — the same guard shape as _complete_state's EndTime check
        if int(k.store.record_get(k.state, guid, PRODUCE_RECORD, prow,
                                  "NextTime")) > self._now():
            return
        item = str(k.store.record_get(k.state, guid, PRODUCE_RECORD, prow,
                                      "ItemID"))
        left = int(k.store.record_get(k.state, guid, PRODUCE_RECORD, prow,
                                      "LeftCount"))
        if self.pack is not None:
            self.pack.create_item(guid, item, 1)
        left -= 1
        if left <= 0:
            k.state = k.store.record_remove_row(k.state, guid,
                                                PRODUCE_RECORD, prow)
            return
        k.state = k.store.record_set(k.state, guid, PRODUCE_RECORD, prow,
                                     "LeftCount", left)
        brow = int(k.store.record_get(k.state, guid, PRODUCE_RECORD, prow,
                                      "BuildingRow"))
        nxt = self._now() + self._produce_dur_s(guid, brow)
        k.state = k.store.record_set(k.state, guid, PRODUCE_RECORD, prow,
                                     "NextTime", nxt)
        heapq.heappush(self._due, (nxt, guid, "produce", prow))

    # ---------------------------------------------------------- resources
    def collect(self, guid: Guid, row: int, resource: str) -> bool:
        """RESOURCE buildings yield accrued stock on demand
        (EFT_COLLECT_GOLD/STONE/STEEL/DIAMOND): level × collect_amount
        per elapsed collect interval since the last collect (LastCollect
        column).  Spamming collects yields nothing — the accrual is
        time-based, not per-call.  The reference's functypes exist only
        as enum values; this is the obvious completion."""
        if resource not in ("Gold", "Stone", "Steel", "Diamond"):
            return False
        blds = self.buildings(guid)
        if row not in blds:
            return False
        elems = self.kernel.elements
        from .defines import SLGBuildingType

        # only a KNOWN RESOURCE building yields — an unconfigured id must
        # refuse, not default-allow (clients pick the row they send)
        if not elems.exists(blds[row]):
            return False
        if int(elems.element(blds[row]).values.get("Type", -1)) != int(
                SLGBuildingType.RESOURCE):
            return False
        k = self.kernel
        now = self._now()
        last = int(self._get(guid, row, "LastCollect"))
        if last < int(self.wall_base):
            # stamp from a different (earlier) time base — e.g. a legacy
            # blob that stored tick counts loaded into a wall-anchored
            # process: rebase instead of paying out an epoch's worth of
            # intervals in one call
            self._set(guid, row, "LastCollect", now)
            return False
        period = self._dur_s(self.collect_interval_s)
        intervals = (now - last) // period
        if intervals <= 0:
            return False  # nothing accrued yet
        amount = self.building_level(guid, row) * self.collect_amount \
            * int(intervals)
        # advance by WHOLE intervals — the fractional remainder keeps
        # accruing (resetting to `now` would tax off-cadence collectors)
        self._set(guid, row, "LastCollect", last + int(intervals) * period)
        k.set_property(guid, resource,
                       int(k.get_property(guid, resource)) + amount)
        return True

    # --------------------------------------------------- resume semantics
    def check_building_status_end(self, guid: Guid) -> None:
        """Re-arm pending completions from the record after a load — the
        reference's CheckBuildingStatusEnd + CheckProduceData on
        COE_CREATE_FINISH (NFCSLGBuildingModule.cpp:334-390)."""
        k = self.kernel
        if guid not in k.store.guid_map:
            return
        for row in self.buildings(guid):
            st = int(self._get(guid, row, "State"))
            if st in (int(SLGBuildingState.UPGRADE),
                      int(SLGBuildingState.BOOST)):
                end = max(int(self._get(guid, row, "StateEndTime")),
                          self._now() + 1)
                heapq.heappush(self._due, (end, guid, "state", row))
        for r in k.store.record_used_rows(k.state, guid, PRODUCE_RECORD):
            nxt = max(
                int(k.store.record_get(k.state, guid, PRODUCE_RECORD, r,
                                       "NextTime")),
                self._now() + 1,
            )
            heapq.heappush(self._due, (nxt, guid, "produce", r))

    def restore_state(self, data: dict) -> None:
        # the records restore through the store; re-arm every alive owner
        self._due = []
        k = self.kernel
        for guid in list(k.store.guid_map):
            cname = k.store.row_of(guid)[0]
            if BUILDING_RECORD in k.store.spec(cname).records:
                self.check_building_status_end(guid)

    def checkpoint_state(self) -> dict:
        return {}  # records are the source of truth


class SLGShopModule(Module):
    """Element-config SLG shop (NFCSLGShopModule::ReqBuyItem,
    NFCSLGShopModule.cpp:52-117): level gate, Gold+Diamond cost, then the
    per-EShopType effect — bag item, equip, or building placement."""

    name = "SLGShopModule"

    def __init__(self, pack, building: SLGBuildingModule) -> None:
        super().__init__()
        self.pack = pack
        self.building = building

    def _consume(self, guid: Guid, prop: str, amount: int) -> bool:
        if amount <= 0:
            return True
        k = self.kernel
        cur = int(k.get_property(guid, prop))
        if cur < amount:
            return False
        k.set_property(guid, prop, cur - amount)
        return True

    def buy(self, guid: Guid, shop_id: str, x: float = 0.0, y: float = 0.0,
            z: float = 0.0) -> bool:
        k = self.kernel
        elems = k.elements
        if guid not in k.store.guid_map or not elems.exists(shop_id):
            return False
        cfg = elems.element(shop_id).values
        need_level = int(cfg.get("Level", 0) or 0)
        if int(k.get_property(guid, "Level")) < need_level:
            return False
        gold = int(cfg.get("Gold", 0) or 0)
        diamond = int(cfg.get("Diamond", 0) or 0)
        if (int(k.get_property(guid, "Gold")) < gold
                or int(k.get_property(guid, "Diamond")) < diamond):
            return False
        item_id = str(cfg.get("ItemID", "") or "")
        if not elems.exists(item_id):
            return False
        # effect FIRST, charge after: a failed effect (building record
        # full, bag full) must not eat the currency.  The deduction cannot
        # fail — balances were checked above and nothing runs in between.
        count = max(1, int(cfg.get("Count", 0) or 0))
        shop_type = int(cfg.get("Type", 0) or 0)
        if shop_type == int(EShopType.BUILDING):
            ok = self.building.add_building(guid, item_id, x, y, z) is not None
        elif shop_type in (int(EShopType.GOLD), int(EShopType.DIAMOND),
                           int(EShopType.SP)):
            ok = self.pack.create_item(guid, item_id, count)
        else:
            item_cfg = elems.element(item_id).values
            if int(item_cfg.get("ItemType", -1)) == int(ItemType.EQUIP):
                ok = self.pack.create_equip(guid, item_id) is not None
            else:
                ok = self.pack.create_item(guid, item_id, count)
        if not ok:
            return False
        self._consume(guid, "Gold", gold)
        self._consume(guid, "Diamond", diamond)
        return True
