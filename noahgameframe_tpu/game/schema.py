"""The standard game schema: IObject / Player / NPC (+ scene classes).

Mirrors the reference's generated class XMLs in capability, not layout:
- IObject root with identity/scene columns (LogicClass.xml root class)
- Player with the full combat-stat block, progression, wallet, and the
  CommPropertyValue stat-group record (Class/Player.xml)
- NPC with the combat-stat block, seed/refresh fields, LastAttacker, and
  movement targets (Class/NPC.xml)

The property set is intentionally the reference's so the persistence,
broadcast-flag and stat-recompute semantics can be tested 1:1; games define
their own classes the same way (see tests/fixtures.py for a minimal one).
"""

from __future__ import annotations

from typing import Iterable, Optional

from ..core.schema import ClassDef, ClassRegistry, prop, record
from .defines import COMM_PROPERTY_RECORD, PropertyGroup, STAT_NAMES


def _stat_props():
    """The shared fighter stat block (Public+Private like the reference)."""
    return [prop(n, "int", public=True, private=True) for n in STAT_NAMES]


def _comm_property_record():
    """Per-group stat contributions; final stat = column sum over the group
    rows (reference CommPropertyValue, Row=15 in the XML but only the
    NPG_ALL=9 enum groups are ever used — we size it exactly from
    PropertyGroup.ALL)."""
    return record(
        COMM_PROPERTY_RECORD,
        int(PropertyGroup.ALL),
        [(n, "int") for n in STAT_NAMES],
        public=True,
        private=True,
    )


def _buff_record():
    """Active timed buffs: config-table index + absolute expiry tick; a
    device phase folds unexpired rows into the RUNTIME_BUFF stat group
    (the reference NFCBuffModule applies/reverts per-buff callbacks)."""
    return record(
        "BuffList", 8,
        [("ConfigIdx", "int"), ("ExpireTick", "int")],
        private=True,
    )


def standard_registry(extra: Optional[Iterable[ClassDef]] = None) -> ClassRegistry:
    reg = ClassRegistry()
    reg.define(
        ClassDef(
            name="IObject",
            properties=[
                prop("ID", "string", private=True),
                prop("ClassName", "string", private=True),
                prop("SceneID", "int", private=True),
                prop("GroupID", "int", private=True),
                prop("ConfigID", "string", private=True),
                prop("Position", "vector3", public=True, private=True, save=True, cache=True),
                prop("Camp", "int", public=True, private=True),
            ],
        )
    )
    reg.define(
        ClassDef(
            name="Player",
            parent="IObject",
            properties=[
                prop("Name", "string", public=True, private=True, save=True, cache=True),
                prop("Sex", "int", public=True, private=True, save=True),
                prop("Race", "int", public=True, private=True, save=True),
                prop("Job", "int", public=True, private=True, save=True),
                prop("Level", "int", public=True, private=True, save=True, cache=True),
                prop("EXP", "int", private=True, save=True),
                prop("VIPLevel", "int", public=True, private=True, save=True),
                prop("VIPEXP", "int", private=True, save=True),
                prop("HP", "int", public=True, private=True, save=True),
                prop("MP", "int", public=True, private=True, save=True),
                prop("SP", "int", public=True, private=True, save=True),
                prop("Gold", "int", private=True, save=True, upload=True),
                prop("Money", "int", private=True, save=True, upload=True),
                # SLG resource block (reference Property.xlsx SLG columns):
                # Diamond is a shop cost, Stone/Steel/Gold accrue from
                # RESOURCE-building collects (game/slg.py)
                prop("Diamond", "int", private=True, save=True, upload=True),
                prop("Stone", "int", private=True, save=True, upload=True),
                prop("Steel", "int", private=True, save=True, upload=True),
                prop("Account", "string", private=True),
                prop("ConnectKey", "string", private=True),
                prop("MAXEXP", "int", public=True, private=True),
                prop("OnlineCount", "int", private=True, save=True),
                prop("TotalTime", "int", private=True, save=True),
                prop("GMLevel", "int", private=True, save=True),
                prop("GameID", "int", private=True),
                prop("GateID", "int", private=True),
                prop("GuildID", "object", public=True, private=True, save=True),
                prop("TeamID", "object", public=True, private=True),
                prop("FirstTarget", "object", public=True, private=True),
                prop("MoveTo", "vector2"),
            ]
            + _stat_props(),
            records=[
                _comm_property_record(),
                _buff_record(),
                # full reference column set (Class/Player.xml:70-93) with
                # one deviation: heroes and their worn equips are
                # row-identified (EquipN holds a BagEquipList row+1, 0 =
                # empty) — the reference's per-row GUID columns exist only
                # to find rows again
                record(
                    "PlayerHero",
                    64,
                    [
                        ("GUID", "object"),
                        ("ConfigID", "string"),
                        ("Level", "int"),
                        ("Exp", "int"),
                        ("Star", "int"),
                        ("Equip1", "int"),
                        ("Equip2", "int"),
                        ("Equip3", "int"),
                        ("Equip4", "int"),
                        ("Equip5", "int"),
                        ("Equip6", "int"),
                        ("Talent1", "string"),
                        ("Talent2", "string"),
                        ("Talent3", "string"),
                        ("Talent4", "string"),
                        ("Talent5", "string"),
                        ("Skill1", "string"),
                        ("Skill2", "string"),
                        ("Skill3", "string"),
                        ("Skill4", "string"),
                        ("Skill5", "string"),
                        ("FightSkill", "string"),
                    ],
                    private=True,
                    save=True,
                ),
                # battle line-up: hero record row per fight position
                # (Class/Player.xml:94-97 PlayerFightHero, Row=5)
                record(
                    "PlayerFightHero",
                    5,
                    [
                        ("HeroRow", "int"),  # PlayerHero row + 1; 0 = empty
                        ("FightPos", "int"),
                    ],
                    private=True,
                    save=True,
                    upload=True,
                ),
                record(
                    "BagItemList",
                    64,
                    [
                        ("ConfigID", "string"),
                        ("ItemCount", "int"),
                        ("Bound", "int"),
                        ("ExpiredType", "int"),
                        ("Date", "int"),
                    ],
                    private=True,
                    save=True,
                ),
                record(
                    "BagEquipList",
                    32,
                    [
                        ("GUID", "object"),
                        ("WearGUID", "object"),
                        ("ConfigID", "string"),
                        ("ExpiredType", "int"),
                        ("Date", "int"),
                        ("SlotCount", "int"),
                        # socketed gem config ids, ";"-joined — row state
                        # lives IN the record so recycle/relog are safe
                        # (reference InlayInfo column)
                        ("InlayInfo", "string"),
                    ],
                    private=True,
                    save=True,
                ),
                record(
                    "TaskList",
                    32,
                    [
                        ("TaskID", "string"),
                        ("TaskStatus", "int"),
                        ("Process", "int"),
                    ],
                    private=True,
                    save=True,
                ),
                # SLG city: buildings are row-identified (no per-row GUID
                # column — the row index rides the wire and restores from
                # checkpoints; reference BuildingList,
                # NFCSLGBuildingModule.cpp:71-96).  Times are kernel ticks.
                record(
                    "BuildingList",
                    16,
                    [
                        ("BuildingID", "string"),
                        ("State", "int"),
                        ("X", "int"),
                        ("Y", "int"),
                        ("Z", "int"),
                        ("StateStartTime", "int"),
                        ("StateEndTime", "int"),
                        ("Level", "int"),
                        ("LastCollect", "int"),
                    ],
                    private=True,
                    save=True,
                ),
                record(
                    "BuildingProduce",
                    16,
                    [
                        ("BuildingRow", "int"),
                        ("ItemID", "string"),
                        ("LeftCount", "int"),
                        ("NextTime", "int"),
                    ],
                    private=True,
                    save=True,
                ),
            ],
        )
    )
    reg.define(
        ClassDef(
            name="NPC",
            parent="IObject",
            properties=[
                prop("SeedID", "string"),
                prop("HP", "int", public=True, private=True, save=True),
                prop("MP", "int", public=True, private=True, save=True),
                prop("SP", "int", public=True, private=True, save=True),
                prop("EXP", "int", public=True, private=True, save=True),
                prop("Gold", "int", public=True, private=True, save=True),
                prop("NPCType", "int"),
                prop("MasterID", "object", private=True, save=True),
                prop("LastAttacker", "object"),
                prop("EffectData", "string"),
                prop("AtkDis", "float"),
                prop("MoveType", "int"),
                prop("TargetPos", "vector2"),
                prop("DeadTick", "int"),
            ]
            + _stat_props(),
            records=[_comm_property_record(), _buff_record()],
        )
    )
    # social container entities: team/guild objects the OBJECT-typed
    # TeamID/GuildID player properties point at (the reference likewise
    # models Guild as an entity class)
    reg.define(
        ClassDef(
            name="Team",
            parent="IObject",
            properties=[
                prop("Name", "string", public=True, private=True),
                prop("LeaderID", "object", public=True, private=True),
                prop("MemberCount", "int", public=True, private=True),
            ],
        )
    )
    reg.define(
        ClassDef(
            name="Guild",
            parent="IObject",
            properties=[
                prop("Name", "string", public=True, private=True, save=True),
                prop("LeaderID", "object", public=True, private=True, save=True),
                prop("MemberCount", "int", public=True, private=True),
                prop("GuildLevel", "int", public=True, private=True, save=True),
                prop("Notice", "string", public=True, private=True, save=True),
            ],
        )
    )
    # item/equip config class (reference Item.xlsx → Class/Item.xml):
    # consumables carry ItemType/SubType/AwardValue, equips carry the
    # stat columns EquipModule folds into the NPG_EQUIP group
    reg.define(
        ClassDef(
            name="Item",
            parent="IObject",
            properties=[
                prop("ItemType", "int"),
                prop("ItemSubType", "int"),
                prop("Level", "int"),
                prop("AwardValue", "int"),
                prop("AwardProperty", "string"),
                prop("CoolDownTime", "float"),
                prop("OverlayCount", "int"),
                prop("ExpiredType", "int"),
                prop("BuyPrice", "int"),
                prop("SalePrice", "int"),
                prop("Script", "string"),
                prop("Extend", "string"),
                prop("Icon", "string"),
                prop("HeroTye", "int"),
                # hero-card columns: initial skill/talent loadout copied
                # into the PlayerHero row on add_hero (Hero.xlsx shape)
                prop("Skill1", "string"),
                prop("Skill2", "string"),
                prop("Skill3", "string"),
                prop("Skill4", "string"),
                prop("Skill5", "string"),
                prop("Talent1", "string"),
                prop("Talent2", "string"),
                prop("Talent3", "string"),
                prop("Talent4", "string"),
                prop("Talent5", "string"),
            ]
            + _stat_props(),
        )
    )
    # skill/talent config classes: upgrade chains ride AfterUpID
    # (reference Skill.xlsx / Talent.xlsx, consumed by
    # HeroModule.hero_skill_up / hero_talent_up)
    reg.define(
        ClassDef(
            name="Skill",
            parent="IObject",
            properties=[
                prop("SkillType", "int"),
                prop("AfterUpID", "string"),
                prop("DamageValue", "int"),
                prop("CoolDownTime", "float"),
            ],
        )
    )
    reg.define(
        ClassDef(
            name="Talent",
            parent="IObject",
            properties=[
                prop("AfterUpID", "string"),
                prop("AwardValue", "int"),
            ],
        )
    )
    # SLG config classes (reference NFDataCfg Shop.xlsx / Building rows,
    # consumed by game/slg.py): a shop row gates on Level, costs
    # Gold+Diamond, and yields ItemID per EShopType; a building row
    # carries its upgrade duration
    reg.define(
        ClassDef(
            name="Shop",
            parent="IObject",
            properties=[
                prop("Type", "int"),  # EShopType
                prop("Level", "int"),
                prop("Gold", "int"),
                prop("Diamond", "int"),
                prop("ItemID", "string"),
                prop("Count", "int"),
            ],
        )
    )
    reg.define(
        ClassDef(
            name="Building",
            parent="IObject",
            properties=[
                prop("Type", "int"),  # EBuildingType
                prop("Level", "int"),
                prop("UpgradeTime", "float"),  # seconds; 0 = module default
                prop("ProduceTime", "float"),
                prop("ItemID", "string"),  # producible item...
                prop("ItemList", "string"),  # ...or a ";"-joined set
            ],
        )
    )
    # per-(job,level) base-stat table rows (reference InitProperty class,
    # consumed by NFCPropertyConfigModule::Load)
    reg.define(
        ClassDef(
            name="InitProperty",
            parent="IObject",
            properties=[
                prop("Job", "int"),
                prop("Level", "int"),
                prop("EffectData", "string"),
                prop("MAXEXP", "int"),
            ],
        )
    )
    reg.define(
        ClassDef(
            name="Scene",
            parent="IObject",
            properties=[
                prop("SceneName", "string"),
                prop("MaxGroup", "int"),
                prop("Width", "int"),
                prop("SceneType", "int"),  # normal vs clone (NFISceneProcessModule.h:15-20)
            ],
        )
    )
    if extra:
        for cd in extra:
            reg.define(cd)
    return reg
