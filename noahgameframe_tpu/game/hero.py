"""Hero system: collection, leveling, stars, skills/talents, battle
line-up, and clone-scene summons.

Reference: NFCHeroModule (`NFServer/NFGameLogicPlugin/NFCHeroModule.cpp`,
443 LoC) over the PlayerHero record (Class/Player.xml:70-93) and the
PlayerFightHero line-up record (`:94-97`):
- AddHero (`:49-70`) appends a hero row;
- AddHeroExp (`:72-127`) levels on a progressive curve — each level
  costs (level+1) x ONCELEVEEXP, capped at HERO_MAXLEVEL
  (NFIHeroModule.h:21-23);
- HeroStarUp (`:129-161`) +1 star up to HERO_MAXSTAR;
- HeroSkillUp / HeroTalentUp (`:162-250`) walk the config chain via the
  skill/talent element's AfterUpID;
- SetFightHero (`:252-293`) places a hero at a battle position in
  PlayerFightHero;
- CreateHero / DestroyHero (`:295-367`) summon the hero as an NPC
  entity (MasterID = owner, owner's camp) in CLONE scenes only;
- HeroWearSkill (`:389-426`) picks the fight skill from the owned
  Skill1-5 set.

Design differences, on purpose: heroes are identified by their record
ROW (the reference's per-row GUID column exists only to find rows
again); add_hero dedupes by ConfigID and a duplicate add raises the
star instead (card-stacking — the reference appends duplicate rows);
the stat fold sums EVERY positioned fight hero's config stats x level
into the FIGHTING_HERO group (the reference's own NPG slot for the hero
lineup contribution, distinct from equipment's EQUIP_AWARD).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.datatypes import Guid
from ..kernel.module import Module
from .defines import STAT_NAMES, PropertyGroup

HERO_RECORD = "PlayerHero"
FIGHT_RECORD = "PlayerFightHero"
HERO_MAXLEVEL = 100  # NFIHeroModule.h:21
HERO_MAXSTAR = 100  # NFIHeroModule.h:22
ONCE_LEVEL_EXP = 100  # NFIHeroModule.h:23
SKILL_SLOTS = ("Skill1", "Skill2", "Skill3", "Skill4", "Skill5")
TALENT_SLOTS = ("Talent1", "Talent2", "Talent3", "Talent4", "Talent5")


class HeroModule(Module):
    name = "HeroModule"

    def __init__(self, properties, exp_per_level: int = ONCE_LEVEL_EXP,
                 max_level: int = HERO_MAXLEVEL,
                 max_star: int = HERO_MAXSTAR) -> None:
        super().__init__()
        self.properties = properties  # game.stats.PropertyModule
        self.exp_per_level = exp_per_level
        self.max_level = max_level
        self.max_star = max_star
        # owner -> summoned entity guid by hero row (transient control
        # plane; summons are entities, not persistent state)
        self._summons: Dict[Guid, Dict[int, Guid]] = {}
        self.scene_process = None  # wired by the world assembly

    def after_init(self) -> None:
        from ..kernel.kernel import ObjectEvent

        def on_player(guid: Guid, _cn: str, ev) -> None:
            if ev == ObjectEvent.DESTROY:
                self._summons.pop(guid, None)  # no growth on dead owners

        self.kernel.register_class_event(on_player, "Player")

    # ----------------------------------------------------------- helpers
    def _get(self, guid: Guid, row: int, tag: str):
        k = self.kernel
        return k.store.record_get(k.state, guid, HERO_RECORD, row, tag)

    def _set(self, guid: Guid, row: int, tag: str, value) -> None:
        k = self.kernel
        k.state = k.store.record_set(k.state, guid, HERO_RECORD, row,
                                     tag, value)

    def _hero_rows(self, guid: Guid) -> List[int]:
        k = self.kernel
        return k.store.record_used_rows(k.state, guid, HERO_RECORD)

    def hero_row_of(self, guid: Guid, config_id: str) -> Optional[int]:
        """GetHeroGUID analog: find the hero row by config
        (NFCHeroModule.cpp:369-387)."""
        rows = self.kernel.store.record_find_rows(
            self.kernel.state, guid, HERO_RECORD, "ConfigID", config_id)
        return rows[0] if rows else None

    # ------------------------------------------------------- collection
    def add_hero(self, guid: Guid, config_id: str) -> Optional[int]:
        """Add a hero; a duplicate ConfigID stacks a star instead of a
        second row (card-stacking; see module docstring).  Skill/talent
        slots initialize from the hero element config when present."""
        k = self.kernel
        existing = self.hero_row_of(guid, config_id)
        if existing is not None:
            self.hero_star_up(guid, existing)
            return existing
        values = {"ConfigID": config_id, "Level": 1, "Exp": 0, "Star": 1}
        elems = k.elements
        if elems.exists(config_id):
            cfg = elems.element(config_id).values
            for slot in SKILL_SLOTS + TALENT_SLOTS:
                v = str(cfg.get(slot, "") or "")
                if v:
                    values[slot] = v
        try:
            k.state, row = k.store.record_add_row(
                k.state, guid, HERO_RECORD, values)
        except RuntimeError:
            return None
        return row

    def hero_level(self, guid: Guid, row: int) -> int:
        return int(self._get(guid, row, "Level"))

    def add_hero_exp(self, guid: Guid, row: int, exp: int) -> int:
        """Progressive curve: level N -> N+1 costs (N+1) x exp_per_level,
        capped at max_level (AddHeroExp, NFCHeroModule.cpp:72-127);
        returns the hero's new level (0 on a bad row/exp)."""
        if exp <= 0 or row not in self._hero_rows(guid):
            return 0
        level = self.hero_level(guid, row)
        total = int(self._get(guid, row, "Exp")) + exp
        while level < self.max_level:
            need = (level + 1) * self.exp_per_level
            if total < need:
                break
            total -= need
            level += 1
        self._set(guid, row, "Exp", total)
        self._set(guid, row, "Level", level)
        if row in self._fight_rows(guid).values():
            self._refresh_fight_stats(guid)
        return level

    def hero_star(self, guid: Guid, row: int) -> int:
        return int(self._get(guid, row, "Star"))

    def hero_star_up(self, guid: Guid, row: int) -> bool:
        """+1 star, capped (HeroStarUp, NFCHeroModule.cpp:129-161)."""
        if row not in self._hero_rows(guid):
            return False
        self._set(guid, row, "Star",
                  min(self.hero_star(guid, row) + 1, self.max_star))
        return True

    # -------------------------------------------------- skills / talents
    def _chain_up(self, guid: Guid, row: int, slot: str) -> bool:
        """Shared HeroSkillUp/HeroTalentUp shape: the slot's current
        element names its successor via AfterUpID
        (NFCHeroModule.cpp:162-250)."""
        if row not in self._hero_rows(guid):
            return False
        cur = str(self._get(guid, row, slot))
        elems = self.kernel.elements
        if not cur or not elems.exists(cur):
            return False
        nxt = str(elems.element(cur).values.get("AfterUpID", "") or "")
        if not nxt:
            return False  # already the best in the chain
        self._set(guid, row, slot, nxt)
        return True

    def hero_skill_up(self, guid: Guid, row: int, index: int) -> bool:
        if not 1 <= index <= len(SKILL_SLOTS):
            return False
        return self._chain_up(guid, row, SKILL_SLOTS[index - 1])

    def hero_talent_up(self, guid: Guid, row: int, index: int) -> bool:
        if not 1 <= index <= len(TALENT_SLOTS):
            return False
        return self._chain_up(guid, row, TALENT_SLOTS[index - 1])

    def hero_wear_skill(self, guid: Guid, row: int, skill_id: str) -> bool:
        """FightSkill must be one of the hero's owned Skill1-5
        (HeroWearSkill, NFCHeroModule.cpp:389-426)."""
        if row not in self._hero_rows(guid):
            return False
        owned = {str(self._get(guid, row, s)) for s in SKILL_SLOTS}
        if not skill_id or skill_id not in owned:
            return False
        self._set(guid, row, "FightSkill", skill_id)
        return True

    # -------------------------------------------------- battle line-up
    def _fight_rows(self, guid: Guid) -> Dict[int, int]:
        """fight position -> hero record row, from PlayerFightHero."""
        k = self.kernel
        cname, erow = k.store.row_of(guid)
        rec = k.state.classes[cname].records.get(FIGHT_RECORD)
        if rec is None:
            return {}
        rs = k.store.spec(cname).records[FIGHT_RECORD]
        used = np.asarray(rec.used[erow])
        hero_col = np.asarray(rec.i32[erow, :, rs.cols["HeroRow"].col])
        return {
            int(p): int(hero_col[p]) - 1
            for p in np.flatnonzero(used)
            if hero_col[p] > 0
        }

    def set_fight_hero(self, guid: Guid, row: int, pos: int = 0) -> bool:
        """Place a hero at a battle position (SetFightHero,
        NFCHeroModule.cpp:252-293); re-placing a position overwrites it."""
        if row not in self._hero_rows(guid):
            return False
        k = self.kernel
        cname, erow = k.store.row_of(guid)
        spec = k.store.spec(cname)
        if not 0 <= pos < spec.records[FIGHT_RECORD].rec.max_rows:
            return False
        rec = k.state.classes[cname].records[FIGHT_RECORD]
        if bool(np.asarray(rec.used[erow, pos])):
            k.state = k.store.record_set(k.state, guid, FIGHT_RECORD, pos,
                                         "HeroRow", row + 1)
        else:
            k.state = k.store.record_restore_row(
                k.state, guid, FIGHT_RECORD, pos,
                {"HeroRow": row + 1, "FightPos": pos})
        self._refresh_fight_stats(guid)
        return True

    def fight_hero(self, guid: Guid, pos: int = 0) -> Optional[int]:
        return self._fight_rows(guid).get(pos)

    def _refresh_fight_stats(self, guid: Guid) -> None:
        """Sum of every positioned hero's config stats x level into the
        FIGHTING_HERO group (NFCHeroPropertyModule recompute shape)."""
        k = self.kernel
        elems = k.elements
        totals = {n: 0 for n in STAT_NAMES}
        for row in set(self._fight_rows(guid).values()):
            config_id = str(self._get(guid, row, "ConfigID"))
            level = self.hero_level(guid, row)
            vals = (elems.element(config_id).values
                    if elems.exists(config_id) else {})
            for n in STAT_NAMES:
                totals[n] += int(vals.get(n, 0) or 0) * level
        for n in STAT_NAMES:
            self.properties.set_group_value(
                guid, n, PropertyGroup.FIGHTING_HERO, totals[n]
            )

    # ------------------------------------------------------- summoning
    def create_hero(self, guid: Guid, row: int) -> Optional[Guid]:
        """Summon the hero as an NPC entity in the owner's scene —
        CLONE scenes only, owner's camp, MasterID = owner (CreateHero,
        NFCHeroModule.cpp:295-337)."""
        if row not in self._hero_rows(guid):
            return None
        k = self.kernel
        scene = int(k.get_property(guid, "SceneID"))
        group = int(k.get_property(guid, "GroupID"))
        from .scene_process import SCENE_TYPE_CLONE

        if (self.scene_process is not None
                and self.scene_process.scene_type(scene) != SCENE_TYPE_CLONE):
            return None
        live = self._summons.get(guid, {}).get(row)
        if live is not None and live in k.store.guid_map:
            return None  # already summoned
        # a summon destroyed from outside destroy_hero (clone-group
        # release, combat death) must not block re-summoning
        self._summons.get(guid, {}).pop(row, None)
        config_id = str(self._get(guid, row, "ConfigID"))
        npc = k.create_object(
            "NPC",
            {
                "ConfigID": config_id,
                "Camp": int(k.get_property(guid, "Camp")),
                "MasterID": guid,
                "Position": tuple(k.get_property(guid, "Position")),
            },
            scene=scene, group=group,
        )
        self._summons.setdefault(guid, {})[row] = npc
        return npc

    def destroy_hero(self, guid: Guid, row: int) -> bool:
        """Unsummon (DestroyHero, NFCHeroModule.cpp:339-367)."""
        npc = self._summons.get(guid, {}).pop(row, None)
        if npc is None or npc not in self.kernel.store.guid_map:
            return False
        self.kernel.destroy_object(npc)
        return True

    # ------------------------------------------------- checkpoint/resume
    def checkpoint_state(self) -> dict:
        # line-up and heroes live in records; summons are transient
        return {}

    def restore_state(self, data: dict) -> None:
        self._summons = {}
        # legacy round-4 checkpoints carried a fight_hero dict; replay it
        # into the PlayerFightHero record at position 0
        from ..core.datatypes import Guid as _Guid

        for g, row in data.get("fight_hero", {}).items():
            guid = _Guid.parse(g)
            if guid in self.kernel.store.guid_map:
                self.set_fight_hero(guid, int(row), 0)
