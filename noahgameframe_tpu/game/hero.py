"""Hero system: collection, leveling, fight-hero stat contribution.

Reference: NFCHeroModule (`NFServer/NFGameLogicPlugin/NFCHeroModule.cpp`,
443 LoC) — AddHero dedupes by ConfigID into the PlayerHero record,
AddHeroExp levels the hero against the player's level cap, and switching
the fight hero re-applies its config+level stats to the owner (via
NFCHeroPropertyModule).  Here the fight hero's stats land in the
EQUIP_AWARD group row so the per-tick recompute folds them in.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..core.datatypes import Guid
from ..kernel.module import Module
from .defines import STAT_NAMES, PropertyGroup

HERO_RECORD = "PlayerHero"


class HeroModule(Module):
    name = "HeroModule"

    def __init__(self, properties, exp_per_level: int = 100) -> None:
        super().__init__()
        self.properties = properties  # game.stats.PropertyModule
        self.exp_per_level = exp_per_level
        self._fight_hero: Dict[Guid, int] = {}  # owner -> hero record row

    # ------------------------------------------------- checkpoint/resume
    def checkpoint_state(self) -> dict:
        return {"fight_hero": {str(g): row for g, row in self._fight_hero.items()}}

    def restore_state(self, data: dict) -> None:
        from ..core.datatypes import Guid as _Guid

        self._fight_hero = {
            _Guid.parse(g): int(row)
            for g, row in data.get("fight_hero", {}).items()
        }

    # ------------------------------------------------------- collection
    def add_hero(self, guid: Guid, config_id: str) -> Optional[int]:
        """Dedupe by ConfigID; returns the hero's record row."""
        k = self.kernel
        rows = k.store.record_find_rows(k.state, guid, HERO_RECORD,
                                        "ConfigID", config_id)
        if rows:
            return rows[0]
        try:
            k.state, row = k.store.record_add_row(
                k.state, guid, HERO_RECORD,
                {"ConfigID": config_id, "Level": 1, "Exp": 0, "Star": 1},
            )
        except RuntimeError:
            return None
        return row

    def hero_level(self, guid: Guid, row: int) -> int:
        return int(self.kernel.store.record_get(
            self.kernel.state, guid, HERO_RECORD, row, "Level"))

    def add_hero_exp(self, guid: Guid, row: int, exp: int) -> int:
        """Level against the owner's level cap (the reference caps hero
        level at player level); returns the hero's new level."""
        k = self.kernel
        cap = int(k.get_property(guid, "Level")) or 1
        level = self.hero_level(guid, row)
        total = int(k.store.record_get(k.state, guid, HERO_RECORD, row,
                                       "Exp")) + exp
        while level < cap and total >= self.exp_per_level:
            total -= self.exp_per_level
            level += 1
        k.state = k.store.record_set(k.state, guid, HERO_RECORD, row,
                                     "Exp", total)
        k.state = k.store.record_set(k.state, guid, HERO_RECORD, row,
                                     "Level", level)
        if self._fight_hero.get(guid) == row:
            self._refresh_fight_stats(guid)
        return level

    # ------------------------------------------------------- fight hero
    def set_fight_hero(self, guid: Guid, row: int) -> bool:
        k = self.kernel
        used = k.store.record_get(k.state, guid, HERO_RECORD, row, "ConfigID")
        if not used:
            return False
        self._fight_hero[guid] = row
        self._refresh_fight_stats(guid)
        return True

    def fight_hero(self, guid: Guid) -> Optional[int]:
        return self._fight_hero.get(guid)

    def _refresh_fight_stats(self, guid: Guid) -> None:
        """Config stats × level into the EQUIP_AWARD group
        (NFCHeroPropertyModule recompute shape)."""
        k = self.kernel
        row = self._fight_hero.get(guid)
        if row is None:
            return
        config_id = str(k.store.record_get(k.state, guid, HERO_RECORD, row,
                                           "ConfigID"))
        level = self.hero_level(guid, row)
        elems = k.elements
        vals = (elems.element(config_id).values
                if elems.exists(config_id) else {})
        for n in STAT_NAMES:
            base = int(vals.get(n, 0) or 0)
            self.properties.set_group_value(
                guid, n, PropertyGroup.EQUIP_AWARD, base * level
            )
