"""Combat: skill use, AoE damage resolution, NPC death & respawn.

Reference behavior being matched:
- NFCSkillModule::OnUseSkill — validate the skill element, damage the
  target (HP-10 floor 0) and stamp LastAttacker
  (NFCSkillModule.cpp:74-160, resolution :133-139).
- NFCNPCRefreshModule — watch HP; at <=0 fire ON_OBJECT_BE_KILLED with the
  LastAttacker and schedule a 5 s respawn heartbeat that restores the NPC
  from its seed/config (NFCNPCRefreshModule.cpp:115-135 and
  OnDeadDestroyHeart).

TPU inversion (BASELINE config 4's 1M-entity AoE resolve): all alive
entities are binned once into the cell-table (ops/stencil.py — one sort,
one scatter); every entity then PULLS incoming damage from the nine
dense-shifted neighbor blocks within the skill radius — a fused pairwise
masked reduction with zero gathers and zero scatter collisions — applies
`max(sum_atk - def, 0)`, picks the strongest in-range attacker as
LastAttacker, and the death sweep emits one batched BE_KILLED event and
arms device-side respawn (HP restored after `respawn_s`, keeping the row;
destroy-on-death is the host path via the event).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.datatypes import Guid
from ..core.store import HANDLE_ROW_BITS, WorldState, with_class
from ..kernel.module import Module
from ..ops.stencil import (
    auto_bucket,
    build_cell_slots_pair,
    build_cell_table_pair,
    pull_slots,
    slots_from_assignment,
    stencil_fold,
)
from ..ops.verlet import (
    full_table,
    init_cache,
    refresh,
    skin_from_env,
    sub_slots,
    sub_table,
)
from .defines import GameEvent

ATTACK_TIMER = "Attack"

# "no attacker" sentinel for the f32 best-row accumulator: 2^24, exactly
# representable and strictly above every representable row id (< 2^24).
# Deliberately finite — see combat_fold_closure.
NO_ROW = 16777216.0


def combat_fold_closure(v, radius: float):
    """(fold, init) over a victim grid view v [H, W, Kv, F+1] — the
    fold body shared by combat_fold_xla (square grids) and the spatial
    slab shards (rectangular grids with real halo rows,
    parallel/spatial.py), so mask semantics and tie-breaks cannot
    drift between the single-chip and distributed paths."""
    vx, vy = v[..., 0], v[..., 1]
    vcamp, vscene, vgroup = v[..., 2], v[..., 3], v[..., 4]
    r2 = float(radius) * float(radius)
    idt = jnp.int32
    f32 = jnp.float32

    def fold(acc, cand):
        inc, besta, bestr = acc
        cx = cand[:, :, None, :, 0]
        cy = cand[:, :, None, :, 1]
        ca = cand[:, :, None, :, 2]
        cc = cand[:, :, None, :, 3]
        cscene = cand[:, :, None, :, 4]
        cgroup = cand[:, :, None, :, 5]
        cr = cand[:, :, None, :, 6]
        dx = vx[..., None] - cx
        dy = vy[..., None] - cy
        ok = (
            (dx * dx + dy * dy <= r2)
            & (ca != 0)  # a real attacker (empty slots carry 0)
            & (cc != vcamp[..., None])  # no friendly fire (also self)
            & (cscene == vscene[..., None])  # same scene...
            & (cgroup == vgroup[..., None])  # ...and group
        )
        inc = inc + jnp.sum(jnp.where(ok, ca, 0.0), axis=-1).astype(idt)
        # strongest attacker; ties resolve to the GLOBAL minimum row id
        # among equal-max in-range attackers.  Min-row (not first-in-
        # stencil-order) makes the answer independent of which cell each
        # attacker is binned in, so Verlet-cached anchor binnings
        # (ops/verlet.py) produce bit-identical LastAttacker to a fresh
        # rebuild.  bestr accumulates as f32 (NO_ROW = none: a finite
        # sentinel, not +inf — an inf loop carry sends the XLA CPU
        # algebraic simplifier into a non-terminating rewrite cycle) and
        # the XLA / Pallas wrappers convert to int32 at the end.
        sa = jnp.where(ok, ca, -1.0)
        m = jnp.max(sa, axis=-1)
        first = jnp.min(jnp.where(sa >= m[..., None], cr, NO_ROW), axis=-1)
        # a shift with zero ok attackers has m == -1 and `first` reads the
        # min over raw row columns — poison; neutralize before comparing
        first = jnp.where(m >= 0.0, first, NO_ROW)
        # merge (m, first) into (besta, bestr) as a lexicographic
        # (max attack, min row) reduction.  Phrased so `bestr` is
        # consumed exactly ONCE per shift: a second use (e.g. an extra
        # tie-select `where(tie, minimum(bestr, first), bestr)`) makes
        # the XLA CPU compiler blow up super-linearly on the 9-shift
        # select chain (minutes -> never returns at width 48)
        top = jnp.maximum(besta, m)
        bestr = jnp.minimum(
            jnp.where(m >= top, first, NO_ROW),
            jnp.where(besta >= top, bestr, NO_ROW),
        )
        besta = top
        return inc, besta, bestr

    zeros = jnp.zeros(v.shape[:3], idt)
    init = (
        zeros,
        jnp.zeros(v.shape[:3], f32) - 1.0,
        jnp.full(v.shape[:3], NO_ROW, f32),
    )
    return fold, init


def combat_fold_xla(vic_table, att_table, radius):
    """The XLA stencil fold over the split victim/attacker cell tables:
    nine shifted candidate blocks against the resident victim grid, with
    [Kv, Ka] pairwise masked reductions fused by XLA onto the VPU.

    Same contract as ops.stencil_pallas.combat_fold_pallas — returns
    (inc [H, W, Kv] int32 damage totals, bestr [H, W, Kv] int32 row id
    of the strongest in-range attacker, -1 = none) — and the single
    source of truth for the fold's feature-column layout and tie-break
    semantics (scripts/profile_passes.py times this exact function).

    Victim payload columns: x, y, camp, scene, group (+occupancy).
    Attacker payload columns: x, y, eff_atk, camp, scene, group, row.
    No self-exclusion compare: self always shares its own camp, so the
    no-friendly-fire mask rules self out of every pair."""
    fold, init = combat_fold_closure(vic_table.grid_view(), radius)
    inc, _besta, bestr = stencil_fold(att_table, fold, init)
    # NO_ROW (no attacker) -> -1; row ids are exact in f32 (< 2^24)
    bestr = jnp.where(bestr >= NO_ROW, -1.0, bestr).astype(jnp.int32)
    return inc, bestr


class CombatModule(Module):
    """Batched AoE combat + death/respawn for one fighter class."""

    name = "CombatModule"

    def __init__(
        self,
        class_name: str = "NPC",
        extent: float = 512.0,
        radius: float = 4.0,
        cell_size: Optional[float] = None,
        bucket: Optional[int] = None,
        respawn_s: float = 5.0,
        attack_period_s: float = 1.0,
        order: int = 30,
        emit_events: bool = True,
        use_pallas: Optional[int] = None,
        verlet_skin: Optional[float] = None,
    ):
        super().__init__()
        self.class_name = class_name
        self.extent = float(extent)
        self.radius = float(radius)
        # Verlet skin (ops/verlet.py): None = NF_VERLET_SKIN env knob,
        # <= 0 = off (rebuild every tick, exactly the legacy path).  A
        # positive skin inflates the grid so the 3x3 stencil still covers
        # the true radius from positions up to skin/2 stale.
        self.verlet_skin = float(
            verlet_skin if verlet_skin is not None else skin_from_env()
        )
        self.cell_size = float(cell_size if cell_size is not None else max(radius, 1.0))
        if self.verlet_skin > 0.0:
            self.cell_size = max(self.cell_size, self.radius + self.verlet_skin)
        self.width = max(1, int(self.extent / self.cell_size))
        # None = size buckets from capacity/cell density at trace time so
        # overflow (entities silently missing combat) stays ~zero
        self.bucket = None if bucket is None else int(bucket)
        self.respawn_s = float(respawn_s)
        self.attack_period_s = float(attack_period_s)
        self.emit_events = emit_events
        # runtime overflow surfacing (round-4 verdict item 5): the tick
        # itself emits ON_COMBAT_TABLE_OVERFLOW; the module subscribes,
        # counts, logs on budget breach, and (auto_resize) doubles the
        # bucket + retraces so the drops stop — not just a bench number
        self.overflow_budget = 1e-4  # dropped/alive alert threshold
        self.auto_resize = True
        self.max_bucket_boost = 8
        self._bucket_boost = 1
        self.overflow_last = (0, 0)  # (victims, attackers) latest tick
        self.overflow_total = 0
        self.overflow_alerts = 0
        self._overflow_log_muted = False
        # tri-state Pallas engine selector (None = NF_PALLAS env knob):
        #   0/False  XLA stencil fold over split cell tables
        #   1/True   Pallas fold kernel over the same split tables
        #            (ops/stencil_pallas.combat_fold_pallas)
        #   2        fused table-free neighborhood engine: gather from
        #            the SoA bank via slot ranks, fold combat + AOI
        #            occupancy on-core, never materialize the payload
        #            tables (ops/stencil_pallas.fused_neighborhood).
        #            Downgrades to 0 when the tile footprint exceeds the
        #            VMEM budget (nf_pallas_fallback_total metric).
        # Opt-in until chip-time confirms a win.  (The stencil engine is
        # the only combat engine: at honest bucket sizes it beats the old
        # per-candidate-gather pipeline even on a single CPU core —
        # 103 ms vs 186 ms at 100k — and by ~25x on a v5e, where
        # irregular gathers run at ~1% of HBM bandwidth.)
        self.use_pallas = use_pallas
        # fraction of the population the attacker candidate table is sized
        # for; 1.0 (safe default) means "everyone could fire on one tick".
        # arm_all(stagger=True) lowers it to dt/attack_period — staggered
        # phases make instantaneous attacker density ~duty * population,
        # and the candidate table (the 9x-scanned side of the fold)
        # shrinks by the same factor.
        self._attacker_duty = 1.0
        self.add_phase("aoe", self._combat_phase, order=order)
        self.add_phase("death", self._death_phase, order=order + 5)

    # -- lifecycle -----------------------------------------------------------

    def init(self) -> None:
        # timer slots must exist before the world is built
        self.kernel.schedule.register_timer(self.class_name, ATTACK_TIMER)
        if self.verlet_skin > 0.0:
            # the Verlet cache rides WorldState.aux as carried tick state;
            # a zero cache forces a rebuild on the first tick, and
            # kernel.invalidate() (bucket boost, duty change) drops it so
            # slot assignments baked against stale geometry cannot leak
            self.kernel.register_aux(
                f"verlet/{self.class_name}",
                lambda: init_cache(self.kernel.store.capacity(self.class_name)),
            )

    def after_init(self) -> None:
        if self.emit_events:
            self.kernel.events.subscribe_batch(
                int(GameEvent.ON_COMBAT_TABLE_OVERFLOW), self._on_overflow
            )

    def execute(self) -> None:
        # the overflow event only fires on drops — reset the per-tick
        # reading each frame so a drop-free tick reads (0, 0) instead of
        # the last bad tick forever (module execute runs before the
        # kernel's device step + event dispatch in the same frame)
        self.overflow_last = (0, 0)

    def _on_overflow(self, cname: str, _mask, params) -> None:
        """Host side of the tick's overflow signal: count, alert on
        budget breach, and auto-resize (double the bucket + retrace) so
        combat drops stop instead of staying a silent bench-only number."""
        import logging

        dv = int(params["dropped_victims"][0])
        da = int(params["dropped_attackers"][0])
        self.overflow_last = (dv, da)
        self.overflow_total += dv + da
        alive = int(self.kernel.store._hosts[cname].alloc_mask.sum())
        if alive <= 0 or (dv + da) / alive <= self.overflow_budget:
            return
        self.overflow_alerts += 1
        log = logging.getLogger("nf.combat")
        if self.auto_resize and self._bucket_boost < self.max_bucket_boost:
            self._bucket_boost *= 2
            self.kernel.invalidate()  # bucket is baked into the trace
            log.warning(
                "combat cell-table overflow: dropped %d/%d victims+attackers "
                "(budget %.4f%%) — bucket boosted x%d, tick retracing",
                dv + da, alive, self.overflow_budget * 100,
                self._bucket_boost,
            )
        elif not self._overflow_log_muted:
            # keep alert COUNTERS per-tick, but log the terminal state
            # once — a pile-up would otherwise spam every tick
            self._overflow_log_muted = True
            log.warning(
                "combat cell-table overflow: dropped %d/%d victims+attackers "
                "(budget %.4f%%) — auto-resize %s; further breaches are "
                "counted (overflow_alerts) but not logged",
                dv + da, alive, self.overflow_budget * 100,
                "exhausted" if self.auto_resize else "disabled",
            )

    def arm_all(self, stagger: bool = True) -> None:
        """Arm the attack heartbeat on every live row (benchmark seeding).

        stagger=True spreads first firings evenly across the attack
        period (`1 + row % interval` ticks) — the batch equivalent of the
        reference arming each object's heartbeat at its own creation time
        (NFCScheduleModule AddSchedule at create).  Synchronized arming
        (stagger=False) makes every entity fire on the same tick, so the
        attacker candidate table must be sized for the full population."""
        import numpy as np

        k = self.kernel
        cs = k.state.classes[self.class_name]
        rows = np.flatnonzero(np.asarray(cs.alive))
        interval = k.schedule.ticks_of(self.attack_period_s)
        delays = 1 + (rows % interval) if (stagger and interval > 1) else None
        k.state = k.schedule.set_timer_rows(
            k.state, self.class_name, rows, ATTACK_TIMER, self.attack_period_s,
            start_delay_ticks=delays,
        )
        new_duty = (1.0 / interval) if delays is not None else 1.0
        if new_duty != self._attacker_duty:
            self._attacker_duty = new_duty
            # candidate-bucket size is baked into the traced tick
            k.invalidate()

    def resolved_bucket(self, capacity: int) -> int:
        """The victim cell-table bucket size the combat phase actually
        uses — shared with bench.py's overflow monitor so both stay in
        sync.  `_bucket_boost` doubles on an overflow-budget breach
        (auto-resize), bounded so a pathological pile-up cannot retrace
        toward capacity-sized buckets."""
        base = (
            self.bucket
            if self.bucket is not None
            else auto_bucket(capacity, self.width)
        )
        return min(int(base * self._bucket_boost), max(capacity, 1))

    def resolved_att_bucket(self, capacity: int) -> int:
        """The attacker candidate-table bucket size: sized for the
        instantaneous attacker density (capacity * duty), never larger
        than the victim bucket.  With staggered arming duty is
        dt/attack_period, so the 9x-scanned candidate side of the fold
        shrinks ~duty-fold while victims stay fully resident."""
        import math

        if self._attacker_duty >= 1.0:
            # synchronized arming: everyone can fire on one tick — the
            # candidate table must be exactly as deep as the victim table
            return self.resolved_bucket(capacity)
        eff = max(1, int(math.ceil(capacity * self._attacker_duty)))
        return min(
            auto_bucket(eff, self.width, lo=4, align=2) * self._bucket_boost,
            self.resolved_bucket(capacity),
        )

    def resolved_engine(self) -> int:
        """The combat engine this trace will bake in: 0 (XLA fold over
        split tables), 1 (Pallas fold, same tables) or 2 (fused
        table-free neighborhood).  `use_pallas` wins when set (bools keep
        their historical meaning: True == 1); otherwise NF_PALLAS decides.
        Unknown env values raise instead of silently running the default
        — a typo'd engine would invalidate any A/B it labeled (same
        contract as ops.stencil.binning_mode).  The VMEM-budget downgrade
        for engine 2 happens at the dispatch site, not here — this is the
        *requested* engine."""
        mode = self.use_pallas
        if mode is None:
            import os

            # nf-lint: disable=trace-safety -- sanctioned A/B knob:
            # trace-time read baked into the compiled fold; flipping
            # NF_PALLAS needs a fresh jit cache by design
            raw = os.environ.get("NF_PALLAS", "").strip()
            if raw in ("", "0", "1", "2"):
                return int(raw or "0")
            raise ValueError(
                f"NF_PALLAS={raw!r}: expected one of '', '0', '1', '2'"
            )
        mode = int(mode)
        if mode not in (0, 1, 2):
            raise ValueError(f"use_pallas={mode!r}: expected 0, 1 or 2")
        return mode

    # -- device phases -------------------------------------------------------

    def _combat_phase(self, state: WorldState, ctx) -> WorldState:
        cname = self.class_name
        store = ctx.store
        if cname not in store.class_index:
            return state
        spec = store.spec(cname)
        cs = state.classes[cname]
        pos = cs.vec[:, spec.slot("Position").col, :2]
        hp_col = spec.slot("HP").col
        hp = cs.i32[:, hp_col]
        atk = cs.i32[:, spec.slot("ATK_VALUE").col]
        deff = cs.i32[:, spec.slot("DEF_VALUE").col]
        camp = (
            cs.i32[:, spec.slot("Camp").col]
            if spec.has_property("Camp")
            else jnp.zeros_like(hp)
        )

        attacking = ctx.fired(cname, ATTACK_TIMER) & cs.alive & (hp > 0)
        if spec.has_property("SKILL_GATE"):
            attacking &= cs.i32[:, spec.slot("SKILL_GATE").col] == 0

        # combat is (scene, group)-scoped like every broadcast in the
        # reference (NFCSceneAOIModule::GetBroadCastObject) — entities at
        # overlapping coordinates in different cells never interact
        n = pos.shape[0]
        bucket = self.resolved_bucket(n)
        att_bucket = self.resolved_att_bucket(n)
        engine = self.resolved_engine()
        if engine == 2:
            from ..ops.stencil_pallas import (
                fused_fits_vmem,
                note_fused_fallback,
            )

            # host-side VMEM gate on the static geometry: an oversize
            # world (1M-entity bank alone outgrows a core's VMEM) must
            # fall back to the split-table path, not fail in Mosaic
            fits, need, budget_b = fused_fits_vmem(
                n, self.width, bucket, att_bucket
            )
            if not fits:
                note_fused_fallback(
                    f"{cname}: n={n} width={self.width} "
                    f"bucket={bucket}/{att_bucket}",
                    need, budget_b,
                )
                engine = 0
        # TWO tables: every alive entity is RESIDENT as a victim (K deep),
        # but only this tick's attackers ride the 9x-scanned candidate
        # side (K_att deep — with staggered attack phases K_att is
        # ~duty*K, which is where the fold's pairwise cost lives).  f32
        # carries each int column exactly for values < 2^24 (row <
        # capacity, atk, scene id, group id — scene and group ride in
        # separate columns so neither magnitude compounds); per-shift
        # damage sums stay < 2^24 because a shift has at most K_att
        # candidates, and the cross-shift total accumulates in exact
        # int32.  Entities beyond a cell's bucket are dropped from that
        # table for the tick (victim table: invisible AND invulnerable;
        # attacker table: the attack doesn't land) — `auto_bucket` keeps
        # both ~zero and CellTable.dropped counts them.
        f32 = jnp.float32
        rows_f = jnp.arange(n, dtype=f32)
        camp_f = camp.astype(f32)
        scene_f = cs.i32[:, spec.slot("SceneID").col].astype(f32)
        group_f = cs.i32[:, spec.slot("GroupID").col].astype(f32)
        # no explicit self-exclusion column: an entity always shares its
        # own camp, so the no-friendly-fire mask (cc != vcamp) already
        # rules self out of every pair.  (If friendly fire is ever
        # enabled, reintroduce a row compare here AND in the Pallas
        # kernel.)
        vic_feats = jnp.stack(
            [pos[:, 0], pos[:, 1], camp_f, scene_f, group_f],
            axis=-1,
        )
        eff_atk = jnp.where(attacking, atk, 0).astype(f32)
        att_feats = jnp.stack(
            [pos[:, 0], pos[:, 1], eff_atk, camp_f, scene_f, group_f, rows_f],
            axis=-1,
        )
        if self.verlet_skin > 0.0:
            # displacement-gated build (ops/verlet.py): the argsort only
            # runs when some entity drifted >= skin/2 from its binning
            # anchor (or the alive set changed); otherwise both payload
            # scatters (or, on the fused path, just the slot bookkeeping)
            # replay against the cached slot assignment.  The fold below
            # masks by TRUE radius on current positions, so results stay
            # bit-identical to rebuilding every tick.
            aux_key = f"verlet/{cname}"
            cache, rebuilt = refresh(
                state.aux[aux_key], pos, cs.alive,
                self.cell_size, self.width, bucket, self.verlet_skin,
            )
            n_cells = self.width * self.width
            if engine == 2:
                # slots only — the payload tables are never materialized
                vic_bin = slots_from_assignment(
                    cs.alive, cache.slot_of, n_cells,
                    self.cell_size, self.width, bucket,
                )
                att_bin = slots_from_assignment(
                    attacking, sub_slots(cache, attacking, n_cells, att_bucket),
                    n_cells, self.cell_size, self.width, att_bucket,
                )
            else:
                vic_bin = full_table(
                    cache, vic_feats, cs.alive, n_cells,
                    self.cell_size, self.width, bucket,
                )
                att_bin = sub_table(
                    cache, attacking, att_feats, n_cells,
                    self.cell_size, self.width, att_bucket,
                )
            ctx.count("grid_rebuilds", rebuilt)
            ctx.count("grid_reuses", 1 - rebuilt)
            ctx.count("grid_cache_age", cache.age)
            state = state.replace(aux={**state.aux, aux_key: cache})
        elif engine == 2:
            # one key pass feeds both slot assignments, no payloads
            vic_bin, att_bin = build_cell_slots_pair(
                pos, cs.alive, attacking,
                self.cell_size, self.width, bucket, att_bucket,
            )
        else:
            # one argsort feeds both tables (attackers subset of alive)
            vic_bin, att_bin = build_cell_table_pair(
                pos, cs.alive, vic_feats, attacking, att_feats,
                self.cell_size, self.width, bucket, att_bucket,
            )
        nbr = None
        if engine == 2:
            import jax

            from ..ops.stencil_pallas import fused_neighborhood

            # one shared SoA bank serves both sides of the fold; the
            # attacker row id is the gather index itself
            bank = jnp.stack(
                [pos[:, 0], pos[:, 1], camp_f, scene_f, group_f, eff_atk],
                axis=-1,
            )
            inc, bestr, nbr = fused_neighborhood(
                bank,
                vic_bin,
                att_bin,
                self.radius,
                # native lowering only on TPU-class backends; anything
                # else (cpu, gpu, metal) runs the kernel interpreted
                interpret=jax.default_backend() not in ("tpu", "axon"),
            )
        elif engine == 1:
            import jax

            from ..ops.stencil_pallas import combat_fold_pallas

            inc, bestr = combat_fold_pallas(
                vic_bin,
                att_bin,
                self.radius,
                interpret=jax.default_backend() not in ("tpu", "axon"),
            )
        else:
            inc, bestr = combat_fold_xla(vic_bin, att_bin, self.radius)
        if self.emit_events:
            # runtime overflow signal: the duty-sized attacker bucket is
            # baked into the traced tick, so arming patterns that
            # concentrate attackers into one residue class (e.g. a spawn
            # wave armed synchronously AFTER arm_all's staggered seeding)
            # would otherwise drop attacks silently.  Subscribe batch to
            # ON_COMBAT_TABLE_OVERFLOW to observe it; bench.py replays
            # the residue classes offline for the same number.
            total_drop = vic_bin.dropped + att_bin.dropped
            mask0 = jnp.zeros((n,), bool).at[0].set(total_drop > 0)
            ctx.emit(
                int(GameEvent.ON_COMBAT_TABLE_OVERFLOW),
                cname,
                mask0,
                dropped_victims=jnp.broadcast_to(vic_bin.dropped, (n,)),
                dropped_attackers=jnp.broadcast_to(att_bin.dropped, (n,)),
            )
        # counter bank (rides the summary fetch; always on, unlike the
        # emit_events-gated overflow event above)
        ctx.count("aoi_victim_overflow_drops", vic_bin.dropped)
        ctx.count("aoi_attacker_overflow_drops", att_bin.dropped)
        pulled = pull_slots(
            vic_bin.slot_of, jnp.stack([inc, bestr], axis=-1), fill=(0, -1)
        )
        if nbr is not None:
            # fused-path bonus output: the AOI/interest occupancy count
            # per entity (scope per ops.interest.scope_mask, self
            # excluded) — a counter, not state, so digests stay
            # bit-identical across engines
            ctx.count(
                "aoi_interest_pairs", pull_slots(vic_bin.slot_of, nbr, fill=0)
            )
        incoming = pulled[..., 0]
        # dead-but-not-yet-respawned victims take no damage
        incoming = jnp.where(cs.alive & (hp > 0), incoming, 0)
        dmg = jnp.maximum(incoming - deff, 0)
        dmg = jnp.where(incoming > 0, jnp.maximum(dmg, 1), 0)  # a hit always chips
        ctx.count("combat_hits", incoming > 0)
        ctx.count("combat_damage_total", dmg)
        new_hp = jnp.maximum(hp - dmg, 0)
        i32 = cs.i32.at[:, hp_col].set(new_hp)

        if spec.has_property("LastAttacker"):
            # strongest in-range attacker, packed as an object handle
            cls_idx = store.class_index[cname]
            best_row = pulled[..., 1]
            handle = (cls_idx << HANDLE_ROW_BITS) | jnp.maximum(best_row, 0)
            la_col = spec.slot("LastAttacker").col
            hit = incoming > 0
            i32 = i32.at[:, la_col].set(
                jnp.where(hit, handle, i32[:, la_col])
            )
        return with_class(state, cname, cs.replace(i32=i32))

    def _death_phase(self, state: WorldState, ctx) -> WorldState:
        cname = self.class_name
        store = ctx.store
        if cname not in store.class_index:
            return state
        spec = store.spec(cname)
        if not spec.has_property("DeadTick"):
            return state
        cs = state.classes[cname]
        hp_col = spec.slot("HP").col
        dead_col = spec.slot("DeadTick").col
        hp = cs.i32[:, hp_col]
        dead_tick = cs.i32[:, dead_col]

        just_died = cs.alive & (hp <= 0) & (dead_tick == 0)
        if self.emit_events:
            params = {}
            if spec.has_property("LastAttacker"):
                params["killer"] = cs.i32[:, spec.slot("LastAttacker").col]
            ctx.emit(int(GameEvent.ON_OBJECT_BE_KILLED), cname, just_died, **params)
        # DeadTick stores tick+1 so tick 0 deaths are distinguishable from 0
        i32 = cs.i32.at[:, dead_col].set(
            jnp.where(just_died, ctx.tick + 1, dead_tick)
        )

        respawn_ticks = max(1, int(round(self.respawn_s / ctx.dt)))
        due = (dead_tick > 0) & (ctx.tick + 1 - dead_tick >= respawn_ticks) & cs.alive
        if spec.has_property("MAXHP"):
            maxhp = cs.i32[:, spec.slot("MAXHP").col]
            # no MAXHP stat -> nothing to restore -> stay dead (otherwise
            # DeadTick would clear with HP still 0 and BE_KILLED would
            # re-fire every respawn interval forever)
            due &= maxhp > 0
            i32 = i32.at[:, hp_col].set(jnp.where(due, maxhp, i32[:, hp_col]))
        else:
            due &= False
        i32 = i32.at[:, dead_col].set(jnp.where(due, 0, i32[:, dead_col]))
        ctx.count("respawns", due)
        if self.emit_events:
            ctx.emit(int(GameEvent.ON_NPC_RESPAWN), cname, due)
        return with_class(state, cname, cs.replace(i32=i32))


class SkillModule(Module):
    """Host-side targeted skill use (reference NFCSkillModule parity)."""

    name = "SkillModule"

    def __init__(self, skill_damage: int = 10):
        super().__init__()
        self.skill_damage = int(skill_damage)

    def use_skill(self, attacker: Guid, skill_id: str, target: Guid) -> bool:
        """Validate the skill element, damage the target by 10 (floor 0),
        stamp LastAttacker (NFCSkillModule.cpp:113-139)."""
        k = self.kernel
        if not k.elements.exists(skill_id):
            return False
        if target not in k.store.guid_map:
            return False
        tclass, _ = k.store.row_of(target)
        cur = int(k.get_property(target, "HP"))
        if cur <= 0:
            return False
        if k.store.spec(tclass).has_property("LastAttacker"):
            k.set_property(target, "LastAttacker", attacker)
        k.set_property(target, "HP", max(cur - self.skill_damage, 0))
        return True
