"""Combat: skill use, AoE damage resolution, NPC death & respawn.

Reference behavior being matched:
- NFCSkillModule::OnUseSkill — validate the skill element, damage the
  target (HP-10 floor 0) and stamp LastAttacker
  (NFCSkillModule.cpp:74-160, resolution :133-139).
- NFCNPCRefreshModule — watch HP; at <=0 fire ON_OBJECT_BE_KILLED with the
  LastAttacker and schedule a 5 s respawn heartbeat that restores the NPC
  from its seed/config (NFCNPCRefreshModule.cpp:115-135 and
  OnDeadDestroyHeart).

TPU inversion (BASELINE config 4's 1M-entity AoE resolve): attackers whose
`Attack` timer fired are binned into the uniform grid (ops/aoi.py); every
entity then PULLS incoming damage from the 3x3-stencil candidates within
the skill radius — a gather-reduce with zero scatter collisions — applies
`max(sum_atk - def, 0)`, picks the strongest in-range attacker as
LastAttacker, and the death sweep emits one batched BE_KILLED event and
arms device-side respawn (HP restored after `respawn_s`, keeping the row;
destroy-on-death is the host path via the event).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.datatypes import Guid
from ..core.store import HANDLE_ROW_BITS, WorldState, with_class
from ..kernel.module import Module
from ..ops.aoi import build_grid, cell_of, neighbor_candidates
from .defines import GameEvent

ATTACK_TIMER = "Attack"


class CombatModule(Module):
    """Batched AoE combat + death/respawn for one fighter class."""

    name = "CombatModule"

    def __init__(
        self,
        class_name: str = "NPC",
        extent: float = 512.0,
        radius: float = 4.0,
        cell_size: Optional[float] = None,
        bucket: int = 8,
        respawn_s: float = 5.0,
        attack_period_s: float = 1.0,
        order: int = 30,
        emit_events: bool = True,
    ):
        super().__init__()
        self.class_name = class_name
        self.extent = float(extent)
        self.radius = float(radius)
        self.cell_size = float(cell_size if cell_size is not None else max(radius, 1.0))
        self.width = max(1, int(self.extent / self.cell_size))
        self.bucket = int(bucket)
        self.respawn_s = float(respawn_s)
        self.attack_period_s = float(attack_period_s)
        self.emit_events = emit_events
        self.add_phase("aoe", self._combat_phase, order=order)
        self.add_phase("death", self._death_phase, order=order + 5)

    # -- lifecycle -----------------------------------------------------------

    def init(self) -> None:
        # timer slots must exist before the world is built
        self.kernel.schedule.register_timer(self.class_name, ATTACK_TIMER)

    def arm_all(self) -> None:
        """Arm the attack heartbeat on every live row (benchmark seeding)."""
        import numpy as np

        k = self.kernel
        cs = k.state.classes[self.class_name]
        rows = np.flatnonzero(np.asarray(cs.alive))
        k.state = k.schedule.set_timer_rows(
            k.state, self.class_name, rows, ATTACK_TIMER, self.attack_period_s
        )

    # -- device phases -------------------------------------------------------

    def _combat_phase(self, state: WorldState, ctx) -> WorldState:
        cname = self.class_name
        store = ctx.store
        if cname not in store.class_index:
            return state
        spec = store.spec(cname)
        cs = state.classes[cname]
        pos = cs.vec[:, spec.slot("Position").col, :2]
        hp_col = spec.slot("HP").col
        hp = cs.i32[:, hp_col]
        atk = cs.i32[:, spec.slot("ATK_VALUE").col]
        deff = cs.i32[:, spec.slot("DEF_VALUE").col]
        camp = (
            cs.i32[:, spec.slot("Camp").col]
            if spec.has_property("Camp")
            else jnp.zeros_like(hp)
        )

        attacking = ctx.fired(cname, ATTACK_TIMER) & cs.alive & (hp > 0)
        if spec.has_property("SKILL_GATE"):
            attacking &= cs.i32[:, spec.slot("SKILL_GATE").col] == 0

        # combat is (scene, group)-scoped like every broadcast in the
        # reference (NFCSceneAOIModule::GetBroadCastObject) — entities at
        # overlapping coordinates in different cells never interact
        from ..kernel.scene import MAX_GROUPS_PER_SCENE

        cell_key = (
            cs.i32[:, spec.slot("SceneID").col] * MAX_GROUPS_PER_SCENE
            + cs.i32[:, spec.slot("GroupID").col]
        )

        grid = build_grid(pos, attacking, self.cell_size, self.width, self.bucket)
        qcell = cell_of(pos, self.cell_size, self.width)
        cand = neighbor_candidates(qcell, grid)  # [C, 9K]
        safe = jnp.maximum(cand, 0)
        d = pos[:, None, :] - pos[safe]
        in_range = jnp.sum(d * d, axis=-1) <= self.radius * self.radius
        valid = (
            (cand >= 0)
            & in_range
            & (cand != jnp.arange(pos.shape[0], dtype=jnp.int32)[:, None])
            & (camp[safe] != camp[:, None])  # no friendly fire
            & (cell_key[safe] == cell_key[:, None])  # same (scene, group)
            & cs.alive[:, None]
            & (hp[:, None] > 0)
        )
        incoming = jnp.sum(jnp.where(valid, atk[safe], 0), axis=-1)
        dmg = jnp.maximum(incoming - deff, 0)
        dmg = jnp.where(incoming > 0, jnp.maximum(dmg, 1), 0)  # a hit always chips
        new_hp = jnp.maximum(hp - dmg, 0)
        i32 = cs.i32.at[:, hp_col].set(new_hp)

        if spec.has_property("LastAttacker"):
            # strongest in-range attacker, packed as an object handle
            cls_idx = store.class_index[cname]
            masked_atk = jnp.where(valid, atk[safe], -1)
            best = jnp.argmax(masked_atk, axis=-1)
            best_row = jnp.take_along_axis(cand, best[:, None], axis=-1)[:, 0]
            handle = (cls_idx << HANDLE_ROW_BITS) | jnp.maximum(best_row, 0)
            la_col = spec.slot("LastAttacker").col
            hit = incoming > 0
            i32 = i32.at[:, la_col].set(
                jnp.where(hit, handle, i32[:, la_col])
            )
        return with_class(state, cname, cs.replace(i32=i32))

    def _death_phase(self, state: WorldState, ctx) -> WorldState:
        cname = self.class_name
        store = ctx.store
        if cname not in store.class_index:
            return state
        spec = store.spec(cname)
        if not spec.has_property("DeadTick"):
            return state
        cs = state.classes[cname]
        hp_col = spec.slot("HP").col
        dead_col = spec.slot("DeadTick").col
        hp = cs.i32[:, hp_col]
        dead_tick = cs.i32[:, dead_col]

        just_died = cs.alive & (hp <= 0) & (dead_tick == 0)
        if self.emit_events:
            params = {}
            if spec.has_property("LastAttacker"):
                params["killer"] = cs.i32[:, spec.slot("LastAttacker").col]
            ctx.emit(int(GameEvent.ON_OBJECT_BE_KILLED), cname, just_died, **params)
        # DeadTick stores tick+1 so tick 0 deaths are distinguishable from 0
        i32 = cs.i32.at[:, dead_col].set(
            jnp.where(just_died, ctx.tick + 1, dead_tick)
        )

        respawn_ticks = max(1, int(round(self.respawn_s / ctx.dt)))
        due = (dead_tick > 0) & (ctx.tick + 1 - dead_tick >= respawn_ticks) & cs.alive
        if spec.has_property("MAXHP"):
            maxhp = cs.i32[:, spec.slot("MAXHP").col]
            # no MAXHP stat -> nothing to restore -> stay dead (otherwise
            # DeadTick would clear with HP still 0 and BE_KILLED would
            # re-fire every respawn interval forever)
            due &= maxhp > 0
            i32 = i32.at[:, hp_col].set(jnp.where(due, maxhp, i32[:, hp_col]))
        else:
            due &= False
        i32 = i32.at[:, dead_col].set(jnp.where(due, 0, i32[:, dead_col]))
        if self.emit_events:
            ctx.emit(int(GameEvent.ON_NPC_RESPAWN), cname, due)
        return with_class(state, cname, cs.replace(i32=i32))


class SkillModule(Module):
    """Host-side targeted skill use (reference NFCSkillModule parity)."""

    name = "SkillModule"

    def __init__(self, skill_damage: int = 10):
        super().__init__()
        self.skill_damage = int(skill_damage)

    def use_skill(self, attacker: Guid, skill_id: str, target: Guid) -> bool:
        """Validate the skill element, damage the target by 10 (floor 0),
        stamp LastAttacker (NFCSkillModule.cpp:113-139)."""
        k = self.kernel
        if not k.elements.exists(skill_id):
            return False
        if target not in k.store.guid_map:
            return False
        tclass, _ = k.store.row_of(target)
        cur = int(k.get_property(target, "HP"))
        if cur <= 0:
            return False
        if k.store.spec(tclass).has_property("LastAttacker"):
            k.set_property(target, "LastAttacker", attacker)
        k.set_property(target, "HP", max(cur - self.skill_damage, 0))
        return True
