"""GameWorld: one-call assembly of the standard game stack.

The reference assembles a Game server from Plugin.xml: Kernel + Config +
GameServerPlugin (property/level/scene modules) + GameLogicPlugin
(skill/NPC modules) loaded into one NFCPluginManager
(_Out/Debug/Plugin.xml).  GameWorld is that composition as a library call,
plus the benchmark scenario builders used by bench.py and the BASELINE
configs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from ..core.schema import ClassRegistry
from ..core.store import StoreConfig
from ..kernel.component import ComponentModule
from ..kernel.kernel import Kernel
from ..kernel.plugin import Plugin, PluginManager
from ..kernel.scene import SceneModule
from .buff import BuffModule
from .combat import CombatModule, SkillModule
from .hero import HeroModule
from .items import EquipModule, ItemModule, PackModule
from .social import (
    FriendModule,
    GmModule,
    GuildModule,
    MailModule,
    PvpMatchModule,
    RankModule,
    ShopModule,
    TeamModule,
)
from .task import TaskModule
from .defines import COMM_PROPERTY_RECORD, PropertyGroup, STAT_NAMES
from .level import LevelModule
from .movement import MovementModule
from .scene_process import SCENE_TYPE_CLONE, SCENE_TYPE_NORMAL, SceneProcessModule  # noqa: F401
from .slg import SLGBuildingModule, SLGShopModule
from .property_config import PropertyConfigModule
from .regen import RegenModule
from .schema import standard_registry
from .stats import PropertyModule


@dataclasses.dataclass
class WorldConfig:
    npc_capacity: int = 1024
    player_capacity: int = 64
    extent: float = 512.0
    dt: float = 1.0 / 30.0
    seed: int = 0
    aoe_radius: float = 4.0
    aoi_bucket: Optional[int] = None  # None = auto-size from density
    respawn_s: float = 5.0
    attack_period_s: float = 1.0
    regen_period_s: float = 1.0
    combat: bool = True
    movement: bool = True
    regen: bool = True
    # Verlet skin for the combat grid (ops/verlet.py); None defers to the
    # NF_VERLET_SKIN env knob, <= 0 disables (rebuild every tick)
    verlet_skin: Optional[float] = None
    middleware: bool = True  # items/hero/task/buff stack
    # private is included so owner-only state (EXP, Gold, bag counters)
    # reaches its own client (GetBroadCastObject: Private -> self)
    diff_flags: tuple = ("public", "private", "upload")
    # config-selected spatial placement: a parallel.SpatialPlacement makes
    # GameWorld attach the full-row cross-shard migration phase (the
    # unified mesh engine); None keeps the world single-shard
    placement: Optional["object"] = None


class GameWorld:
    """The assembled standard stack; `.pm` is the plugin manager."""

    def __init__(self, config: Optional[WorldConfig] = None, registry: Optional[ClassRegistry] = None):
        self.config = cfg = config or WorldConfig()
        reg = registry or standard_registry()
        self.kernel = Kernel(
            reg,
            StoreConfig(
                default_capacity=64,
                capacities={
                    "NPC": cfg.npc_capacity,
                    "Player": cfg.player_capacity,
                    "IObject": 8,
                    "InitProperty": 8,
                    "Scene": 8,
                },
            ),
            dt=cfg.dt,
            seed=cfg.seed,
            diff_flags=cfg.diff_flags,
        )
        self.scene = SceneModule()
        self.scene_process = SceneProcessModule(self.scene)
        self.components = ComponentModule()
        self.property_config = PropertyConfigModule()
        self.properties = PropertyModule()
        self.level = LevelModule(self.property_config, self.properties)
        self.skills = SkillModule()
        modules = [self.kernel, self.scene, self.scene_process, self.components, self.property_config, self.properties, self.level, self.skills]
        self.pack = self.items = self.equip = self.heroes = self.tasks = None
        self.buffs = self.team = self.mail = self.rank = self.shop = None
        self.friends = self.guilds = self.gm = self.pvp = None
        self.slg_building = self.slg_shop = None
        if cfg.middleware:
            self.pack = PackModule()
            self.items = ItemModule(self.pack)
            self.equip = EquipModule(self.pack, self.properties)
            self.heroes = HeroModule(self.properties)
            self.heroes.scene_process = self.scene_process
            self.items.heroes = self.heroes
            self.items.level = self.level
            self.items.equip = self.equip
            self.equip.items = self.items
            self.tasks = TaskModule(self.level)
            self.buffs = BuffModule()
            self.team = TeamModule()
            self.mail = MailModule(self.pack)
            self.rank = RankModule()
            self.shop = ShopModule(self.pack)
            self.friends = FriendModule()
            self.guilds = GuildModule()
            self.gm = GmModule(self.level, self.pack)
            self.pvp = PvpMatchModule()
            self.slg_building = SLGBuildingModule(self.pack)
            self.slg_shop = SLGShopModule(self.pack, self.slg_building)
            modules += [self.pack, self.items, self.equip, self.heroes,
                        self.tasks, self.buffs, self.team, self.mail,
                        self.rank, self.shop, self.friends, self.guilds,
                        self.gm, self.pvp, self.slg_building, self.slg_shop]
        self.movement = None
        self.combat = None
        self.regen = None
        if cfg.movement:
            self.movement = MovementModule(extent=cfg.extent)
            modules.append(self.movement)
        if cfg.combat:
            self.combat = CombatModule(
                extent=cfg.extent,
                radius=cfg.aoe_radius,
                bucket=cfg.aoi_bucket,
                respawn_s=cfg.respawn_s,
                attack_period_s=cfg.attack_period_s,
                verlet_skin=cfg.verlet_skin,
            )
            modules.append(self.combat)
        if cfg.regen:
            self.regen = RegenModule(period_s=cfg.regen_period_s)
            modules.append(self.regen)
        self.migration = None
        if cfg.placement is not None:
            from ..parallel.rowmigrate import RowMigrationModule

            self.migration = RowMigrationModule(cfg.placement)
            modules.append(self.migration)
        # observability: registry + tracer + census, kernel-attached via
        # the pm lifecycle (after_init runs post kernel.build)
        from ..telemetry import TelemetryModule

        self.telemetry = TelemetryModule()
        modules.append(self.telemetry)

        # elastic mesh surface (parallel/elastic.py): populated by
        # .shard(); None keeps the world single-device
        self.sharded = None
        self.elastic = None

        self._rng = np.random.default_rng(cfg.seed)
        self.pm = PluginManager(app_name="game")
        self.pm.register_plugin(Plugin("KernelPlugin", [self.kernel]))
        self.pm.register_plugin(Plugin("ConfigPlugin", [self.property_config]))
        self.pm.register_plugin(
            Plugin("GameServerPlugin", [m for m in modules if m not in (self.kernel, self.property_config)])
        )

    def start(self) -> "GameWorld":
        self.pm.start()
        return self

    @property
    def all_modules(self):
        """Every registered module — the `modules` argument for
        persist.checkpoint save_world/load_world so host state (teams,
        guilds, mail, ranks, buff defs) survives a resume."""
        return list(self.pm.modules.values())

    def shard(self, n_devices: Optional[int] = None, mesh=None,
              ident_cols: Optional[Dict[str, int]] = None,
              exodus_tick_bound: int = 256, autoscaler=None):
        """Place the built world onto a device mesh and attach the
        elastic grow/drain driver.  With a config placement attached,
        the mesh defaults to the migration module's (they must agree —
        the migrate phase shard_maps over the same device set the state
        lives on); an explicit different width retargets the placement.
        Returns the :class:`~..parallel.elastic.ElasticMesh`."""
        import dataclasses as _dc

        from ..parallel.elastic import ElasticMesh
        from ..parallel.mesh import make_mesh
        from ..parallel.shard import ShardedKernel

        if mesh is None:
            if n_devices is None and self.migration is not None:
                mesh = self.migration.mesh
            else:
                mesh = make_mesh(n_devices)
        if self.migration is not None and mesh is not self.migration.mesh:
            self.migration.retarget(
                placement=_dc.replace(self.migration.placement,
                                      n_shards=int(mesh.devices.size)),
                mesh=mesh,
            )
        self.sharded = ShardedKernel(self.kernel, mesh=mesh)
        self.sharded.place()
        self.elastic = ElasticMesh(
            self.sharded, migration=self.migration,
            registry=self.telemetry.registry, ident_cols=ident_cols,
            exodus_tick_bound=exodus_tick_bound, autoscaler=autoscaler,
        )
        return self.elastic

    def save(self, path) -> None:
        from ..persist.checkpoint import save_world

        save_world(self.kernel, path, modules=self.all_modules)

    def load(self, path) -> None:
        from ..persist.checkpoint import load_world

        load_world(self.kernel, path, modules=self.all_modules)
        if self.sharded is not None:
            # cross-engine restore: the snapshot may come from a mesh of
            # any width (load_world leaves arrays uncommitted on the
            # default device) — drop every trace/cache and re-place the
            # restored state through world_shardings on the CURRENT mesh
            self.sharded.reshard(cause="snapshot_load")

    # -- seeding --------------------------------------------------------------

    def seed_npcs(
        self,
        n: int,
        scene: int = 1,
        group: int = 0,
        hp: int = 100,
        atk: int = 12,
        deff: int = 3,
        regen: int = 2,
        move_speed: int = 30000,
        camps: int = 2,
        rng: Optional[np.random.Generator] = None,
    ) -> None:
        """Bulk-spawn n NPCs with randomized positions/camps — the NPC seed
        spawning of scene groups (NFCSceneAOIModule RequestEnterScene) at
        benchmark scale."""
        # the world-owned generator advances across calls — two waves must
        # not land on identical coordinates
        r = rng or self._rng
        ext = self.config.extent
        pos = r.uniform(0.0, ext, (n, 3)).astype(np.float32)
        pos[:, 2] = 0.0
        k = self.kernel
        values = {
            "SceneID": np.full(n, scene, np.int64).tolist(),
            "GroupID": np.full(n, group, np.int64).tolist(),
            "Position": [tuple(p) for p in pos],
            "TargetPos": [tuple(p[:2]) for p in r.uniform(0.0, ext, (n, 2)).astype(np.float32)],
            "HP": [hp] * n,
            "Camp": r.integers(0, camps, n).tolist(),
        }
        k.state, guids, rows = k.store.create_many(k.state, "NPC", n, values=values)
        # combat stats go through the EFFECTVALUE group of the stat record —
        # the recompute phase is the single source of truth for final stats
        # (reference NPCs likewise get theirs from the EffectData config,
        # NFCNPCRefreshModule.cpp:83-96)
        k.state = k.store.record_write_rows(
            k.state,
            "NPC",
            rows,
            COMM_PROPERTY_RECORD,
            int(PropertyGroup.EFFECTVALUE),
            {
                "MAXHP": [hp] * n,
                "HPREGEN": [regen] * n,
                "ATK_VALUE": [atk] * n,
                "DEF_VALUE": [deff] * n,
                "MOVE_SPEED": [move_speed] * n,
            },
        )
        if self.combat is not None:
            self.combat.arm_all()
        if self.regen is not None:
            self.regen.arm_all("NPC")

    def tick(self):
        self.pm.run_once()

    def run(self, frames: int) -> None:
        self.pm.run(frames)


def build_benchmark_world(
    n_npcs: int,
    extent: Optional[float] = None,
    combat: bool = True,
    seed: int = 0,
    attack_period_s: float = 1.0,
    player_capacity: int = 64,
) -> GameWorld:
    """The staged BASELINE configs: density held at ~0.4 NPCs per world
    unit² so AOI cost scales with N, not with density.  `player_capacity`
    sizes the Player bank for served-path runs (bench.py --served seats
    one live avatar per simulated session)."""
    if extent is None:
        extent = max(64.0, float(np.sqrt(n_npcs / 0.4)))
    cap = 1 << int(np.ceil(np.log2(max(n_npcs, 64))))
    w = GameWorld(
        WorldConfig(
            npc_capacity=cap,
            extent=extent,
            combat=combat,
            seed=seed,
            attack_period_s=attack_period_s,
            middleware=False,
            player_capacity=player_capacity,
        )
    )
    w.start()
    w.scene.create_scene(1, width=extent)
    w.seed_npcs(n_npcs)
    return w
