"""Shared gameplay constants: stat names, property groups, event ids.

Reference equivalents: the NPG_* property-group enum
(NFIPropertyModule.h:19-29), the CommPropertyValue stat column set
(_Out/NFDataCfg/Struct/Class/Player.xml, Record Id="CommPropertyValue"),
and the NFEventDefine event-id space (NFComm/NFPluginModule/NFEventDefine.h).
"""

from __future__ import annotations

import enum


class PropertyGroup(enum.IntEnum):
    """Stat contribution groups; the final stat is the sum over groups
    (reference NFIPropertyModule.h:19-29, summed in
    NFCPropertyModule::OnRecordPropertyEvent)."""

    JOBLEVEL = 0
    EFFECTVALUE = 1
    REBIRTH_ADD = 2
    EQUIP = 3
    EQUIP_AWARD = 4
    STATIC_BUFF = 5
    RUNTIME_BUFF = 6
    # the reference sums NINE contribution groups
    # (NFCPropertyModule.cpp:193-240); these two complete the set.
    # NEVER renumber 0-6: saved records and test fixtures index by row.
    FIGHTING_HERO = 7  # the active hero lineup's stat fold (game/hero.py)
    TALENT = 8
    ALL = 9  # row count, not a row


# the combat/consumable stat block every fighter carries — column order of
# the CommPropertyValue record (Player.xml CommPropertyValue cols)
STAT_NAMES = (
    "SUCKBLOOD",
    "REFLECTDAMAGE",
    "CRITICAL",
    "MAXHP",
    "MAXMP",
    "MAXSP",
    "HPREGEN",
    "SPREGEN",
    "MPREGEN",
    "ATK_VALUE",
    "DEF_VALUE",
    "MOVE_SPEED",
    "ATK_SPEED",
    "ATK_FIRE",
    "ATK_LIGHT",
    "ATK_WIND",
    "ATK_ICE",
    "ATK_POISON",
    "DEF_FIRE",
    "DEF_LIGHT",
    "DEF_WIND",
    "DEF_ICE",
    "DEF_POISON",
    "DIZZY_GATE",
    "MOVE_GATE",
    "SKILL_GATE",
    "PHYSICAL_GATE",
    "MAGIC_GATE",
    "BUFF_GATE",
)

COMM_PROPERTY_RECORD = "CommPropertyValue"


class NpcType(enum.IntEnum):
    """NFMsg::ENPCType (NFMsgBase.proto)."""

    NORMAL = 0
    HERO = 1
    TURRET = 2
    FUNC = 3


class GameEvent(enum.IntEnum):
    """Framework gameplay event ids (reference NFEventDefine.h names; the
    numeric values are ours — the reference never pins them on the wire)."""

    ON_OBJECT_BE_KILLED = 1
    ON_LEVEL_UP = 2
    ON_NPC_RESPAWN = 3
    ON_USE_SKILL_RESULT = 4
    # fired (mask on row 0) when the combat cell-tables dropped entities
    # this tick — a runtime signal that bucket sizing no longer matches
    # density (params: dropped_victims / dropped_attackers counts)
    ON_COMBAT_TABLE_OVERFLOW = 5


class ItemType(enum.IntEnum):
    """Top-level item families (reference EItemType,
    NFDefine.proto:341-348)."""

    EQUIP = 0
    GEM = 1
    ITEM = 2
    CARD = 3
    TOKEN = 4


class ItemSubType(enum.IntEnum):
    """Consumable sub-kinds (reference EGameItemSubType,
    NFDefine.proto:378-385)."""

    WATER = 0
    DIAMOND = 1
    CURRENCY = 2
    EXP = 3
    HP = 4
    MP = 5
    SP = 6
    PACK = 7


class EShopType(enum.IntEnum):
    """SLG shop catalogue types (reference EShopType,
    NFDefine.proto:462-472)."""

    BUILDING = 1
    GOLD = 2
    DIAMOND = 3
    SP = 4
    EQUIP = 5
    GEM = 6
    HERO = 7
    OTHER = 8


class SLGBuildingType(enum.IntEnum):
    """Building families (reference EBuildingType, NFSLGDefine.proto).
    Single source of truth — net/wire_families re-exports this."""

    BASE = 0
    DEFENSE = 1
    ARMY = 2
    RESOURCE = 3
    GUILD = 4
    TEMPLE = 5
    NUCLEAR = 6


class SLGBuildingState(enum.IntEnum):
    """Building state machine (reference EBuildingState,
    NFSLGDefine.proto — EBS_IDLE/BOOST/UPGRADE).  Single source of
    truth — net/wire_families re-exports this."""

    IDLE = 0
    BOOST = 1
    UPGRADE = 2


class TaskState(enum.IntEnum):
    """Task lifecycle (reference ETaskState, NFDefine.proto:432-438)."""

    IN_PROCESS = 0
    DONE = 1
    DRAW_AWARD = 2
    FINISH = 3
