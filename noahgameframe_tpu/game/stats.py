"""Property module: stat groups, derived-stat recompute, HP/MP/SP/wallet.

Reference: NFCPropertyModule keeps per-player stat *contributions* in the
CommPropertyValue record (one row per NPG_* group) and, on every record
write, folds the column sum into the final property of the same name
(NFCPropertyModule.cpp:128-150); level changes refresh the NPG_JOBLEVEL row
from the per-(job,level) config and refill HP/MP/SP
(OnObjectLevelEvent/RefreshBaseProperty, :117-125, 193-240).

TPU inversion: contributions live in the record bank `[C, NPG_ALL, S]`
already, so the whole class's recompute is ONE sum over the group axis and
ONE scatter into the property columns, fused into the tick.  The recompute
phase runs unconditionally each tick (cheaper than tracking dirtiness at
[C] granularity — it's a [C, 9, 29] int32 reduce over the reference's
nine NPG_* contribution groups, trivially MXU/VPU friendly); host
mutators mirror the reference's imperative API for control-plane use.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.datatypes import Guid
from ..core.store import WorldState, with_class
from ..kernel.kernel import ObjectEvent
from ..kernel.module import Module
from .defines import COMM_PROPERTY_RECORD, PropertyGroup, STAT_NAMES
from .property_config import PropertyConfigModule

STAT_ORDER = {n: i for i, n in enumerate(STAT_NAMES)}


class PropertyModule(Module):
    """Derived-stat recompute for every class that carries the
    CommPropertyValue record (Player, NPC)."""

    name = "PropertyModule"

    def __init__(self, classes: Sequence[str] = ("Player", "NPC"), order: int = 60):
        super().__init__()
        self.classes = tuple(classes)
        self._stat_cols: Dict[str, np.ndarray] = {}  # class -> i32 prop cols per stat
        self._rec_cols: Dict[str, np.ndarray] = {}  # class -> record i32 cols per stat
        self.add_phase("recompute", self._recompute_phase, order=order)

    # -- wiring --------------------------------------------------------------

    def after_init(self) -> None:
        store = self.kernel.store
        for cname in self.classes:
            if cname not in store.class_index:
                continue
            spec = store.spec(cname)
            if COMM_PROPERTY_RECORD not in spec.records:
                continue
            rs = spec.records[COMM_PROPERTY_RECORD]
            self._stat_cols[cname] = np.asarray(
                [spec.slot(n).col for n in STAT_NAMES], np.int32
            )
            self._rec_cols[cname] = np.asarray(
                [rs.cols[n].col for n in STAT_NAMES], np.int32
            )

    # -- the device phase ----------------------------------------------------

    def _recompute_phase(self, state: WorldState, ctx) -> WorldState:
        for cname, prop_cols in self._stat_cols.items():
            cs = state.classes[cname]
            rec = cs.records[COMM_PROPERTY_RECORD]
            # [C, NPG_ALL, S_rec] -> [C, S_rec]; unused rows are zero-filled
            # so summing all rows is exact
            totals = jnp.sum(rec.i32, axis=1, dtype=jnp.int32)
            rec_cols = self._rec_cols[cname]
            cs = cs.replace(i32=cs.i32.at[:, prop_cols].set(totals[:, rec_cols]))
            state = with_class(state, cname, cs)
        return state

    # -- group mutation (host control plane, reference API parity) ----------

    def set_group_value(
        self, guid: Guid, stat: str, group: PropertyGroup, value: int
    ) -> None:
        k = self.kernel
        k.state = k.store.record_set(
            k.state, guid, COMM_PROPERTY_RECORD, int(group), stat, int(value)
        )

    def get_group_value(self, guid: Guid, stat: str, group: PropertyGroup) -> int:
        k = self.kernel
        return int(
            k.store.record_get(k.state, guid, COMM_PROPERTY_RECORD, int(group), stat)
        )

    def add_group_value(
        self, guid: Guid, stat: str, group: PropertyGroup, value: int
    ) -> None:
        self.set_group_value(
            guid, stat, group, self.get_group_value(guid, stat, group) + int(value)
        )

    def sub_group_value(
        self, guid: Guid, stat: str, group: PropertyGroup, value: int
    ) -> None:
        self.add_group_value(guid, stat, group, -int(value))

    def refresh_base_property(self, guid: Guid, config: PropertyConfigModule) -> None:
        """Write the (job, level) base-stat row into NPG_JOBLEVEL
        (reference RefreshBaseProperty)."""
        k = self.kernel
        job = int(k.get_property(guid, "Job"))
        level = int(k.get_property(guid, "Level"))
        for stat in STAT_NAMES:
            self.set_group_value(
                guid,
                stat,
                PropertyGroup.JOBLEVEL,
                config.calculate_base_value(job, level, stat),
            )

    def recompute_now(self, guid: Guid) -> None:
        """Immediate host-side fold of the group sums into the final
        properties, for callers that need read-after-write before the next
        tick (the device phase keeps everyone consistent each frame)."""
        k = self.kernel
        cname, row = k.store.row_of(guid)
        rec = k.state.classes[cname].records[COMM_PROPERTY_RECORD]
        totals = np.asarray(jnp.sum(rec.i32[row], axis=0, dtype=jnp.int32))
        for stat in STAT_NAMES:
            rcol = self._rec_cols[cname][STAT_ORDER[stat]]
            k.set_property(guid, stat, int(totals[rcol]))

    # -- HP/MP/SP + wallet (reference NFIPropertyModule API) ----------------

    def full_hp_mp(self, guid: Guid) -> None:
        k = self.kernel
        for cur, mx in (("HP", "MAXHP"), ("MP", "MAXMP")):
            m = int(k.get_property(guid, mx))
            if m > 0:
                k.set_property(guid, cur, m)

    def full_sp(self, guid: Guid) -> None:
        k = self.kernel
        m = int(k.get_property(guid, "MAXSP"))
        if m > 0:
            k.set_property(guid, "SP", m)

    def _add(self, guid: Guid, prop: str, maxprop: Optional[str], value: int) -> bool:
        if value <= 0:
            return False
        k = self.kernel
        cur = int(k.get_property(guid, prop))
        if maxprop is not None:
            if cur <= 0:
                return True  # reference AddHP no-ops on dead entities
            cur = min(cur + value, int(k.get_property(guid, maxprop)))
        else:
            cur += value
        k.set_property(guid, prop, cur)
        return True

    def _consume(self, guid: Guid, prop: str, value: int) -> bool:
        k = self.kernel
        cur = int(k.get_property(guid, prop))
        if value <= 0 or cur < value:
            return False
        k.set_property(guid, prop, cur - value)
        return True

    def _enough(self, guid: Guid, prop: str, value: int) -> bool:
        return int(self.kernel.get_property(guid, prop)) >= value > 0

    def add_hp(self, g: Guid, v: int) -> bool:
        return self._add(g, "HP", "MAXHP", int(v))

    def consume_hp(self, g: Guid, v: int) -> bool:
        return self._consume(g, "HP", int(v))

    def enough_hp(self, g: Guid, v: int) -> bool:
        return self._enough(g, "HP", int(v))

    def add_mp(self, g: Guid, v: int) -> bool:
        return self._add(g, "MP", "MAXMP", int(v))

    def consume_mp(self, g: Guid, v: int) -> bool:
        return self._consume(g, "MP", int(v))

    def enough_mp(self, g: Guid, v: int) -> bool:
        return self._enough(g, "MP", int(v))

    def add_sp(self, g: Guid, v: int) -> bool:
        return self._add(g, "SP", "MAXSP", int(v))

    def consume_sp(self, g: Guid, v: int) -> bool:
        return self._consume(g, "SP", int(v))

    def enough_sp(self, g: Guid, v: int) -> bool:
        return self._enough(g, "SP", int(v))

    def add_money(self, g: Guid, v: int) -> bool:
        return self._add(g, "Gold", None, int(v))

    def consume_money(self, g: Guid, v: int) -> bool:
        return self._consume(g, "Gold", int(v))

    def enough_money(self, g: Guid, v: int) -> bool:
        return self._enough(g, "Gold", int(v))

    def add_diamond(self, g: Guid, v: int) -> bool:
        return self._add(g, "Money", None, int(v))

    def consume_diamond(self, g: Guid, v: int) -> bool:
        return self._consume(g, "Money", int(v))

    def enough_diamond(self, g: Guid, v: int) -> bool:
        return self._enough(g, "Money", int(v))
