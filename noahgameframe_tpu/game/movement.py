"""Movement module: batched random-walk / seek steering on device.

Reference NPCs move by writing TargetX/TargetY and letting client-side
interpolation play out; server-side movement is property writes on a
heartbeat (Class/NPC.xml MoveType, NFCNPCRefreshModule).  Here movement is
a device phase over the whole class: seek the TargetPos at MOVE_SPEED, and
when within one step (or on first activation) pick a fresh uniform target
inside the scene extent from the per-tick PRNG stream — BASELINE config 2's
100k-NPC random walk is exactly this phase.

MOVE_SPEED follows the reference's convention of 10000 = 1 m/s
(Class/NPC.xml MOVE_SPEED Desc); MOVE_GATE (stun/root) zeroes movement.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.store import WorldState, with_class
from ..kernel.module import Module

SPEED_UNIT = 10000.0  # reference convention: MOVE_SPEED 10000 == 1 world unit/s


class MovementModule(Module):
    name = "MovementModule"

    def __init__(
        self,
        class_name: str = "NPC",
        extent: float = 512.0,
        order: int = 20,
        respect_gates: bool = True,
    ):
        super().__init__()
        self.class_name = class_name
        self.extent = float(extent)
        self.respect_gates = respect_gates
        self.add_phase("wander", self._move_phase, order=order)

    def _move_phase(self, state: WorldState, ctx) -> WorldState:
        cname = self.class_name
        store = ctx.store
        if cname not in store.class_index:
            return state
        spec = store.spec(cname)
        if not (spec.has_property("Position") and spec.has_property("TargetPos")):
            return state
        cs = state.classes[cname]
        pos_col = spec.slot("Position").col
        tgt_col = spec.slot("TargetPos").col
        pos = cs.vec[:, pos_col, :2]  # [C, 2]
        tgt = cs.vec[:, tgt_col, :2]

        speed = cs.i32[:, spec.slot("MOVE_SPEED").col].astype(jnp.float32) / SPEED_UNIT
        if self.respect_gates and spec.has_property("MOVE_GATE"):
            gate = cs.i32[:, spec.slot("MOVE_GATE").col]
            speed = jnp.where(gate > 0, 0.0, speed)
        if spec.has_property("HP"):
            speed = jnp.where(cs.i32[:, spec.slot("HP").col] > 0, speed, 0.0)
        step = speed * ctx.dt  # [C]

        delta = tgt - pos
        dist = jnp.sqrt(jnp.sum(delta * delta, axis=-1) + 1e-12)
        arrived = dist <= jnp.maximum(step, 1e-6)
        # fresh uniform target for arrived walkers (dead/rooted ones have
        # step 0 and never "arrive" once a target is outstanding)
        new_tgt = jax.random.uniform(
            ctx.rng(), (pos.shape[0], 2), minval=0.0, maxval=self.extent
        )
        tgt = jnp.where((arrived & cs.alive)[:, None], new_tgt, tgt)
        move = jnp.where(
            arrived[:, None], delta, delta / dist[:, None] * step[:, None]
        )
        new_pos = jnp.where(cs.alive[:, None], pos + move, pos)
        new_pos = jnp.clip(new_pos, 0.0, self.extent)

        vec = cs.vec.at[:, pos_col, :2].set(new_pos)
        vec = vec.at[:, tgt_col, :2].set(tgt)
        return with_class(state, cname, cs.replace(vec=vec))
