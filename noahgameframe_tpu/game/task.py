"""Task system: accept/progress/award over the TaskList record.

Reference: NFCTaskModule (`NFServer/NFGameLogicPlugin/NFCTaskModule.cpp`)
— tasks live in the TaskList record (TaskID, TaskStatus, Process); kill
counts advance matching tasks' Process, completion flips TASK_DONE, and
drawing the award pays exp/gold then flips TASK_FINISH (ETaskState,
`NFDefine.proto:432-438`).

TPU integration: kill counting subscribes to the device tick's batched
ON_OBJECT_BE_KILLED event (killer handles arrive as a param column), so
a 10k-kill frame is one callback, not 10k.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..core.datatypes import Guid
from ..kernel.module import Module
from .defines import GameEvent, TaskState

TASK_RECORD = "TaskList"


@dataclasses.dataclass
class TaskDef:
    """A task definition: kill `count` of `target_config` (the reference's
    TASK_KILL_SOME_MONSTER type), rewarded with exp/gold."""

    task_id: str
    target_config: str = ""  # empty = any kill counts
    count: int = 1
    award_exp: int = 0
    award_gold: int = 0


class TaskModule(Module):
    name = "TaskModule"

    def __init__(self, level_module=None) -> None:
        super().__init__()
        self.level = level_module  # game.level.LevelModule (exp awards)
        self.defs: Dict[str, TaskDef] = {}

    def define_task(self, td: TaskDef) -> TaskDef:
        self.defs[td.task_id] = td
        return td

    def after_init(self) -> None:
        # batched kill counting off the device combat event
        self.kernel.events.subscribe_batch(
            int(GameEvent.ON_OBJECT_BE_KILLED), self._on_kills
        )

    # ------------------------------------------------------- record API
    def _task_row(self, guid: Guid, task_id: str) -> Optional[int]:
        rows = self.kernel.store.record_find_rows(
            self.kernel.state, guid, TASK_RECORD, "TaskID", task_id
        )
        return rows[0] if rows else None

    def accept(self, guid: Guid, task_id: str) -> bool:
        if task_id not in self.defs or self._task_row(guid, task_id) is not None:
            return False
        k = self.kernel
        try:
            k.state, _ = k.store.record_add_row(
                k.state, guid, TASK_RECORD,
                {"TaskID": task_id,
                 "TaskStatus": int(TaskState.IN_PROCESS), "Process": 0},
            )
        except RuntimeError:
            return False
        return True

    def status(self, guid: Guid, task_id: str) -> Optional[TaskState]:
        row = self._task_row(guid, task_id)
        if row is None:
            return None
        return TaskState(int(self.kernel.store.record_get(
            self.kernel.state, guid, TASK_RECORD, row, "TaskStatus")))

    def process(self, guid: Guid, task_id: str) -> int:
        row = self._task_row(guid, task_id)
        if row is None:
            return 0
        return int(self.kernel.store.record_get(
            self.kernel.state, guid, TASK_RECORD, row, "Process"))

    def add_process(self, guid: Guid, task_id: str, n: int = 1) -> None:
        """Advance an in-process task; flips DONE at the target count."""
        row = self._task_row(guid, task_id)
        td = self.defs.get(task_id)
        if row is None or td is None:
            return
        k = self.kernel
        status = int(k.store.record_get(k.state, guid, TASK_RECORD, row,
                                        "TaskStatus"))
        if status != int(TaskState.IN_PROCESS):
            return
        cur = int(k.store.record_get(k.state, guid, TASK_RECORD, row,
                                     "Process")) + n
        k.state = k.store.record_set(k.state, guid, TASK_RECORD, row,
                                     "Process", min(cur, td.count))
        if cur >= td.count:
            k.state = k.store.record_set(k.state, guid, TASK_RECORD, row,
                                         "TaskStatus", int(TaskState.DONE))

    def draw_award(self, guid: Guid, task_id: str) -> bool:
        """Pay the award and finish (TASK_DONE → TASK_FINISH)."""
        row = self._task_row(guid, task_id)
        td = self.defs.get(task_id)
        if row is None or td is None:
            return False
        k = self.kernel
        status = int(k.store.record_get(k.state, guid, TASK_RECORD, row,
                                        "TaskStatus"))
        if status != int(TaskState.DONE):
            return False
        if td.award_gold:
            k.set_property(guid, "Gold",
                           int(k.get_property(guid, "Gold")) + td.award_gold)
        if td.award_exp and self.level is not None:
            self.level.add_exp(guid, td.award_exp)
        k.state = k.store.record_set(k.state, guid, TASK_RECORD, row,
                                     "TaskStatus", int(TaskState.FINISH))
        return True

    # ------------------------------------------------------- kill counting
    def _on_kills(self, class_name: str, mask: np.ndarray,
                  params: Dict[str, np.ndarray]) -> None:
        """Batched device kills → per-killer task progress.  `killer` is
        the packed entity handle column written by the combat phase."""
        killers = params.get("killer")
        if killers is None:
            return
        store = self.kernel.store
        spec = store.spec(class_name)
        dead_rows = np.flatnonzero(mask)
        # ONE device fetch for the whole ConfigID column, then host-side
        # decode per dead row — no per-row transfers
        cfg_handles = None
        if spec.has_property("ConfigID"):
            slot = spec.slot("ConfigID")
            cfg_handles = np.asarray(
                self.kernel.state.classes[class_name].i32[:, slot.col]
            )
        per_killer: Dict[Guid, Dict[str, int]] = {}
        for row in dead_rows:
            killer = store.guid_of_handle(int(killers[int(row)]))
            if killer is None:
                continue
            victim_cfg = ""
            if cfg_handles is not None:
                victim_cfg = store.strings.lookup(int(cfg_handles[int(row)]))
            counts = per_killer.setdefault(killer, {})
            counts[victim_cfg] = counts.get(victim_cfg, 0) + 1
        for killer, by_cfg in per_killer.items():
            if killer not in store.guid_map:
                continue
            kc, _ = store.row_of(killer)
            if TASK_RECORD not in store.spec(kc).records:
                continue
            for task_id, td in self.defs.items():
                n = (sum(by_cfg.values()) if not td.target_config
                     else by_cfg.get(td.target_config, 0))
                if n:
                    self.add_process(killer, task_id, n)
