"""Regen module: HPREGEN/MPREGEN/SPREGEN applied on a heartbeat.

Reference: regen stats exist on every fighter (Class/Player.xml HPREGEN &
co) and tutorial/game code applies them on heartbeats (Tutorial3 registers
per-object heartbeats that mutate properties).  Here one `Regen` timer slot
per class drives a fused phase: fired & alive & HP>0 rows add their regen
stats, clamped to the MAX stats — BASELINE config 2's "property-driven
HP-regen tick" over 100k NPCs is this single phase.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..core.store import WorldState, with_class
from ..kernel.module import Module

REGEN_TIMER = "Regen"
_CHANNELS = (("HP", "MAXHP", "HPREGEN"), ("MP", "MAXMP", "MPREGEN"), ("SP", "MAXSP", "SPREGEN"))


class RegenModule(Module):
    name = "RegenModule"

    def __init__(
        self,
        classes: Sequence[str] = ("Player", "NPC"),
        period_s: float = 1.0,
        order: int = 40,
    ):
        super().__init__()
        self.classes = tuple(classes)
        self.period_s = float(period_s)
        self.add_phase("regen", self._regen_phase, order=order)

    def init(self) -> None:
        for cname in self.classes:
            self.kernel.schedule.register_timer(cname, REGEN_TIMER)

    def arm_all(self, class_name: str) -> None:
        k = self.kernel
        cs = k.state.classes[class_name]
        rows = np.flatnonzero(np.asarray(cs.alive))
        k.state = k.schedule.set_timer_rows(
            k.state, class_name, rows, REGEN_TIMER, self.period_s
        )

    def arm(self, guid) -> None:
        k = self.kernel
        k.state = k.schedule.set_timer(k.state, k.store, guid, REGEN_TIMER, self.period_s)

    def _regen_phase(self, state: WorldState, ctx) -> WorldState:
        for cname in self.classes:
            if cname not in ctx.store.class_index:
                continue
            spec = ctx.store.spec(cname)
            if not spec.has_property("HPREGEN"):
                continue
            cs = state.classes[cname]
            fired = ctx.fired(cname, REGEN_TIMER) & cs.alive
            hp = cs.i32[:, spec.slot("HP").col]
            live = fired & (hp > 0)  # the dead don't regenerate
            i32 = cs.i32
            for cur, mx, rg in _CHANNELS:
                if not (spec.has_property(cur) and spec.has_property(rg)):
                    continue
                c, m, r = (spec.slot(n).col for n in (cur, mx, rg))
                val = i32[:, c]
                cap = i32[:, m]
                regened = jnp.minimum(val + i32[:, r], jnp.maximum(cap, val))
                i32 = i32.at[:, c].set(jnp.where(live & (i32[:, r] > 0), regened, val))
            state = with_class(state, cname, cs.replace(i32=i32))
        return state
