"""Typed value model for the entity framework.

The reference framework's universal value type is a tagged variant over
{int, float, string, object(GUID), vector2, vector3} (see reference
NFComm/NFCore/NFIDataList.h:37-47 for the enum and :67-150 for the variant).
On TPU we cannot store variants: every property is compiled to a column in a
dtype-homogeneous bank (see `schema.py`).  This module defines the type enum,
its device representation, and the host-side value coercions.

Device representation choices (TPU-first):
  INT     -> int32 column           (i32 bank)
  FLOAT   -> float32 column         (f32 bank)
  STRING  -> int32 interned handle  (i32 bank; see strings.StringTable)
  OBJECT  -> int32 entity handle    (i32 bank; row-handle into the world,
                                     -1 == null; host maps handle<->Guid)
  VECTOR2 -> float32[3] (z unused)  (vec bank, unified with VECTOR3 so both
                                     live in one [cap, nvec, 3] array)
  VECTOR3 -> float32[3]             (vec bank)

128-bit GUIDs never live on device: entities are addressed by dense row
index, and the host keeps a Guid<->(class,row) map (reference generates
GUIDs as {app_id, time*1e6+counter}, NFCKernelModule.cpp:955-979 — ours are
the same shape, host-side only).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time as _time
from typing import Any, Optional, Tuple, Union

import numpy as np


def next_pow2(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo) — the shape-bucketing unit
    shared by host gathers (utils.hostio), batched row creation
    (core.store), and bench sizing.  Lives here (dependency-free leaf)
    so both core and utils can import it without cycles."""
    n = max(int(n), int(lo), 1)
    return 1 << (n - 1).bit_length()


class DataType(enum.IntEnum):
    """Mirrors the reference TDATA_TYPE enum (NFIDataList.h:37-47)."""

    UNKNOWN = 0
    INT = 1
    FLOAT = 2
    STRING = 3
    OBJECT = 4
    VECTOR2 = 5
    VECTOR3 = 6


# XML `Type=` attribute spelling -> DataType (NFCClassModule::ComputerType,
# reference NFCClassModule.cpp:45-70 accepts these same spellings).
XML_TYPE_NAMES = {
    "int": DataType.INT,
    "float": DataType.FLOAT,
    "double": DataType.FLOAT,
    "string": DataType.STRING,
    "object": DataType.OBJECT,
    "vector2": DataType.VECTOR2,
    "vector3": DataType.VECTOR3,
}

# Which bank each logical type is compiled into.
class Bank(enum.Enum):
    I32 = "i32"
    F32 = "f32"
    VEC = "vec"  # float32[..., 3]


BANK_OF_TYPE = {
    DataType.INT: Bank.I32,
    DataType.STRING: Bank.I32,
    DataType.OBJECT: Bank.I32,
    DataType.FLOAT: Bank.F32,
    DataType.VECTOR2: Bank.VEC,
    DataType.VECTOR3: Bank.VEC,
}

NULL_OBJECT = -1  # device encoding of the null GUID
NULL_STRING = 0  # StringTable interns "" as handle 0


@dataclasses.dataclass(frozen=True, order=True)
class Guid:
    """128-bit entity identity: (head, data) like the reference NFGUID
    (NFGUID.h:17-45). Host-side only; never shipped to device."""

    head: int = 0
    data: int = 0

    def is_null(self) -> bool:
        return self.head == 0 and self.data == 0

    def __str__(self) -> str:  # matches "head-data" human form
        return f"{self.head}-{self.data}"

    @staticmethod
    def parse(s: str) -> "Guid":
        if not s:
            return Guid()
        head, _, data = s.partition("-")
        return Guid(int(head), int(data or 0))


NULL_GUID = Guid()


class GuidAllocator:
    """Monotonic GUID source: {app_id, epoch_micros + counter} like the
    reference kernel's CreateGUID (NFCKernelModule.cpp:955-979), but
    thread-safe."""

    def __init__(self, app_id: int = 1):
        self._app_id = int(app_id)
        self._lock = threading.Lock()
        self._last = 0
        # pinned = deterministic mode: the clock is never read again and
        # every allocation is last+1.  A recording role pins at journal
        # setup (the seed goes into journal meta) so replay can mint the
        # exact guid sequence — wire messages carry guids back into
        # mutating handlers, which makes the clock a hidden replay input
        self.pinned = False

    def pin(self, last: Optional[int] = None) -> int:
        """Switch to pure-counter allocation; returns the seed (the
        point the counter continues from).  With no argument the seed is
        the current clock reading, so pinned and unpinned allocators
        stay in disjoint ranges in practice."""
        with self._lock:
            if last is not None:
                self._last = int(last)
            elif self._last == 0:
                # nf-lint: disable=wall-clock -- one-shot seed so pinned
                # and unpinned allocators land in disjoint guid ranges;
                # replay determinism comes from pin(last=...) instead
                self._last = int(_time.time() * 1_000_000)
            self.pinned = True
            return self._last

    def next(self) -> Guid:
        with self._lock:
            if self.pinned:
                self._last += 1
                return Guid(self._app_id, self._last)
            # nf-lint: disable=wall-clock -- unpinned live mode is
            # wall-clock BY DESIGN (guids order across restarts);
            # deterministic runs pin() before allocating
            now = int(_time.time() * 1_000_000)
            if now <= self._last:
                now = self._last + 1
            self._last = now
            return Guid(self._app_id, now)

    def next_batch(self, n: int) -> list:
        """n distinct guids under ONE lock acquisition + clock read — the
        bulk-create fast path (create_many at 1M NPCs)."""
        with self._lock:
            if self.pinned:
                now = self._last + 1
            else:
                # nf-lint: disable=wall-clock -- same live-mode contract
                # as next(): deterministic runs pin() first
                now = int(_time.time() * 1_000_000)
                if now <= self._last:
                    now = self._last + 1
            self._last = now + n - 1
            app = self._app_id
            return [Guid(app, now + i) for i in range(n)]


Vector2 = Tuple[float, float]
Vector3 = Tuple[float, float, float]
Value = Union[int, float, str, Guid, Vector2, Vector3]


def default_value(t: DataType) -> Value:
    if t == DataType.INT:
        return 0
    if t == DataType.FLOAT:
        return 0.0
    if t == DataType.STRING:
        return ""
    if t == DataType.OBJECT:
        return NULL_GUID
    if t == DataType.VECTOR2:
        return (0.0, 0.0)
    if t == DataType.VECTOR3:
        return (0.0, 0.0, 0.0)
    raise ValueError(f"no default for {t}")


def coerce(t: DataType, v: Any) -> Value:
    """Coerce a python value (e.g. an XML attribute string) to type `t`."""
    if t == DataType.INT:
        if isinstance(v, str):
            return int(float(v)) if v.strip() else 0
        return int(v)
    if t == DataType.FLOAT:
        if isinstance(v, str):
            return float(v) if v.strip() else 0.0
        return float(v)
    if t == DataType.STRING:
        return str(v)
    if t == DataType.OBJECT:
        if isinstance(v, Guid):
            return v
        if isinstance(v, str):
            # instance XMLs write object fields as "0" / "" / "head-data"
            if not v.strip() or v.strip() == "0":
                return NULL_GUID
            return Guid.parse(v)
        if isinstance(v, int):
            return Guid(0, v)
        raise TypeError(f"cannot coerce {v!r} to OBJECT")
    if t in (DataType.VECTOR2, DataType.VECTOR3):
        n = 2 if t == DataType.VECTOR2 else 3
        if isinstance(v, str):
            parts = [p for p in v.replace(",", " ").split() if p]
            vals = [float(p) for p in parts] + [0.0] * n
            return tuple(vals[:n])
        vals = [float(x) for x in v]
        return tuple((vals + [0.0] * n)[:n])
    raise ValueError(f"cannot coerce to {t}")


def np_dtype(bank: Bank) -> np.dtype:
    return np.dtype(np.int32) if bank == Bank.I32 else np.dtype(np.float32)
