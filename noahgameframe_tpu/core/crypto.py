"""RC4 stream cipher for ciphered config files.

The reference ships an RC4 utility for optionally-encrypted data files
(`NFComm/NFConfigPlugin/myrc4.{h,cpp}` — present but unused by any module
in the snapshot).  This is the standard textbook RC4 (KSA + PRGA) plus the
config convention this framework uses: a ciphered XML file carries the
``NFRC4`` magic prefix so loaders can transparently decrypt when given a
key and pass plaintext files through untouched.

RC4 is obsolete as cryptography; it is kept solely for config obfuscation
parity with the reference — do not use it to protect secrets.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Union

MAGIC = b"NFRC4\x00"


def rc4(key: bytes, data: bytes) -> bytes:
    """RC4 keystream XOR (encrypt == decrypt)."""
    if not key:
        raise ValueError("rc4 key must be non-empty")
    s = list(range(256))
    j = 0
    for i in range(256):
        j = (j + s[i] + key[i % len(key)]) & 0xFF
        s[i], s[j] = s[j], s[i]
    out = bytearray(len(data))
    i = j = 0
    for n, b in enumerate(data):
        i = (i + 1) & 0xFF
        j = (j + s[i]) & 0xFF
        s[i], s[j] = s[j], s[i]
        out[n] = b ^ s[(s[i] + s[j]) & 0xFF]
    return bytes(out)


def encrypt_config(data: bytes, key: Union[str, bytes]) -> bytes:
    """Plaintext -> NFRC4-prefixed ciphertext (tools-side helper)."""
    if isinstance(key, str):
        key = key.encode()
    return MAGIC + rc4(key, data)


def decrypt_config(data: bytes, key: Union[str, bytes, None]) -> bytes:
    """Ciphertext (or plaintext) -> plaintext.

    Files without the magic prefix pass through unchanged; ciphered files
    require a key."""
    if not data.startswith(MAGIC):
        return data
    if key is None:
        raise ValueError("config file is RC4-ciphered but no key was given")
    if isinstance(key, str):
        key = key.encode()
    return rc4(key, data[len(MAGIC):])


def read_config_bytes(path: Path, key: Union[str, bytes, None] = None) -> bytes:
    """Read a config file, transparently decrypting NFRC4 content."""
    return decrypt_config(Path(path).read_bytes(), key)
