"""Host-side string interning.

TDATA_STRING properties (names, ConfigIDs, prefab paths) cannot live on
device; they are interned to dense int32 handles.  Handle 0 is always the
empty string so zero-initialised device columns decode to "".
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List


class StringTable:
    """Bidirectional str<->int32 intern table. Append-only, thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._to_id: Dict[str, int] = {"": 0}
        self._to_str: List[str] = [""]

    def intern(self, s: str) -> int:
        if s is None:
            s = ""
        with self._lock:
            h = self._to_id.get(s)
            if h is None:
                h = len(self._to_str)
                self._to_id[s] = h
                self._to_str.append(s)
            return h

    def intern_all(self, items: Iterable[str]) -> List[int]:
        return [self.intern(s) for s in items]

    def lookup(self, handle: int) -> str:
        h = int(handle)
        if 0 <= h < len(self._to_str):
            return self._to_str[h]
        raise KeyError(f"unknown string handle {h}")

    def __len__(self) -> int:
        return len(self._to_str)

    def snapshot(self) -> List[str]:
        """Copy of the table for checkpointing (index == handle)."""
        with self._lock:
            return list(self._to_str)

    @classmethod
    def restore(cls, items: List[str]) -> "StringTable":
        t = cls()
        for i, s in enumerate(items):
            if i == 0:
                continue
            h = t.intern(s)
            if h != i:
                raise ValueError("string table restore out of order")
        return t
