"""Class schema: declarative entity shapes compiled to device bank layouts.

The reference drives everything from XML class schemas — a LogicClass.xml
tree of classes (inheritance by nesting, root `IObject`), each pointing at a
per-class XML with `<Property Id Type Public Private Save Cache Ref Upload>`
rows, `<Record Id Row Col ...><Col Type Tag/></Record>` tables and
`<Component>` entries (reference NFCClassModule.cpp:72-228, LogicClass.xml).

Here a schema has two lives:

1. Declarative (`PropertyDef`/`RecordDef`/`ClassDef`, `ClassRegistry`) —
   built programmatically or loaded from reference-format XML
   (`load_logic_class_xml`).  Inheritance is flattened parent-first, exactly
   like the reference's AddClassInclude chain.

2. Compiled (`ClassSpec`) — the TPU layout.  Every property becomes a column
   in one of three dtype-homogeneous banks (i32 / f32 / vec[3]), so a class
   with 80 properties is 3 device arrays, not 80, and flag-filtered diffing
   or checkpointing is a single masked compare per bank.  Records compile
   the same way with an extra rows axis.  Flags compile to per-bank boolean
   column masks (`ClassSpec.mask`).
"""

from __future__ import annotations

import dataclasses
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .datatypes import (
    BANK_OF_TYPE,
    XML_TYPE_NAMES,
    Bank,
    DataType,
    Value,
    coerce,
    default_value,
)

FLAG_NAMES = ("public", "private", "save", "cache", "ref", "upload")


@dataclasses.dataclass(frozen=True)
class PropertyDef:
    name: str
    type: DataType
    public: bool = False
    private: bool = False
    save: bool = False
    cache: bool = False
    ref: bool = False
    upload: bool = False
    desc: str = ""
    default: Optional[Value] = None

    def flag(self, flag_name: str) -> bool:
        return bool(getattr(self, flag_name))

    def resolved_default(self) -> Value:
        return self.default if self.default is not None else default_value(self.type)


@dataclasses.dataclass(frozen=True)
class RecordColDef:
    tag: str
    type: DataType


@dataclasses.dataclass(frozen=True)
class RecordDef:
    name: str
    max_rows: int
    cols: Tuple[RecordColDef, ...]
    public: bool = False
    private: bool = False
    save: bool = False
    cache: bool = False
    upload: bool = False
    desc: str = ""

    def flag(self, flag_name: str) -> bool:
        return bool(getattr(self, flag_name, False))


@dataclasses.dataclass(frozen=True)
class ComponentDef:
    name: str
    language: str = "python"
    enable: bool = True
    desc: str = ""


@dataclasses.dataclass
class ClassDef:
    name: str
    parent: Optional[str] = None
    properties: List[PropertyDef] = dataclasses.field(default_factory=list)
    records: List[RecordDef] = dataclasses.field(default_factory=list)
    components: List[ComponentDef] = dataclasses.field(default_factory=list)
    instance_path: str = ""
    desc: str = ""


# ---------------------------------------------------------------------------
# Compiled layout
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PropertySlot:
    """Where one property lives on device: (bank, column)."""

    prop: PropertyDef
    bank: Bank
    col: int


@dataclasses.dataclass(frozen=True)
class RecordColSlot:
    col_def: RecordColDef
    bank: Bank
    col: int


@dataclasses.dataclass(frozen=True)
class RecordSpec:
    rec: RecordDef
    cols: Dict[str, RecordColSlot]
    col_order: Tuple[str, ...]
    n_i32: int
    n_f32: int
    n_vec: int

    @property
    def name(self) -> str:
        return self.rec.name

    @property
    def max_rows(self) -> int:
        return self.rec.max_rows


class ClassSpec:
    """Compiled, immutable device layout for one class."""

    def __init__(self, cls: ClassDef):
        self.cls = cls
        self.name = cls.name
        self.slots: Dict[str, PropertySlot] = {}
        self.prop_order: Tuple[str, ...] = tuple(p.name for p in cls.properties)
        if len(set(self.prop_order)) != len(self.prop_order):
            dupes = [n for n in self.prop_order if self.prop_order.count(n) > 1]
            raise ValueError(f"class {cls.name!r} has duplicate properties: {sorted(set(dupes))}")
        counters = {Bank.I32: 0, Bank.F32: 0, Bank.VEC: 0}
        for p in cls.properties:
            bank = BANK_OF_TYPE[p.type]
            self.slots[p.name] = PropertySlot(p, bank, counters[bank])
            counters[bank] += 1
        self.n_i32 = counters[Bank.I32]
        self.n_f32 = counters[Bank.F32]
        self.n_vec = counters[Bank.VEC]

        self.records: Dict[str, RecordSpec] = {}
        self.record_order: Tuple[str, ...] = tuple(r.name for r in cls.records)
        for r in cls.records:
            rc = {Bank.I32: 0, Bank.F32: 0, Bank.VEC: 0}
            cols: Dict[str, RecordColSlot] = {}
            for c in r.cols:
                bank = BANK_OF_TYPE[c.type]
                cols[c.tag] = RecordColSlot(c, bank, rc[bank])
                rc[bank] += 1
            self.records[r.name] = RecordSpec(
                rec=r,
                cols=cols,
                col_order=tuple(c.tag for c in r.cols),
                n_i32=rc[Bank.I32],
                n_f32=rc[Bank.F32],
                n_vec=rc[Bank.VEC],
            )

        self._mask_cache: Dict[Tuple[Bank, str], np.ndarray] = {}

    def slot(self, prop_name: str) -> PropertySlot:
        try:
            return self.slots[prop_name]
        except KeyError:
            raise KeyError(f"class {self.name!r} has no property {prop_name!r}") from None

    def has_property(self, prop_name: str) -> bool:
        return prop_name in self.slots

    def bank_size(self, bank: Bank) -> int:
        return {Bank.I32: self.n_i32, Bank.F32: self.n_f32, Bank.VEC: self.n_vec}[bank]

    def bank_props(self, bank: Bank) -> List[PropertySlot]:
        out = [s for s in self.slots.values() if s.bank == bank]
        out.sort(key=lambda s: s.col)
        return out

    def mask(self, bank: Bank, flag_name: str) -> np.ndarray:
        """Boolean column mask for a flag over one bank, e.g. which i32
        columns are Public.  This is how the reference's per-property flag
        checks (NFCProperty.h:17-94) become vectorised column selects."""
        key = (bank, flag_name)
        m = self._mask_cache.get(key)
        if m is None:
            m = np.zeros(self.bank_size(bank), dtype=bool)
            for s in self.bank_props(bank):
                m[s.col] = s.prop.flag(flag_name)
            m.setflags(write=False)
            self._mask_cache[key] = m
        return m

    def string_cols_i32(self) -> List[int]:
        """i32 columns that hold interned string handles (host decode aid)."""
        return [s.col for s in self.bank_props(Bank.I32) if s.prop.type == DataType.STRING]

    def object_cols_i32(self) -> List[int]:
        return [s.col for s in self.bank_props(Bank.I32) if s.prop.type == DataType.OBJECT]


# ---------------------------------------------------------------------------
# Registry with inheritance flattening
# ---------------------------------------------------------------------------


class ClassRegistry:
    """Holds ClassDefs, resolves inheritance, hands out compiled ClassSpecs.

    Inheritance mirrors the reference: children get the parent's properties,
    records and components prepended (parent-first), transitively up to the
    root (reference NFCClassModule.cpp:230-320)."""

    def __init__(self) -> None:
        self._defs: Dict[str, ClassDef] = {}
        self._specs: Dict[str, ClassSpec] = {}

    def define(self, cls: ClassDef) -> ClassDef:
        if cls.name in self._defs:
            raise ValueError(f"class {cls.name!r} already defined")
        self._defs[cls.name] = cls
        self._specs.pop(cls.name, None)
        return cls

    def __contains__(self, name: str) -> bool:
        return name in self._defs

    def names(self) -> List[str]:
        return list(self._defs)

    def get_def(self, name: str) -> ClassDef:
        return self._defs[name]

    def _flatten(self, name: str, _seen: Optional[set] = None) -> ClassDef:
        seen = _seen or set()
        if name in seen:
            raise ValueError(f"inheritance cycle at {name!r}")
        seen.add(name)
        cls = self._defs[name]
        if not cls.parent:
            return cls
        parent = self._flatten(cls.parent, seen)
        # dict insertion order gives parent-first layout; child overrides
        # replace the parent's definition in place.
        merged_props: Dict[str, PropertyDef] = {p.name: p for p in parent.properties}
        merged_props.update({p.name: p for p in cls.properties})
        merged_recs: Dict[str, RecordDef] = {r.name: r for r in parent.records}
        merged_recs.update({r.name: r for r in cls.records})
        merged_comps: Dict[str, ComponentDef] = {c.name: c for c in parent.components}
        merged_comps.update({c.name: c for c in cls.components})
        return ClassDef(
            name=cls.name,
            parent=None,
            properties=list(merged_props.values()),
            records=list(merged_recs.values()),
            components=list(merged_comps.values()),
            instance_path=cls.instance_path,
            desc=cls.desc,
        )

    def spec(self, name: str) -> ClassSpec:
        s = self._specs.get(name)
        if s is None:
            s = ClassSpec(self._flatten(name))
            self._specs[name] = s
        return s


# ---------------------------------------------------------------------------
# Reference-format XML loading
# ---------------------------------------------------------------------------


def _flag(elem: ET.Element, attr: str) -> bool:
    return elem.get(attr, "0").strip() in ("1", "true", "True")


def _parse_property(elem: ET.Element) -> PropertyDef:
    t = XML_TYPE_NAMES[elem.get("Type", "int").lower()]
    return PropertyDef(
        name=elem.get("Id", ""),
        type=t,
        public=_flag(elem, "Public"),
        private=_flag(elem, "Private"),
        save=_flag(elem, "Save"),
        cache=_flag(elem, "Cache"),
        ref=_flag(elem, "Ref"),
        upload=_flag(elem, "Upload"),
        desc=elem.get("Desc", ""),
    )


def _parse_record(elem: ET.Element) -> RecordDef:
    cols = tuple(
        RecordColDef(tag=c.get("Tag", f"col{i}"), type=XML_TYPE_NAMES[c.get("Type", "int").lower()])
        for i, c in enumerate(elem.findall("Col"))
    )
    declared = elem.get("Col")
    if declared is not None and int(declared) != len(cols):
        # the reference trusts the <Col> children; mirror that but keep note
        pass
    return RecordDef(
        name=elem.get("Id", ""),
        max_rows=int(elem.get("Row", "1")),
        cols=cols,
        public=_flag(elem, "Public"),
        private=_flag(elem, "Private"),
        save=_flag(elem, "Save"),
        cache=_flag(elem, "Cache"),
        upload=_flag(elem, "Upload"),
        desc=elem.get("Desc", ""),
    )


def load_class_xml(path: Path, name: str, parent: Optional[str], instance_path: str = "",
                   cipher_key=None) -> ClassDef:
    """Parse one per-class XML (Propertys/Records/Components sections).
    RC4-ciphered files (core/crypto.py NFRC4 convention; reference myrc4)
    decrypt transparently when `cipher_key` is given."""
    from .crypto import read_config_bytes

    root = ET.fromstring(read_config_bytes(path, cipher_key))
    props = [_parse_property(p) for p in root.findall("./Propertys/Property")]
    recs = [_parse_record(r) for r in root.findall("./Records/Record")]
    comps = [
        ComponentDef(
            name=c.get("Name", ""),
            language=c.get("Language", "python"),
            enable=_flag(c, "Enable"),
            desc=c.get("Desc", ""),
        )
        for c in root.findall("./Components/Component")
    ]
    return ClassDef(
        name=name,
        parent=parent,
        properties=props,
        records=recs,
        components=comps,
        instance_path=instance_path,
    )


def load_logic_class_xml(logic_class_path: Path, data_root: Optional[Path] = None,
                         cipher_key=None) -> ClassRegistry:
    """Load a reference-format LogicClass.xml class tree.

    `Path`/`InstancePath` attributes are resolved relative to `data_root`
    (defaults to the directory containing the parent of LogicClass.xml, i.e.
    the directory that paths like "NFDataCfg/Struct/Class/X.xml" are
    relative to in the reference layout)."""
    logic_class_path = Path(logic_class_path)
    if data_root is None:
        # .../NFDataCfg/Struct/LogicClass.xml -> data_root = .../
        data_root = logic_class_path.parent.parent.parent
    registry = ClassRegistry()

    def walk(elem: ET.Element, parent: Optional[str]) -> None:
        name = elem.get("Id", "")
        rel = elem.get("Path", "")
        inst = elem.get("InstancePath", "")
        cls_path = data_root / rel if rel else None
        if cls_path is not None and cls_path.exists():
            cls = load_class_xml(cls_path, name, parent, inst, cipher_key=cipher_key)
        else:
            cls = ClassDef(name=name, parent=parent, instance_path=inst)
        registry.define(cls)
        for child in elem.findall("Class"):
            walk(child, name)

    from .crypto import read_config_bytes

    root = ET.fromstring(read_config_bytes(logic_class_path, cipher_key))
    for top in root.findall("Class"):
        walk(top, None)
    return registry


# ---------------------------------------------------------------------------
# Convenience builders (programmatic schema definition)
# ---------------------------------------------------------------------------


def prop(name: str, type_name: str, *, default: Optional[Value] = None, **flags) -> PropertyDef:
    t = XML_TYPE_NAMES[type_name.lower()]
    d = None if default is None else coerce(t, default)
    return PropertyDef(name=name, type=t, default=d, **flags)


def record(name: str, max_rows: int, cols: Sequence[Tuple[str, str]], **flags) -> RecordDef:
    return RecordDef(
        name=name,
        max_rows=max_rows,
        cols=tuple(RecordColDef(tag=t, type=XML_TYPE_NAMES[ty.lower()]) for t, ty in cols),
        **flags,
    )
