"""Structure-of-Arrays entity store: the device-resident world.

The reference keeps a GUID->object map of heap objects, each owning
name->property and name->record maps of tagged variants
(NFCKernelModule.h:30-33, NFCObject.h:19-108).  That layout is hostile to a
TPU, so here the *entire world is a pytree of dense arrays*:

    WorldState
      .classes: {class_name: ClassState}
      .tick:    int32 scalar   (frame counter; time = tick * dt on host)
      .rng:     PRNG key

    ClassState                       (capacity C, from StoreConfig)
      .i32:   int32  [C, n_i32]      int / interned-string / object-handle
      .f32:   float32[C, n_f32]      float properties
      .vec:   float32[C, n_vec, 3]   vector2/3 properties
      .alive: bool   [C]             row in use (a live entity)
      .timers: TimerState [C, n_timers]   (see kernel/schedule.py)
      .records: {record_name: RecordState}

    RecordState                      (R = max_rows per entity)
      .i32:  int32  [C, R, n_i32]
      .f32:  float32[C, R, n_f32]
      .vec:  float32[C, R, n_vec, 3]
      .used: bool   [C, R]

Row allocation is host-owned (free-list per class, like the reference's
deferred create/destroy lists, NFCKernelModule.cpp:76-84): device code only
ever *clears* `alive` (deaths inside a tick); the host reconciles via
`EntityStore.reconcile_deaths`.  GUIDs stay host-side in a Guid<->handle
map; object-valued properties store packed int32 handles
(class_index << 24 | row).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .datatypes import (
    Bank,
    DataType,
    Guid,
    GuidAllocator,
    NULL_OBJECT,
    Value,
    coerce,
    default_value,
    next_pow2,
)
from .schema import ClassRegistry, ClassSpec, RecordSpec
from .strings import StringTable

HANDLE_ROW_BITS = 24
HANDLE_ROW_MASK = (1 << HANDLE_ROW_BITS) - 1


class RecordOp(enum.IntEnum):
    """Per-op record event types, value-compatible with the reference's
    NFIRecord::RecordOptype (NFIRecord.h:16-25)."""

    ADD = 0
    DEL = 1
    SWAP = 2
    CREATE = 3
    UPDATE = 4
    CLEARED = 5
    SORT = 6
    COVER = 7


# (class_name, record_name, op, entity_rows, rec_row, tags): fired by the
# host-side record mutators, batch-first — entity_rows is an int array so
# the bulk paths (record_write_rows) cost one call, not one per entity.
# tags is the touched-column subset for UPDATE, None for whole-row ops.
# For SWAP, rec_row is the (origin, target) row pair.
RecordEventFn = Callable[
    [str, str, "RecordOp", np.ndarray, Any, Optional[Tuple[str, ...]]], None
]


def with_class(state: "WorldState", class_name: str, cs: "ClassState") -> "WorldState":
    """Functional single-class replacement — the universal update idiom."""
    new_classes = dict(state.classes)
    new_classes[class_name] = cs
    return state.replace(classes=new_classes)


def pack_handle(class_idx: int, row: int) -> int:
    return (class_idx << HANDLE_ROW_BITS) | row


def unpack_handle(handle: int) -> Tuple[int, int]:
    return handle >> HANDLE_ROW_BITS, handle & HANDLE_ROW_MASK


@struct.dataclass
class TimerState:
    """Vectorised heartbeats (reference NFCScheduleModule walks per-object
    timer maps each tick, NFCScheduleModule.cpp:49-110; here firing is one
    compare over [C, n_timers])."""

    next_fire: jnp.ndarray  # int32 [C, T] tick index of next firing
    interval: jnp.ndarray  # int32 [C, T] ticks between firings
    remain: jnp.ndarray  # int32 [C, T] remaining count, -1 = forever
    active: jnp.ndarray  # bool  [C, T]


@struct.dataclass
class RecordState:
    i32: jnp.ndarray
    f32: jnp.ndarray
    vec: jnp.ndarray
    used: jnp.ndarray


@struct.dataclass
class ClassState:
    i32: jnp.ndarray
    f32: jnp.ndarray
    vec: jnp.ndarray
    alive: jnp.ndarray
    timers: TimerState
    records: Dict[str, RecordState]

    @property
    def capacity(self) -> int:
        return self.alive.shape[0]


@struct.dataclass
class WorldState:
    classes: Dict[str, ClassState]
    tick: jnp.ndarray  # int32 scalar
    rng: jnp.ndarray  # PRNG key
    # module-owned carried tick state (e.g. the Verlet grid caches of
    # ops/verlet.py), keyed by registering module; pytree-of-arrays only.
    # Kernel.register_aux primes entries lazily so worlds that use no
    # aux carry an empty dict (zero structural change).
    aux: Dict[str, Any] = dataclasses.field(default_factory=dict)


@jax.jit
def _reset_and_write_rows(cs: ClassState, rows, i32, f32, vec) -> ClassState:
    """One compiled row-(re)initialization: value banks from the staged
    payloads, timers disarmed, records cleared, alive on.  Cached per
    (class pytree structure, row-count bucket) — the host enter-game path
    calls this once per create instead of ~15 eager scatters."""
    t = cs.timers
    timers = TimerState(
        next_fire=t.next_fire.at[rows].set(0),
        interval=t.interval.at[rows].set(1),
        remain=t.remain.at[rows].set(0),
        active=t.active.at[rows].set(False),
    )
    records = {
        rname: RecordState(
            i32=rec.i32.at[rows].set(0),
            f32=rec.f32.at[rows].set(0.0),
            vec=rec.vec.at[rows].set(0.0),
            used=rec.used.at[rows].set(False),
        )
        for rname, rec in cs.records.items()
    }
    return cs.replace(
        i32=cs.i32.at[rows].set(i32) if cs.i32.shape[1] else cs.i32,
        f32=cs.f32.at[rows].set(f32) if cs.f32.shape[1] else cs.f32,
        vec=cs.vec.at[rows].set(vec) if cs.vec.shape[1] else cs.vec,
        alive=cs.alive.at[rows].set(True),
        timers=timers,
        records=records,
    )


@dataclasses.dataclass
class StoreConfig:
    default_capacity: int = 1024
    capacities: Dict[str, int] = dataclasses.field(default_factory=dict)
    timer_slots: Dict[str, int] = dataclasses.field(default_factory=dict)

    def capacity_of(self, class_name: str) -> int:
        return int(self.capacities.get(class_name, self.default_capacity))


def _zeros_class_state(spec: ClassSpec, cap: int, n_timers: int) -> ClassState:
    recs = {}
    for rname in spec.record_order:
        rs: RecordSpec = spec.records[rname]
        recs[rname] = RecordState(
            i32=jnp.zeros((cap, rs.max_rows, rs.n_i32), jnp.int32),
            f32=jnp.zeros((cap, rs.max_rows, rs.n_f32), jnp.float32),
            vec=jnp.zeros((cap, rs.max_rows, rs.n_vec, 3), jnp.float32),
            used=jnp.zeros((cap, rs.max_rows), bool),
        )
    return ClassState(
        i32=jnp.zeros((cap, spec.n_i32), jnp.int32),
        f32=jnp.zeros((cap, spec.n_f32), jnp.float32),
        vec=jnp.zeros((cap, spec.n_vec, 3), jnp.float32),
        alive=jnp.zeros((cap,), bool),
        timers=TimerState(
            next_fire=jnp.zeros((cap, n_timers), jnp.int32),
            interval=jnp.ones((cap, n_timers), jnp.int32),
            remain=jnp.zeros((cap, n_timers), jnp.int32),
            active=jnp.zeros((cap, n_timers), bool),
        ),
        records=recs,
    )


class _ClassHost:
    """Host bookkeeping for one class: free rows + row->guid."""

    def __init__(self, spec: ClassSpec, class_idx: int, capacity: int):
        self.spec = spec
        self.class_idx = class_idx
        self.capacity = capacity
        self.free: List[int] = list(range(capacity - 1, -1, -1))
        self.row_guid: List[Optional[Guid]] = [None] * capacity
        # host-side allocation bitmap: lets reconcile_deaths find device
        # deaths with ONE vector op instead of a Python scan of every row
        self.alloc_mask = np.zeros(capacity, bool)
        # columnar guid mirror of row_guid — the batch sync path reads
        # guid identities for thousands of rows with one gather
        self.guid_head = np.zeros(capacity, np.int64)
        self.guid_data = np.zeros(capacity, np.int64)
        # allocation generation, +1 per free: guids never recycle (pure
        # counter), so within one generation row ⟺ guid.  The batched
        # serve edge (ops/serving.py) uploads this i32 vector instead of
        # comparing int64 guid pairs on device
        self.row_gen = np.zeros(capacity, np.int32)
        self.live_count = 0

    def alloc(self) -> int:
        if not self.free:
            raise RuntimeError(
                f"class {self.spec.name!r} capacity {self.capacity} exhausted"
            )
        self.live_count += 1
        row = self.free.pop()
        self.alloc_mask[row] = True
        return row

    def alloc_many(self, n: int) -> np.ndarray:
        if n <= 0:  # free[-0:] would slice the WHOLE list
            return np.zeros(0, np.int32)
        if len(self.free) < n:
            raise RuntimeError(
                f"class {self.spec.name!r} capacity {self.capacity} exhausted "
                f"({len(self.free)} free, {n} requested)"
            )
        rows = np.asarray(self.free[-n:][::-1], np.int32)
        del self.free[-n:]
        self.live_count += n
        self.alloc_mask[rows] = True
        return rows

    def release(self, row: int) -> None:
        self.row_guid[row] = None
        self.free.append(row)
        self.alloc_mask[row] = False
        self.guid_head[row] = 0
        self.guid_data[row] = 0
        self.row_gen[row] += 1  # row recycled ⇒ any future guid differs
        self.live_count -= 1


class EntityStore:
    """Host-side owner of the device world: allocation, identity, typed
    access.  All state mutation is functional — methods take and return
    WorldState."""

    def __init__(
        self,
        registry: ClassRegistry,
        config: Optional[StoreConfig] = None,
        strings: Optional[StringTable] = None,
        guid_alloc: Optional[GuidAllocator] = None,
        class_names: Optional[Sequence[str]] = None,
    ):
        self.registry = registry
        self.config = config or StoreConfig()
        self.strings = strings or StringTable()
        self.guids = guid_alloc or GuidAllocator()
        names = list(class_names) if class_names is not None else registry.names()
        self.class_order: List[str] = names
        self.class_index: Dict[str, int] = {n: i for i, n in enumerate(names)}
        self._hosts: Dict[str, _ClassHost] = {}
        self.guid_map: Dict[Guid, int] = {}  # guid -> packed handle
        # host-path record hooks (reference NFIRecord::AddRecordHook);
        # device-path record changes are diffed by the kernel tick instead
        self.record_subs: List[RecordEventFn] = []
        for n in names:
            spec = registry.spec(n)
            self._hosts[n] = _ClassHost(
                spec, self.class_index[n], self.config.capacity_of(n)
            )

    # -- construction -------------------------------------------------------

    def init_state(self, seed: int = 0) -> WorldState:
        classes = {}
        for n in self.class_order:
            h = self._hosts[n]
            n_timers = int(self.config.timer_slots.get(n, 0))
            classes[n] = _zeros_class_state(h.spec, h.capacity, n_timers)
        return WorldState(
            classes=classes,
            tick=jnp.zeros((), jnp.int32),
            rng=jax.random.PRNGKey(seed),
        )

    def spec(self, class_name: str) -> ClassSpec:
        return self._hosts[class_name].spec

    def capacity(self, class_name: str) -> int:
        return self._hosts[class_name].capacity

    def live_count(self, class_name: str) -> int:
        return self._hosts[class_name].live_count

    # -- value encoding -----------------------------------------------------

    def encode(self, t: DataType, v: Value):
        """Host value -> device scalar/vector for a property of type t."""
        if t != DataType.OBJECT:
            v = coerce(t, v)
        if t == DataType.INT:
            return np.int32(v)
        if t == DataType.FLOAT:
            return np.float32(v)
        if t == DataType.STRING:
            return np.int32(self.strings.intern(v))
        if t == DataType.OBJECT:
            if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
                return np.int32(v)  # raw packed handle passed straight through
            v = coerce(t, v)
            if v.is_null():
                return np.int32(NULL_OBJECT)
            h = self.guid_map.get(v)
            if h is None:
                raise KeyError(f"unknown guid {v} for OBJECT property")
            return np.int32(h)
        if t == DataType.VECTOR2:
            return np.asarray([v[0], v[1], 0.0], np.float32)
        if t == DataType.VECTOR3:
            return np.asarray(v, np.float32)
        raise ValueError(f"cannot encode {t}")

    def decode(self, t: DataType, raw) -> Value:
        """Device scalar/vector -> host value."""
        if t == DataType.INT:
            return int(raw)
        if t == DataType.FLOAT:
            return float(raw)
        if t == DataType.STRING:
            return self.strings.lookup(int(raw))
        if t == DataType.OBJECT:
            h = int(raw)
            if h == NULL_OBJECT:
                return Guid()
            ci, row = unpack_handle(h)
            g = self._hosts[self.class_order[ci]].row_guid[row]
            return g if g is not None else Guid()
        if t == DataType.VECTOR2:
            a = np.asarray(raw)
            return (float(a[0]), float(a[1]))
        if t == DataType.VECTOR3:
            a = np.asarray(raw)
            return (float(a[0]), float(a[1]), float(a[2]))
        raise ValueError(f"cannot decode {t}")

    # -- create / destroy ---------------------------------------------------

    def handle_of(self, guid: Guid) -> int:
        return self.guid_map[guid]

    def guid_of_handle(self, handle: int) -> Optional[Guid]:
        h = int(handle)
        if h < 0:  # NULL_OBJECT and any other negative sentinel
            return None
        ci, row = unpack_handle(h)
        if ci >= len(self.class_order):
            return None
        return self._hosts[self.class_order[ci]].row_guid[row]

    def row_of(self, guid: Guid) -> Tuple[str, int]:
        ci, row = unpack_handle(self.guid_map[guid])
        return self.class_order[ci], row

    def create_object(
        self,
        state: WorldState,
        class_name: str,
        guid: Optional[Guid] = None,
        values: Optional[Dict[str, Value]] = None,
    ) -> Tuple[WorldState, Guid, int]:
        """Allocate one row; returns (state', guid, row).  Defaults and
        overrides are applied column-wise.  The create-event chain
        (COE_CREATE_* states, reference NFCKernelModule.cpp:251-267) is
        driven by the kernel module on top of this primitive."""
        state, guids, rows = self.create_many(
            state,
            class_name,
            1,
            guids=[guid] if guid is not None else None,
            values={k: [v] for k, v in (values or {}).items()},
        )
        return state, guids[0], rows[0]

    def create_many(
        self,
        state: WorldState,
        class_name: str,
        n: int,
        guids: Optional[Sequence[Guid]] = None,
        values: Optional[Dict[str, Sequence[Value]]] = None,
    ) -> Tuple[WorldState, List[Guid], np.ndarray]:
        """Bulk allocate n rows of class_name with per-property value
        columns.  One scatter per touched bank — this is the fast path used
        by NPC seeding and the benchmarks."""
        host = self._hosts[class_name]
        spec = host.spec
        # Stage ALL payloads and validate identities BEFORE touching any
        # host bookkeeping, so a bad property name, unknown guid, or full
        # class leaks nothing.
        i32 = np.zeros((n, spec.n_i32), np.int32)
        f32 = np.zeros((n, spec.n_f32), np.float32)
        vec = np.zeros((n, spec.n_vec, 3), np.float32)
        for slot in spec.slots.values():
            d = slot.prop.resolved_default()
            enc = self.encode(slot.prop.type, d)
            if slot.bank == Bank.I32:
                i32[:, slot.col] = enc
            elif slot.bank == Bank.F32:
                f32[:, slot.col] = enc
            else:
                vec[:, slot.col] = enc
        if values:
            for pname, col_vals in values.items():
                slot = spec.slot(pname)
                enc = [self.encode(slot.prop.type, v) for v in col_vals]
                if slot.bank == Bank.I32:
                    i32[:, slot.col] = np.asarray(enc, np.int32)
                elif slot.bank == Bank.F32:
                    f32[:, slot.col] = np.asarray(enc, np.float32)
                else:
                    vec[:, slot.col] = np.asarray(enc, np.float32)
        if guids is not None:
            if len(guids) != n:
                raise ValueError("guids length must equal n")
            if len({*guids}) != n:
                raise ValueError("duplicate guids in create_many batch")
            for g in guids:
                if g in self.guid_map:
                    raise ValueError(f"guid {g} already exists")
        if len(host.free) < n:
            raise RuntimeError(
                f"class {spec.name!r} capacity {host.capacity} exhausted "
                f"({len(host.free)} free, {n} requested)"
            )
        rows = host.alloc_many(n)
        out_guids: List[Guid] = (
            list(guids) if guids is not None else self.guids.next_batch(n)
        )
        ci = host.class_idx
        for g, row in zip(out_guids, rows.tolist()):
            self.guid_map[g] = pack_handle(ci, row)
            host.row_guid[row] = g
        host.guid_head[rows] = np.fromiter((g.head for g in out_guids), np.int64, n)
        host.guid_data[rows] = np.fromiter((g.data for g in out_guids), np.int64, n)

        cs = state.classes[class_name]
        # Fully reset the rows in ONE compiled call (banks to
        # defaults/overrides, timers off, every record cleared — recycled
        # rows must not leak the previous entity's records or heartbeat
        # schedule).  The row index and payloads pad to a power-of-2
        # bucket (repeating row 0 — idempotent duplicate writes) so
        # enter-game-sized creates reuse a cached executable instead of
        # dispatching ~15 eager scatters per object.
        if n == 0:
            return state, out_guids, rows
        m = next_pow2(n)
        if m != n:
            pad = m - n
            rows_p = np.concatenate([rows, np.repeat(rows[:1], pad)])
            i32 = np.concatenate([i32, np.repeat(i32[:1], pad, 0)])
            f32 = np.concatenate([f32, np.repeat(f32[:1], pad, 0)])
            vec = np.concatenate([vec, np.repeat(vec[:1], pad, 0)])
        else:
            rows_p = rows
        cs = _reset_and_write_rows(
            cs, jnp.asarray(rows_p), jnp.asarray(i32), jnp.asarray(f32),
            jnp.asarray(vec),
        )
        return with_class(state, class_name, cs), out_guids, rows

    def destroy_object(self, state: WorldState, guid: Guid) -> WorldState:
        class_name, row = self.row_of(guid)
        host = self._hosts[class_name]
        cs = state.classes[class_name]
        cs = cs.replace(
            alive=cs.alive.at[row].set(False),
            timers=cs.timers.replace(active=cs.timers.active.at[row].set(False)),
        )
        del self.guid_map[guid]
        host.release(row)
        return with_class(state, class_name, cs)

    def reconcile_deaths(self, state: WorldState, class_name: str) -> List[Guid]:
        """Sync host allocation with rows whose `alive` was cleared on
        device (in-tick deaths).  Returns the guids destroyed.  The device
        never allocates — it only kills — so host free-lists stay exact.
        One vector compare against the host alloc bitmap; Python touches
        only the dead rows (round-1: this scanned every capacity row)."""
        host = self._hosts[class_name]
        alive = np.asarray(state.classes[class_name].alive)
        dead_rows = np.flatnonzero(host.alloc_mask & ~alive)
        return self.release_rows(class_name, dead_rows)

    def release_rows(self, class_name: str, rows) -> List[Guid]:
        """Free exactly `rows` (device-killed) and return their guids.
        The tick-train fan-out uses this with each stacked frame's own
        died mask: the post-train alive scan of reconcile_deaths cannot
        say WHICH tick killed a row, but the per-lane mask can.  Rows
        already free are skipped, so replaying a lane is harmless."""
        host = self._hosts[class_name]
        dead: List[Guid] = []
        for row in np.asarray(rows).tolist():
            if not host.alloc_mask[row]:
                continue
            g = host.row_guid[row]
            if g is None:
                continue
            dead.append(g)
            del self.guid_map[g]
            host.release(row)
        return dead

    # -- typed property access (host control plane) -------------------------

    def set_property(
        self, state: WorldState, guid: Guid, prop_name: str, value: Value
    ) -> WorldState:
        class_name, row = self.row_of(guid)
        spec = self.spec(class_name)
        slot = spec.slot(prop_name)
        enc = self.encode(slot.prop.type, value)
        cs = state.classes[class_name]
        if slot.bank == Bank.I32:
            cs = cs.replace(i32=cs.i32.at[row, slot.col].set(enc))
        elif slot.bank == Bank.F32:
            cs = cs.replace(f32=cs.f32.at[row, slot.col].set(enc))
        else:
            cs = cs.replace(vec=cs.vec.at[row, slot.col].set(enc))
        return with_class(state, class_name, cs)

    def get_property(self, state: WorldState, guid: Guid, prop_name: str) -> Value:
        class_name, row = self.row_of(guid)
        spec = self.spec(class_name)
        slot = spec.slot(prop_name)
        cs = state.classes[class_name]
        if slot.bank == Bank.I32:
            raw = cs.i32[row, slot.col]
        elif slot.bank == Bank.F32:
            raw = cs.f32[row, slot.col]
        else:
            raw = cs.vec[row, slot.col]
        return self.decode(slot.prop.type, raw)

    # -- record access (host control plane) ---------------------------------

    def _rec(self, class_name: str, record_name: str) -> RecordSpec:
        return self.spec(class_name).records[record_name]

    def subscribe_records(self, fn: RecordEventFn) -> None:
        """Register a host-path record hook (NFIRecord::AddRecordHook):
        fired after every host record mutation with the op, the touched
        entity rows, the record row, and (for UPDATE) the column tags."""
        self.record_subs.append(fn)

    def _fire_record(
        self,
        class_name: str,
        record_name: str,
        op: RecordOp,
        entity_rows,
        rec_row: int,
        tags: Optional[Tuple[str, ...]] = None,
    ) -> None:
        if not self.record_subs:
            return
        rows = np.atleast_1d(np.asarray(entity_rows, np.int64))
        for fn in self.record_subs:
            fn(class_name, record_name, op, rows, rec_row, tags)

    def record_add_row(
        self,
        state: WorldState,
        guid: Guid,
        record_name: str,
        row_values: Dict[str, Value],
    ) -> Tuple[WorldState, int]:
        """Append a row into the first unused slot (reference
        NFCRecord::AddRow semantics)."""
        class_name, row = self.row_of(guid)
        rs = self._rec(class_name, record_name)
        rec = state.classes[class_name].records[record_name]
        used = np.asarray(rec.used[row])
        free = np.flatnonzero(~used)
        if free.size == 0:
            raise RuntimeError(f"record {record_name!r} full ({rs.max_rows} rows)")
        r = int(free[0])
        # write defaults for unspecified columns so a reused slot cannot
        # expose the deleted row's data (reference AddRow sets every cell)
        full: Dict[str, Value] = {
            tag: default_value(rs.cols[tag].col_def.type) for tag in rs.col_order
        }
        full.update(row_values)
        state = self._record_write(state, class_name, row, record_name, r, full)
        cs = state.classes[class_name]
        rec = cs.records[record_name]
        rec = rec.replace(used=rec.used.at[row, r].set(True))
        state = with_class(
            state, class_name, cs.replace(records={**cs.records, record_name: rec})
        )
        self._fire_record(class_name, record_name, RecordOp.ADD, row, r)
        return state, r

    def record_restore_row(
        self,
        state: WorldState,
        guid: Guid,
        record_name: str,
        rec_row: int,
        row_values: Dict[str, Value],
    ) -> WorldState:
        """Write a row at an exact index and mark it used — the
        persistence/load path, which must preserve row indices (the
        reference's protobuf record blobs are row-addressed)."""
        class_name, row = self.row_of(guid)
        rs = self._rec(class_name, record_name)
        full: Dict[str, Value] = {
            tag: default_value(rs.cols[tag].col_def.type) for tag in rs.col_order
        }
        full.update(row_values)
        state = self._record_write(state, class_name, row, record_name, rec_row, full)
        cs = state.classes[class_name]
        rec = cs.records[record_name]
        rec = rec.replace(used=rec.used.at[row, rec_row].set(True))
        state = with_class(
            state, class_name, cs.replace(records={**cs.records, record_name: rec})
        )
        self._fire_record(class_name, record_name, RecordOp.ADD, row, rec_row)
        return state

    def record_remove_row(
        self, state: WorldState, guid: Guid, record_name: str, rec_row: int
    ) -> WorldState:
        class_name, row = self.row_of(guid)
        cs = state.classes[class_name]
        rec = cs.records[record_name]
        rec = rec.replace(used=rec.used.at[row, rec_row].set(False))
        state = with_class(
            state, class_name, cs.replace(records={**cs.records, record_name: rec})
        )
        self._fire_record(class_name, record_name, RecordOp.DEL, row, rec_row)
        return state

    def record_swap_rows(
        self,
        state: WorldState,
        guid: Guid,
        record_name: str,
        row_origin: int,
        row_target: int,
    ) -> WorldState:
        """Exchange two record rows' contents and used flags in one op
        (reference NFCRecord::SwapRowInfo, NFCRecord.h:17-156)."""
        class_name, row = self.row_of(guid)
        cs = state.classes[class_name]
        rec = cs.records[record_name]
        pair = np.asarray([row_origin, row_target])
        swapped = np.asarray([row_target, row_origin])
        rec = rec.replace(
            i32=rec.i32.at[row, pair].set(rec.i32[row, swapped]),
            f32=rec.f32.at[row, pair].set(rec.f32[row, swapped]),
            vec=rec.vec.at[row, pair].set(rec.vec[row, swapped]),
            used=rec.used.at[row, pair].set(rec.used[row, swapped]),
        )
        state = with_class(
            state, class_name, cs.replace(records={**cs.records, record_name: rec})
        )
        self._fire_record(
            class_name, record_name, RecordOp.SWAP, row, (row_origin, row_target)
        )
        return state

    def record_set(
        self,
        state: WorldState,
        guid: Guid,
        record_name: str,
        rec_row: int,
        tag: str,
        value: Value,
    ) -> WorldState:
        class_name, row = self.row_of(guid)
        state = self._record_write(
            state, class_name, row, record_name, rec_row, {tag: value}
        )
        self._fire_record(
            class_name, record_name, RecordOp.UPDATE, row, rec_row, (tag,)
        )
        return state

    def record_get(
        self, state: WorldState, guid: Guid, record_name: str, rec_row: int, tag: str
    ) -> Value:
        class_name, row = self.row_of(guid)
        rs = self._rec(class_name, record_name)
        slot = rs.cols[tag]
        rec = state.classes[class_name].records[record_name]
        if slot.bank == Bank.I32:
            raw = rec.i32[row, rec_row, slot.col]
        elif slot.bank == Bank.F32:
            raw = rec.f32[row, rec_row, slot.col]
        else:
            raw = rec.vec[row, rec_row, slot.col]
        return self.decode(slot.col_def.type, raw)

    def record_used_rows(
        self, state: WorldState, guid: Guid, record_name: str
    ) -> List[int]:
        """Indices of used rows in an entity's record (the shared scan
        behind row-identified records: heroes, buildings, equips)."""
        class_name, row = self.row_of(guid)
        rec = state.classes[class_name].records.get(record_name)
        if rec is None:
            return []
        return [int(r) for r in np.flatnonzero(np.asarray(rec.used[row]))]

    def record_find_rows(
        self, state: WorldState, guid: Guid, record_name: str, tag: str, value: Value
    ) -> List[int]:
        """Find used rows whose `tag` column equals value (reference
        NFCRecord::FindInt/FindString family)."""
        class_name, row = self.row_of(guid)
        rs = self._rec(class_name, record_name)
        slot = rs.cols[tag]
        rec = state.classes[class_name].records[record_name]
        enc = self.encode(slot.col_def.type, value)
        if slot.bank == Bank.I32:
            col = np.asarray(rec.i32[row, :, slot.col])
        elif slot.bank == Bank.F32:
            col = np.asarray(rec.f32[row, :, slot.col])
        else:
            raise TypeError("find on vector columns unsupported")
        used = np.asarray(rec.used[row])
        return [int(i) for i in np.flatnonzero(used & (col == enc))]

    def record_write_rows(
        self,
        state: WorldState,
        class_name: str,
        rows: np.ndarray,
        record_name: str,
        rec_row: int,
        col_values: Dict[str, Sequence[Value]],
        mark_used: bool = True,
    ) -> WorldState:
        """Bulk write one record row (`rec_row`) across many entities: for
        each tag, col_values[tag][i] lands in entity rows[i].  One scatter
        per touched bank — the batch path stat seeding and equip systems
        use (host-loop-free counterpart of NFCRecord::SetInt per object)."""
        rs = self._rec(class_name, record_name)
        n = len(rows)
        staged: Dict[Bank, np.ndarray] = {}
        shapes = {
            Bank.I32: (n, rs.n_i32),
            Bank.F32: (n, rs.n_f32),
            Bank.VEC: (n, rs.n_vec, 3),
        }
        touched: Dict[Bank, List[int]] = {Bank.I32: [], Bank.F32: [], Bank.VEC: []}
        for tag, vals in col_values.items():
            slot = rs.cols[tag]
            if slot.bank not in staged:
                staged[slot.bank] = np.zeros(shapes[slot.bank], np.float32 if slot.bank != Bank.I32 else np.int32)
            enc = [self.encode(slot.col_def.type, v) for v in vals]
            staged[slot.bank][:, slot.col] = np.asarray(enc)
            touched[slot.bank].append(slot.col)
        cs = state.classes[class_name]
        rec = cs.records[record_name]
        i32, f32, vec = rec.i32, rec.f32, rec.vec
        if touched[Bank.I32]:
            cols = np.asarray(touched[Bank.I32])
            i32 = i32.at[rows[:, None], rec_row, cols[None, :]].set(staged[Bank.I32][:, cols])
        if touched[Bank.F32]:
            cols = np.asarray(touched[Bank.F32])
            f32 = f32.at[rows[:, None], rec_row, cols[None, :]].set(staged[Bank.F32][:, cols])
        if touched[Bank.VEC]:
            cols = np.asarray(touched[Bank.VEC])
            vec = vec.at[rows[:, None], rec_row, cols[None, :]].set(staged[Bank.VEC][:, cols])
        used = rec.used.at[rows, rec_row].set(True) if mark_used else rec.used
        rec = rec.replace(i32=i32, f32=f32, vec=vec, used=used)
        state = with_class(
            state, class_name, cs.replace(records={**cs.records, record_name: rec})
        )
        self._fire_record(
            class_name, record_name, RecordOp.UPDATE, rows, rec_row,
            tuple(col_values),
        )
        return state

    def _record_write(
        self,
        state: WorldState,
        class_name: str,
        row: int,
        record_name: str,
        rec_row: int,
        row_values: Dict[str, Value],
    ) -> WorldState:
        rs = self._rec(class_name, record_name)
        cs = state.classes[class_name]
        rec = cs.records[record_name]
        i32, f32, vec = rec.i32, rec.f32, rec.vec
        for tag, v in row_values.items():
            slot = rs.cols[tag]
            enc = self.encode(slot.col_def.type, v)
            if slot.bank == Bank.I32:
                i32 = i32.at[row, rec_row, slot.col].set(enc)
            elif slot.bank == Bank.F32:
                f32 = f32.at[row, rec_row, slot.col].set(enc)
            else:
                vec = vec.at[row, rec_row, slot.col].set(enc)
        rec = rec.replace(i32=i32, f32=f32, vec=vec)
        return with_class(state, class_name, cs.replace(records={**cs.records, record_name: rec}))

    # -- column views (device fast path) ------------------------------------

    def column(self, state: WorldState, class_name: str, prop_name: str) -> jnp.ndarray:
        """Whole property column [C] (or [C,3] for vectors) — the device
        fast path used inside jitted module phases."""
        slot = self.spec(class_name).slot(prop_name)
        cs = state.classes[class_name]
        if slot.bank == Bank.I32:
            return cs.i32[:, slot.col]
        if slot.bank == Bank.F32:
            return cs.f32[:, slot.col]
        return cs.vec[:, slot.col]

    def with_column(
        self, state: WorldState, class_name: str, prop_name: str, col: jnp.ndarray
    ) -> WorldState:
        slot = self.spec(class_name).slot(prop_name)
        cs = state.classes[class_name]
        if slot.bank == Bank.I32:
            cs = cs.replace(i32=cs.i32.at[:, slot.col].set(col))
        elif slot.bank == Bank.F32:
            cs = cs.replace(f32=cs.f32.at[:, slot.col].set(col))
        else:
            cs = cs.replace(vec=cs.vec.at[:, slot.col].set(col))
        return with_class(state, class_name, cs)
