"""Consistent hash ring for server selection.

Behavioral equivalent of the reference's `NFCConsistentHash.hpp:21-50`:
each real node contributes V virtual nodes hashed as
``crc32("{data}-{vindex}")`` onto a sorted ring; a key routes to the
first virtual node clockwise from ``crc32(key)``.  Used by the network
client pool to pick a game server per player GUID and by the proxy to
route clients.
"""

from __future__ import annotations

import bisect
import zlib
from typing import Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")

VIRTUAL_NODES = 500


def _crc(data: str) -> int:
    return zlib.crc32(data.encode("utf-8")) & 0xFFFFFFFF


class ConsistentHash(Generic[T]):
    def __init__(self, virtual_nodes: int = VIRTUAL_NODES) -> None:
        self._v = virtual_nodes
        self._ring: Dict[int, T] = {}
        self._keys: List[int] = []

    def add(self, name: str, node: T) -> None:
        for i in range(self._v):
            h = _crc(f"{name}-{i}")
            if h not in self._ring:
                bisect.insort(self._keys, h)
            self._ring[h] = node

    def remove(self, name: str) -> None:
        for i in range(self._v):
            h = _crc(f"{name}-{i}")
            if h in self._ring:
                del self._ring[h]
                idx = bisect.bisect_left(self._keys, h)
                if idx < len(self._keys) and self._keys[idx] == h:
                    del self._keys[idx]

    def get(self, key: str) -> Optional[T]:
        if not self._keys:
            return None
        h = _crc(key)
        idx = bisect.bisect_left(self._keys, h)
        if idx == len(self._keys):
            idx = 0
        return self._ring[self._keys[idx]]

    def __len__(self) -> int:
        return len(self._ring)

    def __bool__(self) -> bool:
        return bool(self._keys)
