"""Element (per-instance config) store.

The reference loads each class's InstancePath XML — rows of
`<Object Id="Elem" Prop="value" .../>` — into a string-keyed config map with
typed getters (NFCElementModule.cpp:43-76).  We keep that host API and add
the TPU-side view: `table()` compiles a set of element rows into dense
config arrays + an id->index map so jitted code can gather per-entity config
by an int32 `config_idx` column.
"""

from __future__ import annotations

import dataclasses
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .datatypes import Bank, DataType, Value, coerce, default_value
from .schema import ClassRegistry, ClassSpec
from .strings import StringTable


@dataclasses.dataclass
class Element:
    id: str
    class_name: str
    values: Dict[str, Value]


@dataclasses.dataclass
class ElementTable:
    """Dense config-by-index arrays for one class, for device gathers."""

    class_name: str
    ids: List[str]
    index: Dict[str, int]  # element id -> row
    i32: np.ndarray  # [n_elems, n_i32]
    f32: np.ndarray  # [n_elems, n_f32]
    vec: np.ndarray  # [n_elems, n_vec, 3]


class ElementStore:
    def __init__(self, registry: ClassRegistry, strings: Optional[StringTable] = None):
        self.registry = registry
        self.strings = strings or StringTable()
        self._elements: Dict[str, Element] = {}
        self._by_class: Dict[str, List[str]] = {}
        self._tables: Dict[str, ElementTable] = {}

    # -- loading ------------------------------------------------------------

    def add_element(self, class_name: str, elem_id: str, values: Dict[str, Value]) -> Element:
        if elem_id in self._elements:
            raise ValueError(f"element {elem_id!r} already defined")
        spec = self.registry.spec(class_name)
        coerced: Dict[str, Value] = {}
        for k, v in values.items():
            if spec.has_property(k):
                coerced[k] = coerce(spec.slot(k).prop.type, v)
            # unknown attributes are ignored, as the reference does
        e = Element(elem_id, class_name, coerced)
        self._elements[elem_id] = e
        self._by_class.setdefault(class_name, []).append(elem_id)
        self._tables.pop(class_name, None)
        return e

    def load_instance_xml(self, class_name: str, path: Path) -> int:
        """Load one reference-format Ini XML for class_name; returns count."""
        root = ET.parse(str(path)).getroot()
        n = 0
        for obj in root.findall("Object"):
            attrs = dict(obj.attrib)
            elem_id = attrs.pop("Id", None)
            if not elem_id:
                continue
            self.add_element(class_name, elem_id, attrs)
            n += 1
        return n

    def load_all(self, data_root: Path) -> int:
        """Load every class's InstancePath under data_root (reference layout:
        data_root/NFDataCfg/Ini/NPC/<Class>.xml)."""
        total = 0
        for name in self.registry.names():
            inst = self.registry.get_def(name).instance_path
            if not inst:
                continue
            p = Path(data_root) / inst
            if p.exists():
                total += self.load_instance_xml(name, p)
        return total

    # -- host getters (reference NFIElementModule API) ----------------------

    def exists(self, elem_id: str) -> bool:
        return elem_id in self._elements

    def element(self, elem_id: str) -> Element:
        return self._elements[elem_id]

    def ids_of_class(self, class_name: str) -> List[str]:
        return list(self._by_class.get(class_name, ()))

    def _get(self, elem_id: str, prop: str, t: DataType) -> Value:
        e = self._elements.get(elem_id)
        if e is None:
            return default_value(t)
        v = e.values.get(prop)
        return coerce(t, v) if v is not None else default_value(t)

    def get_int(self, elem_id: str, prop: str) -> int:
        return self._get(elem_id, prop, DataType.INT)  # type: ignore[return-value]

    def get_float(self, elem_id: str, prop: str) -> float:
        return self._get(elem_id, prop, DataType.FLOAT)  # type: ignore[return-value]

    def get_string(self, elem_id: str, prop: str) -> str:
        return self._get(elem_id, prop, DataType.STRING)  # type: ignore[return-value]

    # -- device view --------------------------------------------------------

    def table(self, class_name: str) -> ElementTable:
        """Compile (and cache) the class's elements into dense arrays laid
        out by the class's bank layout, for `config_idx` gathers in jit."""
        tab = self._tables.get(class_name)
        if tab is not None:
            return tab
        spec = self.registry.spec(class_name)
        ids = self.ids_of_class(class_name)
        n = len(ids)
        i32 = np.zeros((n, spec.n_i32), np.int32)
        f32 = np.zeros((n, spec.n_f32), np.float32)
        vec = np.zeros((n, spec.n_vec, 3), np.float32)
        for r, eid in enumerate(ids):
            e = self._elements[eid]
            for slot in spec.slots.values():
                v = e.values.get(slot.prop.name, slot.prop.resolved_default())
                t = slot.prop.type
                if slot.bank == Bank.I32:
                    if t == DataType.STRING:
                        i32[r, slot.col] = self.strings.intern(str(v))
                    elif t == DataType.OBJECT:
                        i32[r, slot.col] = -1
                    else:
                        i32[r, slot.col] = int(v)
                elif slot.bank == Bank.F32:
                    f32[r, slot.col] = float(v)
                else:
                    vv = coerce(t, v)
                    vec[r, slot.col, : len(vv)] = vv
        tab = ElementTable(
            class_name=class_name,
            ids=ids,
            index={eid: r for r, eid in enumerate(ids)},
            i32=i32,
            f32=f32,
            vec=vec,
        )
        self._tables[class_name] = tab
        return tab
