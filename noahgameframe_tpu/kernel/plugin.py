"""Plugin manager: manifest loading, module registry, lifecycle pump.

Reference equivalent: NFCPluginManager — loads Plugin.xml, dlopens each
plugin, drives the 9-phase lifecycle, lets modules find each other via
FindModule<T>(), and supports hot reload (NFCPluginManager.cpp:60-327,
211-300).  Here a plugin is a Python module exposing `create_plugin(pm)`
returning a `Plugin`; "dlopen" is importlib, and hot reload is
importlib.reload + phase recompilation.  The per-frame `run_once()` mirrors
the host side of the reference main loop (NFPluginLoader.cpp:250-273): pump
each module's host `execute()`, then run the compiled device tick once.
"""

from __future__ import annotations

import dataclasses
import importlib
import time
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Type, TypeVar

from .kernel import Kernel
from .module import LIFECYCLE, SHUTDOWN, Module

M = TypeVar("M", bound=Module)


class Plugin:
    """A named group of modules installed/uninstalled together."""

    def __init__(self, name: str, modules: Sequence[Module] = ()):
        self.name = name
        self.modules: List[Module] = list(modules)

    def add(self, module: Module) -> Module:
        self.modules.append(module)
        return module


class PluginManager:
    def __init__(self, app_id: int = 1, app_name: str = "app"):
        self.app_id = app_id
        self.app_name = app_name
        self.plugins: Dict[str, Plugin] = {}
        self._plugin_sources: Dict[str, str] = {}  # plugin name -> import path
        self.modules: Dict[str, Module] = {}
        self.kernel: Optional[Kernel] = None
        self._started = False
        self.frame = 0

    # -- registration -------------------------------------------------------

    def register_plugin(self, plugin: Plugin, source: str = "") -> Plugin:
        if plugin.name in self.plugins:
            raise ValueError(f"plugin {plugin.name!r} already registered")
        self.plugins[plugin.name] = plugin
        if source:
            self._plugin_sources[plugin.name] = source
        for m in plugin.modules:
            self._register_module(m)
        return plugin

    def _register_module(self, m: Module) -> None:
        if m.name in self.modules:
            raise ValueError(f"module {m.name!r} already registered")
        self.modules[m.name] = m
        if isinstance(m, Kernel):
            self.kernel = m
            for other in self.modules.values():
                other.kernel = m
        m.kernel = self.kernel

    def load_plugin_module(self, import_path: str) -> Plugin:
        """Import a python module and install its plugin (the dlopen +
        DllStartPlugin equivalent)."""
        mod = importlib.import_module(import_path)
        plugin = mod.create_plugin(self)
        return self.register_plugin(plugin, source=import_path)

    def load_manifest(self, path: Path) -> int:
        """Load a Plugin.xml-format manifest: <XML><Plugin Name="pkg.mod"/>
        ... (reference _Out/Debug/Plugin.xml)."""
        root = ET.parse(str(path)).getroot()
        n = 0
        for p in root.findall("Plugin"):
            self.load_plugin_module(p.get("Name", ""))
            n += 1
        return n

    def find_module(self, cls: Type[M]) -> M:
        """FindModule<T>: locate the registered instance of a module type
        (the seam all cross-module links go through)."""
        for m in self.modules.values():
            if isinstance(m, cls):
                return m  # type: ignore[return-value]
        raise KeyError(f"no module of type {cls.__name__} registered")

    def find_module_by_name(self, name: str) -> Module:
        return self.modules[name]

    # -- lifecycle ----------------------------------------------------------

    def _each(self, phase: str) -> None:
        for m in self.modules.values():
            getattr(m, phase)()

    def start(self) -> None:
        """awake → init → (kernel.build) → after_init → check_config →
        ready_execute → (compile).  Modules declare schemas and timers in
        init; the world is built before after_init so that phase can create
        seed objects."""
        if self._started:
            return
        self._each("awake")
        self._each("init")
        if self.kernel is not None:
            self.kernel.build(list(self.modules.values()))
        self._each("after_init")
        self._each("check_config")
        self._each("ready_execute")
        if self.kernel is not None:
            self.kernel.compile()
        self._started = True

    def run_once(self) -> None:
        """One frame: host execute() on every module, then the device tick."""
        for m in self.modules.values():
            if m is not self.kernel:
                m.execute()
        if self.kernel is not None:
            self.kernel.execute()
            self.kernel.tick()
        self.frame += 1

    def run(self, frames: int) -> None:
        for _ in range(frames):
            self.run_once()

    def shutdown(self) -> None:
        for phase in SHUTDOWN:
            self._each(phase)
        self._started = False

    # -- hot reload ---------------------------------------------------------

    def reload_plugin(self, name: str) -> Plugin:
        """Live-patch one plugin (reference ReLoadPlugin): shut its modules,
        re-import the source, re-install, rebuild the phase list and force
        recompilation of the tick.  World state is preserved."""
        source = self._plugin_sources.get(name)
        if source is None:
            raise KeyError(f"plugin {name!r} was not loaded from an import path")
        old = self.plugins.pop(name)
        for m in old.modules:
            m.before_shut()
            m.shut()
            self.modules.pop(m.name, None)
        mod = importlib.reload(importlib.import_module(source))
        plugin = mod.create_plugin(self)
        self.register_plugin(plugin, source=source)
        for m in plugin.modules:
            m.awake()
            m.init()
            m.after_init()
            m.ready_execute()
        if self.kernel is not None:
            # every module (including the kernel) contributes its own phases
            # exactly once; stale phases from the unloaded plugin are gone
            self.kernel.set_phases(
                [p for m in self.modules.values() for p in m.phases]
            )
            self.kernel.compile()
        return plugin
