"""Per-object component system (reference NFIComponent / NFCComponentManager).

The reference attaches named components to objects via the class XML
(`<Components><Component Name=... Enable=.../>`), clones a registered
prototype per instance (`CreateNewInstance`, NFIComponent.h:16-80), and
executes every object's enabled components from `NFCObject::Execute`
inside the kernel tick (NFCObject.cpp:42-47, NFCComponentManager.cpp).

TPU contract: components are the HOST path for divergent per-object
logic — the code that doesn't batch (scripted bosses, quest triggers,
per-object AI exceptions).  Anything batchable belongs in a Module device
phase instead; a component may itself register device phases through its
module at build time.  This is the "batchable module vs host module"
seam SURVEY §7 calls out.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type, Union

from ..core.datatypes import Guid
from .module import Module


class Component:
    """Base per-object component (NFIComponent).

    Subclass and override the lifecycle hooks; `self.kernel` and
    `self.guid` are bound before `init()`.  `new_instance` is the
    CreateNewInstance clone used when attaching to an object."""

    name: str = ""
    language: str = "python"

    def __init__(self) -> None:
        if not self.name:
            self.name = type(self).__name__
        self.enabled = True
        self.has_init = False
        self.kernel = None
        self.guid: Optional[Guid] = None

    # -- lifecycle (mirrors NFIComponent's Init/AfterInit/Execute/BeforeShut)
    def init(self) -> None: ...

    def after_init(self) -> None: ...

    def execute(self) -> None:
        """Per-frame host logic for this one object."""

    def before_shut(self) -> None: ...

    def new_instance(self) -> "Component":
        return type(self)()

    def set_enable(self, enable: bool) -> None:
        self.enabled = bool(enable)


ComponentFactory = Union[Type[Component], Callable[[], Component]]


class ComponentModule(Module):
    """Registry + per-object execution of components.

    Prototypes are registered by name; objects get instances attached
    automatically at CREATE_FINISH when their class schema lists a
    `<Component>` of that name (NFCClassModule.cpp:203-228), or manually
    via `attach`.  Instances are torn down at BEFORE_DESTROY."""

    name = "ComponentModule"

    def __init__(self) -> None:
        super().__init__()
        self._protos: Dict[str, ComponentFactory] = {}
        self._instances: Dict[Guid, List[Component]] = {}

    # -- registration --------------------------------------------------------

    def register(self, factory: ComponentFactory,
                 name: Optional[str] = None) -> None:
        """Register a component prototype under `name` (defaults to the
        class's `name`/__name__)."""
        if name is None:
            proto = factory() if not isinstance(factory, type) else None
            name = (proto.name if proto is not None
                    else (factory.name or factory.__name__))
        self._protos[name] = factory

    def _make(self, name: str) -> Optional[Component]:
        f = self._protos.get(name)
        if f is None:
            return None
        inst = f()
        if isinstance(inst, Component):
            return inst
        return None

    # -- kernel binding ------------------------------------------------------

    def after_init(self) -> None:
        from .kernel import ObjectEvent

        def on_event(guid: Guid, cname: str, ev) -> None:
            if ev == ObjectEvent.CREATE_FINISH:
                self._attach_schema_components(guid, cname)
            elif ev == ObjectEvent.BEFORE_DESTROY:
                self.detach_all(guid)

        self.kernel.register_class_event(on_event)

    def _attach_schema_components(self, guid: Guid, cname: str) -> None:
        spec = self.kernel.store.spec(cname)
        for cdef in spec.cls.components:
            inst = self._make(cdef.name)
            if inst is None:
                continue  # schema names a component no code registered
            inst.enabled = bool(getattr(cdef, "enable", True))
            self._bind(guid, inst)

    def _bind(self, guid: Guid, inst: Component) -> None:
        inst.kernel = self.kernel
        inst.guid = guid
        self._instances.setdefault(guid, []).append(inst)
        inst.init()
        inst.after_init()
        inst.has_init = True

    # -- public API (NFIKernelModule::AddComponent / FindComponent) ----------

    def attach(self, guid: Guid, component: Union[str, Component]) -> Optional[Component]:
        """Attach by registered name or from a prototype instance clone."""
        inst = (self._make(component) if isinstance(component, str)
                else component.new_instance())
        if inst is None:
            return None
        self._bind(guid, inst)
        return inst

    def find(self, guid: Guid, name: str) -> Optional[Component]:
        for c in self._instances.get(guid, ()):
            if c.name == name:
                return c
        return None

    def components_of(self, guid: Guid) -> List[Component]:
        return list(self._instances.get(guid, ()))

    def set_enable(self, guid: Guid, name: str, enable: bool) -> bool:
        c = self.find(guid, name)
        if c is None:
            return False
        c.set_enable(enable)
        return True

    def detach_all(self, guid: Guid) -> None:
        for c in self._instances.pop(guid, ()):
            try:
                c.before_shut()
            finally:
                c.kernel = None

    # -- per-frame host execution -------------------------------------------

    def execute(self) -> None:
        """The reference's per-object Execute loop, scoped to objects that
        actually carry components (everything batch lives in device
        phases, so this loop is small by construction)."""
        for comps in self._instances.values():
            for c in comps:
                if c.enabled:
                    c.execute()
