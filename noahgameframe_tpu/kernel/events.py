"""Event module: integer-ID pub/sub plus device-emitted batch events.

Host side mirrors the reference NFCEventModule: module-scope and per-object
subscriptions on integer event IDs, synchronous fan-out, removals deferred
to end-of-frame so handlers may unsubscribe during dispatch
(NFCEventModule.cpp:36-110).

Device side is the batch replacement for "fire an event per entity": a
phase calls `ctx.emit(event_id, class_name, mask, **params)` with a [C]
boolean mask (and optional per-entity param columns).  The kernel returns
these buffers from the jitted tick; after the step the event module fans
each one out — batch subscribers get the raw (mask, params) arrays, object
subscribers get scalar calls for their row only.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.datatypes import Guid
from .module import Module

# host handler signatures
ObjectEventFn = Callable[[Guid, int, Dict[str, Any]], None]
BatchEventFn = Callable[[str, np.ndarray, Dict[str, np.ndarray]], None]


@dataclasses.dataclass
class DeviceEvent:
    """One batch event emitted by a device phase during a tick."""

    event_id: int
    class_name: str
    mask: Any  # bool [C] (jnp during trace, np after fetch)
    params: Dict[str, Any]


class EventModule(Module):
    name = "EventModule"

    def __init__(self) -> None:
        super().__init__()
        self._module_subs: Dict[int, List[ObjectEventFn]] = {}
        self._object_subs: Dict[Tuple[Guid, int], List[ObjectEventFn]] = {}
        self._batch_subs: Dict[int, List[BatchEventFn]] = {}
        self._pending_removals: List[Tuple[str, Any]] = []

    # -- subscribe / unsubscribe -------------------------------------------

    def subscribe(self, event_id: int, fn: ObjectEventFn) -> None:
        self._module_subs.setdefault(int(event_id), []).append(fn)

    def subscribe_object(self, guid: Guid, event_id: int, fn: ObjectEventFn) -> None:
        self._object_subs.setdefault((guid, int(event_id)), []).append(fn)

    def subscribe_batch(self, event_id: int, fn: BatchEventFn) -> None:
        """Batch subscriber: receives (class_name, mask[C], params) per
        device event — the TPU-native consumption path."""
        self._batch_subs.setdefault(int(event_id), []).append(fn)

    def unsubscribe(self, event_id: int) -> None:
        self._pending_removals.append(("module", int(event_id)))

    def unsubscribe_object(self, guid: Guid, event_id: int) -> None:
        self._pending_removals.append(("object", (guid, int(event_id))))

    # -- host-originated synchronous dispatch ------------------------------

    def do_event(self, guid: Guid, event_id: int, args: Optional[Dict[str, Any]] = None) -> int:
        """Synchronous fan-out to object-scope then module-scope handlers;
        returns number of handlers invoked."""
        args = args or {}
        n = 0
        for fn in list(self._object_subs.get((guid, int(event_id)), ())):
            fn(guid, int(event_id), args)
            n += 1
        for fn in list(self._module_subs.get(int(event_id), ())):
            fn(guid, int(event_id), args)
            n += 1
        return n

    # -- device event fan-out (called by the kernel after each tick) -------

    def dispatch_device_events(self, events: List[DeviceEvent], store) -> None:
        for ev in events:
            mask = np.asarray(ev.mask)
            if not mask.any():
                continue
            params_np = {k: np.asarray(v) for k, v in ev.params.items()}
            for fn in list(self._batch_subs.get(ev.event_id, ())):
                fn(ev.class_name, mask, params_np)
            # per-object subscribers, only for rows they watch
            if self._object_subs or self._module_subs:
                rows = np.flatnonzero(mask)
                host = store._hosts[ev.class_name]
                for row in rows:
                    g = host.row_guid[int(row)]
                    if g is None:
                        continue
                    scalar_args = {k: v[int(row)] for k, v in params_np.items()}
                    if (g, ev.event_id) in self._object_subs or self._module_subs.get(
                        ev.event_id
                    ):
                        self.do_event(g, ev.event_id, scalar_args)

    # -- lifecycle ----------------------------------------------------------

    def execute(self) -> None:
        """Drain deferred removals (reference drains its removal lists in
        Execute, NFCEventModule.cpp:36-66)."""
        for kind, key in self._pending_removals:
            if kind == "module":
                self._module_subs.pop(key, None)
            else:
                self._object_subs.pop(key, None)
        self._pending_removals.clear()
