from .actor import ActorModule, AsyncSqlModule, Component
from .events import DeviceEvent, EventModule
from .kernel import Kernel, ObjectEvent, TickCtx, TickOutputs
from .module import Module, Phase
from .plugin import Plugin, PluginManager
from .schedule import ScheduleModule

__all__ = [
    "ActorModule",
    "AsyncSqlModule",
    "Component",
    "DeviceEvent",
    "EventModule",
    "Kernel",
    "Module",
    "ObjectEvent",
    "Phase",
    "Plugin",
    "PluginManager",
    "ScheduleModule",
    "TickCtx",
    "TickOutputs",
]
