from .actor import ActorComponent, ActorModule, AsyncSqlModule
from .component import Component as ObjectComponent
from .component import ComponentModule
from .events import DeviceEvent, EventModule
from .kernel import Kernel, ObjectEvent, TickCtx, TickOutputs
from .module import Module, Phase
from .plugin import Plugin, PluginManager
from .schedule import ScheduleModule

__all__ = [
    "ActorComponent",
    "ActorModule",
    "AsyncSqlModule",
    "ComponentModule",
    "DeviceEvent",
    "EventModule",
    "Kernel",
    "Module",
    "ObjectComponent",
    "ObjectEvent",
    "Phase",
    "Plugin",
    "PluginManager",
    "ScheduleModule",
    "TickCtx",
    "TickOutputs",
]
