"""Module protocol: the unit of framework extension.

The reference's NFIModule declares a 9-phase lifecycle driven by the plugin
manager (Awake → Init → AfterInit → CheckConfig → ReadyExecute → Execute…
→ BeforeShut → Shut → Finalize; NFIPluginManager.h:21-80, NFIPlugin.h).  We
keep that lifecycle for the host control plane and add the TPU seam: a
module may register *device phases* — pure `f(state, ctx) -> state`
functions that the kernel composes, in declared order, into ONE jit-compiled
tick.  The reference's per-object virtual `Execute()` loop
(NFCKernelModule.cpp:88-96) becomes this phase chain over whole columns.

Intra-tick ordering contract (replaces synchronous per-write callbacks):
phases run in ascending `order`; each phase sees all writes of earlier
phases (functional read-after-write).  Cross-entity reduction therefore has
one-phase granularity, which is also the determinism guarantee the golden
tests rely on.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Callable, Dict, List, Optional

import jax.numpy as jnp

if TYPE_CHECKING:
    from ..core.store import WorldState
    from .kernel import Kernel, TickCtx

PhaseFn = Callable[["WorldState", "TickCtx"], "WorldState"]


@dataclasses.dataclass(frozen=True)
class Phase:
    name: str
    fn: PhaseFn
    order: int = 100


class Module:
    """Base class for framework modules (host lifecycle + device phases)."""

    name: str = ""

    def __init__(self) -> None:
        if not self.name:
            self.name = type(self).__name__
        self.kernel: Optional["Kernel"] = None
        self._phases: List[Phase] = []

    # -- lifecycle (host), called by the plugin manager in this order -------

    def awake(self) -> None: ...

    def init(self) -> None: ...

    def after_init(self) -> None: ...

    def check_config(self) -> None: ...

    def ready_execute(self) -> None: ...

    def execute(self) -> None:
        """Per-frame host-side work (network pump, async drains).  Device
        work belongs in phases, not here."""

    def before_shut(self) -> None: ...

    def shut(self) -> None: ...

    def finalize(self) -> None: ...

    # -- checkpoint hooks (host state) ---------------------------------------

    def checkpoint_state(self) -> Optional[dict]:
        """JSON-serializable host state to include in a world checkpoint
        (persist/checkpoint.py).  Device state checkpoints automatically;
        modules holding host-side maps (teams, mailboxes, rank lists…)
        override this so resume really resumes.  None = nothing to save."""
        return None

    def restore_state(self, data: dict) -> None:
        """Inverse of checkpoint_state, called after the device state and
        identity maps are restored (guids resolve again)."""

    # -- device phase registration ------------------------------------------

    def add_phase(self, name: str, fn: PhaseFn, order: int = 100) -> None:
        self._phases.append(Phase(f"{self.name}.{name}", fn, order))

    @property
    def phases(self) -> List[Phase]:
        return list(self._phases)

    def clear_phases(self) -> None:
        self._phases.clear()


LIFECYCLE = (
    "awake",
    "init",
    "after_init",
    "check_config",
    "ready_execute",
)
SHUTDOWN = ("before_shut", "shut", "finalize")
