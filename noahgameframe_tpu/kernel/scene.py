"""Scene/group module: world partitioning + broadcast-set computation.

Reference: NFCSceneAOIModule — scenes hold numbered groups; "AOI" is
group-granular broadcast (NOT spatial): any Public-flagged change fans out
to all Players in the same (scene, group); enter/leave choreography runs on
GroupID/SceneID property changes with before/after hook vectors, and
creating a group seeds its NPCs (NFCSceneAOIModule.cpp:82-160, 292-430,
531-593; data model NFISceneAOIModule.h:36-145).

TPU mapping: (SceneID, GroupID) are int32 columns in each class's i32 bank,
so membership queries and broadcast sets are vectorised compares on device;
`cell_key` (scene*MAX_GROUPS+group) is the partition key the sharding layer
and the spatial-AOI ops both use.  Enter/leave stays host-side control
plane (it is rare relative to the tick) and preserves the reference's hook
ordering; true spatial neighbor queries live in ops/aoi.py.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.datatypes import Guid, Value
from ..core.store import WorldState
from .module import Module

MAX_GROUPS_PER_SCENE = 1024  # fixes the cell_key encoding

# hook signature: (guid, scene_id, group_id)
SceneHookFn = Callable[[Guid, int, int], None]


class GroupIdsExhausted(RuntimeError):
    """A scene's group-id space (MAX_GROUPS_PER_SCENE) is fully minted
    AND nothing sits on the free list.

    Typed so long-lived churn drivers (room directories cycling dungeon
    instances for hours) can catch it and shed load instead of dying on
    a bare RuntimeError mid-choreography.  Released ids recycle through
    ``SceneInfo.free_groups`` (release_group appends, request_group pops),
    so steady-state create/destroy churn never raises this — only >1023
    groups truly live at once in one scene does."""

    def __init__(self, scene_id: int, limit: int = MAX_GROUPS_PER_SCENE):
        self.scene_id = int(scene_id)
        self.limit = int(limit)
        super().__init__(
            f"scene {scene_id} group ids exhausted "
            f"({limit} live groups, none released)"
        )


@dataclasses.dataclass
class SeedSpec:
    """An NPC seed planted in a scene: spawned into every new group
    (reference scene Ini files list seed NPCs per scene)."""

    elem_id: str
    class_name: str
    position: Tuple[float, float, float] = (0.0, 0.0, 0.0)
    values: Optional[Dict[str, Value]] = None


@dataclasses.dataclass
class GroupInfo:
    group_id: int
    seeded: List[Guid] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SceneInfo:
    scene_id: int
    seeds: List[SeedSpec] = dataclasses.field(default_factory=list)
    groups: Dict[int, GroupInfo] = dataclasses.field(default_factory=dict)
    next_group: int = 1
    width: float = 512.0  # world extent, used by spatial AOI grids
    # released group ids, recycled by request_group — clone scenes churn
    # an instance per enter, and the id space is MAX_GROUPS_PER_SCENE
    free_groups: List[int] = dataclasses.field(default_factory=list)


class SceneModule(Module):
    name = "SceneModule"

    def __init__(self) -> None:
        super().__init__()
        self.scenes: Dict[int, SceneInfo] = {}
        # the reference's 10 callback vectors (NFCSceneAOIModule.h:95-105)
        # collapse to 6 hook lists with identical ordering guarantees
        self.before_enter_scene: List[SceneHookFn] = []
        self.after_enter_scene: List[SceneHookFn] = []
        self.before_leave_scene: List[SceneHookFn] = []
        self.after_leave_scene: List[SceneHookFn] = []
        self.on_swap_group: List[SceneHookFn] = []
        self.on_group_created: List[Callable[[int, int], None]] = []

    # -- scene / group management ------------------------------------------

    def create_scene(
        self, scene_id: int, seeds: Sequence[SeedSpec] = (), width: float = 512.0
    ) -> SceneInfo:
        if scene_id in self.scenes:
            raise ValueError(f"scene {scene_id} already exists")
        info = SceneInfo(scene_id=scene_id, seeds=list(seeds), width=width)
        self.scenes[scene_id] = info
        # group 0 always exists: the scene's "lobby" (reference creates
        # group 0 implicitly; GroupID 0 broadcasts scene-wide)
        info.groups[0] = GroupInfo(0)
        return info

    def request_group(
        self, scene_id: int, seed_npcs: bool = True,
        group_id: Optional[int] = None,
    ) -> int:
        """Allocate a group in a scene and seed its NPCs (reference
        RequestGroupScene).  With `group_id` the caller picks the id (it
        must be free); otherwise released ids are recycled before fresh
        ones are minted."""
        info = self.scenes[scene_id]
        if group_id is not None:
            gid = int(group_id)
            if gid <= 0 or gid >= MAX_GROUPS_PER_SCENE:
                raise ValueError(f"group id {gid} out of range")
            if gid in info.groups:
                raise ValueError(f"group {gid} already exists in scene {scene_id}")
            if gid in info.free_groups:
                info.free_groups.remove(gid)
            info.next_group = max(info.next_group, gid + 1)
        elif info.free_groups:
            gid = info.free_groups.pop()
        else:
            gid = info.next_group
            info.next_group += 1
            if gid >= MAX_GROUPS_PER_SCENE:
                raise GroupIdsExhausted(scene_id)
        group = GroupInfo(gid)
        info.groups[gid] = group
        if seed_npcs:
            for seed in info.seeds:
                g = self.kernel.create_from_element(
                    seed.class_name,
                    seed.elem_id,
                    overrides={**(seed.values or {}), "Position": seed.position},
                    scene=scene_id,
                    group=gid,
                )
                group.seeded.append(g)
        for fn in self.on_group_created:
            fn(scene_id, gid)
        return gid

    def release_group(self, scene_id: int, group_id: int) -> int:
        """Destroy a group and everything in it; returns destroyed count
        (reference ReleaseGroupScene)."""
        info = self.scenes[scene_id]
        existed = info.groups.pop(group_id, None) is not None
        n = 0
        for class_name in self.kernel.store.class_order:
            for guid in self.objects_in_group(scene_id, group_id, class_name):
                self.kernel.destroy_object(guid)
                n += 1
        if existed and group_id not in info.free_groups:
            info.free_groups.append(group_id)
        return n

    # -- enter / leave choreography ----------------------------------------

    def enter_scene(self, guid: Guid, scene_id: int, group_id: int) -> None:
        """Full enter pipeline with before/after hooks on both sides
        (reference RequestEnterScene + OnGroupEvent/OnSceneEvent)."""
        if scene_id not in self.scenes:
            raise KeyError(f"scene {scene_id} does not exist")
        if group_id not in self.scenes[scene_id].groups:
            raise KeyError(f"group {group_id} does not exist in scene {scene_id}")
        k = self.kernel
        old_scene = int(k.get_property(guid, "SceneID"))
        old_group = int(k.get_property(guid, "GroupID"))
        if old_scene == scene_id and old_group == group_id:
            return
        for fn in self.before_leave_scene:
            fn(guid, old_scene, old_group)
        for fn in self.before_enter_scene:
            fn(guid, scene_id, group_id)
        k.set_property(guid, "GroupID", 0)  # leave old group first
        k.set_property(guid, "SceneID", scene_id)
        k.set_property(guid, "GroupID", group_id)
        for fn in self.after_leave_scene:
            fn(guid, old_scene, old_group)
        for fn in self.after_enter_scene:
            fn(guid, scene_id, group_id)
        if old_scene == scene_id:
            for fn in self.on_swap_group:
                fn(guid, scene_id, group_id)

    # -- membership queries -------------------------------------------------

    def _member_rows(self, scene_id: int, group_id: Optional[int], class_name: str) -> np.ndarray:
        k = self.kernel
        state = k.state
        spec = k.store.spec(class_name)
        if not (spec.has_property("SceneID") and spec.has_property("GroupID")):
            return np.asarray([], np.int64)
        cs = state.classes[class_name]
        scene_col = np.asarray(cs.i32[:, spec.slots["SceneID"].col])
        alive = np.asarray(cs.alive)
        m = alive & (scene_col == scene_id)
        if group_id is not None:
            group_col = np.asarray(cs.i32[:, spec.slots["GroupID"].col])
            m &= group_col == group_id
        return np.flatnonzero(m)

    def objects_in_group(
        self, scene_id: int, group_id: int, class_name: str
    ) -> List[Guid]:
        """GetGroupObjectList equivalent."""
        host = self.kernel.store._hosts[class_name]
        return [
            host.row_guid[int(r)]
            for r in self._member_rows(scene_id, group_id, class_name)
            if host.row_guid[int(r)] is not None
        ]

    def objects_in_scene(self, scene_id: int, class_name: str) -> List[Guid]:
        host = self.kernel.store._hosts[class_name]
        return [
            host.row_guid[int(r)]
            for r in self._member_rows(scene_id, None, class_name)
            if host.row_guid[int(r)] is not None
        ]

    def broadcast_targets(
        self, guid: Guid, public: bool, player_class: str = "Player"
    ) -> List[Guid]:
        """GetBroadCastObject: Public changes go to every player in the
        same (scene, group) — GroupID 0 means scene-wide — Private changes
        go to self only (if self is a player)
        (NFCSceneAOIModule.cpp:531-593)."""
        k = self.kernel
        class_name, _ = k.store.row_of(guid)
        if not public:
            return [guid] if class_name == player_class else []
        scene = int(k.get_property(guid, "SceneID"))
        group = int(k.get_property(guid, "GroupID"))
        if group == 0:
            return self.objects_in_scene(scene, player_class)
        return self.objects_in_group(scene, group, player_class)

    # -- device views --------------------------------------------------------

    def cell_key(self, state: WorldState, class_name: str) -> jnp.ndarray:
        """[C] int32 partition key = scene*MAX_GROUPS+group; the unit of
        broadcast, sharding and AOI locality."""
        spec = self.kernel.store.spec(class_name)
        cs = state.classes[class_name]
        scene = cs.i32[:, spec.slots["SceneID"].col]
        group = cs.i32[:, spec.slots["GroupID"].col]
        return scene * MAX_GROUPS_PER_SCENE + group
