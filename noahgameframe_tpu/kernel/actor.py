"""Actor module: thread-pool offload with main-loop result marshalling.

Reference: NFActorPlugin — a Theron framework with N worker threads;
`RequireActor()` spawns an actor, `SendMsgToActor` posts
`NFIActorMessage{nMsgID, self, data}` to its mailbox, the actor's
component processes it on a pool thread, and the result returns through a
spin-locked `NFQueue` drained on the main thread, which invokes the
registered end-functor (`NFCActorModule.cpp:18-119`).  The pattern is
*offload → compute on pool → marshal back to the single-threaded main
loop* — game state is only ever touched from the main thread.

Here actors are mailbox wrappers over a shared `ThreadPoolExecutor`
(messages to ONE actor stay ordered; different actors run concurrently),
and `execute()` drains the finished-work queue exactly like the
reference.  The TPU kernel doesn't need this for compute (the tick is
jitted), but the host control plane does: async persistence, blocking
IO, codegen — anything that must not stall the 1 ms main loop.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

from .module import Module

# component handler: (msg_id, payload) -> result payload
HandlerFn = Callable[[int, Any], Any]
# end functor invoked on the main thread: (actor_id, msg_id, result)
EndFn = Callable[[int, int, Any], None]


class ActorComponent:
    """Per-actor logic unit (reference NFIComponent / NFCMysqlComponent):
    register handlers per message id; runs on pool threads, so it must
    not touch world state — results flow back via the end functor."""

    def __init__(self) -> None:
        self._handlers: Dict[int, HandlerFn] = {}
        self._default: Optional[HandlerFn] = None

    def on(self, msg_id: int, fn: HandlerFn) -> None:
        self._handlers[int(msg_id)] = fn

    def on_any(self, fn: HandlerFn) -> None:
        self._default = fn

    def handle(self, msg_id: int, data: Any) -> Any:
        fn = self._handlers.get(int(msg_id), self._default)
        if fn is None:
            raise KeyError(f"component has no handler for msg {msg_id}")
        return fn(msg_id, data)


class _Actor:
    """One mailbox: messages execute in order on the shared pool."""

    def __init__(self, actor_id: int, component: ActorComponent,
                 pool: ThreadPoolExecutor, done: "queue.Queue") -> None:
        self.actor_id = actor_id
        self.component = component
        self._pool = pool
        self._done = done
        self._mailbox: "queue.Queue" = queue.Queue()
        self._running = False
        self._lock = threading.Lock()

    def post(self, msg_id: int, data: Any, end_fn: Optional[EndFn]) -> None:
        self._mailbox.put((msg_id, data, end_fn))
        with self._lock:
            if not self._running:
                self._running = True
                self._pool.submit(self._drain)

    def _drain(self) -> None:
        while True:
            try:
                msg_id, data, end_fn = self._mailbox.get_nowait()
            except queue.Empty:
                with self._lock:
                    if self._mailbox.empty():
                        self._running = False
                        return
                continue
            try:
                result = self.component.handle(msg_id, data)
                err = None
            except Exception as e:  # marshal errors back too
                result, err = None, e
            self._done.put((self.actor_id, msg_id, result, err, end_fn))


class ActorModule(Module):
    """RequireActor / SendMsgToActor / main-loop drain."""

    name = "ActorModule"

    def __init__(self, threads: int = 4) -> None:
        super().__init__()
        self._pool = ThreadPoolExecutor(max_workers=threads,
                                        thread_name_prefix="nf-actor")
        self._done: "queue.Queue" = queue.Queue()
        self._actors: Dict[int, _Actor] = {}
        self._next_id = 1
        self._default_end: List[EndFn] = []
        self._errors: List[Exception] = []

    # -- reference-parity API -------------------------------------------
    def require_actor(self, component: Optional[ActorComponent] = None) -> int:
        """Spawn an actor around `component` and return its id."""
        actor_id = self._next_id
        self._next_id += 1
        self._actors[actor_id] = _Actor(
            actor_id, component or ActorComponent(), self._pool, self._done
        )
        return actor_id

    def component(self, actor_id: int) -> ActorComponent:
        return self._actors[actor_id].component

    def send_to_actor(self, actor_id: int, msg_id: int, data: Any,
                      end_fn: Optional[EndFn] = None) -> bool:
        actor = self._actors.get(actor_id)
        if actor is None:
            return False
        actor.post(int(msg_id), data, end_fn)
        return True

    def release_actor(self, actor_id: int) -> None:
        self._actors.pop(actor_id, None)

    def on_result(self, fn: EndFn) -> None:
        """Fallback end functor for posts that didn't carry one."""
        self._default_end.append(fn)

    # -- main-loop drain -------------------------------------------------
    def execute(self) -> int:
        """Deliver finished work to end functors on the caller's thread
        (the ExecuteEvent drain, `NFCActorModule.cpp:77-101`)."""
        delivered = 0
        while True:
            try:
                actor_id, msg_id, result, err, end_fn = self._done.get_nowait()
            except queue.Empty:
                return delivered
            if err is not None:
                # record, but still deliver (result=None) so waiters make
                # progress — a failed op must not strand its callback
                self._errors.append(err)
            if end_fn is not None:
                end_fn(actor_id, msg_id, result)
            else:
                for fn in self._default_end:
                    fn(actor_id, msg_id, result)
            delivered += 1

    def drain_until(self, n: int, timeout: float = 5.0) -> int:
        """Testing/shutdown aid: pump until n results delivered."""
        import time as _t

        end = _t.monotonic() + timeout
        total = 0
        while total < n and _t.monotonic() < end:
            total += self.execute()
            _t.sleep(0.001)
        return total

    def pop_errors(self) -> List[Exception]:
        out, self._errors = self._errors, []
        return out

    def shut(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._actors.clear()


class AsyncSqlComponent(ActorComponent):
    """Async relational persistence: each request runs on the actor,
    mirroring NFCAsyMysqlModule shipping serialized args to a
    NFCMysqlComponent on a pool actor (`NFCAsyMysqlModule.cpp:558-599`)."""

    OP_UPDATA, OP_QUERY, OP_SELECT, OP_DELETE, OP_EXISTS, OP_KEYS = range(6)

    def __init__(self, sql) -> None:
        super().__init__()
        self.sql = sql
        self.on(self.OP_UPDATA,
                lambda _m, a: self.sql.updata(a["table"], a["key"],
                                              a["fields"], a["values"]))
        self.on(self.OP_QUERY,
                lambda _m, a: self.sql.query(a["table"], a["key"], a["fields"]))
        self.on(self.OP_SELECT,
                lambda _m, a: self.sql.select(a["table"], a["key"]))
        self.on(self.OP_DELETE,
                lambda _m, a: self.sql.delete(a["table"], a["key"]))
        self.on(self.OP_EXISTS,
                lambda _m, a: self.sql.exists(a["table"], a["key"]))
        self.on(self.OP_KEYS,
                lambda _m, a: self.sql.keys(a["table"], a.get("like", "%")))


class AsyncSqlModule(Module):
    """The NFCAsyMysqlModule shape: fire-and-callback DB ops that never
    block the main loop; results arrive during ActorModule.execute()."""

    name = "AsyncSqlModule"

    def __init__(self, actors: ActorModule, sql) -> None:
        super().__init__()
        self.actors = actors
        self.actor_id = actors.require_actor(AsyncSqlComponent(sql))

    def _post(self, op: int, args: dict,
              cb: Optional[Callable[[Any], None]]) -> bool:
        end = (lambda _a, _m, result: cb(result)) if cb is not None else None
        return self.actors.send_to_actor(self.actor_id, op, args, end)

    def updata(self, table: str, key: str, fields, values,
               cb: Optional[Callable[[Any], None]] = None) -> bool:
        return self._post(AsyncSqlComponent.OP_UPDATA,
                          {"table": table, "key": key, "fields": fields,
                           "values": values}, cb)

    def query(self, table: str, key: str, fields,
              cb: Optional[Callable[[Any], None]] = None) -> bool:
        return self._post(AsyncSqlComponent.OP_QUERY,
                          {"table": table, "key": key, "fields": fields}, cb)

    def select(self, table: str, key: str,
               cb: Optional[Callable[[Any], None]] = None) -> bool:
        return self._post(AsyncSqlComponent.OP_SELECT,
                          {"table": table, "key": key}, cb)

    def delete(self, table: str, key: str,
               cb: Optional[Callable[[Any], None]] = None) -> bool:
        return self._post(AsyncSqlComponent.OP_DELETE,
                          {"table": table, "key": key}, cb)

    def exists(self, table: str, key: str,
               cb: Optional[Callable[[Any], None]] = None) -> bool:
        return self._post(AsyncSqlComponent.OP_EXISTS,
                          {"table": table, "key": key}, cb)

    def keys(self, table: str, like: str = "%",
             cb: Optional[Callable[[Any], None]] = None) -> bool:
        return self._post(AsyncSqlComponent.OP_KEYS,
                          {"table": table, "like": like}, cb)
