"""The kernel: object lifecycle + the jit-compiled world tick.

Reference equivalent: NFCKernelModule (object store, create/destroy with the
COE_* create-event chain, property/record access by GUID, common event
fan-out) plus the per-frame Execute loop over every object
(NFCKernelModule.cpp:70-99, 251-308).  Here the per-frame work is ONE
compiled function:

    state', outputs = step(state)

where `step` = schedule advance (vectorised heartbeats) → registered module
phases in order → dirty-diff extraction + death detection, all fused by XLA.
Host-side reactive semantics (the mutate → flags decide visibility →
subscribers converge chain, SURVEY §3.3) are preserved batch-wise: the tick
returns per-bank changed masks (pre-masked by the Public/Upload flags) and
per-class death masks; the kernel fans those out to host subscribers after
each tick, fetching device data only when someone is listening.
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
from fnmatch import fnmatch
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.datatypes import Bank, Guid, Value
from ..core.element import ElementStore
from ..core.schema import ClassRegistry
from ..core.store import EntityStore, StoreConfig, WorldState
from .events import DeviceEvent, EventModule
from .module import Module, Phase
from .schedule import ScheduleModule


class ObjectEvent(enum.IntEnum):
    """Create/destroy state chain, mirroring the reference's
    CLASS_OBJECT_EVENT / COE_* states (NFIObject.h:22-30)."""

    CREATE_NODATA = 0
    CREATE_LOADDATA = 1
    CREATE_BEFORE_EFFECT = 2
    CREATE_EFFECTDATA = 3
    CREATE_AFTER_EFFECT = 4
    CREATE_HASDATA = 5
    CREATE_FINISH = 6
    BEFORE_DESTROY = 7
    DESTROY = 8

ClassEventFn = Callable[[Guid, str, "ObjectEvent"], None]
PropertyEventFn = Callable[[str, str, np.ndarray], None]  # (class, prop, changed_rows)
# (class, record, codes[C, R] int8) — 0 none, 1 added, 2 removed, 3 updated
RecordDiffFn = Callable[[str, str, np.ndarray], None]

REC_NONE, REC_ADDED, REC_REMOVED, REC_UPDATED = 0, 1, 2, 3

# multiplier for the rolling state-digest fold (odd, so it is invertible
# mod 2^32 and single-bit flips diffuse instead of cancelling)
_DIGEST_MULT = 1000003

# Every per-tick output lane of _trace_step that host code consumes must
# match one of these patterns (or appear in TRAIN_EXCLUDED with a
# reason): the K-tick train stacks exactly these lanes into [K, ...]
# device arrays, and a lane missing from the stack would silently lose
# its per-tick history inside a train (journal digests, death masks,
# event params all ride here).  The train-lanes-covered nf-lint rule
# cross-checks this tuple against every `out[...]` consumer statically;
# _assert_train_lanes enforces it at trace time.  Keep it a plain
# literal (the ROW_LEAF_SPEC / ROOM_PACK_SPEC contract).
TRAIN_LANE_SPEC = (
    "fired",
    "diff",
    "diff_count",
    "rec_diff",
    "rec_diff_count",
    "died",
    "died_count",
    "events",
    "summary",
)

# Out-dict lanes waived from train stacking, with a reason each.
# (none today — every per-tick output is host-consumed)
TRAIN_EXCLUDED = ()


def _assert_train_lanes(out: Dict[str, object]) -> None:
    """Trace-time coverage assert for the train's stacked lane set.

    Both directions, like world_room_leaf_items: an out lane not named
    by TRAIN_LANE_SPEC/TRAIN_EXCLUDED means a new per-tick output was
    added without deciding its train fate; a spec pattern matching no
    lane is stale and must be pruned."""
    spec = TRAIN_LANE_SPEC + TRAIN_EXCLUDED
    unlisted = [k for k in out if not any(fnmatch(k, p) for p in spec)]
    stale = [p for p in spec if not any(fnmatch(k, p) for k in out)]
    if unlisted or stale:
        raise AssertionError(
            "TRAIN_LANE_SPEC drift: "
            f"unlisted out lanes {unlisted}, stale patterns {stale}"
        )


def _digest_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Reinterpret any bank dtype as uint32 words, bit-exactly for f32
    (a digest over *rounded* floats would call two bitwise-different
    states equal — the one thing replay must never do)."""
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint32)
    if x.dtype in (jnp.float32, jnp.int32):
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    return x.astype(jnp.uint32)


def state_digest(state, class_order: Sequence[str]) -> jnp.ndarray:
    """One uint32 digest of the whole device-resident world.

    Position-weighted modular sums per bank, folded across banks with a
    rolling multiply — pure uint32 arithmetic, so the reduction is
    associative/commutative (wraparound add) and the result is
    bit-identical across backends and shardings whenever the state
    arrays are.  WorldState.aux is deliberately EXCLUDED: caches there
    (Verlet tables) are rebuilt from scratch on resume and masked out of
    results, so their contents differ between a live run and a
    checkpoint-restored replay of the same world.
    """

    def fold(acc: jnp.ndarray, arr: jnp.ndarray) -> jnp.ndarray:
        x = _digest_u32(arr).ravel()
        w = jnp.arange(x.shape[0], dtype=jnp.uint32) * 2 + 1
        return acc * jnp.uint32(_DIGEST_MULT) + jnp.sum(x * w, dtype=jnp.uint32)

    acc = jnp.uint32(0x9E3779B9)
    acc = fold(acc, state.tick)
    acc = fold(acc, state.rng)
    for cname in class_order:
        cs = state.classes[cname]
        for arr in (cs.i32, cs.f32, cs.vec, cs.alive,
                    cs.timers.next_fire, cs.timers.interval,
                    cs.timers.remain, cs.timers.active):
            acc = fold(acc, arr)
        for rname in sorted(cs.records):
            rec = cs.records[rname]
            for arr in (rec.i32, rec.f32, rec.vec, rec.used):
                acc = fold(acc, arr)
    return acc


class TickCtx:
    """Per-tick context handed to device phases during tracing."""

    def __init__(
        self,
        kernel: "Kernel",
        tick: jnp.ndarray,
        rng: jnp.ndarray,
        fired_masks: Dict[str, jnp.ndarray],
    ):
        self.kernel = kernel
        self.store = kernel.store
        self.tick = tick
        self.dt = kernel.schedule.dt
        self._rng = rng
        self._rng_count = 0
        self._fired = fired_masks
        self.emitted: List[DeviceEvent] = []
        # named int32 scalars accumulated on device across phases; the
        # kernel packs them into the per-tick summary fetch (counter bank)
        self._counters: Dict[str, jnp.ndarray] = {}
        # rooms sharing this trace: 1 for an ordinary world, R when the
        # kernel is a room-batch template (the step is vmapped, so every
        # traced value a phase sees is ONE room's slice; this is static
        # trace-time metadata for phases that size host mirrors)
        self.room_count = (
            1 if kernel.room_batch is None else kernel.room_batch.capacity
        )

    def fired(self, class_name: str, timer_name: str) -> jnp.ndarray:
        """[C] bool — which entities' `timer_name` fired this tick."""
        slot = self.kernel.schedule.slot(class_name, timer_name)
        return self._fired[class_name][:, slot]

    def remap_fired(self, class_name: str, fired: jnp.ndarray) -> None:
        """Republish a class's [C, T] fired mask after a phase permuted its
        rows.  The schedule computes fired masks BEFORE phases run, so a
        phase that moves rows (cross-shard migration) must move the mask
        with them — otherwise a row that migrates mid-tick leaves its fire
        behind on a now-dead slot and later handlers silently skip it."""
        self._fired[class_name] = fired

    def rng(self) -> jnp.ndarray:
        """A fresh PRNG key (deterministic per tick + call position)."""
        self._rng_count += 1
        return jax.random.fold_in(self._rng, self._rng_count)

    def emit(
        self, event_id: int, class_name: str, mask: jnp.ndarray, **params: jnp.ndarray
    ) -> None:
        """Emit a batch event from inside the tick; delivered to host/batch
        subscribers after the step (device replacement for DoEvent).

        The (event_id, class_name) metadata is static per compilation; only
        mask/params are traced values."""
        self.emitted.append(DeviceEvent(int(event_id), class_name, mask, dict(params)))

    def count(self, name: str, value) -> None:
        """Accumulate into the tick's on-device counter bank.  `value` is
        any traced array — bool masks and int vectors are summed to one
        int32 scalar.  Counters ride the packed summary vector the host
        already fetches each tick, so observing them adds ZERO device
        syncs; the name set is static per compilation (phases decide what
        they count at trace time, like event metadata)."""
        v = jnp.asarray(value)
        if v.ndim:
            v = jnp.sum(v, dtype=jnp.int32)
        v = v.astype(jnp.int32)
        prev = self._counters.get(name)
        self._counters[name] = v if prev is None else prev + v


@dataclasses.dataclass
class TickOutputs:
    """Device-resident tick results; host fetches lazily."""

    fired: Dict[str, jnp.ndarray]  # class -> [C, T] bool
    diff: Dict[str, Dict[str, jnp.ndarray]]  # class -> bank -> [C, ncols] bool
    diff_count: Dict[str, jnp.ndarray]  # class -> scalar changed-cell count
    died: Dict[str, jnp.ndarray]  # class -> [C] bool
    died_count: Dict[str, jnp.ndarray]  # class -> scalar
    events: List[DeviceEvent]
    # class -> record -> [C, R] int8 row-change codes (REC_* constants);
    # only populated for (class, record) pairs with a registered
    # record-diff subscriber — unsubscribed records cost zero device work
    rec_diff: Dict[str, Dict[str, jnp.ndarray]] = dataclasses.field(
        default_factory=dict
    )
    rec_diff_count: Dict[str, jnp.ndarray] = dataclasses.field(default_factory=dict)
    # counter bank decoded from the summary fetch: name -> host int
    # (events fired, diff cells, deaths, combat hits, AOI overflow drops
    # + anything phases ctx.count()ed) — already on host, free to read
    counters: Dict[str, int] = dataclasses.field(default_factory=dict)


class Kernel(Module):
    """Owns the world: registry + store + state + the compiled tick."""

    name = "KernelModule"

    def __init__(
        self,
        registry: ClassRegistry,
        store_config: Optional[StoreConfig] = None,
        dt: float = 1.0 / 30.0,
        seed: int = 0,
        class_names: Optional[Sequence[str]] = None,
        diff_flags: Tuple[str, ...] = ("public", "upload"),
    ):
        super().__init__()
        self.registry = registry
        self.store_config = store_config or StoreConfig()
        self.schedule = ScheduleModule(dt=dt)
        self.events = EventModule()
        self.elements = ElementStore(registry)
        self._class_names = class_names
        self._seed = seed
        self._diff_flags = diff_flags
        self.store: Optional[EntityStore] = None
        self.state: Optional[WorldState] = None
        # device cost observatory (telemetry/costbook.py): every jit
        # entry the kernel owns dispatches through this ledger — compile
        # time, cost/memory analysis, retrace cause attribution.  Built
        # here (not by telemetry) so bare-kernel benches record too;
        # TelemetryModule.attach_kernel adopts it for /costbook+metrics.
        # Deferred import: telemetry.module imports kernel.module.
        from ..telemetry.costbook import CostBook

        self.costbook = CostBook()
        # the composed, sorted phase chain the tick runs; the kernel's OWN
        # phases (added via Module.add_phase) stay in self._phases like any
        # other module's so composition can't double-count them
        self._composed: List[Phase] = []
        self._jit_step = None
        self._jit_run = None
        # K-tick train (NF_TICK_TRAIN): one lax.scan dispatch covering
        # _train_k frames with every host-consumed lane stacked [K, ...]
        # (TRAIN_LANE_SPEC).  K is a compile-time constant of the train
        # executable (lax.scan lengths are static by construction);
        # ragged tails ride kernel.step, so one train compile + the
        # always-present step compile serve every run length.
        self._jit_train = None
        self._train_k = 0
        # train accounting, surfaced as nf_train_*_total by telemetry
        self.train_dispatches = 0
        self.train_ticks = 0
        self.train_fetch_bytes = 0
        # monotonically bumped whenever the compiled tick is dropped
        # (invalidate / set_phases) so WRAPPING compilers — ShardedKernel
        # keeps its own jitted variants of _trace_step — can notice and
        # drop theirs too instead of dispatching a stale trace
        self._trace_gen = 0
        self._class_event_subs: List[ClassEventFn] = []
        self._class_event_by_class: Dict[str, List[ClassEventFn]] = {}
        self._prop_event_subs: Dict[Tuple[str, str], List[PropertyEventFn]] = {}
        # class -> props opted into diff extraction beyond diff_flags
        # (debug tools — the property trail); changes invalidate the tick
        self._forced_diff: Dict[str, set] = {}
        self._rec_event_subs: Dict[Tuple[str, str], List[RecordDiffFn]] = {}
        self._pending_destroy: List[Guid] = []
        self._event_meta: List[Tuple[int, str, Tuple[str, ...]]] = []
        self.tick_count = 0
        # module-registered carried tick state (WorldState.aux): name ->
        # zero-arg init fn.  Entries are primed lazily right before
        # dispatch (_ensure_aux) so registration order vs build order
        # doesn't matter, and invalidate() drops them (aux layouts bake
        # trace-time geometry, e.g. Verlet slot assignments)
        self._aux_init: Dict[str, Callable[[], Any]] = {}
        # counter-bank decode order, captured at trace time like
        # _event_meta (static per compilation)
        self._counter_names: Tuple[str, ...] = ()
        self.last_counters: Dict[str, int] = {}  # latest observed tick
        self.counter_totals: Dict[str, int] = {}  # cumulative over tick()s
        # when set, the tick folds a uint32 digest of the post-tick state
        # into the counter bank ("state_digest") — the flight recorder's
        # per-tick fingerprint, riding the summary fetch at zero extra
        # syncs.  Flip via enable_digest() so the tick is retraced.
        self.digest_enabled = False
        # optional telemetry.SpanTracer for host-side tick stage spans
        # (dispatch / summary fetch / post-tick fan-out); None = no cost
        self.tracer = None
        # back-pointer set by parallel/rooms.RoomBatch.attach() when this
        # kernel is the TEMPLATE for a room-batched world: its _trace_step
        # is vmapped over a leading [R] room axis and its own state/jit
        # entries go unused.  None for every ordinary single-world kernel.
        self.room_batch = None
        # honest per-stage timing (NF_STAGE_TIMING=1, set by GameRole /
        # telemetry/pipeline.stage_timing_enabled): block after dispatch
        # so the kernel.dispatch span measures device time, not async
        # enqueue latency.  Never on by default — it serializes the
        # device queue and kills dispatch/fetch overlap.
        self.stage_timing = False

    # -- build --------------------------------------------------------------

    def build(self, modules: Sequence[Module] = ()) -> None:
        """Freeze timer slots, construct the store + initial state, and
        collect device phases from `modules` (plus any added directly)."""
        timer_slots = self.schedule.freeze()
        self.store_config.timer_slots = {
            **timer_slots,
            **{
                k: v
                for k, v in self.store_config.timer_slots.items()
                if k not in timer_slots
            },
        }
        self.store = EntityStore(
            self.registry,
            self.store_config,
            strings=self.elements.strings,
            class_names=self._class_names,
        )
        self.state = self.store.init_state(self._seed)
        phases: List[Phase] = []
        seen_modules = set()
        for m in modules:
            phases.extend(m.phases)
            seen_modules.add(id(m))
        if id(self) not in seen_modules:
            phases.extend(self.phases)
        self.set_phases(phases)

    def set_phases(self, phases: Sequence[Phase]) -> None:
        self._composed = sorted(phases, key=lambda p: p.order)
        self._jit_step = None
        self._jit_run = None
        self._jit_train = None
        self._trace_gen += 1
        self.costbook.generation_bump("set_phases")

    # -- the compiled tick --------------------------------------------------

    def _trace_step(self, state: WorldState):
        old = state
        fired: Dict[str, jnp.ndarray] = {}
        new_classes = {}
        # per-stage named scopes ride the HLO metadata: an XProf/profiler
        # capture attributes device time to "nf.schedule", "nf.phase.*",
        # "nf.diff" instead of one opaque fused computation
        with jax.named_scope("nf.schedule"):
            for cname in self.store.class_order:
                cs, f = self.schedule.advance_class(state.classes[cname], state.tick)
                new_classes[cname] = cs
                fired[cname] = f
            state = state.replace(classes=new_classes)

        rng = jax.random.fold_in(state.rng, state.tick)
        ctx = TickCtx(self, state.tick, rng, fired)
        for phase in self._composed:
            with jax.named_scope(f"nf.phase.{phase.name}"):
                state = phase.fn(state, ctx)

        diff: Dict[str, Dict[str, jnp.ndarray]] = {}
        diff_count: Dict[str, jnp.ndarray] = {}
        rec_diff: Dict[str, Dict[str, jnp.ndarray]] = {}
        rec_diff_count: Dict[str, jnp.ndarray] = {}
        died: Dict[str, jnp.ndarray] = {}
        died_count: Dict[str, jnp.ndarray] = {}
        with jax.named_scope("nf.diff"):
            for cname in self.store.class_order:
                spec = self.store.spec(cname)
                oc, nc = old.classes[cname], state.classes[cname]
                masks: Dict[str, jnp.ndarray] = {}
                total = jnp.zeros((), jnp.int32)
                flag_union = {}
                for bank, nm in ((Bank.I32, "i32"), (Bank.F32, "f32"), (Bank.VEC, "vec")):
                    fm = np.zeros(spec.bank_size(bank), bool)
                    for fl in self._diff_flags:
                        fm |= spec.mask(bank, fl)
                    for pname in self._forced_diff.get(cname, ()):
                        slot = spec.slot(pname)
                        if slot.bank == bank:
                            fm[slot.col] = True
                    flag_union[nm] = fm
                if flag_union["i32"].any():
                    m = (oc.i32 != nc.i32) & nc.alive[:, None] & flag_union["i32"][None, :]
                    masks["i32"] = m
                    total = total + jnp.sum(m, dtype=jnp.int32)
                if flag_union["f32"].any():
                    m = (oc.f32 != nc.f32) & nc.alive[:, None] & flag_union["f32"][None, :]
                    masks["f32"] = m
                    total = total + jnp.sum(m, dtype=jnp.int32)
                if flag_union["vec"].any():
                    m = (
                        jnp.any(oc.vec != nc.vec, axis=-1)
                        & nc.alive[:, None]
                        & flag_union["vec"][None, :]
                    )
                    masks["vec"] = m
                    total = total + jnp.sum(m, dtype=jnp.int32)
                if masks:
                    diff[cname] = masks
                    diff_count[cname] = total
                # record-row diffs: add/remove/update codes per (entity, row),
                # only for subscribed records (device phases mutate records —
                # buff expiry, stat groups — and those changes must reach the
                # same sync spine as host record ops;
                # reference NFCRecord per-op callbacks, NFCRecord.h:17-156)
                rec_codes: Dict[str, jnp.ndarray] = {}
                rec_total = jnp.zeros((), jnp.int32)
                for rname in spec.record_order:
                    if (cname, rname) not in self._rec_event_subs:
                        continue
                    rs = spec.records[rname]
                    orec, nrec = oc.records[rname], nc.records[rname]
                    cell_changed = jnp.zeros(nrec.used.shape, bool)
                    if rs.n_i32:
                        cell_changed |= jnp.any(orec.i32 != nrec.i32, axis=-1)
                    if rs.n_f32:
                        cell_changed |= jnp.any(orec.f32 != nrec.f32, axis=-1)
                    if rs.n_vec:
                        cell_changed |= jnp.any(orec.vec != nrec.vec, axis=(-2, -1))
                    code = jnp.where(
                        ~orec.used & nrec.used,
                        REC_ADDED,
                        jnp.where(
                            orec.used & ~nrec.used,
                            REC_REMOVED,
                            jnp.where(nrec.used & cell_changed, REC_UPDATED, REC_NONE),
                        ),
                    ).astype(jnp.int8)
                    code = code * nc.alive[:, None].astype(jnp.int8)
                    rec_codes[rname] = code
                    rec_total = rec_total + jnp.sum(code != 0, dtype=jnp.int32)
                if rec_codes:
                    rec_diff[cname] = rec_codes
                    rec_diff_count[cname] = rec_total
                d = oc.alive & ~nc.alive
                died[cname] = d
                died_count[cname] = jnp.sum(d, dtype=jnp.int32)

        state = state.replace(tick=state.tick + 1)
        # static event metadata is captured on self at trace time; only the
        # traced arrays cross the jit boundary (dataclasses aren't pytrees)
        self._event_meta = [(e.event_id, e.class_name, tuple(e.params)) for e in ctx.emitted]
        # on-device counter bank: phase-accumulated ctx.count() values plus
        # kernel builtins.  Names are static per compilation (same contract
        # as _event_meta); values ride the summary fetch below, so the
        # telemetry surface costs ZERO extra device syncs per tick.
        ev_counts = [jnp.sum(e.mask, dtype=jnp.int32) for e in ctx.emitted]
        counters = dict(ctx._counters)
        zero = jnp.zeros((), jnp.int32)
        counters["deaths"] = sum(died_count.values(), zero)
        counters["diff_cells"] = sum(diff_count.values(), zero)
        counters["rec_diff_cells"] = sum(rec_diff_count.values(), zero)
        counters["events_fired"] = sum(ev_counts, zero)
        # the tick's own logical number (post-increment, i.e. the value
        # tick_count reaches once this frame lands) rides in-lane so a
        # K-tick train can stamp journal marks and death attribution
        # with the REAL tick of each stacked frame, not the train's end
        counters["tick"] = state.tick
        if self.digest_enabled:
            # post-increment state, i.e. exactly what a checkpoint taken
            # after this tick would capture — replay compares like for like
            counters["state_digest"] = jax.lax.bitcast_convert_type(
                state_digest(state, self.store.class_order), jnp.int32
            )
        self._counter_names = tuple(sorted(counters))
        # ONE packed scalar vector per tick — the only thing the host ever
        # synchronously fetches.  Anything else (masks, params, fired) is
        # fetched lazily and only when this summary says there's something
        # to see; over the TPU tunnel every fetch is a round trip, so this
        # is the difference between 1 and O(classes+events) syncs per tick.
        summary = jnp.concatenate(
            [
                jnp.stack([died_count[c] for c in self.store.class_order])
                if self.store.class_order
                else jnp.zeros((0,), jnp.int32),
                jnp.stack([diff_count[c] for c in sorted(diff_count)])
                if diff_count
                else jnp.zeros((0,), jnp.int32),
                jnp.stack([rec_diff_count[c] for c in sorted(rec_diff_count)])
                if rec_diff_count
                else jnp.zeros((0,), jnp.int32),
                jnp.stack(ev_counts)
                if ctx.emitted
                else jnp.zeros((0,), jnp.int32),
                jnp.stack([counters[k] for k in self._counter_names]),
            ]
        )
        out = {
            "fired": fired,
            "diff": diff,
            "diff_count": diff_count,
            "rec_diff": rec_diff,
            "rec_diff_count": rec_diff_count,
            "died": died,
            "died_count": died_count,
            "events": [(e.mask, e.params) for e in ctx.emitted],
            "summary": summary,
        }
        return state, out

    def compile(self) -> None:
        if self._jit_step is None:
            self._jit_step = self.costbook.wrap(
                "kernel.step", self._trace_step,
                donate_argnums=0, stage="tick",
            )

    def invalidate(self) -> None:
        """Force retrace of the compiled tick.  Call after changing
        anything phases close over (config tables, phase lists) — traced
        constants do NOT update on their own.  Registered aux entries are
        dropped too: their layouts bake the same trace-time geometry
        (bucket sizes, grid widths), so a stale Verlet slot assignment
        must not survive a retrace — _ensure_aux re-primes zero caches
        and the first new tick rebuilds them."""
        self._jit_step = None
        self._jit_run = None
        self._jit_train = None
        self._trace_gen += 1
        # sanctioned retrace: anything compiled after this bump is an
        # expected recompile, not a hazard (soak-gate allowlist seam)
        self.costbook.generation_bump("invalidate")
        if self._aux_init and self.state is not None and self.state.aux:
            kept = {
                k: v for k, v in self.state.aux.items()
                if k not in self._aux_init
            }
            if len(kept) != len(self.state.aux):
                self.state = self.state.replace(aux=kept)

    def enable_digest(self) -> None:
        """Turn on the per-tick state digest (flight-recorder fingerprint).
        A no-op when already on; otherwise the compiled tick is retraced
        so the counter bank grows the "state_digest" slot."""
        if not self.digest_enabled:
            self.digest_enabled = True
            self.invalidate()

    # -- carried aux state ---------------------------------------------------

    def register_aux(self, name: str, init_fn: Callable[[], Any]) -> None:
        """Register module-owned carried tick state (WorldState.aux).

        `init_fn` returns a pytree of arrays; it is called lazily before
        the next dispatch (so store capacities exist by then) and again
        after every invalidate().  Phases read `state.aux[name]` and
        write back via `state.replace(aux={**state.aux, name: new})`."""
        self._aux_init[name] = init_fn

    def _ensure_aux(self) -> None:
        """Prime any registered-but-missing aux entries before dispatch —
        keeps the carried pytree structure stable across every tick()/
        run_device() call of one compilation."""
        if not self._aux_init:
            return
        missing = [k for k in self._aux_init if k not in self.state.aux]
        if missing:
            aux = dict(self.state.aux)
            for k in missing:
                aux[k] = self._aux_init[k]()
            self.state = self.state.replace(aux=aux)

    def _span(self, name: str):
        """Host-side tracer span if a tracer is attached, else free."""
        if self.tracer is not None:
            return self.tracer.span(name)
        return contextlib.nullcontext()

    def tick(self) -> TickOutputs:
        """Advance the world one frame and fan out host-visible effects."""
        return self.tick_finish(self.tick_begin())

    def tick_begin(self) -> Dict[str, object]:
        """Dispatch one frame's step and return the raw output handle
        WITHOUT fetching anything.  The device runs asynchronously until
        `tick_finish(raw)` syncs on the summary — the seam the serving
        edge's overlap mode uses to assemble/encode frame N's packets on
        the host while the device computes frame N+1.

        Donation hazard: `_jit_step` donates the carried state, so the
        PRE-dispatch buffers are invalid the moment this returns.  Any
        reader of pre-tick state (snapshot fetches, serve kernels) must
        run before tick_begin."""
        self.compile()
        self._ensure_aux()
        with self._span("kernel.dispatch"):
            self.state, raw = self._jit_step(self.state)
            if self.stage_timing:
                jax.block_until_ready((self.state, raw))
        self.tick_count += 1
        return raw

    def tick_finish(self, raw: Dict[str, object]) -> TickOutputs:
        """Fetch a dispatched frame's outputs and fan out host-visible
        effects (events, diffs, death reconciliation, counters)."""
        out = TickOutputs(
            fired=raw["fired"],
            diff=raw["diff"],
            diff_count=raw["diff_count"],
            rec_diff=raw["rec_diff"],
            rec_diff_count=raw["rec_diff_count"],
            died=raw["died"],
            died_count=raw["died_count"],
            events=[
                DeviceEvent(eid, cname, mask, dict(params))
                for (eid, cname, pnames), (mask, params) in zip(
                    self._event_meta, raw["events"]
                )
            ],
        )
        with self._span("kernel.summary_fetch"):
            summary = np.asarray(raw["summary"])
        # decode the counter bank from the summary tail (names captured at
        # trace time, same static-metadata contract as _event_meta)
        if self._counter_names:
            out.counters = {
                k: int(v) for k, v in self.decode_counters(summary).items()
            }
            self.last_counters = dict(out.counters)
            for k, v in out.counters.items():
                if k in ("state_digest", "tick"):
                    continue  # a hash / a stamp; summing either is noise
                self.counter_totals[k] = self.counter_totals.get(k, 0) + v
        with self._span("kernel.post_tick"):
            self._post_tick(out, summary)
        return out

    def decode_counters(self, summary) -> Dict[str, np.ndarray]:
        """Slice the named counter bank off a summary vector's tail.

        The bank rides the LAST ``len(self._counter_names)`` lanes of
        the packed summary, so the decode is a trailing-axis slice and
        works unchanged on a room-batched ``[R, L]`` summary (the room
        engine vmaps the step, giving every lane a leading room axis):
        scalars come back for a single world, per-room ``[R]`` columns
        for a batch."""
        names = self._counter_names
        if not names:
            return {}
        arr = np.asarray(summary)
        tail = arr[..., arr.shape[-1] - len(names):]
        return {k: tail[..., i] for i, k in enumerate(names)}

    def run_device(self, n: int, reconcile: bool = True) -> int:
        """Advance n frames entirely on device (lax.fori_loop over the
        step) with ZERO host syncs — the headless/benchmark fast path.

        Per-tick host observation is skipped: device events, per-tick
        diffs and fired masks are not delivered (XLA dead-code-eliminates
        them); deaths are reconciled once at the end.  Use tick() when
        host subscribers must see every frame.

        reconcile=False skips the end-of-run death reconciliation (one
        device→host fetch per class — ~4 tunnel RTTs on a remote chip,
        which would dominate short timing windows).  Host free-lists then
        lag the device until the next reconciling call; benchmark latency
        sampling is the intended user."""
        self.compile()
        self._ensure_aux()
        key = int(n)
        if self._jit_run is None:
            # trip count rides in as a TRACED scalar so ONE compile
            # serves every n — a fresh 1M-entity compile per window size
            # cost the round-4 bench minutes of wall per variant
            def body(_, st):
                st2, _out = self._trace_step(st)
                return st2

            self._jit_run = self.costbook.wrap(
                "kernel.run",
                lambda st, k: jax.lax.fori_loop(0, k, body, st),
                donate_argnums=0, stage="tick",
            )
        self.state = self._jit_run(self.state, jnp.int32(key))
        self.tick_count += key
        if not reconcile:
            return 0
        freed = 0
        for cname in self.store.class_order:
            for g in self.store.reconcile_deaths(self.state, cname):
                self._fire_class_event(g, cname, ObjectEvent.DESTROY)
                freed += 1
        return freed

    # -- K-tick trains (NF_TICK_TRAIN) --------------------------------------

    def configure_train(self, k: int) -> None:
        """Pin the train length.  Changing K drops only the train
        executable (the step/run traces are K-independent); the retrace
        is announced like every other sanctioned recompile so soak
        gates armed across a reconfigure stay clean."""
        k = int(k)
        if k < 1:
            raise ValueError(f"train length must be >= 1, got {k}")
        if k == self._train_k:
            return
        self._train_k = k
        if self._jit_train is not None:
            self._jit_train = None
            self.costbook.generation_bump(f"train_k:{k}")

    def _trace_train(self, state: WorldState):
        """K steps under ONE lax.scan whose per-tick outputs scan-stack
        into [K, ...] lanes — the whole observed surface of K frames in
        one dispatch + one summary fetch.  Plain scan, not unrolled:
        measured on the rooms flagship shape the rolled loop both runs
        faster and compiles ~7x faster than an unrolled body."""

        def body(st, _):
            st2, out = self._trace_step(st)
            return st2, out

        state, lanes = jax.lax.scan(body, state, None, length=self._train_k)
        _assert_train_lanes(lanes)
        return state, lanes

    def compile_train(self) -> None:
        if self._jit_train is None:
            if self._train_k < 1:
                raise RuntimeError("configure_train(k) before train()")
            self._jit_train = self.costbook.wrap(
                "kernel.train", self._trace_train,
                donate_argnums=0, stage="tick",
            )

    def train_begin(self) -> Dict[str, object]:
        """Dispatch one K-tick train; same donation hazard and async
        contract as tick_begin, K frames deep."""
        self.compile_train()
        self._ensure_aux()
        with self._span("kernel.dispatch"):
            self.state, raw = self._jit_train(self.state)
            if self.stage_timing:
                jax.block_until_ready((self.state, raw))
        self.tick_count += self._train_k
        self.train_dispatches += 1
        self.train_ticks += self._train_k
        return raw

    def train_finish(self, raw: Dict[str, object]) -> List[TickOutputs]:
        """Fetch one train's stacked lanes and fan out K frames of
        host-visible effects IN TICK ORDER: lane i's events fire before
        lane i's deaths free rows, before anything from lane i+1 — the
        same per-frame sequencing tick_finish gives a single frame.
        Deaths are attributed from each lane's own died mask (the final
        carried state cannot say WHICH tick killed a row)."""
        k = self._train_k
        with self._span("kernel.summary_fetch"):
            summary = np.asarray(raw["summary"])  # [K, L]
        self.train_fetch_bytes += summary.nbytes
        stacked = {kk: vv for kk, vv in raw.items() if kk != "summary"}
        outs: List[TickOutputs] = []
        for i in range(k):
            lane = jax.tree.map(lambda x: x[i], stacked)
            out = TickOutputs(
                fired=lane["fired"],
                diff=lane["diff"],
                diff_count=lane["diff_count"],
                rec_diff=lane["rec_diff"],
                rec_diff_count=lane["rec_diff_count"],
                died=lane["died"],
                died_count=lane["died_count"],
                events=[
                    DeviceEvent(eid, cname, mask, dict(params))
                    for (eid, cname, pnames), (mask, params) in zip(
                        self._event_meta, lane["events"]
                    )
                ],
            )
            row = summary[i]
            if self._counter_names:
                out.counters = {
                    kk: int(v)
                    for kk, v in self.decode_counters(row).items()
                }
                self.last_counters = dict(out.counters)
                for kk, v in out.counters.items():
                    if kk in ("state_digest", "tick"):
                        continue
                    self.counter_totals[kk] = (
                        self.counter_totals.get(kk, 0) + v
                    )
            with self._span("kernel.post_tick"):
                self._post_tick(out, row, exact_deaths=True)
            outs.append(out)
        return outs

    def train(self, n: int) -> List[TickOutputs]:
        """Advance n frames in ⌊n/K⌋ train dispatches plus a per-tick
        ragged tail, delivering every frame's host effects — the
        observed-mode counterpart of run_device.  Returns one
        TickOutputs per frame, in order; out.counters["tick"] carries
        each frame's logical number."""
        n = int(n)
        k = self._train_k
        if k < 1:
            raise RuntimeError("configure_train(k) before train()")
        outs: List[TickOutputs] = []
        for _ in range(n // k):
            outs.extend(self.train_finish(self.train_begin()))
        for _ in range(n % k):
            outs.append(self.tick())
        return outs

    def _post_tick(self, out: TickOutputs, summary: np.ndarray,
                   exact_deaths: bool = False) -> None:
        n_cls = len(self.store.class_order)
        died_counts = summary[:n_cls]
        diff_keys = sorted(out.diff_count)
        diff_counts = dict(zip(diff_keys, summary[n_cls : n_cls + len(diff_keys)]))
        off = n_cls + len(diff_keys)
        rec_keys = sorted(out.rec_diff_count)
        rec_counts = dict(zip(rec_keys, summary[off : off + len(rec_keys)]))
        off2 = off + len(rec_keys)
        # bounded slice: the on-device counter bank rides AFTER the event
        # counts, so an open-ended slice would absorb it
        event_counts = summary[off2 : off2 + len(out.events)]
        # device-emitted events FIRST — entities that died this tick must
        # still deliver their events (the reference fires events before
        # destroy), so guid identities are intact here
        live_events = [
            ev for ev, cnt in zip(out.events, event_counts) if cnt > 0
        ]
        if live_events:
            self.events.dispatch_device_events(live_events, self.store)
        # deaths: reconcile host allocation + fire destroy events.
        # exact_deaths (the train path) frees the rows named by THIS
        # frame's died mask — the carried post-train state's alive mask
        # would pin every death to the train's last tick, so attribution
        # must come from the lane, not from reconcile's final-state scan
        for cname, cnt in zip(self.store.class_order, died_counts):
            if int(cnt) == 0:
                continue
            if exact_deaths:
                rows = np.flatnonzero(np.asarray(out.died[cname]))
                dead = self.store.release_rows(cname, rows)
            else:
                dead = self.store.reconcile_deaths(self.state, cname)
            for g in dead:
                self._fire_class_event(g, cname, ObjectEvent.DESTROY)
        # property-change host subscribers (batch granularity)
        if self._prop_event_subs:
            for (cname, pname), fns in self._prop_event_subs.items():
                masks = out.diff.get(cname)
                if not masks:
                    continue
                if int(diff_counts[cname]) == 0:
                    continue
                slot = self.store.spec(cname).slot(pname)
                bank_name = slot.bank.value
                m = masks.get(bank_name)
                if m is None:
                    continue
                rows = np.flatnonzero(np.asarray(m[:, slot.col]))
                if rows.size:
                    for fn in fns:
                        fn(cname, pname, rows)
        # record-diff subscribers (device-path record mutations)
        if self._rec_event_subs:
            for (cname, rname), fns in self._rec_event_subs.items():
                if int(rec_counts.get(cname, 0)) == 0:
                    continue
                codes_dev = out.rec_diff.get(cname, {}).get(rname)
                if codes_dev is None:
                    continue
                codes = np.asarray(codes_dev)
                if codes.any():
                    for fn in fns:
                        fn(cname, rname, codes)

    # -- object lifecycle (host control plane) ------------------------------

    def create_object(
        self,
        class_name: str,
        values: Optional[Dict[str, Value]] = None,
        guid: Optional[Guid] = None,
        scene: int = 0,
        group: int = 0,
    ) -> Guid:
        vals = dict(values or {})
        if self.store.spec(class_name).has_property("SceneID"):
            vals.setdefault("SceneID", scene)
        if self.store.spec(class_name).has_property("GroupID"):
            vals.setdefault("GroupID", group)
        if self.store.spec(class_name).has_property("ClassName"):
            vals.setdefault("ClassName", class_name)
        self.state, g, _ = self.store.create_object(self.state, class_name, guid, vals)
        if self.store.spec(class_name).has_property("ID"):
            self.state = self.store.set_property(self.state, g, "ID", str(g))
        # full create chain, in order (reference NFCKernelModule.cpp:251-267)
        for ev in (
            ObjectEvent.CREATE_NODATA,
            ObjectEvent.CREATE_LOADDATA,
            ObjectEvent.CREATE_BEFORE_EFFECT,
            ObjectEvent.CREATE_EFFECTDATA,
            ObjectEvent.CREATE_AFTER_EFFECT,
            ObjectEvent.CREATE_HASDATA,
            ObjectEvent.CREATE_FINISH,
        ):
            self._fire_class_event(g, class_name, ev)
        return g

    def create_from_element(
        self,
        class_name: str,
        elem_id: str,
        overrides: Optional[Dict[str, Value]] = None,
        scene: int = 0,
        group: int = 0,
    ) -> Guid:
        """Create seeded from element config (reference CreateObject applies
        the element's Ref/IOBJECT property defaults)."""
        e = self.elements.element(elem_id)
        vals = dict(e.values)
        vals["ConfigID"] = elem_id
        vals.update(overrides or {})
        vals = {
            k: v for k, v in vals.items() if self.store.spec(class_name).has_property(k)
        }
        return self.create_object(class_name, vals, scene=scene, group=group)

    def destroy_object(self, guid: Guid, deferred: bool = False) -> None:
        """Destroy now, or at end of current frame if deferred (reference
        defers self-destroys mid-tick, NFCKernelModule.cpp:273-308)."""
        if deferred:
            self._pending_destroy.append(guid)
            return
        class_name, _ = self.store.row_of(guid)
        self._fire_class_event(guid, class_name, ObjectEvent.BEFORE_DESTROY)
        self.state = self.store.destroy_object(self.state, guid)
        self._fire_class_event(guid, class_name, ObjectEvent.DESTROY)

    def flush_pending_destroy(self) -> int:
        n = 0
        for g in self._pending_destroy:
            if g in self.store.guid_map:
                self.destroy_object(g)
                n += 1
        self._pending_destroy.clear()
        return n

    def execute(self) -> None:
        self.flush_pending_destroy()
        self.events.execute()

    # -- property access with host-callback parity --------------------------

    def set_property(self, guid: Guid, prop_name: str, value: Value) -> None:
        """Host-originated write; fires property subscribers synchronously
        like the reference's SetProperty -> OnEventHandler chain."""
        class_name, row = self.store.row_of(guid)
        old = self.store.get_property(self.state, guid, prop_name)
        self.state = self.store.set_property(self.state, guid, prop_name, value)
        if old != value:
            for fn in self._prop_event_subs.get((class_name, prop_name), ()):
                fn(class_name, prop_name, np.asarray([row]))

    def get_property(self, guid: Guid, prop_name: str) -> Value:
        return self.store.get_property(self.state, guid, prop_name)

    # -- event registration --------------------------------------------------

    def register_class_event(
        self, fn: ClassEventFn, class_name: Optional[str] = None
    ) -> None:
        """Subscribe to create/destroy chains — all classes or one
        (reference RegisterCommonClassEvent / AddClassCallBack)."""
        if class_name is None:
            self._class_event_subs.append(fn)
        else:
            self._class_event_by_class.setdefault(class_name, []).append(fn)

    def register_property_event(
        self, class_name: str, prop_name: str, fn: PropertyEventFn
    ) -> None:
        """Subscribe to a property's changes; called with changed row
        indices after each tick (and synchronously on host writes)."""
        self.store.spec(class_name).slot(prop_name)  # validate
        # diff extraction depends only on diff_flags (static), so no
        # recompilation is needed when subscribers change
        self._prop_event_subs.setdefault((class_name, prop_name), []).append(fn)

    def force_diff_property(self, class_name: str, prop_name: str) -> None:
        """Opt an unflagged property into device diff extraction so its
        tick-path changes reach property subscribers (diff_flags normally
        limit extraction to public/upload columns).  Debug-tool surface —
        the property trail uses it; the first new column per class
        invalidates the compiled tick."""
        self.store.spec(class_name).slot(prop_name)  # validate
        s = self._forced_diff.setdefault(class_name, set())
        if prop_name not in s:
            s.add(prop_name)
            self.invalidate()

    def register_record_diff(
        self, class_name: str, record_name: str, fn: RecordDiffFn
    ) -> None:
        """Subscribe to a record's device-path changes; called after each
        tick with an int8 [C, R] code array (REC_ADDED/REMOVED/UPDATED).
        The diff is computed on device ONLY for subscribed records, so
        registration invalidates the compiled tick."""
        spec = self.store.spec(class_name)
        if record_name not in spec.records:
            raise KeyError(f"{class_name!r} has no record {record_name!r}")
        key = (class_name, record_name)
        first = key not in self._rec_event_subs
        self._rec_event_subs.setdefault(key, []).append(fn)
        if first:
            self.invalidate()

    def subscribe_record_host(self, fn) -> None:
        """Host-path per-op record hook (store mutators; reference
        NFIRecord::AddRecordHook) — see EntityStore.subscribe_records."""
        self.store.subscribe_records(fn)

    def _fire_class_event(self, guid: Guid, class_name: str, ev: ObjectEvent) -> None:
        for fn in self._class_event_by_class.get(class_name, ()):
            fn(guid, class_name, ev)
        for fn in self._class_event_subs:
            fn(guid, class_name, ev)
