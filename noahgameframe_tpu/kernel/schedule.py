"""Schedule module: vectorised heartbeats.

The reference walks guid -> name -> timer maps every tick and fires
`DoHeartBeatEvent` when now > next (NFCScheduleModule.cpp:49-110) — O(live
timers) of pointer chasing on the host.  Here every class has a fixed set of
*timer slots* (registered before the world is built); per-entity timer state
is four [C, T] arrays in ClassState.timers, and firing is one fused compare
on device:

    fired = active & alive & (tick >= next_fire)

Handlers are device phases that read `ctx.fired(class_name, timer_name)`
— a [C] bool column — instead of receiving one callback per object.
Host-side per-object callbacks remain available via the event module
(subscribe to the timer's event id) for control-plane consumers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.datatypes import Guid
from ..core.store import ClassState, TimerState, WorldState, with_class
from .module import Module


class ScheduleModule(Module):
    name = "ScheduleModule"

    def __init__(self, dt: float = 1.0 / 30.0) -> None:
        super().__init__()
        self.dt = float(dt)
        # (class_name -> timer_name -> slot index); frozen at build time
        self._slots: Dict[str, Dict[str, int]] = {}
        self._frozen = False

    # -- registration (before kernel.build) ---------------------------------

    def register_timer(self, class_name: str, timer_name: str) -> int:
        """Declare a timer slot on a class.  Must happen before the world is
        built — slot count fixes the [C, T] timer array shapes."""
        if self._frozen:
            raise RuntimeError("timer registration is closed once the world is built")
        slots = self._slots.setdefault(class_name, {})
        if timer_name in slots:
            return slots[timer_name]
        slots[timer_name] = len(slots)
        return slots[timer_name]

    def freeze(self) -> Dict[str, int]:
        """Close registration; returns class -> slot count for StoreConfig."""
        self._frozen = True
        return {c: len(s) for c, s in self._slots.items()}

    def slot(self, class_name: str, timer_name: str) -> int:
        return self._slots[class_name][timer_name]

    def timer_names(self, class_name: str) -> List[str]:
        return list(self._slots.get(class_name, ()))

    def ticks_of(self, seconds: float) -> int:
        return max(1, int(round(float(seconds) / self.dt)))

    # -- per-entity timer control (host, functional) ------------------------

    def set_timer(
        self,
        state: WorldState,
        store,
        guid: Guid,
        timer_name: str,
        interval_s: float,
        count: int = -1,
        start_delay_s: Optional[float] = None,
    ) -> WorldState:
        """Arm a timer on one entity: fire every interval_s, `count` times
        (-1 = forever), first firing after start_delay_s (defaults to one
        interval) — AddHeartBeat semantics."""
        class_name, row = store.row_of(guid)
        return self.set_timer_rows(
            state, class_name, np.asarray([row]), timer_name, interval_s, count, start_delay_s
        )

    def set_timer_rows(
        self,
        state: WorldState,
        class_name: str,
        rows: np.ndarray,
        timer_name: str,
        interval_s: float,
        count: int = -1,
        start_delay_s: Optional[float] = None,
        start_delay_ticks: Optional[np.ndarray] = None,
    ) -> WorldState:
        """Batch-arm one timer slot.  `start_delay_ticks` (per-row int
        array aligned with `rows`) staggers first firings — the batch
        equivalent of the reference's per-object AddHeartBeat calls, whose
        first firings spread naturally over object creation times."""
        slot = self.slot(class_name, timer_name)
        interval = self.ticks_of(interval_s)
        if start_delay_ticks is not None:
            delay = np.maximum(np.asarray(start_delay_ticks, np.int32), 1)
        elif start_delay_s is not None:
            delay = self.ticks_of(start_delay_s)
        else:
            delay = interval
        cs = state.classes[class_name]
        t = cs.timers
        now = state.tick
        t = TimerState(
            next_fire=t.next_fire.at[rows, slot].set(now + delay),
            interval=t.interval.at[rows, slot].set(interval),
            remain=t.remain.at[rows, slot].set(count),
            active=t.active.at[rows, slot].set(True),
        )
        return with_class(state, class_name, cs.replace(timers=t))

    def cancel_timer(self, state: WorldState, store, guid: Guid, timer_name: str) -> WorldState:
        class_name, row = store.row_of(guid)
        slot = self.slot(class_name, timer_name)
        cs = state.classes[class_name]
        t = cs.timers
        t = t.replace(active=t.active.at[row, slot].set(False))
        return with_class(state, class_name, cs.replace(timers=t))

    # -- device step (composed into the jitted tick by the kernel) ----------

    def advance_class(
        self, cs: ClassState, tick: jnp.ndarray
    ) -> Tuple[ClassState, jnp.ndarray]:
        """One schedule step for one class: returns (new_cs, fired[C, T]).

        fired timers advance next_fire by interval; finite timers count
        down and deactivate at zero.  Dead rows never fire."""
        t = cs.timers
        if t.active.shape[1] == 0:
            return cs, t.active
        due = t.active & (tick >= t.next_fire) & cs.alive[:, None]
        next_fire = jnp.where(due, t.next_fire + t.interval, t.next_fire)
        remain = jnp.where(due & (t.remain > 0), t.remain - 1, t.remain)
        active = t.active & ~(due & (remain == 0))
        return (
            cs.replace(
                timers=TimerState(
                    next_fire=next_fire, interval=t.interval, remain=remain, active=active
                )
            ),
            due,
        )
