#!/usr/bin/env python
"""Supervised session-failover smoke (ISSUE 10): kill a game mid-combat
under link faults and prove the blip is bounded and lossless.

    JAX_PLATFORMS=cpu python scripts/failover_smoke.py
    JAX_PLATFORMS=cpu python scripts/failover_smoke.py --surge

Default scenario — boots a two-game LocalCluster where each game owns
its OWN write-behind WAL + checkpoint dirs over one shared store, logs
two clients into Game1, drives movement/chat, wedges Game1's store
flusher (StoreFaults.fail_first) so saves stay WAL-only, snapshots both
players, then HARD-kills Game1 (crash path: no drain, no goodbye) while
the clients keep talking.  Asserts:

- the world's FailoverDriver re-homes both sessions onto the survivor,
  reconstructing each blob from the dead game's WAL suffix (basis
  "wal" — the store never saw the final save);
- recovered player state is bit-identical to the pre-kill snapshot
  (WAL bytes) and property-identical on the adopting game;
- client frames sent into the outage PARK at the proxy and replay in
  order after the re-point — chat echoes arrive complete and ordered,
  ``nf_failover_dropped_total`` stays 0, zero sessions drop;
- clients receive the explicit REHOMING switch notice (satellite 2:
  no more silent unbinds);
- the master surfaces the failover block (pending/lag) on /json.

--surge (ROADMAP 4c) — one client ping-pongs between the two games via
the real ``switch_server`` protocol under an active FaultPlan, with the
flight recorder journaling Game1.  Measures completed switches/sec,
digest-pins the run via offline replay, and writes
``bench_runs/r06_handoff_surge.json``.

Exits 0 on success — tests/test_failover.py wires this into CI.
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

REPO = Path(__file__).resolve().parent.parent
AFTER_CHATS = 5  # numbered chats each client sends into the outage


def _login(cluster, cli, game_id: int, role: str, pump) -> bool:
    """The full reference login pipeline (login -> world -> proxy ->
    game); each hop gates on its ack and fails fast with the stage name
    on timeout."""
    steps = [
        (lambda: cli.connect("127.0.0.1", cluster.login.config.port),
         "login connect", lambda: cli.connected),
        (cli.login, "login ack", lambda: cli.logged_in),
        (cli.request_world_list, "world list", lambda: cli.worlds),
        (lambda: cli.connect_world(cli.worlds[0].server_id),
         "world grant", lambda: cli.world_grant is not None),
        (cli.connect_proxy, "proxy connect", lambda: cli.connected),
        (cli.verify_key, "key verify", lambda: cli.key_verified),
        (lambda: cli.select_server(game_id),
         "server select", lambda: cli.server_selected),
        (lambda: cli.create_role(role), "role list", lambda: cli.roles),
        (lambda: cli.enter_game(role), "enter game",
         lambda: cli.entered),
    ]
    for action, stage, cond in steps:
        action()
        if not pump(cond):
            print(f"  login stalled for {cli.account} at: {stage}")
            return False
    return True


def _session_of(game, account: str):
    for sess in game.sessions.values():
        if sess.account == account and sess.guid is not None:
            return sess
    return None


def _chat_positions(log, prefix: str):
    """Indices of this client's own numbered echoes, in arrival order."""
    return [i for i, (_who, text) in enumerate(log)
            if text.startswith(prefix)]


def run(tmpdir, seed: int = 7) -> dict:
    """Run the kill/re-home scenario; returns {check name: bool}."""
    from noahgameframe_tpu.client import GameClient
    from noahgameframe_tpu.net.chaos import (
        FaultPlan,
        LinkFaults,
        StoreFaults,
    )
    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.persist.agent import PlayerDataAgent
    from noahgameframe_tpu.persist.codec import snapshot_object
    from noahgameframe_tpu.persist.writebehind import read_peer_wal

    from noahgameframe_tpu.persist.kv import MemoryKV

    tmp = Path(tmpdir)
    kv = MemoryKV()
    checks: dict = {}
    cluster = LocalCluster(
        http_port=0,
        n_games=2,
        lease_suspect_seconds=1.0,
        lease_down_seconds=2.0,
        # autosave/checkpoint timers OFF: the explicit save below must be
        # the only staged write, or bit-identity would race the timer
        game_kwargs={
            "autosave_seconds": 3600.0,
            "checkpoint_seconds": 3600.0,
            "persist_drain_timeout": 0.3,
        },
        game_kwargs_by_name={
            "Game1": {
                "data_agent": PlayerDataAgent(kv),
                "persist_store": kv,
                "persist_wal_dir": tmp / "wal1",
                "checkpoint_dir": tmp / "ckpt1",
            },
            "Game2": {
                "data_agent": PlayerDataAgent(kv),
                "persist_store": kv,
                "persist_wal_dir": tmp / "wal2",
                "checkpoint_dir": tmp / "ckpt2",
            },
        },
        world_kwargs={"recover_store": kv},
    )
    game1, game2 = cluster.games[0], cluster.games[1]
    proxy, world, master = cluster.proxy, cluster.world, cluster.master
    ada, bob = GameClient("ada"), GameClient("bob")

    def stir():
        ada.execute()
        bob.execute()

    def pump(cond, t=20.0):
        return cluster.pump_until(cond, extra=stir, timeout=t)

    try:
        cluster.start(timeout=30)
        # faults from the start: mild duplication + delay on the proxy's
        # game links and the dying game's world link, and a WEDGED store
        # flusher under Game1 — every flush fails, so the final saves
        # live only in the WAL and recovery MUST take the WAL basis
        cluster.apply_chaos(FaultPlan(
            seed=seed,
            links={
                # the dying game's links can reorder freely (delay);
                # the SURVIVOR path gets dup-only faults — a delaying
                # link downstream of the parking buffer would reorder
                # frames the replay just put back in order, and that is
                # the transport's doing, not the failover's
                "proxy5.games->6": LinkFaults(dup=0.05, delay=0.05,
                                              delay_polls=2),
                "proxy5.games->16": LinkFaults(dup=0.02),
                "game6.world": LinkFaults(dup=0.02),
            },
            stores={"game6.store": StoreFaults(fail_first=1_000_000)},
        ))
        checks["cluster wired"] = True
        ok_a = _login(cluster, ada, game1.config.server_id, "Ada", pump)
        ok_b = _login(cluster, bob, game1.config.server_id, "Bob", pump)
        checks["both clients entered game 6"] = ok_a and ok_b

        # --- mid-combat activity: movement + chat on the doomed game
        step = [0]

        def fight():
            stir()
            step[0] += 1
            if step[0] % 20 == 0:
                ada.move_to(float(step[0] % 300), 50.0)
                bob.move_to(float(step[0] % 300), 80.0)
            if step[0] == 50:
                ada.chat("warm-a")
                bob.chat("warm-b")

        checks["pre-kill chat round-tripped"] = cluster.pump_until(
            lambda: (any(t == "warm-a" for _w, t in ada.chat_log)
                     and any(t == "warm-b" for _w, t in bob.chat_log)),
            extra=fight, timeout=20,
        )

        # --- freeze: distinct durable state per player, staged to the
        # WAL in the same pump step as the snapshot (no tick between ->
        # the save bytes are bit-identical to the snapshot bytes)
        sa, sb = _session_of(game1, "ada"), _session_of(game1, "bob")
        checks["sessions bound on game 6"] = sa is not None and sb is not None
        if sa is None or sb is None:
            # no point driving the kill without the precondition — report
            # the failed checks instead of tracebacking on sa.guid
            return checks
        k1, agent1 = game1.kernel, game1.data_agent
        pre = {}
        pre_blob = {}
        for sess, gold in ((sa, 4242), (sb, 777)):
            k1.set_property(sess.guid, "Gold", gold)
            k1.set_property(sess.guid, "Level", 9)
            pre[sess.account] = {
                p: k1.get_property(sess.guid, p)
                for p in ("Name", "Level", "Gold")
            }
            pre_blob[sess.account] = snapshot_object(
                k1.store, k1.state, sess.guid, agent1.flags
            )
            agent1.save(sess.guid)
        keys = {s.account: agent1._key_of(s.guid) for s in (sa, sb)}
        game1.checkpoint_now()  # ckpt + WAL barrier (fsync)

        # the WAL's staged bytes ARE the snapshot — the recovery basis
        view = read_peer_wal(tmp / "wal1")
        checks["WAL holds bit-identical pre-kill blobs"] = all(
            view.pending.get(keys[acc]) == pre_blob[acc]
            for acc in ("ada", "bob")
        )
        checks["store never saw the final saves"] = all(
            kv.get(keys[acc]) != pre_blob[acc] for acc in ("ada", "bob")
        )

        # --- CRASH: hard kill (no session saves, no persist drain)
        max_pending = [0]

        def watch():
            stir()
            max_pending[0] = max(max_pending[0],
                                 world.failover.pending_count())

        cluster.kill_role("Game1", hard=True)
        # wait until the proxy's link has actually dropped before the
        # clients talk again — a frame written into the dying socket
        # would be lost upstream of the parking buffer
        checks["proxy saw the link drop"] = cluster.pump_until(
            lambda: 6 not in proxy.games.connected_servers(),
            extra=watch, timeout=10.0,
        )

        # --- clients keep talking INTO the outage: numbered chats that
        # must park, replay in order, and echo back complete.  The first
        # chat goes out NOW — before the next pump round — so it reaches
        # the proxy while the binding is dead but the survivor's
        # re-point has not landed yet (roles pump server conns first,
        # game links second, so a same-round chat parks)
        ada.chat("after-a-0")
        bob.chat("after-b-0")
        stir()
        sent = [1]

        def talk():
            watch()
            if sent[0] < AFTER_CHATS:
                ada.chat(f"after-a-{sent[0]}")
                bob.chat(f"after-b-{sent[0]}")
                sent[0] += 1

        done = cluster.pump_until(
            lambda: (
                sent[0] >= AFTER_CHATS
                and _session_of(game2, "ada") is not None
                and _session_of(game2, "bob") is not None
                and world.failover.pending_count() == 0
                and proxy.parking.depth() == 0
                and len(_chat_positions(ada.chat_log, "after-a-")) >= AFTER_CHATS
                and len(_chat_positions(bob.chat_log, "after-b-")) >= AFTER_CHATS
            ),
            extra=talk, timeout=30,
        )
        checks["sessions re-homed to survivor"] = done
        checks["failover was observable while pending"] = max_pending[0] > 0

        # --- ordered, lossless replay
        for cli, prefix, name in ((ada, "after-a-", "ada"),
                                  (bob, "after-b-", "bob")):
            texts = [t for _w, t in cli.chat_log if t.startswith(prefix)]
            # dedupe (the chaos link dups messages) but keep first-seen
            # order: replay must deliver 0..N-1 ascending
            first_seen = list(dict.fromkeys(texts))
            checks[f"{name} chat replayed complete + in order"] = (
                first_seen == [f"{prefix}{i}" for i in range(AFTER_CHATS)]
            )
        checks["frames were actually parked"] = proxy.parking.parked_total > 0
        checks["nf_failover_dropped_total == 0"] = (
            proxy.parking.dropped_total == 0
        )

        # --- recovered state: new guid on the survivor, same player
        k2 = game2.kernel
        basis_ok = True
        for acc in ("ada", "bob"):
            s2 = _session_of(game2, acc)
            got = {p: k2.get_property(s2.guid, p)
                   for p in ("Name", "Level", "Gold")}
            checks[f"{acc} state recovered on game 16"] = got == pre[acc]
            checks[f"{acc} rebound to game 16"] = (
                int(k2.get_property(s2.guid, "GameID")) == 16
            )
        for entry in world.failover.completed:
            basis_ok = basis_ok and entry["basis"] == "wal"
        checks["recovery basis was the WAL suffix"] = (
            basis_ok and len(world.failover.completed) == 2
        )
        reg = world.telemetry.registry
        checks["failover counters balanced"] = (
            reg.value("nf_failover_initiated_total") == 2.0
            and reg.value("nf_failover_completed_total") == 2.0
        )
        checks["clients got the REHOMING notice"] = all(
            any(int(n.code) == 1 for n in cli.switch_notices)
            for cli in (ada, bob)
        )
        checks["zero session drops"] = (
            ada.entered and bob.entered and len(game2.sessions) >= 2
            and proxy.parking.dropped_disconnect == 0
        )

        # --- master surfaces the failover block on /json.  The block
        # rides the world's heartbeat ext, so the master's view lags the
        # re-home by up to one report interval — pump until the fresh
        # report lands instead of sampling a possibly-stale one
        def _fo_settled():
            fo = master.servers_status().get("failover", {})
            return bool(fo) and all(
                v.get("pending") == 0 for v in fo.values() if "pending" in v
            )

        checks["master /json failover block"] = (
            _fo_settled() or pump(_fo_settled, t=10.0)
        )
        import threading

        stop = threading.Event()

        def _bg():
            while not stop.is_set():
                cluster.execute()
                stir()
                time.sleep(0.002)

        th = threading.Thread(target=_bg, daemon=True)
        th.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{master.http.port}/json", timeout=5
            ) as r:
                page = json.loads(r.read().decode())
        finally:
            stop.set()
            th.join(timeout=2)
        checks["/json serves failover block over HTTP"] = (
            "failover" in page
        )
    finally:
        ada.close()
        bob.close()
        cluster.shut()
    return checks


def surge(tmpdir, seed: int = 11, rounds: int = 40,
          out_path=None) -> dict:
    """Handoff surge (ROADMAP 4c): ping-pong one session between the two
    games through the full switch protocol under an active FaultPlan,
    with Game1 journaling.  Returns checks; writes the bench artifact
    when `out_path` is given."""
    from noahgameframe_tpu.client import GameClient
    from noahgameframe_tpu.net.chaos import FaultPlan, LinkFaults
    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.replay import replay_journal

    jdir = Path(tmpdir) / "journal"
    checks: dict = {}
    cluster = LocalCluster(
        http_port=0,
        n_games=2,
        game_kwargs_by_name={"Game1": {"journal_dir": jdir}},
    )
    plan = FaultPlan(
        seed=seed,
        links={"proxy5.games": LinkFaults(dup=0.01, delay=0.02,
                                          delay_polls=2)},
    )
    cli = GameClient("surger")
    switches = 0
    elapsed = 0.0
    try:
        cluster.start(timeout=30)
        cluster.apply_chaos(plan)

        def pump(cond, t=20.0):
            return cluster.pump_until(cond, extra=cli.execute, timeout=t)

        ok = _login(cluster, cli, 6, "Surge", pump)
        checks["client entered game 6"] = ok
        by_id = {g.config.server_id: g for g in cluster.games}
        here = 6
        t0 = time.monotonic()
        for _ in range(rounds):
            target = 16 if here == 6 else 6
            sess = _session_of(by_id[here], "surger")
            if sess is None:
                break
            by_id[here].switch_server(sess.guid, target)
            if not pump(lambda: _session_of(by_id[target], "surger")
                        is not None, t=15.0):
                break
            here = target
            switches += 1
        elapsed = time.monotonic() - t0
        checks["all switches completed"] = switches == rounds
        checks["proxy re-pointed with the session"] = (
            _session_of(by_id[here], "surger") is not None
        )
    finally:
        cli.close()
        cluster.shut()

    # digest pin: the journaled run must replay bit-identically
    rep = replay_journal(jdir)
    checks["replay digest-identical under surge"] = rep.ok
    checks["replayed ticks"] = rep.ticks_replayed > 0

    rate = switches / elapsed if elapsed > 0 else 0.0
    if out_path is not None:
        Path(out_path).write_text(json.dumps({
            "metric": "handoff_switches_per_sec",
            "value": round(rate, 2),
            "unit": "switches/s",
            "detail": {
                "switches": switches,
                "elapsed_s": round(elapsed, 4),
                "seed": seed,
                "faults": {"proxy5.games": {"dup": 0.01, "delay": 0.02}},
                "replay_ok": rep.ok,
                "ticks_replayed": rep.ticks_replayed,
                "platform": "cpu",
            },
        }) + "\n")
    print(f"  surge: {switches} switches in {elapsed:.2f}s "
          f"({rate:.1f}/s), replay ok={rep.ok}")
    return checks


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--surge", action="store_true",
                    help="run the handoff-surge benchmark scenario")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=40,
                    help="surge round trips (2 switches each)")
    args = ap.parse_args()
    with tempfile.TemporaryDirectory() as tmpdir:
        if args.surge:
            out = REPO / "bench_runs" / "r06_handoff_surge.json"
            checks = surge(tmpdir, seed=args.seed or 11,
                           rounds=args.rounds, out_path=out)
        else:
            checks = run(tmpdir, seed=args.seed or 7)
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"FAILOVER SMOKE FAILED: {failed}")
        return 1
    print(f"FAILOVER SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
