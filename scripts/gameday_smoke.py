#!/usr/bin/env python
"""Game-day drill (ISSUE 11): kill a game DURING a hard store outage
DURING a session surge, heal, and prove the whole reliability stack —
failover + WAL recovery + journal replay — converges bit-identically to
a fault-free control with zero dropped sessions.

    JAX_PLATFORMS=cpu python scripts/gameday_smoke.py           # 40 sessions
    JAX_PLATFORMS=cpu python scripts/gameday_smoke.py --short   # tier-1 size

The composition is driven by a :class:`drill.DrillRunner` over a
seeded, tick-indexed :class:`drill.Campaign` (the ROADMAP item-5 game
day as a declarative schedule), with the full invariant library sampled
every pump:

    tick   0  surge active (N clients logged into Game1, chatting)
    tick   5  hard store outage under Game1 (flusher wedged — every
              flush fails, saves live only in the WAL)
    tick  10  final saves staged; checkpoint barrier fsyncs the WAL
    tick  15  assert the WAL holds the staged blobs, the store doesn't
    tick  20  Game1 HARD-killed (crash path: no drain, no goodbye)
    ...       clients keep chatting into the outage: frames park at the
              proxy, the world re-homes all N sessions onto Game2 from
              the dead game's WAL suffix (basis "wal")
    tick 120  store outage heals
    tick 125  Game1 revived from its (checkpoint, WAL) pair

Asserts: every session re-homed with zero drops and ordered chat
replay, every invariant clean for the whole run, the revived world's
NPC banks + tick bit-identical to a fault-free control driven the same
number of ticks, and Game2's journal (which recorded the entire surge
intake) replays digest-clean offline.  Writes
``bench_runs/r07_gameday.json`` pinning the re-home rate, the replay
digest, and the drill verdict together.

Exits 0 on success — tests/test_drill.py wires this into CI (short
mode tier-1, full mode ``slow``).
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

REPO = Path(__file__).resolve().parent.parent

NPCS = 8
EXTRA_TICKS = 20
KILL_TICK = 20
HEAL_TICK = 120
REVIVE_TICK = 125


def build_world(seed: int, player_capacity: int = 64):
    """Deterministic regen-only world (the chaos-smoke recipe, with a
    player bank big enough for the whole surge).  Used three times for
    Game1: live, revive substrate, and fault-free control."""
    from noahgameframe_tpu.game.defines import (
        COMM_PROPERTY_RECORD,
        PropertyGroup,
    )
    from noahgameframe_tpu.game.world import GameWorld, WorldConfig

    w = GameWorld(WorldConfig(
        npc_capacity=64, player_capacity=player_capacity, seed=seed,
        combat=False, movement=False, regen=True, middleware=False,
        regen_period_s=0.1,
    )).start()
    if 1 not in w.scene.scenes:
        w.scene.create_scene(1)
    if 1 not in w.scene.scenes[1].groups:
        w.scene.request_group(1)
    w.seed_npcs(NPCS, hp=100)
    k = w.kernel
    k.state = k.store.record_write_rows(
        k.state, "NPC", np.arange(NPCS), COMM_PROPERTY_RECORD,
        int(PropertyGroup.EFFECTVALUE), {"MAXHP": [200] * NPCS},
    )
    return w


def _drive_control(world, ticks: int) -> None:
    """Replay GameRole.execute's exact per-tick module ordering."""
    pm, k = world.pm, world.kernel
    while k.tick_count < ticks:
        for m in pm.modules.values():
            if m is not k:
                m.execute()
        k.execute()
        k.tick()
        pm.frame += 1


def _warm_compile_paths(seed: int, capacity: int) -> None:
    """Compile the player-lifecycle jits on a throwaway world BEFORE the
    cluster is under its tight lease clock.  The jax compile cache is
    process-global and keyed by shape, so a same-recipe world warms the
    live ones: without this, the first create/snapshot/apply dispatch
    stalls the single pump for seconds, the 2 s leases expire, and the
    world "fails over" a perfectly healthy game mid-login-wave."""
    from noahgameframe_tpu.persist.agent import PlayerDataAgent
    from noahgameframe_tpu.persist.codec import (
        apply_snapshot,
        snapshot_object,
    )
    from noahgameframe_tpu.persist.kv import MemoryKV

    w = build_world(seed + 2000, player_capacity=capacity)
    k = w.kernel
    flags = PlayerDataAgent(MemoryKV()).flags
    guid = k.create_object(
        "Player",
        {"Account": "_warm", "Name": "_warm", "GameID": 0},
        scene=1, group=1,
    )
    k.set_property(guid, "Gold", 1)
    if w.properties is not None:
        w.properties.full_hp_mp(guid)
        w.properties.full_sp(guid)
    blob = snapshot_object(k.store, k.state, guid, flags)
    k.state = apply_snapshot(k.store, k.state, guid, blob)
    k.destroy_object(guid)
    _drive_control(w, 3)


def _session_of(game, account: str):
    for sess in game.sessions.values():
        if sess.account == account and sess.guid is not None:
            return sess
    return None


def _first_seen(log, prefix: str):
    """This client's own numbered echoes, deduped (chaos dups) but in
    first-seen order — replay must deliver 0..N-1 ascending."""
    return list(dict.fromkeys(
        t for _w, t in log if t.startswith(prefix)
    ))


def _batch_login(cluster, clients, game_id: int, pump,
                 timeout: float = 30.0) -> bool:
    """The full reference login pipeline for N clients in lockstep:
    every client runs stage k, then one pump gates on ALL of them
    passing — a surge logs in in stage-time, not N × pipeline-time."""
    stages = [
        (lambda c: c.connect("127.0.0.1", cluster.login.config.port),
         "login connect", lambda c: c.connected),
        (lambda c: c.login(), "login ack", lambda c: c.logged_in),
        (lambda c: c.request_world_list(), "world list",
         lambda c: c.worlds),
        (lambda c: c.connect_world(c.worlds[0].server_id),
         "world grant", lambda c: c.world_grant is not None),
        (lambda c: c.connect_proxy(), "proxy connect",
         lambda c: c.connected),
        (lambda c: c.verify_key(), "key verify",
         lambda c: c.key_verified),
        (lambda c: c.select_server(game_id), "server select",
         lambda c: c.server_selected),
        (lambda c: c.create_role(f"P{c.account}"), "role list",
         lambda c: c.roles),
        (lambda c: c.enter_game(f"P{c.account}"), "enter game",
         lambda c: c.entered),
    ]
    for action, stage, cond in stages:
        for cli in clients:
            action(cli)
        if not pump(lambda: all(cond(c) for c in clients), timeout):
            stalled = [c.account for c in clients if not cond(c)]
            print(f"  surge login stalled at {stage}: {stalled[:5]}"
                  f"{'…' if len(stalled) > 5 else ''}")
            return False
    return True


def run(tmpdir, seed: int = 7, sessions: int = 40, chats: int = 5,
        out_path=None) -> dict:
    """Run the flagship campaign; returns {check name: bool}."""
    from noahgameframe_tpu.client import GameClient
    from noahgameframe_tpu.drill import (
        Campaign,
        DrillRunner,
        default_invariants,
    )
    from noahgameframe_tpu.net.chaos import (
        FaultPlan,
        LinkFaults,
        StoreFaults,
    )
    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.persist.agent import PlayerDataAgent
    from noahgameframe_tpu.persist.checkpoint import _flatten_state
    from noahgameframe_tpu.persist.codec import snapshot_object
    from noahgameframe_tpu.persist.kv import MemoryKV
    from noahgameframe_tpu.persist.writebehind import read_peer_wal
    from noahgameframe_tpu.replay import replay_journal

    tmp = Path(tmpdir)
    kv = MemoryKV()
    checks: dict = {}
    capacity = max(64, sessions + 8)
    survivor_factory = (lambda: build_world(seed + 1000,
                                            player_capacity=capacity))
    jdir2 = tmp / "journal2"
    cluster = LocalCluster(
        http_port=0,
        n_games=2,
        game_world=build_world(seed, player_capacity=capacity),
        lease_suspect_seconds=2.0,
        lease_down_seconds=4.0,
        game_kwargs={
            "autosave_seconds": 3600.0,
            "checkpoint_seconds": 3600.0,
            "persist_drain_timeout": 0.3,
        },
        game_kwargs_by_name={
            "Game1": {
                "data_agent": PlayerDataAgent(kv),
                "persist_store": kv,
                "persist_wal_dir": tmp / "wal1",
                "checkpoint_dir": tmp / "ckpt1",
            },
            "Game2": {
                "world": survivor_factory(),
                "journal_dir": jdir2,
                "data_agent": PlayerDataAgent(kv),
                "persist_store": kv,
                "persist_wal_dir": tmp / "wal2",
                "checkpoint_dir": tmp / "ckpt2",
            },
        },
        world_kwargs={"recover_store": kv},
    )
    game1, game2 = cluster.games[0], cluster.games[1]
    proxy, world, master = cluster.proxy, cluster.world, cluster.master
    clients = [GameClient(f"p{i:02d}") for i in range(sessions)]

    def stir():
        for c in clients:
            c.execute()

    def pump(cond, t=30.0):
        return cluster.pump_until(cond, extra=stir, timeout=t)

    def store_probe():
        out = {}
        for key in kv.keys("__wb__:*"):
            raw = kv.get(key)
            if raw is None:
                continue
            seq, _, tick = raw.decode("ascii", "replace").partition(":")
            out[f"store:{key}"] = (int(seq), int(tick or 0))
        return out

    # staged-save bookkeeping shared between campaign call steps
    expected: dict = {}
    pre_blob: dict = {}
    save_keys: dict = {}
    stage_flags = {"saves": False, "wal": False, "store_clean": False}

    def stage_saves(_runner) -> None:
        k1, agent1 = game1.kernel, game1.data_agent
        ok = True
        for i, cli in enumerate(clients):
            sess = _session_of(game1, cli.account)
            if sess is None:
                ok = False
                continue
            k1.set_property(sess.guid, "Gold", 1000 + i)
            k1.set_property(sess.guid, "Level", 5)
            expected[cli.account] = {
                p: k1.get_property(sess.guid, p)
                for p in ("Name", "Level", "Gold")
            }
            pre_blob[cli.account] = snapshot_object(
                k1.store, k1.state, sess.guid, agent1.flags
            )
            save_keys[cli.account] = agent1._key_of(sess.guid)
            agent1.save(sess.guid)
        game1.checkpoint_now()  # ckpt + WAL barrier (fsync)
        stage_flags["saves"] = ok

    def wal_check(_runner) -> None:
        view = read_peer_wal(tmp / "wal1")
        stage_flags["wal"] = bool(pre_blob) and all(
            view.pending.get(save_keys[acc]) == pre_blob[acc]
            for acc in pre_blob
        )
        # the store is wedged: the final saves must NOT have reached it
        stage_flags["store_clean"] = all(
            kv.get(save_keys[acc]) != pre_blob[acc] for acc in pre_blob
        )

    campaign = (
        Campaign("gameday", seed=seed)
        .add(0, "note", label="surge active")
        .add(5, "store_faults", label="hard store outage under Game1",
             pattern="game6.store",
             faults=StoreFaults(fail_first=1_000_000_000))
        .add(10, "call", label="stage final saves into the WAL",
             fn=stage_saves)
        .add(15, "call", label="WAL holds the blobs, store does not",
             fn=wal_check)
        .add(KILL_TICK, "kill_role",
             label="kill Game1 mid-outage mid-surge",
             role="Game1", hard=True)
        .add(HEAL_TICK, "heal", label="store outage heals",
             pattern="game6.store")
        .add(REVIVE_TICK, "revive_role",
             label="revive Game1 from (ckpt, WAL)", name="Game1",
             world_factory=lambda: build_world(
                 seed, player_capacity=capacity),
             resume=True)
    )

    rehome_elapsed = 0.0
    rep = None
    try:
        _warm_compile_paths(seed, capacity)
        cluster.start(timeout=60)
        # mild link chaos from the start: the dying game's links can
        # reorder freely; the SURVIVOR path is dup-only (a delaying link
        # downstream of the parking buffer would reorder frames the
        # replay just put back in order — transport's doing, not ours)
        cluster.apply_chaos(FaultPlan(
            seed=seed,
            links={
                "proxy5.games->6": LinkFaults(dup=0.05, delay=0.05,
                                              delay_polls=2),
                "proxy5.games->16": LinkFaults(dup=0.02),
                "game6.world": LinkFaults(dup=0.02),
            },
        ))
        checks["cluster wired under chaos"] = True
        # stage timeouts scale with the surge: 40 concurrent enters are
        # 40 jax create+restore dispatches through one pump
        stage_t = 30.0 + 3.0 * sessions
        # log in by squads: a single 40-wide enter wave can starve the
        # game's keepalive reports past the lease window (every enter is
        # a jax dispatch), and the master would "crash" a healthy game
        checks[f"all {sessions} clients entered game 6"] = all(
            _batch_login(cluster, clients[i:i + 8],
                         game1.config.server_id, pump, timeout=stage_t)
            for i in range(0, sessions, 8)
        )
        for c in clients:
            c.chat(f"warm-{c.account}")
        checks["surge warm chat round-tripped"] = pump(
            lambda: all(
                any(t == f"warm-{c.account}" for _w, t in c.chat_log)
                for c in clients
            ),
            t=stage_t,
        )

        # ---- the drill proper: campaign + invariants, sampled per pump
        runner = DrillRunner(
            cluster, campaign,
            invariants=default_invariants(store_probe=store_probe),
        )
        sent = [0]
        t_kill = [0.0]
        t_done = [0.0]

        def surge_extra():
            stir()
            if runner.tick <= KILL_TICK:
                return
            if t_kill[0] == 0.0:
                # don't talk into the dying socket: frames sent before
                # the proxy sees the drop are lost upstream of parking
                if 6 in proxy.games.connected_servers():
                    return
                t_kill[0] = time.monotonic()
            if sent[0] < chats:
                for c in clients:
                    c.chat(f"after-{c.account}-{sent[0]}")
                sent[0] += 1

        def rehomed():
            done = (
                sent[0] >= chats
                and world.failover.pending_count() == 0
                and proxy.parking.depth() == 0
                and all(_session_of(game2, c.account) is not None
                        for c in clients)
                and all(len(_first_seen(c.chat_log,
                                        f"after-{c.account}-")) >= chats
                        for c in clients)
            )
            if done and t_done[0] == 0.0:
                t_done[0] = time.monotonic()
            return done

        checks["all sessions re-homed, parked frames drained"] = (
            runner.pump_until(rehomed, extra=surge_extra,
                              timeout=60.0 + 3.0 * sessions)
        )
        if not checks["all sessions re-homed, parked frames drained"]:
            on2 = sum(1 for c in clients
                      if _session_of(game2, c.account) is not None)
            print(f"  re-home stalled: tick={runner.tick} sent={sent[0]}"
                  f" on_game2={on2}/{sessions}"
                  f" pending={world.failover.pending_count()}"
                  f" parked={proxy.parking.depth()}"
                  f" chats_min={min(len(_first_seen(c.chat_log, f'after-{c.account}-')) for c in clients)}")
        rehome_elapsed = max(0.0, t_done[0] - t_kill[0])
        checks["final saves staged for every session"] = (
            stage_flags["saves"])
        checks["WAL held bit-identical pre-kill blobs"] = (
            stage_flags["wal"])
        checks["store never saw the final saves"] = (
            stage_flags["store_clean"])

        # the campaign must have run to completion (heal + revive fired)
        checks["campaign fully fired"] = runner.pump_until(
            lambda: runner.steps_remaining == 0,
            extra=surge_extra, timeout=30,
        )

        # ---- ordered, lossless replay per client
        checks["chats replayed complete + in order (all clients)"] = all(
            _first_seen(c.chat_log, f"after-{c.account}-")
            == [f"after-{c.account}-{i}" for i in range(chats)]
            for c in clients
        )
        checks["zero parked frames dropped"] = (
            proxy.parking.dropped_total == 0)
        checks["every client heard REHOMING"] = all(
            any(int(n.code) == 1 for n in c.switch_notices)
            for c in clients
        )

        # ---- recovery basis + counter bank
        done_entries = world.failover.completed[-sessions:]
        checks["every re-home used the WAL basis"] = (
            len(done_entries) >= sessions
            and all(e["basis"] == "wal" for e in done_entries)
        )
        reg = world.telemetry.registry
        checks["failover counters balanced"] = (
            reg.value("nf_failover_initiated_total") == float(sessions)
            and reg.value("nf_failover_completed_total") == float(sessions)
        )
        k2 = game2.kernel

        def _props_match(cli) -> bool:
            sess = _session_of(game2, cli.account)
            if sess is None:
                return False
            return {
                p: k2.get_property(sess.guid, p)
                for p in ("Name", "Level", "Gold")
            } == expected.get(cli.account)

        checks["recovered state property-identical on survivor"] = all(
            _props_match(c) for c in clients
        )

        # ---- revived Game1 converges to the fault-free control
        revived = cluster.role_by_name("Game1")
        target = revived.kernel.tick_count + EXTRA_TICKS
        checks["revived game ticking"] = runner.pump_until(
            lambda: revived.kernel.tick_count >= target
            and cluster.wired(),
            extra=surge_extra, timeout=60,
        )
        control = build_world(seed, player_capacity=capacity)
        _drive_control(control, revived.kernel.tick_count)
        a = _flatten_state(revived.kernel.state)
        b = _flatten_state(control.kernel.state)
        npc_keys = [key for key in b if key.startswith("c/NPC/")]
        checks["world bit-identical to fault-free control"] = (
            int(a["tick"]) == int(b["tick"])
            and bool(npc_keys)
            and all(np.array_equal(a[key], b[key]) for key in npc_keys)
        )

        # ---- the drill's own verdicts
        report = runner.report()
        checks["all invariants sampled"] = all(
            report.checks.get(inv.name, 0) > 0
            for inv in runner.invariants
        )
        checks["zero invariant violations"] = report.clean
        if not report.clean:
            for v in report.violations[:10]:
                print(f"    violation @tick {v.tick} [{v.invariant}] "
                      f"{v.detail}")
        status = master.servers_status()
        checks["/json drill block live"] = (
            status.get("drill", {}).get("campaign") == "gameday")
        phase = (status.get("chaos", {}).get("store_phase", {})
                 .get("game6.store", {}))
        checks["/json store phase shows the healed outage"] = (
            phase.get("ops_seen", 0) > 0
            and phase.get("fail_first_remaining") == 0
            and phase.get("fails_injected", 0) > 0
        )
    finally:
        for c in clients:
            c.close()
        cluster.shut()

    # ---- digest pin: the survivor journaled the WHOLE game day
    # (surge intake, switch-ins, chat replay); it must replay clean
    # the offline role must mirror the recorded role's non-network kwargs
    # too (a data agent binds persist flags into kernel state, so a bare
    # stock role computes a different digest trajectory)
    from noahgameframe_tpu.net.defines import ServerType
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole

    replay_kv = MemoryKV()
    offline = GameRole(
        RoleConfig(16, int(ServerType.GAME), "Replay", "127.0.0.1", 0,
                   targets=[]),
        world=survivor_factory(),
        data_agent=PlayerDataAgent(replay_kv),
        persist_store=replay_kv,
        persist_wal_dir=tmp / "replay_wal",
        checkpoint_dir=tmp / "replay_ckpt",
        autosave_seconds=3600.0,
        checkpoint_seconds=3600.0,
        persist_drain_timeout=0.3,
    )
    offline.server.send_raw = lambda _conn, _msg, _body: True
    rep = replay_journal(jdir2, role=offline)
    checks["survivor journal replays digest-clean"] = rep.ok
    checks["survivor journal replayed ticks"] = rep.ticks_replayed > 0
    if not rep.ok:
        print(f"  {rep.summary()}")

    rate = sessions / rehome_elapsed if rehome_elapsed > 0 else 0.0
    print(f"  gameday: {sessions} sessions re-homed in "
          f"{rehome_elapsed:.2f}s ({rate:.1f}/s), replay ok={rep.ok} "
          f"({rep.ticks_replayed} ticks)")
    if out_path is not None:
        final_tick = max(rep.digests) if rep.digests else 0
        Path(out_path).write_text(json.dumps({
            "metric": "gameday_sessions_rehomed_per_sec",
            "value": round(rate, 2),
            "unit": "sessions/s",
            "detail": {
                "sessions": sessions,
                "chats_per_session": chats,
                "rehome_elapsed_s": round(rehome_elapsed, 4),
                "seed": seed,
                "campaign": "gameday",
                "kill_tick": KILL_TICK,
                "heal_tick": HEAL_TICK,
                "revive_tick": REVIVE_TICK,
                "drill_clean": bool(checks.get(
                    "zero invariant violations", False)),
                "replay_ok": bool(rep.ok),
                "ticks_replayed": int(rep.ticks_replayed),
                "final_digest": (f"{rep.digests.get(final_tick, 0):#010x}"
                                 if rep.digests else "0x0"),
                "platform": "cpu",
            },
        }, indent=2, sort_keys=True) + "\n")
    return checks


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--short", action="store_true",
                    help="tier-1 sized campaign (<30 s): 6 sessions")
    ap.add_argument("--sessions", type=int, default=None)
    ap.add_argument("--chats", type=int, default=None)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-bench", action="store_true",
                    help="skip writing bench_runs/r07_gameday.json")
    args = ap.parse_args()
    sessions = args.sessions or (6 if args.short else 40)
    chats = args.chats or (3 if args.short else 5)
    out = None
    if not args.short and not args.no_bench:
        out = REPO / "bench_runs" / "r07_gameday.json"
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = run(tmpdir, seed=args.seed, sessions=sessions,
                     chats=chats, out_path=out)
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"GAMEDAY SMOKE FAILED: {failed}")
        return 1
    print(f"GAMEDAY SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
