"""Promote measured A/B winners into bench_runs/tuning.json.

The harvest queue captures the 1M tick under the default engines and
under the opt-in variants (NF_RADIX=1/2 sort, NF_PALLAS=1 fold /
NF_PALLAS=2 fused table-free).  This
script compares whatever captures exist and records the winning flag
set, so later bench runs (including the driver's end-of-round one) use
the fastest measured configuration instead of the defaults.  Env vars
still override (bench.py applies tuning via setdefault).

A variant must beat the baseline fused tick by >3% to be promoted —
within that margin the default (simpler) engine wins ties.
"""
from __future__ import annotations

import json
import os
import sys

RUNS = os.path.join(os.path.dirname(__file__), "..", "bench_runs")
MARGIN = 0.97


def tick_ms(name: str):
    path = os.path.join(RUNS, name)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            d = json.load(f)
        if "error" in d:
            return None
        return float(d["detail"]["tick_ms"])
    except Exception:  # noqa: BLE001
        return None


def main() -> None:
    base = tick_ms("r06_tpu_1m.json")
    if base is None:
        base = tick_ms("r05_tpu_1m.json")
    if base is None:
        print("no baseline 1M capture; not writing tuning", file=sys.stderr)
        return
    tuning: dict = {}
    detail = {"baseline_tick_ms": base}

    radix_variants = [
        ("1", tick_ms("r05_tpu_1m_radix.json")),
        ("2", tick_ms("r05_tpu_1m_radix2.json")),
    ]
    best_flag, best_ms = None, base * MARGIN
    for flag, ms in radix_variants:
        detail[f"radix{flag}_tick_ms"] = ms
        if ms is not None and ms < best_ms:
            best_flag, best_ms = flag, ms
    if best_flag is not None:
        tuning["NF_RADIX"] = best_flag

    # NF_PALLAS tri-state election: 1 (fold-only kernel, plus its lane-
    # aligned variant) and 2 (fused table-free engine, r11) compete
    # against the same baseline; the fastest capture past the margin
    # wins.  Crash-immune like every rule here: a missing/errored
    # capture is None and simply doesn't compete (a 1M world may land in
    # the fused engine's VMEM-fallback regime, in which case its capture
    # ~equals baseline and loses the margin on its own).
    pallas_ms = tick_ms("r05_tpu_1m_pallas.json")
    pallas_al_ms = tick_ms("r05_tpu_1m_pallas_aligned.json")
    pallas2_ms = tick_ms("r11_tpu_1m_pallas2.json")
    detail["pallas_tick_ms"] = pallas_ms
    detail["pallas_aligned_tick_ms"] = pallas_al_ms
    detail["pallas2_tick_ms"] = pallas2_ms
    candidates = [
        ("1", pallas_ms),
        ("1", pallas_al_ms),
        ("2", pallas2_ms),
    ]
    best_mode, best_pallas = None, base * MARGIN
    for mode, ms in candidates:
        if ms is not None and ms < best_pallas:
            best_mode, best_pallas = mode, ms
    if best_mode is not None:
        tuning["NF_PALLAS"] = best_mode
        if (
            best_mode == "1"
            and best_pallas == pallas_al_ms
            and pallas_al_ms != pallas_ms
        ):
            tuning["NF_PALLAS_ALIGN"] = "128"

    # Verlet skin (ops/verlet.py): the harvest queue captures the 1M tick
    # at skins 1/2/4; the fastest capture that beats the margin elects
    # NF_VERLET_SKIN.  A too-large skin loses through bucket inflation
    # (cell_size >= radius + skin), a too-small one through rebuild rate,
    # so this is a measured election, not a formula.
    best_skin, best_skin_ms = None, base * MARGIN
    for skin in ("1", "2", "4"):
        ms = tick_ms(f"r06_tpu_1m_verlet{skin}.json")
        detail[f"verlet{skin}_tick_ms"] = ms
        if ms is not None and ms < best_skin_ms:
            best_skin, best_skin_ms = skin, ms
    if best_skin is not None:
        tuning["NF_VERLET_SKIN"] = best_skin

    # Counting-sort binning (NF_BINNING, ops/stencil.py): the r07 A/B
    # pins its OWN baseline (env NF_BINNING=sort in the harvest queue,
    # immune to this file's previous output) — compare count against
    # that same-round capture when it exists, else the round baseline.
    count_base = tick_ms("r07_tpu_1m.json")
    if count_base is None:
        count_base = base
    count_ms = tick_ms("r07_tpu_1m_count.json")
    detail["binning_sort_tick_ms"] = count_base
    detail["binning_count_tick_ms"] = count_ms
    if count_ms is not None and count_ms < count_base * MARGIN:
        tuning["NF_BINNING"] = "count"

    # K-tick trains (NF_TICK_TRAIN, ISSUE 20): the r13 A/B captures the
    # 100k tick with --train 8 (tick_ms is already amortized PER TICK:
    # train wall / K), compared against the same-shape 100k baseline —
    # the 1M `base` above is the wrong shape for this election.  Trains
    # only pay off where the per-dispatch host round-trip is a real
    # fraction of the tick, so the promotion is measured, never assumed.
    # Crash-immune like every rule here: a missing/errored capture is
    # None and doesn't compete.
    train_base = tick_ms("r07_tpu_100k.json")
    if train_base is None:
        train_base = tick_ms("r05_tpu_100k_v2.json")
    train_ms = tick_ms("r13_tpu_100k_train8.json")
    detail["train_base_100k_tick_ms"] = train_base
    detail["train8_100k_tick_ms"] = train_ms
    if (train_base is not None and train_ms is not None
            and train_ms < train_base * MARGIN):
        tuning["NF_TICK_TRAIN"] = "8"

    out = {"env": tuning, "detail": detail}
    with open(os.path.join(RUNS, "tuning.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
