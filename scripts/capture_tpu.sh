#!/bin/bash
# Round-5 on-chip capture chain.  The axon tunnel is flaky (died mid-round-3,
# whole round-4, and flaps within round 5): probe cheaply in a loop, and the
# moment a dispatch succeeds run the whole capture ladder, writing each
# artifact as soon as it exists so a mid-chain tunnel death loses only the
# remaining steps.  Usage: scripts/capture_tpu.sh [once]
set -u
cd "$(dirname "$0")/.."
OUT=bench_runs
LOG=/tmp/capture_tpu.log
export NF_COMPILE_CACHE=/tmp/nf_xla_cache

probe() {
  timeout 75 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
x = jax.jit(lambda x: x * 2)(jnp.ones(128))
x.block_until_ready()
assert jax.devices()[0].platform == "tpu"
EOF
}

run_one() {  # run_one <outfile> <timeout_s> [bench args...]
  local out="$1" tmo="$2"; shift 2
  echo "$(date -u +%H:%M:%S) start $out: bench.py $*" >>"$LOG"
  if timeout "$tmo" python bench.py --platform tpu "$@" >"/tmp/cap.$$" 2>>"$LOG"; then
    if [ -s "/tmp/cap.$$" ] && python -c "import json,sys; json.load(open('/tmp/cap.$$'))" 2>/dev/null; then
      mv "/tmp/cap.$$" "$OUT/$out"
      echo "$(date -u +%H:%M:%S) DONE $out" >>"$LOG"
      return 0
    fi
  fi
  rm -f "/tmp/cap.$$"
  echo "$(date -u +%H:%M:%S) FAILED/timeout $out" >>"$LOG"
  return 1
}

chain() {
  # Re-capture 100k with the fixed (reconcile-free) windowed sampler.
  run_one r05_tpu_100k_fixed.json 900 --entities 100000 --ticks 60 --lat-budget-s 10 || return 1
  # The headline: 1M, round-4/5 geometry, first time on chip.
  run_one r05_tpu_1m.json 1500 --entities 1000000 --ticks 90 --lat-budget-s 25 || return 1
  # A/B the radix-binning sort replacement at 1M (ROOFLINE.md prime suspect).
  NF_RADIX=1 run_one r05_tpu_1m_radix.json 1500 --entities 1000000 --ticks 90 --lat-budget-s 25
  # A/B the Pallas fold at 100k first (cheap validity check), then 1M.
  NF_PALLAS=1 run_one r05_tpu_100k_pallas.json 900 --entities 100000 --ticks 60 --lat-budget-s 10
  NF_PALLAS=1 run_one r05_tpu_1m_pallas.json 1500 --entities 1000000 --ticks 90 --lat-budget-s 25
  # Served path on chip (verdict item 8): tick + diff flush + interest fanout.
  run_one r05_tpu_served_100k.json 900 --served --entities 100000 --ticks 30 \
    --sessions 500 --interest-radius 8
  return 0
}

while :; do
  if probe; then
    echo "$(date -u +%H:%M:%S) tunnel UP - starting chain" >>"$LOG"
    chain && { echo "$(date -u +%H:%M:%S) chain complete" >>"$LOG"; exit 0; }
    echo "$(date -u +%H:%M:%S) chain incomplete; re-probing" >>"$LOG"
  else
    echo "$(date -u +%H:%M:%S) tunnel down" >>"$LOG"
  fi
  [ "${1:-}" = once ] && exit 1
  sleep 120
done
