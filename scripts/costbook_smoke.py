#!/usr/bin/env python
"""Cost-observatory smoke: /costbook end to end over a served cluster.

    JAX_PLATFORMS=cpu python scripts/costbook_smoke.py

Boots the five-role LocalCluster, walks a GameClient through the full
login pipeline, drives movement until the serving edge has compiled its
interest entries, and asserts:

- every role serves `/costbook` (master's aggregate on its status
  server; world/login/proxy/game each on a serve_metrics() server) and
  the document is well-formed JSON with the snapshot schema;
- the game role's book covers the expected entries (kernel.step plus
  the interest/serve edge) with compile wall time and cost analysis
  recorded for each;
- `nf_recompiles_total` / `nf_hbm_bytes_in_use` ride the game's
  /metrics exposition;
- the master aggregates the games' heartbeat `costbook` ext blobs at
  `/costbook` (totals + per-game), next to `/pipeline`;
- after warmup, continued movement/combat churn causes ZERO compiles
  not covered by a sanctioned generation bump
  (CostBook.unexplained_since — the live twin of nf-lint's static
  recompile-hazard rule).

Exits 0 on success — tests/test_costbook.py wires this into CI.
"""

from __future__ import annotations

import json
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: entries the served game role must have compiled after the drive
EXPECTED_GAME_ENTRIES = ("kernel.step", "interest.step/Player")


def _scrape(cluster, port: int, path: str):
    """GET a status endpoint while a background thread pumps the
    cluster (urlopen blocks; same pattern as pipeline_smoke)."""
    import threading
    import time as _t

    stop = threading.Event()

    def _bg():
        while not stop.is_set():
            cluster.execute()
            _t.sleep(0.002)

    th = threading.Thread(target=_bg, daemon=True)
    th.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as r:
            body = r.read().decode()
    finally:
        stop.set()
        th.join(timeout=2)
    return body


def run() -> dict:
    """Run the whole scenario; returns {check name: bool}."""
    from noahgameframe_tpu.client import GameClient
    from noahgameframe_tpu.net.roles.cluster import LocalCluster

    checks = {}
    cluster = LocalCluster(http_port=0,
                           game_kwargs={"interest_radius": 16.0})
    game, master = cluster.game, cluster.master
    # the kernel-less roles get /costbook via serve_metrics (ephemeral
    # ports, pumped from each role's execute)
    side = {r: r.serve_metrics(0)
            for r in (cluster.world, cluster.login, cluster.proxy, game)}
    cli = GameClient("cost")
    try:
        cluster.start(timeout=30)
        cli.connect("127.0.0.1", cluster.login.config.port)

        def pump(cond, t=15.0):
            return cluster.pump_until(cond, extra=cli.execute, timeout=t)

        ok = pump(lambda: cli.connected)
        cli.login()
        ok = ok and pump(lambda: cli.logged_in)
        cli.request_world_list()
        ok = ok and pump(lambda: cli.worlds)
        cli.connect_world(cli.worlds[0].server_id)
        ok = ok and pump(lambda: cli.world_grant is not None)
        cli.connect_proxy()
        ok = ok and pump(lambda: cli.connected)
        cli.verify_key()
        ok = ok and pump(lambda: cli.key_verified)
        cli.select_server(game.config.server_id)
        ok = ok and pump(lambda: cli.server_selected)
        cli.create_role("Cost")
        ok = ok and pump(lambda: cli.roles)
        cli.enter_game("Cost")
        ok = ok and pump(lambda: cli.entered)
        checks["client entered world"] = ok

        # movement churn until the serving edge compiled its entries
        step = [0]

        def stir():
            cli.execute()
            step[0] += 1
            if step[0] % 25 == 0 and cli.entered:
                cli.move_to(float(step[0] % 500), 100.0)

        book = game.kernel.costbook
        checks["game entries compiled"] = cluster.pump_until(
            lambda: all(n in book.entries and book.entries[n].compiles
                        for n in EXPECTED_GAME_ENTRIES),
            extra=stir, timeout=30,
        )

        # ---- recompile-free churn after warmup (the soak gate, live)
        mark = book.mark()
        # brief live churn window — the long recompile-free soak is
        # tests/test_costbook.py::test_soak_120_ticks_recompile_free
        cluster.pump_until(lambda: False, extra=stir, timeout=0.75)
        unexplained = book.unexplained_since(mark)
        checks["zero unexplained retraces"] = not unexplained
        if unexplained:
            print(f"  unexplained: {unexplained}", file=sys.stderr)

        # ---- /costbook on every role, uniform schema
        for role, http in side.items():
            doc = json.loads(_scrape(cluster, http.port, "/costbook"))
            name = role.config.name
            checks[f"/costbook on {name}"] = (
                isinstance(doc.get("entries"), dict)
                and "generation" in doc and "hbm" in doc
                and "compiles" in doc
            )
            if role is game:
                checks["game /costbook covers entries"] = all(
                    n in doc["entries"] for n in EXPECTED_GAME_ENTRIES
                )
                e = doc["entries"].get("kernel.step", {})
                checks["entry has compile wall + cost"] = (
                    e.get("compile_ms_total", 0) > 0
                    and "flops" in e.get("last", {})
                    and "temp_bytes" in e.get("last", {})
                )
                checks["hbm census sampled"] = (
                    doc["hbm"].get("source") in
                    ("memory_stats", "live_arrays")
                    and doc["hbm"].get("live_bytes", 0) > 0
                )

        # ---- nf_recompiles_total / nf_hbm_* on the game's /metrics
        text = _scrape(cluster, side[game].port, "/metrics")
        checks["nf_compiles_total exposed"] = "nf_compiles_total{" in text
        checks["nf_hbm gauges exposed"] = (
            "nf_hbm_bytes_in_use" in text and "nf_hbm_peak_bytes" in text
        )

        # ---- master aggregation from the heartbeat ext blobs
        checks["heartbeats carried costbook blob"] = cluster.pump_until(
            lambda: master.costbook_status()["games"],
            extra=cli.execute, timeout=15,
        )
        agg = json.loads(_scrape(cluster, master.http.port, "/costbook"))
        games = agg.get("games", {})
        checks["master /costbook aggregates"] = (
            bool(games)
            and all("entries" in g for g in games.values())
            and agg.get("totals", {}).get("compiles", 0) > 0
        )
        checks["master /json costbook block"] = bool(
            master.servers_status().get("costbook")
        )
    finally:
        cli.close()
        cluster.shut()
    return checks


def main() -> int:
    checks = run()
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"COSTBOOK SMOKE FAILED: {failed}")
        return 1
    print(f"COSTBOOK SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
