"""Per-pass on-chip timing for the fused world tick.

docs/ROOFLINE.md puts the measured 1M tick ~25-30x above its bandwidth
roofline and names the global sort as prime suspect, the table-build
scatter grain second.  This script arbitrates: it times each pass of the
combat pipeline SEPARATELY on the live backend (full tick, XLA argsort,
radix argsort, pair-table build, stencil fold XLA/Pallas, payload
scatter, pull gather) and prints one JSON object, ready for
`bench_runs/`.

RTT discipline: each timed region issues `reps` async dispatches and
blocks ONCE at the end, so per-pass tunnel RTT amortizes to RTT/reps.

Usage: python scripts/profile_passes.py [--entities 1000000] [--reps 20]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=1_000_000)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--platform", choices=("tpu", "cpu"), default="tpu",
                    help="cpu = smoke-test the harness off-chip (the "
                         "sitecustomize axon hook overrides JAX_PLATFORMS, "
                         "so this must force it post-import)")
    args = ap.parse_args()

    from noahgameframe_tpu.utils.platform import force_cpu, init_compile_cache

    if args.platform == "cpu":
        force_cpu()
    # default-on cache: a harvest retry after a tunnel flap mid-run
    # re-pays only the passes that never compiled
    os.environ.setdefault("NF_COMPILE_CACHE", "/tmp/nf_xla_cache")
    init_compile_cache()

    import jax
    import jax.numpy as jnp

    from noahgameframe_tpu.game import build_benchmark_world
    from noahgameframe_tpu.ops.aoi import cell_of
    from noahgameframe_tpu.ops.stencil import (
        _bits_for,
        _build_pair_counting,
        _cell_counts,
        _counting_ranks,
        _radix_argsort,
        build_cell_table_pair,
        pull,
    )

    n = args.entities
    reps = args.reps
    world = build_benchmark_world(n, combat=True, seed=42)
    k = world.kernel
    combat = world.combat
    spec = k.store.spec("NPC")

    # every timed pass routes through the kernel's CostBook — the pass
    # list, per-pass compile wall time and compiled FLOPs/bytes land in
    # ONE ledger shared with bench.py's detail block (the fused tick is
    # already in it as "kernel.run")
    book = k.costbook

    def wrap(name, fn):
        return book.wrap(f"pass.{name}", fn, stage="profile")

    dev = jax.devices()[0]
    out: dict = {
        "metric": "pass_ms",
        "entities": n,
        "reps": reps,
        "device": str(dev),
        "platform": dev.platform,
        "passes": {},
    }

    def timed(name, fn, *a):
        """Median-free single measurement: warmup compile, then `reps`
        queued dispatches with one terminal block (RTT/reps pollution).
        The accumulated JSON reprints after EVERY pass (last line wins)
        so a tunnel death mid-run still leaves decision-grade data."""
        try:
            r = fn(*a)
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for _ in range(reps):
                r = fn(*a)
            jax.block_until_ready(r)
            ms = 1000 * (time.perf_counter() - t0) / reps
            out["passes"][name] = round(ms, 3)
            print(f"# {name}: {ms:.3f} ms", file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — record and keep going
            out["passes"][name] = f"ERROR {type(e).__name__}: {e}"
            print(f"# {name}: FAILED {e}", file=sys.stderr, flush=True)
        print(json.dumps(out), flush=True)

    # -- the whole fused tick (1 tick per dispatch) ---------------------------
    k.run_device(1)  # compile + host reconcile once
    def tick():
        k.run_device(1, reconcile=False)
        return k.state.classes["NPC"].i32
    timed("full_tick", tick)

    # -- geometry shared with CombatModule -----------------------------------
    # (read the class state AFTER the tick timing: the fused step donates
    # its input buffers, so references captured earlier are deleted)
    cs = k.state.classes["NPC"]
    pos = cs.vec[:, spec.slot("Position").col, :2]
    alive = cs.alive
    cap = alive.shape[0]  # bank capacity (pow2) >= n live entities
    cell_size, width = combat.cell_size, combat.width
    bucket = combat.resolved_bucket(cap)
    att_bucket = combat.resolved_att_bucket(cap)
    n_cells = width * width
    out["geometry"] = {
        "width": width, "cell_size": cell_size,
        "bucket": bucket, "att_bucket": att_bucket,
    }

    key = jnp.where(alive, cell_of(pos, cell_size, width), n_cells)
    key = jax.block_until_ready(jax.jit(lambda x: x)(key))

    timed("argsort_xla", wrap("argsort_xla", jnp.argsort), key)
    bits = _bits_for(n_cells)
    for b in (1, 2, 3):  # binary / 4-way / 8-way digit variants
        timed(
            f"argsort_radix_b{b}",
            wrap(f"argsort_radix_b{b}",
                 lambda kk, b=b: _radix_argsort(kk, bits, b)),
            key,
        )

    # -- pair-table build (argsort + rank + scatter), as combat runs it -------
    f32 = jnp.float32
    camp_f = cs.i32[:, spec.slot("Camp").col].astype(f32)
    scene_f = cs.i32[:, spec.slot("SceneID").col].astype(f32)
    group_f = cs.i32[:, spec.slot("GroupID").col].astype(f32)
    rows_f = jnp.arange(cap, dtype=f32)
    atk_f = cs.i32[:, spec.slot("ATK_VALUE").col].astype(f32)
    # attacker mask at the staggered duty the bench runs with
    interval = max(1, k.schedule.ticks_of(combat.attack_period_s))
    attacking = alive & ((jnp.arange(cap) % interval) == 0)
    vic_feats = jnp.stack([pos[:, 0], pos[:, 1], camp_f, scene_f, group_f], -1)
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], atk_f, camp_f, scene_f, group_f, rows_f], -1
    )

    # CellTable carries static geometry ints — passing one through jit
    # would trace them and break grid_view's reshape, so the jitted
    # pieces take raw arrays and rebuild tables against closed-over
    # static geometry.
    from noahgameframe_tpu.ops.stencil import CellTable

    def mk_vic(payload, slot_of):
        return CellTable(payload, slot_of, jnp.int32(0), width, cell_size, bucket)

    def mk_att(payload, slot_of):
        return CellTable(payload, slot_of, jnp.int32(0), width, cell_size,
                         att_bucket)

    build = wrap(
        "build_pair_tables",
        lambda p, al, vf, am, af: build_cell_table_pair(
            p, al, vf, am, af, cell_size, width, bucket, att_bucket
        ),
    )
    timed("build_pair_tables", build, pos, alive, vic_feats, attacking, att_feats)
    vic_table, att_table = jax.block_until_ready(
        build(pos, alive, vic_feats, attacking, att_feats)
    )

    # -- counting-sort binning passes (NF_BINNING=count, ops/stencil.py):
    # histogram, the K-round scatter-min rank selection, and the whole
    # sort-free pair build — timed directly against argsort_* and
    # build_pair_tables above so the A/B decomposes per pass -------------
    timed(
        "count_histogram",
        wrap("count_histogram", lambda kk: _cell_counts(kk, n_cells)),
        key,
    )
    timed(
        "count_rank_rounds",  # bucket rounds of scatter-min over [N]
        wrap("count_rank_rounds",
             lambda kk: _counting_ranks(kk, n_cells, bucket)),
        key,
    )
    timed(
        "count_build_pair",  # full sort-free twin of build_pair_tables
        wrap(
            "count_build_pair",
            lambda kk, al, vf, am, af: _build_pair_counting(
                vf, al, am, af, kk, n_cells, cell_size, width, bucket,
                att_bucket,
            ),
        ),
        key, alive, vic_feats, attacking, att_feats,
    )

    # -- Verlet cache passes (ops/verlet.py): what a rebuild tick, a reuse
    # vote, and the sort-free table replay each cost on this geometry ---------
    from noahgameframe_tpu.ops.verlet import (
        full_table as v_full,
        init_cache,
        refresh,
        sub_table as v_sub,
    )

    skin = 2.0  # representative; geometry stays the bench world's own
    fresh = init_cache(cap)  # all-False anchor: every refresh rebuilds
    reb = wrap(
        "verlet_rebuild",
        lambda c, p, al: refresh(c, p, al, cell_size, width, bucket, skin),
    )
    timed("verlet_rebuild", reb, fresh, pos, alive)
    warm, _ = jax.block_until_ready(reb(fresh, pos, alive))
    timed(
        "verlet_reuse",  # anchored at these exact positions: zero motion
        reb,             # same program — the cache vote decides at runtime
        warm, pos, alive,
    )
    timed(
        "verlet_cached_tables",  # the payload replay both tables run on a
        wrap(                    # reuse tick — the argsort-free build half
            "verlet_cached_tables",
            lambda c, al, vf, am, af: (
                v_full(c, vf, al, n_cells, cell_size, width, bucket),
                v_sub(c, am, af, n_cells, cell_size, width, att_bucket),
            ),
        ),
        warm, alive, vic_feats, attacking, att_feats,
    )

    # -- payload scatter / pull gather in isolation ---------------------------
    dump = n_cells * bucket
    occ = jnp.concatenate([vic_feats, jnp.ones((cap, 1), f32)], -1)
    timed(
        "payload_scatter",
        wrap(
            "payload_scatter",
            lambda so, ft: jnp.zeros((dump + 1, ft.shape[-1]),
                                     f32).at[so].set(ft),
        ),
        vic_table.slot_of, occ,
    )
    slot_res = jnp.zeros((width, width, bucket, 2), jnp.int32)
    timed(
        "pull_gather",
        wrap("pull_gather",
             lambda so, r: pull(mk_vic(vic_table.payload, so), r,
                                fill=(0, -1))),
        vic_table.slot_of, slot_res,
    )

    # -- the stencil fold, XLA and Pallas (the production fold functions —
    # combat_fold_xla is the single source of truth for layout/semantics) ----
    from noahgameframe_tpu.game.combat import combat_fold_xla

    def fold_xla(vt, at):
        return combat_fold_xla(vt, at, combat.radius)

    timed(
        "fold_xla",
        wrap("fold_xla",
             lambda vp, vs, ap, as_: fold_xla(mk_vic(vp, vs),
                                              mk_att(ap, as_))),
        vic_table.payload, vic_table.slot_of,
        att_table.payload, att_table.slot_of,
    )

    try:
        from noahgameframe_tpu.ops.stencil_pallas import combat_fold_pallas

        interp = jax.default_backend() not in ("tpu", "axon")
        pname = "fold_pallas" + ("_interpret" if interp else "")
        timed(
            pname,
            wrap(
                pname,
                lambda vp, vs, ap, as_: combat_fold_pallas(
                    mk_vic(vp, vs), mk_att(ap, as_), combat.radius,
                    interpret=interp,
                ),
            ),
            vic_table.payload, vic_table.slot_of,
            att_table.payload, att_table.slot_of,
        )
    except Exception as e:  # noqa: BLE001
        out["passes"]["fold_pallas"] = f"ERROR {type(e).__name__}: {e}"

    # -- fused table-free engine (NF_PALLAS=2, r11): the slots-only build
    # and the bank-gathering fused kernel, as separate CostBook entries so
    # the harvest attributes compile wall + FLOPs/bytes per variant from
    # the same ledger the split passes use ------------------------------------
    try:
        from noahgameframe_tpu.ops.stencil import (
            CellSlots,
            build_cell_slots_pair,
        )
        from noahgameframe_tpu.ops.stencil_pallas import (
            fused_fits_vmem,
            fused_neighborhood,
        )

        interp = jax.default_backend() not in ("tpu", "axon")
        fits, need, budget = fused_fits_vmem(cap, width, bucket, att_bucket)
        out["pallas2_vmem"] = {
            "fits": bool(fits), "need_bytes": int(need),
            "budget_bytes": int(budget),
        }
        slots_pair = wrap(
            "pallas2_slots_pair",
            lambda p, al, am: build_cell_slots_pair(
                p, al, am, cell_size, width, bucket, att_bucket
            ),
        )
        timed("pallas2_slots_pair", slots_pair, pos, alive, attacking)
        vic_slots, att_slots = jax.block_until_ready(
            slots_pair(pos, alive, attacking)
        )
        bank = jnp.stack(
            [pos[:, 0], pos[:, 1], camp_f, scene_f, group_f, atk_f], -1
        )

        def mk_slots(so, kk):
            return CellSlots(so, jnp.int32(0), width, cell_size, kk)

        if fits:
            fname = "pallas2_fused" + ("_interpret" if interp else "")
            timed(
                fname,
                wrap(
                    fname,
                    lambda bk, vso, aso: fused_neighborhood(
                        bk, mk_slots(vso, bucket), mk_slots(aso, att_bucket),
                        combat.radius, interpret=interp,
                    ),
                ),
                bank, vic_slots.slot_of, att_slots.slot_of,
            )
        else:
            # the engine dispatch would downgrade here — record the
            # regime instead of timing a kernel production never runs
            out["passes"]["pallas2_fused"] = (
                f"VMEM_FALLBACK need={need} budget={budget}"
            )
    except Exception as e:  # noqa: BLE001
        out["passes"]["pallas2_fused"] = f"ERROR {type(e).__name__}: {e}"

    # compile/cost ledger for the whole pass list — same schema as the
    # /costbook route, so pass profiles and BENCH detail join on entry
    out["costbook"] = book.snapshot()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
