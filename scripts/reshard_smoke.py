#!/usr/bin/env python
"""Elastic-mesh game day (ISSUE 17): grow the serving mesh DURING a
session surge, then drain a device out from under the same live world —
all under link chaos — and prove digest-pinned parity with a fault-free
static-mesh control.

    JAX_PLATFORMS=cpu python scripts/reshard_smoke.py           # full
    JAX_PLATFORMS=cpu python scripts/reshard_smoke.py --short   # tier-1

The composition rides the drill engine: a seeded, tick-indexed
:class:`drill.Campaign` over a LocalCluster whose Game1 world is placed
on a 2-device mesh with the elastic driver attached
(``GameWorld.shard``), sampled every pump by the standard invariant
library plus :class:`drill.StableUnderReshard` pinned to a 1-shard
fault-free :class:`~parallel.elastic.DigestControl` twin:

    tick   0  surge active (N clients logged into Game1, chatting)
    tick   6  grow_mesh 2 -> 4 devices; clients chat INTO the reshard
    tick 120  drain_device 1 (budgeted row exodus, then 4 -> 3 shrink);
              more chat traffic rides the drain
    tick 160  chaos heals

Asserts: per-tick ``canonical_digest`` equality with the control at
every sampled tick (the mesh grew, drained and rebalanced in between —
the NPC bytes may not differ), zero rows dropped by the exodus
protocol, population conserved across both ops, every mid-reshard chat
echoed exactly once (no dropped or duplicated frames at the serve
edge), every recompile sanctioned by a reshard generation bump
(``unexplained_since() == []``), and the drill verdict clean.  Full
mode writes ``bench_runs/r10_reshard_gameday.json``.

Exits 0 on success — tests/test_drill.py wires this into CI (short
mode tier-1, full mode ``slow``).
"""

from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import scripts.cpu_env  # noqa: F401,E402  (8 virtual CPU devices)

REPO = Path(__file__).resolve().parent.parent

NPCS = 16
GROW_TICK = 6
DRAIN_TICK = 120
HEAL_TICK = 160
GROW_TO = 4
DRAIN_DEVICE = 1


def build_world(seed: int, n_shards: int, player_capacity: int = 96):
    """Deterministic regen world with the spatial placement attached.
    Capacities are divisible by every mesh width this campaign visits
    (2, 4, 3 — and 1 for the control): NPC 48, Player 96."""
    from noahgameframe_tpu.game.defines import (
        COMM_PROPERTY_RECORD,
        PropertyGroup,
    )
    from noahgameframe_tpu.game.world import GameWorld, WorldConfig
    from noahgameframe_tpu.parallel.rowmigrate import SpatialPlacement

    w = GameWorld(WorldConfig(
        npc_capacity=48, player_capacity=player_capacity, seed=seed,
        extent=64.0, dt=0.01, combat=False, movement=False, regen=True,
        middleware=False, regen_period_s=0.1,
        placement=SpatialPlacement(
            class_name="NPC", pos_prop="Position", extent=64.0,
            cell_size=8.0, width=8, n_shards=n_shards, mig_budget=8,
        ),
    )).start()
    if 1 not in w.scene.scenes:
        w.scene.create_scene(1)
    if 1 not in w.scene.scenes[1].groups:
        w.scene.request_group(1)
    w.seed_npcs(NPCS, hp=100)
    k = w.kernel
    k.state = k.store.record_write_rows(
        k.state, "NPC", np.arange(NPCS), COMM_PROPERTY_RECORD,
        int(PropertyGroup.EFFECTVALUE), {"MAXHP": [200] * NPCS},
    )
    # unique identity in an inert saved column (Gold) so the placement-
    # invariant digest can pair rows however the mesh has shuffled them
    from noahgameframe_tpu.core.store import with_class

    import jax.numpy as jnp

    slot = k.store.spec("NPC").slot("Gold")
    cs = k.state.classes["NPC"]
    k.state = with_class(k.state, "NPC", cs.replace(
        i32=cs.i32.at[:, slot.col].set(
            jnp.arange(cs.i32.shape[0], dtype=jnp.int32))))
    return w, slot.col


class _ControlTwin:
    """The DigestControl world shim: ticks the control world with
    GameRole.execute's exact per-tick module ordering."""

    def __init__(self, world):
        self.world = world
        self.kernel = world.kernel

    def tick(self) -> None:
        pm, k = self.world.pm, self.world.kernel
        for m in pm.modules.values():
            if m is not k:
                m.execute()
        k.execute()
        k.tick()
        pm.frame += 1


def _session_of(game, account: str):
    for sess in game.sessions.values():
        if sess.account == account and sess.guid is not None:
            return sess
    return None


def _batch_login(cluster, clients, game_id: int, pump,
                 timeout: float = 30.0) -> bool:
    stages = [
        (lambda c: c.connect("127.0.0.1", cluster.login.config.port),
         "login connect", lambda c: c.connected),
        (lambda c: c.login(), "login ack", lambda c: c.logged_in),
        (lambda c: c.request_world_list(), "world list",
         lambda c: c.worlds),
        (lambda c: c.connect_world(c.worlds[0].server_id),
         "world grant", lambda c: c.world_grant is not None),
        (lambda c: c.connect_proxy(), "proxy connect",
         lambda c: c.connected),
        (lambda c: c.verify_key(), "key verify",
         lambda c: c.key_verified),
        (lambda c: c.select_server(game_id), "server select",
         lambda c: c.server_selected),
        (lambda c: c.create_role(f"P{c.account}"), "role list",
         lambda c: c.roles),
        (lambda c: c.enter_game(f"P{c.account}"), "enter game",
         lambda c: c.entered),
    ]
    for action, stage, cond in stages:
        for cli in clients:
            action(cli)
        if not pump(lambda: all(cond(c) for c in clients), timeout):
            stalled = [c.account for c in clients if not cond(c)]
            print(f"  surge login stalled at {stage}: {stalled[:5]}"
                  f"{'…' if len(stalled) > 5 else ''}")
            return False
    return True


def run(tmpdir, seed: int = 7, sessions: int = 12, chats: int = 4,
        out_path=None) -> dict:
    """Run the elastic-mesh campaign; returns {check name: bool}."""
    import time

    from noahgameframe_tpu.client import GameClient
    from noahgameframe_tpu.drill import (
        Campaign,
        DrillRunner,
        StableUnderReshard,
        default_invariants,
    )
    from noahgameframe_tpu.net.chaos import FaultPlan, LinkFaults
    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.parallel.elastic import DigestControl

    checks: dict = {}
    world, gold_col = build_world(seed, n_shards=2)
    ident_cols = {"NPC": gold_col}
    cluster = LocalCluster(
        http_port=0,
        n_games=1,
        game_world=world,
        # a mesh-width recompile stalls one pump for seconds on CPU; the
        # lease clock must not read that as a dead game
        lease_suspect_seconds=30.0,
        lease_down_seconds=60.0,
        game_kwargs={
            "autosave_seconds": 3600.0,
            "checkpoint_seconds": 3600.0,
        },
    )
    game1 = cluster.games[0]
    proxy, master = cluster.proxy, cluster.master
    # the elastic driver rides the role's own world — grow_mesh /
    # drain_device campaign actions resolve through GameRole
    elastic = world.shard(2, ident_cols=ident_cols, exodus_tick_bound=64)
    control = DigestControl(
        _ControlTwin(build_world(seed, n_shards=1)[0]), ident_cols)

    clients = [GameClient(f"e{i:02d}") for i in range(sessions)]

    def stir():
        for c in clients:
            c.execute()

    def pump(cond, t=30.0):
        return cluster.pump_until(cond, extra=stir, timeout=t)

    campaign = (
        Campaign("reshard", seed=seed)
        .add(0, "note", label="surge active on a 2-device mesh")
        .add(GROW_TICK, "grow_mesh", label="grow 2 -> 4 mid-surge",
             role="Game1", n=GROW_TO)
        .add(DRAIN_TICK, "drain_device",
             label="drain device 1 under chat traffic",
             role="Game1", device=DRAIN_DEVICE)
        .add(HEAL_TICK, "heal", label="link chaos heals")
    )

    rep = None
    t0 = time.monotonic()
    try:
        cluster.start(timeout=60)
        # delay-only link chaos: frames stall and reorder but never
        # duplicate, so "every chat echoed exactly once" is a real
        # serve-edge coherence check, not an artifact of dup faults
        cluster.apply_chaos(FaultPlan(
            seed=seed,
            links={
                "proxy5.games->6": LinkFaults(delay=0.08, delay_polls=2),
                "game6.world": LinkFaults(delay=0.05, delay_polls=1),
            },
        ))
        checks["cluster wired under link chaos"] = True
        stage_t = 30.0 + 3.0 * sessions
        checks[f"all {sessions} clients entered game 6"] = all(
            _batch_login(cluster, clients[i:i + 8],
                         game1.config.server_id, pump, timeout=stage_t)
            for i in range(0, sessions, 8)
        )
        for c in clients:
            c.chat(f"warm-{c.account}")
        checks["surge warm chat round-tripped"] = pump(
            lambda: all(
                any(t == f"warm-{c.account}" for _w, t in c.chat_log)
                for c in clients
            ),
            t=stage_t,
        )
        # every recompile from here on must be reshard-sanctioned
        mark = game1.kernel.costbook.mark()

        runner = DrillRunner(
            cluster, campaign,
            invariants=default_invariants()
            + [StableUnderReshard(control=control)],
        )
        sent = [0]

        def surge_extra():
            stir()
            # chat INTO the reshards: a numbered burst per in-flight op
            if elastic.inflight is not None and sent[0] < chats:
                for c in clients:
                    c.chat(f"mid-{c.account}-{sent[0]}")
                sent[0] += 1

        checks["grow completed to 4 devices"] = runner.pump_until(
            lambda: elastic.n_devices == GROW_TO
            and elastic.inflight is None,
            extra=surge_extra, timeout=stage_t,
        )
        # pump the campaign clock up to the drain step, then through it
        checks["drain completed to 3 devices"] = runner.pump_until(
            lambda: runner.tick > DRAIN_TICK
            and elastic.n_devices == GROW_TO - 1
            and elastic.inflight is None,
            extra=surge_extra, timeout=stage_t + 30.0,
        )
        checks["campaign fully fired"] = runner.pump_until(
            lambda: runner.steps_remaining == 0,
            extra=surge_extra, timeout=30.0,
        )
        # drain any still-delayed echo frames before the exactly-once
        # audit (chaos healed at HEAL_TICK; give the links a settle)
        want = [f"mid-{c.account}-{i}"
                for c in clients for i in range(sent[0])]
        runner.pump_until(
            lambda: all(
                sum(1 for _w, t in c.chat_log
                    if t == f"mid-{c.account}-{i}") >= 1
                for c in clients for i in range(sent[0])
            ),
            extra=stir, timeout=30.0,
        )

        ops = list(elastic.ops_done)
        checks["both reshards in the ledger"] = (
            [op["kind"] for op in ops] == ["grow", "drain"])
        checks["reshards moved real rows"] = (
            elastic.rows_moved_total > 0)
        checks["zero rows dropped by the exodus"] = (
            elastic.dropped_rows == 0)
        checks["population conserved across both ops"] = all(
            op["pop_after"] == op["pop_before"] for op in ops
        )
        checks["exodus drained within its tick budget"] = all(
            op.get("drained_in_budget", True) for op in ops
        )
        checks["mid-reshard chats echoed exactly once each"] = (
            bool(want) and all(
                sum(1 for _w, t in c.chat_log
                    if t == f"mid-{c.account}-{i}") == 1
                for c in clients for i in range(sent[0])
            )
        )
        checks["zero parked frames dropped"] = (
            proxy.parking.dropped_total == 0)

        # final digest pin: the elastic world, having grown, drained and
        # rebalanced, equals the static 1-shard fault-free control
        live_tick = int(game1.kernel.tick_count)
        checks["final digest equals static-mesh control"] = (
            elastic.digest() == control.advance_to(live_tick))

        checks["zero unexplained recompiles"] = (
            game1.kernel.costbook.unexplained_since(mark) == [])

        report = runner.report()
        rep = report
        checks["stable_under_reshard sampled"] = (
            report.checks.get("stable_under_reshard", 0) > 0)
        checks["zero invariant violations"] = report.clean
        if not report.clean:
            for v in report.violations[:10]:
                print(f"    violation @tick {v.tick} [{v.invariant}] "
                      f"{v.detail}")
        status = master.servers_status()
        checks["/json drill block live"] = (
            status.get("drill", {}).get("campaign") == "reshard")
    finally:
        for c in clients:
            c.close()
        cluster.shut()

    elapsed = time.monotonic() - t0
    drain_ops = [op for op in (rep and elastic.ops_done or [])
                 if op["kind"] == "drain"]
    exodus_ticks = drain_ops[0]["exodus_ticks"] if drain_ops else 0
    print(f"  reshard: {sessions} sessions held through grow 2->4 and "
          f"drain->3 in {elapsed:.1f}s, exodus={exodus_ticks} ticks, "
          f"rows_moved={elastic.rows_moved_total}, "
          f"dropped={elastic.dropped_rows}")
    if out_path is not None:
        Path(out_path).write_text(json.dumps({
            "metric": "reshard_gameday_exodus_ticks",
            "value": int(exodus_ticks),
            "unit": "ticks",
            "detail": {
                "sessions": sessions,
                "chats_per_session": chats,
                "seed": seed,
                "campaign": "reshard",
                "grow_tick": GROW_TICK,
                "drain_tick": DRAIN_TICK,
                "devices_visited": [2, GROW_TO, GROW_TO - 1],
                "rows_moved_total": int(elastic.rows_moved_total),
                "dropped_rows": int(elastic.dropped_rows),
                "drill_clean": bool(checks.get(
                    "zero invariant violations", False)),
                "digest_pinned": bool(checks.get(
                    "final digest equals static-mesh control", False)),
                "elapsed_s": round(elapsed, 2),
                "platform": "cpu",
            },
        }, indent=2, sort_keys=True) + "\n")
    return checks


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--short", action="store_true",
                    help="tier-1 sized campaign: 4 sessions, 2 bursts")
    ap.add_argument("--sessions", type=int, default=None)
    ap.add_argument("--chats", type=int, default=None)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-bench", action="store_true",
                    help="skip writing bench_runs/r10_reshard_gameday.json")
    args = ap.parse_args()
    sessions = args.sessions or (4 if args.short else 12)
    chats = args.chats or (2 if args.short else 4)
    out = None
    if not args.short and not args.no_bench:
        out = REPO / "bench_runs" / "r10_reshard_gameday.json"
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = run(tmpdir, seed=args.seed, sessions=sessions,
                     chats=chats, out_path=out)
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"RESHARD SMOKE FAILED: {failed}")
        return 1
    print(f"RESHARD SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
