"""Import-first helper for ad-hoc scripts: force the CPU backend.

The container's sitecustomize registers the tunnelled-TPU ("axon") backend
at interpreter start and overrides JAX_PLATFORMS, so env vars alone don't
keep scratch scripts off the (single, shared, slow-per-op) tunnel chip.
`import scripts.cpu_env` before anything that touches jax.  Mirrors
tests/conftest.py.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu"
